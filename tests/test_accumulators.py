"""Device-resident analytics parity (ISSUE 20): the in-scan SummaryAcc
fold must reproduce the post-hoc host oracles it replaced — ChainMonitor's
Welford/thinning-buffer fold, the stats R-hat/ESS oracles, and the
history-mode moments — on every kernel path, including tiny runs and
partial final chunks. The summary path must also leave the trajectory
itself untouched: same seed, analytics on or off, bit-identical states."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flipcomplexityempirical_tpu as fce
from flipcomplexityempirical_tpu import obs, stats
from flipcomplexityempirical_tpu.stats import accumulators as sacc


def synthetic_block(rng, c=6, t=50, integer=True):
    """(T, C) observable + waits, integer-valued by default (the f32
    device fold is exact there — cut counts live far below 2^24)."""
    if integer:
        x = rng.integers(5, 60, size=(t, c)).astype(np.float32)
    else:
        x = rng.normal(20.0, 3.0, size=(t, c)).astype(np.float32)
    w = rng.integers(0, 4, size=(t, c)).astype(np.float32)
    return x, w


def fold_all(x, w=None, cap=4096, accepts=None):
    acc = sacc.init_summary(x.shape[1], cap=cap)
    block = {"cut_count": jnp.asarray(x)}
    if w is not None:
        block["wait"] = jnp.asarray(w)
    if accepts is not None:
        block["accepts"] = jnp.asarray(accepts)
    return sacc.fold_block(acc, block)


# ---------------------------------------------------------------------------
# fold vs ChainMonitor host oracles (synthetic data)
# ---------------------------------------------------------------------------

def test_fold_matches_monitor_welford_and_buffer(rng):
    x, w = synthetic_block(rng, c=6, t=120)
    acc = fold_all(x, w, cap=32)

    mon = obs.ChainMonitor(obs.NULL, buffer_cap=32)
    for t in range(x.shape[0]):                 # fed one step at a time,
        col = x[t][:, None].astype(np.float64)  # exactly like the scan
        mon._fold_welford(col)
        mon._fold_buffer(col)

    assert int(acc.n) == mon._n
    np.testing.assert_allclose(np.asarray(acc.mean), mon._mean, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(acc.m2), mon._m2, rtol=1e-5)
    # buffer: same kept columns, same stride, bit-equal contents
    kept, stride = int(acc.kept), int(acc.stride)
    assert stride == mon._stride
    assert kept == mon._buf.shape[1]
    np.testing.assert_array_equal(np.asarray(acc.buf)[:, :kept], mon._buf)


def test_fold_welford_matches_numpy_float_data(rng):
    x, _ = synthetic_block(rng, c=4, t=200, integer=False)
    acc = fold_all(x)
    np.testing.assert_allclose(np.asarray(acc.mean), x.mean(axis=0),
                               rtol=1e-5)
    var = np.asarray(acc.m2) / (x.shape[0] - 1)
    np.testing.assert_allclose(var, x.var(axis=0, ddof=1), rtol=1e-4)


def test_weighted_moments_match_numpy(rng):
    """Lazy-uniform reweighting: weight 1 + wait, computed on device where
    the geometric draws live."""
    x, w = synthetic_block(rng, c=5, t=80)
    acc = fold_all(x, w)
    wt = 1.0 + w
    np.testing.assert_allclose(np.asarray(acc.wsum), wt.sum(axis=0),
                               rtol=1e-6)
    wmean = (wt * x).sum(axis=0) / wt.sum(axis=0)
    np.testing.assert_allclose(np.asarray(acc.wmean), wmean, rtol=1e-5)
    wm2 = (wt * (x - wmean) ** 2).sum(axis=0)
    np.testing.assert_allclose(np.asarray(acc.wm2), wm2, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(acc.waits), w.sum(axis=0),
                               rtol=1e-6)


@pytest.mark.parametrize("t", [1, 2, 3, 8, 9, 24, 25, 100])
def test_buffer_mirror_replays_device_counters(rng, t):
    """kept/stride are deterministic in (samples, cap): the host mirror
    must always agree with the device fold without any readback."""
    x, _ = synthetic_block(rng, c=3, t=t)
    acc = fold_all(x, cap=8)
    mirror = sacc.BufferMirror(cap=8)
    mirror.advance(t)
    assert mirror.n == int(acc.n) == t
    assert mirror.kept == int(acc.kept)
    assert mirror.stride == int(acc.stride)


def test_diagnostics_match_host_oracles(rng):
    """Unthinned regime: the buffer IS the trajectory, so the device
    split R-hat / Sokal ESS equal the host oracles on the raw block."""
    x, _ = synthetic_block(rng, c=6, t=64, integer=False)
    acc = fold_all(x, cap=128)
    assert int(acc.stride) == 1 and int(acc.kept) == 64
    rhat_d, ess_d = sacc.summary_diagnostics(acc, 64)
    assert float(rhat_d) == pytest.approx(stats.gelman_rubin(x.T),
                                          rel=1e-5)
    _, ess_h = stats.ess(x.T.astype(np.float64))
    assert float(ess_d) == pytest.approx(float(ess_h), rel=1e-4)


def test_diagnostics_thinned_matches_monitor(rng):
    """Once the buffer decimates, diagnostics run on the kept grid and
    ESS scales by the stride — exactly ChainMonitor._diagnostics."""
    x, _ = synthetic_block(rng, c=6, t=300, integer=False)
    cap = 64
    acc = fold_all(x, cap=cap)
    mon = obs.ChainMonitor(obs.NULL, buffer_cap=cap)
    for t in range(x.shape[0]):
        mon._fold_buffer(x[t][:, None].astype(np.float64))
    assert int(acc.stride) == mon._stride > 1
    kept = int(acc.kept)
    rhat_d, ess_d = sacc.summary_diagnostics(acc, kept)
    rhat_m, ess_m = mon._diagnostics()
    assert float(rhat_d) == pytest.approx(rhat_m, rel=1e-5)
    assert float(ess_d) * int(acc.stride) == pytest.approx(ess_m, rel=1e-4)


def test_init_summary_validates():
    with pytest.raises(ValueError):
        sacc.init_summary(4, cap=7)
    with pytest.raises(ValueError):
        sacc.init_summary(4, cap=4)
    with pytest.raises(ValueError):
        sacc.init_summary(4, series_keys=("slope",), series_cap=0)


def test_summary_nbytes_counts_readback_leaves():
    acc = sacc.init_summary(8, cap=16)
    s = sacc.summary(acc)
    want = sum(int(np.prod(v.shape)) * v.dtype.itemsize
               for v in s.values())
    assert sacc.summary_nbytes(acc) == want
    # the buffer and series never ride the per-chunk readback
    assert sacc.summary_nbytes(acc) < acc.buf.nbytes


# ---------------------------------------------------------------------------
# runner parity: summary mode vs the flagged history oracle path
# ---------------------------------------------------------------------------

def _reconstruct(history, n_chains, keys=("cut_count", "wait", "accepts")):
    """Fold the history-mode (C, T) rows through fold_block — the summary
    run must land on the identical accumulator state."""
    block = {k: jnp.asarray(history[k]).T for k in keys if k in history}
    return sacc.fold_block(sacc.init_summary(n_chains), block)


def _assert_acc_matches(analytics, ref):
    got = sacc.summary_host(analytics.acc)
    want = sacc.summary_host(ref)
    for k in sacc.SUMMARY_FIELDS:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    np.testing.assert_array_equal(
        np.asarray(analytics.acc.buf), np.asarray(ref.buf))


def general_batch(chains=6, kernel_path=None):
    if kernel_path == "general_dense":
        g = fce.graphs.hex_lattice(4, 4)
        spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                        geom_waits=True, parity_metrics=False)
    else:
        g = fce.graphs.square_grid(6)
        spec = fce.Spec(contiguity="patch")
    plan = fce.graphs.stripes_plan(g, 2)
    dg, st, params = fce.init_batch(g, plan, n_chains=chains, seed=3,
                                    spec=spec, base=1.4, pop_tol=0.35)
    return dg, spec, params, st


@pytest.mark.parametrize("kernel_path", ["general", "general_dense"])
def test_general_runner_summary_parity(kernel_path):
    """Same seed, history mode vs summary mode, partial final chunk
    (41 yields / chunk 16): bit-identical trajectory, and the in-scan
    fold lands exactly where folding the history block lands."""
    dg, spec, params, st = general_batch(kernel_path=kernel_path)
    res_h = fce.run_chains(dg, spec, params, st, n_steps=41, chunk=16,
                           kernel_path=kernel_path)

    ana = sacc.DeviceAnalytics(6)
    res_s = fce.run_chains(dg, spec, params, st, n_steps=41, chunk=16,
                           record_history=False, kernel_path=kernel_path,
                           analytics=ana)
    np.testing.assert_array_equal(
        np.asarray(res_h.state.assignment), np.asarray(res_s.state.assignment))
    np.testing.assert_array_equal(
        np.asarray(res_h.state.accept_count),
        np.asarray(res_s.state.accept_count))

    assert int(ana.acc.n) == 41
    _assert_acc_matches(ana, _reconstruct(res_h.history, 6))
    # accepts leaf is the cumulative counter at the final fold
    np.testing.assert_array_equal(
        np.asarray(ana.acc.accepts), np.asarray(res_s.state.accept_count))


@pytest.mark.parametrize("t", [1, 2, 3])
def test_general_runner_tiny_runs(t):
    """T=1,2,3: the fold is total-order exact and diagnostics stay None
    (gelman_rubin needs >= 4 kept samples)."""
    dg, spec, params, st = general_batch()
    res_h = fce.run_chains(dg, spec, params, st, n_steps=t)
    ana = sacc.DeviceAnalytics(6)
    fce.run_chains(dg, spec, params, st, n_steps=t,
                   record_history=False, analytics=ana)
    assert int(ana.acc.n) == t
    np.testing.assert_allclose(
        np.asarray(ana.acc.mean),
        np.asarray(res_h.history["cut_count"]).mean(axis=1), rtol=1e-6)
    assert ana.maybe_diagnostics(force=True) == (None, None)


def board_batch(chains=4, interface=False):
    if interface:
        g = fce.graphs.grid_sec11()
        plan = fce.graphs.sec11_plan(g, alignment=0)
        spec = fce.Spec(n_districts=2, proposal="bi", contiguity="patch",
                        invalid="repropose", accept="cut",
                        parity_metrics=True, geom_waits=True,
                        record_interface=True)
    else:
        g = fce.graphs.square_grid(8)
        plan = fce.graphs.stripes_plan(g, 2)
        spec = fce.Spec(contiguity="patch")
    return fce.sampling.init_board(g, plan, n_chains=chains, seed=11,
                                   spec=spec, base=1.4, pop_tol=0.3), spec


def test_board_runner_summary_parity():
    """Board fast path, partial final chunk (29 yields / chunk 8): the
    stashed-refs summary flow (no mid-run sync) matches the history fold
    and leaves the trajectory bit-identical."""
    (bg, st, params), spec = board_batch()
    res_h = fce.sampling.run_board(bg, spec, params, st, n_steps=29,
                                   chunk=8)
    ana = sacc.DeviceAnalytics(4)
    res_s = fce.sampling.run_board(bg, spec, params, st, n_steps=29,
                                   chunk=8, record_history=False,
                                   analytics=ana)
    np.testing.assert_array_equal(
        np.asarray(res_h.state.board), np.asarray(res_s.state.board))
    assert int(ana.acc.n) == 29
    _assert_acc_matches(ana, _reconstruct(res_h.history, 4))


@pytest.mark.slow
def test_lowered_bits_series_parity():
    """sec11 corner-surgery grid on the lowered_bits body: the chain-0
    interface series read back at run end bit-match the history rows
    (NaN-for-NaN — no-interface yields record NaN in both modes)."""
    (bg, st, params), spec = board_batch(interface=True)
    from flipcomplexityempirical_tpu.kernel import board as kboard
    assert kboard.body_for(bg, spec) == "lowered_bits"
    res_h = fce.sampling.run_board(bg, spec, params, st, n_steps=24,
                                   chunk=8)
    ana = sacc.DeviceAnalytics(4, series_keys=("slope", "angle"),
                               series_cap=24)
    fce.sampling.run_board(bg, spec, params, st, n_steps=24, chunk=8,
                           record_history=False, analytics=ana)
    series = ana.series_host()
    for k in ("slope", "angle"):
        np.testing.assert_array_equal(
            series[k], np.asarray(res_h.history[k][0], np.float32))
    _assert_acc_matches(ana, _reconstruct(res_h.history, 4))


def test_summary_readback_accounting_and_events(tmp_path):
    """Summary mode's chunk events carry readback_bytes orders of
    magnitude below history mode's, and run_end declares the mode."""
    import json

    def run(analytics, path):
        (bg, st, params), spec = board_batch()
        with obs.Recorder(path=str(path)) as rec:
            fce.sampling.run_board(bg, spec, params, st, n_steps=65,
                                   chunk=16, recorder=rec,
                                   record_history=analytics is None,
                                   analytics=analytics)
        events = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        chunks = [e for e in events if e["event"] == "chunk"]
        end = [e for e in events if e["event"] == "run_end"][0]
        return chunks, end

    chunks_h, end_h = run(None, tmp_path / "h.jsonl")
    ana = sacc.DeviceAnalytics(4)
    chunks_s, end_s = run(ana, tmp_path / "s.jsonl")
    assert end_h["readback_mode"] == "history"
    assert end_s["readback_mode"] == "summary"
    rb_h = sum(e["readback_bytes"] for e in chunks_h)
    rb_s = sum(e["readback_bytes"] for e in chunks_s)
    assert 0 < rb_s < rb_h
    # run_end totals ALL device->host traffic (summaries + counter
    # syncs + waits drain); the analytics object meters only its own
    # explicit reads, so it can never exceed the event's total
    assert ana.readback_bytes <= end_s["readback_bytes"]
    assert end_s["readback_bytes"] < end_h["readback_bytes"]


def test_sharded_allreduce_parity(mesh8):
    """16 chains over 8 devices, general kernel: the mesh-wide summary
    (all_gathered per-chain moments + psum'd pooled counters) equals the
    fold of the identical unsharded run's history."""
    from flipcomplexityempirical_tpu import distribute

    g = fce.graphs.square_grid(6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    dg, st, params = fce.init_batch(g, plan, n_chains=16, seed=5,
                                    spec=spec, base=1.4, pop_tol=0.35)
    # oracle: unsharded, record AFTER transition — 40 transition yields
    res = fce.run_chains(dg, spec, params, st, n_steps=40,
                         record_initial=False)
    ref = _reconstruct(res.history, 16)

    st2 = distribute.shard_chain_batch(mesh8, st)
    params2 = distribute.shard_chain_batch(mesh8, params)
    step = distribute.make_train_step(dg, spec, mesh8, inner_steps=8,
                                      exchange=False)
    ana = sacc.DeviceAnalytics(16)
    _, _, info = distribute.run_sharded(
        step, params2, st2, rounds=5, inner_steps=8,
        key=jax.random.PRNGKey(0), analytics=ana)

    summ, want = info["summary"], sacc.summary_host(ref)
    assert int(summ["n"]) == 40
    for k in ("mean", "m2", "wsum", "wmean", "wm2", "waits"):
        np.testing.assert_allclose(summ[k], want[k], rtol=1e-5,
                                   err_msg=k)
    np.testing.assert_array_equal(summ["accepts"], want["accepts"])
    assert int(summ["pooled_accepts"]) == int(want["accepts"].sum())
    assert float(summ["pooled_wsum"]) == pytest.approx(
        float(want["wsum"].sum()), rel=1e-6)
    assert info["readback_bytes"] == ana.readback_bytes


def test_sharded_analytics_rejects_series(mesh8):
    from flipcomplexityempirical_tpu import distribute

    g = fce.graphs.square_grid(6)
    plan = fce.graphs.stripes_plan(g, 2)
    spec = fce.Spec(contiguity="patch")
    dg, st, params = fce.init_batch(g, plan, n_chains=16, seed=5,
                                    spec=spec, base=1.4, pop_tol=0.35)
    st = distribute.shard_chain_batch(mesh8, st)
    params = distribute.shard_chain_batch(mesh8, params)
    step = distribute.make_train_step(dg, spec, mesh8, inner_steps=4,
                                      exchange=False)
    ana = sacc.DeviceAnalytics(16, series_keys=("slope",), series_cap=8)
    with pytest.raises(ValueError, match="series"):
        distribute.run_sharded(step, params, st, rounds=1, inner_steps=4,
                               key=jax.random.PRNGKey(0), analytics=ana)
