"""Control policies: pure observed-history -> proposed-action functions.

Every policy sees one ``ObservedState`` — the accumulated, checkpoint-
resumable view of a config at a segment boundary — and returns zero or
more ``ControlAction`` proposals. PURITY IS THE CONTRACT (enforced by
graftlint G008): no wall-clock reads, no unseeded RNG, no recorder or
hook mutation. A policy that is pure in the observed history makes the
whole control plane journal-replayable: a drained run and its recovery
see bit-identical histories at the same segment boundaries (chain PRNG
keys live in the checkpointed state), so they derive the identical
action sequence — ``SweepService.recover`` replays decisions instead of
re-litigating them.

Built-ins:

- ``EarlyStopPolicy``: stop a config once its split R-hat and total ESS
  targets hold at K consecutive segment-grid points (with a min-steps
  floor). Diagnostics are recomputed from the accumulated (C, T)
  history via the stats oracles (f64, deterministic) rather than read
  from ChainMonitor's process-lifetime buffers, which reset on recovery.
- ``AutotunePolicy``: propose a segment-length retune from the metrics
  registry's p95 ``segment_wall_s``, quantized to the histogram's own
  1-2-5 bucket edges so the proposal is a pure function of which bucket
  the latency landed in, not of the raw jittery wall-clock values. The
  proposal is ADVISORY (surfaced in events/reports, never applied
  mid-run): applying it would change segment shapes and break the
  bit-identical-artifacts contract.
- ``LadderPolicy``: map the tempered family's per-pair swap statistics
  (plus acceptance_collapse / frozen_chain anomalies) into a geometric
  beta-ladder reshape targeting a swap-rate band. The coldest rung
  (beta max) is held exactly fixed so the physical chain — and the
  driver's cold-row bookkeeping — survive the reshape.
"""

from __future__ import annotations

import dataclasses
from bisect import bisect_left
from typing import Optional, Protocol

import numpy as np

from ..obs.metrics import DEFAULT_EDGES
from ..stats.diagnostics import ess, gelman_rubin

ACTION_KINDS = ("stop", "retune", "reshape_ladder", "reallocate")


@dataclasses.dataclass(frozen=True)
class ControlAction:
    """One typed control decision. ``detail`` must be JSON-canonical
    (plain dicts/lists/str/int/float/bool) — it rides the journal and
    the event stream verbatim, and replay equality is judged on it."""

    kind: str                 # one of ACTION_KINDS
    tag: str                  # config (or batch) acted on
    step: int                 # segment boundary (transitions done)
    policy: str               # deciding policy's name
    detail: dict = dataclasses.field(default_factory=dict)

    def doc(self) -> dict:
        return {"kind": self.kind, "tag": self.tag, "step": self.step,
                "policy": self.policy, "detail": self.detail}


@dataclasses.dataclass(frozen=True)
class ObservedState:
    """What a policy may see at one segment boundary: accumulated,
    checkpoint-resumable observations only. Anything process-lifetime
    (monitor buffers, wall clocks) is deliberately absent — it would
    diverge between a run and its recovery."""

    tag: str
    family: str
    done: int                         # transitions/yields advanced
    total: int                        # the run's full schedule
    every: int                        # segment length (boundary grid)
    history: Optional[np.ndarray] = None   # (C, T) accumulated observable
    diag: tuple = ()                  # ((step, rhat, ess), ...) one point
                                      # per consulted boundary — the
                                      # summary-mode view when the full
                                      # history never leaves the device
                                      # (stats.accumulators); rhat/ess
                                      # may be None before the device
                                      # buffer fills
    swap_attempts: Optional[np.ndarray] = None  # (n_rungs-1,) temper
    swap_accepts: Optional[np.ndarray] = None
    betas: Optional[tuple] = None     # current ladder by rank, coldest 1st
    anomalies: tuple = ()             # anomaly kinds observed for tag
    taken: dict = dataclasses.field(default_factory=dict)  # kind -> count
    p95_bucket: dict = dataclasses.field(default_factory=dict)
    # metric -> (bucket upper edge, count): pre-quantized histogram
    # reading (see ControlLoop._quantize) — the only latency view pure
    # enough for a policy


class ControlPolicy(Protocol):
    """A policy proposes actions; the ControlLoop emits/journals them."""

    name: str

    def propose(self, view: ObservedState) -> list:  # list[ControlAction]
        ...


def quantize_latency(value: float) -> float:
    """Snap a latency to the metrics registry's 1-2-5 bucket upper edge
    (Histogram.percentile interpolates within buckets, so raw p95 values
    carry wall-clock jitter; the bucket a latency falls in does not)."""
    i = bisect_left(DEFAULT_EDGES, value)
    return DEFAULT_EDGES[min(i, len(DEFAULT_EDGES) - 1)]


class EarlyStopPolicy:
    """Stop once split R-hat <= rhat_target AND total ESS >= ess_target
    at ``patience`` consecutive segment-grid points, not before
    ``min_steps`` transitions.

    The grid points are derived purely from (done, every, T): column
    T * g / done for each boundary g — so the SAME boundaries are judged
    whether the history arrived in one run or across a drain/recovery.
    Tempered configs are skipped: closing a temper run early would need
    a final-yield segment mid-schedule (run_tempered's segment=False
    epilogue), and the ladder's value is mixing the full horizon anyway.

    ``tags``: optional whitelist — only listed configs may be stopped
    (lets an operator, or a test, target one straggling tenant's peers).
    """

    def __init__(self, rhat_target: float = 1.05,
                 ess_target: float = 200.0, patience: int = 2,
                 min_steps: int = 0, min_columns: int = 8,
                 tags: Optional[tuple] = None, name: str = "early_stop"):
        self.rhat_target = float(rhat_target)
        self.ess_target = float(ess_target)
        self.patience = max(int(patience), 1)
        self.min_steps = int(min_steps)
        self.min_columns = max(int(min_columns), 4)
        self.tags = tuple(tags) if tags is not None else None
        self.name = name

    def _passes(self, hist: np.ndarray, t_col: int) -> bool:
        if t_col < self.min_columns:
            return False
        window = hist[:, :t_col]
        try:
            rhat = gelman_rubin(window)
        except ValueError:
            return False
        if not np.isfinite(rhat) or rhat > self.rhat_target:
            return False
        _, total = ess(window)
        return total >= self.ess_target

    def _propose_from_diag(self, view: ObservedState) -> list:
        """Summary-mode path: no (C, T) history ever reached the host,
        so judge the trailing ``patience`` boundary diagnostics the
        device accumulator produced ((step, rhat, ess) points from
        ``stats.accumulators.summary_diagnostics``). The same grid
        discipline holds — one point per consulted boundary — and the
        points are pure in the trajectory, so a replayed run re-derives
        the identical decision."""
        points = [p for p in view.diag[-self.patience:]]
        if len(points) < self.patience:
            return []
        def _ok(p):
            step, rhat, ess_total = p
            return (rhat is not None and ess_total is not None
                    and np.isfinite(rhat) and rhat <= self.rhat_target
                    and ess_total >= self.ess_target)
        if not all(_ok(p) for p in points):
            return []
        _, rhat, ess_total = points[-1]
        return [ControlAction(
            kind="stop", tag=view.tag, step=view.done, policy=self.name,
            detail={"rhat": round(float(rhat), 6),
                    "ess": round(float(ess_total), 3),
                    "rhat_target": self.rhat_target,
                    "ess_target": self.ess_target,
                    "patience": self.patience,
                    "total": view.total,
                    "source": "device_summary",
                    "saved_steps": view.total - view.done})]

    def propose(self, view: ObservedState) -> list:
        if (view.family == "temper"
                or (view.history is None and not view.diag)
                or view.taken.get("stop") or view.done >= view.total
                or view.done < self.min_steps
                or (self.tags is not None and view.tag not in self.tags)):
            return []
        if view.history is None:
            return self._propose_from_diag(view)
        hist = np.asarray(view.history, dtype=np.float64)
        t = hist.shape[1]
        grid = list(range(view.every, view.done + 1, view.every)) or \
            [view.done]
        points = grid[-self.patience:]
        if len(points) < self.patience:
            return []
        cols = [max(1, (t * g) // view.done) for g in points]
        if not all(self._passes(hist, tc) for tc in cols):
            return []
        rhat = gelman_rubin(hist[:, :cols[-1]])
        _, ess_total = ess(hist[:, :cols[-1]])
        return [ControlAction(
            kind="stop", tag=view.tag, step=view.done, policy=self.name,
            detail={"rhat": round(float(rhat), 6),
                    "ess": round(float(ess_total), 3),
                    "rhat_target": self.rhat_target,
                    "ess_target": self.ess_target,
                    "patience": self.patience,
                    "total": view.total,
                    "saved_steps": view.total - view.done})]


class AutotunePolicy:
    """Advisory segment-length retune from the quantized p95
    ``segment_wall_s``: when a segment's p95 bucket sits above
    ``target_wall_s``, propose halving the segment length toward the
    target (and doubling when it sits far below, capped by the run
    length). At most one proposal per config — the point is a concrete
    number for the NEXT submission of this shape, not a stream of
    nudges. Never applied mid-run (see module docstring)."""

    def __init__(self, target_wall_s: float = 1.0,
                 name: str = "autotune"):
        self.target_wall_s = float(target_wall_s)
        self.name = name

    def propose(self, view: ObservedState) -> list:
        reading = view.p95_bucket.get("segment_wall_s")
        if reading is None or view.taken.get("retune"):
            return []
        bucket, count = reading
        if count < 2:
            return []
        if bucket > self.target_wall_s:
            factor = 1
            while bucket > self.target_wall_s * factor and \
                    view.every // (2 * factor) >= 1:
                factor *= 2
            proposal = max(view.every // factor, 1)
        elif bucket <= self.target_wall_s / 4:
            proposal = min(view.every * 2, max(view.total, view.every))
        else:
            return []
        if proposal == view.every:
            return []
        return [ControlAction(
            kind="retune", tag=view.tag, step=view.done, policy=self.name,
            detail={"segment_steps": int(proposal),
                    "current_segment_steps": int(view.every),
                    "p95_bucket_s": bucket,
                    "p95_count": count,
                    "target_wall_s": self.target_wall_s,
                    "advisory": True})]


class LadderPolicy:
    """Reshape a tempered beta ladder toward a swap-rate band.

    Pure in (swap_attempts, swap_accepts, current betas, anomalies):
    the mean accept rate is a ratio of integers, the reshape is a
    closed-form geometric respacing. A rate below ``low`` (or an
    acceptance_collapse / frozen_chain anomaly with the rate below
    ``high``) means adjacent rungs are too far apart — the span
    b_min/b_max contracts (sqrt); a rate above ``high`` means the
    ladder wastes rungs on near-identical temperatures — the span
    widens (squares, floored). beta_max is held EXACTLY fixed; the new
    rungs are assigned by rank, so each chain keeps its rank and the
    physical (coldest) chain is untouched."""

    def __init__(self, low: float = 0.15, high: float = 0.60,
                 min_attempts_per_pair: int = 4, max_reshapes: int = 1,
                 min_span: float = 1e-3, name: str = "ladder"):
        self.low = float(low)
        self.high = float(high)
        self.min_attempts_per_pair = int(min_attempts_per_pair)
        self.max_reshapes = int(max_reshapes)
        self.min_span = float(min_span)
        self.name = name

    def propose(self, view: ObservedState) -> list:
        if (view.family != "temper" or view.betas is None
                or view.swap_attempts is None or view.swap_accepts is None
                or view.taken.get("reshape_ladder", 0)
                >= self.max_reshapes or view.done >= view.total):
            return []
        attempts = np.asarray(view.swap_attempts, dtype=np.int64)
        accepts = np.asarray(view.swap_accepts, dtype=np.int64)
        n_pairs = attempts.shape[0]
        if n_pairs < 1 or attempts.sum() < \
                self.min_attempts_per_pair * n_pairs:
            return []
        rate = float(accepts.sum()) / float(max(int(attempts.sum()), 1))
        anomalous = bool(set(view.anomalies)
                         & {"acceptance_collapse", "frozen_chain"})
        if rate < self.low or (anomalous and rate < self.high):
            direction, exponent = "contract", 0.5
        elif rate > self.high:
            direction, exponent = "widen", 2.0
        else:
            return []
        betas = np.asarray(view.betas, dtype=np.float64)
        b_max, b_min = betas[0], betas[-1]
        if not (b_max > 0 and b_min > 0 and b_max > b_min):
            return []
        span = max((b_min / b_max) ** exponent, self.min_span)
        n = betas.shape[0]
        new = b_max * span ** (np.arange(n) / max(n - 1, 1))
        new32 = new.astype(np.float32)
        new32[0] = np.float32(b_max)  # exactly fixed cold rung
        if len(set(new32.tolist())) != n:
            return []                 # degenerate in f32: keep the ladder
        return [ControlAction(
            kind="reshape_ladder", tag=view.tag, step=view.done,
            policy=self.name,
            detail={"betas": [float(b) for b in new32],
                    "old_betas": [float(b) for b in
                                  betas.astype(np.float32)],
                    "mean_swap_rate": round(rate, 6),
                    "band": [self.low, self.high],
                    "direction": direction,
                    "anomalous": anomalous})]


def default_policies(rhat_target: float = 1.05,
                     ess_target: float = 200.0, patience: int = 2,
                     min_steps: int = 0) -> list:
    """The standard adaptive-sweep trio (--adaptive flags thread the
    early-stop targets; autotune and ladder run with their defaults)."""
    return [EarlyStopPolicy(rhat_target=rhat_target,
                            ess_target=ess_target, patience=patience,
                            min_steps=min_steps),
            AutotunePolicy(),
            LadderPolicy()]
