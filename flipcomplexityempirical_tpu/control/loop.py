"""The ControlLoop: fold observations into journaled ControlActions.

The loop is the single mutation point of the control plane. Policies
(control/policy.py) are pure proposal functions; the loop builds their
``ObservedState`` view at each segment boundary, filters proposals
against what has already been taken (once-per-config stops, bounded
ladder reshapes), and emits every accepted action twice: a
``control_action`` registry event on the recorder (telemetry) and a
``control_action`` record in the service journal (durability). The
journal is the loop's durable memory: ``adopt`` re-seeds the dedup
state from recovered records, so a recovered service never re-emits a
decision it already journaled and honors prior stops at the exact
boundary they were taken (``stop_step``).

Journal field naming: the journal envelope already uses ``kind`` for
the record type, so the ACTION's kind rides as ``action`` —
``{"kind": "control_action", "action": "stop", tag, step, policy,
detail}``. ``journal.replay`` ignores unknown record kinds, so control
records coexist with the job-state fold.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import obs
from .policy import (ControlAction, ObservedState, default_policies,
                     quantize_latency)


class ControlLoop:
    """Deterministic observe -> act fold for one sweep/service run.

    ``consult`` is called by the drivers at segment boundaries (next to
    ``_check_drain``); ``consult_stop`` is the early-stop convenience
    the segment loops branch on; ``reallocate`` is called by the
    scheduler when it hands an early-stopped tenant's chains back to
    the batch's stragglers."""

    def __init__(self, policies=None, recorder=None, journal=None,
                 metrics=None):
        self.policies = (list(policies) if policies is not None
                         else default_policies())
        self._rec = obs.resolve_recorder(recorder)
        self.journal = journal
        self.metrics = metrics
        self.actions: list = []            # emitted by THIS process
        self._taken: dict = {}             # (tag, kind) -> count
        self._stop_steps: dict = {}        # tag -> step of the stop
        self._anomalies: dict = {}         # tag -> [kind, ...]

    # -- wiring ------------------------------------------------------

    def attach(self, recorder=None, journal=None, metrics=None):
        """Late wiring for components the owner creates after the loop
        (the SweepService attaches its recorder/journal/metrics)."""
        if recorder is not None and not self._rec:
            self._rec = obs.resolve_recorder(recorder)
        if journal is not None and self.journal is None:
            self.journal = journal
        if metrics is not None and self.metrics is None:
            self.metrics = metrics
        return self

    # -- durable memory ----------------------------------------------

    def adopt(self, records) -> int:
        """Seed the dedup state from recovered journal records so a
        recovered run REPLAYS prior decisions instead of re-deriving
        (and re-journaling) them. Returns the number adopted."""
        n = 0
        for record in records:
            if record.get("kind") != "control_action":
                continue
            action, tag = record.get("action"), record.get("tag")
            if not action or tag is None:
                continue
            key = (tag, action)
            self._taken[key] = self._taken.get(key, 0) + 1
            if action == "stop" and tag not in self._stop_steps:
                self._stop_steps[tag] = int(record.get("step", 0))
            n += 1
        return n

    def observe_anomaly(self, tag: str, kind: str):
        """Record an anomaly kind for ``tag`` (driver hooks forward
        ChainMonitor anomaly events here; LadderPolicy consumes them)."""
        kinds = self._anomalies.setdefault(tag, [])
        if kind not in kinds:
            kinds.append(kind)

    def stopped(self, tag: str) -> bool:
        return tag in self._stop_steps

    def stop_step(self, tag: str) -> Optional[int]:
        return self._stop_steps.get(tag)

    def taken(self, tag: str) -> dict:
        return {kind: count for (t, kind), count in self._taken.items()
                if t == tag}

    # -- the consult points ------------------------------------------

    def _quantize_histograms(self) -> dict:
        out = {}
        if self.metrics is None:
            return out
        for name in ("segment_wall_s",):
            h = self.metrics.histogram(name)
            if h is None or not h.count:
                continue
            p95 = h.percentile(0.95)
            if p95 is not None:
                out[name] = (quantize_latency(p95), int(h.count))
        return out

    def consult(self, tag: str, *, family: str, done: int, total: int,
                every: int, history=None, diag=(), swap_attempts=None,
                swap_accepts=None, betas=None) -> list:
        """Evaluate every policy at one segment boundary; emit and
        journal the accepted actions. Pure in the passed observations
        plus the adopted journal state — NOT in any wall clock."""
        if self.stopped(tag):
            return []
        view = ObservedState(
            tag=tag, family=family, done=int(done), total=int(total),
            every=int(every),
            history=history,
            diag=tuple(diag),
            swap_attempts=swap_attempts, swap_accepts=swap_accepts,
            betas=(tuple(float(b) for b in np.asarray(betas).ravel())
                   if betas is not None else None),
            anomalies=tuple(self._anomalies.get(tag, ())),
            taken=self.taken(tag),
            p95_bucket=self._quantize_histograms())
        accepted = []
        for policy in self.policies:
            for action in policy.propose(view):
                if action.kind == "stop" and (
                        view.taken.get("stop")
                        or any(a.kind == "stop" for a in accepted)):
                    continue
                accepted.append(action)
        for action in accepted:
            self._emit(action)
        return accepted

    def consult_stop(self, tag: str, **kw) -> bool:
        """The early-stop branch for the segment loops: True when this
        boundary is where the config stops — either a fresh decision or
        the replay of an adopted one at its original boundary."""
        ss = self._stop_steps.get(tag)
        if ss is not None:
            return int(kw.get("done", 0)) >= ss
        return any(a.kind == "stop" for a in self.consult(tag, **kw))

    def reallocate(self, batch_tag: str, *, step: int, from_tag: str,
                   to_tags, freed_chains: int):
        """Journal the scheduler handing an early-stopped tenant's
        device time to the batch's stragglers. Deterministic: a pure
        consequence of a stop decision and the batch's membership."""
        action = ControlAction(
            kind="reallocate", tag=batch_tag, step=int(step),
            policy="scheduler",
            detail={"from": from_tag, "to": sorted(to_tags),
                    "freed_chains": int(freed_chains)})
        self._emit(action)
        return action

    # -- emission ----------------------------------------------------

    def _emit(self, action: ControlAction):
        key = (action.tag, action.kind)
        self._taken[key] = self._taken.get(key, 0) + 1
        if action.kind == "stop":
            self._stop_steps.setdefault(action.tag, action.step)
        self.actions.append(action)
        if self._rec:
            self._rec.emit("control_action", kind=action.kind,
                           tag=action.tag, step=action.step,
                           policy=action.policy, detail=action.detail)
        if self.journal is not None:
            self.journal.append("control_action", action=action.kind,
                                tag=action.tag, step=action.step,
                                policy=action.policy,
                                detail=action.detail)
