"""control/ — monitor-driven adaptive sweep control (ROADMAP item 5).

A deterministic, journal-replayable control plane over the obs/ stack:
pure policies (policy.py) propose typed actions from observed history;
the ControlLoop (loop.py) emits them as ``control_action`` events and
journal records at the drivers' existing segment boundaries. See
README "Adaptive control" for the quick-start.

This package must stay importable without jax (policies run on numpy +
stats oracles only) and late-importable from experiments.driver — it
imports only obs/ and stats/.
"""

from .loop import ControlLoop
from .policy import (ACTION_KINDS, AutotunePolicy, ControlAction,
                     ControlPolicy, EarlyStopPolicy, LadderPolicy,
                     ObservedState, default_policies)

__all__ = [
    "ACTION_KINDS", "AutotunePolicy", "ControlAction", "ControlLoop",
    "ControlPolicy", "EarlyStopPolicy", "LadderPolicy", "ObservedState",
    "default_policies",
]
