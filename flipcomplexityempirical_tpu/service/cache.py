"""Compile cache for the sweep service: signature-keyed AOT amortization.

Two layers, both keyed on the stable lowering signature
(``lower.dispatch.lowering_signature`` — kernel path + graph topology +
Spec statics) plus the batch shape jit specializes on:

- **In-process**: ``CompileCache.check`` records which keys this
  process has already dispatched. A second tenant whose batch resolves
  to a seen key emits ``compile_cache_hit`` and, because jax's own jit
  cache holds the specialization, produces ZERO ``compile`` events —
  the event-stream proof of amortization (ISSUE 9 acceptance).
- **On disk**: ``enable_persistent_cache(dir)`` wires JAX's persistent
  compilation cache (``jax_compilation_cache_dir``), and the index
  JSON written next to it survives restarts, so a restarted service
  knows a key's XLA work is served from disk (the ~30-60s/config
  compile becomes a deserialization).

The probe is bookkeeping, not a gate: the runners' jit cache is the
actual mechanism; this records and events the decision so reports and
smokes can assert on it.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .. import obs

INDEX_NAME = "service_compile_index.json"


def enable_persistent_cache(cache_dir: str,
                            min_compile_secs: float = 1.0) -> str:
    """Point JAX's on-disk persistent compilation cache at
    ``cache_dir`` (created if missing) so XLA compiles survive process
    restarts. Returns the directory (for ``run_start`` meta — see
    ``Recorder.run_meta``). Same knobs as the experiments CLI's
    ``--jax-cache``."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    return cache_dir


class CompileCache:
    """Signature -> seen bookkeeping with hit/miss events.

    ``cache_dir=None`` keeps the index in-process only (simulation
    mode); with a directory the index is loaded at construction and
    re-written (atomically) on every new key, so a restarted service
    reports hits for work the persistent XLA cache will serve."""

    def __init__(self, cache_dir: Optional[str] = None, recorder=None):
        self.cache_dir = cache_dir
        self._rec = obs.resolve_recorder(recorder)
        self._seen: dict = {}
        if cache_dir:
            self._seen.update(self._load_index())

    # -- persistence -------------------------------------------------

    def _index_path(self) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, INDEX_NAME)

    def _load_index(self) -> dict:
        path = self._index_path()
        try:
            with open(path, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            return {}
        return d if isinstance(d, dict) else {}

    def _save_index(self):
        path = self._index_path()
        if not path:
            return
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._seen, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:
            # the index is an optimization record, never load-bearing
            print(f"[compile-cache] index write failed ({e}); "
                  "continuing in-process only")

    # -- the probe ---------------------------------------------------

    @staticmethod
    def key(signature: str, n_chains: int, total_steps: int,
            segment: int) -> str:
        """The cache key: lowering signature + everything the jitted
        chunk kernels specialize on for a batch — total chain count
        (the leading shape) and the segmenting that determines the
        chunk-length set (``pick_chunk`` keys per length)."""
        return (f"{signature}|chains={int(n_chains)}"
                f"|steps={int(total_steps)}|seg={int(segment)}")

    def check(self, key: str, kernel_path: str, **meta) -> bool:
        """True on hit. Emits ``compile_cache_hit``/``_miss`` and, on a
        miss with a cache_dir, persists the updated index."""
        hit = key in self._seen
        if self._rec:
            fields = dict(key=key, kernel_path=kernel_path,
                          persistent=bool(self.cache_dir), **meta)
            if hit:
                self._rec.emit("compile_cache_hit", **fields)
            else:
                self._rec.emit("compile_cache_miss", **fields)
        if not hit:
            self._seen[key] = {"kernel_path": kernel_path, **meta}
            self._save_index()
        return hit

    def __len__(self) -> int:
        return len(self._seen)
