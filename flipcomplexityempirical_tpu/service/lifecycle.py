"""Preemption lifecycle: graceful drain + hung-dispatch watchdog.

TPU pods are preempted mid-batch as a matter of course; the sweeps this
package serves run for hours, so preemption is the common case the
service must survive, not an edge case. Two mechanisms live here:

**Graceful drain.** SIGTERM/SIGINT must not kill the process mid-write:
``DrainController`` installs handlers that only raise a cooperative
flag; ``check_drain`` — called at the same segment boundaries as
``check_deadline`` — turns the flag into a ``DrainRequested`` exception
at the next safe point. The scheduler catches it, checkpoints and
requeues the in-flight batch, journals ``service_draining``, and exits
with the distinct drain code (``EXIT_DRAINED``) so an orchestrator
knows to restart with ``SweepService.recover``. Because the flag is
only *checked* at boundaries where every tenant has a consistent
checkpoint, a drained-and-recovered run is bit-identical to an
uninterrupted one (``make preempt-check`` gates this).

The ``sigterm`` fault site stands in for a real signal: an armed rule
(``sigterm:once@HIT``) raises the flag at exactly the HIT-th boundary,
making preemption drains byte-reproducible in chaos tests.

**Hung-dispatch watchdog.** A JAX dispatch cannot be interrupted from
Python — a wedged device call would hang the drain forever and a
cooperative deadline check never runs. ``DispatchWatchdog`` is a
daemon thread that watches each armed dispatch window: when a dispatch
exceeds its timeout (explicit ``--dispatch-timeout``, else scaled from
the service's observed p95 segment latency), it emits
``dispatch_stalled`` and journals the batch as poison-suspect — it
cannot kill the dispatch, but after the orchestrator's hard kill and
restart, recovery sees the marker and retries that batch's jobs SOLO
under the supervisor taxonomy (a hung coalesced batch must not take
its tenants down with it twice). The ``dispatch.stall`` fault site
simulates the hang: ``stall_point`` holds the dispatch past the
timeout so the watchdog demonstrably fires, then surfaces the fault as
the killed call's error.

Exit codes (the CLI contract, documented in README):

=====  ================================================================
code   meaning
=====  ================================================================
0      all jobs done
2      failures/quarantines present (mirrors the driver's chaos code)
3      drained on SIGTERM/SIGINT — restart with ``--recover``
=====  ================================================================
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import TYPE_CHECKING, Optional

from ..resilience import faults as rfaults

if TYPE_CHECKING:
    from .journal import Journal

EXIT_DRAINED = 3

_MONOTONIC = time.monotonic


class DrainRequested(RuntimeError):
    """Raised by ``check_drain`` at a segment boundary after a drain
    request. NOT a failure: the scheduler requeues the in-flight jobs
    without burning a retry."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(f"drain requested ({reason})")


# Process-wide by design: a SIGTERM addresses the process, and every
# segment loop in it must see the flag. Mutated only via the functions
# below; tests reset with clear_drain().
_DRAIN_LOCK = threading.Lock()
_DRAIN_REASON: Optional[str] = None


def request_drain(reason: str) -> None:
    """Raise the cooperative stop flag (signal-handler safe: one
    assignment, no I/O)."""
    global _DRAIN_REASON
    with _DRAIN_LOCK:
        if _DRAIN_REASON is None:
            _DRAIN_REASON = reason


def drain_requested() -> Optional[str]:
    """The drain reason, or None when no drain is pending."""
    return _DRAIN_REASON


def clear_drain() -> None:
    global _DRAIN_REASON
    with _DRAIN_LOCK:
        _DRAIN_REASON = None


def check_drain(tag: str = "") -> None:
    """Cooperative drain point — call where a stop is safe (segment
    boundaries, between batches). Consults the ``sigterm`` fault site
    first so chaos plans can deliver a deterministic 'signal' at an
    exact boundary, then raises DrainRequested if the flag is up."""
    try:
        rfaults.fault_point("sigterm", tag=tag)
    except rfaults.InjectedFault as e:
        request_drain(f"injected-sigterm@{e.hit}")
    reason = _DRAIN_REASON
    if reason is not None:
        raise DrainRequested(reason)


# -- cross-process drain (the worker fleet) ---------------------------
#
# The in-process flag above addresses ONE process; a fleet is N worker
# processes plus a front-door server sharing a directory. The server's
# /v1/drain endpoint (and its own SIGTERM handler) writes a DRAIN
# marker file into the shared root; workers poll it between jobs and
# exit with EXIT_DRAINED after finishing (and checkpointing) their
# current lease. The marker is advisory data, not a lock — torn writes
# are impossible (one atomic rename) and a stale marker just means the
# next fleet run starts drained, which `clear_drain_marker` fixes.

DRAIN_MARKER = "DRAIN"


def drain_marker_path(root: str) -> str:
    return os.path.join(root, DRAIN_MARKER)


def mark_drain(root: str, reason: str, clock=time.time) -> str:
    """Write the fleet-wide drain marker atomically; returns its path.
    Idempotent: a second drain request keeps the first reason."""
    path = drain_marker_path(root)
    if os.path.exists(path):
        return path
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"reason": reason, "ts": clock()}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def drain_marked(root: str):
    """The fleet drain reason, or None. Unreadable markers still drain
    (``"torn-marker"``): a half-written drain request is a drain
    request."""
    path = drain_marker_path(root)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f).get("reason", "unknown")
    except (OSError, ValueError):
        return "torn-marker"


def clear_drain_marker(root: str) -> None:
    try:
        os.remove(drain_marker_path(root))
    except FileNotFoundError:
        pass


class DrainController:
    """Installs SIGTERM/SIGINT handlers that request a drain (and
    nothing else — all real work happens cooperatively at the next
    ``check_drain``). ``uninstall`` restores the previous handlers.
    Usable as a context manager."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._previous: dict = {}

    def install(self) -> "DrainController":
        for sig in self.SIGNALS:
            self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}

    @staticmethod
    def _handler(signum, frame) -> None:
        request_drain(signal.Signals(signum).name)

    def __enter__(self) -> "DrainController":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class DispatchWatchdog:
    """Daemon thread detecting hung device dispatches.

    The scheduler arms a window around each dispatch::

        with watchdog.watch(batch_id, job_ids):
            watchdog.stall_point(batch_id)   # chaos hook
            ... run the segment ...

    While a window is armed, the thread polls the monotonic clock; past
    the timeout it fires ONCE for that window: emits
    ``dispatch_stalled`` and journals ``batch_poison_suspect``. The
    timeout is ``timeout_s`` when given, else ``scale`` x the p95 of
    the ``segment_wall_s`` histogram in ``metrics`` (floored at
    ``floor_s``); with neither, the window is unarmed — a fresh
    service has no latency prior to scale from.
    """

    def __init__(self, recorder=None,
                 journal: Optional["Journal"] = None,
                 timeout_s: Optional[float] = None, metrics=None,
                 floor_s: float = 30.0, scale: float = 10.0,
                 poll_s: float = 0.05):
        self.recorder = recorder
        self.journal = journal
        self.timeout_s = timeout_s
        self.metrics = metrics
        self.floor_s = float(floor_s)
        self.scale = float(scale)
        self.poll_s = float(poll_s)
        self.stalled: list = []      # batch_ids that fired
        self._lock = threading.Lock()
        self._armed = None           # (batch_id, jobs, start, timeout)
        self._fired_current = False
        self._thread = None
        self._stop = threading.Event()

    # -- timeout resolution -------------------------------------------

    def effective_timeout(self) -> Optional[float]:
        if self.timeout_s is not None:
            return float(self.timeout_s)
        if self.metrics is None:
            return None
        hist = self.metrics.histogram("segment_wall_s")
        if hist is None or hist.count == 0:
            return None
        return max(self.floor_s, self.scale * hist.percentile(0.95))

    # -- arming -------------------------------------------------------

    def watch(self, batch_id: str, jobs):
        """Context manager arming the watchdog for one dispatch."""
        return _Watch(self, batch_id, list(jobs))

    def _arm(self, batch_id, jobs):
        timeout = self.effective_timeout()
        if timeout is None:
            return
        self._ensure_thread()
        with self._lock:
            self._armed = (batch_id, jobs, _MONOTONIC(), timeout)
            self._fired_current = False

    def _disarm(self):
        with self._lock:
            self._armed = None
            self._fired_current = False

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dispatch-watchdog", daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- the thread ---------------------------------------------------

    def _run(self):
        while not self._stop.wait(self.poll_s):
            with self._lock:
                armed = self._armed
                fired = self._fired_current
            if armed is None or fired:
                continue
            batch_id, jobs, start, timeout = armed
            waited = _MONOTONIC() - start
            if waited <= timeout:
                continue
            with self._lock:
                if self._fired_current or self._armed is not armed:
                    continue
                self._fired_current = True
            self._fire(batch_id, jobs, timeout, waited)

    def _fire(self, batch_id, jobs, timeout, waited):
        with self._lock:
            self.stalled.append(batch_id)
        if self.recorder is not None:
            self.recorder.emit("dispatch_stalled", batch_id=batch_id,
                               timeout_s=timeout,
                               waited_s=round(waited, 6), jobs=jobs)
        if self.journal is not None:
            try:
                self.journal.append("batch_poison_suspect",
                                    batch_id=batch_id, jobs=jobs,
                                    timeout_s=timeout)
            except (OSError, rfaults.InjectedFault):
                pass  # the marker is advisory; the stall event stands

    def fired_for(self, batch_id: str) -> bool:
        with self._lock:
            return batch_id in self.stalled

    # -- chaos hook ---------------------------------------------------

    def stall_point(self, batch_id: str) -> None:
        """``dispatch.stall`` fault-site hook, called inside an armed
        window: a firing rule holds the 'dispatch' until the watchdog
        fires (bounded), then re-raises the fault as the hung call's
        eventual error — the closest CPU-testable analogue of a wedged
        device call that an orchestrator hard-kills."""
        try:
            rfaults.fault_point("dispatch.stall", batch_id=batch_id)
        except rfaults.InjectedFault:
            timeout = self.effective_timeout() or 0.0
            deadline = _MONOTONIC() + timeout + 5.0
            while (not self.fired_for(batch_id)
                   and _MONOTONIC() < deadline):
                time.sleep(self.poll_s)
            raise


class _Watch:
    def __init__(self, watchdog, batch_id, jobs):
        self._w = watchdog
        self._batch_id = batch_id
        self._jobs = jobs

    def __enter__(self):
        self._w._arm(self._batch_id, self._jobs)
        return self

    def __exit__(self, *exc):
        self._w._disarm()
