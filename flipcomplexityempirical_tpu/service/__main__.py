"""CLI: python -m flipcomplexityempirical_tpu.service
         --simulate --out /tmp/svc [--tenants 4] [--chains 2]
         [--compile-cache DIR] [--events PATH]
     or: ... --family frank --out plots/frank-svc [--steps N]
     or: ... serve ROOT [--port N] / worker ROOT / submit URL /
         status URL [JOB]

Fleet subcommands (PR 17 — the network front door)::

    serve ROOT    HTTP front door over the shared fleet root: quotas,
                  weighted-fair admission, the fleet journal. Blocks
                  until drained (POST /v1/drain or SIGTERM), exits 3.
    worker ROOT   one fleet worker process: claims spooled jobs via
                  atomic leases, runs each through its own
                  SweepService, publishes verdicts + artifacts.
    submit URL    POST one job (--workload NAME [--set k=v ...] or
                  --config FILE.json) as --tenant; prints the job doc;
                  --wait polls to a terminal status.
    status URL    GET fleet status, or one job's (status URL JOB_ID);
                  --artifact fetches the result summary instead.

With no subcommand the legacy flat interface below runs unchanged.

Exit codes (extends the 0/2/3 table in ``service.lifecycle``):

=====  ================================================================
code   meaning
=====  ================================================================
0      all jobs done (worker: all it executed; submit --wait: job done)
2      failures/quarantines present among executed/waited jobs
3      drained — server always exits 3 (serving only ends by drain);
       workers exit 3 when the drain marker/signal stopped them
4      client-side refusal: submit/status got an HTTP error (429 quota,
       503 draining, 400 bad request, 404 unknown job) or no server
=====  ================================================================

``--simulate`` is the hardware-free proof of the sweep service
(ISSUE 9): N coalescible tenants are submitted against one device and
drained as ONE batch, a solo tenant is measured for reference, and the
per-tenant end-to-end throughput ratio is printed as a bench-style
``tenant_efficiency`` record (also reachable as ``bench.py --service``).
The efficiency is measured on COLD turnarounds — submit-to-result
including the XLA compile the service pays on the tenant's behalf —
because compile amortization is precisely what coalescing buys: one
compile serves every tenant in the batch where serial solo service
would pay it N times.

Without ``--simulate``, a reference sweep family is submitted through
the service instead of the one-shot driver: fingerprint-equal configs
coalesce, failures retry/quarantine per the supervisor taxonomy, and
the exit code is nonzero when any job ends failed/quarantined (same
contract as the supervised experiments CLI).

Preemption contract (ISSUE 11): SIGTERM/SIGINT request a graceful
drain — in-flight work checkpoints per tenant at the next segment
boundary, running jobs requeue, and the process exits with code 3
(``service.EXIT_DRAINED``). ``--recover`` restarts from OUT's
``journal.jsonl`` instead of resubmitting: done jobs stay done,
requeued jobs resume from their last checkpoint bit-identically.
``--dispatch-timeout`` arms the hung-dispatch watchdog explicitly
(otherwise it scales itself from observed p95 segment latency).
"""

import argparse
import json
import os
import sys
import time

from ..obs import from_spec
from ..resilience import faults as rfaults
from ..resilience.supervisor import RetryPolicy
from ..experiments.config import SWEEPS, ExperimentConfig
from .cache import CompileCache, enable_persistent_cache
from .lifecycle import DrainController
from .scheduler import SweepService

# families whose (alignment, base) grid gives coalescible-but-distinct
# tenants: alignment varies the initial plan, base the per-chain params
# — neither moves ExperimentConfig.fingerprint(), both move the tag
_SIM_FAMILIES = ("frank", "sec11")


def tenant_configs(tenants: int, chains: int, steps: int,
                   family: str = "frank", seed: int = 3,
                   record_every: int = 1) -> list:
    """N fingerprint-equal tenant configs with distinct tags and seeds —
    the service coalesces them into one device batch."""
    if family not in _SIM_FAMILIES:
        raise ValueError(f"simulation families are {_SIM_FAMILIES}, "
                         f"got {family!r}")
    return [ExperimentConfig(family=family, alignment=(2, 1, 0)[i % 3],
                             base=0.3 + 0.01 * i, pop_tol=0.1,
                             total_steps=steps, n_chains=chains,
                             seed=seed + 13 * i,
                             record_every=record_every)
            for i in range(tenants)]


def _drain_cold(configs, outdir: str, recorder=None, heartbeat=None,
                compile_cache=None, policy=None) -> tuple:
    """Submit ``configs`` to a fresh service and drain; returns
    (turnaround_s, service). Cold for its batch shape: jit caches key on
    the chain count, so the solo and coalesced rounds each pay their own
    compile — exactly what a tenant experiences."""
    svc = SweepService(outdir=outdir, recorder=recorder,
                       heartbeat=heartbeat, compile_cache=compile_cache,
                       policy=policy)
    jobs = [svc.submit(c) for c in configs]
    t0 = time.perf_counter()
    svc.run_until_idle()
    wall = time.perf_counter() - t0
    bad = [(j.tag, j.status, j.error) for j in jobs if j.status != "done"]
    if bad:
        raise RuntimeError(f"simulation jobs did not complete: {bad}")
    return wall, svc


def run_simulation(tenants: int = 4, chains: int = 2, steps: int = 400,
                   family: str = "frank", seed: int = 3,
                   outdir: str = ".", recorder=None, heartbeat=None,
                   compile_cache=None, policy=None) -> dict:
    """The N-tenant coalescing measurement; returns the bench record.

    The coalesced round runs FIRST so any process-global first-dispatch
    warmup lands on the batch side — the reported efficiency is the
    conservative one."""
    import jax

    cfgs = tenant_configs(tenants, chains, steps, family=family,
                          seed=seed)
    wall_batch, svc_b = _drain_cold(
        cfgs, os.path.join(outdir, "tenants"), recorder=recorder,
        heartbeat=heartbeat, compile_cache=compile_cache, policy=policy)
    stats = svc_b.batch_stats
    if len(stats) != 1 or len(stats[0].jobs) != tenants:
        raise RuntimeError(
            f"expected one coalesced batch of {tenants} tenants, got "
            f"{[(s.batch_id, s.jobs) for s in stats]}")
    wall_solo, svc_s = _drain_cold(
        cfgs[:1], os.path.join(outdir, "solo"), recorder=recorder,
        compile_cache=compile_cache, policy=policy)
    eff = wall_solo / wall_batch
    return {
        "metric": "tenant_efficiency",
        "value": round(eff, 4),
        "unit": "ratio",
        "tenants": tenants,
        "chains_per_tenant": chains,
        "steps": steps,
        "family": family,
        "kernel_path": stats[0].kernel_path,
        "solo_turnaround_s": round(wall_solo, 3),
        "batch_turnaround_s": round(wall_batch, 3),
        # run-only occupancy view (excludes compile): how much slower
        # the coalesced device pass is than a solo pass
        "solo_run_s": round(svc_s.batch_stats[0].wall_s, 4),
        "batch_run_s": round(stats[0].wall_s, 4),
        "serial_service_s": round(tenants * wall_solo, 3),
        "device": jax.devices()[0].platform,
    }


EXIT_CLIENT_ERROR = 4


def _parse_overrides(pairs) -> dict:
    """``--set k=v`` pairs -> override dict; values parse as JSON when
    they can (numbers, bools, lists), else stay strings."""
    out = {}
    for pair in pairs or ():
        if "=" not in pair:
            raise SystemExit(f"--set expects k=v, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = json.loads(v)
        except ValueError:
            out[k] = v
    return out


def _parse_weights(spec):
    """``--weights a=2,b=1`` -> {tenant: weight} or None."""
    if not spec:
        return None
    out = {}
    for pair in spec.split(","):
        k, v = pair.split("=", 1)
        out[k.strip()] = int(v)
    return out


def _fleet_main(argv) -> int:
    from .client import ClientError, ServiceClient
    from .server import serve
    from .worker import Worker

    ap = argparse.ArgumentParser(
        prog="python -m flipcomplexityempirical_tpu.service")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="HTTP front door over ROOT")
    sp.add_argument("root")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0,
                    help="0 binds an OS-assigned port (see --ready-file)")
    sp.add_argument("--ready-file", default=None,
                    help="write {host, port, url, pid} JSON once bound "
                         "(default ROOT/server.json)")
    sp.add_argument("--events", default=None,
                    help="obs JSONL stream (default "
                         "ROOT/events/server.jsonl; 'none' disables)")
    sp.add_argument("--quota-rate", type=float, default=None,
                    metavar="R", help="per-tenant submissions/s "
                    "(default: unlimited)")
    sp.add_argument("--quota-burst", type=float, default=10.0)
    sp.add_argument("--weights", default=None, metavar="T=W,...",
                    help="admission weights per tenant (default 1)")
    sp.add_argument("--ttl", type=float, default=15.0,
                    help="lease TTL used for liveness in status views")
    sp.add_argument("--faults", default=None)

    wp = sub.add_parser("worker", help="one fleet worker over ROOT")
    wp.add_argument("root")
    wp.add_argument("--name", default=None,
                    help="worker id (default w<pid>)")
    wp.add_argument("--ttl", type=float, default=15.0)
    wp.add_argument("--hb", type=float, default=None,
                    help="heartbeat period (default TTL/3)")
    wp.add_argument("--poll", type=float, default=0.5)
    wp.add_argument("--idle-timeout", type=float, default=None,
                    help="exit after this long with nothing claimable "
                         "(default: poll forever)")
    wp.add_argument("--events", default=None,
                    help="obs JSONL stream (default "
                         "ROOT/events/<name>.jsonl; 'none' disables)")
    wp.add_argument("--compile-cache", default=None)
    wp.add_argument("--retries", type=int, default=3)
    wp.add_argument("--quarantine-after", type=int, default=2)
    wp.add_argument("--dispatch-timeout", type=float, default=None)
    wp.add_argument("--cpu", action="store_true")
    wp.add_argument("--faults", default=None)
    wp.add_argument("--verbose", action="store_true")

    bp = sub.add_parser("submit", help="submit one job to URL")
    bp.add_argument("url")
    bp.add_argument("--workload", default=None,
                    help="workload-catalog name (GET /v1/workloads)")
    bp.add_argument("--config", default=None, metavar="FILE",
                    help="full ExperimentConfig JSON doc")
    bp.add_argument("--set", dest="overrides", action="append",
                    metavar="K=V", help="workload override (repeat)")
    bp.add_argument("--tenant", default="default")
    bp.add_argument("--wait", action="store_true",
                    help="poll until the job is terminal")
    bp.add_argument("--timeout", type=float, default=600.0)

    tp = sub.add_parser("status", help="fleet (or one job's) status")
    tp.add_argument("url")
    tp.add_argument("job_id", nargs="?", default=None)
    tp.add_argument("--artifact", action="store_true",
                    help="fetch the job's result summary instead")
    tp.add_argument("--tenant", default="default")

    args = ap.parse_args(argv)

    if args.cmd == "serve":
        rfaults.install_from_spec(args.faults) if args.faults \
            else rfaults.install_from_env()
        os.makedirs(args.root, exist_ok=True)
        ready = args.ready_file or os.path.join(args.root,
                                                "server.json")
        # canonical fleet stream layout: every process appends to its
        # own ROOT/events/<name>.jsonl (one writer per file); the
        # FleetCollector behind /v1/metrics tails exactly this dir
        events = args.events
        if events is None:
            events = os.path.join(args.root, "events", "server.jsonl")
            os.makedirs(os.path.dirname(events), exist_ok=True)
        elif events == "none":
            events = None
        with from_spec(events,
                       ident={"pid": os.getpid(),
                              "worker_name": "server"}) as rec:
            return serve(args.root, host=args.host, port=args.port,
                         recorder=rec, ready_file=ready,
                         quota_rate=args.quota_rate,
                         quota_burst=args.quota_burst,
                         weights=_parse_weights(args.weights),
                         ttl_s=args.ttl)

    if args.cmd == "worker":
        if args.cpu:
            import jax
            jax.config.update("jax_platforms", "cpu")
        rfaults.install_from_spec(args.faults) if args.faults \
            else rfaults.install_from_env()
        policy = RetryPolicy(max_retries=args.retries,
                             quarantine_after=args.quarantine_after)
        name = args.name or f"w{os.getpid()}"
        events = args.events
        if events is None:
            events = os.path.join(args.root, "events", f"{name}.jsonl")
            os.makedirs(os.path.dirname(events), exist_ok=True)
        elif events == "none":
            events = None
        with from_spec(events,
                       ident={"pid": os.getpid(),
                              "worker_name": name}) as rec:
            compile_cache = None
            if args.compile_cache:
                enable_persistent_cache(args.compile_cache)
                compile_cache = CompileCache(args.compile_cache,
                                             recorder=rec)
            worker = Worker(args.root, worker=name,
                            ttl_s=args.ttl, hb_s=args.hb,
                            poll_s=args.poll,
                            idle_timeout_s=args.idle_timeout,
                            recorder=rec,
                            compile_cache=compile_cache,
                            policy=policy,
                            dispatch_timeout=args.dispatch_timeout,
                            verbose=args.verbose)
            with DrainController():
                return worker.run()

    client = ServiceClient(args.url, tenant=args.tenant)
    try:
        if args.cmd == "submit":
            config = None
            if args.config:
                with open(args.config, "r", encoding="utf-8") as f:
                    config = json.load(f)
            out = client.submit(workload=args.workload, config=config,
                                overrides=_parse_overrides(
                                    args.overrides))
            if args.wait:
                out = client.wait(out["job_id"],
                                  timeout_s=args.timeout)
            print(json.dumps(out, sort_keys=True))
            if args.wait and out.get("status") != "done":
                return 2
            return 0
        # status
        if args.artifact:
            if not args.job_id:
                raise SystemExit("status --artifact needs a JOB_ID")
            out = client.artifact(args.job_id)
        elif args.job_id:
            out = client.status(args.job_id)
        else:
            out = client.jobs()
        print(json.dumps(out, sort_keys=True))
        return 0
    except (ClientError, ValueError, OSError) as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return EXIT_CLIENT_ERROR


def main():
    # Fleet subcommands dispatch on the first positional token; any
    # flag-led invocation is the legacy flat interface, untouched.
    if len(sys.argv) > 1 and sys.argv[1] in ("serve", "worker",
                                             "submit", "status"):
        sys.exit(_fleet_main(sys.argv[1:]))
    ap = argparse.ArgumentParser()
    ap.add_argument("--simulate", action="store_true",
                    help="N-tenant coalescing measurement on this host "
                         "(no hardware assumptions); prints a "
                         "tenant_efficiency bench record")
    ap.add_argument("--family", choices=sorted(SWEEPS), default="frank",
                    help="sweep family to submit through the service "
                         "(simulation mode: frank|sec11)")
    ap.add_argument("--out", required=True)
    ap.add_argument("--tenants", type=int, default=4,
                    help="simulation: coalescible tenants sharing the "
                         "device")
    ap.add_argument("--chains", type=int, default=2)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--record-every", type=int, default=1)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--only", nargs="*", default=None,
                    help="config tags to submit, e.g. 2B30P10")
    ap.add_argument("--events", metavar="PATH", default=None,
                    help="append obs JSONL (job_submitted/job_batched/"
                         "compile_cache_* and all runner events) to "
                         "PATH; '-' streams to stderr")
    ap.add_argument("--heartbeat", metavar="PATH", default=None,
                    help="merged service heartbeat JSON (per-job files "
                         "appear as heartbeat.<tag>.json next to it); "
                         "defaults to OUT/heartbeat.json")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persistent compile cache directory: wires "
                         "JAX's on-disk XLA cache AND the service's "
                         "signature index, so restarts skip compiles "
                         "and report hits; the directory is stamped "
                         "into every run_start event")
    ap.add_argument("--max-batch-chains", type=int, default=None,
                    help="cap on total chains per coalesced batch "
                         "(default: unbounded)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--faults", metavar="SPEC", default=None,
                    help="fault-injection plan (resilience/faults.py "
                         "grammar); overrides GRAFT_FAULTS")
    ap.add_argument("--retries", type=int, default=3)
    ap.add_argument("--quarantine-after", type=int, default=2)
    ap.add_argument("--deadline", type=float, default=None, metavar="S",
                    help="per-batch wall budget in seconds")
    ap.add_argument("--dispatch-timeout", type=float, default=None,
                    metavar="S",
                    help="hung-dispatch watchdog budget per device "
                         "dispatch; default scales from observed p95 "
                         "segment latency (unarmed until one exists)")
    ap.add_argument("--recover", action="store_true",
                    help="rebuild the queue from OUT/journal.jsonl "
                         "instead of submitting --family configs: done "
                         "jobs stay done, interrupted jobs resume from "
                         "their last checkpoint")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.faults is not None:
        rfaults.install_from_spec(args.faults)
    else:
        rfaults.install_from_env()
    os.makedirs(args.out, exist_ok=True)
    heartbeat = args.heartbeat or os.path.join(args.out,
                                               "heartbeat.json")
    policy = RetryPolicy(max_retries=args.retries,
                         quarantine_after=args.quarantine_after,
                         deadline_s=args.deadline, seed=args.seed)
    compile_cache = None
    with from_spec(args.events) as rec:
        if args.compile_cache:
            enable_persistent_cache(args.compile_cache)
            compile_cache = CompileCache(args.compile_cache,
                                         recorder=rec)
            if rec:
                rec.run_meta["compile_cache_dir"] = args.compile_cache
        if args.simulate:
            record = run_simulation(
                tenants=args.tenants, chains=args.chains,
                steps=args.steps, family=args.family, seed=args.seed,
                outdir=args.out, recorder=rec, heartbeat=heartbeat,
                compile_cache=compile_cache, policy=policy)
            print(json.dumps(record))
            return
        svc_kwargs = dict(checkpoint_dir=args.checkpoint_dir,
                          recorder=rec, heartbeat=heartbeat,
                          compile_cache=compile_cache, policy=policy,
                          max_batch_chains=args.max_batch_chains,
                          dispatch_timeout=args.dispatch_timeout,
                          verbose=True)
        if args.recover:
            svc = SweepService.recover(args.out, **svc_kwargs)
        else:
            sweep = SWEEPS[args.family]
            configs = list(sweep(total_steps=args.steps,
                                 n_chains=args.chains, seed=args.seed,
                                 record_every=args.record_every))
            if args.only:
                configs = [c for c in configs if c.tag in set(args.only)]
            svc = SweepService(outdir=args.out, **svc_kwargs)
            for cfg in configs:
                svc.submit(cfg)
        with DrainController():
            svc.run_until_idle()
        sys.exit(svc.exit_code)


if __name__ == "__main__":
    main()
