"""CLI: python -m flipcomplexityempirical_tpu.service
         --simulate --out /tmp/svc [--tenants 4] [--chains 2]
         [--compile-cache DIR] [--events PATH]
     or: ... --family frank --out plots/frank-svc [--steps N]

``--simulate`` is the hardware-free proof of the sweep service
(ISSUE 9): N coalescible tenants are submitted against one device and
drained as ONE batch, a solo tenant is measured for reference, and the
per-tenant end-to-end throughput ratio is printed as a bench-style
``tenant_efficiency`` record (also reachable as ``bench.py --service``).
The efficiency is measured on COLD turnarounds — submit-to-result
including the XLA compile the service pays on the tenant's behalf —
because compile amortization is precisely what coalescing buys: one
compile serves every tenant in the batch where serial solo service
would pay it N times.

Without ``--simulate``, a reference sweep family is submitted through
the service instead of the one-shot driver: fingerprint-equal configs
coalesce, failures retry/quarantine per the supervisor taxonomy, and
the exit code is nonzero when any job ends failed/quarantined (same
contract as the supervised experiments CLI).

Preemption contract (ISSUE 11): SIGTERM/SIGINT request a graceful
drain — in-flight work checkpoints per tenant at the next segment
boundary, running jobs requeue, and the process exits with code 3
(``service.EXIT_DRAINED``). ``--recover`` restarts from OUT's
``journal.jsonl`` instead of resubmitting: done jobs stay done,
requeued jobs resume from their last checkpoint bit-identically.
``--dispatch-timeout`` arms the hung-dispatch watchdog explicitly
(otherwise it scales itself from observed p95 segment latency).
"""

import argparse
import json
import os
import sys
import time

from ..obs import from_spec
from ..resilience import faults as rfaults
from ..resilience.supervisor import RetryPolicy
from ..experiments.config import SWEEPS, ExperimentConfig
from .cache import CompileCache, enable_persistent_cache
from .lifecycle import DrainController
from .scheduler import SweepService

# families whose (alignment, base) grid gives coalescible-but-distinct
# tenants: alignment varies the initial plan, base the per-chain params
# — neither moves ExperimentConfig.fingerprint(), both move the tag
_SIM_FAMILIES = ("frank", "sec11")


def tenant_configs(tenants: int, chains: int, steps: int,
                   family: str = "frank", seed: int = 3,
                   record_every: int = 1) -> list:
    """N fingerprint-equal tenant configs with distinct tags and seeds —
    the service coalesces them into one device batch."""
    if family not in _SIM_FAMILIES:
        raise ValueError(f"simulation families are {_SIM_FAMILIES}, "
                         f"got {family!r}")
    return [ExperimentConfig(family=family, alignment=(2, 1, 0)[i % 3],
                             base=0.3 + 0.01 * i, pop_tol=0.1,
                             total_steps=steps, n_chains=chains,
                             seed=seed + 13 * i,
                             record_every=record_every)
            for i in range(tenants)]


def _drain_cold(configs, outdir: str, recorder=None, heartbeat=None,
                compile_cache=None, policy=None) -> tuple:
    """Submit ``configs`` to a fresh service and drain; returns
    (turnaround_s, service). Cold for its batch shape: jit caches key on
    the chain count, so the solo and coalesced rounds each pay their own
    compile — exactly what a tenant experiences."""
    svc = SweepService(outdir=outdir, recorder=recorder,
                       heartbeat=heartbeat, compile_cache=compile_cache,
                       policy=policy)
    jobs = [svc.submit(c) for c in configs]
    t0 = time.perf_counter()
    svc.run_until_idle()
    wall = time.perf_counter() - t0
    bad = [(j.tag, j.status, j.error) for j in jobs if j.status != "done"]
    if bad:
        raise RuntimeError(f"simulation jobs did not complete: {bad}")
    return wall, svc


def run_simulation(tenants: int = 4, chains: int = 2, steps: int = 400,
                   family: str = "frank", seed: int = 3,
                   outdir: str = ".", recorder=None, heartbeat=None,
                   compile_cache=None, policy=None) -> dict:
    """The N-tenant coalescing measurement; returns the bench record.

    The coalesced round runs FIRST so any process-global first-dispatch
    warmup lands on the batch side — the reported efficiency is the
    conservative one."""
    import jax

    cfgs = tenant_configs(tenants, chains, steps, family=family,
                          seed=seed)
    wall_batch, svc_b = _drain_cold(
        cfgs, os.path.join(outdir, "tenants"), recorder=recorder,
        heartbeat=heartbeat, compile_cache=compile_cache, policy=policy)
    stats = svc_b.batch_stats
    if len(stats) != 1 or len(stats[0].jobs) != tenants:
        raise RuntimeError(
            f"expected one coalesced batch of {tenants} tenants, got "
            f"{[(s.batch_id, s.jobs) for s in stats]}")
    wall_solo, svc_s = _drain_cold(
        cfgs[:1], os.path.join(outdir, "solo"), recorder=recorder,
        compile_cache=compile_cache, policy=policy)
    eff = wall_solo / wall_batch
    return {
        "metric": "tenant_efficiency",
        "value": round(eff, 4),
        "unit": "ratio",
        "tenants": tenants,
        "chains_per_tenant": chains,
        "steps": steps,
        "family": family,
        "kernel_path": stats[0].kernel_path,
        "solo_turnaround_s": round(wall_solo, 3),
        "batch_turnaround_s": round(wall_batch, 3),
        # run-only occupancy view (excludes compile): how much slower
        # the coalesced device pass is than a solo pass
        "solo_run_s": round(svc_s.batch_stats[0].wall_s, 4),
        "batch_run_s": round(stats[0].wall_s, 4),
        "serial_service_s": round(tenants * wall_solo, 3),
        "device": jax.devices()[0].platform,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--simulate", action="store_true",
                    help="N-tenant coalescing measurement on this host "
                         "(no hardware assumptions); prints a "
                         "tenant_efficiency bench record")
    ap.add_argument("--family", choices=sorted(SWEEPS), default="frank",
                    help="sweep family to submit through the service "
                         "(simulation mode: frank|sec11)")
    ap.add_argument("--out", required=True)
    ap.add_argument("--tenants", type=int, default=4,
                    help="simulation: coalescible tenants sharing the "
                         "device")
    ap.add_argument("--chains", type=int, default=2)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--record-every", type=int, default=1)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--only", nargs="*", default=None,
                    help="config tags to submit, e.g. 2B30P10")
    ap.add_argument("--events", metavar="PATH", default=None,
                    help="append obs JSONL (job_submitted/job_batched/"
                         "compile_cache_* and all runner events) to "
                         "PATH; '-' streams to stderr")
    ap.add_argument("--heartbeat", metavar="PATH", default=None,
                    help="merged service heartbeat JSON (per-job files "
                         "appear as heartbeat.<tag>.json next to it); "
                         "defaults to OUT/heartbeat.json")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persistent compile cache directory: wires "
                         "JAX's on-disk XLA cache AND the service's "
                         "signature index, so restarts skip compiles "
                         "and report hits; the directory is stamped "
                         "into every run_start event")
    ap.add_argument("--max-batch-chains", type=int, default=None,
                    help="cap on total chains per coalesced batch "
                         "(default: unbounded)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--faults", metavar="SPEC", default=None,
                    help="fault-injection plan (resilience/faults.py "
                         "grammar); overrides GRAFT_FAULTS")
    ap.add_argument("--retries", type=int, default=3)
    ap.add_argument("--quarantine-after", type=int, default=2)
    ap.add_argument("--deadline", type=float, default=None, metavar="S",
                    help="per-batch wall budget in seconds")
    ap.add_argument("--dispatch-timeout", type=float, default=None,
                    metavar="S",
                    help="hung-dispatch watchdog budget per device "
                         "dispatch; default scales from observed p95 "
                         "segment latency (unarmed until one exists)")
    ap.add_argument("--recover", action="store_true",
                    help="rebuild the queue from OUT/journal.jsonl "
                         "instead of submitting --family configs: done "
                         "jobs stay done, interrupted jobs resume from "
                         "their last checkpoint")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.faults is not None:
        rfaults.install_from_spec(args.faults)
    else:
        rfaults.install_from_env()
    os.makedirs(args.out, exist_ok=True)
    heartbeat = args.heartbeat or os.path.join(args.out,
                                               "heartbeat.json")
    policy = RetryPolicy(max_retries=args.retries,
                         quarantine_after=args.quarantine_after,
                         deadline_s=args.deadline, seed=args.seed)
    compile_cache = None
    with from_spec(args.events) as rec:
        if args.compile_cache:
            enable_persistent_cache(args.compile_cache)
            compile_cache = CompileCache(args.compile_cache,
                                         recorder=rec)
            if rec:
                rec.run_meta["compile_cache_dir"] = args.compile_cache
        if args.simulate:
            record = run_simulation(
                tenants=args.tenants, chains=args.chains,
                steps=args.steps, family=args.family, seed=args.seed,
                outdir=args.out, recorder=rec, heartbeat=heartbeat,
                compile_cache=compile_cache, policy=policy)
            print(json.dumps(record))
            return
        svc_kwargs = dict(checkpoint_dir=args.checkpoint_dir,
                          recorder=rec, heartbeat=heartbeat,
                          compile_cache=compile_cache, policy=policy,
                          max_batch_chains=args.max_batch_chains,
                          dispatch_timeout=args.dispatch_timeout,
                          verbose=True)
        if args.recover:
            svc = SweepService.recover(args.out, **svc_kwargs)
        else:
            sweep = SWEEPS[args.family]
            configs = list(sweep(total_steps=args.steps,
                                 n_chains=args.chains, seed=args.seed,
                                 record_every=args.record_every))
            if args.only:
                configs = [c for c in configs if c.tag in set(args.only)]
            svc = SweepService(outdir=args.out, **svc_kwargs)
            for cfg in configs:
                svc.submit(cfg)
        with DrainController():
            svc.run_until_idle()
        sys.exit(svc.exit_code)


if __name__ == "__main__":
    main()
