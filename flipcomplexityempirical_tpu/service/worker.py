"""Worker fleet: crash-interchangeable executors over a shared spool.

The front door (``service.server``) turns HTTP submissions into job
docs spooled under one shared fleet root; this module is the other half
of the contract — N worker *processes* that claim those jobs, run each
through its own single-job ``SweepService`` (so every PR 10/11
guarantee — write-ahead journal, checkpointed segments, supervisor
taxonomy, bit-identical recovery — applies per job, now across
processes), and publish terminal verdicts + artifact summaries back
into the shared root.

Fleet root layout (everything the fleet shares is a file)::

    root/
      journal.jsonl        server's WAL (job_submitted/job_admitted)
      jobs/<id>.json       admitted job docs: config + tenant + admit_seq
      leases/<id>.lease    atomic claim files (this module)
      started/<id>.json    first-claim marker (queue-to-start anchor)
      status/<id>.json     terminal verdict (done/failed/quarantined)
      artifacts/<id>.json  result summary + array sha256 (DONE jobs)
      run/<id>/            the job's own SweepService outdir + journal
      ckpt/                shared sliced checkpoints (resume points)
      events/<name>.jsonl  per-process obs streams (server + workers)
      workers/<name>.json  per-worker heartbeat docs (pid, status, job)
      profile/<id>.json    on-demand profiling markers (service.profiling)
      DRAIN                fleet-wide drain marker (lifecycle)

**The lease protocol.** A job may be executed by at most one worker at
a time, with no coordinator: claims are ``O_CREAT|O_EXCL`` creates of
``leases/<id>.lease`` (atomic on POSIX — exactly one concurrent
claimer wins), liveness is the lease file's mtime refreshed by a
heartbeat thread every ``hb_s`` seconds, and expiry is
``now - mtime > ttl_s``. Reclaiming an expired (or torn — an
unparseable payload does NOT block the job) lease renames it to a
tombstone first: ``os.replace`` is atomic, so of two workers racing a
stale lease exactly one wins the rename and the loser's subsequent
claim sees the winner's fresh lease. mtime (not a payload timestamp)
carries liveness so tests age leases deterministically with
``os.utime`` and a torn payload cannot forge freshness.

Why per-job run dirs instead of N appenders on one journal: the
journal's integrity contract is a contiguous ``seq`` per file —
cross-process interleaved appends would tear it by construction. One
writer per file is the discipline everywhere: the server owns
``journal.jsonl``, and whichever worker holds a job's lease owns
``run/<id>/journal.jsonl`` (a reclaim re-opens it through
``SweepService.recover``, continuing the same file's story).

The ``worker.sigkill`` fault site is consulted on every heartbeat
beat: an armed rule SIGKILLs this process mid-run — the chaos stand-in
for a preempted node. The job's lease goes stale, a surviving worker
breaks it (``lease_expired``), and ``recover`` resumes from the sliced
checkpoint bit-identically — `make fleet-check` gates the whole story.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import threading
import time
from typing import Optional

import numpy as np

from .. import obs
from ..resilience import faults as rfaults
from ..resilience.supervisor import RetryPolicy
from . import journal as jnl
from . import lifecycle
from . import profiling
from . import queue as q
from .profiling import PROFILE_DIR
from .scheduler import SweepService

JOBS_DIR = "jobs"
LEASES_DIR = "leases"
STARTED_DIR = "started"
STATUS_DIR = "status"
ARTIFACTS_DIR = "artifacts"
RUN_DIR = "run"
CKPT_DIR = "ckpt"
EVENTS_DIR = "events"
WORKERS_DIR = "workers"


def fleet_dirs(root: str) -> dict:
    """Ensure and return the shared fleet subdirectories."""
    dirs = {name: os.path.join(root, name)
            for name in (JOBS_DIR, LEASES_DIR, STARTED_DIR, STATUS_DIR,
                         ARTIFACTS_DIR, RUN_DIR, CKPT_DIR, EVENTS_DIR,
                         WORKERS_DIR, PROFILE_DIR)}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)
    return dirs


def _write_json_atomic(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    """Parsed JSON doc, or None when missing/torn (callers treat torn
    exactly like missing — a half-written doc must never wedge the
    fleet)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def result_summary(job: q.Job, worker: str,
                   job_id: Optional[str] = None) -> dict:
    """Compact JSON artifact for one DONE job: every scalar field of the
    run data plus a single SHA-256 over all array leaves (sorted key
    order, shape/dtype folded in). The digest is the fleet's
    bit-identity witness: a job resumed by a different worker after a
    SIGKILL must produce the same digest a solo uninterrupted run does
    (timing fields are scalars, so they never enter it).

    ``job_id`` is the FLEET job id; ``job.job_id`` is the per-job
    SweepService's internal numbering (always j0000 in a one-job
    service) and must never name shared-root files."""
    data = job.result or {}
    h = hashlib.sha256()
    arrays: dict = {}

    def fold(prefix: str, val):
        if isinstance(val, dict):
            for k in sorted(val):
                fold(f"{prefix}/{k}", val[k])
        elif hasattr(val, "shape") and hasattr(val, "dtype"):
            arr = np.ascontiguousarray(np.asarray(val))
            h.update(prefix.encode("utf-8"))
            h.update(str(arr.dtype).encode("utf-8"))
            h.update(repr(arr.shape).encode("utf-8"))
            h.update(arr.tobytes())
            arrays[prefix] = {"shape": list(arr.shape),
                              "dtype": str(arr.dtype)}

    for key in sorted(data):
        fold(key, data[key])
    scalars = {k: v for k, v in data.items()
               if v is None or isinstance(v, (str, int, float, bool))}
    return {
        "job_id": job_id or job.job_id,
        "tag": job.tag,
        "status": job.status,
        "attempts": job.attempts,
        "worker": worker,
        "result_sha256": h.hexdigest() if arrays else None,
        "arrays": arrays,
        "summary": scalars,
    }


class Lease:
    """Handle for one held lease; returned by ``LeaseManager.claim``."""

    def __init__(self, manager: "LeaseManager", job_id: str):
        self._mgr = manager
        self.job_id = job_id
        self.released = False

    @property
    def path(self) -> str:
        return self._mgr.path(self.job_id)

    def refresh(self) -> None:
        self._mgr.refresh(self.job_id)

    def release(self) -> None:
        if not self.released:
            self.released = True
            self._mgr.release(self.job_id)


class LeaseManager:
    """Atomic lease files with mtime-heartbeat liveness (module doc has
    the full protocol). One instance per worker process."""

    def __init__(self, root: str, worker: str, ttl_s: float = 15.0,
                 clock=time.time, recorder=None):
        self.root = root
        self.dir = os.path.join(root, LEASES_DIR)
        os.makedirs(self.dir, exist_ok=True)
        self.worker = worker
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._rec = obs.resolve_recorder(recorder)
        self._tomb_seq = 0
        # trace context per held job: rides every lease payload so the
        # lease file itself witnesses which distributed trace owns it.
        # Claim/release mutate on the worker's job thread while the
        # lease heartbeat thread reads it through _payload — hence the
        # lock.
        self._traces: dict = {}
        self._traces_lock = threading.Lock()

    def path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.lease")

    def holder(self, job_id: str) -> Optional[dict]:
        """The lease payload ({worker, pid, ts[, trace]}), or None when
        the lease is missing or torn."""
        return _read_json(self.path(job_id))

    def age_s(self, job_id: str) -> Optional[float]:
        """Seconds since the lease's last heartbeat (mtime), or None
        when no lease exists. Compares the injected clock against
        mtime, so tests age leases with ``os.utime``."""
        try:
            mtime = os.path.getmtime(self.path(job_id))
        except OSError:
            return None
        return self._clock() - mtime

    def live(self, job_id: str) -> bool:
        age = self.age_s(job_id)
        return age is not None and age <= self.ttl_s

    def _payload(self, job_id: str) -> dict:
        doc = {"worker": self.worker, "pid": os.getpid(),
               "ts": self._clock()}
        with self._traces_lock:
            trace = self._traces.get(job_id)
        if trace:
            doc["trace"] = trace
        return doc

    def _create(self, path: str, job_id: str) -> bool:
        """One O_EXCL create attempt; False when somebody else holds
        the name. The ``lease.write`` fault site raises *before* the
        create (a claim that never lands) and its truncate rules tear
        the payload *after* (the torn lease a peer must not block on)."""
        rfaults.fault_point("lease.write", path=path,
                            worker=self.worker)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(self._payload(job_id), f)
            f.flush()
            os.fsync(f.fileno())
        rfaults.corrupt_file("lease.write", path)
        return True

    def claim(self, job_id: str,
              trace: Optional[dict] = None) -> Optional[Lease]:
        """Try to acquire ``job_id``'s lease. Returns a Lease, or None
        when a live peer holds it (or we lost a reclaim race —
        indistinguishable, and equally retriable next scan). ``trace``
        is the job's submit-time trace context (from the spool doc): it
        rides the lease payload and stamps the claim events, so the
        lease protocol itself is visible in the job's distributed
        trace."""
        with self._traces_lock:
            self._traces[job_id] = dict(trace or {})
            trace_id = self._traces[job_id].get("trace_id")
        path = self.path(job_id)
        reclaim = False
        if not self._create(path, job_id):
            if self.live(job_id):
                return None
            # Stale or torn: break it via an atomic rename — exactly
            # one of N racing reclaimers wins the replace.
            prev = self.holder(job_id) or {}
            age = self.age_s(job_id)
            if age is not None:
                tomb = (f"{path}.expired."
                        f"{self.worker}.{self._tomb_seq}")
                self._tomb_seq += 1
                try:
                    os.replace(path, tomb)
                except FileNotFoundError:
                    return None       # a peer broke it first
                self._rec.emit("lease_expired", job_id=job_id,
                               worker=prev.get("worker", "unknown"),
                               by=self.worker,
                               age_s=round(age, 3),
                               trace_id=trace_id)
                reclaim = True
            # else: released between checks — plain fresh claim below
            if not self._create(path, job_id):
                return None           # a third claimer slipped in
        self._rec.emit("lease_acquired", job_id=job_id,
                       worker=self.worker, reclaim=reclaim,
                       trace_id=trace_id)
        return Lease(self, job_id)

    def refresh(self, job_id: str) -> None:
        """Heartbeat: rewrite the payload atomically, advancing mtime.
        Raises on an armed ``lease.write`` fault — the caller skips the
        beat and the lease ages toward expiry (the chaos story)."""
        path = self.path(job_id)
        rfaults.fault_point("lease.write", path=path,
                            worker=self.worker)
        tmp = f"{path}.hb.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._payload(job_id), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        rfaults.corrupt_file("lease.write", path)

    def release(self, job_id: str) -> None:
        with self._traces_lock:
            self._traces.pop(job_id, None)
        try:
            os.remove(self.path(job_id))
        except FileNotFoundError:
            pass        # expired + reclaimed out from under us


class _LeaseHeartbeat(threading.Thread):
    """Daemon thread refreshing one held lease every ``hb_s`` seconds.
    Each beat consults the ``worker.sigkill`` fault site first: an
    armed rule hard-kills the process (uncatchable, mid-dispatch) —
    the closest CPU-testable analogue of node preemption. A failed
    refresh (armed ``lease.write``, full disk) skips the beat; the
    lease simply ages. ``beat_fn`` (optional, best-effort) runs every
    beat — the worker passes its own heartbeat-file writer so
    ``workers/<name>.json`` stays fresh through a long job whose run()
    loop never spins."""

    def __init__(self, lease: Lease, hb_s: float, beat_fn=None):
        super().__init__(name=f"lease-hb-{lease.job_id}", daemon=True)
        self._lease = lease
        self._hb_s = hb_s
        self._beat_fn = beat_fn
        # NB: not `_stop` — that name is Thread internals.
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self._hb_s):
            try:
                rfaults.fault_point("worker.sigkill",
                                    job_id=self._lease.job_id)
            except rfaults.InjectedFault:
                os.kill(os.getpid(), signal.SIGKILL)
            if self._beat_fn is not None:
                try:
                    self._beat_fn()
                except OSError:
                    pass
            try:
                self._lease.refresh()
            except (OSError, rfaults.InjectedFault):
                continue

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5.0)


class Worker:
    """One fleet worker: scan the spool in admission order, claim, run,
    publish. ``run()`` loops until drained/idle; ``run_once()`` is one
    scan pass (tests drive it directly)."""

    def __init__(self, root: str, worker: Optional[str] = None,
                 ttl_s: float = 15.0, hb_s: Optional[float] = None,
                 poll_s: float = 0.5,
                 idle_timeout_s: Optional[float] = None,
                 recorder=None, compile_cache=None,
                 policy: Optional[RetryPolicy] = None,
                 dispatch_timeout: Optional[float] = None,
                 clock=time.time, verbose: bool = False):
        self.root = root
        self.dirs = fleet_dirs(root)
        self.worker = worker or f"w{os.getpid()}"
        self._rec = obs.resolve_recorder(recorder)
        self._clock = clock
        self.ttl_s = float(ttl_s)
        # Three beats per TTL: one lost beat (fault, disk hiccup) never
        # expires a healthy worker's lease.
        self.hb_s = float(hb_s) if hb_s is not None else self.ttl_s / 3.0
        self.poll_s = float(poll_s)
        self.idle_timeout_s = idle_timeout_s
        self.compile_cache = compile_cache
        self.policy = policy
        self.dispatch_timeout = dispatch_timeout
        self.verbose = verbose
        self.leases = LeaseManager(root, self.worker, ttl_s=ttl_s,
                                   clock=clock, recorder=recorder)
        self.executed: list = []      # (job_id, status) this process ran
        self.failures = 0             # failed/quarantined among those
        self.heartbeat_path = os.path.join(self.dirs[WORKERS_DIR],
                                           f"{self.worker}.json")

    def _beat(self, status: str, job_id: Optional[str] = None) -> None:
        """Refresh ``workers/<name>.json`` — the per-worker liveness doc
        ``obs_report --heartbeat`` probes (mtime carries freshness, like
        leases). Written from the run() loop between jobs and from the
        lease heartbeat thread during one, so a long job never looks
        dead. Atomic: probes must never see a torn doc."""
        _write_json_atomic(self.heartbeat_path, {
            "worker": self.worker, "pid": os.getpid(),
            "ts": self._clock(), "status": status, "job_id": job_id,
            "hb_s": self.hb_s})

    # -- spool views --------------------------------------------------

    def spooled(self) -> list:
        """Admitted job docs in admission order (torn docs skipped —
        the server's spool write is atomic, so torn means mid-replace
        on a non-POSIX filesystem; the next scan sees it whole)."""
        docs = []
        try:
            names = os.listdir(self.dirs[JOBS_DIR])
        except OSError:
            return []
        for name in names:
            if not name.endswith(".json"):
                continue
            doc = _read_json(os.path.join(self.dirs[JOBS_DIR], name))
            if doc is not None and "job_id" in doc:
                docs.append(doc)
        docs.sort(key=lambda d: (d.get("admit_seq", 0), d["job_id"]))
        return docs

    def status_path(self, job_id: str) -> str:
        return os.path.join(self.dirs[STATUS_DIR], f"{job_id}.json")

    def terminal(self, job_id: str) -> Optional[dict]:
        return _read_json(self.status_path(job_id))

    def all_terminal(self) -> bool:
        return all(self.terminal(d["job_id"]) is not None
                   for d in self.spooled())

    # -- execution ----------------------------------------------------

    def _mark_started(self, job_id: str) -> bool:
        """First-claim marker (O_EXCL — first worker wins, reclaims
        keep the original anchor): queue-to-start is measured from the
        job's FIRST execution start, not a post-crash resume. Returns
        True when THIS call planted the marker (first execution)."""
        path = os.path.join(self.dirs[STARTED_DIR], f"{job_id}.json")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump({"job_id": job_id, "worker": self.worker,
                       "started_ts": self._clock()}, f)
            f.flush()
            os.fsync(f.fileno())
        return True

    def _publish(self, job: q.Job, doc: dict) -> None:
        # doc["job_id"] is the FLEET id; job.job_id is the per-job
        # service's internal numbering (j0000 for every one-job
        # service) and must not key anything in the shared root.
        job_id = doc["job_id"]
        if job.status == q.DONE:
            art = result_summary(job, self.worker, job_id=job_id)
            if job.result is None:
                # recovered-DONE edge: the journal says done but the
                # arrays died with the previous worker; the verdict
                # stands, the digest is honestly absent
                art["recovered"] = True
            _write_json_atomic(
                os.path.join(self.dirs[ARTIFACTS_DIR],
                             f"{job_id}.json"), art)
        started = _read_json(os.path.join(self.dirs[STARTED_DIR],
                                          f"{job_id}.json")) or {}
        _write_json_atomic(self.status_path(job_id), {
            "job_id": job_id,
            "tag": job.tag,
            "tenant": doc.get("tenant"),
            "status": job.status,
            "attempts": job.attempts,
            "error": job.error,
            "worker": self.worker,
            "submitted_ts": doc.get("submitted_ts"),
            "started_ts": started.get("started_ts"),
            "finished_ts": self._clock(),
        })

    def _execute(self, lease: Lease, doc: dict) -> bool:
        """Run one claimed job to a terminal state (or to a drain
        boundary). Returns True when a terminal verdict was published.

        Runs under the job's adopted trace context (``obs.adopt``): the
        queue_wait back-stamp, the ``job`` span, and every span the
        per-job SweepService opens on this thread all join the submit
        span's trace, so one Perfetto timeline tells the job's whole
        cross-process story."""
        job_id = doc["job_id"]
        trace = doc.get("trace") or {}
        first = self._mark_started(job_id)
        hb = _LeaseHeartbeat(lease, self.hb_s,
                             beat_fn=lambda: self._beat("running",
                                                        job_id=job_id))
        hb.start()
        rundir = os.path.join(self.dirs[RUN_DIR], job_id)
        # per-job checkpoint subdir: the ckpt tree is shared (any
        # worker can resume any job) but jobs with equal tags must not
        # clobber each other's resume points
        ckpt_dir = os.path.join(self.dirs[CKPT_DIR], job_id)
        os.makedirs(ckpt_dir, exist_ok=True)
        kwargs = dict(checkpoint_dir=ckpt_dir,
                      recorder=self._rec,
                      compile_cache=self.compile_cache,
                      policy=self.policy,
                      dispatch_timeout=self.dispatch_timeout,
                      clock=self._clock, verbose=self.verbose)
        watcher = profiling.ProfileWatcher(self.root, job_id,
                                           self.worker,
                                           recorder=self._rec,
                                           clock=self._clock)
        prev_watcher = profiling.install(watcher)
        try:
            with obs.adopt(self._rec, trace):
                sub_ts = doc.get("submitted_ts")
                if first and isinstance(sub_ts, (int, float)):
                    # back-stamp the spool wait: begins at submission,
                    # ends now (first claim) — visible queue time in
                    # the job's trace without a live server-side span
                    obs.emit_span_at(
                        self._rec, "queue_wait", ts_begin=sub_ts,
                        dur_s=max(0.0, self._clock() - sub_ts),
                        job_id=job_id, worker=self.worker)
                with obs.span(self._rec, "job", job_id=job_id,
                              worker=self.worker, tag=doc.get("tag")):
                    if os.path.exists(jnl.journal_path_for(rundir)):
                        svc = SweepService.recover(rundir, **kwargs)
                    else:
                        svc = SweepService(rundir, **kwargs)
                        svc.submit(jnl.config_from_doc(doc["config"]))
                    svc.run_until_idle()
                    if svc.drained:
                        # requeued + checkpointed in the run journal;
                        # the released lease lets any worker resume
                        # after restart
                        return False
                    job = svc.queue.jobs()[0]
                    self._publish(job, doc)
                    self.executed.append((job_id, job.status))
                    if job.status != q.DONE:
                        self.failures += 1
                    if self.verbose:
                        print(f"[{self.worker}] {job_id} {job.tag} "
                              f"-> {job.status}")
                    return True
        finally:
            watcher.finish()
            profiling.install(prev_watcher)
            hb.stop()

    def run_once(self) -> int:
        """One spool scan: claim and run every claimable job. Returns
        the number of terminal verdicts published."""
        n = 0
        for doc in self.spooled():
            if (lifecycle.drain_requested() is not None
                    or lifecycle.drain_marked(self.root) is not None):
                break
            job_id = doc["job_id"]
            if self.terminal(job_id) is not None:
                continue
            lease = self.leases.claim(job_id, trace=doc.get("trace"))
            if lease is None:
                continue
            try:
                if self.terminal(job_id) is not None:
                    continue    # published between scan and claim
                if self._execute(lease, doc):
                    n += 1
            finally:
                lease.release()
        return n

    def run(self) -> int:
        """The worker loop: scan until drained (marker or signal) or
        idle past ``idle_timeout_s``. Returns the CLI exit code
        (0 / 2 failures / 3 drained)."""
        self._rec.emit("worker_started", worker=self.worker,
                       pid=os.getpid(), root=self.root)
        self._beat("idle")
        idle_t0 = time.monotonic()
        reason = "idle"
        while True:
            if lifecycle.drain_requested() is not None:
                reason = "drain"
                break
            marker = lifecycle.drain_marked(self.root)
            if marker is not None:
                reason = "drain"
                break
            did = self.run_once()
            self._beat("idle")
            if lifecycle.drain_requested() is not None:
                reason = "drain"
                break
            if did:
                idle_t0 = time.monotonic()
                continue
            if (self.idle_timeout_s is not None
                    and time.monotonic() - idle_t0
                    >= self.idle_timeout_s):
                reason = "done" if self.all_terminal() else "idle"
                break
            time.sleep(self.poll_s)
        self._rec.emit("worker_exited", worker=self.worker,
                       reason=reason, n_executed=len(self.executed),
                       n_failures=self.failures)
        # terminal heartbeat doc: probes exempt "exited" workers from
        # staleness (a clean exit is not a dead worker)
        self._beat("exited")
        if reason == "drain":
            return lifecycle.EXIT_DRAINED
        return 2 if self.failures else 0
