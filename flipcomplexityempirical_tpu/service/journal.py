"""Write-ahead job journal: the service's queue, made crash-durable.

PR 9's ``JobQueue`` is an in-memory list — a preemption forgets every
submission and every terminal verdict. The journal records each
job/batch state transition as one JSONL record *before* the transition
takes effect (write-ahead), so ``SweepService.recover`` can rebuild the
queue after a crash: DONE jobs stay done, RUNNING jobs requeue from
their last checkpoint, poison-suspect batches requeue SOLO.

Durability discipline matches PR 7's checkpoints: every append is
flushed and fsync'd before the mutation it describes proceeds, and the
record carries integrity metadata so a torn tail (the write the
preemption interrupted) is *detected*, not misread:

- ``seq``: contiguous 0-based sequence number — a gap means records
  were lost in the middle, which invalidates everything after it;
- ``sha256``: hex digest of the record's canonical JSON (sorted keys,
  compact separators, digest field excluded) — a torn or bit-rotted
  line fails this before it can corrupt recovery.

``Journal.read`` returns the longest intact prefix plus a truncation
flag; recovery drops the tail and emits ``journal_truncated``. The
``journal.append`` fault site raises before the write (a crash *before*
journaling) and its truncate rules tear the file after it (a crash
*during* journaling) — both halves of the torn-tail story are
chaos-testable on CPU.

Record kinds (one writer per journal FILE — the integrity contract is
a contiguous ``seq``, so cross-process appends are forbidden by
construction: the scheduler owns its outdir's journal, the fleet front
door owns the shared root's, and each fleet job's run dir has its own):

==================   ==================================================
kind                 meaning
==================   ==================================================
job_submitted        queue accepted a config; carries the full
                     ExperimentConfig dict so recovery can rebuild the
                     Job without the caller resubmitting
batch_started        a coalesced batch began executing; members are
                     RUNNING until a terminal/requeue record follows
job_done             terminal: completed
job_failed           terminal: retry budget exhausted
job_quarantined      terminal: poison config isolated
job_requeued         back to QUEUED (retry backoff or drain); carries
                     the solo flag and det_failures so recovery
                     preserves the supervisor taxonomy state
batch_poison_suspect the watchdog marked this batch's dispatch as hung;
                     on recovery its jobs retry SOLO
service_draining     drain request honored; RUNNING members of any
                     open batch were checkpointed and requeued
job_admitted         fleet front door only: the admission pump granted
                     this submission its ``admit_seq`` and spooled it
                     for the worker fleet (``replay`` ignores it — a
                     recovering scheduler never sees one)
==================   ==================================================
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Optional

from ..experiments.config import ExperimentConfig
from ..resilience import faults as rfaults

JOURNAL_NAME = "journal.jsonl"

_CANONICAL = dict(sort_keys=True, separators=(",", ":"))


def _record_digest(record: dict) -> str:
    body = {k: v for k, v in record.items() if k != "sha256"}
    payload = json.dumps(body, **_CANONICAL).encode("utf-8")
    return hashlib.sha256(payload).hexdigest()


def config_to_doc(cfg: ExperimentConfig) -> dict:
    return dataclasses.asdict(cfg)


def config_from_doc(doc: dict) -> ExperimentConfig:
    # JSON has no tuples; restore the fields the dataclass types as one.
    doc = dict(doc)
    if "betas" in doc:
        doc["betas"] = tuple(doc["betas"])
    return ExperimentConfig(**doc)


class Journal:
    """Append-only JSONL journal with fsync'd writes and per-record
    integrity. One instance per service; ``append`` is thread-safe (the
    watchdog thread journals poison-suspect markers concurrently with
    the scheduler)."""

    def __init__(self, path: str, clock=time.time):
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # Continue an existing journal's sequence (recover appends to
        # the same file it read, so one journal tells the whole story
        # across restarts).
        records, truncated = Journal.read(path)
        self.recovered_records = records
        self.dropped = 0
        if truncated:
            # Drop the torn tail ON DISK too: appending after garbage
            # would strand every later record behind the integrity
            # break. Rewrite the intact prefix atomically (tmp + fsync
            # + rename, the checkpoint discipline).
            with open(path, "r", encoding="utf-8") as f:
                n_lines = sum(1 for ln in f if ln.strip())
            self.dropped = max(1, n_lines - len(records))
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for record in records:
                    f.write(json.dumps(record, **_CANONICAL) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        self._seq = (records[-1]["seq"] + 1) if records else 0

    @property
    def seq(self) -> int:
        return self._seq

    def append(self, kind: str, **fields) -> dict:
        """Journal one transition: build, hash, append, flush, fsync.
        Returns the written record. The caller performs the transition
        only after this returns (write-ahead)."""
        with self._lock:
            rfaults.fault_point("journal.append", kind=kind)
            record = {"seq": self._seq, "ts": self._clock(),
                      "kind": kind}
            record.update(fields)
            record["sha256"] = _record_digest(record)
            line = json.dumps(record, **_CANONICAL) + "\n"
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())
            self._seq += 1
            # Truncate rules tear the tail AFTER a successful write —
            # the mid-write preemption recovery must detect.
            rfaults.corrupt_file("journal.append", self.path)
            return record

    @staticmethod
    def read(path: str):
        """``(records, truncated)``: the longest intact prefix of the
        journal at ``path``. A record is intact when its line parses,
        its sha256 matches the canonical body, and its seq continues
        the prefix. The first broken record invalidates itself and
        everything after it (a torn write means later appends never
        happened — the file is append-only)."""
        records: list = []
        truncated = False
        if not os.path.exists(path):
            return records, truncated
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                truncated = True
                break
            if not isinstance(record, dict):
                truncated = True
                break
            if record.get("sha256") != _record_digest(record):
                truncated = True
                break
            if record.get("seq") != len(records):
                truncated = True
                break
            records.append(record)
        return records, truncated


def replay(records) -> dict:
    """Fold journal records into per-job recovery state. Returns::

        {job_id: {"config": dict, "status": str, "solo": bool,
                  "attempts": int, "det_failures": int,
                  "error": str | None}}

    in submission order (dicts preserve insertion order, and job ids
    are assigned in submission order, so re-submitting in this order
    reproduces the original ids). Statuses use service.queue's
    vocabulary; RUNNING here means "was in flight at the crash" — the
    caller requeues those."""
    jobs: dict = {}
    batches: dict = {}   # batch_id -> member job_ids
    for record in records:
        kind = record["kind"]
        if kind == "job_submitted":
            jobs[record["job_id"]] = {
                "config": record["config"], "status": "queued",
                "solo": False, "attempts": 0, "det_failures": 0,
                "error": None,
            }
        elif kind == "batch_started":
            batches[record["batch_id"]] = list(record["jobs"])
            for jid in record["jobs"]:
                if jid in jobs:
                    jobs[jid]["status"] = "running"
                    # attempts is exactly the number of batches the
                    # job entered — no separate counter record needed.
                    jobs[jid]["attempts"] += 1
        elif kind == "job_done":
            if record["job_id"] in jobs:
                jobs[record["job_id"]]["status"] = "done"
        elif kind == "job_failed":
            if record["job_id"] in jobs:
                jobs[record["job_id"]]["status"] = "failed"
                jobs[record["job_id"]]["error"] = record.get("error")
        elif kind == "job_quarantined":
            if record["job_id"] in jobs:
                jobs[record["job_id"]]["status"] = "quarantined"
                jobs[record["job_id"]]["error"] = record.get("error")
        elif kind == "job_requeued":
            if record["job_id"] in jobs:
                jobs[record["job_id"]]["status"] = "queued"
                jobs[record["job_id"]]["solo"] = bool(
                    record.get("solo", False))
                jobs[record["job_id"]]["det_failures"] = int(
                    record.get("det_failures", 0))
        elif kind == "batch_poison_suspect":
            for jid in batches.get(record["batch_id"],
                                   record.get("jobs", ())):
                if jid in jobs:
                    jobs[jid]["solo"] = True
        # service_draining carries no per-job state: its RUNNING
        # members were individually journaled as job_requeued.
    return jobs


def journal_path_for(outdir: str) -> str:
    return os.path.join(outdir, JOURNAL_NAME)
