"""Job queue for the sweep service: config submissions as host records.

Deliberately boring — a list of ``Job`` dataclasses with submission-order
iteration. The interesting scheduling decisions (which jobs coalesce,
when to retry) live in ``scheduler.SweepService``; the queue only owns
identity (monotonic job ids), lifecycle status, and the
``job_submitted`` event. No threads: the service is a single host loop
driving batched device dispatches, matching the runners'
no-added-syncs contract (PROFILE.md).

Timestamps come from an injected ``clock`` (default ``time.time``) so
the seeded fault/replay harness can pin them — a journal replayed under
test reproduces byte-identical records. graftlint G007 flags bare
``time.time()`` calls anywhere in ``service/`` to keep it that way.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from .. import obs
from ..experiments.config import ExperimentConfig

# Job lifecycle. queued -> running -> done, with failed/quarantined as
# the supervisor-taxonomy terminals (resilience.supervisor).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
QUARANTINED = "quarantined"

TERMINAL = (DONE, FAILED, QUARANTINED)


@dataclasses.dataclass
class Job:
    """One submitted config and its service-side lifecycle."""

    job_id: str
    config: ExperimentConfig
    submitted_ts: float
    status: str = QUEUED
    attempts: int = 0                 # execution attempts so far
    det_failures: int = 0             # deterministic failures (quarantine)
    solo: bool = False                # isolation flag: never coalesce
    batch: Optional[str] = None       # last batch this job ran in
    error: Optional[str] = None       # last failure message
    result: Optional[dict] = None     # per-tenant data dict when DONE

    @property
    def tag(self) -> str:
        return self.config.tag

    @property
    def fingerprint(self) -> str:
        return self.config.fingerprint()


class JobQueue:
    """Submission-ordered job store. ``submit`` assigns ``j<K>`` ids and
    emits ``job_submitted``; ``runnable`` yields non-terminal jobs in
    submission order (the scheduler re-runs a retried job by flipping
    its status back to QUEUED)."""

    def __init__(self, recorder=None, clock=time.time):
        self._rec = obs.resolve_recorder(recorder)
        self._clock = clock
        self._jobs: list[Job] = []

    def submit(self, config: ExperimentConfig) -> Job:
        job = Job(job_id=f"j{len(self._jobs):04d}", config=config,
                  submitted_ts=self._clock())
        self._jobs.append(job)
        if self._rec:
            self._rec.emit("job_submitted", job_id=job.job_id,
                           tag=job.tag, family=config.family,
                           fingerprint=job.fingerprint,
                           n_chains=config.n_chains)
        return job

    def jobs(self) -> list[Job]:
        return list(self._jobs)

    def runnable(self) -> list[Job]:
        return [j for j in self._jobs if j.status == QUEUED]

    def active(self) -> list[Job]:
        return [j for j in self._jobs if j.status not in TERMINAL]

    def __len__(self) -> int:
        return len(self._jobs)
