"""The network front door: stdlib HTTP/JSON surface over the fleet.

``python -m flipcomplexityempirical_tpu.service serve OUT`` exposes the
sweep service to tenants who cannot ``import flipcomplexityempirical_tpu``
— the ROADMAP's "millions of users" axis finally has an entry point.
Threaded ``http.server``, JSON bodies, no dependencies:

=======  =====================  ==================================
method   route                  meaning
=======  =====================  ==================================
POST     /v1/jobs               submit: a workload-catalog name
                                (``{"workload": "frank", "overrides":
                                {...}}``) or a full ExperimentConfig
                                doc (``{"config": {...}}``) — PR 12
                                fingerprints are the request schema
GET      /v1/jobs               fleet status: every job + counts
GET      /v1/jobs/<id>          one job's status (queue-to-start
                                included once started)
GET      /v1/jobs/<id>/artifact result summary JSON (DONE jobs)
GET      /v1/workloads          catalog names a tenant may submit
GET      /v1/healthz            liveness + drain flag
POST     /v1/drain              graceful fleet drain (marker +
                                in-process flag, journaled)
GET      /v1/metrics            Prometheus text exposition: per-
                                worker + fleet-rollup counters /
                                gauges / histogram percentiles from
                                the FleetCollector (ISSUE 18)
GET      /v1/fleet              JSON live topology: workers, job
                                stages, stream tails, queue depth
POST     /v1/profile/<id>       drop the on-demand profiling marker
                                the owning worker honors at its next
                                segment boundary
GET      /v1/profile/<id>       profiling request + published
                                capture artifact, read-only
=======  =====================  ==================================

**Handler hygiene (the graftlint G009 contract).** Request threads
never touch ``SweepService`` — execution belongs to the worker fleet
(``service.worker``), reached only through the spool directory. A
submit handler does exactly three things: journals the submission
write-ahead, indexes it, and enqueues it for the admission pump; all
other handlers are read-only over the shared files. No handler calls
``time.time()`` (the clock is injected — PR 10's G007 rule) and no
handler mutates state it does not journal.

**Admission.** Behind the door sit per-tenant token buckets
(``quota_rate`` tokens/s, ``quota_burst`` cap — a refused take is an
HTTP 429 + ``quota_rejected`` event) and a weighted deficit
round-robin (``FairAdmission``): each tenant's accepted submissions
wait in their own FIFO, and the admission pump thread spools them to
``jobs/`` in weighted-fair interleaved order, assigning the
``admit_seq`` workers honor. One tenant's 10k-chain burst therefore
delays its *own* queue, not its neighbors' — Jain's fairness index
over queue-to-start is the bench gate (``tools/loadtest.py``).

The server is the ONE writer of the fleet journal (``journal.jsonl``):
``job_submitted`` (full config doc + tenant — the same record shape
``SweepService`` journals, so ``journal.replay`` folds it) and
``job_admitted`` records make a server restart lossless — pending
submissions re-enter the admission queue, spooled ones don't double.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .. import obs
from ..obs.aggregate import FleetCollector
from ..resilience import faults as rfaults
from ..workloads import registry as wreg
from . import journal as jnl
from . import lifecycle
from .worker import (ARTIFACTS_DIR, JOBS_DIR, PROFILE_DIR, STARTED_DIR,
                     STATUS_DIR, LeaseManager, _read_json,
                     _write_json_atomic, fleet_dirs)


class FrontDoorError(RuntimeError):
    """An HTTP-mappable refusal; ``status`` is the response code."""

    status = 500

    def __init__(self, message: str):
        self.message = message
        super().__init__(message)


class BadRequest(FrontDoorError):
    status = 400


class NotFound(FrontDoorError):
    status = 404


class QuotaExceeded(FrontDoorError):
    status = 429


class Unavailable(FrontDoorError):
    status = 503


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.
    Thread-safe; the clock is injected (G007) so quota tests replay on
    a virtual timeline."""

    def __init__(self, rate: float, burst: float, clock=time.time):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst,
                self._tokens + max(0.0, now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class FairAdmission:
    """Weighted deficit round-robin over per-tenant FIFOs. ``enqueue``
    appends to the tenant's queue; ``pop`` serves tenants in first-seen
    cycle order, up to ``weight`` items per tenant per round — an
    8-job burst from one tenant interleaves behind every other
    tenant's head-of-line job instead of monopolizing the spool. Not
    thread-safe on its own (the FrontDoor serializes access)."""

    def __init__(self, weights: Optional[dict] = None,
                 default_weight: int = 1):
        self._weights = dict(weights or {})
        self._default = int(default_weight)
        self._queues: dict = {}
        self._order: list = []
        self._credits: dict = {}
        self._cursor = 0

    def weight(self, tenant: str) -> int:
        return max(1, int(self._weights.get(tenant, self._default)))

    def enqueue(self, tenant: str, item) -> None:
        if tenant not in self._queues:
            self._queues[tenant] = collections.deque()
            self._order.append(tenant)
            self._credits[tenant] = self.weight(tenant)
        self._queues[tenant].append(item)

    def __len__(self) -> int:
        return sum(len(qd) for qd in self._queues.values())

    def pop(self):
        """``(tenant, item)`` in weighted-fair order, or None when
        every queue is empty."""
        if not len(self):
            return None
        n = len(self._order)
        scanned = 0
        while True:
            tenant = self._order[self._cursor % n]
            qd = self._queues[tenant]
            if qd and self._credits[tenant] > 0:
                self._credits[tenant] -= 1
                if self._credits[tenant] == 0:
                    self._cursor += 1
                return tenant, qd.popleft()
            self._cursor += 1
            scanned += 1
            if scanned >= n:
                for t in self._order:
                    self._credits[t] = self.weight(t)
                scanned = 0


class FrontDoor:
    """The server's state: journal (sole writer), quota buckets, the
    admission queue + pump thread, and read-only status snapshots over
    the shared fleet files. HTTP handlers call NOTHING else."""

    def __init__(self, root: str, recorder=None,
                 quota_rate: Optional[float] = None,
                 quota_burst: float = 10.0,
                 weights: Optional[dict] = None,
                 ttl_s: float = 15.0,
                 clock=time.time):
        self.root = root
        self.dirs = fleet_dirs(root)
        self._rec = obs.resolve_recorder(recorder)
        self._clock = clock
        self.quota_rate = quota_rate
        self.quota_burst = quota_burst
        # per-tenant buckets get-or-created on concurrent handler
        # threads (submit runs before _cond is taken), so the map has
        # its own lock; each TokenBucket then locks its own counters
        self._buckets: dict = {}
        self._buckets_lock = threading.Lock()
        self.journal = jnl.Journal(jnl.journal_path_for(root),
                                   clock=clock)
        self._leases = LeaseManager(root, "server", ttl_s=ttl_s,
                                    clock=clock, recorder=None)
        self._admission = FairAdmission(weights=weights)
        self._cond = threading.Condition()
        self._jobs: dict = {}       # job_id -> submission index entry
        self._admit_seq = 0
        self._stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        # live observability: one collector per server (the checkpoint
        # file has one writer), serialized behind its own lock because
        # /v1/metrics and /v1/fleet arrive on concurrent handler
        # threads. Host-side file tailing only — never device work.
        self._collector = FleetCollector(root, clock=clock)
        self._collector_lock = threading.Lock()
        self._recover()

    # -- restart recovery ---------------------------------------------

    def _recover(self) -> None:
        """Rebuild the submission index from the journal: admitted jobs
        are already spooled (workers own them from here); pending ones
        re-enter the admission queue. Lossless across server crashes —
        the WAL is written before any in-memory mutation."""
        admitted = set()
        for record in self.journal.recovered_records:
            kind = record.get("kind")
            if kind == "job_submitted":
                self._jobs[record["job_id"]] = {
                    "job_id": record["job_id"],
                    "tag": record.get("tag"),
                    "tenant": record.get("tenant", "default"),
                    "submitted_ts": record.get("ts"),
                    "config": record.get("config"),
                    "trace": record.get("trace"),
                }
            elif kind == "job_admitted":
                admitted.add(record["job_id"])
                self._admit_seq = max(self._admit_seq,
                                      record.get("admit_seq", 0) + 1)
        for job_id, info in self._jobs.items():
            if job_id not in admitted:
                self._admission.enqueue(info["tenant"], job_id)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._pump_thread is None:
            self._pump_thread = threading.Thread(
                target=self._pump, name="admission-pump", daemon=True)
            self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5.0)

    @property
    def draining(self) -> bool:
        return (lifecycle.drain_requested() is not None
                or lifecycle.drain_marked(self.root) is not None)

    def drain(self, reason: str) -> dict:
        """The /v1/drain action: journal first (write-ahead), then the
        in-process flag and the fleet-wide marker the workers poll."""
        self.journal.append("service_draining", reason=reason)
        self._rec.emit("service_draining", reason=reason)
        lifecycle.request_drain(reason)
        lifecycle.mark_drain(self.root, reason, clock=self._clock)
        return {"draining": reason}

    # -- submission ---------------------------------------------------

    def _resolve_config(self, body: dict):
        if "config" in body:
            try:
                return jnl.config_from_doc(dict(body["config"]))
            except (TypeError, ValueError) as e:
                raise BadRequest(f"bad config doc: {e}")
        if "workload" in body:
            name = body["workload"]
            try:
                spec = wreg.get(name)
            except KeyError:
                raise BadRequest(
                    f"unknown workload {name!r} "
                    f"(GET /v1/workloads lists the catalog)")
            overrides = body.get("overrides") or {}
            if not isinstance(overrides, dict):
                raise BadRequest("overrides must be an object")
            try:
                return spec.to_config(**overrides)
            except (TypeError, ValueError) as e:
                raise BadRequest(f"bad overrides: {e}")
        raise BadRequest("body needs 'workload' or 'config'")

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        if self.quota_rate is None:
            return None
        with self._buckets_lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(self.quota_rate, self.quota_burst,
                                     clock=self._clock)
                self._buckets[tenant] = bucket
            return bucket

    def submit(self, body: dict, tenant: str) -> dict:
        """Accept one submission: quota check, write-ahead journal,
        index, enqueue for the pump. Raises FrontDoorError refusals."""
        if self.draining:
            raise Unavailable("service is draining")
        config = self._resolve_config(body)
        bucket = self._bucket(tenant)
        if bucket is not None and not bucket.take():
            self._rec.emit("quota_rejected", tenant=tenant,
                           path="/v1/jobs", rate=self.quota_rate,
                           trace_id=None)
            raise QuotaExceeded(
                f"tenant {tenant!r} exceeded {self.quota_rate:g} "
                "submissions/s")
        with self._cond:
            job_id = f"j{len(self._jobs):04d}"
            doc = jnl.config_to_doc(config)
            # Mint the submission's trace identity: deterministic in
            # the job id (recovery re-mints the same trace), carried by
            # the WAL record, spool doc, and lease file; workers adopt
            # it (obs.adopt) so their run spans join THIS trace. The
            # submit span is the fleet-wide root every worker-side span
            # hangs under (via ctx_parent_id) in trace_export --fleet.
            trace = {"trace_id": f"job:{job_id}"}
            with obs.adopt(self._rec, trace):
                sp = obs.span(self._rec, "submit", job_id=job_id,
                              tenant=tenant, tag=config.tag).begin()
            if sp:
                trace["span_id"] = sp.span_id
            # WAL before any mutation the record describes
            self.journal.append("job_submitted", job_id=job_id,
                                tag=config.tag, tenant=tenant,
                                config=doc, trace=trace)
            self._jobs[job_id] = {
                "job_id": job_id, "tag": config.tag, "tenant": tenant,
                "submitted_ts": self._clock(), "config": doc,
                "trace": trace,
            }
            self._admission.enqueue(tenant, job_id)
            self._cond.notify()
        self._rec.emit("job_submitted", job_id=job_id, tag=config.tag,
                       tenant=tenant, fingerprint=config.fingerprint(),
                       trace_id=trace["trace_id"])
        sp.end()
        return {"job_id": job_id, "tag": config.tag,
                "tenant": tenant, "trace_id": trace["trace_id"],
                "fingerprint": config.fingerprint()}

    # -- the admission pump -------------------------------------------

    def _pump(self) -> None:
        """Drain the fair-admission queue into the spool: journal
        ``job_admitted`` (write-ahead), then write the job doc workers
        claim. Runs until stop(); keeps spooling while draining so
        accepted work is never stranded in memory."""
        while not self._stop.is_set():
            with self._cond:
                item = self._admission.pop()
                if item is None:
                    self._cond.wait(timeout=0.2)
                    continue
                tenant, job_id = item
                admit_seq = self._admit_seq
                self._admit_seq += 1
            info = self._jobs[job_id]
            self.journal.append("job_admitted", job_id=job_id,
                                tenant=tenant, admit_seq=admit_seq)
            _write_json_atomic(
                os.path.join(self.dirs[JOBS_DIR], f"{job_id}.json"),
                {"job_id": job_id, "tenant": tenant,
                 "tag": info["tag"], "admit_seq": admit_seq,
                 "submitted_ts": info["submitted_ts"],
                 "admitted_ts": self._clock(),
                 "trace": info.get("trace"),
                 "config": info["config"]})

    def pump_idle(self) -> bool:
        with self._cond:
            return len(self._admission) == 0

    # -- read-only views ----------------------------------------------

    def job_status(self, job_id: str) -> dict:
        info = self._jobs.get(job_id)
        if info is None:
            raise NotFound(f"unknown job {job_id!r}")
        out = {"job_id": job_id, "tag": info["tag"],
               "tenant": info["tenant"],
               "submitted_ts": info["submitted_ts"]}
        verdict = _read_json(os.path.join(self.dirs[STATUS_DIR],
                                          f"{job_id}.json"))
        started = _read_json(os.path.join(self.dirs[STARTED_DIR],
                                          f"{job_id}.json"))
        if started and started.get("started_ts") is not None \
                and info["submitted_ts"] is not None:
            out["started_ts"] = started["started_ts"]
            out["worker"] = started.get("worker")
            out["queue_to_start_s"] = round(
                started["started_ts"] - info["submitted_ts"], 6)
        if verdict is not None:
            out.update({k: verdict[k] for k in
                        ("status", "attempts", "error", "worker",
                         "finished_ts") if k in verdict})
        elif started is not None and self._leases.live(job_id):
            out["status"] = "running"
        elif os.path.exists(os.path.join(self.dirs[JOBS_DIR],
                                         f"{job_id}.json")):
            out["status"] = "queued"
        else:
            out["status"] = "pending"
        return out

    def jobs_status(self) -> dict:
        jobs = [self.job_status(job_id) for job_id in self._jobs]
        counts: dict = {}
        for j in jobs:
            counts[j["status"]] = counts.get(j["status"], 0) + 1
        return {"jobs": jobs, "counts": counts,
                "draining": self.draining}

    def artifact(self, job_id: str) -> dict:
        if job_id not in self._jobs:
            raise NotFound(f"unknown job {job_id!r}")
        doc = _read_json(os.path.join(self.dirs[ARTIFACTS_DIR],
                                      f"{job_id}.json"))
        if doc is None:
            status = self.job_status(job_id).get("status")
            raise NotFound(f"no artifact for {job_id} yet "
                           f"(status: {status})")
        return doc

    def workloads(self) -> dict:
        return {"workloads": wreg.names()}

    def healthz(self) -> dict:
        return {"ok": True, "draining": self.draining,
                "n_jobs": len(self._jobs)}

    def metrics_text(self) -> str:
        """The /v1/metrics body: poll the collector (host-side file
        tailing only), render Prometheus text exposition."""
        with self._collector_lock:
            self._collector.poll()
            return self._collector.prometheus_text()

    def fleet_status(self) -> dict:
        """The /v1/fleet body: the collector's stream-derived topology
        merged with what only the server knows — authoritative per-job
        stage (status files beat stream inference) and the live
        admission-queue depth (which never transits a stream)."""
        with self._collector_lock:
            self._collector.poll()
            doc = self._collector.fleet_doc()
        status = self.jobs_status()
        for j in status["jobs"]:
            entry = doc["jobs"].setdefault(j["job_id"], {})
            entry["stage"] = j["status"]
            if j.get("worker") is not None:
                entry["worker"] = j["worker"]
        doc["stages"] = status["counts"]
        with self._cond:
            doc["queue_depth"] = len(self._admission)
        doc["draining"] = self.draining
        return doc

    def profile_request(self, job_id: str, body: dict) -> dict:
        """POST /v1/profile/<job>: journal the request (write-ahead,
        like every other accepted mutation), then drop the atomic
        marker the owning worker honors at its next segment boundary.
        The handler thread touches files only — capture itself happens
        in the worker process (G009: no device work here)."""
        if job_id not in self._jobs:
            raise NotFound(f"unknown job {job_id!r}")
        segments = body.get("segments", 3)
        if not isinstance(segments, int) or not 1 <= segments <= 1000:
            raise BadRequest("segments must be an int in [1, 1000]")
        self.journal.append("profile_requested", job_id=job_id,
                            segments=segments)
        _write_json_atomic(
            os.path.join(self.dirs[PROFILE_DIR], f"{job_id}.json"),
            {"job_id": job_id, "segments": segments,
             "requested_ts": self._clock()})
        return {"job_id": job_id, "segments": segments,
                "profiling": "requested"}

    def profile_status(self, job_id: str) -> dict:
        """GET /v1/profile/<job>: pending marker + published capture
        (the worker's ``<job>.profile.json`` artifact), read-only."""
        if job_id not in self._jobs:
            raise NotFound(f"unknown job {job_id!r}")
        marker = _read_json(os.path.join(self.dirs[PROFILE_DIR],
                                         f"{job_id}.json"))
        capture = _read_json(os.path.join(self.dirs[ARTIFACTS_DIR],
                                          f"{job_id}.profile.json"))
        return {"job_id": job_id, "requested": marker,
                "captured": capture}

    def observe_request(self, method: str, path: str, status: int,
                        tenant: Optional[str], dur_s: float,
                        job_id: Optional[str] = None) -> None:
        info = self._jobs.get(job_id) if job_id else None
        trace = (info or {}).get("trace") or {}
        self._rec.emit("http_request", method=method, path=path,
                       status=status, tenant=tenant,
                       dur_s=round(dur_s, 6), job_id=job_id,
                       trace_id=trace.get("trace_id"))


class FleetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    front: FrontDoor


class FrontDoorHandler(BaseHTTPRequestHandler):
    """Thin routing layer: parse, delegate to the FrontDoor, serialize.
    Holds NO state and mutates none — see the module docstring for the
    G009 hygiene contract this class is linted against."""

    server_version = "graft-fleet/1"
    protocol_version = "HTTP/1.1"

    # -- plumbing -----------------------------------------------------

    def log_message(self, fmt, *args):   # stdlib default spams stderr
        pass

    def _tenant(self, body: Optional[dict] = None) -> str:
        if body and isinstance(body.get("tenant"), str):
            return body["tenant"]
        return self.headers.get("X-Tenant", "default")

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        raw = self.rfile.read(length)
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            raise BadRequest("body is not JSON")
        if not isinstance(doc, dict):
            raise BadRequest("body must be a JSON object")
        return doc

    def _reply(self, status: int, doc: dict) -> None:
        payload = json.dumps(doc).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _reply_text(self, status: int, text: str,
                    content_type: str = "text/plain; version=0.0.4; "
                                        "charset=utf-8") -> None:
        # Prometheus scrapers want the text exposition content type,
        # not JSON — everything else about the reply is the same
        payload = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    # -- routes -------------------------------------------------------

    def _route(self, method: str) -> None:
        t0 = time.monotonic()
        tenant = None
        job_id = None
        raw_text = False
        try:
            rfaults.fault_point("http.accept", path=self.path)
            front = self.server.front
            parts = [p for p in self.path.split("?")[0].split("/")
                     if p]
            if method == "POST" and parts == ["v1", "jobs"]:
                body = self._body()
                tenant = self._tenant(body)
                out = front.submit(body, tenant)
                job_id = out["job_id"]
                status = 200
            elif method == "POST" and parts == ["v1", "drain"]:
                out = front.drain("http")
                status = 200
            elif method == "GET" and parts == ["v1", "jobs"]:
                out = front.jobs_status()
                status = 200
            elif (method == "GET" and len(parts) == 3
                  and parts[:2] == ["v1", "jobs"]):
                job_id = parts[2]
                out = front.job_status(job_id)
                status = 200
            elif (method == "GET" and len(parts) == 4
                  and parts[:2] == ["v1", "jobs"]
                  and parts[3] == "artifact"):
                job_id = parts[2]
                out = front.artifact(job_id)
                status = 200
            elif method == "GET" and parts == ["v1", "workloads"]:
                out = front.workloads()
                status = 200
            elif method == "GET" and parts == ["v1", "healthz"]:
                out = front.healthz()
                status = 200
            elif method == "GET" and parts == ["v1", "metrics"]:
                out = front.metrics_text()
                raw_text = True
                status = 200
            elif method == "GET" and parts == ["v1", "fleet"]:
                out = front.fleet_status()
                status = 200
            elif (method == "POST" and len(parts) == 3
                  and parts[:2] == ["v1", "profile"]):
                job_id = parts[2]
                out = front.profile_request(job_id, self._body())
                status = 200
            elif (method == "GET" and len(parts) == 3
                  and parts[:2] == ["v1", "profile"]):
                job_id = parts[2]
                out = front.profile_status(job_id)
                status = 200
            else:
                raise NotFound(f"no route {method} {self.path}")
        except FrontDoorError as e:
            status, out, raw_text = e.status, {"error": e.message}, False
        except rfaults.InjectedFault as e:
            status, out, raw_text = 503, {"error": str(e)}, False
        try:
            if raw_text:
                self._reply_text(status, out)
            else:
                self._reply(status, out)
        finally:
            self.server.front.observe_request(
                method, self.path, status, tenant,
                time.monotonic() - t0, job_id=job_id)

    def do_GET(self) -> None:
        self._route("GET")

    def do_POST(self) -> None:
        self._route("POST")


class FleetServer:
    """The served front door: FrontDoor + ThreadingHTTPServer on a
    background thread. ``with FleetServer(root) as srv:`` yields a
    bound server; ``srv.port`` is the OS-assigned port when 0 was
    requested (tests and the gate script read it from the ready
    file)."""

    def __init__(self, root: str, host: str = "127.0.0.1",
                 port: int = 0, ready_file: Optional[str] = None,
                 **front_kwargs):
        self.root = root
        self.host = host
        self._port = port
        self.ready_file = ready_file
        self.front = FrontDoor(root, **front_kwargs)
        self._httpd: Optional[FleetHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetServer":
        self._httpd = FleetHTTPServer((self.host, self._port),
                                      FrontDoorHandler)
        self._httpd.front = self.front
        self.front.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="front-door", daemon=True)
        self._thread.start()
        if self.ready_file:
            _write_json_atomic(self.ready_file,
                               {"host": self.host, "port": self.port,
                                "url": self.url, "pid": os.getpid()})
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.front.stop()

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def serve(root: str, host: str = "127.0.0.1", port: int = 0,
          recorder=None, ready_file: Optional[str] = None,
          poll_s: float = 0.2, **front_kwargs) -> int:
    """Blocking CLI entry: serve until a drain arrives (HTTP endpoint,
    SIGTERM/SIGINT, or a pre-existing marker), keep serving status
    reads until the admission queue is spooled, then stop. Returns the
    process exit code (EXIT_DRAINED — serving only ends by drain)."""
    with lifecycle.DrainController():
        with FleetServer(root, host=host, port=port,
                         ready_file=ready_file,
                         recorder=recorder, **front_kwargs) as srv:
            while not srv.front.draining:
                time.sleep(poll_s)
            reason = (lifecycle.drain_requested()
                      or lifecycle.drain_marked(root) or "drain")
            # a signal-delivered drain never hit the endpoint: journal
            # + marker it so workers drain too
            if lifecycle.drain_marked(root) is None:
                srv.front.drain(str(reason))
            while not srv.front.pump_idle():
                time.sleep(poll_s)
    return lifecycle.EXIT_DRAINED
