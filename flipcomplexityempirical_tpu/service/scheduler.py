"""Coalescing scheduler: compatible jobs share one device batch.

The batching contract rests on two repo invariants:

1. Chains are independent: every per-chain PRNG key lives in the chain
   state (``runner.init_batch`` vmaps ``init_state`` over split keys),
   every kernel body is vmapped over the leading chain axis, and
   per-chain StepParams leaves (``log_base``/``beta``/``pop_lo``/
   ``pop_hi``) are ``(C,)`` arrays. Concatenating two tenants' states
   and params along axis 0, running one batched segment, and slicing
   the rows back out is therefore BIT-identical to running each tenant
   alone (tests/test_service.py proves it on both the lowered-bits and
   general paths).
2. Compile keys are shapes + statics: jobs with equal
   ``ExperimentConfig.fingerprint()`` build the same graph and Spec, so
   one coalesced dispatch compiles ONE kernel where N solo runs would
   compile N (and a later tenant with the same signature and batch
   shape compiles zero — ``service.cache``).

Failure handling reuses the PR 7 supervisor taxonomy per job
(``classify_error`` + ``RetryPolicy`` backoff + quarantine); a job that
fails inside a batch is retried SOLO (isolation first, so a poison
tenant cannot re-poison its neighbors), and jobs with an existing
checkpoint run solo from their resume point (coalescing assumes a
common step 0). Everything here is host-side between segments — no
added device syncs (PROFILE.md guard-rail).

Preemption-proofing (PR 11) rides the same segment boundaries:

- every job/batch transition is journaled write-ahead
  (``service.journal``) so ``SweepService.recover(outdir)`` rebuilds
  the queue after a crash — DONE stays done, RUNNING requeues from its
  last checkpoint (``_solo_only`` already forces checkpointed jobs
  solo), poison-suspect batches requeue SOLO;
- ``lifecycle.check_drain`` runs next to ``check_deadline`` in the
  batch segment loop: a SIGTERM stops the service at the next boundary
  with every tenant checkpointed, requeues the in-flight jobs without
  burning a retry, and exits with the drain code;
- each device dispatch runs under the ``lifecycle.DispatchWatchdog``
  window (timeout: ``dispatch_timeout`` or scaled from the p95
  ``segment_wall_s`` in the service's metrics registry), so a wedged
  device call is journaled poison-suspect for the restart to isolate.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from types import SimpleNamespace
from typing import Optional

import numpy as np

from .. import obs
from ..experiments import driver as drv
from ..experiments.config import ExperimentConfig
from ..kernel import board as kboard
from ..lower.dispatch import kernel_path_for, lowering_signature
from ..obs.metrics import MetricsRegistry
from ..resilience import faults as rfaults
from ..resilience.supervisor import (DETERMINISTIC, DeadlineScope,
                                     RetryPolicy, check_deadline,
                                     classify_error)
from ..sampling import init_batch, init_board, run_chains
from ..sampling.board_runner import finalize_board_run, run_board_segment
from .cache import CompileCache
from . import journal as jnl
from . import lifecycle
from . import profiling
from . import queue as q


def concat_states(states_list):
    """Stack tenant chain states along the chain axis. Every non-None
    leaf of ChainState/BoardState is per-chain (leading axis C) by
    construction — see state/chain_state.py — so a plain tree-concat is
    exact."""
    import jax
    import jax.numpy as jnp

    if len(states_list) == 1:
        return states_list[0]
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0),
                        *states_list)


def concat_params(params_list):
    """Stack StepParams along the chain axis: leaves ``vmap_axes``
    marks with axis 0 (log_base/beta/pop_lo/pop_hi — so coalesced
    tenants may differ in base/pop_tol) are concatenated, shared leaves
    (label_values, anneal schedule) are taken from the first tenant
    (equal within a fingerprint group by construction)."""
    import jax.numpy as jnp

    if len(params_list) == 1:
        return params_list[0]
    p0 = params_list[0]
    axes = type(p0).vmap_axes()
    fields = {}
    for f in p0.__dataclass_fields__:
        vals = [getattr(p, f) for p in params_list]
        if getattr(axes, f, None) == 0:
            fields[f] = jnp.concatenate(vals, axis=0)
        else:
            fields[f] = vals[0]
    return type(p0)(**fields)


def _slice_chains(tree, lo: int, hi: int):
    import jax

    return jax.tree.map(lambda x: x[lo:hi], tree)


@dataclasses.dataclass
class _Prepared:
    """One tenant initialized and ready to join a batch."""

    job: q.Job
    g: object
    plan: object
    spec: object
    use_board: bool
    handle: object
    states: object
    params: object
    n_parts: int = 0


@dataclasses.dataclass
class BatchStats:
    """Host-side record of one executed batch (bench.py --service and
    the simulation mode read these for tenant-efficiency math)."""

    batch_id: str
    jobs: list
    chains: int
    steps: int
    wall_s: float
    kernel_path: str
    cache_hit: bool


class SweepService:
    """The sweep-as-a-service loop: submit ExperimentConfigs, then
    ``run_until_idle`` drains the queue — coalescing fingerprint-equal
    fresh jobs into shared device batches, running checkpointed /
    solo-flagged / temper jobs through the one-shot driver paths, and
    retrying/quarantining failures per the supervisor taxonomy."""

    def __init__(self, outdir: str,
                 checkpoint_dir: Optional[str] = None,
                 recorder=None,
                 heartbeat: Optional[str] = None,
                 compile_cache: Optional[CompileCache] = None,
                 policy: Optional[RetryPolicy] = None,
                 max_batch_chains: Optional[int] = None,
                 verbose: bool = False,
                 journal=None,
                 dispatch_timeout: Optional[float] = None,
                 clock=time.time,
                 control=None):
        self.outdir = outdir
        self.checkpoint_dir = checkpoint_dir
        self._rec = obs.resolve_recorder(recorder)
        self.heartbeat = heartbeat
        self.policy = policy or RetryPolicy()
        self._rng = random.Random(self.policy.seed)
        self.cache = compile_cache or CompileCache(recorder=self._rec)
        self.max_batch_chains = max_batch_chains
        self.verbose = verbose
        self.clock = clock
        self.queue = q.JobQueue(recorder=self._rec, clock=clock)
        self.batch_stats: list[BatchStats] = []
        self._batch_seq = 0
        os.makedirs(outdir, exist_ok=True)
        # Journal on by default (outdir/journal.jsonl): crash
        # consistency is not opt-in. ``journal=False`` disables (pure
        # in-memory simulation runs); a path or Journal overrides.
        if journal is False:
            self.journal = None
        elif journal is None:
            self.journal = jnl.Journal(jnl.journal_path_for(outdir),
                                       clock=clock)
        elif isinstance(journal, jnl.Journal):
            self.journal = journal
        else:
            self.journal = jnl.Journal(str(journal), clock=clock)
        if self.journal is not None and self.journal.dropped:
            self._rec.emit("journal_truncated", path=self.journal.path,
                           dropped=self.journal.dropped)
        self.metrics = MetricsRegistry()
        self.watchdog = lifecycle.DispatchWatchdog(
            recorder=self._rec, journal=self.journal,
            timeout_s=dispatch_timeout, metrics=self.metrics)
        self.drained = False
        self.drain_reason: Optional[str] = None
        # Adaptive control (control.ControlLoop): consulted by the
        # drivers at segment boundaries; its actions ride THIS journal,
        # so recover() replays them instead of re-deriving.
        self.control = control
        if self.control is not None:
            self.control.attach(recorder=self._rec,
                                journal=self.journal,
                                metrics=self.metrics)

    # -- submission --------------------------------------------------

    def submit(self, config: ExperimentConfig) -> q.Job:
        job = self.queue.submit(config)
        self._journal("job_submitted", job_id=job.job_id, tag=job.tag,
                      config=jnl.config_to_doc(config))
        self._write_summary()
        return job

    def _journal(self, kind: str, **fields):
        """Append one transition record when journaling is on. Append
        failures propagate: a WAL that cannot write must not let the
        transition proceed silently."""
        if self.journal is not None:
            self.journal.append(kind, **fields)

    # -- recovery ----------------------------------------------------

    @classmethod
    def recover(cls, outdir: str, **kwargs) -> "SweepService":
        """Rebuild a service from ``outdir``'s journal after a crash or
        drain. DONE/FAILED/QUARANTINED jobs keep their verdicts (DONE
        results are not re-materialized — the journal records state,
        not data); jobs that were RUNNING at the crash are requeued —
        ``_solo_only`` routes them through their last checkpoint — and
        members of a poison-suspect batch are forced SOLO. Opening the
        journal repairs a torn tail (``journal_truncated`` is emitted);
        the rebuilt service appends to the same journal, so one file
        narrates the job history across every restart."""
        svc = cls(outdir, **kwargs)
        if svc.journal is None:
            raise ValueError("recover() needs a journal "
                             "(journal=False was passed)")
        if svc.control is not None:
            # adopt journaled control decisions: a recovered run honors
            # prior stops/reshapes at their original boundaries instead
            # of re-deriving (and re-journaling) them
            svc.control.adopt(svc.journal.recovered_records)
        state = jnl.replay(svc.journal.recovered_records)
        n_requeued = 0
        for jid, st in state.items():
            job = svc.queue.submit(jnl.config_from_doc(st["config"]))
            job.attempts = st["attempts"]
            job.det_failures = st["det_failures"]
            job.solo = st["solo"]
            job.error = st["error"]
            if st["status"] == q.DONE:
                job.status = q.DONE
            elif st["status"] == q.FAILED:
                job.status = q.FAILED
            elif st["status"] == q.QUARANTINED:
                job.status = q.QUARANTINED
            else:
                # queued at crash, or running (requeue: the resume
                # point is the job's last checkpoint).
                if st["status"] == q.RUNNING:
                    svc._journal("job_requeued", job_id=job.job_id,
                                 solo=job.solo,
                                 det_failures=job.det_failures,
                                 reason="recovery")
                job.status = q.QUEUED
                n_requeued += 1
        svc._rec.emit("service_recovered", path=svc.journal.path,
                      n_jobs=len(state), n_requeued=n_requeued)
        svc._write_summary()
        return svc

    # -- grouping ----------------------------------------------------

    def _has_checkpoint(self, cfg: ExperimentConfig) -> bool:
        if not self.checkpoint_dir:
            return False
        return any(os.path.exists(os.path.join(self.checkpoint_dir, f))
                   for f in (cfg.tag + ".npz",
                             cfg.tag + ".manifest.json"))

    def _solo_only(self, job: q.Job) -> bool:
        """Jobs the coalescer must not touch: isolation retries, the
        temper family (run-global ladder swap state), non-flip chain
        families (the coalesced executor drives run_chains directly —
        recom jobs run solo through the driver, which routes them to
        run_recom; their fingerprints differ from any flip config so
        they could never share a batch anyway), and anything with an
        existing checkpoint (resume points differ, coalescing assumes
        a common step 0)."""
        return (job.solo or job.config.family == "temper"
                or job.config.chain != "flip"
                or self._has_checkpoint(job.config))

    def _form_groups(self, jobs: list) -> list:
        """Submission-ordered greedy grouping: fingerprint-equal
        batchable jobs share a group (capped at ``max_batch_chains``
        total chains), everything else is a singleton."""
        groups: list[list] = []
        by_key: dict = {}
        for job in jobs:
            if self._solo_only(job):
                groups.append([job])
                continue
            key = job.fingerprint
            grp = by_key.get(key)
            if grp is not None and (
                    self.max_batch_chains is None
                    or sum(j.config.n_chains for j in grp)
                    + job.config.n_chains <= self.max_batch_chains):
                grp.append(job)
            else:
                grp = [job]
                groups.append(grp)
                by_key[key] = grp
        return groups

    # -- heartbeats --------------------------------------------------

    def _job_counts(self) -> dict:
        jobs = self.queue.jobs()
        return {
            "n_jobs": len(jobs),
            "n_done": sum(j.status == q.DONE for j in jobs),
            "n_failed": sum(j.status == q.FAILED for j in jobs),
            "n_quarantined": sum(j.status == q.QUARANTINED
                                 for j in jobs),
            "n_queued": sum(j.status == q.QUEUED for j in jobs),
        }

    def _write_summary(self):
        """The merged service-level heartbeat: one ``jobs`` map over
        every submission (per-job liveness lives in the namespaced
        ``heartbeat.<tag>.json`` / ``heartbeat.<batch>.json`` files —
        obs_report --heartbeat probes both shapes)."""
        if not self.heartbeat:
            return
        jobs = self.queue.jobs()
        status = ("running" if any(j.status not in q.TERMINAL
                                   for j in jobs)
                  else "complete" if not any(
                      j.status in (q.FAILED, q.QUARANTINED)
                      for j in jobs)
                  else "complete_with_failures")
        drv.write_heartbeat(
            self.heartbeat, recorder=self._rec, status=status,
            service=True,
            jobs={j.tag: {"job_id": j.job_id, "status": j.status,
                          "attempts": j.attempts,
                          **({"batch": j.batch} if j.batch else {})}
                  for j in jobs},
            **self._job_counts())

    def _write_job_heartbeat(self, job: q.Job, status: str, **extra):
        drv.write_heartbeat(
            drv.heartbeat_path_for(self.heartbeat, job.tag),
            recorder=self._rec, status=status, job_id=job.job_id,
            tag=job.tag, attempts=job.attempts, **extra)

    # -- the drain loop ----------------------------------------------

    def run_until_idle(self) -> list:
        """Process the queue to quiescence; returns all jobs (terminal
        states set, ``result`` populated on DONE). Emits one
        ``sweep_summary`` per drain so obs_report folds a service
        stream like a supervised sweep."""
        rec = self._rec
        retried = 0
        svc_span = obs.span(rec, "service",
                            n_jobs=len(self.queue.runnable())).begin()
        try:
            while lifecycle.drain_requested() is None:
                runnable = self.queue.runnable()
                if not runnable:
                    break
                for jobs in self._form_groups(runnable):
                    if lifecycle.drain_requested() is not None:
                        break   # no new dispatches once draining
                    retried += self._execute(jobs)
        finally:
            reason = lifecycle.drain_requested()
            if reason is not None and not self.drained:
                self.drained = True
                self.drain_reason = reason
                self._journal("service_draining", reason=reason)
                rec.emit("service_draining", reason=reason)
                if self.verbose:
                    print(f"[drain] stopping at segment boundary "
                          f"({reason}); restart with recover()")
            self.watchdog.stop()
            counts = self._job_counts()
            svc_span.end(drained=self.drained, **counts)
        jobs = self.queue.jobs()
        quarantined = [j.tag for j in jobs
                       if j.status == q.QUARANTINED]
        failed = [j.tag for j in jobs if j.status == q.FAILED]
        rec.emit("sweep_summary",
                 completed=counts["n_done"], retried=retried,
                 quarantined=len(quarantined), failed=len(failed),
                 quarantined_tags=quarantined, failed_tags=failed,
                 service=True, drained=self.drained)
        self._write_summary()
        return jobs

    @property
    def exit_code(self) -> int:
        """0 done; 2 failures/quarantines; 3 drained (EXIT_DRAINED) —
        the orchestrator contract: 3 means restart with recover()."""
        if any(j.status in (q.FAILED, q.QUARANTINED)
               for j in self.queue.jobs()):
            return 2
        if self.drained:
            return lifecycle.EXIT_DRAINED
        return 0

    # -- execution ---------------------------------------------------

    def _execute(self, jobs: list) -> int:
        """Run one group (1..N jobs) as a single attempt per member.
        Returns the number of jobs sent back for retry."""
        rec = self._rec
        batch_id = f"b{self._batch_seq:04d}"
        self._batch_seq += 1
        self._journal("batch_started", batch_id=batch_id,
                      jobs=[j.job_id for j in jobs])
        for job in jobs:
            job.attempts += 1
            job.status = q.RUNNING
            job.batch = batch_id
            self._write_job_heartbeat(job, "running", batch=batch_id)
        self._write_summary()
        span = obs.span(rec, "batch", batch_id=batch_id,
                        n_jobs=len(jobs),
                        tags=[j.tag for j in jobs]).begin()
        hb_state, uninstall = drv.install_live_hooks(
            rec, self.heartbeat, SimpleNamespace(tag=batch_id),
            self._job_counts(), namespace=True)
        deadline = DeadlineScope(self.policy.deadline_s,
                                 batch_id).begin()
        retried = 0
        t0 = time.perf_counter()
        try:
            if len(jobs) == 1 and self._solo_only(jobs[0]):
                results = [(jobs[0],
                            self._run_solo(jobs[0], batch_id))]
            else:
                prepared = []
                for job in jobs:
                    try:
                        prepared.append(self._prepare(job))
                    except Exception as e:
                        retried += self._fail(job, e, hb_state)
                results = (self._run_batch(prepared, batch_id)
                           if prepared else [])
        except lifecycle.DrainRequested:
            # Not a failure: the in-flight tenants are checkpointed at
            # the boundary that observed the drain. Requeue without
            # burning a retry; run_until_idle stops dispatching.
            for job in jobs:
                if job.status == q.RUNNING:
                    job.attempts -= 1
                    self._journal("job_requeued", job_id=job.job_id,
                                  solo=job.solo,
                                  det_failures=job.det_failures,
                                  reason="drain")
                    job.status = q.QUEUED
                    self._write_job_heartbeat(job, "draining",
                                              batch=batch_id)
            self._write_summary()
            span.end(drained=True)
            return retried
        except Exception as e:
            for job in jobs:
                if job.status == q.RUNNING:
                    retried += self._fail(job, e, hb_state)
            span.end(error=type(e).__name__)
            return retried
        finally:
            deadline.end()
            uninstall()
        wall = time.perf_counter() - t0
        for job, data in results:
            self._complete(job, data, batch_id, wall)
        span.end(seconds=wall, n_done=len(results))
        return retried

    def _prepare(self, job: q.Job) -> _Prepared:
        """Build graph/plan/spec and initialize this tenant's own
        (states, params) — each tenant keeps its own seed-derived
        per-chain PRNG keys, so coalescing changes nothing about any
        chain's trajectory."""
        cfg = job.config
        if cfg.backend != "jax":
            raise ValueError(
                f"service batches run backend='jax' only, got "
                f"{cfg.backend!r} ({job.tag})")
        if (cfg.checkpoint_every and cfg.record_every > 1
                and cfg.checkpoint_every % cfg.record_every):
            raise ValueError(
                f"checkpoint_every ({cfg.checkpoint_every}) must be a "
                f"multiple of record_every ({cfg.record_every})")
        with obs.span(self._rec, "build_graph", tag=cfg.tag,
                      family=cfg.family):
            g, plan, _geo = drv.build_graph_and_plan(cfg)
        spec = drv.spec_for(cfg)
        use_board = kboard.supports(g, spec)
        if use_board:
            handle, states, params = init_board(
                g, plan, n_chains=cfg.n_chains, seed=cfg.seed,
                spec=spec, base=cfg.base, pop_tol=cfg.pop_tol)
        else:
            handle, states, params = init_batch(
                g, plan, n_chains=cfg.n_chains, seed=cfg.seed,
                spec=spec, base=cfg.base, pop_tol=cfg.pop_tol)
        return _Prepared(job=job, g=g, plan=plan, spec=spec,
                         use_board=use_board, handle=handle,
                         states=states, params=params)

    def _probe_cache(self, g, spec, n_chains: int, total_steps: int,
                     segment: int, batch_id: str) -> tuple:
        path = kernel_path_for(g, spec)
        key = CompileCache.key(lowering_signature(g, spec), n_chains,
                               total_steps, segment)
        hit = self.cache.check(key, kernel_path=path, batch=batch_id)
        return path, hit

    def _run_solo(self, job: q.Job, batch_id: str) -> dict:
        """Singleton execution through the one-shot driver runners —
        exactly the resume/degradation semantics of a supervised sweep
        config, minus artifact rendering."""
        cfg = job.config
        if cfg.backend != "jax":
            raise ValueError(
                f"service runs backend='jax' configs only, got "
                f"{cfg.backend!r} ({job.tag})")
        g, plan, _geo = drv.build_graph_and_plan(cfg)
        spec = drv.spec_for(cfg)
        chains = cfg.n_chains * (len(cfg.betas)
                                 if cfg.family == "temper" else 1)
        path, hit = self._probe_cache(
            g, spec, chains, cfg.total_steps,
            cfg.checkpoint_every or cfg.total_steps, batch_id)
        self._rec.emit("job_batched", batch_id=batch_id,
                       jobs=[job.job_id], chains=chains,
                       fingerprint=job.fingerprint, kernel_path=path)
        t0 = time.perf_counter()
        # A solo run is one opaque dispatch: bracket it with the two
        # profiling boundaries it has (start + end), so an on-demand
        # capture still covers the whole dispatch.
        profiling.segment_boundary(batch_id)
        # One watchdog window for the whole solo run (the driver owns
        # the segment loop; a solo run is one opaque dispatch span from
        # the service's point of view).
        with self.watchdog.watch(batch_id, [job.job_id]):
            self.watchdog.stall_point(batch_id)
            if cfg.family == "temper":
                data = drv._run_temper(cfg, g, plan,
                                       self.checkpoint_dir,
                                       recorder=self._rec,
                                       control=self.control)
            else:
                data = drv._run_jax(cfg, g, plan, self.checkpoint_dir,
                                    recorder=self._rec,
                                    control=self.control)
        wall = time.perf_counter() - t0
        profiling.segment_boundary(batch_id)
        data["seconds"] = wall
        self.batch_stats.append(BatchStats(
            batch_id=batch_id, jobs=[job.job_id], chains=chains,
            steps=cfg.total_steps, wall_s=wall, kernel_path=path,
            cache_hit=hit))
        return data

    def _run_batch(self, prepared: list, batch_id: str) -> list:
        """The coalesced executor: mirror of driver._run_jax's segment
        loop over the concatenated batch, with per-tenant checkpoints
        (sliced host state per segment) and per-tenant result slicing
        at the end. All members are fresh (step 0) with equal
        fingerprints, so spec/graph/run-shape agree by construction."""
        rec = self._rec
        lead = prepared[0]
        cfg0 = lead.job.config
        spec, use_board, handle = lead.spec, lead.use_board, lead.handle
        counts = [p.job.config.n_chains for p in prepared]
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
        c_total = int(offsets[-1])
        states = concat_states([p.states for p in prepared])
        params = concat_params([p.params for p in prepared])
        every = min(c.checkpoint_every or c.total_steps
                    for c in (p.job.config for p in prepared))
        total = cfg0.total_steps - (1 if use_board else 0)
        path, hit = self._probe_cache(lead.g, spec, c_total,
                                      cfg0.total_steps, every, batch_id)
        rec.emit("job_batched", batch_id=batch_id,
                 jobs=[p.job.job_id for p in prepared], chains=c_total,
                 fingerprint=lead.job.fingerprint, kernel_path=path)

        t0 = time.perf_counter()
        done = 0
        hist_parts: dict = {}
        waits_total = np.zeros(c_total, np.float64)
        job_ids = [p.job.job_id for p in prepared]
        ctl = self.control
        # Active tenants by index into `prepared`. With control on, the
        # loop may retire a tenant early (control stop) and re-pack the
        # survivors so their chains keep the whole device; with control
        # off, `active` never changes and the loop below is the original
        # whole-batch path verbatim.
        active = list(range(len(prepared)))
        per_hist: list = [dict() for _ in prepared]  # control only
        results = []

        def _active_offsets():
            cs = [prepared[i].job.config.n_chains for i in active]
            return np.concatenate([[0], np.cumsum(cs)]).astype(int)

        def _tenant_data(i, states_i, per_parts, waits_i, stop_at=None):
            """Finalize one tenant's run from its sliced state/history
            parts; `stop_at` is the early-stop boundary (None = ran the
            full schedule)."""
            p = prepared[i]
            cfg = p.job.config
            if use_board:
                t_close = (cfg.total_steps if stop_at is None
                           else stop_at + 1)
                res_i = finalize_board_run(
                    handle, spec, p.params, states_i, per_parts,
                    waits_i, [], True, t_close, cfg.record_every,
                    recorder=rec)
                data = drv.assemble_run_data(
                    cfg, p.g, handle, use_board, res_i.state,
                    res_i.history, res_i.waits_total,
                    t_final=(None if stop_at is None else stop_at + 1))
            else:
                history_i = {k: np.concatenate(v, axis=1)
                             for k, v in per_parts.items()}
                data = drv.assemble_run_data(
                    cfg, p.g, handle, use_board, states_i, history_i,
                    waits_i, t_final=stop_at)
            if stop_at is not None:
                data["early_stopped"] = stop_at
            data["batch"] = batch_id
            data["batch_chains"] = c_total
            return data

        while done < total and active:
            check_deadline()
            lifecycle.check_drain(batch_id)
            # on-demand profiling hook: same cadence as the drain
            # check — segment edges are the only host-side points
            profiling.segment_boundary(batch_id)
            rfaults.fault_point("segment.step", tag=batch_id, done=done)
            n = min(every, total - done)
            seg_t0 = time.perf_counter()
            with self.watchdog.watch(batch_id, job_ids):
                self.watchdog.stall_point(batch_id)
                if use_board:
                    res = run_board_segment(
                        handle, spec, params, states, n,
                        record_every=cfg0.record_every, recorder=rec)
                else:
                    res = run_chains(handle, spec, params, states,
                                     n_steps=n,
                                     record_initial=(done == 0),
                                     record_every=cfg0.record_every,
                                     recorder=rec)
            self.metrics.observe("segment_wall_s",
                                 time.perf_counter() - seg_t0)
            states = res.state
            for k, v in res.history.items():
                hist_parts.setdefault(k, []).append(v)
            waits_total += res.waits_total
            done += n
            if ctl is not None:
                for pos, i in enumerate(active):
                    lo, hi = int(offsets[pos]), int(offsets[pos + 1])
                    for k, v in res.history.items():
                        per_hist[i].setdefault(k, []).append(
                            np.asarray(v)[lo:hi])
            if self.checkpoint_dir:
                host = res.host_state()
                for pos, i in enumerate(active):
                    p = prepared[i]
                    lo, hi = int(offsets[pos]), int(offsets[pos + 1])
                    cfg = p.job.config
                    with obs.span(rec, "checkpoint", tag=cfg.tag,
                                  done=done):
                        p.n_parts = drv.save_checkpoint(
                            self.checkpoint_dir, cfg,
                            _slice_chains(host, lo, hi), done=done,
                            waits_total=waits_total[lo:hi],
                            new_hist={k: np.asarray(v)[lo:hi]
                                      for k, v in res.history.items()},
                            part_idx=p.n_parts)
            if ctl is not None and done < total:
                stopped_now = []
                for pos, i in enumerate(active):
                    cfg = prepared[i].job.config
                    if ctl.consult_stop(
                            cfg.tag, family=cfg.family, done=done,
                            total=total, every=every,
                            history=drv._control_history(per_hist[i])):
                        stopped_now.append((pos, i))
                if stopped_now:
                    stop_set = {i for _, i in stopped_now}
                    for pos, i in stopped_now:
                        lo, hi = int(offsets[pos]), int(offsets[pos + 1])
                        results.append((prepared[i].job, _tenant_data(
                            i, _slice_chains(states, lo, hi),
                            per_hist[i], waits_total[lo:hi].copy(),
                            stop_at=done)))
                    remaining = [i for i in active if i not in stop_set]
                    if remaining:
                        keep = [(int(offsets[pos]), int(offsets[pos + 1]))
                                for pos, i in enumerate(active)
                                if i in remaining]
                        states = concat_states(
                            [_slice_chains(states, lo, hi)
                             for lo, hi in keep])
                        params = concat_params(
                            [prepared[i].params for i in remaining])
                        waits_total = np.concatenate(
                            [waits_total[lo:hi] for lo, hi in keep])
                        to_tags = [prepared[i].job.tag
                                   for i in remaining]
                        for _, i in stopped_now:
                            ctl.reallocate(
                                batch_id, step=done,
                                from_tag=prepared[i].job.tag,
                                to_tags=to_tags,
                                freed_chains=(
                                    prepared[i].job.config.n_chains))
                    active = remaining
                    offsets = _active_offsets()
                    job_ids = [prepared[i].job.job_id for i in active]

        wall = None
        if active and not (ctl is not None and len(active)
                           < len(prepared)):
            # original whole-batch epilogue (control off, or control on
            # with nothing stopped): finalize the full concat once and
            # slice per tenant
            if use_board:
                res = finalize_board_run(handle, spec, params, states,
                                         hist_parts, waits_total, [],
                                         True, cfg0.total_steps,
                                         cfg0.record_every, recorder=rec)
                states, history, waits_total = (res.state, res.history,
                                                res.waits_total)
            else:
                history = {k: np.concatenate(v, axis=1)
                           for k, v in hist_parts.items()}
            wall = time.perf_counter() - t0
            for i, p in enumerate(prepared):
                lo, hi = int(offsets[i]), int(offsets[i + 1])
                data = drv.assemble_run_data(
                    p.job.config, p.g, handle, use_board,
                    _slice_chains(states, lo, hi),
                    {k: np.asarray(v)[lo:hi] for k, v in history.items()},
                    waits_total[lo:hi].copy())
                data["batch"] = batch_id
                data["batch_chains"] = c_total
                results.append((p.job, data))
        elif active:
            # some tenants retired mid-run: the whole-batch history
            # layout changed, so finalize the survivors per tenant
            for pos, i in enumerate(active):
                lo, hi = int(offsets[pos]), int(offsets[pos + 1])
                results.append((prepared[i].job, _tenant_data(
                    i, _slice_chains(states, lo, hi), per_hist[i],
                    waits_total[lo:hi].copy())))
        if wall is None:
            wall = time.perf_counter() - t0
        for _, data in results:
            data["seconds"] = wall
        self.batch_stats.append(BatchStats(
            batch_id=batch_id, jobs=[p.job.job_id for p in prepared],
            chains=c_total, steps=cfg0.total_steps, wall_s=wall,
            kernel_path=path, cache_hit=hit))
        results.sort(key=lambda r: [p.job.job_id
                                    for p in prepared].index(r[0].job_id))
        return results

    # -- job terminals -----------------------------------------------

    def _complete(self, job: q.Job, data: dict, batch_id: str,
                  wall: float):
        self._journal("job_done", job_id=job.job_id, tag=job.tag,
                      batch_id=batch_id)
        job.status = q.DONE
        job.result = data
        job.error = None
        self._rec.emit("job_done", job_id=job.job_id, tag=job.tag,
                       status="done", batch=batch_id,
                       seconds=data.get("seconds", wall),
                       attempts=job.attempts)
        self._write_job_heartbeat(job, "done", batch=batch_id)
        self._write_summary()
        if self.verbose:
            print(f"[done] {job.job_id} {job.tag} "
                  f"({data.get('seconds', wall):.2f}s, {batch_id})")

    def _fail(self, job: q.Job, exc: BaseException, hb_state) -> int:
        """Supervisor-taxonomy failure handling for one job; returns 1
        when the job was requeued for retry (solo — isolation first),
        0 on a terminal failure."""
        rec = self._rec
        klass = classify_error(exc, anomalies=hb_state["anomalies"])
        msg = f"{type(exc).__name__}: {exc}"
        job.error = msg
        rec.emit("error", message=msg, tag=job.tag, job_id=job.job_id,
                 error_class=klass, attempt=job.attempts)
        if klass == DETERMINISTIC:
            job.det_failures += 1
        if job.det_failures >= self.policy.quarantine_after:
            self._journal("job_quarantined", job_id=job.job_id,
                          error=msg)
            job.status = q.QUARANTINED
            rec.emit("config_quarantined", tag=job.tag,
                     failures=job.det_failures)
            rec.emit("job_done", job_id=job.job_id, tag=job.tag,
                     status="quarantined", attempts=job.attempts)
            self._write_job_heartbeat(job, "quarantined", error=msg)
            self._write_summary()
            if self.verbose:
                print(f"[quarantine] {job.job_id} {job.tag} after "
                      f"{job.det_failures} deterministic failures "
                      f"({msg})")
            return 0
        if job.attempts > self.policy.max_retries:
            self._journal("job_failed", job_id=job.job_id, error=msg)
            job.status = q.FAILED
            rec.emit("config_failed", tag=job.tag, error_class=klass,
                     message=msg, attempts=job.attempts)
            rec.emit("job_done", job_id=job.job_id, tag=job.tag,
                     status="failed", attempts=job.attempts)
            self._write_job_heartbeat(job, "failed", error=msg)
            self._write_summary()
            if self.verbose:
                print(f"[failed] {job.job_id} {job.tag} after "
                      f"{job.attempts} attempts ({msg})")
            return 0
        wait = self.policy.backoff(job.attempts, self._rng)
        rec.emit("retry", tag=job.tag, attempt=job.attempts,
                 error_class=klass, backoff_s=wait, message=msg,
                 job_id=job.job_id)
        if self.verbose:
            print(f"[retry] {job.job_id} {job.tag} attempt "
                  f"{job.attempts} failed ({klass}: {msg}); backing "
                  f"off {wait:.2f}s")
        with obs.span(rec, "backoff", tag=job.tag,
                      attempt=job.attempts, backoff_s=wait,
                      error_class=klass):
            time.sleep(wait)
        self._journal("job_requeued", job_id=job.job_id, solo=True,
                      det_failures=job.det_failures, reason="retry")
        job.status = q.QUEUED
        job.solo = True
        self._write_job_heartbeat(job, "retrying", error=msg)
        self._write_summary()
        return 1
