"""On-demand device profiling for fleet jobs (ISSUE 18).

``POST /v1/profile/<job>`` drops an atomic marker doc under
``<root>/profile/<job>.json``. The worker that owns the job's lease
installs a :class:`ProfileWatcher` for the duration of the job; the
scheduler's segment loop calls :func:`segment_boundary` at every
segment edge (right next to the drain check — the one place the run is
guaranteed host-side and checkpoint-consistent). The watcher:

1. sees the marker at a boundary -> opens ``jax.profiler.start_trace``
   into ``<root>/profile/<job>.trace/``;
2. counts the requested number of segment boundaries;
3. stops the trace and publishes ``<root>/artifacts/<job>.profile.json``
   (atomic), removes the marker, and emits ``profile_captured`` — the
   capture is then fetchable via ``GET /v1/profile/<job>``.

Degradation is graceful by construction: a jax without a usable
profiler backend (CPU CI, missing tensorboard plugin) records
``ok=False`` with the error string and the run proceeds untouched; a
job that finishes before K segments publishes the segments it actually
bracketed. The marker probe is an ``os.path.exists`` per segment —
host-side file work only, in keeping with PROFILE.md's
no-extra-device-syncs rule (the profiler trace itself is the payload
the user explicitly requested).

The process-global watcher slot mirrors ``lifecycle``'s drain flag: the
scheduler consults it without threading a handle through SweepService's
API, and the worker installs/uninstalls around each job. One job runs
per worker process at a time, so one slot suffices.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from .. import obs

PROFILE_DIR = "profile"
_ARTIFACTS_DIR = "artifacts"

_LOCK = threading.Lock()
_WATCHER: Optional["ProfileWatcher"] = None


def install(watcher) -> Optional["ProfileWatcher"]:
    """Install (or, with None, clear) the process-global watcher;
    returns the previous one so callers can restore it."""
    global _WATCHER
    with _LOCK:
        prev = _WATCHER
        _WATCHER = watcher
    return prev


def segment_boundary(tag=None) -> None:
    """The scheduler's hook: called at every segment edge of the run
    loop (service.scheduler._run_batch, and around a solo dispatch).
    No-op unless a worker installed a watcher."""
    w = _WATCHER
    if w is not None:
        w.at_segment_boundary(tag)


# local copies of the fleet-root helpers: worker.py imports this module
# (and scheduler.py calls into it), so importing them back from
# worker.py would be a cycle
def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_json_atomic(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class ProfileWatcher:
    """Per-job marker watcher; the worker installs one around each
    claimed job and calls :meth:`finish` when the job leaves its hands
    (terminal, drained, or crashed out of the try block).

    Single-threaded by contract: every method runs on the worker's job
    thread (the scheduler loop IS that thread), so no locking."""

    def __init__(self, root: str, job_id: str, worker: str,
                 recorder=None, clock=time.time):
        self.root = root
        self.job_id = job_id
        self.worker = worker
        self._rec = obs.resolve_recorder(recorder)
        self._clock = clock
        self.marker_path = os.path.join(root, PROFILE_DIR,
                                        f"{job_id}.json")
        self._active: Optional[dict] = None

    def at_segment_boundary(self, tag=None) -> None:
        if self._active is None:
            if not os.path.exists(self.marker_path):
                return
            doc = _read_json(self.marker_path)
            if doc is None:
                return      # torn mid-replace; next boundary rereads
            self._start(doc)
            return
        self._active["segments_done"] += 1
        if self._active["segments_done"] >= self._active["segments"]:
            self._stop_and_publish()

    def finish(self) -> None:
        """Close out an in-flight capture at job exit: publish whatever
        was actually bracketed (a short job beats a lost capture)."""
        if self._active is not None:
            self._stop_and_publish()

    # -- internals ----------------------------------------------------

    def _start(self, marker: dict) -> None:
        segments = marker.get("segments")
        if not isinstance(segments, int) or segments < 1:
            segments = 1
        trace_dir = os.path.join(self.root, PROFILE_DIR,
                                 f"{self.job_id}.trace")
        active = {"segments": segments, "segments_done": 0,
                  "trace_dir": trace_dir, "ok": False, "error": None,
                  "started_ts": self._clock()}
        try:
            import jax

            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            active["ok"] = True
        except Exception as e:      # no profiler backend: degrade
            active["error"] = f"{type(e).__name__}: {e}"
        self._active = active

    def _stop_and_publish(self) -> None:
        active, self._active = self._active, None
        if active["ok"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                active["ok"] = False
                active["error"] = f"{type(e).__name__}: {e}"
        _write_json_atomic(
            os.path.join(self.root, _ARTIFACTS_DIR,
                         f"{self.job_id}.profile.json"),
            {"job_id": self.job_id, "worker": self.worker,
             "segments": active["segments_done"],
             "requested_segments": active["segments"],
             "trace_dir": active["trace_dir"] if active["ok"] else None,
             "ok": active["ok"], "error": active["error"],
             "started_ts": active["started_ts"],
             "captured_ts": self._clock()})
        try:
            os.remove(self.marker_path)
        except OSError:
            pass
        self._rec.emit("profile_captured", job_id=self.job_id,
                       segments=active["segments_done"],
                       ok=active["ok"], error=active["error"],
                       worker=self.worker)
