"""Stdlib client for the fleet front door (``service.server``).

The other side of the HTTP contract: ``ServiceClient`` wraps the
``/v1`` routes in methods the CLI subcommands (``submit`` / ``status``)
and the live-mode loadtest drive. Pure stdlib (``urllib``), pure JSON —
a tenant integration needs nothing from this package beyond this file's
idea of the routes, which is the point of having a network surface.

Refusals map to ``ClientError`` with the HTTP status attached, so
callers distinguish a quota rejection (429 — back off and retry) from a
drain (503 — the fleet is going away) from a bad request (400 — fix the
submission) without string-matching."""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional


class ClientError(RuntimeError):
    """An HTTP-level refusal. ``status`` is the response code (429
    quota, 503 draining/fault, 404 unknown, 400 bad submission);
    ``body`` is the parsed error doc when the server sent one."""

    def __init__(self, status: int, message: str,
                 body: Optional[dict] = None):
        self.status = status
        self.body = body or {}
        super().__init__(f"HTTP {status}: {message}")


class ServiceClient:
    """One front door, one tenant identity. ``timeout_s`` bounds each
    request; ``wait`` polls with the injected sleep so tests drive it
    on a virtual timeline."""

    def __init__(self, url: str, tenant: str = "default",
                 timeout_s: float = 10.0, sleep=time.sleep):
        self.url = url.rstrip("/")
        self.tenant = tenant
        self.timeout_s = float(timeout_s)
        self._sleep = sleep

    # -- transport ----------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        data = (json.dumps(body).encode("utf-8")
                if body is not None else None)
        req = urllib.request.Request(
            self.url + path, data=data, method=method,
            headers={"Content-Type": "application/json",
                     "X-Tenant": self.tenant})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                doc = {}
            raise ClientError(e.code, doc.get("error", e.reason),
                              body=doc) from None
        except urllib.error.URLError as e:
            raise ClientError(0, f"unreachable: {e.reason}") from None

    # -- the /v1 surface ----------------------------------------------

    def submit(self, workload: Optional[str] = None,
               config: Optional[dict] = None,
               overrides: Optional[dict] = None) -> dict:
        """POST /v1/jobs: by catalog name or full config doc. Returns
        ``{job_id, tag, tenant, fingerprint}``."""
        body: dict = {"tenant": self.tenant}
        if workload is not None:
            body["workload"] = workload
            if overrides:
                body["overrides"] = overrides
        elif config is not None:
            body["config"] = config
        else:
            raise ValueError("submit needs a workload name or a "
                             "config doc")
        return self._request("POST", "/v1/jobs", body)

    def status(self, job_id: str) -> dict:
        return self._request("GET", f"/v1/jobs/{job_id}")

    def jobs(self) -> dict:
        return self._request("GET", "/v1/jobs")

    def artifact(self, job_id: str) -> dict:
        return self._request("GET", f"/v1/jobs/{job_id}/artifact")

    def workloads(self) -> list:
        return self._request("GET", "/v1/workloads")["workloads"]

    def healthz(self) -> dict:
        return self._request("GET", "/v1/healthz")

    def drain(self) -> dict:
        return self._request("POST", "/v1/drain", {})

    # -- conveniences -------------------------------------------------

    TERMINAL = ("done", "failed", "quarantined")

    def wait(self, job_id: str, timeout_s: float = 300.0,
             poll_s: float = 0.5) -> dict:
        """Poll until ``job_id`` is terminal; returns its final status
        doc. Raises ClientError(0) on timeout — the job itself is NOT
        cancelled (the fleet owns it; the client only watches)."""
        waited = 0.0
        while True:
            doc = self.status(job_id)
            if doc.get("status") in self.TERMINAL:
                return doc
            if waited >= timeout_s:
                raise ClientError(
                    0, f"timeout: {job_id} still "
                       f"{doc.get('status')!r} after {timeout_s:g}s")
            self._sleep(poll_s)
            waited += poll_s

    def wait_all(self, job_ids, timeout_s: float = 300.0,
                 poll_s: float = 0.5) -> dict:
        """``{job_id: final status doc}`` for every id, polling the
        fleet view (one request per poll, not per job)."""
        pending = set(job_ids)
        out: dict = {}
        waited = 0.0
        while pending:
            fleet = {j["job_id"]: j for j in self.jobs()["jobs"]}
            for job_id in list(pending):
                doc = fleet.get(job_id)
                if doc and doc.get("status") in self.TERMINAL:
                    out[job_id] = doc
                    pending.discard(job_id)
            if not pending:
                break
            if waited >= timeout_s:
                raise ClientError(
                    0, f"timeout: {sorted(pending)} not terminal "
                       f"after {timeout_s:g}s")
            self._sleep(poll_s)
            waited += poll_s
        return out
