"""Sweep-as-a-service: coalesced multi-tenant batching over one device.

The one-shot drivers (``experiments.driver.run_sweep`` and the
supervised variant) own the hardware for one sweep and pay a full XLA
compile per config shape — fine for the replication grids, wasteful for
the real demand shape of many small heterogeneous sweep REQUESTS
(ISSUE 9): the chip idles between invocations and every tenant
recompiles kernels a neighbor just built.

This package turns that loop inside out:

- ``queue.JobQueue``    — accepts ``ExperimentConfig`` submissions as
  ``Job`` records (``job_submitted`` events).
- ``scheduler.SweepService`` — groups compatible jobs (equal
  ``ExperimentConfig.fingerprint()`` => same graph, Spec, and run
  shape) and runs each group as ONE device batch along the chain axis,
  slicing per-tenant results back out (``job_batched`` /
  ``job_done``). Chains are independent by construction (per-chain
  PRNG keys live in the state), so a tenant's sliced rows are
  bit-identical to a solo run. Failures reuse the PR 7 machinery:
  ``resilience.supervisor.classify_error`` + ``RetryPolicy`` backoff,
  quarantine for poison configs, per-segment checkpoints per tenant.
- ``cache.CompileCache`` — probe keyed on
  ``lower.dispatch.lowering_signature`` + batch shape
  (``compile_cache_hit`` / ``compile_cache_miss`` events), optionally
  persisted next to JAX's on-disk compilation cache
  (``enable_persistent_cache``) so service restarts skip XLA compiles.

Preemption-proofing (ISSUE 11) makes the loop durable:

- ``journal.Journal`` — fsync'd write-ahead JSONL of every job/batch
  transition (seq + per-record SHA-256; torn tails detected and
  dropped with ``journal_truncated``), consumed by
  ``SweepService.recover(outdir)`` to rebuild the queue after a crash.
- ``lifecycle`` — graceful drain (SIGTERM/SIGINT -> cooperative flag
  -> ``DrainRequested`` at segment boundaries, distinct exit code
  ``EXIT_DRAINED``) and the ``DispatchWatchdog`` thread that journals
  hung device dispatches as poison-suspect so a restart retries those
  jobs solo.

The fleet layer (PR 17) scales the loop across processes and a
network boundary:

- ``server`` — the HTTP/JSON front door (stdlib threaded
  ``http.server``): submit by workload-catalog name or full config
  doc, per-tenant token-bucket quotas, weighted-fair admission into a
  shared spool, status/artifact reads, a journaled drain endpoint.
- ``worker`` — N crash-interchangeable worker processes claiming
  spooled jobs via atomic lease files with mtime heartbeats; each job
  runs in its own single-job ``SweepService`` so every journal /
  checkpoint / recovery guarantee holds per job across processes (a
  SIGKILLed worker's job is reclaimed and resumed bit-identically).
- ``client`` — the stdlib tenant client the ``submit`` / ``status``
  CLI subcommands and the live-mode loadtest drive.

``python -m flipcomplexityempirical_tpu.service --simulate`` is the
hardware-free proof: N tenants coalesced on one device vs one tenant
solo, reported as ``tenant_efficiency`` (also ``bench.py --service``).
``serve`` / ``worker`` / ``submit`` / ``status`` subcommands run the
fleet (``make fleet-check`` gates it end to end).
"""

from .cache import CompileCache, enable_persistent_cache
from .client import ClientError, ServiceClient
from .journal import Journal
from .lifecycle import (DispatchWatchdog, DrainController,
                        DrainRequested, EXIT_DRAINED, check_drain,
                        clear_drain, clear_drain_marker, drain_marked,
                        drain_requested, mark_drain, request_drain)
from .queue import Job, JobQueue
from .scheduler import SweepService, concat_params, concat_states
from .server import (FairAdmission, FleetServer, FrontDoor, TokenBucket,
                     serve)
from .worker import LeaseManager, Worker, fleet_dirs, result_summary

__all__ = [
    "CompileCache", "enable_persistent_cache",
    "ClientError", "ServiceClient",
    "Journal",
    "DispatchWatchdog", "DrainController", "DrainRequested",
    "EXIT_DRAINED", "check_drain", "clear_drain",
    "clear_drain_marker", "drain_marked", "drain_requested",
    "mark_drain", "request_drain",
    "Job", "JobQueue",
    "SweepService", "concat_params", "concat_states",
    "FairAdmission", "FleetServer", "FrontDoor", "TokenBucket",
    "serve",
    "LeaseManager", "Worker", "fleet_dirs", "result_summary",
]
