"""flipcomplexityempirical_tpu — TPU-native flip-walk sampling framework.

A from-scratch, JAX/XLA-first re-design of the capabilities of
LorenzoNajt/FlipComplexityEmpirical (replication code for "Complexity of
Sampling Connected Graph Partitions") plus the gerrychain engine surface it
consumes: batched single-node-flip Markov chains over planar graph
partitions, vectorized as jit+vmap kernels over an (n_chains, n_nodes)
assignment tensor, sharded over TPU meshes, with the reference's experiment
sweeps, metrics, and artifact pipeline reproduced on top.
"""

__version__ = "0.1.0"

from . import obs  # noqa: F401
from . import graphs  # noqa: F401
from . import compat  # noqa: F401
from . import state  # noqa: F401
from . import lower  # noqa: F401
from . import kernel  # noqa: F401
from . import sampling  # noqa: F401
from . import stats  # noqa: F401
from .kernel import Spec  # noqa: F401
from .sampling import run_chains, init_batch  # noqa: F401
