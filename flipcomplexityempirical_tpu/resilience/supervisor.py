"""Sweep supervisor: per-config isolation, classified retries, quarantine.

``run_supervised_sweep`` is the fault-tolerant counterpart of
``experiments.driver.run_sweep``: one config's failure no longer kills
the sweep. Each failure is classified (``classify_error``) as

- ``transient``  — I/O hiccups, injected non-poison faults, anything
  unrecognized: retry with exponential backoff + seeded jitter,
  resuming from the config's last checkpoint;
- ``resource``   — OOM / RESOURCE_EXHAUSTED / deadline overruns: also
  retried (the resume shrinks the remaining work, and pressure may
  pass);
- ``deterministic`` — identity/shape/value errors, poison faults, or
  failures under frozen-chain / acceptance-collapse anomalies (the PR 3
  taxonomy: the walk itself is sick, not the machinery): these count
  toward quarantine — after ``quarantine_after`` of them the config is
  isolated (``config_quarantined`` event) so a poison config cannot
  starve the rest of the sweep.

Everything here is host-side between segments: backoff sleeps, deadline
checks and event emission never touch the device, so the
no-added-syncs guard-rail (PROFILE.md) is untouched.

The wall-clock watchdog is cooperative: a ``DeadlineScope`` arms a
monotonic budget for ONE supervision and the driver's segment loops
call ``check_deadline()`` between segments — a JAX dispatch cannot be
interrupted mid-flight, but a segment is bounded (checkpoint_every
steps), which bounds the overshoot. Scopes are tracked by identity in
a registry of *all* active supervisions, so two jobs supervised in the
same process (the sweep service interleaves them) cannot clobber each
other's budget — ending one scope never disarms another, and
``check_deadline`` raises for whichever active scope expired.
``set_deadline``/``clear_deadline`` remain as LIFO wrappers for
call sites that own the whole process.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional

from .. import obs
from .errors import CheckpointIdentityError, ConfigDeadlineExceeded
from .faults import InjectedFault

TRANSIENT = "transient"
RESOURCE = "resource"
DETERMINISTIC = "deterministic"

# message markers for resource pressure (jax surfaces OOM as
# XlaRuntimeError text, not a dedicated class)
_RESOURCE_MARKERS = ("resource_exhausted", "resource exhausted",
                     "out of memory", "oom", "memory_limit",
                     "allocation failure")

# monitor anomaly kinds that mark the *walk* as deterministically sick
# (PR 3 taxonomy): a config failing while frozen or collapsed will fail
# the same way on every retry.
_POISON_ANOMALIES = ("frozen_chain", "acceptance_collapse")


def classify_error(exc: BaseException, anomalies=()) -> str:
    """transient / resource / deterministic for one failure, given the
    exception and the per-kind anomaly tally observed during the
    attempt (the heartbeat hook state of driver.install_live_hooks)."""
    if isinstance(exc, InjectedFault):
        return DETERMINISTIC if exc.poison else TRANSIENT
    if isinstance(exc, (ConfigDeadlineExceeded, MemoryError)):
        return RESOURCE
    msg = str(exc).lower()
    if any(m in msg for m in _RESOURCE_MARKERS):
        return RESOURCE
    if isinstance(exc, (CheckpointIdentityError, ValueError, TypeError,
                        KeyError, IndexError, AssertionError,
                        ZeroDivisionError)):
        return DETERMINISTIC
    if any(k in _POISON_ANOMALIES for k in anomalies):
        return DETERMINISTIC
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return TRANSIENT
    return TRANSIENT


# ---------------------------------------------------------------------
# cooperative per-supervision deadlines
#
# Every active supervision holds its own DeadlineScope; the registry
# below tracks them by object identity. The historical single module
# slot meant two interleaved supervisions clobbered each other (job B's
# set_deadline(None) silently disarmed job A's budget) — with identity
# tracking, ending one scope can only ever remove that scope.

_active_deadlines: list = []          # DeadlineScope objects, any order
_legacy_deadlines: list = []          # scopes opened via set_deadline


class DeadlineScope:
    """One supervision's wall-clock budget. ``begin`` arms it on the
    monotonic clock and registers it; ``end`` unregisters (idempotent).
    A None/0 budget is a valid unarmed scope — it participates in the
    begin/end pairing without ever expiring."""

    def __init__(self, budget_s: Optional[float], tag: str = ""):
        self.budget_s = float(budget_s) if budget_s else None
        self.tag = tag
        self._end = None

    def begin(self) -> "DeadlineScope":
        if self.budget_s is not None:
            self._end = time.monotonic() + self.budget_s
        _active_deadlines.append(self)
        return self

    def end(self) -> None:
        try:
            _active_deadlines.remove(self)
        except ValueError:
            pass

    def expired(self) -> bool:
        return self._end is not None and time.monotonic() > self._end

    def __enter__(self) -> "DeadlineScope":
        return self.begin()

    def __exit__(self, *exc) -> None:
        self.end()


def set_deadline(budget_s: Optional[float], tag: str = ""):
    """LIFO wrapper over DeadlineScope for single-supervision callers
    (CLI paths, tests). Interleaved supervisions must hold their own
    scope objects instead. Returns the opened scope."""
    scope = DeadlineScope(budget_s, tag).begin()
    _legacy_deadlines.append(scope)
    return scope


def clear_deadline():
    """Close the most recent set_deadline scope (no-op when none is
    open, so historical double-clear call sites stay harmless)."""
    if _legacy_deadlines:
        _legacy_deadlines.pop().end()


def check_deadline():
    """Called by the driver's segment loops between segments: raises
    for whichever active supervision's budget expired."""
    for scope in list(_active_deadlines):
        if scope.expired():
            raise ConfigDeadlineExceeded(scope.tag, scope.budget_s)


# ---------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Retry/backoff/quarantine knobs. ``max_retries`` is retries, not
    attempts (a config gets 1 + max_retries tries). ``seed`` drives the
    jitter PRNG — supervised sweeps are as reproducible as the faults
    they absorb."""

    max_retries: int = 3
    quarantine_after: int = 2       # deterministic failures -> quarantine
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter: float = 0.25            # uniform extra fraction of the backoff
    deadline_s: Optional[float] = None  # per-config wall budget
    seed: int = 0

    def backoff(self, attempt: int, rng: random.Random) -> float:
        base = min(self.backoff_base_s
                   * self.backoff_factor ** (attempt - 1),
                   self.backoff_max_s)
        return base * (1.0 + self.jitter * rng.random())


@dataclass
class SweepReport:
    """What ``run_supervised_sweep`` returns. ``results`` matches
    run_sweep's (cfg, data) list for the configs that completed this
    call; the tag lists drive the CLI exit code and sweep_summary."""

    results: list = field(default_factory=list)
    completed: list = field(default_factory=list)
    skipped: list = field(default_factory=list)
    quarantined: list = field(default_factory=list)
    failed: list = field(default_factory=list)
    retried: int = 0
    attempts: dict = field(default_factory=dict)   # tag -> tries used

    @property
    def exit_code(self) -> int:
        return 2 if (self.quarantined or self.failed) else 0


def run_supervised_sweep(configs, outdir: str,
                         checkpoint_dir: Optional[str] = None,
                         verbose: bool = True, recorder=None,
                         heartbeat: Optional[str] = None,
                         policy: Optional[RetryPolicy] = None,
                         control=None) -> SweepReport:
    """The fault-tolerant sweep. Same per-config telemetry contract as
    driver.run_sweep (sweep/config spans, sweep_config events, live
    heartbeat hooks) plus: ``retry`` events with ``backoff`` spans
    around the waits, ``config_failed`` / ``config_quarantined`` when a
    config is given up on, and one ``sweep_summary`` at the end.
    Retries resume from the config's last checkpoint automatically
    (run_config's segment resume)."""
    from ..experiments import driver as drv

    policy = policy or RetryPolicy()
    rng = random.Random(policy.seed)
    rec = obs.resolve_recorder(recorder)
    if control is not None:
        control.attach(recorder=rec)
    configs = list(configs)
    report = SweepReport()
    n_configs = len(configs)

    def _progress():
        return dict(n_done=len(report.completed),
                    n_skipped=len(report.skipped), n_configs=n_configs)

    sweep_span = obs.span(rec, "sweep", n_configs=n_configs,
                          supervised=True)
    sweep_span.begin()
    deadline = None
    try:
        for i, cfg in enumerate(configs):
            if drv.is_done(cfg, outdir):
                report.skipped.append(cfg.tag)
                if verbose:
                    print(f"[skip] {cfg.family} {cfg.tag} "
                          f"(artifacts complete)")
                rec.emit("sweep_config", tag=cfg.tag, family=cfg.family,
                         status="skip",
                         artifacts=len(drv.artifact_kinds(cfg.family)),
                         index=i, n_configs=n_configs)
                drv.write_heartbeat(heartbeat, recorder=rec,
                                    status="running", current=None,
                                    last=cfg.tag, **_progress())
                continue
            attempts = 0
            det_failures = 0
            while True:
                attempts += 1
                report.attempts[cfg.tag] = attempts
                t0 = time.monotonic()
                rec.emit("sweep_config", tag=cfg.tag, family=cfg.family,
                         status="start",
                         artifacts=drv.count_artifacts(cfg, outdir),
                         index=i, n_configs=n_configs,
                         attempt=attempts)
                drv.write_heartbeat(heartbeat, recorder=rec,
                                    status="running", current=cfg.tag,
                                    last=None, attempt=attempts,
                                    **_progress())
                cfg_span = obs.span(rec, "config", tag=cfg.tag,
                                    family=cfg.family,
                                    attempt=attempts).begin()
                hb_state, uninstall = drv.install_live_hooks(
                    rec, heartbeat, cfg, _progress(), control=control)
                deadline = DeadlineScope(policy.deadline_s,
                                         cfg.tag).begin()
                # control is threaded only when armed: run_config
                # stand-ins (tests, older callers) need not grow the
                # kwarg to stay substitutable
                _ctl = {} if control is None else {"control": control}
                try:
                    data = drv.run_config(cfg, outdir, checkpoint_dir,
                                          recorder=rec, **_ctl)
                except Exception as e:
                    deadline.end()
                    uninstall()
                    klass = classify_error(
                        e, anomalies=hb_state["anomalies"])
                    msg = f"{type(e).__name__}: {e}"
                    rec.emit("error", message=msg, tag=cfg.tag,
                             family=cfg.family, error_class=klass,
                             attempt=attempts)
                    cfg_span.end(error=type(e).__name__,
                                 error_class=klass)
                    if klass == DETERMINISTIC:
                        det_failures += 1
                    if det_failures >= policy.quarantine_after:
                        report.quarantined.append(cfg.tag)
                        rec.emit("config_quarantined", tag=cfg.tag,
                                 failures=det_failures)
                        if verbose:
                            print(f"[quarantine] {cfg.family} {cfg.tag} "
                                  f"after {det_failures} deterministic "
                                  f"failures ({msg})")
                        drv.write_heartbeat(
                            heartbeat, recorder=rec,
                            status="quarantined", current=cfg.tag,
                            last=None, error=msg, **_progress())
                        break
                    if attempts > policy.max_retries:
                        report.failed.append(cfg.tag)
                        rec.emit("config_failed", tag=cfg.tag,
                                 error_class=klass, message=msg,
                                 attempts=attempts)
                        if verbose:
                            print(f"[failed] {cfg.family} {cfg.tag} "
                                  f"after {attempts} attempts ({msg})")
                        drv.write_heartbeat(
                            heartbeat, recorder=rec, status="failed",
                            current=cfg.tag, last=None, error=msg,
                            **_progress())
                        break
                    report.retried += 1
                    wait = policy.backoff(attempts, rng)
                    rec.emit("retry", tag=cfg.tag, attempt=attempts,
                             error_class=klass, backoff_s=wait,
                             message=msg)
                    if verbose:
                        print(f"[retry] {cfg.family} {cfg.tag} "
                              f"attempt {attempts} failed "
                              f"({klass}: {msg}); backing off "
                              f"{wait:.2f}s")
                    with obs.span(rec, "backoff", tag=cfg.tag,
                                  attempt=attempts, backoff_s=wait,
                                  error_class=klass):
                        time.sleep(wait)
                    continue
                else:
                    deadline.end()
                    uninstall()
                    report.completed.append(cfg.tag)
                    report.results.append((cfg, data))
                    seconds = time.monotonic() - t0
                    cfg_span.end(seconds=seconds, attempts=attempts)
                    rec.emit("sweep_config", tag=cfg.tag,
                             family=cfg.family, status="done",
                             artifacts=drv.count_artifacts(cfg, outdir),
                             seconds=seconds, index=i,
                             n_configs=n_configs, attempt=attempts)
                    drv.write_heartbeat(heartbeat, recorder=rec,
                                        status="running", current=None,
                                        last=cfg.tag, **_progress())
                    if verbose:
                        print(f"[done] {cfg.family} {cfg.tag} "
                              f"waits={data['waits_sum']:.4g} "
                              f"({seconds:.1f}s"
                              + (f", attempt {attempts}"
                                 if attempts > 1 else "") + ")")
                    break
    finally:
        if deadline is not None:
            deadline.end()   # idempotent: covers an escape mid-attempt
        sweep_span.end(n_done=len(report.completed),
                       n_skipped=len(report.skipped),
                       n_quarantined=len(report.quarantined),
                       n_failed=len(report.failed))
    rec.emit("sweep_summary", completed=len(report.completed),
             retried=report.retried,
             quarantined=len(report.quarantined),
             failed=len(report.failed),
             skipped=len(report.skipped),
             quarantined_tags=list(report.quarantined),
             failed_tags=list(report.failed))
    drv.write_heartbeat(
        heartbeat, recorder=rec,
        status=("complete" if not (report.quarantined or report.failed)
                else "complete_with_failures"),
        current=None, last=None, quarantined=list(report.quarantined),
        failed=list(report.failed), **_progress())
    return report
