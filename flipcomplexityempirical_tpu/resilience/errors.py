"""Typed failure vocabulary for the resilience layer.

Every exception here exists so the supervisor (resilience.supervisor)
can *classify* a failure instead of pattern-matching strings: checkpoint
identity clashes are deterministic (retrying reproduces them), deadline
overruns are resource pressure (a retry under less contention may pass),
and kernel-path failures carry which dispatch-ladder body died so the
driver can fall to the next one.
"""

from __future__ import annotations


class CheckpointIdentityError(RuntimeError):
    """A checkpoint exists for this tag but was written under a different
    kernel path or Spec: the state fields on disk don't cover the fields
    the current run's state template needs. Resuming would silently mix
    two walks, so this refuses loudly and names both sides plus the
    remedy (ISSUE 7 satellite: previously a bare KeyError)."""

    def __init__(self, tag: str, expected_fields, found_fields,
                 identity: str = ""):
        self.tag = tag
        self.expected_fields = tuple(sorted(expected_fields))
        self.found_fields = tuple(sorted(found_fields))
        self.identity = identity
        missing = sorted(set(self.expected_fields)
                         - set(self.found_fields))
        super().__init__(
            f"checkpoint for {tag!r} was written by a different kernel "
            f"path or Spec: it carries state fields "
            f"{list(self.found_fields)} but the current run's state "
            f"template needs {list(self.expected_fields)} "
            f"(missing: {missing}). Remedy: delete the checkpoint "
            f"(fresh start) or rerun under the config that wrote it "
            f"(identity {identity!r}).")


class ConfigDeadlineExceeded(RuntimeError):
    """The cooperative per-config wall-clock watchdog tripped: the
    segment loop checked ``supervisor.check_deadline()`` between
    segments and found the budget spent. Classified as a *resource*
    failure — the retry resumes from the last checkpoint with a fresh
    budget, so a config slightly over the line still finishes."""

    def __init__(self, tag: str, budget_s: float):
        self.tag = tag
        self.budget_s = float(budget_s)
        super().__init__(
            f"config {tag!r} exceeded its {budget_s:.1f}s wall-clock "
            "deadline (checked between segments; resume from the last "
            "checkpoint continues the walk)")


class KernelPathError(RuntimeError):
    """A dispatch-ladder body failed (compile or runtime) and no
    lower body exists *within the board family* — the driver catches
    this and reruns the config on the general gather kernel."""

    def __init__(self, path: str, cause: BaseException):
        self.path = path
        self.cause = cause
        super().__init__(
            f"kernel path {path!r} failed "
            f"({type(cause).__name__}: {cause})")
