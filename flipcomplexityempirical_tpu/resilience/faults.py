"""Deterministic fault injection: every recovery path testable on CPU.

A ``FaultPlan`` arms named *sites* — fixed strings the production code
consults at its failure-prone boundaries:

=================  ====================================================
site               consulted by
=================  ====================================================
checkpoint.write   driver.save_checkpoint (raise before writing;
                   truncate rules corrupt the freshly-renamed file)
checkpoint.load    driver.load_checkpoint (raise before reading;
                   truncate rules corrupt the on-disk main file)
segment.step       the segment loops in driver._run_jax /
                   _run_temper_segmented, before each segment
compile            sampling.board_runner / distribute.sharded, before
                   each chunk dispatch, and sampling.runner on the
                   general_dense rung only — the legacy general floor
                   stays fault-free so poisoned runs can complete
                   (stands in for an XLA compile/runtime error to
                   exercise degradation)
recorder.emit      obs.recorder.Recorder.emit (telemetry sink I/O)
heartbeat.write    driver.write_heartbeat (must be non-fatal)
sigterm            service.lifecycle.check_drain (an armed rule stands
                   in for a real SIGTERM: the drain flag is raised at
                   that segment boundary, so preemption drains are
                   byte-reproducible — ``sigterm:once@HIT`` picks the
                   boundary)
journal.append     service.journal.Journal.append (raise before the
                   write; truncate rules tear the journal tail after
                   it — the torn-tail detection path)
dispatch.stall     service.lifecycle.DispatchWatchdog.stall_point (a
                   firing rule holds the dispatch past the watchdog
                   timeout, then surfaces as the killed hung call)
lease.write        service.worker lease-file claim/refresh (raise
                   before the write — a claim that never lands;
                   truncate rules tear the freshly-written lease file —
                   the torn lease another worker must treat as dead,
                   not block on)
http.accept        service.server request dispatch, before routing (a
                   firing rule turns into a 503 — the front door's
                   failure mode is a refused request, never a torn
                   state mutation)
worker.sigkill     service.worker lease-heartbeat beats (a firing rule
                   SIGKILLs the worker process mid-run — the
                   crash-interchangeability story: the stale lease
                   expires and a surviving worker resumes the job from
                   its sliced checkpoint)
=================  ====================================================

Plan grammar (CLI ``--faults`` / env ``GRAFT_FAULTS``), comma-separated
entries::

    checkpoint.write:once,segment.step:once@4,compile:p=0.1,seed=7

    entry := SITE ':' MODE | 'seed=' INT
    MODE  := 'once'['@'HIT]        fail exactly one hit
           | 'fail*'COUNT['@'HIT]  fail COUNT consecutive hits
           | 'always'              poison: fail every hit (deterministic)
           | 'p='PROB['@'HIT]      fail each hit w.p. PROB (seeded PRNG)
           | 'truncate'['@'HIT]    I/O sites: truncate the file instead
                                   of raising (a torn write)

``@HIT`` is the 1-based hit ordinal at which the rule arms (default 1);
earlier hits pass through. Hit counters are per site and process-wide,
so a spec addresses "the 4th segment dispatched anywhere in the sweep"
— which is what makes chaos tests byte-reproducible. Raising modes and
truncate modes count hits independently (a site's ``fault_point`` calls
vs its ``corrupt_file`` calls are different streams).

Everything is plain-Python and host-side: with no plan installed,
``fault_point`` is one global read — nothing is added to traced code.
"""

from __future__ import annotations

import os
import random
import re
import threading
from typing import Optional

ENV_VAR = "GRAFT_FAULTS"

# The canonical fault-site registry. Every ``fault_point`` /
# ``corrupt_file`` call names a key of this dict, and graftlint's G013
# checks injection points AND the ``--faults`` plan strings in the gate
# scripts against it — rename a site here and every stale literal
# anywhere in the tree flags at lint time instead of silently never
# arming.
FAULT_SITES = {
    "checkpoint.write": "atomic checkpoint doc write (corruptible)",
    "checkpoint.load": "checkpoint doc read/parse on recovery",
    "segment.step": "one dispatched segment of the sweep loop",
    "compile": "kernel compile/lower (cache-miss path)",
    "recorder.emit": "telemetry event append",
    "heartbeat.write": "driver/worker heartbeat doc write (corruptible)",
    "sigterm": "drain-signal delivery point",
    "journal.append": "fleet/run journal WAL append",
    "dispatch.stall": "watchdog-observed dispatch stall",
    "lease.write": "worker lease claim/refresh write (corruptible)",
    "http.accept": "front-door connection accept",
    "worker.sigkill": "hard worker kill between segments",
}

# Backwards-compatible tuple view (insertion order preserved).
SITES = tuple(FAULT_SITES)

_RAISING_MODES = ("fail", "always", "p")


class InjectedFault(RuntimeError):
    """Raised by an armed ``fault_point``. ``poison`` marks the
    ``always`` mode — a deterministic failure the supervisor must
    quarantine rather than burn retries on."""

    def __init__(self, site: str, mode: str, hit: int):
        self.site = site
        self.mode = mode
        self.hit = hit
        super().__init__(
            f"injected fault at site {site!r} (mode {mode}, hit {hit})")

    @property
    def poison(self) -> bool:
        return self.mode == "always"


class FaultRule:
    """One armed behavior at one site. ``kind`` in fail/always/p/
    truncate; see the module docstring for semantics."""

    def __init__(self, site: str, kind: str, count: int = 1,
                 prob: float = 0.0, at: int = 1):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(sites: {list(SITES)})")
        if kind not in _RAISING_MODES + ("truncate",):
            raise ValueError(f"unknown fault mode {kind!r}")
        if at < 1:
            raise ValueError(f"@HIT ordinal must be >= 1, got {at}")
        self.site = site
        self.kind = kind
        self.count = int(count)
        self.prob = float(prob)
        self.at = int(at)
        self.fired = 0

    def should_fire(self, hit: int, rng: random.Random) -> bool:
        if hit < self.at:
            return False
        if self.kind == "always":
            return True
        if self.kind == "p":
            return rng.random() < self.prob
        if self.fired >= self.count:       # fail / truncate: budgeted
            return False
        self.fired += 1
        return True

    def describe(self) -> str:
        mode = {"fail": (f"once" if self.count == 1
                         else f"fail*{self.count}"),
                "always": "always",
                "p": f"p={self.prob:g}",
                "truncate": "truncate"}[self.kind]
        return (f"{self.site}:{mode}"
                + (f"@{self.at}" if self.at != 1 else ""))


def _parse_mode(tok: str):
    """(kind, count, prob, at) from one MODE token."""
    at = 1
    if "@" in tok:
        tok, at_s = tok.split("@", 1)
        at = int(at_s)
    if tok == "once":
        return "fail", 1, 0.0, at
    if tok == "always":
        return "always", 0, 0.0, at
    if tok == "truncate":
        return "truncate", 1, 0.0, at
    m = re.fullmatch(r"fail\*(\d+)", tok)
    if m:
        return "fail", int(m.group(1)), 0.0, at
    m = re.fullmatch(r"p=([0-9.eE+-]+)", tok)
    if m:
        return "p", 0, float(m.group(1)), at
    raise ValueError(f"unknown fault mode {tok!r} (grammar: once[@H], "
                     "fail*N[@H], always, p=X[@H], truncate[@H])")


class FaultPlan:
    """A parsed, seeded set of FaultRules plus the per-site hit
    counters. One plan is installed process-wide (``install_plan``);
    the production sites consult it through ``fault_point`` /
    ``corrupt_file`` below."""

    def __init__(self, rules=(), seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._hits: dict = {}      # site -> fault_point hit count
        self._io_hits: dict = {}   # site -> corrupt_file hit count
        self._lock = threading.Lock()
        self.log: list = []        # (site, mode, hit) of every firing

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        rules = []
        seed = 0
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            m = re.fullmatch(r"seed=(\d+)", entry)
            if m:
                seed = int(m.group(1))
                continue
            if ":" not in entry:
                raise ValueError(f"fault entry {entry!r} is not "
                                 "SITE:MODE or seed=N")
            site, mode = entry.split(":", 1)
            kind, count, prob, at = _parse_mode(mode.strip())
            rules.append(FaultRule(site.strip(), kind, count=count,
                                   prob=prob, at=at))
        return cls(rules, seed=seed)

    def describe(self) -> str:
        parts = [r.describe() for r in self.rules]
        if self.seed:
            parts.append(f"seed={self.seed}")
        return ",".join(parts)

    def check(self, site: str, **ctx):
        """Raise InjectedFault when a raising rule at ``site`` fires."""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for rule in self.rules:
                if rule.site != site or rule.kind == "truncate":
                    continue
                if rule.should_fire(hit, self._rng):
                    mode = ("always" if rule.kind == "always"
                            else rule.kind)
                    self.log.append((site, mode, hit))
                    raise InjectedFault(site, mode, hit)

    def wants_corruption(self, site: str) -> bool:
        """One truncate-rule consultation for ``site`` (independent hit
        stream from ``check``)."""
        with self._lock:
            hit = self._io_hits.get(site, 0) + 1
            self._io_hits[site] = hit
            for rule in self.rules:
                if rule.site != site or rule.kind != "truncate":
                    continue
                if rule.should_fire(hit, self._rng):
                    self.log.append((site, "truncate", hit))
                    return True
        return False


_ACTIVE: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` process-wide (None clears). Also syncs the
    recorder's lazy hook so ``Recorder.emit`` consults the plan without
    obs importing this package at module level. Returns the previous
    plan."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    from ..obs import recorder as _recorder_mod

    _recorder_mod._fault_check = (None if plan is None
                                  else plan.check)
    return prev


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def install_from_spec(spec: Optional[str]) -> Optional[FaultPlan]:
    """``--faults`` / env plumbing: parse and install, or clear on a
    falsy spec. Returns the installed plan (or None)."""
    if not spec:
        install_plan(None)
        return None
    plan = FaultPlan.from_spec(spec)
    install_plan(plan)
    return plan


def install_from_env(environ=os.environ) -> Optional[FaultPlan]:
    return install_from_spec(environ.get(ENV_VAR))


def fault_point(site: str, **ctx):
    """The production-code hook: no-op unless a plan is installed and a
    raising rule at ``site`` fires (then: InjectedFault)."""
    plan = _ACTIVE
    if plan is not None:
        plan.check(site, **ctx)


def truncate_file(path: str, keep_numerator: int = 1,
                  keep_denominator: int = 2):
    """Cut a file to its leading fraction in place — a torn write. The
    default half is enough to invalidate any npz/json payload while
    keeping the file present (the harder failure mode: exists but
    unreadable)."""
    size = os.path.getsize(path)
    keep = (size * keep_numerator) // keep_denominator
    with open(path, "r+b") as f:
        f.truncate(keep)


def corrupt_file(site: str, path: str) -> bool:
    """I/O-site hook: when a truncate rule at ``site`` fires, tear the
    file at ``path``. Returns whether corruption happened."""
    plan = _ACTIVE
    if plan is None or not os.path.exists(path):
        return False
    if not plan.wants_corruption(site):
        return False
    truncate_file(path)
    return True
