"""Fault tolerance for long sweeps: injection, supervision, degradation.

Four pieces (ISSUE 7):

- ``faults``      — deterministic fault-injection harness (named sites,
  env/CLI-configurable FaultPlan) so every recovery path below is
  testable on CPU in tier-1;
- ``supervisor``  — per-config isolation, classified retries with
  seeded backoff, quarantine of poison configs, cooperative deadlines;
- ``errors``      — the typed failure vocabulary the classifier keys on;
- ``degrade``     — dispatch-ladder fallback bookkeeping
  (kernel_path_degraded events + the process-wide audit trail bench
  records consume).

Checkpoint integrity (SHA-256 manifests, generations, ``.corrupt/``
quarantine) lives in ``experiments.driver`` next to the checkpoint
format itself; this package supplies the errors and fault sites it
uses.
"""

from .errors import (CheckpointIdentityError, ConfigDeadlineExceeded,
                     KernelPathError)
from .faults import (ENV_VAR, FAULT_SITES, SITES, FaultPlan, FaultRule,
                     InjectedFault, active_plan, corrupt_file,
                     fault_point, install_from_env, install_from_spec,
                     install_plan, truncate_file)
from .degrade import (DEGRADATIONS, is_device_loss, is_kernel_error,
                      next_board_body, next_general_path,
                      record_degradation)
from .supervisor import (DETERMINISTIC, RESOURCE, TRANSIENT,
                         DeadlineScope, RetryPolicy, SweepReport,
                         check_deadline, classify_error,
                         clear_deadline, run_supervised_sweep,
                         set_deadline)

__all__ = [
    "CheckpointIdentityError", "ConfigDeadlineExceeded",
    "KernelPathError",
    "ENV_VAR", "FAULT_SITES", "SITES", "FaultPlan", "FaultRule",
    "InjectedFault",
    "active_plan", "corrupt_file", "fault_point", "install_from_env",
    "install_from_spec", "install_plan", "truncate_file",
    "DEGRADATIONS", "is_device_loss", "is_kernel_error",
    "next_board_body", "next_general_path", "record_degradation",
    "DETERMINISTIC", "RESOURCE", "TRANSIENT", "DeadlineScope",
    "RetryPolicy", "SweepReport", "check_deadline", "classify_error",
    "clear_deadline", "run_supervised_sweep", "set_deadline",
]
