"""Graceful kernel degradation: fall down the dispatch ladder, loudly.

The ladder (lower.dispatch) is packed lowered -> int8 lowered ->
bitboard -> int8 board -> general. When a body fails to compile or
trips an XLA runtime error mid-segment, the runners retry the same
segment on the next body down instead of surfacing the error — emitting
a ``kernel_path_degraded`` event and appending to the process-wide
``DEGRADATIONS`` audit trail, which bench.py folds into its record
(``degraded``/``degradations``) so ``tools/bench_compare.py`` can
refuse to gate a record whose winning body was reached by falling off
the intended path.

Within the board family, lowered_bits -> lowered and bitboard -> int8
board are retryable *in-segment* (each pair advances the same
BoardState; the bit-packing happens inside ``run_board_chunk``). Within
the general family, general_dense -> general is likewise in-segment
(both advance a ChainState; the dense rung's extra ``conn_bits`` plane
is stripped on the way down — ``next_general_path``). A lowered or
int8-board failure raises ``KernelPathError`` instead, and the driver
reruns the config on the general runner from its last compatible
checkpoint (board and general states are different pytrees, so there
is no mid-segment hop between them).
"""

from __future__ import annotations

from . import faults

# Process-wide audit trail: one dict per degradation event, in order.
# bench.py snapshots len() around a timed run to tag its record.
DEGRADATIONS: list = []

# Kernel errors we treat as "this body is broken here", by exception
# class name (jax's exception classes move between versions; matching
# the terminal name over the MRO is the stable check).
_KERNEL_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "JaxStackTraceBeforeTransformation",
    "InternalError", "UnfilteredStackTrace", "CompilationError",
})


def is_kernel_error(exc: BaseException) -> bool:
    """Does this exception mean the *body* failed (compile/XLA runtime),
    as opposed to a bug in the calling code? Injected ``compile``-site
    faults count — that is how chaos tests exercise this path on CPU."""
    if isinstance(exc, faults.InjectedFault):
        return exc.site == "compile"
    return any(k.__name__ in _KERNEL_ERROR_NAMES
               for k in type(exc).__mro__)


# message markers for device loss: jax surfaces a dead/preempted chip as
# runtime-error text (UNAVAILABLE / FAILED_PRECONDITION grpc statuses),
# not a dedicated class.
_DEVICE_LOSS_MARKERS = ("device", "unavailable", "failed precondition",
                        "data loss", "connection reset", "socket closed")


def is_device_loss(exc: BaseException) -> bool:
    """Does this exception mean devices dropped out from under a sharded
    run (so the mesh itself must shrink, not just the kernel body)?
    Every kernel error qualifies — a chip that can no longer execute the
    body is indistinguishable from a lost chip at this layer, and
    resharding onto the survivors is the recovery either way — plus the
    runtime-error texts jax uses for dead/preempted devices."""
    if is_kernel_error(exc):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


def next_board_body(path: str):
    """The next body down *within the board family*, or None when the
    fall must leave the family (KernelPathError -> general rerun).
    Only lowered_bits -> lowered and bitboard -> board share a state
    layout; see module doc."""
    from ..lower.dispatch import next_path  # import-light until needed

    nxt = next_path(path)
    return (nxt if (path, nxt) in (("lowered_bits", "lowered"),
                                   ("bitboard", "board")) else None)


def next_general_path(path: str):
    """The next body down *within the general family*, or None.
    general_dense -> general shares the ChainState layout (the runner
    strips ``conn_bits`` on the hop); plain general is the ladder floor."""
    from ..lower.dispatch import next_path  # import-light until needed

    nxt = next_path(path)
    return nxt if (path, nxt) == ("general_dense", "general") else None


def describe_error(exc: BaseException) -> str:
    return f"{type(exc).__name__}: {exc}"


def record_degradation(rec, from_path: str, to_path: str, reason: str,
                       **ctx):
    """Append to the audit trail and (when a recorder is live) emit the
    ``kernel_path_degraded`` event."""
    entry = {"from_path": from_path, "to_path": to_path,
             "reason": reason}
    entry.update(ctx)
    DEGRADATIONS.append(entry)
    if rec:
        rec.emit("kernel_path_degraded", from_path=from_path,
                 to_path=to_path, reason=reason, **ctx)


def snapshot() -> int:
    """Marker for "how many degradations so far" — diff two snapshots
    around a run to attribute degradations to it (bench.py)."""
    return len(DEGRADATIONS)


def since(marker: int) -> list:
    return list(DEGRADATIONS[marker:])
