"""Structured run telemetry: JSONL Recorder + shared profiler hook.

The package itself previously emitted nothing — every round-3 kernel win
came from ``jax.profiler`` traces hand-bolted onto bench.py, and a
multi-hour ``run_sweep`` gave no heartbeat (ISSUE 1 motivation). This
module is the zero-dependency core: a ``Recorder`` that appends
schema-versioned events (obs.events) to a file and/or a text stream, a
``NullRecorder`` default whose falsiness lets instrumented loops skip
metric computation entirely (the off path costs nothing), and the
``jax.profiler`` trace context promoted out of bench.py so runners,
examples, and bench share one hook.

jax is imported lazily (inside ``profile_region`` only): the schema and
recorder are importable — and ``tools/obs_report.py`` can validate a
stream — without touching the accelerator runtime.
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
import sys
import time

from .events import EVENT_FIELDS, SCHEMA_VERSION

# Lazy fault-injection hook (resilience.faults.install_plan sets this to
# the active plan's ``check``; None means no plan). obs must not import
# the resilience package at module level — the dependency points the
# other way — so the harness reaches in through this slot to make
# ``recorder.emit`` an injectable site.
_fault_check = None


class NullRecorder:
    """Default recorder: every emit is a no-op and ``bool(rec)`` is
    False, so call sites gate their metric readbacks on ``if rec:`` and
    the un-instrumented hot loops stay byte-identical to before."""

    enabled = False
    n_emitted = 0
    # hook attrs mirror Recorder's so hasattr-free hook plumbing
    # (monitor, MetricsRegistry.notify) treats both uniformly
    diag_hook = None
    anomaly_hook = None
    metrics_hook = None
    run_meta: dict = {}
    ident: dict = {}

    def __bool__(self):
        return False

    def emit(self, event, ts=None, **fields):
        return None

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL = NullRecorder()


def _jsonable(o):
    """json.dumps default for numpy scalars/arrays riding in fields."""
    to_item = getattr(o, "item", None)
    if callable(to_item) and getattr(o, "ndim", 0) == 0:
        return to_item()
    to_list = getattr(o, "tolist", None)
    if callable(to_list):
        return to_list()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


class Recorder:
    """Appends one JSON object per event to ``path`` and/or ``stream``.

    Each line is flushed as written, so ``tail -f`` and post-crash reads
    see every event emitted so far — the telemetry exists precisely for
    runs that may not end cleanly. ``emit`` rejects unknown event types
    and missing core fields at the call site (a typo'd emitter must fail
    its own tests, not poison downstream streams); the schema is
    obs.events.EVENT_REGISTRY — the same registry graftlint rule G004
    checks statically and ``obs_report.py --check`` applies to streams.
    """

    enabled = True

    # Optional live-observer callbacks, installed by the driver while a
    # heartbeat is active (see experiments/driver.py run_sweep):
    # diag_hook(diag_event), anomaly_hook(anomaly_event) — called by
    # ChainMonitor — and metrics_hook(snapshot) — called by the runners'
    # MetricsRegistry.notify. All best-effort; None means "nobody
    # listening".
    diag_hook = None
    anomaly_hook = None
    metrics_hook = None

    def __init__(self, path=None, stream=None, ident=None):
        if path is None and stream is None:
            raise ValueError("Recorder needs a path and/or a stream "
                             "(use obs.NULL for the no-op recorder)")
        # Process-level context merged into every run_start event (a
        # CLI sets e.g. run_meta["compile_cache_dir"] once; every
        # runner's run_start then carries it without the runners
        # knowing). Explicit emit kwargs win on collision.
        self.run_meta: dict = {}
        # Opt-in process identity stamped into EVERY event (fleet
        # processes pass e.g. {"pid": ..., "worker_name": ...} so a
        # multi-stream merge never needs filename heuristics). Additive:
        # the default empty dict keeps single-process streams
        # byte-compatible; explicit emit kwargs win on collision.
        self.ident: dict = dict(ident or {})
        self.path = path
        if path:
            # the sweep CLI defaults the stream into its --out directory,
            # which may not exist until the driver creates it
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            if path.endswith(".gz"):
                # transparent gzip sink: long sweeps' span streams are
                # highly repetitive JSON. "at" appends a fresh gzip
                # member, which every stdlib/CLI reader concatenates
                # transparently; flush() below uses Z_SYNC_FLUSH so a
                # tail of the file stays decodable after a crash.
                self._file = gzip.open(path, "at", encoding="utf-8")
            else:
                self._file = open(path, "a", encoding="utf-8")
        else:
            self._file = None
        self._stream = stream
        self.n_emitted = 0

    def __bool__(self):
        return True

    def emit(self, event, ts=None, **fields):
        if _fault_check is not None:
            _fault_check("recorder.emit", event=event)
        if event not in EVENT_FIELDS:
            raise ValueError(f"unknown event type {event!r} "
                             f"(schema v{SCHEMA_VERSION}: "
                             f"{sorted(EVENT_FIELDS)})")
        missing = EVENT_FIELDS[event] - fields.keys()
        if missing:
            raise ValueError(f"emit({event!r}): missing core field(s) "
                             f"{sorted(missing)} (see obs/events.py "
                             "EVENT_REGISTRY)")
        obj = {"v": SCHEMA_VERSION,
               "ts": time.time() if ts is None else float(ts),
               "event": event}
        if event == "run_start" and self.run_meta:
            obj.update(self.run_meta)
        if self.ident:
            obj.update(self.ident)
        obj.update(fields)
        line = json.dumps(obj, separators=(",", ":"), default=_jsonable)
        if self._file is not None:
            self._file.write(line + "\n")
            self._file.flush()
            if event == "error":
                # an error event is usually the last thing a dying sweep
                # writes — force it to stable storage so the post-mortem
                # stream ends with the diagnosis, not mid-buffer
                try:
                    os.fsync(self._file.fileno())
                except OSError:
                    pass
        if self._stream is not None:
            print(line, file=self._stream, flush=True)
        self.n_emitted += 1
        return obj

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def per_host_path(path, index=None):
    """Multi-host sink naming: when this process is one of several jax
    hosts, rewrite ``events.jsonl`` -> ``events.host<K>.jsonl`` (the
    ``.gz`` suffix is preserved) so every host appends spans to its own
    file — concurrent appends to one shared file would interleave mid-
    line. ``tools/trace_export.py`` merges the per-host files back into
    one timeline, mapping the host id from the filename onto the Chrome
    trace ``pid``. Single-host (and jax-less) processes get ``path``
    back unchanged; an explicit ``index`` forces the rewrite (tests,
    non-jax launchers that know their own rank)."""
    if index is not None:
        idx = int(index)
    else:
        try:
            import jax

            if jax.process_count() <= 1:
                return path
            idx = jax.process_index()
        except Exception:
            return path
    root, ext = os.path.splitext(path)
    if ext == ".gz":
        root, inner = os.path.splitext(root)
        ext = inner + ext
    return f"{root}.host{idx}{ext}"


def from_spec(spec, per_host=False, ident=None):
    """CLI convenience: ``None``/empty -> NULL, ``"-"`` -> stderr
    stream, anything else -> append-to-file Recorder (the ``--events``
    flag of bench.py and experiments/__main__.py). A ``.gz`` path gets a
    gzip sink; ``per_host=True`` routes multi-host processes through
    ``per_host_path`` (sharded runs — see distribute.sharded);
    ``ident`` stamps process identity into every event (the fleet
    CLIs — see Recorder)."""
    if not spec:
        return NULL
    if spec == "-":
        return Recorder(stream=sys.stderr, ident=ident)
    return Recorder(path=per_host_path(spec) if per_host else spec,
                    ident=ident)


_default = NULL


def default_recorder():
    return _default


def set_default_recorder(rec):
    """Install a process-wide default (returned by ``resolve_recorder``
    for call sites that don't pass one explicitly). Returns the previous
    default so tests and tools can restore it."""
    global _default
    prev = _default
    _default = NULL if rec is None else rec
    return prev


def resolve_recorder(rec):
    """The runners' argument coercion: ``None`` means "whatever the
    process default is" (NULL unless someone configured one), an
    explicit recorder — including NULL — wins."""
    return _default if rec is None else rec


def profile_region(trace_dir):
    """The ``jax.profiler`` trace context shared by bench.py, the
    examples, and ad-hoc runner scripts (promoted out of bench.py,
    SURVEY.md §5 tracing): a nullcontext when ``trace_dir`` is falsy, so
    callers wrap their timed region unconditionally."""
    if not trace_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(trace_dir)


def jit_cache_size(fn):
    """Compiled-specialization count of a ``jax.jit`` callable; None
    when unavailable (``_cache_size`` is private API, stable on the
    pinned jax — degrade to "no compile events" rather than crash a
    run if it moves)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def device_memory_snapshot():
    """Per-device ``memory_stats()`` where the platform exposes them
    (TPU/GPU report bytes_in_use etc.; CPU returns None). Guarded: any
    runtime that lacks the API degrades to None, never an exception."""
    try:
        import jax

        out = {}
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                out[f"{d.platform}:{d.id}"] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float)) and not isinstance(v, bool)
                }
        return out or None
    except Exception:
        return None


def aot_cost(fn, *args, **kwargs):
    """Compile-time cost introspection for a jitted callable on concrete
    args: ``{"flops", "bytes_accessed", "memory": {...}}`` from
    ``Compiled.cost_analysis()`` / ``memory_analysis()``, or None when
    the backend doesn't expose them. ``fn.lower(...).compile()`` is a
    *fresh* compile (the jit execution cache is separate), so call this
    only when a specialization is new — JitWatch.poll's ``cost=``
    callable is invoked exactly on cache growth for this reason."""
    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception:
        return None
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed")):
                v = ca.get(src)
                if v is not None:
                    out[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        mem = {}
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                mem[k] = int(v)
        if mem:
            out["memory"] = mem
    except Exception:
        pass
    return out or None


class JitWatch:
    """Cache-miss watcher for one jitted callable: ``poll(rec)`` after a
    call emits a ``compile`` event when the trace cache grew, giving
    compile-vs-execute attribution (each distinct ``_run_chunk`` length
    — the ``pick_chunk`` remainder-chunk recompile story — shows up as
    an event instead of an anomalous chunk wall time)."""

    def __init__(self, fn, name):
        self.fn = fn
        self.name = name
        self.last = jit_cache_size(fn)

    def poll(self, rec, cost=None, **fields):
        """``cost`` is an optional zero-arg callable (typically a
        closure over ``aot_cost`` with the call's concrete args) invoked
        only when the cache grew; its dict — plus a device-memory
        snapshot where supported — is merged into the compile event."""
        n = jit_cache_size(self.fn)
        grew = n is not None and (self.last is None or n > self.last)
        self.last = n
        if grew:
            # span over the (host-side) cost introspection: the compile
            # itself already happened inside the preceding chunk call,
            # but the AOT lower+compile in cost() is real wall time and
            # the span puts the cache miss on the Perfetto timeline with
            # flops/bytes attached as args. Lazy import: trace imports
            # recorder, not vice versa at module level.
            from .trace import span as _span

            extra = {}
            with _span(rec, f"compile:{self.name}", cache_size=n,
                       **fields) as sp:
                if cost is not None:
                    try:
                        c = cost()
                    except Exception:
                        c = None
                    if c:
                        extra.update(c)
                mem = device_memory_snapshot()
                if mem:
                    extra["device_memory"] = mem
                sp.end(**{k: v for k, v in extra.items()
                          if k in ("flops", "bytes_accessed")})
            rec.emit("compile", fn=self.name, cache_size=n,
                     **fields, **extra)
        return grew


def dict_nbytes(d) -> int:
    """Total payload bytes of a dict of array-likes (one chunk's history
    block) — the per-chunk host-transfer / HBM-residency metric."""
    if not d:
        return 0
    return int(sum(getattr(v, "nbytes", 0) for v in d.values()))
