"""Span-based tracing over the Recorder event stream.

A *span* is a named wall-clock interval with identity and lineage:

    ``trace_id``   one hex id per recorder (one timeline per stream)
    ``span_id``    monotonically increasing int, unique within the trace
    ``parent_id``  span_id of the innermost span open on this thread
                   when the span began (None at top level)

Spans are emitted as ordinary schema-versioned events (``span_begin`` /
``span_end``) through the same Recorder the runners already use, so one
``--events`` stream carries both the chunk telemetry and the timeline;
``tools/trace_export.py`` converts it to Chrome trace-event JSON for
Perfetto / chrome://tracing, and ``tools/obs_report.py --check``
validates the nesting (every begin closed, no orphan parents).

Durations come from ``time.perf_counter()`` (monotonic), never from the
wall-clock ``ts`` stamps, so spans survive NTP steps. The subsystem is
thread-safe: span ids are allocated from one atomic counter, the open-
span stack is per-thread (``threading.local``), and each ``span_begin``
carries a compact ``tid`` so the exporter can lay threads on separate
tracks.

Cross-process (the fleet): :func:`adopt` scopes a foreign trace context
onto the current thread so a worker's spans join the submitting
request's trace — same ``trace_id``, per-stream lineage, the foreign
parent attached as an additive ``ctx_parent_id`` field (details on
:func:`adopt`).

Hot-path contract (mirrors the rest of obs — see PROFILE.md):

* ``span(rec, ...)`` with a falsy recorder returns a shared no-op span —
  zero allocation beyond the call, zero events, NullRecorder runs stay
  byte-identical.
* Span emission must add NO device syncs. Begin/end sites in the
  runners live inside the existing ``if rec:`` blocks at existing sync
  points and only attach values already copied there; the board path,
  which never syncs mid-run, defers its chunk spans and back-stamps
  them at flush time via :func:`emit_span_at`.
* ``annotate=True`` additionally brackets the span in a
  ``jax.profiler.TraceAnnotation`` so device profiles collected with
  ``jax.profiler.trace`` line up with the host timeline. The import is
  lazy and failure-tolerant; everything else here is stdlib-only.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import threading
import time
import uuid

from .recorder import resolve_recorder

__all__ = ["span", "traced", "emit_span_at", "adopt", "Span"]

_ANNOTATION_CLS = None
_ANNOTATION_FAILED = False


def _annotation(name):
    """``jax.profiler.TraceAnnotation(name)`` or None; lazy + cached so
    the bridge costs one sys.modules hit per span and nothing when jax
    is absent (obs stays importable without it)."""
    global _ANNOTATION_CLS, _ANNOTATION_FAILED
    if _ANNOTATION_FAILED:
        return None
    if _ANNOTATION_CLS is None:
        try:
            from jax.profiler import TraceAnnotation
            _ANNOTATION_CLS = TraceAnnotation
        except Exception:
            _ANNOTATION_FAILED = True
            return None
    try:
        return _ANNOTATION_CLS(name)
    except Exception:
        return None


class _TraceState:
    """Per-recorder trace identity, attached lazily to the recorder
    instance (the Recorder itself stays tracing-agnostic)."""

    __slots__ = ("trace_id", "ids", "local", "_tid_lock", "_tids")

    def __init__(self):
        self.trace_id = uuid.uuid4().hex[:16]
        self.ids = itertools.count(1)   # next() is atomic in CPython
        self.local = threading.local()
        self._tid_lock = threading.Lock()
        self._tids: dict = {}

    def stack(self):
        st = getattr(self.local, "stack", None)
        if st is None:
            st = self.local.stack = []
        return st

    def adopted(self):
        """Innermost adopted trace context on this thread, or None."""
        lst = getattr(self.local, "adopted", None)
        return lst[-1] if lst else None

    def tid(self):
        ident = threading.get_ident()
        with self._tid_lock:
            t = self._tids.get(ident)
            if t is None:
                t = self._tids[ident] = len(self._tids)
            return t


def _state(rec) -> _TraceState:
    st = getattr(rec, "_trace_state", None)
    if st is None:
        st = rec._trace_state = _TraceState()
    return st


class _Adopted:
    """Live adoption scope; see :func:`adopt`."""

    __slots__ = ("_st", "_ctx")

    def __init__(self, st, ctx):
        self._st = st
        self._ctx = ctx

    def __enter__(self):
        lst = getattr(self._st.local, "adopted", None)
        if lst is None:
            lst = self._st.local.adopted = []
        lst.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        lst = getattr(self._st.local, "adopted", None)
        if lst and self._ctx in lst:
            lst.remove(self._ctx)
        return False


def adopt(rec, ctx):
    """Adopt a foreign trace context on this thread for the duration of
    the ``with`` block — the fleet's cross-process trace propagation.

    ``ctx`` is ``{"trace_id": ..., "span_id": ...}`` as minted by the
    front door at submit time and carried through the WAL record, spool
    job doc, and lease file. While the scope is active, spans begun on
    this thread (and :func:`emit_span_at` back-stamps) carry
    ``ctx["trace_id"]`` instead of the stream's own trace id, so every
    worker-side span of one submission shares the submit span's trace.

    Lineage stays per-stream: ``parent_id`` always references a span in
    the SAME stream (``validate_spans``'s contract), so a top-level
    adopted span keeps ``parent_id=None`` and instead attaches the
    foreign parent as an ADDITIVE ``ctx_parent_id`` field — the submit
    span's id in the server stream. ``trace_export --fleet`` joins the
    two streams on (trace_id, ctx_parent_id) and renders the link as a
    Perfetto flow; single-stream tooling ignores the extra field.

    Falsy recorder or a ctx without ``trace_id`` yields a no-op scope,
    so call sites need no guards. Nesting is allowed; the innermost
    adoption wins. The scope is thread-local: spawn-per-job worker
    threads adopt independently.
    """
    if not rec or not ctx or not ctx.get("trace_id"):
        return contextlib.nullcontext(dict(ctx or {}))
    return _Adopted(_state(rec), dict(ctx))


class _NullSpan:
    """Shared do-nothing span for falsy recorders."""

    __slots__ = ()

    def __bool__(self):
        return False

    def begin(self):
        return self

    def end(self, **end_args):
        return None

    def set_args(self, **args):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


# A Span is a per-operation object owned by the thread that created it:
# begin/end run on that one thread, and only the emitted events cross
# threads (via the recorder's own discipline), so no field needs a lock.
# graftlint: guarded-by(none: per-operation object, single-thread by construction)
class Span:
    """One live span. Use as a context manager::

        with obs.span(rec, "render", tag=cfg.tag):
            ...

    or explicitly when begin/end straddle block boundaries (the runner
    chunk loops)::

        sp = obs.span(rec, "chunk", kernel_path=path, steps=n).begin()
        ...dispatch, sync...
        sp.end(reject=reject)

    ``end_args`` merge into the ``span_end`` event alongside ``dur_s``.
    Single-use: begin once, end once; a second ``end`` is a no-op.
    """

    __slots__ = ("rec", "name", "args", "annotate", "span_id", "trace_id",
                 "parent_id", "_t0", "_begun", "_ended", "_ann", "_st")

    def __init__(self, rec, name, annotate=False, args=None):
        self.rec = rec
        self.name = name
        self.args = args or {}
        self.annotate = annotate
        self.span_id = None
        self.trace_id = None
        self.parent_id = None
        self._t0 = None
        self._begun = False
        self._ended = False
        self._ann = None
        self._st = None

    def set_args(self, **args):
        """Attach more args before ``begin`` (after it they'd be lost —
        pass late values to ``end`` instead)."""
        self.args.update(args)
        return self

    def begin(self):
        if self._begun:
            return self
        self._begun = True
        st = self._st = _state(self.rec)
        stack = st.stack()
        self.parent_id = stack[-1].span_id if stack else None
        self.span_id = next(st.ids)
        ctx = st.adopted()
        extra = {}
        if ctx is not None:
            self.trace_id = ctx["trace_id"]
            if self.parent_id is None and ctx.get("span_id") is not None:
                extra["ctx_parent_id"] = ctx["span_id"]
        else:
            self.trace_id = st.trace_id
        self.rec.emit("span_begin", name=self.name, span_id=self.span_id,
                      trace_id=self.trace_id, parent_id=self.parent_id,
                      tid=st.tid(), **extra, **self.args)
        stack.append(self)
        if self.annotate:
            ann = _annotation(self.name)
            if ann is not None:
                try:
                    ann.__enter__()
                    self._ann = ann
                except Exception:
                    self._ann = None
        self._t0 = time.perf_counter()
        return self

    def end(self, **end_args):
        if not self._begun or self._ended:
            return None
        self._ended = True
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            except Exception:
                pass
            self._ann = None
        stack = self._st.stack()
        if self in stack:        # tolerate out-of-order ends
            stack.remove(self)
        return self.rec.emit("span_end", name=self.name,
                             span_id=self.span_id, trace_id=self.trace_id,
                             dur_s=dur, **end_args)

    def __enter__(self):
        return self.begin()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.end(error=exc_type.__name__)
        else:
            self.end()
        return False


def span(rec, name, annotate=False, **args):
    """A span on ``rec``'s stream, or the shared no-op span when the
    recorder is falsy (NullRecorder / None). ``args`` land on the
    ``span_begin`` event."""
    if not rec:
        return NULL_SPAN
    return Span(rec, name, annotate=annotate, args=args)


def traced(name=None, **span_args):
    """Decorator form: wrap every call of ``fn`` in a span against the
    process-default recorder (resolved at call time, so recording can be
    switched on after import). With the default NULL recorder the
    wrapper is a plain passthrough call.

        @obs.traced("partisan")
        def _partisan_summary(...): ...

    Bare ``@obs.traced`` uses the function's qualname as the span name.
    """
    def deco(fn, _label=None):
        label = _label or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            rec = resolve_recorder(None)
            if not rec:
                return fn(*a, **kw)
            with span(rec, label, **span_args):
                return fn(*a, **kw)
        return wrapper

    if callable(name):          # bare @traced
        return deco(name)
    return lambda fn: deco(fn, name)


def emit_span_at(rec, name, ts_begin, dur_s, parent_id=None,
                 end_args=None, **args):
    """Back-stamped span for work whose timing was measured earlier at a
    point where emitting was not allowed — the board runner's chunk
    loop, which never syncs mid-run and flushes deferred chunk telemetry
    just before ``run_end``. Emits a matched begin/end pair with
    explicit ``ts`` stamps (``ts_begin`` .. ``ts_begin + dur_s``);
    ``parent_id`` defaults to the innermost span currently open on this
    thread (the run span, still open at flush time); ``end_args`` merge
    into the ``span_end`` event like ``Span.end(**end_args)`` would.
    Returns the span_id, or None on a falsy recorder."""
    if not rec:
        return None
    st = _state(rec)
    ctx = st.adopted()
    trace_id = ctx["trace_id"] if ctx is not None else st.trace_id
    extra = {}
    if parent_id is None:
        stack = st.stack()
        parent_id = stack[-1].span_id if stack else None
        if (parent_id is None and ctx is not None
                and ctx.get("span_id") is not None):
            extra["ctx_parent_id"] = ctx["span_id"]
    sid = next(st.ids)
    rec.emit("span_begin", ts=ts_begin, name=name, span_id=sid,
             trace_id=trace_id, parent_id=parent_id, tid=st.tid(),
             **extra, **args)
    rec.emit("span_end", ts=ts_begin + float(dur_s), name=name,
             span_id=sid, trace_id=trace_id, dur_s=float(dur_s),
             **(end_args or {}))
    return sid
