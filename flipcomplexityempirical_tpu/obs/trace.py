"""Span-based tracing over the Recorder event stream.

A *span* is a named wall-clock interval with identity and lineage:

    ``trace_id``   one hex id per recorder (one timeline per stream)
    ``span_id``    monotonically increasing int, unique within the trace
    ``parent_id``  span_id of the innermost span open on this thread
                   when the span began (None at top level)

Spans are emitted as ordinary schema-versioned events (``span_begin`` /
``span_end``) through the same Recorder the runners already use, so one
``--events`` stream carries both the chunk telemetry and the timeline;
``tools/trace_export.py`` converts it to Chrome trace-event JSON for
Perfetto / chrome://tracing, and ``tools/obs_report.py --check``
validates the nesting (every begin closed, no orphan parents).

Durations come from ``time.perf_counter()`` (monotonic), never from the
wall-clock ``ts`` stamps, so spans survive NTP steps. The subsystem is
thread-safe: span ids are allocated from one atomic counter, the open-
span stack is per-thread (``threading.local``), and each ``span_begin``
carries a compact ``tid`` so the exporter can lay threads on separate
tracks.

Hot-path contract (mirrors the rest of obs — see PROFILE.md):

* ``span(rec, ...)`` with a falsy recorder returns a shared no-op span —
  zero allocation beyond the call, zero events, NullRecorder runs stay
  byte-identical.
* Span emission must add NO device syncs. Begin/end sites in the
  runners live inside the existing ``if rec:`` blocks at existing sync
  points and only attach values already copied there; the board path,
  which never syncs mid-run, defers its chunk spans and back-stamps
  them at flush time via :func:`emit_span_at`.
* ``annotate=True`` additionally brackets the span in a
  ``jax.profiler.TraceAnnotation`` so device profiles collected with
  ``jax.profiler.trace`` line up with the host timeline. The import is
  lazy and failure-tolerant; everything else here is stdlib-only.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
import uuid

from .recorder import resolve_recorder

__all__ = ["span", "traced", "emit_span_at", "Span"]

_ANNOTATION_CLS = None
_ANNOTATION_FAILED = False


def _annotation(name):
    """``jax.profiler.TraceAnnotation(name)`` or None; lazy + cached so
    the bridge costs one sys.modules hit per span and nothing when jax
    is absent (obs stays importable without it)."""
    global _ANNOTATION_CLS, _ANNOTATION_FAILED
    if _ANNOTATION_FAILED:
        return None
    if _ANNOTATION_CLS is None:
        try:
            from jax.profiler import TraceAnnotation
            _ANNOTATION_CLS = TraceAnnotation
        except Exception:
            _ANNOTATION_FAILED = True
            return None
    try:
        return _ANNOTATION_CLS(name)
    except Exception:
        return None


class _TraceState:
    """Per-recorder trace identity, attached lazily to the recorder
    instance (the Recorder itself stays tracing-agnostic)."""

    __slots__ = ("trace_id", "ids", "local", "_tid_lock", "_tids")

    def __init__(self):
        self.trace_id = uuid.uuid4().hex[:16]
        self.ids = itertools.count(1)   # next() is atomic in CPython
        self.local = threading.local()
        self._tid_lock = threading.Lock()
        self._tids: dict = {}

    def stack(self):
        st = getattr(self.local, "stack", None)
        if st is None:
            st = self.local.stack = []
        return st

    def tid(self):
        ident = threading.get_ident()
        with self._tid_lock:
            t = self._tids.get(ident)
            if t is None:
                t = self._tids[ident] = len(self._tids)
            return t


def _state(rec) -> _TraceState:
    st = getattr(rec, "_trace_state", None)
    if st is None:
        st = rec._trace_state = _TraceState()
    return st


class _NullSpan:
    """Shared do-nothing span for falsy recorders."""

    __slots__ = ()

    def __bool__(self):
        return False

    def begin(self):
        return self

    def end(self, **end_args):
        return None

    def set_args(self, **args):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One live span. Use as a context manager::

        with obs.span(rec, "render", tag=cfg.tag):
            ...

    or explicitly when begin/end straddle block boundaries (the runner
    chunk loops)::

        sp = obs.span(rec, "chunk", kernel_path=path, steps=n).begin()
        ...dispatch, sync...
        sp.end(reject=reject)

    ``end_args`` merge into the ``span_end`` event alongside ``dur_s``.
    Single-use: begin once, end once; a second ``end`` is a no-op.
    """

    __slots__ = ("rec", "name", "args", "annotate", "span_id", "trace_id",
                 "parent_id", "_t0", "_begun", "_ended", "_ann", "_st")

    def __init__(self, rec, name, annotate=False, args=None):
        self.rec = rec
        self.name = name
        self.args = args or {}
        self.annotate = annotate
        self.span_id = None
        self.trace_id = None
        self.parent_id = None
        self._t0 = None
        self._begun = False
        self._ended = False
        self._ann = None
        self._st = None

    def set_args(self, **args):
        """Attach more args before ``begin`` (after it they'd be lost —
        pass late values to ``end`` instead)."""
        self.args.update(args)
        return self

    def begin(self):
        if self._begun:
            return self
        self._begun = True
        st = self._st = _state(self.rec)
        stack = st.stack()
        self.parent_id = stack[-1].span_id if stack else None
        self.span_id = next(st.ids)
        self.trace_id = st.trace_id
        self.rec.emit("span_begin", name=self.name, span_id=self.span_id,
                      trace_id=self.trace_id, parent_id=self.parent_id,
                      tid=st.tid(), **self.args)
        stack.append(self)
        if self.annotate:
            ann = _annotation(self.name)
            if ann is not None:
                try:
                    ann.__enter__()
                    self._ann = ann
                except Exception:
                    self._ann = None
        self._t0 = time.perf_counter()
        return self

    def end(self, **end_args):
        if not self._begun or self._ended:
            return None
        self._ended = True
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            except Exception:
                pass
            self._ann = None
        stack = self._st.stack()
        if self in stack:        # tolerate out-of-order ends
            stack.remove(self)
        return self.rec.emit("span_end", name=self.name,
                             span_id=self.span_id, trace_id=self.trace_id,
                             dur_s=dur, **end_args)

    def __enter__(self):
        return self.begin()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.end(error=exc_type.__name__)
        else:
            self.end()
        return False


def span(rec, name, annotate=False, **args):
    """A span on ``rec``'s stream, or the shared no-op span when the
    recorder is falsy (NullRecorder / None). ``args`` land on the
    ``span_begin`` event."""
    if not rec:
        return NULL_SPAN
    return Span(rec, name, annotate=annotate, args=args)


def traced(name=None, **span_args):
    """Decorator form: wrap every call of ``fn`` in a span against the
    process-default recorder (resolved at call time, so recording can be
    switched on after import). With the default NULL recorder the
    wrapper is a plain passthrough call.

        @obs.traced("partisan")
        def _partisan_summary(...): ...

    Bare ``@obs.traced`` uses the function's qualname as the span name.
    """
    def deco(fn, _label=None):
        label = _label or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            rec = resolve_recorder(None)
            if not rec:
                return fn(*a, **kw)
            with span(rec, label, **span_args):
                return fn(*a, **kw)
        return wrapper

    if callable(name):          # bare @traced
        return deco(name)
    return lambda fn: deco(fn, name)


def emit_span_at(rec, name, ts_begin, dur_s, parent_id=None,
                 end_args=None, **args):
    """Back-stamped span for work whose timing was measured earlier at a
    point where emitting was not allowed — the board runner's chunk
    loop, which never syncs mid-run and flushes deferred chunk telemetry
    just before ``run_end``. Emits a matched begin/end pair with
    explicit ``ts`` stamps (``ts_begin`` .. ``ts_begin + dur_s``);
    ``parent_id`` defaults to the innermost span currently open on this
    thread (the run span, still open at flush time); ``end_args`` merge
    into the ``span_end`` event like ``Span.end(**end_args)`` would.
    Returns the span_id, or None on a falsy recorder."""
    if not rec:
        return None
    st = _state(rec)
    if parent_id is None:
        stack = st.stack()
        parent_id = stack[-1].span_id if stack else None
    sid = next(st.ids)
    rec.emit("span_begin", ts=ts_begin, name=name, span_id=sid,
             trace_id=st.trace_id, parent_id=parent_id, tid=st.tid(),
             **args)
    rec.emit("span_end", ts=ts_begin + float(dur_s), name=name,
             span_id=sid, trace_id=st.trace_id, dur_s=float(dur_s),
             **(end_args or {}))
    return sid
