"""Event schema for the chain telemetry stream (JSONL, one object/line).

Every event carries the envelope ``{"v": SCHEMA_VERSION, "ts": <unix
seconds>, "event": <type>}`` plus the type's core fields (EVENT_FIELDS).
Emitters may attach extra fields freely — validation is
forward-compatible and checks only the envelope and each type's core, so
``tools/obs_report.py`` can fold any conforming stream without knowing
which runner wrote it. A version bump means a core field changed
meaning; adding optional fields does not bump.

Core field semantics:

- ``run_start``: one per runner entry (``run_chains``,
  ``run_board_segment``, ``run_tempered``); ``chains`` is the batch
  size, ``n_steps`` the requested yields/transitions, ``chunk`` the
  resolved scan length.
- ``chunk``: one per executed device chunk. ``wall_s`` is host
  wall-clock between the chunk boundaries the runner already has (the
  general path syncs per chunk on its waits drain; the board path never
  syncs mid-run, so its per-chunk walls are dispatch intervals and the
  ``run_end`` wall is the authoritative end-to-end time).
  ``flips`` = chains * steps; ``accept_rate`` is this chunk's accepted
  fraction; ``transfer_bytes`` the history bytes copied device->host for
  this chunk; ``hbm_history_bytes`` the cumulative device-resident
  history footprint (``history_device=True`` runs); ``done``/``total``
  give progress. Runners additionally attach the optional
  ``readback_bytes`` field: the honest total device->host traffic the
  chunk caused (history transfer + counter/waits sync + the analytics
  summary pytree when device-resident analytics are enabled) — the
  number ``tools/obs_report.py``'s Readback section and the
  devstats gate fold. Optional fields ride the forward-compatible
  extras channel, so no SCHEMA_VERSION bump.
- ``compile``: the runner's jitted chunk kernel traced a new
  specialization (cache miss) during the preceding call — the
  ``pick_chunk`` recompile story as data.
- ``transfer``: a one-off device->host copy outside the per-chunk
  stream (initial/final record blocks).
- ``run_end``: totals for the run; ``flips_per_s`` is the aggregate
  throughput over ``wall_s``. Optional extras: ``readback_bytes`` (the
  run's total device->host traffic, the sum of the per-chunk values
  plus any one-off drains) and ``readback_mode`` (``"summary"`` when a
  ``stats.accumulators.DeviceAnalytics`` carried the telemetry on
  device, ``"history"`` for the flagged oracle path that reads back
  full per-step histories).
- ``sweep_config``: driver progress, ``status`` in SWEEP_STATUSES with
  per-config artifact counts.
- ``error``: a failure the emitter survived or is about to re-raise.
- ``diag``: one per observed chunk from ``obs.monitor.ChainMonitor`` —
  streaming convergence health. ``observable`` names the tracked series
  (e.g. ``cut_count``), ``samples`` the per-chain sample count folded so
  far, ``rhat``/``ess``/``ess_per_s`` the split Gelman-Rubin statistic,
  total effective sample size, and ESS per wall-second over the
  monitor's bounded thinning buffer (null until enough samples, or when
  non-finite — e.g. R-hat diverges on chains frozen apart), and
  ``accept_ewma``/``throughput_ewma`` the run's own exponentially
  weighted trends (null until first observed).
- ``anomaly``: the monitor crossed a health threshold. ``kind`` is one
  of ``frozen_chain`` / ``acceptance_collapse`` /
  ``pop_bound_saturation`` / ``throughput_regression``; ``detail`` is a
  kind-specific object. Each kind re-arms after recovery, so a stream
  records episodes, not one line per chunk.
- ``span_begin`` / ``span_end``: the tracing subsystem (``obs.trace``).
  ``span_id`` is unique within ``trace_id`` (one trace per recorder);
  ``parent_id`` is the enclosing span's id or null at top level, and the
  begin of a parent always precedes the begins of its children in the
  stream. ``dur_s`` on the end is measured on the monotonic clock, NOT
  derived from the ``ts`` stamps (the board path back-stamps deferred
  chunk spans; see ``obs.trace.emit_span_at``). ``validate_spans``
  below checks the pairing/nesting contract; ``tools/trace_export.py``
  turns conforming streams into Chrome trace-event JSON.
- ``metrics_snapshot``: an ``obs.metrics.MetricsRegistry`` snapshot —
  ``counters``/``gauges`` are flat name->value objects, ``histograms``
  maps name -> {count, sum, min, max, mean, p50, p95, p99}. Runners
  emit exactly one per run (right before ``run_end``, which embeds the
  same object under ``metrics=``).
- ``retry``: the supervisor (resilience.supervisor) is retrying a
  failed config: ``attempt`` is the try that just failed (1-based),
  ``error_class`` the transient/resource/deterministic classification,
  ``backoff_s`` the jittered wait about to be slept (wrapped in a
  ``backoff`` span).
- ``config_failed``: a config exhausted its retry budget; the sweep
  continues without it.
- ``config_quarantined``: a config hit ``quarantine_after``
  deterministic failures and is isolated (poison config); the driver
  exits nonzero when any config carries this event.
- ``checkpoint_corrupt``: a checkpoint generation failed its SHA-256
  manifest (truncated/bit-rotted part); the generation was moved to
  the ``.corrupt/`` subdir and resume fell back to the previous one.
- ``kernel_path_degraded``: a dispatch-ladder body failed
  (compile/XLA runtime) and the runner fell to ``to_path`` for the
  same segment; bench records reached through a degradation are
  refused by ``tools/bench_compare.py`` gating.
- ``sweep_summary``: one per supervised sweep, after the ``sweep``
  span closes — completed/retried/quarantined/failed counts (plus the
  quarantined/failed tag lists as extra fields).
- ``heartbeat_error``: a heartbeat write failed (full disk, missing
  dir); the run continued — heartbeats are liveness telemetry, never
  load-bearing.
- ``job_submitted``: the sweep service accepted an ``ExperimentConfig``
  submission (service.queue). ``job_id`` is the service-local handle,
  ``tag`` the config tag; extras carry the config fingerprint the
  scheduler coalesces on.
- ``job_batched``: the scheduler coalesced a group of compatible jobs
  (same ``ExperimentConfig.fingerprint()``) into one device batch along
  the chain axis. ``jobs`` lists the member job ids, ``chains`` the
  total batched chain count. Singleton batches emit it too (``jobs``
  of length 1), so the stream records every device dispatch decision.
- ``job_done``: terminal state of one job: ``status`` is ``done`` /
  ``failed`` / ``quarantined`` (the latter two mirror the supervisor's
  ``config_failed`` / ``config_quarantined`` taxonomy, which the
  service also emits per job).
- ``compile_cache_hit`` / ``compile_cache_miss``: the service's
  compile-cache probe before a batch dispatch. ``key`` is the stable
  cache key (``lower.dispatch.lowering_signature`` + batch shape),
  ``kernel_path`` the dispatch-ladder rung it resolves to. A miss means
  this (kernel, batch shape) pays XLA compilation in this process (and
  seeds the persistent on-disk cache when ``--compile-cache`` is set);
  a hit means the jit/persistent cache serves it.
- ``service_draining``: the service saw a drain request (SIGTERM/SIGINT
  or an injected ``sigterm`` fault) and stopped at a segment boundary
  after checkpointing the in-flight batch's tenants. ``reason`` names
  the trigger. The process exits with the distinct drain code (3) so
  an orchestrator restarts it with ``SweepService.recover``.
- ``service_recovered``: ``SweepService.recover`` rebuilt a queue from
  a journal: ``n_jobs`` total jobs reconstructed, ``n_requeued`` the
  DONE-less jobs put back in the runnable queue.
- ``journal_truncated``: the journal's tail failed integrity (torn
  JSON line, SHA-256 mismatch, or a sequence-number gap). ``dropped``
  records were discarded; recovery proceeded from the last intact
  record.
- ``dispatch_stalled``: the hung-dispatch watchdog saw a device call
  exceed its timeout (``--dispatch-timeout``, or scaled from the p95
  segment latency in the metrics registry). The batch is journaled as
  poison-suspect, so on restart its jobs retry SOLO. ``--strict``
  report mode fails on this event.
- ``mesh_degraded``: a sharded run lost devices mid-run and resumed on
  the surviving power-of-two sub-mesh (``from_devices`` ->
  ``to_devices``). Bench records from such a run carry
  ``degraded: true`` and are refused by ``tools/bench_compare.py``
  gating.
- ``control_action``: the adaptive control loop (control/loop.py) took
  a typed action at a segment boundary: ``kind`` in ``stop`` (config
  reached its split-R-hat/ESS targets and was finished early) /
  ``retune`` (advisory segment-length proposal from the p95 latency
  histograms) / ``reshape_ladder`` (tempered beta ladder adjusted
  toward the swap-rate band) / ``reallocate`` (an early-stopped
  tenant's device time handed to the batch's stragglers). ``tag`` is
  the acted-on config (or the batch for reallocations), ``step`` the
  segment boundary, ``policy`` the deciding policy's name; a free
  ``detail`` object carries the decision evidence. Actions are pure
  functions of observed history, so a drained/recovered sweep replays
  the identical sequence — ``obs_report --heartbeat`` treats a
  ``kind=stop`` like ``job_done`` when probing namespaced heartbeats.
- ``http_request``: the front door (service.server) served one HTTP
  request: ``method``/``path`` name the route, ``status`` the response
  code; extras carry ``tenant``, ``job_id`` (submissions), and
  ``dur_s`` (monotonic handler time). obs_report's Fleet section
  derives request-mix and error-rate views from these.
- ``quota_rejected``: a tenant's submission was refused by its
  token-bucket quota (429). ``tenant`` names the bucket; extras carry
  the route and the bucket's refill rate — admission-control pressure
  as data.
- ``lease_acquired``: a worker claimed a job's atomic lease file
  (service.worker). ``job_id``/``worker`` identify the claim; extras
  mark ``reclaim=True`` when the claim broke an expired lease.
- ``lease_expired``: a worker found a lease past its heartbeat TTL (or
  torn) and broke it before reclaiming the job. ``worker`` is the
  *previous* holder (the crashed process); extras carry the reclaiming
  worker and the lease age. ``--strict`` report mode fails when one
  job accumulates more than two of these (a lease-expiry storm: the
  TTL is racing the job's own runtime).
- ``worker_started`` / ``worker_exited``: fleet membership. ``worker``
  is the stable worker id; ``reason`` on exit is ``idle`` / ``drain``
  / ``done`` / an error class. A SIGKILLed worker has a start with no
  exit — obs_report's Fleet section surfaces the asymmetry.
- ``profile_captured``: the owning worker honored an on-demand
  profiling marker (``POST /v1/profile/<job>``) at a segment boundary
  and closed the capture. ``segments`` counts the boundaries actually
  bracketed by ``jax.profiler.trace``; ``ok=False`` (extras carry the
  error string) means capture degraded to a graceful no-op — e.g. no
  profiler backend on CPU — while the run itself proceeded untouched.

Adding a new event *type* (as ``diag``/``anomaly`` were added) does NOT
bump SCHEMA_VERSION: readers fold by type and validation rejects only
events claiming a type they don't define, so old streams stay valid and
old readers simply ignore lines they don't know. Only a change to the
*meaning* of an existing core field bumps the version.
"""

from __future__ import annotations

SCHEMA_VERSION = 1

# THE single source of truth for the event schema. Both validators
# consume it: ``Recorder.emit`` checks each emitted event's name and
# core-field coverage at runtime, and ``tools.graftlint`` rule G004
# parses this literal out of the AST to check every ``.emit(...)`` call
# site statically — so keep it a PURE LITERAL (string keys, tuple
# ``fields``), no computed values.
EVENT_REGISTRY = {
    "run_start": {
        "fields": ("runner", "chains", "n_steps", "chunk"),
        "doc": "one per runner entry",
    },
    "chunk": {
        "fields": ("runner", "steps", "chains", "flips", "wall_s",
                   "flips_per_s", "accept_rate", "transfer_bytes",
                   "hbm_history_bytes", "done", "total"),
        "doc": "one per executed device chunk",
    },
    "compile": {
        "fields": ("fn", "cache_size"),
        "doc": "jit cache miss observed by JitWatch.poll",
    },
    "transfer": {
        "fields": ("what", "bytes"),
        "doc": "one-off device->host copy outside the chunk stream",
    },
    "run_end": {
        "fields": ("runner", "n_yields", "wall_s", "flips_per_s"),
        "doc": "totals for the run",
    },
    "sweep_config": {
        "fields": ("tag", "family", "status"),
        "doc": "driver progress; status in SWEEP_STATUSES",
    },
    "error": {
        "fields": ("message",),
        "doc": "a failure the emitter survived or is about to re-raise",
    },
    "diag": {
        "fields": ("observable", "samples", "rhat", "ess", "ess_per_s",
                   "accept_ewma", "throughput_ewma"),
        "doc": "streaming convergence health (obs.monitor.ChainMonitor)",
    },
    "anomaly": {
        "fields": ("kind", "detail"),
        "doc": "monitor health-threshold episode",
    },
    "span_begin": {
        "fields": ("name", "span_id", "trace_id", "parent_id"),
        "doc": "host wall-clock span opened (obs.trace)",
    },
    "span_end": {
        "fields": ("name", "span_id", "trace_id", "dur_s"),
        "doc": "host span closed; dur_s from the monotonic clock",
    },
    "metrics_snapshot": {
        "fields": ("counters", "gauges", "histograms"),
        "doc": "obs.metrics.MetricsRegistry snapshot",
    },
    "retry": {
        "fields": ("tag", "attempt", "error_class", "backoff_s"),
        "doc": "supervisor retrying a failed config after backoff",
    },
    "config_failed": {
        "fields": ("tag", "error_class", "message"),
        "doc": "a config exhausted its retry budget; sweep continues",
    },
    "config_quarantined": {
        "fields": ("tag", "failures"),
        "doc": "poison config isolated after N deterministic failures",
    },
    "checkpoint_corrupt": {
        "fields": ("tag", "path", "reason"),
        "doc": "checkpoint generation failed integrity; quarantined "
               "to .corrupt/ and resume fell back a generation",
    },
    "kernel_path_degraded": {
        "fields": ("from_path", "to_path", "reason"),
        "doc": "dispatch ladder fell to the next body after a kernel "
               "error; bench_compare refuses to gate such records",
    },
    "sweep_summary": {
        "fields": ("completed", "retried", "quarantined", "failed"),
        "doc": "supervised sweep totals; quarantined/failed nonzero "
               "means nonzero driver exit",
    },
    "heartbeat_error": {
        "fields": ("message",),
        "doc": "heartbeat write failed; run continues (non-fatal)",
    },
    "job_submitted": {
        "fields": ("job_id", "tag"),
        "doc": "sweep service accepted a config submission",
    },
    "job_batched": {
        "fields": ("batch_id", "jobs", "chains"),
        "doc": "scheduler coalesced compatible jobs into one device "
               "batch along the chain axis",
    },
    "job_done": {
        "fields": ("job_id", "tag", "status"),
        "doc": "terminal job state: done / failed / quarantined",
    },
    "compile_cache_hit": {
        "fields": ("key", "kernel_path"),
        "doc": "batch signature already compiled (jit or persistent "
               "cache serves it)",
    },
    "compile_cache_miss": {
        "fields": ("key", "kernel_path"),
        "doc": "new batch signature: this dispatch pays XLA "
               "compilation and seeds the persistent cache",
    },
    "service_draining": {
        "fields": ("reason",),
        "doc": "drain request honored at a segment boundary; in-flight "
               "tenants checkpointed, process exits with the drain code",
    },
    "service_recovered": {
        "fields": ("path", "n_jobs", "n_requeued"),
        "doc": "SweepService.recover rebuilt the queue from a journal",
    },
    "journal_truncated": {
        "fields": ("path", "dropped"),
        "doc": "journal tail failed integrity (torn line / sha256 "
               "mismatch / seq gap); dropped records discarded and "
               "recovery proceeded from the last intact record",
    },
    "dispatch_stalled": {
        "fields": ("batch_id", "timeout_s", "waited_s"),
        "doc": "watchdog saw a device call exceed its timeout; batch "
               "journaled poison-suspect so its jobs retry solo",
    },
    "mesh_degraded": {
        "fields": ("from_devices", "to_devices", "reason"),
        "doc": "sharded run resumed on the surviving power-of-two "
               "sub-mesh; bench records marked degraded",
    },
    "control_action": {
        "fields": ("kind", "tag", "step", "policy"),
        "doc": "adaptive control decision at a segment boundary: "
               "stop / retune / reshape_ladder / reallocate; pure in "
               "observed history so recovery replays it bit-identically",
    },
    "http_request": {
        "fields": ("method", "path", "status"),
        "doc": "front door served one HTTP request; extras carry "
               "tenant/job_id/dur_s",
    },
    "quota_rejected": {
        "fields": ("tenant",),
        "doc": "submission refused by the tenant's token-bucket quota "
               "(HTTP 429)",
    },
    "lease_acquired": {
        "fields": ("job_id", "worker"),
        "doc": "worker claimed a job's atomic lease file; "
               "reclaim=True extra when it broke an expired lease",
    },
    "lease_expired": {
        "fields": ("job_id", "worker"),
        "doc": "lease past its heartbeat TTL (or torn) was broken; "
               "worker is the previous holder",
    },
    "worker_started": {
        "fields": ("worker",),
        "doc": "fleet worker process came up and began scanning for "
               "claimable jobs",
    },
    "worker_exited": {
        "fields": ("worker", "reason"),
        "doc": "fleet worker stopped: idle / drain / done / error "
               "class (a SIGKILL leaves no exit event)",
    },
    "profile_captured": {
        "fields": ("job_id", "segments", "ok"),
        "doc": "on-demand device profile finished: segments actually "
               "bracketed by jax.profiler.trace (ok=False extras "
               "carry the error when capture degraded to a no-op)",
    },
}

# Derived view (event -> frozenset of core fields) kept for existing
# consumers: validate_event below, tools/obs_report.py, tests.
EVENT_FIELDS = {name: frozenset(entry["fields"])
                for name, entry in EVENT_REGISTRY.items()}

SWEEP_STATUSES = ("start", "done", "skip")


def validate_event(obj) -> str | None:
    """None when ``obj`` is a schema-conforming event, else a short
    reason string (the ``--check`` contract: unknown or malformed events
    must be reported, extra fields must not)."""
    if not isinstance(obj, dict):
        return f"not an object: {type(obj).__name__}"
    if obj.get("v") != SCHEMA_VERSION:
        return f"unknown schema version {obj.get('v')!r}"
    ts = obj.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        return f"missing/non-numeric ts: {ts!r}"
    event = obj.get("event")
    if event not in EVENT_FIELDS:
        return f"unknown event type {event!r}"
    missing = EVENT_FIELDS[event] - obj.keys()
    if missing:
        return f"{event} missing fields {sorted(missing)}"
    if event == "sweep_config" and obj["status"] not in SWEEP_STATUSES:
        return f"sweep_config status {obj['status']!r} not in " \
               f"{SWEEP_STATUSES}"
    return None


def validate_spans(events) -> list:
    """Span pairing/nesting errors over a stream of parsed events (other
    event types pass through untouched). Shared by ``obs_report --check``
    and ``trace_export --validate``, which both load this module by file
    path. The contract:

    * every ``span_begin`` is closed by exactly one ``span_end`` with
      the same ``span_id`` and ``name``;
    * span ids are never reused within a stream;
    * a non-null ``parent_id`` refers to a span that is open at the
      child's begin (parents precede children, in stream order);
    * a parent does not close while a child is still open.

    Returns a list of human-readable error strings (empty == clean).
    """
    errors = []
    open_spans: dict = {}
    closed = set()
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            continue
        kind = e.get("event")
        if kind == "span_begin":
            sid = e.get("span_id")
            if sid in open_spans or sid in closed:
                errors.append(f"event {i}: span_begin reuses span_id "
                              f"{sid!r} ({e.get('name')!r})")
                continue
            pid = e.get("parent_id")
            if pid is not None and pid not in open_spans:
                errors.append(f"event {i}: span_begin {e.get('name')!r} "
                              f"has parent {pid!r} that is not open")
            open_spans[sid] = e
        elif kind == "span_end":
            sid = e.get("span_id")
            begin = open_spans.pop(sid, None)
            if begin is None:
                errors.append(f"event {i}: span_end {e.get('name')!r} "
                              f"for span_id {sid!r} with no open begin")
                continue
            if begin.get("name") != e.get("name"):
                errors.append(f"event {i}: span_end name "
                              f"{e.get('name')!r} != begin name "
                              f"{begin.get('name')!r} (span_id {sid!r})")
            closed.add(sid)
            orphans = [b for b in open_spans.values()
                       if b.get("parent_id") == sid]
            for b in orphans:
                errors.append(f"event {i}: span {sid!r} "
                              f"({e.get('name')!r}) closed while child "
                              f"{b.get('span_id')!r} ({b.get('name')!r}) "
                              f"is still open")
    for sid, b in open_spans.items():
        errors.append(f"span {sid!r} ({b.get('name')!r}) never closed")
    return errors


def validate_line(line: str) -> str | None:
    """validate_event over one raw JSONL line (blank lines pass: an
    interrupted writer may leave a trailing newline)."""
    import json

    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
    except json.JSONDecodeError as e:
        return f"malformed JSON: {e.msg}"
    return validate_event(obj)
