"""Chain telemetry subsystem: structured JSONL run events, per-chunk
metrics, and the shared ``jax.profiler`` hook (ISSUE 1).

Zero-dependency by construction — stdlib only at import time, jax
imported lazily inside ``profile_region`` — so the schema and recorder
stay usable from tools and tests that never touch the device runtime.
The default recorder is the no-op ``NULL``; enable telemetry by passing
``recorder=`` to a runner / ``run_sweep``, via ``--events PATH`` on
bench.py and ``python -m flipcomplexityempirical_tpu.experiments``, or
process-wide with ``set_default_recorder``.
"""

from .events import (EVENT_FIELDS, SCHEMA_VERSION, SWEEP_STATUSES,
                     validate_event, validate_line)
from .recorder import (NULL, JitWatch, NullRecorder, Recorder, aot_cost,
                       default_recorder, device_memory_snapshot,
                       dict_nbytes, from_spec, jit_cache_size,
                       profile_region, resolve_recorder,
                       set_default_recorder)

__all__ = [
    "EVENT_FIELDS", "SCHEMA_VERSION", "SWEEP_STATUSES",
    "validate_event", "validate_line",
    "NULL", "NullRecorder", "Recorder", "JitWatch", "ChainMonitor",
    "default_recorder", "set_default_recorder", "resolve_recorder",
    "from_spec", "profile_region", "jit_cache_size", "dict_nbytes",
    "aot_cost", "device_memory_snapshot",
]


def __getattr__(name):
    # ChainMonitor pulls numpy + stats.diagnostics; load it lazily so
    # the package keeps its stdlib-only-at-import contract for tools
    if name == "ChainMonitor":
        from .monitor import ChainMonitor
        return ChainMonitor
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
