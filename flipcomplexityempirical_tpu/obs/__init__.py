"""Chain telemetry subsystem: structured JSONL run events, per-chunk
metrics, span-based tracing, and the shared ``jax.profiler`` hook
(ISSUEs 1, 3, 5).

Zero-dependency by construction — stdlib only at import time, jax
imported lazily inside ``profile_region`` / the TraceAnnotation bridge —
so the schema, recorder, tracer, and metrics registry stay usable from
tools and tests that never touch the device runtime.
The default recorder is the no-op ``NULL``; enable telemetry by passing
``recorder=`` to a runner / ``run_sweep``, via ``--events PATH`` on
bench.py and ``python -m flipcomplexityempirical_tpu.experiments``, or
process-wide with ``set_default_recorder``.

Tracing (``obs.trace``): ``span(rec, name, **args)`` context manager /
``.begin()``/``.end()`` pairs emit ``span_begin``/``span_end`` events;
``traced`` is the decorator form; ``tools/trace_export.py`` converts a
stream to Chrome trace-event JSON for Perfetto. Metrics
(``obs.metrics.MetricsRegistry``): counters/gauges/histograms whose
p50/p95/p99 snapshots ride ``run_end`` events and driver heartbeats.
"""

from .events import (EVENT_FIELDS, SCHEMA_VERSION, SWEEP_STATUSES,
                     validate_event, validate_line, validate_spans)
from .metrics import Histogram, MetricsRegistry
from .recorder import (NULL, JitWatch, NullRecorder, Recorder, aot_cost,
                       default_recorder, device_memory_snapshot,
                       dict_nbytes, from_spec, jit_cache_size,
                       per_host_path, profile_region, resolve_recorder,
                       set_default_recorder)
from .trace import Span, adopt, emit_span_at, span, traced

__all__ = [
    "EVENT_FIELDS", "SCHEMA_VERSION", "SWEEP_STATUSES",
    "validate_event", "validate_line", "validate_spans",
    "NULL", "NullRecorder", "Recorder", "JitWatch", "ChainMonitor",
    "default_recorder", "set_default_recorder", "resolve_recorder",
    "from_spec", "per_host_path", "profile_region", "jit_cache_size",
    "dict_nbytes", "aot_cost", "device_memory_snapshot",
    "Span", "span", "traced", "emit_span_at", "adopt",
    "Histogram", "MetricsRegistry", "FleetCollector",
]


def __getattr__(name):
    # ChainMonitor pulls numpy + stats.diagnostics; load it lazily so
    # the package keeps its stdlib-only-at-import contract for tools
    if name == "ChainMonitor":
        from .monitor import ChainMonitor
        return ChainMonitor
    if name == "FleetCollector":
        from .aggregate import FleetCollector
        return FleetCollector
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
