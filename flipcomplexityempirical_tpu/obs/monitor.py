"""In-flight chain health: streaming convergence diagnostics (ISSUE 3).

``ChainMonitor`` rides the host side of the runner loops, consuming the
per-chunk history blocks they already copy back — it introduces **no new
device syncs**. Per chunk it folds the observable series into online
per-chain Welford moments and a bounded thinning buffer, computes split
R-hat and ESS over that buffer with the *same* host oracles the offline
analysis uses (``stats.diagnostics.gelman_rubin`` / ``ess`` — when the
buffer is unthinned the streaming numbers are exactly the oracle
numbers), tracks EWMA acceptance/throughput trends, and emits a ``diag``
event. Health thresholds emit ``anomaly`` events:

- ``frozen_chain``: a chain accepted nothing for ``freeze_chunks``
  consecutive observed chunks (the paper's frozen-phase signature —
  10^5 dead steps no longer look like healthy throughput).
- ``acceptance_collapse``: the acceptance EWMA fell below
  ``collapse_rate`` after warmup.
- ``pop_bound_saturation``: the chunk's reject breakdown attributes more
  than ``pop_sat_frac`` of proposals to the population bound.
- ``throughput_regression``: chunk throughput fell below
  ``regression_frac`` of the run's own EWMA after warmup.

Each kind re-arms when the condition clears, so a long sick run records
episodes rather than one anomaly per chunk. Memory is bounded: the
buffer caps at ``buffer_cap`` samples per chain, after which it is
decimated 2x and the keep-stride doubles (classic stride-doubling
thinning — the kept samples stay an evenly spaced grid over the whole
run, which is what split R-hat and the Sokal ESS window want).

numpy is imported here (and stats.diagnostics transitively) — the obs
package keeps its stdlib-only import contract by exporting ChainMonitor
lazily via module ``__getattr__``.
"""

from __future__ import annotations

import math

import numpy as np

from ..stats.diagnostics import ess as _ess
from ..stats.diagnostics import gelman_rubin as _gelman_rubin
from .trace import span as _span

REJECT_KEYS = ("nonboundary", "pop", "disconnect", "metropolis")


def _finite(x):
    """float(x) when finite else None — JSONL streams carry null, not
    Infinity/NaN (strict parsers reject bare Infinity tokens)."""
    if x is None:
        return None
    x = float(x)
    return x if math.isfinite(x) else None


class ChainMonitor:
    """Streaming per-run convergence/health monitor.

    One instance per run (the runners build one when a truthy recorder
    is attached). ``observe_chunk`` is fed whatever the runner already
    has on the host at its existing chunk boundary: the thinned history
    block (``outs``, dict of (T, C) arrays — optional: without history
    the monitor still tracks EWMA trends and reject anomalies), the
    chunk wall/throughput, and the reject breakdown read back from the
    device counters.
    """

    def __init__(self, rec, observable="cut_count", total=None, path=None,
                 runner=None, buffer_cap=4096, ewma_alpha=0.3,
                 freeze_chunks=3, collapse_rate=0.02, pop_sat_frac=0.9,
                 regression_frac=0.5, warmup_chunks=3):
        self._rec = rec
        self.observable = observable
        self.total = total
        self.path = path
        self.runner = runner
        self.buffer_cap = max(int(buffer_cap), 8)
        self.ewma_alpha = float(ewma_alpha)
        self.freeze_chunks = int(freeze_chunks)
        self.collapse_rate = float(collapse_rate)
        self.pop_sat_frac = float(pop_sat_frac)
        self.regression_frac = float(regression_frac)
        self.warmup_chunks = int(warmup_chunks)
        # Welford per chain (exact over ALL samples, not just the buffer)
        self._n = 0
        self._mean = None          # f64[C]
        self._m2 = None            # f64[C]
        # bounded thinning buffer: f64[C, L], keep-stride doubles at cap
        self._buf = None
        self._stride = 1
        self._seen = 0             # samples consumed (thinned-grid index)
        # trends / anomaly arming
        self._chunks = 0
        self._wall = 0.0
        self._acc_ewma = None
        self._thr_ewma = None
        self._last_accepts = None  # f64[C] cumulative accepts at last chunk
        self._freeze_streak = None  # int[C] consecutive zero-accept chunks
        self._frozen = None        # bool[C] already reported frozen
        self._collapsed = False
        self._pop_saturated = False
        self._regressed = False

    # ---- streaming moments ------------------------------------------

    def _fold_welford(self, arr):
        """Merge a (C, T) block into the per-chain running moments."""
        t = arr.shape[1]
        if t == 0:
            return
        bmean = arr.mean(axis=1)
        bm2 = ((arr - bmean[:, None]) ** 2).sum(axis=1)
        if self._n == 0:
            self._mean, self._m2, self._n = bmean, bm2, t
            return
        n, tot = self._n, self._n + t
        delta = bmean - self._mean
        self._mean = self._mean + delta * (t / tot)
        self._m2 = self._m2 + bm2 + delta * delta * (n * t / tot)
        self._n = tot

    def _fold_buffer(self, arr):
        """Append the block's stride-aligned columns; decimate at cap."""
        t = arr.shape[1]
        idx = np.arange(self._seen, self._seen + t)
        self._seen += t
        keep = arr[:, idx % self._stride == 0]
        if keep.shape[1]:
            self._buf = (keep if self._buf is None
                         else np.concatenate([self._buf, keep], axis=1))
        while self._buf is not None and self._buf.shape[1] > self.buffer_cap:
            self._buf = self._buf[:, ::2]
            self._stride *= 2

    def _diagnostics(self):
        """(rhat, ess_total) over the buffer via the host oracles; None
        where not yet computable. gelman_rubin needs >= 4 kept samples
        (it splits each chain in half)."""
        if self._buf is None or self._buf.shape[1] < 4:
            return None, None
        rhat = _finite(_gelman_rubin(self._buf))
        # ESS is computed on the kept grid; with stride s each kept
        # sample stands for s raw samples, so scale back up
        _, ess_total = _ess(self._buf)
        ess_total = _finite(ess_total)
        if ess_total is not None:
            ess_total *= self._stride
        return rhat, ess_total

    def _ewma(self, prev, x):
        if x is None:
            return prev
        x = float(x)
        return x if prev is None else (self.ewma_alpha * x
                                       + (1 - self.ewma_alpha) * prev)

    def _anomaly(self, kind, **detail):
        e = self._rec.emit("anomaly", kind=kind, detail=detail,
                           observable=self.observable, runner=self.runner,
                           path=self.path)
        # mirror of diag_hook below: the driver installs anomaly_hook
        # while a heartbeat is active so the heartbeat JSON carries a
        # live per-kind anomaly tally (best-effort, never raises)
        hook = getattr(self._rec, "anomaly_hook", None)
        if hook is not None and e is not None:
            try:
                hook(e)
            except Exception:
                pass

    # ---- per-chunk entry point --------------------------------------

    def observe_chunk(self, outs=None, wall_s=None, flips_per_s=None,
                      accept_rate=None, reject=None, done=None,
                      ts=None):
        """Fold one chunk's host-side data; emit ``diag`` (+ any
        ``anomaly``). Returns the emitted diag event dict.

        ``outs``: dict of (T, C) host arrays (the runner's thinned
        history block). Uses ``self.observable`` for convergence and,
        when present, the cumulative ``accepts`` series for per-chain
        freeze detection. ``reject``: the chunk event's breakdown
        ({nonboundary, pop, disconnect, metropolis, accepted,
        proposals}).

        The fold runs inside a ``diag`` span: the host-side diagnostics
        work (Welford merge, R-hat/ESS over the buffer) is real wall
        time the timeline should attribute, distinct from kernel time.
        """
        with _span(self._rec, "diag", observable=self.observable):
            return self._observe_chunk(outs, wall_s, flips_per_s,
                                       accept_rate, reject, done, ts)

    def _observe_chunk(self, outs, wall_s, flips_per_s, accept_rate,
                       reject, done, ts):
        self._chunks += 1
        if wall_s:
            self._wall += float(wall_s)

        accepts_delta = None
        if outs:
            obs_series = outs.get(self.observable)
            if obs_series is not None:
                arr = np.asarray(obs_series, np.float64)
                if arr.ndim == 1:
                    arr = arr[:, None]
                arr = arr.T  # (C, T)
                self._fold_welford(arr)
                self._fold_buffer(arr)
            acc = outs.get("accepts")
            if acc is not None:
                acc = np.asarray(acc, np.float64)
                if acc.ndim == 1:
                    acc = acc[:, None]
                last = acc[-1]  # cumulative per-chain accepts at chunk end
                if self._last_accepts is not None:
                    accepts_delta = last - self._last_accepts
                else:
                    accepts_delta = last - np.asarray(acc[0], np.float64)
                self._last_accepts = last

        if accept_rate is None and reject is not None:
            prop = reject.get("proposals") or 0
            if prop:
                accept_rate = reject.get("accepted", 0) / prop
        self._acc_ewma = self._ewma(self._acc_ewma, accept_rate)

        rhat, ess_total = self._diagnostics()
        ess_per_s = (ess_total / self._wall
                     if ess_total is not None and self._wall > 0 else None)

        diag = self._rec.emit(
            "diag", ts=ts, observable=self.observable,
            samples=self._n, rhat=rhat, ess=ess_total,
            ess_per_s=_finite(ess_per_s),
            accept_ewma=_finite(self._acc_ewma),
            throughput_ewma=_finite(self._thr_ewma),
            mean=_finite(self._mean.mean()) if self._mean is not None
            else None,
            chunks=self._chunks, runner=self.runner, path=self.path,
            done=done, total=self.total)

        self._check_anomalies(accepts_delta, flips_per_s, reject)
        # throughput EWMA updates AFTER the regression check — the
        # comparison is "this chunk vs the run's own trend so far"
        self._thr_ewma = self._ewma(self._thr_ewma, flips_per_s)

        hook = getattr(self._rec, "diag_hook", None)
        if hook is not None and diag is not None:
            try:
                hook(diag)
            except Exception:
                pass
        return diag

    # ---- summary-mode entry point (ISSUE 20) ------------------------

    def observe_summary(self, summary, rhat=None, ess=None, wall_s=None,
                        flips_per_s=None, accept_rate=None, reject=None,
                        done=None, ts=None):
        """Summary-mode twin of ``observe_chunk``: consumes the
        device-resident analytics' per-chunk summary pytree (host dict
        from ``stats.accumulators.summary_host``) instead of a history
        block. The device accumulator is authoritative for the Welford
        moments; R-hat/ESS arrive precomputed from the on-device
        thinning buffer (None = not refreshed this chunk — the last
        refreshed values are reported by the caller). Emits the same
        ``diag`` event shape, drives the same anomaly thresholds,
        ``diag_hook`` and ``anomaly_hook``."""
        with _span(self._rec, "diag", observable=self.observable):
            return self._observe_summary(summary, rhat, ess, wall_s,
                                         flips_per_s, accept_rate,
                                         reject, done, ts)

    def _observe_summary(self, summary, rhat, ess, wall_s, flips_per_s,
                         accept_rate, reject, done, ts):
        self._chunks += 1
        if wall_s:
            self._wall += float(wall_s)
        self._n = int(summary["n"])
        self._mean = np.asarray(summary["mean"], np.float64)
        self._m2 = np.asarray(summary["m2"], np.float64)

        accepts_delta = None
        accs = summary.get("accepts")
        if accs is not None:
            last = np.asarray(accs, np.float64)
            if self._last_accepts is not None:
                accepts_delta = last - self._last_accepts
            self._last_accepts = last

        if accept_rate is None and reject is not None:
            prop = reject.get("proposals") or 0
            if prop:
                accept_rate = reject.get("accepted", 0) / prop
        self._acc_ewma = self._ewma(self._acc_ewma, accept_rate)

        rhat = _finite(rhat)
        ess = _finite(ess)
        ess_per_s = (ess / self._wall
                     if ess is not None and self._wall > 0 else None)

        diag = self._rec.emit(
            "diag", ts=ts, observable=self.observable,
            samples=self._n, rhat=rhat, ess=ess,
            ess_per_s=_finite(ess_per_s),
            accept_ewma=_finite(self._acc_ewma),
            throughput_ewma=_finite(self._thr_ewma),
            mean=_finite(self._mean.mean()) if self._mean is not None
            else None,
            chunks=self._chunks, runner=self.runner, path=self.path,
            done=done, total=self.total)

        self._check_anomalies(accepts_delta, flips_per_s, reject)
        self._thr_ewma = self._ewma(self._thr_ewma, flips_per_s)

        hook = getattr(self._rec, "diag_hook", None)
        if hook is not None and diag is not None:
            try:
                hook(diag)
            except Exception:
                pass
        return diag

    # ---- anomaly thresholds -----------------------------------------

    def _check_anomalies(self, accepts_delta, flips_per_s, reject):
        if accepts_delta is not None:
            c = accepts_delta.shape[0]
            if self._freeze_streak is None:
                self._freeze_streak = np.zeros(c, np.int64)
                self._frozen = np.zeros(c, bool)
            stalled = accepts_delta <= 0
            self._freeze_streak = np.where(stalled,
                                           self._freeze_streak + 1, 0)
            hit = self._freeze_streak >= self.freeze_chunks
            fresh = hit & ~self._frozen
            if fresh.any():
                idx = np.flatnonzero(fresh)
                self._anomaly("frozen_chain",
                              chains=int(hit.sum()),
                              new_chains=[int(i) for i in idx[:16]],
                              streak_chunks=int(self._freeze_streak.max()))
            self._frozen = hit  # thawed chains re-arm

        if self._acc_ewma is not None and self._chunks > self.warmup_chunks:
            if self._acc_ewma < self.collapse_rate and not self._collapsed:
                self._collapsed = True
                self._anomaly("acceptance_collapse",
                              accept_ewma=float(self._acc_ewma),
                              threshold=self.collapse_rate)
            elif self._acc_ewma >= self.collapse_rate:
                self._collapsed = False

        if reject:
            prop = reject.get("proposals") or 0
            frac = (reject.get("pop", 0) / prop) if prop else 0.0
            if frac > self.pop_sat_frac and not self._pop_saturated:
                self._pop_saturated = True
                self._anomaly("pop_bound_saturation",
                              pop_reject_frac=float(frac),
                              threshold=self.pop_sat_frac)
            elif frac <= self.pop_sat_frac:
                self._pop_saturated = False

        if (flips_per_s is not None and self._thr_ewma is not None
                and self._chunks > self.warmup_chunks):
            floor = self.regression_frac * self._thr_ewma
            if flips_per_s < floor and not self._regressed:
                self._regressed = True
                self._anomaly("throughput_regression",
                              flips_per_s=float(flips_per_s),
                              ewma=float(self._thr_ewma),
                              frac=self.regression_frac)
            elif flips_per_s >= floor:
                self._regressed = False
