"""Live fleet aggregation: incremental tailing of every event stream
under a shared fleet root (ISSUE 18).

``FleetCollector`` is the read side of the fleet observability plane.
A fleet run (service.server + N service.worker processes) appends
schema-versioned JSONL to ``<root>/events/<name>.jsonl`` — one file per
process, one writer per file (the journal's discipline). The collector
tails all of them *incrementally*:

* **File-offset checkpoints.** Each ``poll()`` reads only bytes past
  the last checkpointed offset per stream, folds the new events into
  its aggregate state, and atomically rewrites
  ``<root>/events/.collector.json`` (tmp + fsync + rename — the same
  recipe as every other atomic doc in the fleet root). A restarted
  collector — the server process bounced — resumes from the checkpoint
  without re-counting a single event.
* **Torn-tail tolerant.** Only complete, newline-terminated lines are
  consumed; a line still being written (or torn by a SIGKILL) stays in
  the file past the offset and is re-read whole on the next poll, the
  journal reader's tolerance applied to live tailing. A stream that
  SHRANK (rotation, truncation) resets to offset 0 rather than reading
  garbage from the middle of a new file.
* **Host-side only.** The collector reads files and parses JSON;
  it never touches jax, device memory, or the run loop (PROFILE.md's
  no-extra-device-syncs rule extends to observers). The injected
  ``clock`` keeps staleness math testable on a virtual clock.

Aggregate state feeds the server's two read-only surfaces:
``prometheus_text()`` renders the Prometheus text exposition served at
``GET /v1/metrics`` (per-worker counters/gauges/histogram percentiles
from the newest ``metrics_snapshot`` per stream, plus fleet rollups),
and ``fleet_doc()`` the JSON topology at ``GET /v1/fleet`` (workers,
job stages, per-stream tailing positions). Stdlib-only.
"""

from __future__ import annotations

import json
import os
import time

_CKPT_NAME = ".collector.json"
_STATE_V = 1

# stream-derived stages stop at "running": terminal stages live in the
# server's status files (the authoritative merge happens in /v1/fleet),
# because worker-internal sweep events reuse the fleet's job-id space
_STAGE_QUEUED = "queued"
_STAGE_RUNNING = "running"


def _atomic_write(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"), sort_keys=True)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:
            pass
    os.replace(tmp, path)


class FleetCollector:
    """Incremental aggregator over ``<root>/events/*.jsonl``.

    ``poll()`` is the only mutator; everything else renders the state
    it left behind. Thread-unsafe by design — the server serializes
    access behind its own lock (one collector per server process, the
    same one-writer-per-file discipline the checkpoint itself needs).

    ``checkpoint=False`` reads without ever writing the checkpoint file
    (tools pointed at a fixture directory they must not dirty).
    """

    def __init__(self, root, clock=time.time, checkpoint=True):
        self.root = root
        self.events_dir = os.path.join(root, "events")
        self.clock = clock
        self.checkpoint = checkpoint
        self._ckpt_path = os.path.join(self.events_dir, _CKPT_NAME)
        self.state = {"v": _STATE_V, "streams": {}, "jobs": {},
                      "workers": {}}
        if checkpoint:
            try:
                with open(self._ckpt_path, encoding="utf-8") as f:
                    doc = json.load(f)
                if doc.get("v") == _STATE_V:
                    self.state = doc
            except (OSError, ValueError):
                pass        # fresh or torn checkpoint: start from zero

    # -- tailing -------------------------------------------------------

    def _stream_names(self):
        try:
            names = os.listdir(self.events_dir)
        except OSError:
            return []
        return sorted(n for n in names
                      if n.endswith(".jsonl") and not n.startswith("."))

    def poll(self) -> dict:
        """Tail every stream from its checkpointed offset, fold new
        events, persist the checkpoint; returns a small summary of the
        increment ({"events": n, "streams": k})."""
        new_events = 0
        for name in self._stream_names():
            path = os.path.join(self.events_dir, name)
            st = self.state["streams"].setdefault(
                name, {"offset": 0, "events": {}, "last_ts": None,
                       "ident": {}, "snapshot": None, "malformed": 0})
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size < st["offset"]:
                st["offset"] = 0        # rotated/truncated: re-read
            if size == st["offset"]:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(st["offset"])
                    buf = f.read(size - st["offset"])
            except OSError:
                continue
            # consume only complete lines; a torn tail waits for the
            # writer to finish it
            cut = buf.rfind(b"\n")
            if cut < 0:
                continue
            for raw in buf[:cut].split(b"\n"):
                if not raw.strip():
                    continue
                try:
                    ev = json.loads(raw)
                except ValueError:
                    st["malformed"] += 1
                    continue
                if not isinstance(ev, dict) or "event" not in ev:
                    st["malformed"] += 1
                    continue
                self._fold(name, st, ev)
                new_events += 1
            st["offset"] += cut + 1
        if self.checkpoint:
            try:
                _atomic_write(self._ckpt_path, self.state)
            except OSError:
                pass        # a read-only root degrades to re-counting
        return {"events": new_events,
                "streams": len(self.state["streams"])}

    def _fold(self, stream: str, st: dict, ev: dict) -> None:
        kind = ev["event"]
        st["events"][kind] = st["events"].get(kind, 0) + 1
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            if st["last_ts"] is None or ts > st["last_ts"]:
                st["last_ts"] = ts
        for key in ("pid", "worker_name"):
            if key in ev:
                st["ident"][key] = ev[key]
        if kind == "metrics_snapshot":
            st["snapshot"] = {"counters": ev.get("counters") or {},
                              "gauges": ev.get("gauges") or {},
                              "histograms": ev.get("histograms") or {},
                              "ts": ts}
            return
        jobs = self.state["jobs"]
        workers = self.state["workers"]
        if kind == "job_submitted":
            # only the SERVER's submission event carries the fleet
            # stage; a worker-internal sweep-queue job_submitted reuses
            # the same id space (its service's own j0000...) but never
            # carries a trace_id — folding it would alias fleet jobs
            if "trace_id" in ev:
                job = jobs.setdefault(ev.get("job_id"), {})
                job.setdefault("stage", _STAGE_QUEUED)
                job["tenant"] = ev.get("tenant")
                job["trace_id"] = ev.get("trace_id")
                job["submitted_ts"] = ts
        elif kind == "lease_acquired":
            job = jobs.setdefault(ev.get("job_id"), {})
            job["stage"] = _STAGE_RUNNING
            job["worker"] = ev.get("worker")
            job.setdefault("started_ts", ts)
            if ev.get("reclaim"):
                job["reclaims"] = job.get("reclaims", 0) + 1
        elif kind == "lease_expired":
            job = jobs.setdefault(ev.get("job_id"), {})
            job["expired"] = job.get("expired", 0) + 1
        elif kind == "worker_started":
            w = workers.setdefault(ev.get("worker"), {})
            w.update({"stream": stream, "started_ts": ts,
                      "pid": ev.get("pid"), "exited": False})
        elif kind == "worker_exited":
            w = workers.setdefault(ev.get("worker"), {})
            w.update({"exited": True, "reason": ev.get("reason"),
                      "exited_ts": ts})
        elif kind == "profile_captured":
            job = jobs.setdefault(ev.get("job_id"), {})
            job["profiled_segments"] = ev.get("segments")

    # -- render --------------------------------------------------------

    def fleet_doc(self) -> dict:
        """JSON topology for ``GET /v1/fleet`` — live view of whatever
        the streams said so far (the server merges in its own queue
        depth, which never transits a stream)."""
        now = self.clock()
        streams = {}
        for name, st in sorted(self.state["streams"].items()):
            streams[name] = {
                "offset": st["offset"],
                "events": sum(st["events"].values()),
                "malformed": st["malformed"],
                "ident": st["ident"],
                "idle_s": (None if st["last_ts"] is None
                           else max(0.0, now - st["last_ts"])),
            }
        stages: dict = {}
        for job in self.state["jobs"].values():
            stage = job.get("stage") or "unknown"
            stages[stage] = stages.get(stage, 0) + 1
        return {"workers": self.state["workers"],
                "jobs": self.state["jobs"],
                "stages": stages,
                "streams": streams}

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4) for
        ``GET /v1/metrics``: per-stream event counts, the newest
        MetricsRegistry snapshot per stream (counters, gauges, and
        histogram count/sum/percentiles), and fleet rollups."""
        lines = []

        def sample(name, labels, value):
            if value is None:
                return
            if labels:
                body = ",".join(f'{k}="{v}"'
                                for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{body}}} {_num(value)}")
            else:
                lines.append(f"{name} {_num(value)}")

        streams = self.state["streams"]
        lines.append("# HELP graft_events_total events consumed per "
                     "stream, by type")
        lines.append("# TYPE graft_events_total counter")
        for sname, st in sorted(streams.items()):
            stream = _stream_label(sname)
            for kind, n in sorted(st["events"].items()):
                sample("graft_events_total",
                       {"stream": stream, "event": kind}, n)
        lines.append("# HELP graft_stream_offset_bytes checkpointed "
                     "tail offset per stream")
        lines.append("# TYPE graft_stream_offset_bytes gauge")
        for sname, st in sorted(streams.items()):
            sample("graft_stream_offset_bytes",
                   {"stream": _stream_label(sname)}, st["offset"])

        # newest per-stream MetricsRegistry snapshot
        lines.append("# HELP graft_counter MetricsRegistry counters "
                     "(newest snapshot per stream)")
        lines.append("# TYPE graft_counter gauge")
        roll_counters: dict = {}
        for sname, st in sorted(streams.items()):
            snap = st.get("snapshot")
            if not snap:
                continue
            stream = _stream_label(sname)
            for k, v in sorted(snap["counters"].items()):
                sample("graft_counter", {"stream": stream, "name": k}, v)
                roll_counters[k] = roll_counters.get(k, 0) + v
        lines.append("# HELP graft_gauge MetricsRegistry gauges "
                     "(newest snapshot per stream)")
        lines.append("# TYPE graft_gauge gauge")
        for sname, st in sorted(streams.items()):
            snap = st.get("snapshot")
            if not snap:
                continue
            stream = _stream_label(sname)
            for k, v in sorted(snap["gauges"].items()):
                sample("graft_gauge", {"stream": stream, "name": k}, v)
        lines.append("# HELP graft_histogram MetricsRegistry histogram "
                     "digests (newest snapshot per stream)")
        lines.append("# TYPE graft_histogram gauge")
        for sname, st in sorted(streams.items()):
            snap = st.get("snapshot")
            if not snap:
                continue
            stream = _stream_label(sname)
            for k, h in sorted(snap["histograms"].items()):
                for stat in ("count", "sum", "p50", "p95", "p99"):
                    sample("graft_histogram",
                           {"stream": stream, "name": k, "stat": stat},
                           h.get(stat))

        # fleet rollups
        lines.append("# HELP graft_fleet_counter fleet-wide rollup of "
                     "MetricsRegistry counters")
        lines.append("# TYPE graft_fleet_counter gauge")
        for k, v in sorted(roll_counters.items()):
            sample("graft_fleet_counter", {"name": k}, v)
        workers = self.state["workers"]
        lines.append("# HELP graft_fleet_workers fleet worker "
                     "processes by liveness")
        lines.append("# TYPE graft_fleet_workers gauge")
        live = sum(1 for w in workers.values() if not w.get("exited"))
        sample("graft_fleet_workers", {"state": "live"}, live)
        sample("graft_fleet_workers", {"state": "exited"},
               len(workers) - live)
        lines.append("# HELP graft_fleet_jobs fleet jobs by stage")
        lines.append("# TYPE graft_fleet_jobs gauge")
        stages: dict = {}
        for job in self.state["jobs"].values():
            stage = job.get("stage") or "unknown"
            stages[stage] = stages.get(stage, 0) + 1
        for stage, n in sorted(stages.items()):
            sample("graft_fleet_jobs", {"stage": stage}, n)
        return "\n".join(lines) + "\n"


def _stream_label(name: str) -> str:
    return name[:-len(".jsonl")] if name.endswith(".jsonl") else name


def _num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))
