"""Declarative fleet SLOs evaluated as burn rates (ISSUE 18).

An SLO spec is a plain dict — ``name``, ``kind`` (which evaluator
runs), ``target``, and evaluator-specific knobs. :func:`evaluate` folds
a fleet event timeline (merged or per-stream JSONL events, already
parsed) through every spec and returns one result row per spec:

    {"name", "kind", "target", "value", "burn", "ok", "count",
     "detail"}

``burn`` is the burn *rate*: observed badness over allowed badness,
normalized so ``burn <= 1.0`` means the objective holds and ``burn ==
2.0`` means the error budget is being consumed at twice the sustainable
pace. ``obs_report.py`` renders the rows as the "SLO" section and
``--strict`` turns any ``ok=False`` row into a nonzero exit. Specs
with fewer than ``min_count`` observations pass vacuously (``burn
0.0``) — a two-job smoke must not trip a tail-latency objective that
needs a population.

The four defaults are the fleet's serving objectives:

* ``queue_to_start_tail`` — p99/p50 of queue-to-start (submission to
  first lease claim) ≤ ``target``. Tail fairness: an even fleet keeps
  the ratio near 1; stragglers blow the p99 first (ROADMAP's 500-tenant
  axis measures the same ratio via tools/loadtest.py).
* ``lease_expiry_rate`` — lease expirations per minute, taken over the
  worst ``window_s`` window of the timeline (a storm is a burst, not
  an average), ≤ ``target``.
* ``throughput_floor`` — per kernel path, the slowest run's flips/s
  must stay ≥ ``target`` × that path's median (self-referential floor:
  no hardware constants, trips on a straggler run, not a slow machine).
  The first run of each (process, path, shape) group is warmup — it
  pays that specialization's jit compile — and is excluded; the
  objective judges steady-state serving.
* ``compile_cache_hit_ratio`` — cache hits / repeat probes ≥
  ``target``; a fleet that recompiles per job starves the accelerator
  on host time. Each key's first-seen miss is compulsory (no cache hits
  a key it has never seen) and excluded.

Stdlib-only, no intra-package imports: tools/obs_report.py loads this
module by file path (like obs/events.py), outside the package.
"""

from __future__ import annotations

__all__ = ["DEFAULT_SLOS", "evaluate"]

DEFAULT_SLOS = (
    {"name": "queue_to_start_tail", "kind": "queue_tail_ratio",
     "target": 8.0, "min_count": 4},
    {"name": "lease_expiry_rate", "kind": "lease_expiry_rate",
     "target": 2.0, "window_s": 60.0, "min_count": 0},
    {"name": "throughput_floor", "kind": "throughput_floor",
     "target": 0.2, "min_count": 2},
    {"name": "compile_cache_hit_ratio", "kind": "cache_hit_ratio",
     "target": 0.25, "min_count": 4},
)


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _queue_tail_ratio(events, spec):
    """(value, count, detail): p99/p50 over per-job queue-to-start."""
    submitted: dict = {}
    started: dict = {}
    for e in events:
        jid = e.get("job_id")
        if jid is None:
            continue
        if e.get("event") == "job_submitted":
            ts = e.get("ts")
            if ts is not None and (jid not in submitted
                                   or ts < submitted[jid]):
                submitted[jid] = ts
        elif e.get("event") == "lease_acquired" and jid not in started:
            started[jid] = e.get("ts")
    waits = sorted(started[j] - submitted[j] for j in started
                   if j in submitted and started[j] is not None
                   and started[j] >= submitted[j])
    if not waits:
        return None, 0, "no queue-to-start pairs"
    p50, p99 = _pctl(waits, 0.5), _pctl(waits, 0.99)
    if p50 <= 0.0:
        # sub-resolution waits: an idle fleet's p50 rounds to ~0;
        # a ratio over it is noise, not a tail
        return 1.0, len(waits), f"p50~0s over {len(waits)} jobs"
    return (p99 / p50, len(waits),
            f"p50={p50:.3f}s p99={p99:.3f}s over {len(waits)} jobs")


def _lease_expiry_rate(events, spec):
    """(value, count, detail): expirations/min, worst window."""
    window = float(spec.get("window_s", 60.0))
    times = sorted(e["ts"] for e in events
                   if e.get("event") == "lease_expired"
                   and e.get("ts") is not None)
    if not times:
        return 0.0, 0, "no lease expirations"
    worst, lo = 0, 0
    for hi in range(len(times)):
        while times[hi] - times[lo] > window:
            lo += 1
        worst = max(worst, hi - lo + 1)
    rate = worst / (window / 60.0)
    return (rate, len(times),
            f"{len(times)} total, worst {worst}/{window:.0f}s window")


def _throughput_floor(events, spec):
    """(value, count, detail): min over paths of (slowest run flips/s
    over the path's median), after warmup exclusion — the FIRST run of
    each (process, path, shape) group pays that specialization's jit
    compile, which is cold-start cost, not a straggler (the objective
    is about steady-state serving)."""
    groups: dict = {}
    for e in events:
        if e.get("event") != "run_end":
            continue
        fps = e.get("flips_per_s")
        path = e.get("kernel_path") or e.get("path")
        if isinstance(fps, (int, float)) and fps > 0 and path:
            proc = e.get("worker_name") or e.get("pid")
            shape = (path, proc, e.get("chains"), e.get("n_yields"))
            groups.setdefault(shape, []).append(
                (e.get("ts") or 0.0, float(fps)))
    per_path: dict = {}
    warmups = 0
    for (path, *_shape), runs in groups.items():
        runs.sort()
        warmups += 1
        for _ts, fps in runs[1:]:
            per_path.setdefault(path, []).append(fps)
    n = sum(len(v) for v in per_path.values())
    if not per_path:
        if groups:
            return (None, 0, f"only warmup runs ({warmups} group(s) "
                             "of one)")
        return None, 0, "no run_end throughput samples"
    worst, worst_path = None, None
    for path, vals in sorted(per_path.items()):
        vals.sort()
        ratio = vals[0] / _pctl(vals, 0.5)
        if worst is None or ratio < worst:
            worst, worst_path = ratio, path
    return (worst, n,
            f"slowest/median={worst:.3f} on {worst_path} "
            f"({n} steady-state runs, {len(per_path)} path(s), "
            f"{warmups} warmup(s) excluded)")


def _cache_hit_ratio(events, spec):
    """(value, count, detail): compile-cache hits over repeat probes.
    Each key's FIRST probe is a compulsory miss — no cache can hit a
    key it has never seen — so cold-start misses are excluded and the
    ratio judges only probes the cache had a chance to serve. Probes
    without a ``key`` field (older streams) count as repeats."""
    hits = 0
    repeats = 0
    cold = 0
    seen: set = set()
    for e in events:
        ev = e.get("event")
        if ev not in ("compile_cache_hit", "compile_cache_miss"):
            continue
        key = e.get("key")
        first = key is not None and key not in seen
        if key is not None:
            seen.add(key)
        if first and ev == "compile_cache_miss":
            cold += 1          # compulsory; a first-seen HIT still
            continue           # counts (persistent index pre-warm)
        repeats += 1
        if ev == "compile_cache_hit":
            hits += 1
    if repeats == 0:
        if cold:
            return None, 0, f"only cold misses ({cold} first-seen key(s))"
        return None, 0, "no compile-cache probes"
    return (hits / repeats, repeats,
            f"{hits} hit(s) / {repeats} repeat probe(s) "
            f"({cold} cold)")


_EVALUATORS = {
    "queue_tail_ratio": _queue_tail_ratio,
    "lease_expiry_rate": _lease_expiry_rate,
    "throughput_floor": _throughput_floor,
    "cache_hit_ratio": _cache_hit_ratio,
}


def _burn(kind, value, target):
    """Normalize to a burn rate: >1.0 means the objective is violated.
    Ratio-above-target objectives burn as value/target; floor-below-
    target objectives burn as target/value; hit-ratio burns as the
    consumed fraction of the error budget (1-target)."""
    if value is None:
        return 0.0
    if kind in ("queue_tail_ratio", "lease_expiry_rate"):
        return value / target if target > 0 else 0.0
    if kind == "throughput_floor":
        return target / value if value > 0 else float("inf")
    if kind == "cache_hit_ratio":
        budget = 1.0 - target
        return (1.0 - value) / budget if budget > 0 else 0.0
    raise ValueError(f"unknown SLO kind {kind!r}")


def evaluate(events, specs=DEFAULT_SLOS):
    """Evaluate every spec over one event timeline; returns the result
    rows in spec order. ``events`` is an iterable of parsed event dicts
    (any mix of fleet streams; ordering does not matter)."""
    events = list(events)
    results = []
    for spec in specs:
        kind = spec["kind"]
        fn = _EVALUATORS.get(kind)
        if fn is None:
            raise ValueError(f"unknown SLO kind {kind!r}")
        value, count, detail = fn(events, spec)
        target = float(spec["target"])
        min_count = int(spec.get("min_count", 0))
        if count < min_count:
            burn, ok = 0.0, True
            detail += f" — vacuous (n={count} < {min_count})"
        else:
            burn = _burn(kind, value, target)
            ok = burn <= 1.0
        results.append({"name": spec["name"], "kind": kind,
                        "target": target, "value": value,
                        "burn": burn, "ok": ok, "count": count,
                        "detail": detail})
    return results
