"""Metrics registry: counters, gauges, and fixed-bucket histograms.

The runtime-cheap half of the observability story (ISSUE 5): spans say
*where* the wall-clock went, the registry says *how it was distributed*.
One ``MetricsRegistry`` per run rides the runner's existing ``if rec:``
blocks — a counter bump and a bucket increment per chunk, nothing more —
and serializes into the ``run_end`` event (``metrics=``), one
``metrics_snapshot`` event per run, and the driver heartbeat (via
``Recorder.metrics_hook``), so a sweep watcher sees live p50/p95/p99
chunk latency without parsing the whole stream.

Histograms use FIXED bucket edges (default: a 1-2-5 log ladder spanning
1e-9..1e12, wide enough for both seconds and flips/s) so per-chunk
observation is O(log buckets) with bounded memory regardless of run
length; percentiles are estimated by linear interpolation inside the
target bucket, clamped to the observed min/max. Thread-safe (one lock
per registry) because sharded drivers may observe from helper threads.

Stdlib-only, like the rest of the obs core: the registry must be
importable from tools and tests that never touch jax.
"""

from __future__ import annotations

import bisect
import threading


def _default_edges():
    """The 1-2-5 log ladder: 1e-9, 2e-9, 5e-9, ..., 5e11, 1e12."""
    edges = []
    for e in range(-9, 12):
        for m in (1, 2, 5):
            edges.append(m * (10.0 ** e))
    edges.append(1e12)
    return tuple(edges)


DEFAULT_EDGES = _default_edges()


class Histogram:
    """Fixed-bucket histogram with exact count/sum/min/max and
    interpolated percentiles. ``edges`` are the bucket boundaries;
    bucket i holds values in [edges[i-1], edges[i]), with an underflow
    and an overflow bucket at the ends."""

    def __init__(self, edges=DEFAULT_EDGES):
        self.edges = tuple(float(e) for e in edges)
        if list(self.edges) != sorted(set(self.edges)):
            raise ValueError("histogram edges must be strictly increasing")
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value):
        v = float(value)
        self.counts[bisect.bisect_right(self.edges, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def percentile(self, q: float):
        """Linear-interpolated q-quantile (q in [0, 1]); None when
        empty. Exact at the bucket boundaries, clamped to [min, max]."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0.0
        for i, c in enumerate(self.counts):
            if c and cum + c >= target:
                lo = self.edges[i - 1] if i > 0 else self.min
                hi = self.edges[i] if i < len(self.edges) else self.max
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi <= lo:
                    return lo
                return lo + ((target - cum) / c) * (hi - lo)
            cum += c
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": (self.sum / self.count) if self.count else None,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    """Named counters / gauges / histograms behind one lock.

    The convenience methods (``inc`` / ``set`` / ``observe``) get-or-
    create, so call sites stay one line. ``snapshot()`` returns a plain
    JSON-ready dict — the exact object embedded in ``run_end`` events
    and heartbeats.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    def inc(self, name: str, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set(self, name: str, value):
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value, edges=DEFAULT_EDGES):
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(edges)
            h.observe(value)

    def histogram(self, name: str) -> Histogram | None:
        with self._lock:
            return self._histograms.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self._histograms.items()},
            }

    def emit_snapshot(self, rec, **fields):
        """One ``metrics_snapshot`` event from the current state (the
        runners emit exactly one, right before ``run_end``)."""
        s = self.snapshot()
        return rec.emit("metrics_snapshot", counters=s["counters"],
                        gauges=s["gauges"], histograms=s["histograms"],
                        **fields)

    def notify(self, rec):
        """Push the current snapshot into ``rec.metrics_hook`` when one
        is installed (the driver's heartbeat refresher) — a no-op
        otherwise, so per-chunk calls cost one getattr."""
        hook = getattr(rec, "metrics_hook", None)
        if hook is None:
            return
        try:
            hook(self.snapshot())
        except Exception:
            pass
