"""Device mesh helpers: the chains axis is the framework's primary (and
embarrassingly parallel) sharding dimension; replica-exchange ladders ride
the same axis via collectives (SURVEY.md section 2.4).

Multi-host: `initialize_distributed` wraps jax.distributed for DCN-connected
pods; single-process multi-device (one host, n chips, or
--xla_force_host_platform_device_count virtual CPUs) needs no setup.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CHAINS_AXIS = "chains"


def make_mesh(n_devices: int | None = None, axis: str = CHAINS_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def chain_sharding(mesh: Mesh, axis: str = CHAINS_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_chain_batch(mesh: Mesh, tree, axis: str = CHAINS_AXIS):
    """Place every leaf with a leading chains axis on the mesh (leading-axis
    sharding); scalars/replicated leaves are broadcast.

    The chain count (inferred as the largest leading dimension in the
    tree) must divide by the mesh size: silently replicating a
    chain-axis leaf that misses the divisibility check would hand every
    device the FULL batch — a correctness trap at C not divisible by
    the device count, caught here instead of as an 8x slowdown.
    Intentionally replicated leaves (label_values, anneal constants)
    have smaller leading dims and broadcast as before."""
    n_dev = mesh.devices.size
    leaves = [x for x in jax.tree.leaves(tree)
              if getattr(x, "ndim", 0) >= 1]
    n_chains = max((x.shape[0] for x in leaves), default=0)
    if n_chains and n_chains % n_dev:
        raise ValueError(
            f"shard_chain_batch: chain axis of size {n_chains} does not "
            f"divide across {n_dev} device(s); pad or resize the batch "
            f"(chains % devices == 0) — silent replication would give "
            "every device the full batch")
    cs = chain_sharding(mesh, axis)
    rep = replicated(mesh)

    def place(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == n_chains:
            return jax.device_put(x, cs)
        return jax.device_put(x, rep)

    return jax.tree.map(place, tree)


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host bring-up over DCN (no-op single-host).

    Smoke-tested by tests/test_distributed_smoke.py: two localhost
    processes form the cluster, build the global chains mesh, and run a
    cross-process collective (--runslow tier)."""
    if coordinator is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
