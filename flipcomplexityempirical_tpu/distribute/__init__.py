from .mesh import (
    CHAINS_AXIS, make_mesh, chain_sharding, replicated, shard_chain_batch,
    initialize_distributed,
)
from .sharded import (
    host_recorder, make_board_train_step, make_train_step, run_sharded,
)

__all__ = [
    "CHAINS_AXIS", "make_mesh", "chain_sharding", "replicated",
    "shard_chain_batch", "initialize_distributed", "make_train_step",
    "make_board_train_step", "run_sharded", "host_recorder",
]
