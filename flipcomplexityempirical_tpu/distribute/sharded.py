"""shard_map'd training step: data-parallel chains + cross-device replica
exchange over ICI.

The full "training step" of this framework (the analogue of a model's
fwd+bwd+optimizer): advance every chain ``inner_steps`` flips locally
(zero communication), then run an even-odd replica-exchange round where the
temperature ladder runs ALONG THE DEVICE AXIS — local chain slot i forms a
ladder whose rungs START one per device. Swaps pair adjacent TEMPERATURES
(rank-based — see _swap_round), exchanged via one `lax.all_gather` of the
per-chain beta/energy scalars over ICI plus replicated selection. Telemetry
(aggregate accepts) reduces with `lax.psum`.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..graphs.lattice import DeviceGraph
from ..kernel import board as kboard
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..sampling.tempering import chain_rungs
from ..state.chain_state import ChainState
from .mesh import CHAINS_AXIS


def _params_spec(sharded: bool):
    p = P(CHAINS_AXIS) if sharded else P()
    return StepParams(log_base=p, beta=p, pop_lo=p, pop_hi=p,
                      label_values=P(), anneal_t0=P(), anneal_ramp=P(),
                      anneal_beta_max=P())


def _swap_round(key, params, cut_count, parity, n_dev):
    """One even-odd replica-exchange round along the device axis.

    Pairs are ADJACENT TEMPERATURES, not adjacent devices: accepted swaps
    move betas between devices, so after a few rounds the device order no
    longer tracks the temperature order and device-neighbor pairing would
    exchange arbitrary (mostly-rejecting) temperature pairs — the same
    degradation tempering.swap_within_batch fixes in-batch. The partner
    device is therefore data-dependent, which rules out a static
    ``ppermute``; instead each device ``all_gather``s one stacked
    (3, L) f32 block of (beta, cut, log_base) scalars over ICI and
    computes the WHOLE round's outcome redundantly from the shared
    replicated key, then keeps its own row. Swap decisions are identical
    on every device by construction."""
    idx = jax.lax.axis_index(CHAINS_AXIS)
    stacked = jax.lax.all_gather(
        jnp.stack([params.beta, cut_count.astype(jnp.float32),
                   params.log_base]), CHAINS_AXIS)            # (D, 3, L)
    bl = stacked[:, 0].T                                      # (L, D)
    # per-chain ENERGY log_base * cut: the swap ratio for targets
    # pi_i ∝ exp(-beta_i * lb_i * cut) is exp((b1-b2)(lb1*c1 - lb2*c2)),
    # which is symmetric under partner exchange even when log_base
    # differs per chain (the (b1-b2)*lb*(c1-c2) shortcut is not)
    el = stacked[:, 2].T * stacked[:, 1].T                    # (L, D)
    n_l = bl.shape[0]
    # rank of each device's beta within its slot's ladder (0 = coldest;
    # the same convention as the in-batch tempering.chain_rungs)
    rung_flat, pos_of_rank = chain_rungs(bl.reshape(-1), n_dev)
    rank_of_pos = rung_flat.reshape(n_l, n_dev)
    lo = (rank_of_pos % 2) == parity
    partner_rank = jnp.clip(jnp.where(lo, rank_of_pos + 1,
                                      rank_of_pos - 1), 0, n_dev - 1)
    partner_pos = jnp.take_along_axis(pos_of_rank, partner_rank, axis=1)
    valid = jnp.where(lo, rank_of_pos + 1 < n_dev, rank_of_pos >= 1)
    beta_p = jnp.take_along_axis(bl, partner_pos, axis=1)
    e_p = jnp.take_along_axis(el, partner_pos, axis=1)
    log_a = (bl - beta_p) * (el - e_p)
    # shared uniform per unordered pair: one (L, D) draw read through the
    # pair's lower rank, identical on both partners and on every device
    pair_rank = jnp.minimum(rank_of_pos, partner_rank)
    u_rank = jax.random.uniform(jax.random.fold_in(key, parity),
                                (n_l, n_dev))
    u = jnp.take_along_axis(u_rank, pair_rank, axis=1)
    accept = valid & (jnp.log(jnp.maximum(u, 1e-12)) < log_a)  # (L, D)
    new_bl = jnp.where(accept, beta_p, bl)
    my_beta = new_bl.T[idx]
    my_accept = accept.T[idx]
    return params.replace(beta=my_beta), my_accept.sum()


def make_train_step(dg: DeviceGraph, spec: Spec, mesh, inner_steps: int,
                    exchange: bool = True):
    """Build a jitted sharded train step:
    (key, params, states) -> (params, states, info).

    ``key`` is a replicated PRNG key for the swap rounds (chain-local
    randomness lives inside ChainState.key). Swap decisions are computed
    identically on both partners from the shared key.
    """
    if exchange and spec.anneal != "none":
        # annealed chains ignore params.beta (kernel effective_beta), so a
        # beta-exchanging ladder would swap values with no dynamical effect
        raise ValueError("replica exchange is incompatible with "
                         "Spec.anneal != 'none': swaps exchange StepParams."
                         "beta, which the annealed kernel ignores")
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    paxes = StepParams.vmap_axes()

    def local_advance(params, states):
        def body(states, _):
            states = jax.vmap(
                lambda p, s: kstep.transition(dg, spec, p, s),
                in_axes=(paxes, 0))(params, states)
            states, _ = jax.vmap(
                lambda p, s: kstep.record(dg, spec, p, s),
                in_axes=(paxes, 0))(params, states)
            return states, ()
        states, _ = jax.lax.scan(body, states, None, length=inner_steps)
        return states

    pspec = _params_spec(sharded=True)
    state_spec = jax.tree.map(lambda _: P(CHAINS_AXIS), states_struct())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), pspec, state_spec),
        out_specs=(pspec, state_spec, P()),
        check_vma=False)
    def train_step(key, params, states):
        states = local_advance(params, states)
        swaps = jnp.int32(0)
        if exchange and n_dev > 1:
            params, s0 = _swap_round(key, params, states.cut_count, 0,
                                     n_dev)
            # graftlint: disable=G002(_swap_round folds in the parity)
            params, s1 = _swap_round(key, params, states.cut_count, 1,
                                     n_dev)
            swaps = s0 + s1
        info = {
            "accepts": jax.lax.psum(states.accept_count.sum(), CHAINS_AXIS),
            "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
        }
        return params, states, info

    return jax.jit(train_step)


def make_board_train_step(bg: "kboard.BoardGraph", spec: Spec, mesh,
                          inner_steps: int, exchange: bool = True):
    """The board fast path's sharded train step: advance every chain
    ``inner_steps`` yields locally with the stencil kernel (zero
    communication), then the same even-odd beta-exchange ladder along the
    device axis as ``make_train_step``. This is the multi-chip form of the
    headline benchmark workload."""
    if exchange and spec.anneal != "none":
        raise ValueError("replica exchange is incompatible with "
                         "Spec.anneal != 'none'")
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    pspec = _params_spec(sharded=True)
    state_spec = jax.tree.map(lambda _: P(CHAINS_AXIS),
                              board_states_struct())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), pspec, state_spec),
        out_specs=(pspec, state_spec, P()),
        check_vma=False)
    def train_step(key, params, states):
        states, _ = kboard.run_board_chunk(bg, spec, params, states,
                                           inner_steps, collect=False)
        swaps = jnp.int32(0)
        if exchange and n_dev > 1:
            # the board loop carries cut_count incrementally, so it is the
            # current energy right after a chunk
            cuts = states.cut_count
            params, s0 = _swap_round(key, params, cuts, 0, n_dev)
            # graftlint: disable=G002(_swap_round folds in the parity)
            params, s1 = _swap_round(key, params, cuts, 1, n_dev)
            swaps = s0 + s1
        info = {
            "accepts": jax.lax.psum(states.accept_count.sum(), CHAINS_AXIS),
            "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
        }
        return params, states, info

    return jax.jit(train_step)


def host_recorder(spec):
    """Per-host event sink for sharded runs: ``obs.from_spec`` with
    multi-host path rewriting, so each jax host appends its events and
    spans to its own ``events.host<K>.jsonl`` (concurrent appends to one
    shared file would interleave mid-line). ``tools/trace_export.py``
    merges the per-host files into a single Chrome trace, one ``pid``
    per host id parsed from the filename; ``tools/obs_report.py``
    accepts any one of them. Single-host processes get a plain
    single-file recorder — same spec, same call site either way."""
    from ..obs import from_spec

    return from_spec(spec, per_host=True)


def states_struct():
    """A ChainState of leaf placeholders for building PartitionSpec trees."""
    return ChainState(
        key=0, assignment=0, cut=0, cut_deg=0, dist_pop=0, cut_count=0,
        b_count=0, cur_wait=0, cur_flip_node=0, t_yield=0, part_sum=0,
        last_flipped=0, num_flips=0, cut_times=0, waits_sum=0,
        move_clock=0, accept_count=0, tries_sum=0, exhausted_count=0)


def board_states_struct():
    """BoardState leaf placeholders for building PartitionSpec trees."""
    return kboard.BoardState(
        key=0, board=0, dist_pop=0, cut_count=0, cur_wait=0, wait_pending=0,
        cur_flip=0, cur_sign=0, t_yield=0, move_clock=0, part_sum=0,
        last_flipped=0,
        num_flips=0, cut_times_e=0, cut_times_s=0, waits_sum=0,
        accept_count=0, tries_sum=0, exhausted_count=0)
