"""shard_map'd training step: data-parallel chains + cross-device replica
exchange over ICI.

The full "training step" of this framework (the analogue of a model's
fwd+bwd+optimizer): advance every chain ``inner_steps`` flips locally
(zero communication), then run an even-odd replica-exchange round where the
temperature ladder runs ALONG THE DEVICE AXIS — local chain i on device d is
rung d of ladder i — so a swap is one `lax.ppermute` neighbor exchange of
(cut_count, beta) vectors plus a select, riding ICI. Telemetry (aggregate
accepts) reduces with `lax.psum`.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..graphs.lattice import DeviceGraph
from ..kernel import board as kboard
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..state.chain_state import ChainState
from .mesh import CHAINS_AXIS


def _params_spec(sharded: bool):
    p = P(CHAINS_AXIS) if sharded else P()
    return StepParams(log_base=p, beta=p, pop_lo=p, pop_hi=p,
                      label_values=P(), anneal_t0=P(), anneal_ramp=P(),
                      anneal_beta_max=P())


def _even_odd_perms(n_dev: int):
    perms = []
    for parity in (0, 1):
        perm = []
        for i in range(n_dev):
            j = i + 1 if i % 2 == parity else i - 1
            if 0 <= j < n_dev:
                perm.append((i, j))
        perms.append(tuple(perm))
    return perms


def _swap_round(key, params, cut_count, parity, n_dev, perms):
    """One even-odd replica-exchange round along the device axis: exchange
    (cut_count, beta) with the ppermute neighbor, Metropolis-accept the
    beta swap per chain slot from a shared replicated key, return the
    updated params and the per-slot accept mask's sum."""
    idx = jax.lax.axis_index(CHAINS_AXIS)
    partner_exists = jnp.where(
        idx % 2 == parity, idx + 1 < n_dev, idx - 1 >= 0)
    cut = cut_count.astype(jnp.float32)
    beta = params.beta
    cut_p = jax.lax.ppermute(cut, CHAINS_AXIS, perms[parity])
    beta_p = jax.lax.ppermute(beta, CHAINS_AXIS, perms[parity])
    log_a = params.log_base * (beta - beta_p) * (cut - cut_p)
    # shared uniform per unordered pair (pair id = lower device index),
    # computed identically on both partners from the replicated key
    pair_id = jnp.where(idx % 2 == parity, idx, idx - 1)
    k = jax.random.fold_in(key, parity)
    u = jax.vmap(lambda i: jax.random.uniform(
        jax.random.fold_in(k, pair_id * beta.shape[0] + i)))(
        jnp.arange(beta.shape[0]))
    accept = partner_exists & (jnp.log(jnp.maximum(u, 1e-12)) < log_a)
    new_beta = jnp.where(accept, beta_p, beta)
    return params.replace(beta=new_beta), accept.sum()


def make_train_step(dg: DeviceGraph, spec: Spec, mesh, inner_steps: int,
                    exchange: bool = True):
    """Build a jitted sharded train step:
    (key, params, states) -> (params, states, info).

    ``key`` is a replicated PRNG key for the swap rounds (chain-local
    randomness lives inside ChainState.key). Swap decisions are computed
    identically on both partners from the shared key.
    """
    if exchange and spec.anneal != "none":
        # annealed chains ignore params.beta (kernel effective_beta), so a
        # beta-exchanging ladder would swap values with no dynamical effect
        raise ValueError("replica exchange is incompatible with "
                         "Spec.anneal != 'none': swaps exchange StepParams."
                         "beta, which the annealed kernel ignores")
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    paxes = StepParams.vmap_axes()
    perms = _even_odd_perms(n_dev)

    def local_advance(params, states):
        def body(states, _):
            states = jax.vmap(
                lambda p, s: kstep.transition(dg, spec, p, s),
                in_axes=(paxes, 0))(params, states)
            states, _ = jax.vmap(
                lambda p, s: kstep.record(dg, spec, p, s),
                in_axes=(paxes, 0))(params, states)
            return states, ()
        states, _ = jax.lax.scan(body, states, None, length=inner_steps)
        return states

    pspec = _params_spec(sharded=True)
    state_spec = jax.tree.map(lambda _: P(CHAINS_AXIS), states_struct())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), pspec, state_spec),
        out_specs=(pspec, state_spec, P()),
        check_vma=False)
    def train_step(key, params, states):
        states = local_advance(params, states)
        swaps = jnp.int32(0)
        if exchange and n_dev > 1:
            params, s0 = _swap_round(key, params, states.cut_count, 0,
                                     n_dev, perms)
            params, s1 = _swap_round(key, params, states.cut_count, 1,
                                     n_dev, perms)
            swaps = s0 + s1
        info = {
            "accepts": jax.lax.psum(states.accept_count.sum(), CHAINS_AXIS),
            "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
        }
        return params, states, info

    return jax.jit(train_step)


def make_board_train_step(bg: "kboard.BoardGraph", spec: Spec, mesh,
                          inner_steps: int, exchange: bool = True):
    """The board fast path's sharded train step: advance every chain
    ``inner_steps`` yields locally with the stencil kernel (zero
    communication), then the same even-odd beta-exchange ladder along the
    device axis as ``make_train_step``. This is the multi-chip form of the
    headline benchmark workload."""
    if exchange and spec.anneal != "none":
        raise ValueError("replica exchange is incompatible with "
                         "Spec.anneal != 'none'")
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    perms = _even_odd_perms(n_dev)
    pspec = _params_spec(sharded=True)
    state_spec = jax.tree.map(lambda _: P(CHAINS_AXIS),
                              board_states_struct())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), pspec, state_spec),
        out_specs=(pspec, state_spec, P()),
        check_vma=False)
    def train_step(key, params, states):
        states, _ = kboard.run_board_chunk(bg, spec, params, states,
                                           inner_steps, collect=False)
        swaps = jnp.int32(0)
        if exchange and n_dev > 1:
            # the board loop carries cut_count incrementally, so it is the
            # current energy right after a chunk
            cuts = states.cut_count
            params, s0 = _swap_round(key, params, cuts, 0, n_dev, perms)
            params, s1 = _swap_round(key, params, cuts, 1, n_dev, perms)
            swaps = s0 + s1
        info = {
            "accepts": jax.lax.psum(states.accept_count.sum(), CHAINS_AXIS),
            "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
        }
        return params, states, info

    return jax.jit(train_step)


def states_struct():
    """A ChainState of leaf placeholders for building PartitionSpec trees."""
    return ChainState(
        key=0, assignment=0, cut=0, cut_deg=0, dist_pop=0, cut_count=0,
        b_count=0, cur_wait=0, cur_flip_node=0, t_yield=0, part_sum=0,
        last_flipped=0, num_flips=0, cut_times=0, waits_sum=0,
        move_clock=0, accept_count=0, tries_sum=0, exhausted_count=0)


def board_states_struct():
    """BoardState leaf placeholders for building PartitionSpec trees."""
    return kboard.BoardState(
        key=0, board=0, dist_pop=0, cut_count=0, cur_wait=0, wait_pending=0,
        cur_flip=0, cur_sign=0, t_yield=0, move_clock=0, part_sum=0,
        last_flipped=0,
        num_flips=0, cut_times_e=0, cut_times_s=0, waits_sum=0,
        accept_count=0, tries_sum=0, exhausted_count=0)
