"""shard_map'd training step: data-parallel chains + cross-device replica
exchange over ICI.

The full "training step" of this framework (the analogue of a model's
fwd+bwd+optimizer): advance every chain ``inner_steps`` flips locally
(zero communication), then run an even-odd replica-exchange round where the
temperature ladder runs ALONG THE DEVICE AXIS — local chain slot i forms a
ladder whose rungs START one per device. Swaps pair adjacent TEMPERATURES
(rank-based — see _swap_round), exchanged via one `lax.all_gather` of the
per-chain beta/energy scalars over ICI plus replicated selection. Telemetry
(aggregate accepts) reduces with `lax.psum`.

The board step dispatches exactly as ``sampling/board_runner`` does
(lowered -> bitboard -> int8 board; ``kernel_path`` is tagged on the step
and every event), so multi-chip runs keep the single-chip fast-path wins.
``run_sharded`` is the instrumented multi-round driver behind
``bench.py --mesh``: per-round chunk/swap_round spans and deferred chunk
events on a per-host recorder (``host_recorder``), with aggregate AND
per-chip flips/s in the run_end event and the returned info.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6: public API, replication checking spelled check_vma
    from jax import shard_map as _shard_map_fn
    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_fn
    _CHECK_KW = "check_rep"

from .. import obs
from ..graphs.lattice import DeviceGraph
from ..resilience import degrade as rdegrade
from ..resilience import faults as rfaults
from ..kernel import bitboard
from ..kernel import board as kboard
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..sampling.tempering import chain_rungs
from ..stats import accumulators as _sacc
from .mesh import CHAINS_AXIS, make_mesh, shard_chain_batch


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off (the
    swap round's data-dependent gathers defeat the static rep checker on
    both spellings of the flag)."""
    return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **{_CHECK_KW: False})


def _params_spec(sharded: bool):
    p = P(CHAINS_AXIS) if sharded else P()
    return StepParams(log_base=p, beta=p, pop_lo=p, pop_hi=p,
                      label_values=P(), anneal_t0=P(), anneal_ramp=P(),
                      anneal_beta_max=P())


def _swap_round(key, params, cut_count, parity, n_dev):
    """One even-odd replica-exchange round along the device axis.

    Pairs are ADJACENT TEMPERATURES, not adjacent devices: accepted swaps
    move betas between devices, so after a few rounds the device order no
    longer tracks the temperature order and device-neighbor pairing would
    exchange arbitrary (mostly-rejecting) temperature pairs — the same
    degradation tempering.swap_within_batch fixes in-batch. The partner
    device is therefore data-dependent, which rules out a static
    ``ppermute``; instead each device ``all_gather``s one stacked
    (3, L) f32 block of (beta, cut, log_base) scalars over ICI and
    computes the WHOLE round's outcome redundantly from the shared
    replicated key, then keeps its own row. Swap decisions are identical
    on every device by construction.

    Returns ``(params with exchanged betas, this shard's per-chain
    swap-accept mask)`` — the mask's sum matches the in-batch oracle's
    convention of counting both partners of an accepted pair.
    """
    idx = jax.lax.axis_index(CHAINS_AXIS)
    stacked = jax.lax.all_gather(
        jnp.stack([params.beta, cut_count.astype(jnp.float32),
                   params.log_base]), CHAINS_AXIS)            # (D, 3, L)
    bl = stacked[:, 0].T                                      # (L, D)
    # per-chain ENERGY log_base * cut: the swap ratio for targets
    # pi_i ∝ exp(-beta_i * lb_i * cut) is exp((b1-b2)(lb1*c1 - lb2*c2)),
    # which is symmetric under partner exchange even when log_base
    # differs per chain (the (b1-b2)*lb*(c1-c2) shortcut is not)
    el = stacked[:, 2].T * stacked[:, 1].T                    # (L, D)
    n_l = bl.shape[0]
    # rank of each device's beta within its slot's ladder (0 = coldest;
    # the same convention as the in-batch tempering.chain_rungs)
    rung_flat, pos_of_rank = chain_rungs(bl.reshape(-1), n_dev)
    rank_of_pos = rung_flat.reshape(n_l, n_dev)
    lo = (rank_of_pos % 2) == parity
    partner_rank = jnp.clip(jnp.where(lo, rank_of_pos + 1,
                                      rank_of_pos - 1), 0, n_dev - 1)
    partner_pos = jnp.take_along_axis(pos_of_rank, partner_rank, axis=1)
    valid = jnp.where(lo, rank_of_pos + 1 < n_dev, rank_of_pos >= 1)
    beta_p = jnp.take_along_axis(bl, partner_pos, axis=1)
    e_p = jnp.take_along_axis(el, partner_pos, axis=1)
    log_a = (bl - beta_p) * (el - e_p)
    # shared uniform per unordered pair: one (L, D) draw read through the
    # pair's lower rank, identical on both partners and on every device
    pair_rank = jnp.minimum(rank_of_pos, partner_rank)
    u_rank = jax.random.uniform(jax.random.fold_in(key, parity),
                                (n_l, n_dev))
    u = jnp.take_along_axis(u_rank, pair_rank, axis=1)
    accept = valid & (jnp.log(jnp.maximum(u, 1e-12)) < log_a)  # (L, D)
    new_bl = jnp.where(accept, beta_p, bl)
    my_beta = new_bl.T[idx]
    my_accept = accept.T[idx]
    return params.replace(beta=my_beta), my_accept


class _ShardedStep:
    """A sharded train step: ``(key, params, states) -> (params, states,
    info)`` with ``info = {"accepts", "swaps"}`` psum'd over the mesh.

    The shard_map in_specs for ``states`` are built lazily from the
    ACTUAL state tree on first call and cached per treedef: ChainState/
    BoardState carry trailing Optional leaves (``cut_times_se``/``sw``
    on the lowered stencil body, ``reject_count`` under a recorder) that
    change the pytree treedef, so a fixed placeholder struct would
    reject exactly the fast-path states this step exists to serve —
    the pre-rework sharded path only ever reached the int8/general
    bodies for that reason. Every leaf of both state types carries a
    leading chains axis, so the spec tree is uniformly P(chains).

    ``kernel_path`` is the body the local advance dispatches to
    ('lowered_bits' | 'lowered' | 'bitboard' | 'board' |
    'general_dense' | 'general'), tagged per shard on events by
    ``run_sharded``. ``_cache_size`` sums the underlying jit
    caches so ``obs.JitWatch`` sees compile events across treedef
    specializations too.
    """

    def __init__(self, mesh, body, kernel_path: str, n_devices: int,
                 exchange: bool):
        self.mesh = mesh
        self.kernel_path = kernel_path
        self.n_devices = n_devices
        self.exchange = exchange
        self._body = body
        self._built: dict = {}
        # packed steps (bitboard / lowered_bits) get a zero-arg rebuild
        # hook -> (body, path) so run_sharded can drop to the int8 body
        # of the same family on a kernel error (BoardState is shared:
        # the bit-pack happens inside run_board_chunk, so the carried
        # states need no rewrite); general_dense gets the same hook down
        # to the legacy general body (ChainState is shared — the dense
        # rung's conn_bits plane is stripped by the prepare hook swap)
        self.fallback = None
        # optional per-call state adapter (states -> states), applied
        # before the treedef lookup: the general_dense step uses it to
        # attach/strip the packed conn plane so callers keep handing in
        # plain init_batch states
        self.prepare = None

    def degrade(self):
        """Swap in the fallback body and clear the built cache so the
        next call recompiles on the safer path."""
        body, path, prepare = self.fallback()
        self._body, self.kernel_path, self.prepare = body, path, prepare
        self._built.clear()
        self.fallback = None

    def _build(self, states, acc=None):
        pspec = _params_spec(sharded=True)
        state_spec = jax.tree.map(lambda _: P(CHAINS_AXIS), states)
        if acc is None:
            return jax.jit(_shard_map(
                self._body, self.mesh,
                in_specs=(P(), pspec, state_spec),
                out_specs=(pspec, state_spec, P())))
        # SummaryAcc leaves with a leading chains axis shard like the
        # states; the fold counters (n/kept/stride) are replicated —
        # every device advances its replica identically
        acc_spec = jax.tree.map(
            lambda leaf: (P(CHAINS_AXIS) if getattr(leaf, "ndim", 0) >= 1
                          else P()), acc)
        return jax.jit(_shard_map(
            self._body, self.mesh,
            in_specs=(P(), pspec, state_spec, acc_spec),
            out_specs=(pspec, state_spec, acc_spec, P())))

    def __call__(self, key, params, states, acc=None):
        if self.prepare is not None:
            states = self.prepare(states)
        treedef = (jax.tree.structure(states),
                   acc is not None and jax.tree.structure(acc))
        fn = self._built.get(treedef)
        if fn is None:
            fn = self._built[treedef] = self._build(states, acc)
        if acc is None:
            return fn(key, params, states)
        return fn(key, params, states, acc)

    def _cache_size(self):
        return sum(int(f._cache_size()) for f in self._built.values())


def _check_exchange(exchange: bool, spec: Spec):
    if exchange and spec.anneal != "none":
        # annealed chains ignore params.beta (kernel effective_beta), so a
        # beta-exchanging ladder would swap values with no dynamical effect
        raise ValueError("replica exchange is incompatible with "
                         "Spec.anneal != 'none': swaps exchange StepParams."
                         "beta, which the annealed kernel ignores")


def _mesh_size(mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))


def make_train_step(dg: DeviceGraph, spec: Spec, mesh, inner_steps: int,
                    exchange: bool = True,
                    dense: bool | None = None) -> _ShardedStep:
    """Build a jitted sharded train step on the general-family kernels:
    (key, params, states) -> (params, states, info).

    ``key`` is a replicated PRNG key for the swap rounds (chain-local
    randomness lives inside ChainState.key). Swap decisions are computed
    identically on both partners from the shared key.

    ``dense`` picks the body exactly like the runner's ``kernel_path``:
    None (default) auto-selects the rejection-free ``general_dense``
    kernel when ``kernel.dense.supported`` holds, True demands it
    (build-time error otherwise), False forces the legacy gather kernel.
    The dense step's ``prepare`` hook attaches the packed conn plane to
    incoming plain states (sharding follows the chains axis), and its
    ``fallback`` drops to the legacy body with a conn-stripping prepare
    — ``run_sharded``'s same-key replay then works unchanged because
    both bodies advance a ChainState."""
    _check_exchange(exchange, spec)
    n_dev = _mesh_size(mesh)
    paxes = StepParams.vmap_axes()
    from ..kernel import dense as kdense
    if dense is None:
        use_dense = kdense.supported(dg, spec)
    elif dense:
        if not kdense.supported(dg, spec):
            raise ValueError("dense=True: kernel.dense.supported rejects "
                             "this (graph, spec)")
        use_dense = True
    else:
        use_dense = False

    def make_body(body_dense):
        trans = kdense.transition if body_dense else kstep.transition

        def local_advance(params, states, acc):
            def body(carry, _):
                states, acc = carry
                states = jax.vmap(
                    lambda p, s: trans(dg, spec, p, s),
                    in_axes=(paxes, 0))(params, states)
                states, out = jax.vmap(
                    lambda p, s: kstep.record(dg, spec, p, s),
                    in_axes=(paxes, 0))(params, states)
                if acc is not None:
                    acc = _sacc.fold_out(acc, out)
                return (states, acc), ()
            (states, acc), _ = jax.lax.scan(body, (states, acc), None,
                                            length=inner_steps)
            return states, acc

        def train_step(key, params, states, acc=None):
            states, acc = local_advance(params, states, acc)
            swaps = jnp.int32(0)
            if exchange and n_dev > 1:
                params, a0 = _swap_round(key, params, states.cut_count, 0,
                                         n_dev)
                # graftlint: disable=G002(_swap_round folds in the parity)
                params, a1 = _swap_round(key, params, states.cut_count, 1,
                                         n_dev)
                swaps = a0.sum() + a1.sum()
            info = {
                "accepts": jax.lax.psum(states.accept_count.sum(),
                                        CHAINS_AXIS),
                "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
            }
            if acc is None:
                return params, states, info
            # the telemetry allreduce: every device sees the mesh-wide
            # summary (per-chain moment leaves gathered — R-hat needs
            # every chain — pooled accepts/wsum psum'd)
            info["summary"] = _sacc.summary_allreduce(
                _sacc.summary(acc), CHAINS_AXIS)
            return params, states, acc, info
        return train_step

    step = _ShardedStep(mesh, make_body(use_dense),
                        "general_dense" if use_dense else "general",
                        n_dev, exchange)
    if use_dense:
        step.prepare = lambda states: kdense.ensure_conn_bits(dg, spec,
                                                              states)
        step.fallback = lambda: (make_body(False), "general",
                                 kdense.strip_conn_bits)
    return step


def make_board_train_step(bg: "kboard.BoardGraph", spec: Spec, mesh,
                          inner_steps: int, exchange: bool = True,
                          bits: bool | None = None) -> _ShardedStep:
    """The board fast path's sharded train step: advance every chain
    ``inner_steps`` yields locally with the stencil kernel (zero
    communication), then the same even-odd beta-exchange ladder along the
    device axis as ``make_train_step``. This is the multi-chip form of the
    headline benchmark workload.

    The local advance is ``kernel.board.run_board_chunk``, so the body
    dispatch is board_runner's: surgical/interface stencils run the
    lowered family (packed ``lowered_bits`` where
    ``bitboard.supported_lowered`` holds, int8 ``lowered`` otherwise),
    plain grids the bit-board body where supported, int8 otherwise.
    ``bits`` forces the packed/int8 choice within the active family
    exactly like the runner's flag (None = auto); the selected body is
    exposed as ``step.kernel_path``. Invalid forcings fail here, at
    build time, with ``run_board_chunk``'s messages — not at first
    dispatch.
    """
    _check_exchange(exchange, spec)
    n_dev = _mesh_size(mesh)
    lowered = bg.surgical or spec.record_interface
    if bits:
        if lowered:
            if not bitboard.supported_lowered(bg, spec):
                raise ValueError("bits=True: workload not supported by "
                                 "the packed lowered body (see "
                                 "bitboard.supported_lowered); "
                                 "bits=False selects the int8 'lowered' "
                                 "body")
        else:
            bits_ok = (bitboard.supported_pair(bg, spec)
                       if spec.proposal == "pair"
                       else bitboard.supported(bg, spec))
            if not bits_ok:
                raise ValueError("bits=True: workload not supported by "
                                 "the bit-board body (see "
                                 "bitboard.supported / supported_pair)")
    kernel_path = kboard.body_for(bg, spec, bits)

    def make_body(body_bits):
        def train_step(key, params, states, acc=None):
            if acc is None:
                states, _ = kboard.run_board_chunk(
                    bg, spec, params, states, inner_steps, collect=False,
                    bits=body_bits)
            else:
                states, _, acc = kboard.run_board_chunk(
                    bg, spec, params, states, inner_steps, collect=False,
                    bits=body_bits, acc=acc)
            swaps = jnp.int32(0)
            if exchange and n_dev > 1:
                # the board loop carries cut_count incrementally, so it is
                # the current energy right after a chunk
                cuts = states.cut_count
                params, a0 = _swap_round(key, params, cuts, 0, n_dev)
                # graftlint: disable=G002(_swap_round folds in the parity)
                params, a1 = _swap_round(key, params, cuts, 1, n_dev)
                swaps = a0.sum() + a1.sum()
            info = {
                "accepts": jax.lax.psum(states.accept_count.sum(),
                                        CHAINS_AXIS),
                "swaps": jax.lax.psum(swaps, CHAINS_AXIS),
            }
            if acc is None:
                return params, states, info
            info["summary"] = _sacc.summary_allreduce(
                _sacc.summary(acc), CHAINS_AXIS)
            return params, states, acc, info
        return train_step

    step = _ShardedStep(mesh, make_body(bits), kernel_path, n_dev,
                        exchange)
    if kernel_path in ("bitboard", "lowered_bits"):
        step.fallback = lambda: (make_body(False),
                                 kboard.body_for(bg, spec, False), None)
    return step


def run_sharded(step: _ShardedStep, params, states, *, rounds: int,
                inner_steps: int, key=None, recorder=None,
                analytics=None):
    """Drive a sharded train step for ``rounds`` rounds of
    ``inner_steps`` local transitions + one replica-exchange step each.
    Returns ``(params, states, info)`` with a HOST info dict: totals,
    aggregate ``flips_per_s`` AND ``flips_per_s_per_chip`` (the
    cross-device-count regression metric), swap/accept counts, and the
    winning ``kernel_path``.

    Telemetry contract (mirrors the board runner's): with a falsy
    recorder the loop enqueues rounds back-to-back with NO host syncs
    until the final info readback; with a recorder it emits run_start /
    per-round chunk events / metrics_snapshot / run_end, wraps each
    round in a live ``chunk`` span with a ``swap_round`` marker span
    nested inside, and defers every device readback (accepts, swaps) to
    the run-end sync — per-round walls are dispatch intervals, the
    run_end wall is authoritative. Pass ``host_recorder(path)`` so
    multi-host meshes write ``events.host<K>.jsonl`` streams that
    ``tools/trace_export.py`` merges onto per-host pids.

    ``analytics``: a ``stats.accumulators.DeviceAnalytics`` (no series
    keys — the sharded fold keeps only moments/buffer). The fold runs
    inside the sharded body; every round allreduces the summary (per-
    chain moment leaves all_gather'd over the mesh — R-hat needs every
    chain — pooled counters psum'd) into a device ref that is read back
    ONCE at the run-end sync as ``info['summary']`` with mesh-wide
    ``(C_total,)`` per-chain moments. Deferred like every other
    readback: the pipelined dispatch stays pipelined.
    """
    rec = obs.resolve_recorder(recorder)
    if key is None:
        key = jax.random.PRNGKey(0)
    n_chains = int(states.accept_count.shape[0])
    n_dev = step.n_devices
    total = rounds * inner_steps
    if rec:
        rec.emit("run_start", runner="sharded", path=step.kernel_path,
                 chains=n_chains, n_steps=total, chunk=inner_steps,
                 devices=n_dev, exchange=step.exchange)
        watch = obs.JitWatch(step, f"sharded.{step.kernel_path}")
        met = obs.MetricsRegistry()
        run_span = obs.span(rec, "run:sharded", annotate=True,
                            kernel_path=step.kernel_path, chains=n_chains,
                            n_steps=total, devices=n_dev).begin()
        acc0 = states.accept_count
        chunk_meta: list = []
    t_run0 = t_prev = time.perf_counter()

    swaps_dev = jnp.int32(0)
    info_dev = {}
    acc_dev = None
    if analytics is not None:
        if analytics.acc.series:
            raise ValueError("run_sharded analytics must carry no series "
                             "keys: series index per fold, which the "
                             "replicated fold counters cannot shard")
        acc_dev = analytics.acc
    for r in range(rounds):
        key, kr = jax.random.split(key)
        if rec:
            csp = obs.span(rec, "chunk", kernel_path=step.kernel_path,
                           steps=inner_steps, round=r).begin()
        try:
            rfaults.fault_point("compile", path=step.kernel_path, round=r)
            if acc_dev is None:
                params, states, info_dev = step(kr, params, states)
            else:
                params, states, acc_dev, info_dev = step(
                    kr, params, states, acc_dev)
        except Exception as e:
            if not rdegrade.is_kernel_error(e) or step.fallback is None:
                raise
            prev_path = step.kernel_path
            step.degrade()
            rdegrade.record_degradation(rec, prev_path, step.kernel_path,
                                        reason=rdegrade.describe_error(e),
                                        round=r)
            # same key on purpose: the failed dispatch never consumed it,
            # and the fallback body must replay the identical round
            if acc_dev is None:
                params, states, info_dev = step(
                    kr, params, states)  # graftlint: disable=G002(retry replays the unconsumed key)
            else:
                params, states, acc_dev, info_dev = step(
                    kr, params, states, acc_dev)  # graftlint: disable=G002(retry replays the unconsumed key)
        # device-side accumulation: no host sync until the run-end readback
        swaps_dev = swaps_dev + info_dev["swaps"]
        if rec:
            watch.poll(rec, round=r)
            if step.exchange and n_dev > 1:
                # zero-duration marker: the exchange executes fused inside
                # the step's dispatch, so the span records placement
                # (inside this round's chunk span), not a host-measurable
                # duration
                obs.emit_span_at(rec, "swap_round", time.time(), 0.0,
                                 round=r, parities=[0, 1])
            now = time.perf_counter()
            wall = now - t_prev
            t_prev = now
            csp.end(wall_s=wall)
            # readbacks deferred: stash the device refs, flush after the
            # run-end sync (the pipelined dispatch stays pipelined)
            chunk_meta.append((wall, states.accept_count,
                               info_dev["swaps"], time.time()))
            met.observe("chunk_wall_s", wall)
            met.observe("flips_per_s",
                        n_chains * inner_steps / max(wall, 1e-12))
            met.inc("chunks")
            met.inc("flips", n_chains * inner_steps)
            met.set("done", (r + 1) * inner_steps)
            met.notify(rec)

    jax.block_until_ready(states.accept_count)
    if getattr(states, "conn_bits", None) is not None:
        # the dense step's prepare hook attached the conn plane; hand
        # the caller's treedef back (checkpoints, downstream jits)
        states = states.replace(conn_bits=None)
    wall_total = time.perf_counter() - t_run0
    flips = n_chains * total
    fps = flips / max(wall_total, 1e-12)
    accepts = int(np.asarray(info_dev["accepts"])) if info_dev else 0
    swaps = int(np.asarray(swaps_dev))
    info = {
        "accepts": accepts,
        "swaps": swaps,
        "rounds": rounds,
        "inner_steps": inner_steps,
        "chains": n_chains,
        "devices": n_dev,
        "kernel_path": step.kernel_path,
        "flips": flips,
        "wall_s": wall_total,
        "flips_per_s": fps,
        "flips_per_s_per_chip": fps / max(n_dev, 1),
    }
    rb_total = (int(np.asarray(swaps_dev).nbytes)
                + (int(np.asarray(info_dev["accepts"]).nbytes)
                   if info_dev else 0))
    if acc_dev is not None:
        # ONE summary readback for the whole run: the mesh-wide
        # allreduced summary from the final round
        summ = {k: np.asarray(v) for k, v in info_dev["summary"].items()}
        info["summary"] = summ
        rb_total += sum(v.nbytes for v in summ.values())
        analytics.update(acc_dev, total)
        analytics.readback_bytes += rb_total
    info["readback_bytes"] = rb_total
    if rec:
        last_acc = int(np.asarray(acc0, np.int64).sum())
        acc_start = last_acc
        done = 0
        for wall, acc_ref, swaps_ref, ts in chunk_meta:
            acc = int(np.asarray(acc_ref, np.int64).sum())
            done += inner_steps
            rec.emit("chunk", ts=ts, runner="sharded",
                     path=step.kernel_path, steps=inner_steps,
                     chains=n_chains, flips=n_chains * inner_steps,
                     wall_s=wall,
                     flips_per_s=n_chains * inner_steps / max(wall, 1e-12),
                     accept_rate=(acc - last_acc)
                     / (n_chains * inner_steps),
                     transfer_bytes=0, hbm_history_bytes=0,
                     done=done, total=total, devices=n_dev,
                     swaps=int(np.asarray(swaps_ref)))
            last_acc = acc
        info["accept_rate"] = ((last_acc - acc_start)
                               / max(n_chains * total, 1))
        met.set("flips_per_s_per_chip", info["flips_per_s_per_chip"])
        snap = met.snapshot()
        rec.emit("metrics_snapshot", counters=snap["counters"],
                 gauges=snap["gauges"], histograms=snap["histograms"],
                 runner="sharded", path=step.kernel_path)
        rec.emit("run_end", runner="sharded", path=step.kernel_path,
                 n_yields=total, chains=n_chains, flips=flips,
                 wall_s=wall_total, flips_per_s=fps,
                 flips_per_s_per_chip=info["flips_per_s_per_chip"],
                 devices=n_dev, swaps=swaps,
                 accept_rate=info["accept_rate"], metrics=snap,
                 readback_bytes=rb_total,
                 readback_mode=("summary" if acc_dev is not None
                                else "history"))
        run_span.end(flips=flips, wall_s=wall_total)
    return params, states, info


def largest_pow2(n: int) -> int:
    """Largest power of two <= n (>= 1)."""
    if n < 1:
        raise ValueError(f"need at least one device, got {n}")
    return 1 << (int(n).bit_length() - 1)


def reshard_down(states, mesh, lost: int = 1, axis: str = CHAINS_AXIS):
    """Re-place a chain-state tree onto the surviving power-of-two
    sub-mesh after ``lost`` devices dropped out of ``mesh``. Returns
    ``(new_mesh, placed_states)``.

    The collectives (all_gather ladders, psum telemetry) assume a
    power-of-two device axis, and the chain count divides the original
    (power-of-two) mesh — so it divides every power-of-two sub-mesh
    too: shrinking never strands chains, it only deepens the per-device
    ladder. Leaves are snapshotted to host first (their old placements
    may reference the lost devices) and re-placed with the same
    leading-axis discipline as the original sharding."""
    n = _mesh_size(mesh)
    target = largest_pow2(max(1, n - max(1, int(lost))))
    if target >= n:
        raise ValueError(
            f"reshard_down: {n}-device mesh cannot shed {lost} "
            f"device(s) into a smaller power-of-two sub-mesh")
    new_mesh = make_mesh(target, axis=axis)
    host = jax.tree.map(np.asarray, states)
    return new_mesh, shard_chain_batch(new_mesh, host, axis)


def run_sharded_elastic(make_step, mesh, params, states, *, rounds: int,
                        inner_steps: int, key=None, recorder=None,
                        segment_rounds: int | None = None):
    """``run_sharded`` with elastic mesh recovery: when a segment fails
    with a device-loss error (``resilience.degrade.is_device_loss`` —
    injected ``compile`` faults stand in on CPU), the run reshards onto
    the surviving power-of-two sub-mesh and REPLAYS that segment from
    its host snapshot — the in-memory form of "resume the checkpoint on
    the survivors". ``make_step(mesh) -> _ShardedStep`` rebuilds the
    step for each mesh (telemetry re-tags itself: the resumed
    ``run_start``/``run_end`` events carry the new device count).

    Rounds run in segments of ``segment_rounds`` (default: one segment)
    with a host snapshot of (params, states) taken at each segment
    boundary — the snapshot is the recovery point, so at most one
    segment of work replays. Per-segment keys are ``fold_in(key, seg)``:
    a replayed segment reuses its own key, so the degraded run replays
    the identical segment decisions on fewer devices.

    Returns ``(params, states, info)`` where info aggregates the
    segments; after any reshard it carries ``degraded: True`` plus a
    ``mesh_degradations`` list — ``tools/bench_compare.py`` refuses to
    gate records marked this way, exactly like kernel-path
    degradations. A failure on a 1-device mesh (nothing left to shed)
    re-raises."""
    rec = obs.resolve_recorder(recorder)
    if key is None:
        key = jax.random.PRNGKey(0)
    step = make_step(mesh)
    seg_rounds = segment_rounds or rounds
    bounds = [(r, min(seg_rounds, rounds - r))
              for r in range(0, rounds, seg_rounds)]
    total_info = {"accepts": 0, "swaps": 0, "flips": 0, "wall_s": 0.0}
    degradations: list = []
    seg = 0
    while seg < len(bounds):
        start, n_rounds = bounds[seg]
        # host snapshot = the recovery point for this segment
        snap_params = jax.tree.map(np.asarray, params)
        snap_states = jax.tree.map(np.asarray, states)
        seg_key = jax.random.fold_in(key, seg)  # graftlint: disable=G002(per-segment fold_in; a replayed segment must reuse its own key)
        try:
            params, states, info = run_sharded(
                step, params, states, rounds=n_rounds,
                inner_steps=inner_steps, key=seg_key, recorder=rec)
        except Exception as e:
            n_dev = _mesh_size(step.mesh)
            if not rdegrade.is_device_loss(e) or n_dev <= 1:
                raise
            new_mesh, states = reshard_down(snap_states, step.mesh)
            params = shard_chain_batch(new_mesh, snap_params)
            to_dev = _mesh_size(new_mesh)
            reason = rdegrade.describe_error(e)
            degradations.append({"from_devices": n_dev,
                                 "to_devices": to_dev,
                                 "reason": reason, "segment": seg,
                                 "round": start})
            if rec:
                rec.emit("mesh_degraded", from_devices=n_dev,
                         to_devices=to_dev, reason=reason,
                         segment=seg, round=start)
            step = make_step(new_mesh)
            continue            # replay the same segment, same key
        total_info["accepts"] += info["accepts"]
        total_info["swaps"] += info["swaps"]
        total_info["flips"] += info["flips"]
        total_info["wall_s"] += info["wall_s"]
        seg += 1
    n_dev = _mesh_size(step.mesh)
    fps = total_info["flips"] / max(total_info["wall_s"], 1e-12)
    info = {
        **total_info,
        "rounds": rounds,
        "inner_steps": inner_steps,
        "chains": int(states.accept_count.shape[0]),
        "devices": n_dev,
        "kernel_path": step.kernel_path,
        "flips_per_s": fps,
        "flips_per_s_per_chip": fps / max(n_dev, 1),
    }
    if degradations:
        info["degraded"] = True
        info["mesh_degradations"] = degradations
    return params, states, info


def host_recorder(spec):
    """Per-host event sink for sharded runs: ``obs.from_spec`` with
    multi-host path rewriting, so each jax host appends its events and
    spans to its own ``events.host<K>.jsonl`` (concurrent appends to one
    shared file would interleave mid-line). ``tools/trace_export.py``
    merges the per-host files into a single Chrome trace, one ``pid``
    per host id parsed from the filename; ``tools/obs_report.py``
    accepts any one of them. Single-host processes get a plain
    single-file recorder — same spec, same call site either way."""
    return obs.from_spec(spec, per_host=True)
