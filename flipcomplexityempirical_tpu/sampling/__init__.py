from .runner import RunResult, run_chains, init_batch, pop_bounds

__all__ = ["RunResult", "run_chains", "init_batch", "pop_bounds"]
