from .runner import RunResult, run_chains, init_batch, pop_bounds
from .board_runner import run_board, init_board
from .pallas_runner import run_board_pallas
from .recom import recom_move

__all__ = ["RunResult", "run_chains", "init_batch", "pop_bounds",
           "run_board", "init_board", "run_board_pallas", "recom_move"]
