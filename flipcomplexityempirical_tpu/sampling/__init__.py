from .runner import RunResult, run_chains, init_batch, pop_bounds
from .board_runner import run_board, init_board
from .pallas_runner import run_board_pallas
from .recom import recom_move, run_recom
from .tempered import (TemperResult, init_tempered, run_tempered,
                       per_rung_history)
from .tempering import make_ladder_params, swap_within_batch

__all__ = ["RunResult", "run_chains", "init_batch", "pop_bounds",
           "run_board", "init_board", "run_board_pallas", "recom_move",
           "run_recom",
           "TemperResult", "init_tempered", "run_tempered",
           "per_rung_history", "make_ladder_params", "swap_within_batch"]
