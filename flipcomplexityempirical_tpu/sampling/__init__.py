from .runner import RunResult, run_chains, init_batch, pop_bounds
from .recom import recom_move

__all__ = ["RunResult", "run_chains", "init_batch", "pop_bounds",
           "recom_move"]
