"""Replica exchange over a beta (inverse-temperature) ladder.

The reference carries an annealing schedule in dead code
(grid_chain_sec11.py:88-95) and BASELINE.json lists "beta-tempered flip
chains with replica-exchange swaps across a temperature ladder" as a target
config. TPU-native design: the ladder lives along the chains axis — chain c
is rung ``c % n_rungs`` of ladder ``c // n_rungs`` — so a swap round is a
pure permutation-and-select over the batch (no gather/scatter), and a
cross-device ladder rides `lax.ppermute` over ICI (distribute/sharded.py).

Swaps exchange TEMPERATURES (the beta entries of StepParams), not states:
exchanging the cheap scalar keeps assignment tensors in place, which is the
bandwidth-optimal formulation on TPU.

Acceptance: with per-rung target pi_r(x) ∝ exp(-beta_r * log(base) * |cut(x)|),
the swap of rungs (i, j) accepts with probability
min(1, exp(log(base) * (beta_i - beta_j) * (cut_i - cut_j))).

Incompatible with ``Spec.anneal != 'none'``: the annealed kernel derives its
inverse temperature from the step counter and ignores ``StepParams.beta``,
so exchanged betas would have no dynamical effect (distribute/sharded.py
raises on this combination).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..kernel.step import StepParams


def make_ladder_params(params: StepParams, betas, n_ladders: int) -> StepParams:
    """Tile a base StepParams into (n_ladders * n_rungs) chains whose beta
    varies along the rung axis (rung fastest)."""
    betas = jnp.asarray(betas, jnp.float32)
    r = betas.shape[0]
    c = n_ladders * r
    def tile(x):
        x = jnp.asarray(x, jnp.float32)
        if x.ndim == 0:
            return jnp.broadcast_to(x, (c,))
        return jnp.broadcast_to(x[:1], (c,))
    return StepParams(
        log_base=tile(params.log_base),
        beta=jnp.tile(betas, n_ladders),
        pop_lo=tile(params.pop_lo),
        pop_hi=tile(params.pop_hi),
        label_values=params.label_values,
        anneal_t0=params.anneal_t0,
        anneal_ramp=params.anneal_ramp,
        anneal_beta_max=params.anneal_beta_max,
    )


def swap_within_batch(key, states, params: StepParams,
                      n_rungs: int, parity: int, spec=None):
    """One even-odd swap round inside a batch laid out (ladders, rungs).

    ``parity`` 0 pairs rungs (0,1),(2,3),...; parity 1 pairs (1,2),(3,4),...
    Returns (params with exchanged betas, swap-accept mask) — states are
    untouched by design. Pass the chains' ``Spec`` so the annealing
    incompatibility (module docstring) is caught at the misuse site.

    ``states`` may be the general path's ChainState or the board path's
    BoardState: only the batch size and the carried per-chain
    ``cut_count`` (the energy) are read.
    """
    if spec is not None and spec.anneal != "none":
        raise ValueError("replica exchange is incompatible with Spec.anneal "
                         "!= 'none': the annealed kernel ignores "
                         "StepParams.beta, so swapped betas have no effect")
    c = states.cut_count.shape[0]
    rung = jnp.arange(c) % n_rungs
    # partner of each chain within its ladder (identity at ladder edges)
    lo = (rung % 2) == (parity % 2)
    partner = jnp.where(lo, jnp.arange(c) + 1, jnp.arange(c) - 1)
    valid_pair = jnp.where(
        lo, rung + 1 < n_rungs, (rung >= 1) & (rung % 2 == (1 - parity % 2)))
    # guard ladder boundaries and batch edges
    partner = jnp.clip(partner, 0, c - 1)
    same_ladder = (jnp.arange(c) // n_rungs) == (partner // n_rungs)
    valid_pair = valid_pair & same_ladder

    cut = states.cut_count.astype(jnp.float32)
    beta = params.beta
    lb = params.log_base
    log_a = lb * (beta - beta[partner]) * (cut - cut[partner])
    # one shared uniform per unordered pair: draw at the lower index
    pair_id = jnp.minimum(jnp.arange(c), partner)
    u = jax.random.uniform(key, (c,))
    u_pair = u[pair_id]
    accept = valid_pair & (jnp.log(jnp.maximum(u_pair, 1e-12)) < log_a)

    new_beta = jnp.where(accept, beta[partner], beta)
    return params.replace(beta=new_beta), accept
