"""Replica exchange over a beta (inverse-temperature) ladder.

The reference carries an annealing schedule in dead code
(grid_chain_sec11.py:88-95) and BASELINE.json lists "beta-tempered flip
chains with replica-exchange swaps across a temperature ladder" as a target
config. TPU-native design: the ladder lives along the chains axis — chain c
is rung ``c % n_rungs`` of ladder ``c // n_rungs`` — so a swap round is a
pure permutation-and-select over the batch (no gather/scatter), and a
cross-device ladder rides a scalar `lax.all_gather` over ICI with
rank-paired replicated selection (distribute/sharded.py).

Swaps exchange TEMPERATURES (the beta entries of StepParams), not states:
exchanging the cheap scalar keeps assignment tensors in place, which is the
bandwidth-optimal formulation on TPU.

Acceptance: with per-rung target pi_r(x) ∝ exp(-beta_r * log(base) * |cut(x)|),
the swap of rungs (i, j) accepts with probability
min(1, exp(log(base) * (beta_i - beta_j) * (cut_i - cut_j))).

Incompatible with ``Spec.anneal != 'none'``: the annealed kernel derives its
inverse temperature from the step counter and ignores ``StepParams.beta``,
so exchanged betas would have no dynamical effect (distribute/sharded.py
raises on this combination).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..kernel.step import StepParams


def make_ladder_params(params: StepParams, betas, n_ladders: int) -> StepParams:
    """Tile a base StepParams into (n_ladders * n_rungs) chains whose beta
    varies along the rung axis (rung fastest)."""
    betas = jnp.asarray(betas, jnp.float32)
    r = betas.shape[0]
    c = n_ladders * r
    def tile(x):
        x = jnp.asarray(x, jnp.float32)
        if x.ndim == 0:
            return jnp.broadcast_to(x, (c,))
        return jnp.broadcast_to(x[:1], (c,))
    return StepParams(
        log_base=tile(params.log_base),
        beta=jnp.tile(betas, n_ladders),
        pop_lo=tile(params.pop_lo),
        pop_hi=tile(params.pop_hi),
        label_values=params.label_values,
        anneal_t0=params.anneal_t0,
        anneal_ramp=params.anneal_ramp,
        anneal_beta_max=params.anneal_beta_max,
    )


def chain_rungs(beta, n_rungs: int):
    """Per-chain rung = rank of the chain's CURRENT beta within its
    ladder, rank 0 = largest beta (coldest). Swaps move betas between
    chains, so a chain's rung follows its temperature, not its batch
    position; ties (equal betas) fall back to position order."""
    c = beta.shape[0]
    b_lr = beta.reshape(c // n_rungs, n_rungs)
    pos_of_rank = jnp.argsort(-b_lr, axis=1, stable=True)   # (L, R)
    rank_of_pos = jnp.argsort(pos_of_rank, axis=1, stable=True)
    return rank_of_pos.reshape(-1), pos_of_rank


def swap_within_batch(key, states, params: StepParams,
                      n_rungs: int, parity: int, spec=None):
    """One even-odd swap round inside a batch laid out (ladders, rungs).

    Pairs are ADJACENT TEMPERATURES (rung = rank of each chain's current
    beta within its ladder, coldest first), the standard ladder scheme:
    ``parity`` 0 pairs rungs (0,1),(2,3),...; parity 1 pairs (1,2),...
    Pairing by batch position instead would exchange arbitrary
    temperature pairs once betas have permuted — still a valid MCMC move,
    but with vanishing acceptance between distant rungs and mislabeled
    diagnostics. Returns (params with exchanged betas, swap-accept mask)
    — states are untouched by design. Pass the chains' ``Spec`` so the
    annealing incompatibility (module docstring) is caught at the misuse
    site.

    ``states`` may be the general path's ChainState or the board path's
    BoardState: only the batch size and the carried per-chain
    ``cut_count`` (the energy) are read.
    """
    if spec is not None and spec.anneal != "none":
        raise ValueError("replica exchange is incompatible with Spec.anneal "
                         "!= 'none': the annealed kernel ignores "
                         "StepParams.beta, so swapped betas have no effect")
    c = states.cut_count.shape[0]
    beta = params.beta
    rung, pos_of_rank = chain_rungs(beta, n_rungs)
    ladder = jnp.arange(c) // n_rungs
    # partner of each chain = the chain holding the adjacent rung of the
    # same ladder (identity at ladder edges)
    lo = (rung % 2) == (parity % 2)
    partner_rank = jnp.clip(jnp.where(lo, rung + 1, rung - 1),
                            0, n_rungs - 1)
    partner = (ladder * n_rungs
               + jnp.take_along_axis(
                   pos_of_rank, partner_rank.reshape(-1, n_rungs), axis=1
               ).reshape(-1))
    valid_pair = jnp.where(
        lo, rung + 1 < n_rungs, (rung >= 1) & (rung % 2 == (1 - parity % 2)))

    # per-chain ENERGY log_base * cut: exp((b1-b2)(lb1*c1 - lb2*c2)) is
    # the correct swap ratio and stays partner-symmetric even if
    # log_base differs per chain (the lb*(b1-b2)*(c1-c2) shortcut does
    # not — partners would disagree on the same shared uniform)
    energy = params.log_base * states.cut_count.astype(jnp.float32)
    log_a = (beta - beta[partner]) * (energy - energy[partner])
    # one shared uniform per unordered pair: draw at the lower index
    pair_id = jnp.minimum(jnp.arange(c), partner)
    u = jax.random.uniform(key, (c,))
    u_pair = u[pair_id]
    accept = valid_pair & (jnp.log(jnp.maximum(u_pair, 1e-12)) < log_a)

    new_beta = jnp.where(accept, beta[partner], beta)
    return params.replace(beta=new_beta), accept
