"""Single-device replica-exchange orchestration: chunked advance + swaps.

``run_tempered`` composes the chunked chain runners (general and board
paths) with ``tempering.swap_within_batch`` into the run loop the sharded
train steps (distribute/sharded.py) fuse on-device: advance every chain
``swap_every`` transitions, then one even-odd swap round with alternating
parity. Temperatures (StepParams.beta) are exchanged, not states, so the
orchestration is a pure params update between chunks — the chunk kernels
recompile for nothing (beta is a traced per-chain array).

The batch is laid out (ladders, rungs): chain c is rung ``c % n_rungs``
of ladder ``c // n_rungs`` (tempering.make_ladder_params). Per-round
diagnostics accumulate on host: swap attempts/accepts per adjacent rung
pair, and the per-round beta assignment (``beta_hist``) from which
``per_rung_history`` reconstructs rung-r trajectories — after a swap the
physical rung wanders between chains, so per-chain histories alone cannot
answer "what did the cold chain do".

Capability target: BASELINE.json config 4 ("beta-tempered flip chains
with replica-exchange swaps across a temperature ladder"); the reference
itself carries only a dead annealing schedule (grid_chain_sec11.py:88-95).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..kernel import board as kboard
from ..kernel.step import Spec, StepParams
from . import board_runner, runner
from .runner import thin_outs
from .tempering import make_ladder_params, swap_within_batch


def init_tempered(graph, assignment, *, betas, n_ladders: int, seed: int,
                  spec: Spec, base: float, pop_tol: float):
    """Build (handle, states, ladder params) for ``run_tempered``:
    C = n_ladders * len(betas) chains laid out rung-fastest, routed to the
    board fast path when ``board.supports`` holds."""
    c = n_ladders * len(tuple(betas))
    if kboard.supports(graph, spec):
        handle, states, params = board_runner.init_board(
            graph, assignment, n_chains=c, seed=seed, spec=spec,
            base=base, pop_tol=pop_tol)
    else:
        handle, states, params = runner.init_batch(
            graph, assignment, n_chains=c, seed=seed, spec=spec,
            base=base, pop_tol=pop_tol)
    return handle, states, make_ladder_params(params, betas, n_ladders)


@dataclasses.dataclass
class TemperResult:
    """RunResult plus the ladder diagnostics."""
    state: object                # final chain state (device)
    history: dict                # name -> (C, T') recorded history
    waits_total: np.ndarray      # f64 (C,)
    n_yields: int
    params: StepParams           # final params (exchanged betas)
    betas: np.ndarray            # (n_rungs,) the ladder, rung 0 first
    n_rungs: int
    swap_every: int
    record_every: int
    general_initial: bool        # general path: extra initial record at t=0
    beta_hist: np.ndarray        # (n_rounds, C) beta of chain c in round r
    swap_attempts: np.ndarray    # (n_rungs-1,) pair (r, r+1) attempts
    swap_accepts: np.ndarray     # (n_rungs-1,) accepted exchanges
    end_parity: int = 0          # swap parity a continuation starts from
    end_swap_key: object = None  # PRNG key a continuation starts from

    def host_state(self):
        return jax.tree.map(np.asarray, self.state)

    def swap_rates(self) -> np.ndarray:
        with np.errstate(invalid="ignore"):
            return self.swap_accepts / np.maximum(self.swap_attempts, 1)


def _host_rungs(beta, n_rungs: int) -> np.ndarray:
    """numpy mirror of tempering.chain_rungs: per-chain rank of the
    CURRENT beta within its ladder, rank 0 = coldest (largest beta)."""
    b_lr = np.asarray(beta).reshape(-1, n_rungs)
    pos_of_rank = np.argsort(-b_lr, axis=1, kind="stable")
    return np.argsort(pos_of_rank, axis=1, kind="stable").reshape(-1)


def _accumulate_swaps(accept_mask, rungs, n_rungs, parity,
                      attempts, accepts, n_ladders):
    """Host-side per-pair bookkeeping for one swap round. ``rungs`` is
    the pre-swap rank assignment (a chain's rung follows its current
    temperature). Pair (r, r+1) is active when r % 2 == parity; the
    accept mask is symmetric, so the lower rung's entries count each
    exchanged pair once."""
    for r in range(n_rungs - 1):
        if r % 2 != parity % 2:
            continue
        attempts[r] += n_ladders
        accepts[r] += int(accept_mask[rungs == r].sum())


def run_tempered(graph_handle, spec: Spec, params: StepParams, states,
                 n_steps: int, *, betas, n_ladders: int,
                 swap_every: int, swap_seed: int = 0,
                 record_history: bool = True, record_every: int = 1,
                 bits: Optional[bool] = None,
                 segment: bool = False, record_initial: bool = True,
                 start_parity: int = 0, swap_key=None,
                 recorder=None) -> TemperResult:
    """Run C = n_ladders * len(betas) chains for ``n_steps`` yields with a
    replica-exchange round every ``swap_every`` transitions.

    ``graph_handle`` is the DeviceGraph (general path) or BoardGraph
    (board path — chosen by the type of ``states``). ``params`` must
    already carry the ladder betas (tempering.make_ladder_params).
    ``spec.anneal`` must be 'none' (swap_within_batch raises otherwise).

    Yield/record semantics match run_chains / run_board exactly at
    swap_every = n_steps - 1 (one round, no swap effect); the final
    partial round is advanced without a trailing swap.

    Checkpoint-segment composition (the experiment driver's temper
    checkpointing): call with ``segment=True`` for every non-final slice
    — ``n_steps`` then counts TRANSITIONS (the board path's final record
    and the trailing-swap omission are deferred to the final slice), a
    between-segment swap still fires after the last round, and the
    continuation resumes with ``record_initial=False`` (general path),
    ``start_parity=result.end_parity``, ``swap_key=result.end_swap_key``,
    and the returned ``params``. Segments must be multiples of
    ``swap_every``.

    ``recorder``: an obs.Recorder emits run_start / one ``chunk`` event
    per swap round (with the round index) / compile / run_end. The
    per-round accept readback rides the round boundary this
    orchestration already synchronizes at (``_host_rungs`` pulls beta to
    host every swap round); the NullRecorder path is unchanged.
    """
    betas = np.asarray(betas, np.float64)
    n_rungs = betas.shape[0]
    is_board = isinstance(states, kboard.BoardState)
    c = states.cut_count.shape[0]
    if c != n_ladders * n_rungs:
        raise ValueError(f"batch size {c} != n_ladders*n_rungs "
                         f"{n_ladders}*{n_rungs}")
    if swap_every < 1:
        raise ValueError("swap_every must be >= 1")
    if record_every > 1 and swap_every % record_every:
        raise ValueError("record_every must divide swap_every so the "
                         "record grid survives round boundaries")
    if segment and n_steps % swap_every:
        raise ValueError("a checkpoint segment must be a whole number of "
                         "swap rounds (n_steps % swap_every == 0)")
    attempts = np.zeros(n_rungs - 1, np.int64)
    accepts = np.zeros(n_rungs - 1, np.int64)
    beta_rows = []
    key = (swap_key if swap_key is not None
           else jax.random.PRNGKey(swap_seed))

    hist_parts: dict = {}
    waits_total = np.asarray(states.waits_sum, np.float64).copy()
    states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))
    pending: list = []

    def collect(outs, offset):
        # graftlint: disable=G014(ladder history is host-assembled by design; bytes flow into rb_total via the returned dict_nbytes)
        outs = jax.tree.map(np.asarray,
                            thin_outs(outs, record_every, offset=offset))
        for k, v in outs.items():
            hist_parts.setdefault(k, []).append(v.T)
        return obs.dict_nbytes(outs), outs

    transitions = n_steps if segment else n_steps - 1
    rec = obs.resolve_recorder(recorder)
    path = (kboard.body_for(graph_handle, spec, bits) if is_board
            else "general")
    had_rej = states.reject_count is not None
    if rec and not had_rej:
        states = states.replace(reject_count=jnp.zeros((c, 4), jnp.int32))
    if rec:
        chunk_fn = kboard.run_board_chunk if is_board else runner._run_chunk
        watch = obs.JitWatch(
            chunk_fn, ("board.run_board_chunk" if is_board
                       else "runner._run_chunk"))
        rec.emit("run_start", runner="tempered", chains=c,
                 n_steps=n_steps, chunk=swap_every, n_rungs=n_rungs,
                 n_ladders=n_ladders, swap_every=swap_every,
                 segment=segment, record_history=record_history,
                 record_every=record_every,
                 path=path)
        t_run0 = t_prev = time.perf_counter()
        last_acc = int(np.asarray(states.accept_count, np.int64).sum())
        acc_start, transfer_total = last_acc, 0
        rb_total = 0
        last_rej = np.asarray(states.reject_count, np.int64).sum(axis=0)
        last_tries = int(np.asarray(states.tries_sum, np.int64).sum())
        # one monitor across the whole ladder: R-hat/ESS here mix rungs
        # (hot chains explore wider), so read the diag stream as a
        # health signal, not a cold-chain convergence certificate
        mon = obs.ChainMonitor(rec, total=transitions, path=path,
                               runner="tempered")
        met = obs.MetricsRegistry()
        run_span = obs.span(rec, "run:tempered", annotate=True,
                            kernel_path=path, chains=c,
                            n_steps=n_steps).begin()
    done = 0
    parity = start_parity
    if not is_board and record_initial:
        states, out0 = runner._record_initial(
            graph_handle, spec, params, states)
        if record_history:
            if rec:
                rec.emit("transfer", what="initial_record",
                         bytes=obs.dict_nbytes(out0))
            for k, v in out0.items():
                hist_parts.setdefault(k, []).append(np.asarray(v)[:, None])
    while done < transitions:
        this = min(swap_every, transitions - done)
        beta_rows.append(np.asarray(params.beta, np.float32))
        if rec:
            csp = obs.span(rec, "chunk", annotate=True, kernel_path=path,
                           steps=this, done=done,
                           round=len(beta_rows) - 1).begin()
        if is_board:
            states, outs = kboard.run_board_chunk(
                graph_handle, spec, params, states, this,
                collect=record_history, bits=bits)
        else:
            states, outs = runner._run_chunk(
                graph_handle, spec, params, states, this,
                collect=record_history)
        if rec:
            watch.poll(rec, chunk=this)
        transfer_bytes = 0
        host_outs = None
        if record_history:
            transfer_bytes, host_outs = collect(outs, 0 if is_board else
                                                record_every - 1)
        pending.append(states.waits_sum)
        states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))
        done += this
        if rec:
            # _host_rungs / the swap below synchronize every round
            # anyway; piggyback the round's accept/reject readbacks on it
            acc = int(np.asarray(states.accept_count, np.int64).sum())
            now = time.perf_counter()
            wall = now - t_prev
            t_prev = now
            transfer_total += transfer_bytes
            rej = np.asarray(states.reject_count, np.int64).sum(axis=0)
            tries = int(np.asarray(states.tries_sum, np.int64).sum())
            d = rej - last_rej
            reject = {"nonboundary": int(d[0]), "pop": int(d[1]),
                      "disconnect": int(d[2]), "metropolis": int(d[3]),
                      "accepted": acc - last_acc,
                      "proposals": tries - last_tries}
            last_rej, last_tries = rej, tries
            accept_rate = (acc - last_acc) / (c * this)
            flips_per_s = c * this / max(wall, 1e-12)
            # honest device->host traffic for this round: the history
            # block plus every counter sync the swap round piggybacks
            # (accepts, reject breakdown, tries, waits drain, beta rungs)
            readback_bytes = (
                transfer_bytes
                + int(np.asarray(states.accept_count).nbytes)
                + int(np.asarray(states.reject_count).nbytes)
                + int(np.asarray(states.tries_sum).nbytes)
                + int(np.asarray(states.waits_sum).nbytes)
                + int(np.asarray(params.beta).nbytes))
            rb_total += readback_bytes
            rec.emit("chunk", runner="tempered", path=path, steps=this,
                     chains=c,
                     flips=c * this, wall_s=wall,
                     flips_per_s=flips_per_s,
                     accept_rate=accept_rate,
                     transfer_bytes=transfer_bytes, hbm_history_bytes=0,
                     readback_bytes=readback_bytes,
                     done=done, total=transitions,
                     round=len(beta_rows) - 1, parity=parity,
                     reject=reject)
            last_acc = acc
            mon.observe_chunk(outs=host_outs, wall_s=wall,
                              flips_per_s=flips_per_s,
                              accept_rate=accept_rate, reject=reject,
                              done=done)
            csp.end(wall_s=wall, reject=reject)
            met.observe("chunk_wall_s", wall)
            met.observe("flips_per_s", flips_per_s)
            met.inc("chunks")
            met.inc("flips", c * this)
            met.inc("transfer_bytes", transfer_bytes)
            met.set("done", done)
            met.notify(rec)
        if done < transitions or segment:
            # swaps sit BETWEEN rounds only: no trailing swap on a FULL
            # run, so the final recorded yield still belongs to
            # beta_hist's last row; a checkpoint segment DOES end with
            # its between-segment swap (the continuation's rounds follow)
            if rec:
                ssp = obs.span(rec, "swap_round", parity=parity,
                               round=len(beta_rows) - 1).begin()
            key, sub = jax.random.split(key)
            rungs_now = _host_rungs(params.beta, n_rungs)
            params, acc = swap_within_batch(sub, states, params,
                                            n_rungs, parity, spec=spec)
            _accumulate_swaps(np.asarray(acc), rungs_now, n_rungs, parity,
                              attempts, accepts, n_ladders)
            parity ^= 1
            if rec:
                ssp.end()

    if rec and not had_rej:
        # drop the telemetry-enabled counters so the returned state (and
        # the finalize jit below) keeps the caller's treedef
        states = states.replace(reject_count=None)
    if is_board and not segment:
        res = board_runner.finalize_board_run(
            graph_handle, spec, params, states, hist_parts, waits_total,
            pending, record_history, n_steps, record_every, recorder=rec)
        states, history, waits_total = res.state, res.history, \
            res.waits_total
    else:
        for w in pending:
            waits_total += np.asarray(w, np.float64)
        history = ({k: np.concatenate(v, axis=1)
                    for k, v in hist_parts.items()}
                   if record_history and hist_parts else {})

    if rec:
        wall = time.perf_counter() - t_run0
        flips = c * transitions
        snap = met.snapshot()
        rec.emit("metrics_snapshot", counters=snap["counters"],
                 gauges=snap["gauges"], histograms=snap["histograms"],
                 runner="tempered", path=path)
        rec.emit("run_end", runner="tempered", path=path,
                 n_yields=n_steps,
                 chains=c, flips=flips, wall_s=wall,
                 flips_per_s=flips / max(wall, 1e-12),
                 accept_rate=(last_acc - acc_start) / max(flips, 1),
                 transfer_bytes=transfer_total, hbm_history_bytes=0,
                 readback_bytes=rb_total, readback_mode="history",
                 n_rounds=len(beta_rows),
                 swap_attempts=int(attempts.sum()),
                 swap_accepts=int(accepts.sum()), metrics=snap)
        run_span.end(flips=flips, wall_s=wall)

    return TemperResult(
        state=states, history=history, waits_total=waits_total,
        n_yields=n_steps, params=params, betas=betas, n_rungs=n_rungs,
        swap_every=swap_every, record_every=record_every,
        general_initial=not is_board,
        beta_hist=(np.stack(beta_rows) if beta_rows
                   else np.zeros((0, c), np.float32)),
        swap_attempts=attempts, swap_accepts=accepts,
        end_parity=parity, end_swap_key=key)


def per_rung_history(res: TemperResult, name: str) -> np.ndarray:
    """Reconstruct rung-resolved trajectories from a per-chain history:
    returns (n_rungs, n_ladders, T') where entry [r, l, t] is the value
    recorded at yield t by whichever of ladder l's chains held rung r
    then. Swaps exchange temperatures, so the physical rung-r chain hops
    between batch rows; this inverts the hop using ``beta_hist``.
    Requires the ladder's betas to be pairwise distinct; rungs are
    matched by RANK within each ladder column (rank 0 = largest beta),
    which equals exact-value matching for a fixed ladder and stays
    correct across a mid-run control reshape (control.LadderPolicy
    rewrites beta VALUES but preserves every chain's rank).
    """
    beta32 = res.betas.astype(np.float32)
    if len(set(beta32.tolist())) != res.n_rungs:
        raise ValueError("per_rung_history needs pairwise-distinct betas")
    h = np.asarray(res.history[name])                       # (C, T')
    c, t_rec = h.shape
    nl = c // res.n_rungs
    se = res.swap_every
    n_rounds = res.beta_hist.shape[0]
    # round of each recorded column: the general path records yield t > 0
    # AFTER transition t (round (t-1)//se, with the initial yield 0 in
    # round 0); board chunks record yield t BEFORE transition t+1
    # (round t//se), and the final yield lands in the last round
    yields = np.arange(t_rec) * res.record_every
    if res.general_initial:
        rounds = np.maximum(yields - 1, 0) // se
    else:
        rounds = yields // se
    rounds = np.minimum(rounds, max(n_rounds - 1, 0))

    bh3 = res.beta_hist[rounds].reshape(t_rec, nl, res.n_rungs)
    h3 = h.reshape(nl, res.n_rungs, t_rec)
    # rank of rung r within res.betas, and the position of each rank in
    # each recorded ladder column: order[t, l, k] is the row holding the
    # k-th largest beta of ladder l at column t
    rank_of_rung = np.argsort(np.argsort(-beta32, kind="stable"),
                              kind="stable")
    order = np.argsort(-bh3, axis=2, kind="stable")         # (T', nl, R)
    out = np.empty((res.n_rungs, nl, t_rec), h.dtype)
    for r in range(res.n_rungs):
        # position of rung r inside each ladder, per recorded column
        j = order[:, :, rank_of_rung[r]]                    # (T', nl)
        out[r] = np.take_along_axis(h3, j.T[:, None, :], axis=1)[:, 0]
    return out
