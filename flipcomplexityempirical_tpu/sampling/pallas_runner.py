"""Runner for the Pallas board kernel: chunked VMEM-resident execution.

Same contract as ``board_runner.run_board`` (RunResult, history keys, f64
wait accumulation, record-final epilogue); per chunk the kernel returns
its flip log and int32 cut planes, and the shared XLA pieces
(``kernel.board.apply_flip_log``, ``kernel.board.record_final``) finish
the bookkeeping. On TPU the kernel draws its own random bits
(``pltpu.prng_*``), seeded per (block, chunk) from the run seed — an
independent stream from the XLA paths, so cross-path comparisons are
statistical (as with the oracle)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..kernel import board as kboard
from ..kernel import pallas_board as pboard
from ..kernel.step import Spec, StepParams
from .board_runner import drain_waits, finalize_board_run
from .runner import RunResult, pick_chunk


def run_board_pallas(bg: kboard.BoardGraph, spec: Spec, params: StepParams,
                     state: kboard.BoardState, n_steps: int,
                     record_history: bool = True,
                     chunk: Optional[int] = None,
                     block_chains: int = 128,
                     seed: int = 0,
                     interpret: bool = False,
                     _host_bits=None) -> RunResult:
    """Run ``n_steps`` yields via the Pallas kernel. ``block_chains`` must
    divide the batch; ``seed`` scopes the kernel's PRNG streams.

    ``_host_bits(chunk_idx, t, c, n) -> (bits_plane, bits_scal)`` replaces
    the in-kernel PRNG with caller-supplied uint32 bits — the interpret
    (CPU) test path, where ``pltpu.prng_*`` is unavailable."""
    c = state.board.shape[0]
    pboard.check(spec, params, c, block_chains)
    if chunk is None:
        chunk = pick_chunk(n_steps, 512)
    nb = c // block_chains
    n = bg.n
    pop_plane, deg_plane, masks8 = pboard.make_static_inputs(bg)
    dummy_bits = jnp.zeros((1, 1), jnp.uint32)

    hist_parts: dict = {}
    waits_total = np.asarray(state.waits_sum, np.float64).copy()
    state = state.replace(waits_sum=jnp.zeros_like(state.waits_sum))
    pending_waits: list = []

    done = 0
    chunk_idx = 0
    transitions = n_steps - 1
    while done < transitions:
        this = min(chunk, transitions - done)
        # well-mixed independent per-(run, chunk, block) streams
        seeds = jnp.asarray(
            np.random.SeedSequence(entropy=(seed, chunk_idx))
            .generate_state(nb).view(np.int32))
        dist_pop, scal, ints = pboard.pack_state(state, params)
        t0 = state.t_yield
        if _host_bits is not None:
            bits_plane, bits_scal = _host_bits(chunk_idx, this, c, n)
            host_rng = True
        else:
            bits_plane = bits_scal = dummy_bits
            host_rng = False
        outs = pboard.run_pallas_chunk(
            spec, bg.h, bg.w, this, block_chains, seeds, state.board,
            pop_plane, deg_plane, masks8, dist_pop, scal, ints,
            bits_plane, bits_scal, host_rng=host_rng, interpret=interpret)
        state = pboard.unpack_state(state, bg, outs, this)
        if spec.parity_metrics:
            ps, lf, nf = kboard.apply_flip_log(
                state.part_sum, state.last_flipped, state.num_flips,
                outs[4], outs[5], t0)
            state = state.replace(part_sum=ps, last_flipped=lf,
                                  num_flips=nf)
        if record_history:
            for k, v in zip(("cut_count", "b_count", "wait", "accepts"),
                            outs[6:10]):
                hist_parts.setdefault(k, []).append(np.asarray(v).T)
        state = drain_waits(state, pending_waits)
        done += this
        chunk_idx += 1

    return finalize_board_run(bg, spec, params, state, hist_parts,
                              waits_total, pending_waits, record_history,
                              n_steps)
