"""Batched chain runner: vmap over chains, scan over steps, chunked readback.

The reference runs one chain per config in a Python loop
(grid_chain_sec11.py:366-402); here a whole batch advances per XLA step and
histories stream back to host once per chunk, keeping HBM usage flat and the
device loop free of host synchronization. Long-horizon sums (waits) are
accumulated on host in float64 from per-chunk float32 partial sums, so the
device kernel stays pure 32-bit (TPU-friendly) without precision loss over
1e5+ step runs.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..graphs.lattice import DeviceGraph, LatticeGraph
from ..state.chain_state import ChainState, init_state
from ..kernel import dense as kdense
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..resilience import degrade as rdegrade
from ..resilience import faults as rfaults
from ..stats import accumulators as _sacc


@dataclasses.dataclass
class RunResult:
    state: ChainState            # batched final state (device)
    history: dict                # name -> (C, T) array when recorded:
                                 # np.ndarray, or jax.Array under
                                 # history_device=True
    waits_total: np.ndarray      # float64 (C,) host-accumulated sum of waits
    n_yields: int

    def host_state(self):
        return jax.tree.map(np.asarray, self.state)


def pick_chunk(n_steps: int, cap: int) -> int:
    """Default scan length: at most ``cap``, snapped to a nearby divisor of
    the transition count so long runs compile a single scan length instead
    of paying a second full compile for the remainder chunk."""
    chunk = max(1, min(n_steps - 1, cap))
    total = n_steps - 1
    for d in range(chunk, max(chunk // 2, 1) - 1, -1):
        if total % d == 0:
            return d
    return chunk


def pop_bounds(graph: LatticeGraph, k: int, tol: float):
    """within_percent_of_ideal_population semantics
    (grid_chain_sec11.py:319): bounds from the ideal of the initial
    partition, inclusive."""
    ideal = float(graph.pop.sum()) / k
    return (1.0 - tol) * ideal, (1.0 + tol) * ideal


def default_label_values(k: int):
    """The reference's district labels: signed +1/-1 for 2 districts
    (grid_chain_sec11.py's cddict values), plain indices otherwise."""
    return [1, -1] if k == 2 else list(range(k))


def init_batch(graph: LatticeGraph, assignment: np.ndarray, n_chains: int,
               seed: int, spec: Spec, base: float, pop_tol: float,
               label_values=None, beta=1.0) -> tuple:
    """Build (device_graph, batched ChainState, batched StepParams)."""
    dg = graph.device()
    k = spec.n_districts
    if label_values is None:
        label_values = default_label_values(k)
    label_values = jnp.asarray(label_values, jnp.int32)
    lo, hi = pop_bounds(graph, k, pop_tol)
    params = kstep.make_params(base, lo, hi, label_values, beta=beta,
                               n_chains=n_chains)
    keys = jax.random.split(jax.random.PRNGKey(seed), n_chains)
    a0 = jnp.asarray(assignment, jnp.int8)

    if spec.geom_waits:
        def siw(key, b):
            return kstep.sample_geom_minus1(key, b, graph.n_nodes, k)
    else:
        siw = None

    def one(key):
        return init_state(dg, a0, k, key, label_values,
                          sample_initial_wait=siw, proposal=spec.proposal)

    states = jax.vmap(one)(keys)
    return dg, states, params


@functools.partial(jax.jit, static_argnames=("spec", "chunk", "collect"))
def _run_chunk(dg: DeviceGraph, spec: Spec, params: StepParams,
               states: ChainState, chunk: int, collect: bool = True,
               acc=None):
    paxes = StepParams.vmap_axes()
    # general-family body dispatch is a trace-time treedef decision: a
    # state carrying the packed conn plane runs the rejection-free dense
    # kernel, a bare state runs the legacy re-propose kernel — exactly
    # how reject_count toggles counting. The runner (and the degradation
    # hop) controls which by attaching/stripping conn_bits.
    trans = (kdense.transition if states.conn_bits is not None
             else kstep.transition)

    def body(carry, _):
        states, acc = carry
        states = jax.vmap(
            lambda p, s: trans(dg, spec, p, s),
            in_axes=(paxes, 0))(params, states)
        states, out = jax.vmap(
            lambda p, s: kstep.record(dg, spec, p, s),
            in_axes=(paxes, 0))(params, states)
        if acc is not None:
            acc = _sacc.fold_out(acc, out)
        return (states, acc), out if collect else {}

    # acc (stats.accumulators.SummaryAcc | None) rides the carry: the
    # device-resident analytics fold. None is an empty pytree node —
    # that specialization traces to the pre-analytics graph.
    (states, acc), outs = jax.lax.scan(body, (states, acc), None,
                                       length=chunk)
    if acc is not None:
        return states, outs, acc
    return states, outs


@functools.partial(jax.jit, static_argnames=("spec",))
def _record_initial(dg: DeviceGraph, spec: Spec, params: StepParams,
                    states: ChainState):
    paxes = StepParams.vmap_axes()
    return jax.vmap(lambda p, s: kstep.record(dg, spec, p, s),
                    in_axes=(paxes, 0))(params, states)


def maybe_host(outs, history_device: bool):
    """History block host copy, skipped when the history is to stay
    device-resident (shared by the general and board runners)."""
    return outs if history_device else jax.tree.map(np.asarray, outs)


def assemble_history(hist_parts, record_history: bool,
                     history_device: bool) -> dict:
    """Concatenate per-chunk history parts along T with the backend the
    ``history_device`` contract promises (jnp arrays vs numpy)."""
    if not (record_history and hist_parts):
        return {}
    xp = jnp if history_device else np
    return {k: xp.concatenate(v, axis=1) for k, v in hist_parts.items()}


def thin_outs(outs: dict, every: int, offset: Optional[int] = None):
    """Device-side stride of a chunk's (T, C) history block BEFORE host
    transfer: keeps a 1e4-chain x 1e5-step recorded run inside host RAM
    (and cuts the device->host copy) by the thinning factor. The default
    slice offset ``every - 1`` puts record-after-transition chunks on the
    global grid 0, every, 2*every, ... shared with the initial record;
    the board runner's record-before-transition chunks pass offset 0."""
    if every == 1:
        return outs
    if offset is None:
        offset = every - 1
    return {k: v[offset::every] for k, v in outs.items()}


def snap_chunk_to(chunk: int, every: int) -> int:
    """Largest multiple of ``every`` <= chunk (at least ``every``): full
    chunks must hold a whole number of record periods so every chunk
    boundary lands on the thinned grid."""
    return max(every, chunk - chunk % every)


def run_chains(dg: DeviceGraph, spec: Spec, params: StepParams,
               states: ChainState, n_steps: int,
               record_history: bool = True,
               chunk: Optional[int] = None,
               record_initial: bool = True,
               record_every: int = 1,
               history_device: bool = False,
               recorder=None,
               kernel_path: Optional[str] = None,
               analytics=None) -> RunResult:
    """Run the batched chain for ``n_steps`` yields (the first yield is the
    initial state, as the reference's ``for part in exp_chain`` sees it).

    ``record_initial=False`` continues an earlier run: the current state
    was already recorded as that run's last yield, so all ``n_steps``
    yields here are fresh transitions (checkpoint-resume path).

    ``record_every=k`` records yields 0, k, 2k, ... only (metric
    accumulators — cut_times, flip counts, waits — still advance every
    step; only the returned history is strided). When continuing a run,
    segment lengths divisible by k keep the grid uniform across segments.

    ``history_device=True`` skips the per-chunk host copy and returns the
    history as device arrays (costs (C, T_recorded) HBM per key) — the
    input to device-side diagnostics (stats.ess_device), same contract
    as the board runner's flag. On a tunneled chip the history readback
    alone dwarfed the sampling wall clock (PROFILE.md round-5 ESS
    records), and the general path serves exactly the graphs the big
    sweeps run on (sec11, frank, dual).

    ``recorder``: an obs.Recorder emits one ``run_start``, one ``chunk``
    event per executed chunk (wall time, aggregate flips/s, accept rate,
    history transfer/HBM bytes, the kernel's reject-reason breakdown), a
    ``compile`` event per fresh ``_run_chunk`` specialization (with AOT
    flops/bytes cost analysis), a ``diag`` convergence snapshot per
    chunk, ``anomaly`` events from the health thresholds, and one
    ``run_end``. The per-chunk accept/reject/timing readbacks piggyback
    on this runner's EXISTING per-chunk sync (the waits drain) — no
    extra device syncs — and the default NullRecorder skips all of it.
    Attaching a recorder enables the kernel's reject-reason counters
    (``states.reject_count``), which respecializes the jit via the
    pytree treedef; the sampled trajectories are bit-identical either
    way (counting draws no randomness).

    ``kernel_path``: which general-family body advances the chain.
    None (the default) auto-resolves like lower/dispatch.py —
    'general_dense' (the rejection-free bit-packed kernel,
    kernel/dense.py) when the (graph, spec) supports it, else the
    legacy 'general'. Pass 'general' to force the legacy oracle (bench
    races, parity tests) or 'general_dense' to demand the dense body
    (raises when unsupported). The two bodies are distribution-
    equivalent, not bit-identical, so the resolved path is tagged on
    every obs event and never swapped silently; an injected/real
    compile failure on the dense body degrades in-segment to 'general'
    (conn_bits stripped, same chunk replayed) with a
    ``kernel_path_degraded`` event + DEGRADATIONS entry.

    ``analytics``: an optional ``stats.accumulators.DeviceAnalytics``.
    When attached, its SummaryAcc rides the scan carry (every yield
    folds on device) and the per-chunk telemetry readback is the small
    summary pytree instead of the history block — pass
    ``record_history=False`` for the full summary-readback mode where
    histories never leave the device. History readback stays available
    (``record_history=True``) as the flagged oracle path. Chunk events
    carry honest ``readback_bytes`` accounting in every mode.
    """
    rec = obs.resolve_recorder(recorder)
    n_chains = states.assignment.shape[0]
    had_rej = states.reject_count is not None
    if rec and not had_rej:
        states = states.replace(
            reject_count=jnp.zeros((n_chains, 4), jnp.int32))
    if kernel_path is None:
        path = ("general_dense" if kdense.supported(dg, spec)
                else "general")
    elif kernel_path == "general_dense":
        if not kdense.supported(dg, spec):
            raise ValueError(
                "kernel_path='general_dense' demanded but "
                "kernel.dense.supported rejects this (graph, spec)")
        path = "general_dense"
    elif kernel_path == "general":
        path = "general"
    else:
        raise ValueError(f"kernel_path {kernel_path!r}: general-family "
                         f"runner takes 'general_dense' | 'general' | None")
    had_conn = states.conn_bits is not None
    if path == "general_dense":
        states = kdense.ensure_conn_bits(dg, spec, states)
    elif not had_conn:
        states = kdense.strip_conn_bits(states)
    if record_every < 1:
        raise ValueError(f"record_every must be >= 1, got {record_every}")
    if chunk is None:
        chunk = pick_chunk(n_steps + (0 if record_initial else 1), 4096)
    if record_every > 1:
        chunk = snap_chunk_to(chunk, record_every)

    if rec:
        rec.emit("run_start", runner="general", path=path,
                 chains=n_chains,
                 n_steps=n_steps, chunk=chunk,
                 record_history=record_history, record_every=record_every,
                 record_initial=record_initial,
                 history_device=history_device)
        watch = obs.JitWatch(_run_chunk, "runner._run_chunk")
        t_run0 = time.perf_counter()
        last_acc = int(np.asarray(states.accept_count, np.int64).sum())
        acc_start, hbm_bytes, transfer_total = last_acc, 0, 0
        rb_total = 0
        last_tries = int(np.asarray(states.tries_sum, np.int64).sum())
        last_rej = (np.asarray(states.reject_count, np.int64).sum(axis=0)
                    if states.reject_count is not None else None)
        mon = obs.ChainMonitor(rec, total=n_steps, path=path,
                               runner="general")
        met = obs.MetricsRegistry()
        run_span = obs.span(rec, f"run:{path}", annotate=True,
                            kernel_path=path, chains=n_chains,
                            n_steps=n_steps).begin()

    if record_initial:
        states, out0 = _record_initial(dg, spec, params, states)
        if analytics is not None:
            # the initial yield is part of the recorded grid; fold it so
            # the summary matches the history block sample-for-sample
            analytics.update(_sacc.fold_out(analytics.acc, out0), 1)
        if record_history:
            out0 = maybe_host(out0, history_device)
            hist_parts = {k: [v[:, None]] for k, v in out0.items()}
            if rec:
                nb = obs.dict_nbytes(out0)
                if history_device:
                    hbm_bytes += nb
                else:
                    transfer_total += nb
                    rec.emit("transfer", what="initial_record", bytes=nb)
        else:
            hist_parts = None
        done = 1
    else:
        hist_parts = {} if record_history else None
        done = 0
    done0 = done
    # waits accumulate on device in f32 but are drained and zeroed at every
    # chunk boundary, so the host f64 total stays exact over long horizons
    waits_total = np.asarray(states.waits_sum, np.float64).copy()
    states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))

    t_prev = time.perf_counter() if rec else None
    while done < n_steps:
        this = min(chunk, n_steps - done)
        if rec:
            # span brackets dispatch..sync; ended below after the chunk
            # event so compile/diag spans nest inside it. annotate=True
            # mirrors it into jax.profiler.TraceAnnotation.
            csp = obs.span(rec, "chunk", annotate=True,
                           kernel_path=path, steps=this,
                           done=done).begin()
        try:
            if path == "general_dense":
                # the legacy floor carries no fault point: it is the
                # ladder's terminal rung, so a persistent injected
                # compile fault (chaos: compile:always) must still let
                # the run complete there
                rfaults.fault_point("compile", path=path, done=done)
            if analytics is not None:
                states, outs, new_acc = _run_chunk(
                    dg, spec, params, states, this,
                    collect=record_history, acc=analytics.acc)
            else:
                states, outs = _run_chunk(dg, spec, params, states, this,
                                          collect=record_history)
        except Exception as e:  # noqa: BLE001 — classified just below
            if path != "general_dense" or not rdegrade.is_kernel_error(e):
                raise
            # in-segment fall-through: strip the dense-only conn plane
            # and replay this very chunk on the legacy kernel with the
            # SAME state/key (deterministic; `done` is untouched).
            rdegrade.record_degradation(
                rec, "general_dense", "general",
                rdegrade.describe_error(e), done=done)
            path = "general"
            states = kdense.strip_conn_bits(states)
            if rec:
                csp.end(degraded=True)
            continue
        if rec:
            watch.poll(rec, chunk=this,
                       cost=lambda: obs.aot_cost(
                           _run_chunk, dg, spec, params, states, this,
                           collect=record_history))
        if analytics is not None:
            # adopt the folded accumulator (device refs — no sync) and
            # advance the host mirror by the chunk's yield count
            analytics.update(new_acc, this)
        transfer_bytes = 0
        readback_bytes = 0
        host_outs = None
        if record_history:
            outs = maybe_host(thin_outs(outs, record_every), history_device)
            if not history_device:
                host_outs = outs
            if rec:
                nb = obs.dict_nbytes(outs)
                if history_device:
                    hbm_bytes += nb
                else:
                    transfer_bytes = nb
                    transfer_total += nb
                    readback_bytes += nb
        # this drain is the runner's one per-chunk sync; it reads a (C,)
        # f32 back regardless of mode, and the accounting says so
        readback_bytes += int(np.asarray(states.waits_sum).nbytes)
        waits_total += np.asarray(states.waits_sum, np.float64)
        states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))
        if record_history:
            for k, v in outs.items():
                hist_parts.setdefault(k, []).append(v.T)  # (chunk, C)->(C,)
        done += this
        if rec:
            # the waits drain above already synchronized on this chunk,
            # so the accept/reject readbacks and the wall stamp cost no
            # new sync
            acc = int(np.asarray(states.accept_count, np.int64).sum())
            readback_bytes += int(np.asarray(states.accept_count).nbytes)
            now = time.perf_counter()
            wall = now - t_prev
            t_prev = now
            reject = None
            if last_rej is not None:
                rej = np.asarray(states.reject_count, np.int64).sum(axis=0)
                tries = int(np.asarray(states.tries_sum, np.int64).sum())
                readback_bytes += (
                    int(np.asarray(states.reject_count).nbytes)
                    + int(np.asarray(states.tries_sum).nbytes))
                d = rej - last_rej
                reject = {"nonboundary": int(d[0]), "pop": int(d[1]),
                          "disconnect": int(d[2]), "metropolis": int(d[3]),
                          "accepted": acc - last_acc,
                          "proposals": tries - last_tries}
                last_rej, last_tries = rej, tries
            accept_rate = (acc - last_acc) / (n_chains * this)
            flips_per_s = n_chains * this / max(wall, 1e-12)
            summ = None
            if analytics is not None:
                pre_rb = analytics.readback_bytes
                summ = analytics.summary_to_host()
                analytics.maybe_diagnostics()
                readback_bytes += analytics.readback_bytes - pre_rb
            rb_total += readback_bytes
            rec.emit("chunk", runner="general", path=path,
                     steps=this,
                     chains=n_chains, flips=n_chains * this,
                     wall_s=wall,
                     flips_per_s=flips_per_s,
                     accept_rate=accept_rate,
                     transfer_bytes=transfer_bytes,
                     hbm_history_bytes=hbm_bytes,
                     readback_bytes=readback_bytes,
                     done=done, total=n_steps, reject=reject)
            last_acc = acc
            if summ is not None:
                mon.observe_summary(summ, rhat=analytics.rhat,
                                    ess=analytics.ess, wall_s=wall,
                                    flips_per_s=flips_per_s,
                                    accept_rate=accept_rate,
                                    reject=reject, done=done)
            else:
                mon.observe_chunk(outs=host_outs, wall_s=wall,
                                  flips_per_s=flips_per_s,
                                  accept_rate=accept_rate, reject=reject,
                                  done=done)
            csp.end(wall_s=wall, reject=reject)
            met.observe("chunk_wall_s", wall)
            met.observe("flips_per_s", flips_per_s)
            met.inc("chunks")
            met.inc("flips", n_chains * this)
            met.inc("transfer_bytes", transfer_bytes)
            met.inc("readback_bytes", readback_bytes)
            met.set("done", done)
            met.notify(rec)

    history = assemble_history(hist_parts, record_history, history_device)
    if rec:
        wall = time.perf_counter() - t_run0
        flips = n_chains * (n_steps - done0)
        met.set("hbm_history_bytes", hbm_bytes)
        snap = met.snapshot()
        rec.emit("metrics_snapshot", counters=snap["counters"],
                 gauges=snap["gauges"], histograms=snap["histograms"],
                 runner="general", path=path)
        rec.emit("run_end", runner="general", path=path,
                 n_yields=n_steps,
                 chains=n_chains, flips=flips, wall_s=wall,
                 flips_per_s=flips / max(wall, 1e-12),
                 accept_rate=(last_acc - acc_start) / max(flips, 1),
                 transfer_bytes=transfer_total,
                 hbm_history_bytes=hbm_bytes, metrics=snap,
                 readback_bytes=rb_total,
                 readback_mode=("summary" if analytics is not None
                                else "history"))
        run_span.end(flips=flips, wall_s=wall)
    if rec and not had_rej:
        # the counters were telemetry-enabled here; hand back the
        # caller's treedef (checkpoints, downstream jits) unchanged
        states = states.replace(reject_count=None)
    if not had_conn:
        # same treedef contract for the dense conn plane
        states = kdense.strip_conn_bits(states)
    return RunResult(state=states, history=history,
                     waits_total=waits_total, n_yields=n_steps)
