"""Runner for the board (stencil) fast path: chunked scan + host readback.

Mirrors ``sampling/runner.py``'s contract — same RunResult shape, same
history keys, same f64 host accumulation of waits — so callers (bench,
driver, tests) can switch between the general and board paths on a
``board.supports(graph, spec)`` check without touching downstream code.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..graphs.lattice import LatticeGraph
from ..kernel import board as kboard
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..resilience import degrade as rdegrade
from ..resilience import faults as rfaults
from ..resilience.errors import KernelPathError
from ..stats import accumulators as _sacc
from .runner import (RunResult, assemble_history, default_label_values,
                     maybe_host, pick_chunk, pop_bounds, snap_chunk_to,
                     thin_outs)


def init_board(graph: LatticeGraph, assignment: np.ndarray, n_chains: int,
               seed: int, spec: Spec, base: float, pop_tol: float,
               label_values=None, beta=1.0):
    """Build (BoardGraph, BoardState, StepParams) — the board-path analogue
    of ``runner.init_batch``."""
    if not kboard.supports(graph, spec):
        raise ValueError(
            f"board path does not support (graph={graph.name!r}, {spec})")
    if label_values is None:
        label_values = default_label_values(spec.n_districts)
    lo, hi = pop_bounds(graph, spec.n_districts, pop_tol)
    params = kstep.make_params(base, lo, hi, label_values, beta=beta,
                               n_chains=n_chains)
    bg = kboard.make_board_graph(graph)
    state = kboard.init_board_state(graph, bg, assignment, n_chains, seed,
                                    spec, params)
    return bg, state, params


def drain_waits(state, pending_waits):
    """Stash the device f32 chunk-local wait sum and zero it. The stash is
    a list of (C,) DEVICE arrays summed in f64 on host only at run end —
    keeping the f64 accumulation per chunk (a 100k-step chain's wait sum
    overflows f32 precision) WITHOUT a per-chunk host sync, so the runner
    enqueues chunks back-to-back and dispatch pipelines."""
    pending_waits.append(state.waits_sum)
    return state.replace(waits_sum=jnp.zeros_like(state.waits_sum))


def _sum_pending(waits_total, pending_waits):
    for w in pending_waits:
        waits_total += np.asarray(w, np.float64)
    return waits_total


def finalize_board_run(bg, spec, params, state, hist_parts, waits_total,
                       pending_waits, record_history, n_steps,
                       record_every: int = 1,
                       history_device: bool = False,
                       recorder=None, analytics=None) -> RunResult:
    """Shared run epilogue for the board-path runners: record the final
    yield (no trailing transition), drain waits, assemble the RunResult.
    Under thinning the final yield joins the history only when it lands
    on the record grid (its wait/bookkeeping effects apply regardless).
    ``history_device=True`` keeps the history as device arrays (for
    device-side diagnostics, stats.ess_device) instead of copying each
    chunk to host."""
    rec = obs.resolve_recorder(recorder)
    if rec:
        fsp = obs.span(rec, "finalize", annotate=True,
                       kernel_path="board").begin()
    state, out_last = kboard.record_final(bg, spec, params, state)
    if analytics is not None:
        # the final yield joins the fold exactly as it joins the history
        analytics.update(_sacc.fold_out(analytics.acc, out_last), 1)
    if record_history and (n_steps - 1) % record_every == 0:
        out_last = maybe_host(out_last, history_device)
        if rec and not history_device:
            rec.emit("transfer", what="final_record",
                     bytes=obs.dict_nbytes(out_last))
        for k, v in out_last.items():
            hist_parts.setdefault(k, []).append(v[:, None])
    state = drain_waits(state, pending_waits)
    waits_total = _sum_pending(waits_total, pending_waits)
    history = assemble_history(hist_parts, record_history, history_device)
    if rec:
        fsp.end()
    return RunResult(state=state, history=history,
                     waits_total=waits_total, n_yields=n_steps)


def _reject_dict(delta, proposals):
    """Chunk-event ``reject`` breakdown from a per-chunk (4,) counter
    delta. On the board path every step consumes exactly one proposal,
    so accepted = proposals - rejects by the kernel invariant."""
    d = [int(x) for x in delta]
    return {"nonboundary": d[0], "pop": d[1], "disconnect": d[2],
            "metropolis": d[3], "accepted": proposals - sum(d),
            "proposals": proposals}


def _emit_board_chunks(rec, chunk_meta, acc0, rej0, n_chains,
                       n_transitions, transfer_total, hbm_bytes,
                       path="board", mon=None, analytics=None):
    """Flush the deferred per-chunk telemetry of a board run. The board
    loop never syncs mid-run (waits, accept and reject counts — and in
    summary mode the per-chunk analytics summaries — are stashed as
    device refs so dispatch pipelines); those readbacks happen HERE, at
    the run-end sync that already exists, and each chunk event is
    back-stamped with its dispatch-time ``ts``. Per-chunk ``wall_s`` is
    therefore a dispatch interval — the run_end wall is the
    authoritative end-to-end time (obs.events docstring). Chunks whose
    loop iteration already synced (host history copies) carry a
    precomputed ``reject`` dict instead of a device ref.

    ``mon``/``analytics``: in summary mode each stashed summary feeds
    ``mon.observe_summary`` with the back-stamped ``ts`` (deferred
    ``diag`` events); the on-device R-hat/ESS refresh runs once, at the
    final chunk. Returns ``(accept_rate, readback_total)``."""
    last_acc = int(np.asarray(acc0, np.int64).sum())
    acc_start = last_acc
    last_rej = (np.asarray(rej0, np.int64).sum(axis=0)
                if rej0 is not None else None)
    done = 0
    rb_total = 0
    n_meta = len(chunk_meta)
    for i, (steps, wall, tb, hbm, acc_ref, rej_ref, reject, ts, summ_ref,
            rb) in enumerate(chunk_meta):
        acc = int(np.asarray(acc_ref, np.int64).sum())
        done += steps
        rb_total += rb
        if reject is None and rej_ref is not None:
            rej = np.asarray(rej_ref, np.int64).sum(axis=0)
            reject = _reject_dict(rej - last_rej, n_chains * steps)
            last_rej = rej
        rec.emit("chunk", ts=ts, runner="board", path=path, steps=steps,
                 chains=n_chains, flips=n_chains * steps, wall_s=wall,
                 flips_per_s=n_chains * steps / max(wall, 1e-12),
                 accept_rate=(acc - last_acc) / (n_chains * steps),
                 transfer_bytes=tb, hbm_history_bytes=hbm,
                 readback_bytes=rb,
                 done=done, total=n_transitions, reject=reject)
        # deferred chunk span, back-stamped over the dispatch interval
        # [ts - wall, ts]. The run span is still open at flush time, so
        # emit_span_at parents these under it — no live span objects
        # were allowed mid-run (no mid-run syncs, no mid-run emits).
        obs.emit_span_at(rec, "chunk", ts - wall, wall,
                         kernel_path=path, steps=steps, done=done,
                         end_args={"wall_s": wall, "reject": reject})
        if mon is not None and summ_ref is not None:
            rhat = ess = None
            if analytics is not None and i == n_meta - 1:
                pre = analytics.readback_bytes
                rhat, ess = analytics.maybe_diagnostics(force=True)
                rb_total += analytics.readback_bytes - pre
            mon.observe_summary(_sacc.summary_host(summ_ref), rhat=rhat,
                                ess=ess, wall_s=wall,
                                flips_per_s=n_chains * steps
                                / max(wall, 1e-12),
                                reject=reject, done=done, ts=ts)
        last_acc = acc
    accept_rate = (last_acc - acc_start) / max(n_chains * n_transitions, 1)
    return accept_rate, rb_total


def run_board_segment(bg: kboard.BoardGraph, spec: Spec,
                      params: StepParams, state: kboard.BoardState,
                      n_transitions: int,
                      record_history: bool = True,
                      chunk: Optional[int] = None,
                      bits: Optional[bool] = None,
                      record_every: int = 1,
                      history_device: bool = False,
                      recorder=None, analytics=None) -> RunResult:
    """Advance ``n_transitions`` transitions, recording the same number of
    yields (each BEFORE its transition) — and NO trailing record, so
    segments compose without duplicate boundary yields: a full run is
    segments summing to n_steps - 1 transitions plus one
    ``kboard.record_final``. ``run_board`` is exactly that composition;
    the experiment driver checkpoints between segments.
    ``history_device=True`` skips the per-chunk host copy and returns the
    history as device arrays (costs (C, T_recorded) HBM per key).

    ``recorder``: an obs.Recorder emits run_start / per-chunk / compile /
    run_end events. Telemetry preserves this runner's no-mid-run-sync
    contract: accept counts are stashed as (C,) device refs per chunk
    (like the pending waits) and read back only at run end, so enabling
    events does not serialize the pipelined dispatch.

    ``analytics``: optional ``stats.accumulators.DeviceAnalytics`` —
    its SummaryAcc rides the scan carry and folds every yield on
    device. Per-chunk summary device refs are stashed beside the accept
    refs (the no-mid-run-sync contract holds) and flushed as
    back-stamped ``diag`` events at run end; pass
    ``record_history=False`` for the full summary-readback mode where
    the history block never materializes. Chunk events carry honest
    ``readback_bytes`` in every mode."""
    rec = obs.resolve_recorder(recorder)
    if record_every < 1:
        raise ValueError(f"record_every must be >= 1, got {record_every}")
    if chunk is None:
        chunk = pick_chunk(n_transitions + 1, 2048)
    if record_every > 1:
        chunk = snap_chunk_to(chunk, record_every)

    hist_parts: dict = {}
    waits_total = np.asarray(state.waits_sum, np.float64).copy()
    state = state.replace(waits_sum=jnp.zeros_like(state.waits_sum))
    pending_waits: list = []

    n_chains = state.waits_sum.shape[0]
    # which body run_board_chunk will select (lowered / bitboard / board)
    # — tagged on every event so fallback regressions are visible in
    # scoreboards (tools/obs_report.py breaks throughput out per path)
    path = kboard.body_for(bg, spec, bits)
    had_rej = state.reject_count is not None
    if rec and not had_rej:
        state = state.replace(
            reject_count=jnp.zeros((n_chains, 4), jnp.int32))
    if rec:
        rec.emit("run_start", runner="board", path=path, chains=n_chains,
                 n_steps=n_transitions, chunk=chunk,
                 record_history=record_history, record_every=record_every,
                 history_device=history_device)
        watch = obs.JitWatch(kboard.run_board_chunk,
                             "board.run_board_chunk")
        acc0, chunk_meta, hbm_bytes, transfer_total = \
            state.accept_count, [], 0, 0
        rej0 = state.reject_count
        last_rej = np.asarray(rej0, np.int64).sum(axis=0)
        mon = obs.ChainMonitor(rec, total=n_transitions, path=path,
                               runner="board")
        met = obs.MetricsRegistry()
        run_span = obs.span(rec, "run:board", annotate=True,
                            kernel_path=path, chains=n_chains,
                            n_steps=n_transitions).begin()
        t_run0 = t_prev = time.perf_counter()

    done = 0
    while done < n_transitions:
        this = min(chunk, n_transitions - done)
        try:
            rfaults.fault_point("compile", path=path, done=done)
            if analytics is not None:
                state, outs, new_acc = kboard.run_board_chunk(
                    bg, spec, params, state, this,
                    collect=record_history, bits=bits, acc=analytics.acc)
            else:
                state, outs = kboard.run_board_chunk(
                    bg, spec, params, state, this,
                    collect=record_history, bits=bits)
        except Exception as e:
            if not rdegrade.is_kernel_error(e):
                raise
            nxt = rdegrade.next_board_body(path)
            if nxt is None:
                # no lower body shares this state layout — hand the
                # ladder back to the driver (general-kernel rerun)
                raise KernelPathError(path, e) from e
            # lowered_bits -> lowered / bitboard -> int8 board: same
            # BoardState, the bit-packing lives inside run_board_chunk,
            # so the SAME segment retries on the next body down with
            # nothing converted. Loop back (``done`` unchanged) rather
            # than retrying inline: a persistent failure then keeps
            # falling through the ladder instead of surfacing on the
            # retry.
            rdegrade.record_degradation(
                rec, path, nxt, reason=rdegrade.describe_error(e),
                done=done)
            path, bits = nxt, False
            continue
        if rec:
            watch.poll(rec, chunk=this,
                       cost=lambda: obs.aot_cost(
                           kboard.run_board_chunk, bg, spec, params,
                           state, this, collect=record_history,
                           bits=bits))
        summ_ref = None
        if analytics is not None:
            # adopt the folded accumulator and stash this chunk's small
            # summary refs — device handles only, no sync (the board
            # contract); they are read back at the run-end flush
            analytics.update(new_acc, this)
            summ_ref = analytics.summary_refs()
        transfer_bytes = 0
        host_outs = None
        if record_history:
            # board chunks record BEFORE transitioning, so block-local
            # index 0 is already on the global grid
            outs = maybe_host(thin_outs(outs, record_every, offset=0),
                              history_device)
            if not history_device:
                host_outs = outs
            if rec:
                nb = obs.dict_nbytes(outs)
                if history_device:
                    hbm_bytes += nb
                else:
                    transfer_bytes = nb
                    transfer_total += nb
            for k, v in outs.items():
                hist_parts.setdefault(k, []).append(v.T)  # (T, C) -> (C, T)
        state = drain_waits(state, pending_waits)
        done += this
        if rec:
            now = time.perf_counter()
            wall = now - t_prev
            t_prev = now
            reject = None
            if host_outs is not None:
                # the history copy above already synchronized on this
                # chunk, so the (C, 4) counter readback costs no new
                # sync; without host copies the ref is stashed and read
                # at the run-end sync like the accepts
                rej = np.asarray(state.reject_count, np.int64).sum(axis=0)
                reject = _reject_dict(rej - last_rej, n_chains * this)
                last_rej = rej
            # honest per-chunk host readback: the history block when it
            # copies, plus the (C,) waits stash and the stashed summary
            # (both sized now from shapes, read at the run-end sync)
            rb = (transfer_bytes + state.waits_sum.shape[0] * 4
                  + (_sacc.summary_nbytes(summ_ref) if summ_ref is not None
                     else 0))
            chunk_meta.append((this, wall, transfer_bytes, hbm_bytes,
                               state.accept_count, state.reject_count,
                               reject, time.time(), summ_ref, rb))
            # wall is a dispatch interval when the loop pipelines; with
            # host history copies (the common telemetry config) the copy
            # synced above and it is real chunk wall time. In summary
            # mode the monitor is fed at the run-end flush instead
            # (back-stamped diag events — no mid-run sync).
            if analytics is None:
                mon.observe_chunk(outs=host_outs, wall_s=wall,
                                  flips_per_s=n_chains * this
                                  / max(wall, 1e-12),
                                  reject=reject, done=done)
            met.observe("chunk_wall_s", wall)
            met.observe("flips_per_s", n_chains * this / max(wall, 1e-12))
            met.inc("chunks")
            met.inc("flips", n_chains * this)
            met.inc("transfer_bytes", transfer_bytes)
            met.inc("readback_bytes", rb)
            met.set("done", done)
            met.notify(rec)

    waits_total = _sum_pending(waits_total, pending_waits)
    history = assemble_history(hist_parts, record_history, history_device)
    if rec:
        wall = time.perf_counter() - t_run0
        flips = n_chains * n_transitions
        accept_rate, rb_total = _emit_board_chunks(
            rec, chunk_meta, acc0, rej0, n_chains, n_transitions,
            transfer_total, hbm_bytes, path=path, mon=mon,
            analytics=analytics)
        met.set("hbm_history_bytes", hbm_bytes)
        snap = met.snapshot()
        rec.emit("metrics_snapshot", counters=snap["counters"],
                 gauges=snap["gauges"], histograms=snap["histograms"],
                 runner="board", path=path)
        rec.emit("run_end", runner="board", path=path,
                 n_yields=n_transitions,
                 chains=n_chains, flips=flips, wall_s=wall,
                 flips_per_s=flips / max(wall, 1e-12),
                 accept_rate=accept_rate, transfer_bytes=transfer_total,
                 hbm_history_bytes=hbm_bytes, metrics=snap,
                 readback_bytes=rb_total,
                 readback_mode=("summary" if analytics is not None
                                else "history"))
        run_span.end(flips=flips, wall_s=wall)
        if not had_rej:
            state = state.replace(reject_count=None)
    return RunResult(state=state, history=history,
                     waits_total=waits_total, n_yields=n_transitions)


def run_board(bg: kboard.BoardGraph, spec: Spec, params: StepParams,
              state: kboard.BoardState, n_steps: int,
              record_history: bool = True,
              chunk: Optional[int] = None,
              bits: Optional[bool] = None,
              record_every: int = 1,
              history_device: bool = False,
              recorder=None, analytics=None) -> RunResult:
    """Run the batched board chain for ``n_steps`` yields (yield 0 is the
    initial state, as the reference's ``for part in exp_chain`` sees it).
    ``bits`` overrides the bit-board body dispatch (perf toggle; the
    bodies are bit-identical). ``record_every=k`` keeps only yields
    0, k, 2k, ... in the returned history (accumulators still advance
    every step), strided on device before the host copy.
    ``recorder``: obs events for the segment (run_start/chunk/run_end)
    plus the final record's ``transfer``."""
    seg = run_board_segment(bg, spec, params, state, n_steps - 1,
                            record_history=record_history, chunk=chunk,
                            bits=bits, record_every=record_every,
                            history_device=history_device,
                            recorder=recorder, analytics=analytics)
    hist_parts = {k: [v] for k, v in seg.history.items()}
    return finalize_board_run(bg, spec, params, seg.state, hist_parts,
                              seg.waits_total, [], record_history,
                              n_steps, record_every,
                              history_device=history_device,
                              recorder=recorder, analytics=analytics)
