"""Runner for the fused Pallas grid kernel (kernel/fused.py): ChainState in,
ChainState out, same yield semantics as sampling/runner.run_chains.

Restricted to the workload the fused kernel specializes (plain nx x ny
square grid with unit populations, 2 districts, 'bi' proposal, re-propose
semantics, literal cut acceptance, beta == 1); everything else uses the
general XLA runner. The two paths are distribution-equivalent (asserted
statistically in tests/test_fused.py).

Division of labor per chunk: the kernel advances the chains entirely
on-chip and emits a signed flip log; this runner replays the log into the
reference parity accumulators (part_sum / last_flipped / num_flips,
including the re-apply-on-self-loop quirk) on host — a ~T-iteration numpy
loop over (C,) vectors, amortized across the whole chunk.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from ..graphs.lattice import LatticeGraph
from ..kernel.step import Spec
from ..kernel import fused
from ..state.chain_state import ChainState, derive


@dataclasses.dataclass
class FusedRunResult:
    state: ChainState
    history: dict
    waits_total: np.ndarray
    n_yields: int

    def host_state(self):
        return jax.tree.map(np.asarray, self.state)


def supports(graph: LatticeGraph, spec: Spec, beta=1.0) -> bool:
    """True when the fused kernel implements these exact semantics:
    2-district bi-proposal re-propose cut-accept chain, beta 1, on a plain
    rook nx x ny grid with unit populations (checked structurally)."""
    if spec.n_districts != 2 or spec.proposal != "bi":
        return False
    if spec.contiguity not in ("patch", "exact"):
        return False
    if spec.invalid != "repropose" or spec.accept != "cut":
        return False
    if spec.anneal != "none" or spec.frame_interface or spec.weighted_cut:
        return False
    if not np.all(np.asarray(beta) == 1.0):
        return False
    try:
        nx_, ny_ = _grid_dims(graph)
    except (ValueError, TypeError, IndexError):
        return False
    if graph.n_edges != nx_ * (ny_ - 1) + (nx_ - 1) * ny_:
        return False
    if graph.max_deg > 4 or not np.all(graph.pop == 1):
        return False
    return True


def _grid_dims(graph: LatticeGraph):
    xs = [lab[0] for lab in graph.labels]
    ys = [lab[1] for lab in graph.labels]
    nx_, ny_ = max(xs) + 1, max(ys) + 1
    if graph.n_nodes != nx_ * ny_:
        raise ValueError("fused runner needs a full nx x ny grid")
    return nx_, ny_


def _node_perm(graph: LatticeGraph, nx_: int, ny_: int):
    """graph node index -> fused slot (x * ny + y)."""
    perm = np.zeros(graph.n_nodes, dtype=np.int64)
    for i, (x, y) in enumerate(graph.labels):
        perm[i] = x * ny_ + y
    return perm


def run_fused(graph: LatticeGraph, spec: Spec, states: ChainState,
              n_steps: int, *, base: float, pop_lo: float, pop_hi: float,
              seed: int = 0, record_history: bool = True,
              chunk: int = 512, block_chains: int = 256) -> FusedRunResult:
    """Advance the batch ``n_steps`` yields (first yield = initial state,
    as in run_chains) on the fused kernel."""
    if not supports(graph, spec):
        raise ValueError("workload not supported by the fused kernel; use "
                         "sampling.run_chains")
    nx_, ny_ = _grid_dims(graph)
    n = graph.n_nodes
    perm = _node_perm(graph, nx_, ny_)
    inv_perm = np.argsort(perm)
    c = states.assignment.shape[0]

    def pack(arr, dtype):
        a = np.asarray(arr)
        out = np.empty_like(a, dtype=dtype)
        out[:, perm] = a
        return out

    a = jnp.asarray(pack(states.assignment, np.int8))

    # parity accumulators stay host-side (replayed from the flip log)
    part_sum = pack(states.part_sum, np.int64)
    last_flipped = pack(states.last_flipped, np.int64)
    num_flips = pack(states.num_flips, np.int64)

    # cut_times -> vert/horiz slot panels
    ctv = np.zeros((c, n), np.int32)
    cth = np.zeros((c, n), np.int32)
    ct = np.asarray(states.cut_times)
    for ei in range(graph.n_edges):
        ia, ib = int(graph.edges[ei, 0]), int(graph.edges[ei, 1])
        (xa, ya), (xb, yb) = graph.labels[ia], graph.labels[ib]
        if xa == xb:
            ctv[:, xa * ny_ + min(ya, yb)] = ct[:, ei]
        else:
            cth[:, min(xa, xb) * ny_ + ya] = ct[:, ei]
    ctv, cth = jnp.asarray(ctv), jnp.asarray(cth)

    scal_i = np.zeros((c, 128), np.int32)
    scal_i[:, 0] = np.asarray(states.cut_count)
    scal_i[:, 1] = np.asarray(states.accept_count)
    scal_i[:, 2] = np.asarray(states.move_clock)
    scal_i[:, 3] = np.asarray(states.t_yield)
    scal_f = np.zeros((c, 128), np.float32)
    scal_f[:, 0] = np.asarray(states.cur_wait)

    # flip cursor carried across chunks, in fused slot space
    flip = np.asarray(states.cur_flip_node).astype(np.int64)
    cur_flip = np.where(flip >= 0, perm[np.clip(flip, 0, n - 1)], -1)
    a_host = np.asarray(a, np.int64)
    cur_sign = np.where(
        cur_flip >= 0,
        1 - 2 * a_host[np.arange(c), np.clip(cur_flip, 0, n - 1)], 1)

    # --- initial record (yield 0): one dense XLA pass + one replay step -
    idx = np.arange(n)
    has_n = ((idx % ny_) < ny_ - 1)[None, :]
    has_e = ((idx // ny_) < nx_ - 1)[None, :]
    a_i32 = a.astype(jnp.int32)
    cut_v0 = (a_i32 != jnp.roll(a_i32, -1, axis=1)) & jnp.asarray(has_n)
    cut_h0 = (a_i32 != jnp.roll(a_i32, -ny_, axis=1)) & jnp.asarray(has_e)
    ctv = ctv + cut_v0.astype(jnp.int32)
    cth = cth + cut_h0.astype(jnp.int32)
    waits_total = np.asarray(states.cur_wait, np.float64).copy()
    fused.replay_parity(np.zeros((c, 1), np.int64), scal_i[:, 3].copy(),
                        part_sum, last_flipped, num_flips, cur_flip,
                        cur_sign)
    scal_i[:, 3] += 1
    scal_i = jnp.asarray(scal_i)
    scal_f = jnp.asarray(scal_f)

    hist = {"cut_count": [np.asarray(states.cut_count)[:, None]],
            "b_count": [np.asarray(states.b_count)[:, None]],
            "wait": [np.asarray(states.cur_wait)[:, None]]} \
        if record_history else None

    if chunk % 128 or (n_steps - 1) % 128:
        raise ValueError(
            "fused runner needs chunk and n_steps-1 divisible by 128 "
            "(Mosaic lane alignment for the per-chunk log blocks); got "
            f"chunk={chunk}, n_steps={n_steps}")
    done = 1
    while done < n_steps:
        this = min(chunk, n_steps - done)
        t_start = np.asarray(scal_i[:, 3]).astype(np.int64)
        out = fused.fused_grid_chunk(
            seed + done, a, ctv, cth, scal_i, scal_f,
            nx=nx_, ny=ny_, n_steps=this, log_base=float(np.log(base)),
            pop_lo=float(pop_lo), pop_hi=float(pop_hi),
            record=record_history, block_chains=block_chains)
        if record_history:
            a, ctv, cth, scal_i, scal_f, flog, cc_h, bc_h, w_h = out
            hist["cut_count"].append(np.asarray(cc_h))
            hist["b_count"].append(np.asarray(bc_h))
            hist["wait"].append(np.asarray(w_h))
        else:
            a, ctv, cth, scal_i, scal_f, flog = out
        fused.replay_parity(np.asarray(flog, np.int64), t_start,
                            part_sum, last_flipped, num_flips, cur_flip,
                            cur_sign)
        waits_total += np.asarray(scal_f[:, 1], np.float64)
        scal_f = scal_f.at[:, 1].set(0.0)
        done += this

    # --- unpack back to ChainState graph order --------------------------
    def unpack(arr, dtype):
        return jnp.asarray(np.asarray(arr)[:, perm].astype(dtype))

    ct_full = fused.fold_cut_panels(nx_, ny_, np.asarray(ctv),
                                    np.asarray(cth), graph)
    flip_g = np.where(cur_flip >= 0,
                      inv_perm[np.clip(cur_flip, 0, n - 1)], -1)

    a_graph = unpack(a, np.int8)
    cut, cut_deg, dist_pop, cut_count, b_count = jax.vmap(
        lambda x: derive(graph.device(), x, 2))(a_graph)

    state = states.replace(
        assignment=a_graph,
        cut=cut, cut_deg=cut_deg, dist_pop=dist_pop,
        cut_count=jnp.asarray(np.asarray(scal_i[:, 0])),
        b_count=b_count,
        cur_wait=jnp.asarray(np.asarray(scal_f[:, 0])),
        cur_flip_node=jnp.asarray(flip_g.astype(np.int32)),
        t_yield=jnp.asarray(np.asarray(scal_i[:, 3])),
        part_sum=unpack(part_sum, np.int32),
        last_flipped=unpack(last_flipped, np.int32),
        num_flips=unpack(num_flips, np.int32),
        cut_times=jnp.asarray(ct_full.astype(np.int32)),
        waits_sum=jnp.zeros_like(states.waits_sum),
        accept_count=jnp.asarray(np.asarray(scal_i[:, 1])),
        move_clock=jnp.asarray(np.asarray(scal_i[:, 2])),
    )
    history = ({k: np.concatenate(v, axis=1) for k, v in hist.items()}
               if record_history else {})
    return FusedRunResult(state=state, history=history,
                          waits_total=waits_total, n_yields=n_steps)
