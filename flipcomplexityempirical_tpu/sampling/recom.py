"""Batched TPU ReCom: the spanning-tree recombination move as a jit+vmap
kernel over the (n_chains, n_nodes) assignment tensor.

The host oracle (semantics source) is compat/recom.py; the reference
constructs exactly this proposal at grid_chain_sec11.py:328-335. The
vectorized redesign replaces every data-dependent structure with
fixed-shape array passes:

- random spanning tree: iid uniform edge weights -> minimum spanning forest
  via Boruvka rounds (scatter-min per component + pointer-jumping union),
  the parallel-friendly MST that matches gerrychain's random-weight-MST
  tree distribution;
- rooting + subtree populations: parent pointers by masked BFS
  (lax.while_loop frontier expansion), then leaf-to-root accumulation by
  scatter-adding each BFS level from deepest to shallowest;
- balanced-cut choice: masked Gumbel-max over tree edges whose subtree
  population lands both sides within epsilon of target;
- the move commits by relabeling one subtree and re-deriving the chain
  state's incremental fields (a recom move touches O(N) nodes, so a full
  O(E) re-derive is the right cost model, unlike the O(deg) flip commit).

A chain whose bipartition finds no balanced tree edge draws fresh trees
up to a total of ``tree_retries`` attempts inside the move (the bounded
analogue of the host path's unbounded ``bipartition_tree`` retry), then
keeps its current partition for the round — the bound keeps one unlucky
chain from straggling the whole vmapped batch. ``tests/test_recom.py``
compares the batched and host-oracle chains' stationary statistics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graphs.lattice import DeviceGraph
from ..kernel import step as kstep
from ..kernel.step import Spec
from ..state.chain_state import ChainState, derive


def _ceil_log2(n: int) -> int:
    b = 1
    while (1 << b) < n:
        b += 1
    return b


def spanning_forest(dg: DeviceGraph, member, key):
    """Random minimum-spanning-forest edge mask of the subgraph induced by
    ``member`` (bool[N]): Boruvka with iid uniform weights. Non-member
    nodes stay singleton components. Returns bool[E]."""
    n, e = dg.n_nodes, dg.n_edges
    eu, ev = dg.edges[:, 0], dg.edges[:, 1]
    internal = member[eu] & member[ev]
    # Random-MST depends only on the weight ORDER, so draw a uniform random
    # permutation as integer ranks: ties are impossible by construction
    # (float iid uniforms collide, and Boruvka with ties can cycle). Kept
    # as int32 — a float32 cast would re-introduce ties above 2^24 edges.
    w = jax.random.permutation(key, e).astype(jnp.int32)
    big = jnp.int32(e)  # ranks are 0..e-1, so e acts as +inf

    def round_body(carry):
        comp, in_tree, _ = carry
        cu, cv = comp[eu], comp[ev]
        alive = internal & (cu != cv)
        we = jnp.where(alive, w, big)
        # per-component minimum outgoing edge (scatter-min both endpoints)
        best = jnp.full(n, big, jnp.int32).at[cu].min(we).at[cv].min(we)
        # an edge is selected if it is the minimum for either component
        sel = alive & ((we <= best[cu]) | (we <= best[cv]))
        # union: point the larger component id at the smaller (deterministic)
        lo = jnp.minimum(cu, cv)
        hi = jnp.maximum(cu, cv)
        parent = jnp.arange(n).at[jnp.where(sel, hi, 0)].min(
            jnp.where(sel, lo, n))
        parent = jnp.minimum(parent, jnp.arange(n))
        # pointer jumping to canonical roots
        for _ in range(_ceil_log2(max(n, 2))):
            parent = parent[parent]
        comp = parent[comp]
        return comp, in_tree | sel, alive.any()

    def cond(carry):
        return carry[2]

    comp0 = jnp.arange(n)
    in_tree0 = jnp.zeros(e, dtype=bool)
    comp, in_tree, _ = jax.lax.while_loop(
        cond, round_body, (comp0, in_tree0, jnp.bool_(True)))
    return in_tree


def tree_structure(dg: DeviceGraph, in_tree, member, root):
    """Parent pointers and BFS depth for the spanning tree restricted to
    ``member``, rooted at ``root``. parent[root] = root; non-members keep
    parent = self, depth = -1. Returns (parent i32[N], depth i32[N])."""
    n = dg.n_nodes
    tree_nbr = in_tree[dg.nbr_edge] & dg.nbr_mask          # (N, D)
    parent0 = jnp.arange(n).at[root].set(root)
    depth0 = jnp.full(n, -1, jnp.int32).at[root].set(0)

    def cond(carry):
        _, _, frontier, lvl = carry
        return frontier.any()

    def body(carry):
        parent, depth, frontier, lvl = carry
        # nodes adjacent (in-tree) to the frontier and not yet visited
        hit = (frontier[dg.nbr] & tree_nbr).any(axis=1)
        new = hit & (depth < 0) & member
        # choose the parent = any frontier tree-neighbor (first slot wins)
        nbr_is_par = frontier[dg.nbr] & tree_nbr
        first = jnp.argmax(nbr_is_par, axis=1)
        cand = dg.nbr[jnp.arange(n), first]
        parent = jnp.where(new, cand, parent)
        depth = jnp.where(new, lvl + 1, depth)
        return parent, depth, new, lvl + 1

    parent, depth, _, _ = jax.lax.while_loop(
        cond, body, (parent0, depth0,
                     jnp.zeros(n, bool).at[root].set(True), jnp.int32(0)))
    return parent, depth


def subtree_populations(dg: DeviceGraph, parent, depth):
    """f32[N] subtree population sums via level-by-level rollup from the
    deepest BFS level to the root."""
    n = dg.n_nodes
    pop = jnp.where(depth >= 0, dg.pop.astype(jnp.float32), 0.0)
    maxd = depth.max()

    def cond(carry):
        _, lvl = carry
        return lvl > 0

    def body(carry):
        acc, lvl = carry
        at_lvl = depth == lvl
        acc = acc.at[jnp.where(at_lvl, parent, n)].add(
            jnp.where(at_lvl, acc, 0.0), mode="drop")
        return acc, lvl - 1

    acc, _ = jax.lax.while_loop(cond, body, (pop, maxd))
    return acc


def mark_subtree(dg: DeviceGraph, parent, depth, cut_child):
    """bool[N]: nodes whose root-path passes through ``cut_child``, by
    top-down level sweep (a node is in the subtree iff its parent is,
    seeded at cut_child)."""
    n = dg.n_nodes
    mark0 = jnp.zeros(n, bool).at[cut_child].set(True)
    maxd = depth.max()

    def cond(carry):
        _, lvl = carry
        return lvl <= maxd

    def body(carry):
        mark, lvl = carry
        at_lvl = (depth == lvl) & (jnp.arange(n) != cut_child)
        mark = mark | (at_lvl & mark[parent])
        return mark, lvl + 1

    mark, _ = jax.lax.while_loop(
        cond, body, (mark0, depth[cut_child] + 1))
    return mark


def recom_move(dg: DeviceGraph, spec: Spec, state: ChainState,
               epsilon: float = 0.05, pop_target=None, label_values=None,
               tree_retries: int = 4):
    """One ReCom move for one chain (vmap over chains): merge the two
    districts straddling a random cut edge, tree-bipartition, commit if a
    balanced cut exists. Returns the new ChainState (unchanged assignment
    when no balanced edge was found).

    ``pop_target`` is the ideal per-district population the split sides
    must land within epsilon of (the reference's pop_target,
    grid_chain_sec11.py:330-335); default = half the merged pair's total
    (exact only while district populations haven't drifted).

    ``tree_retries`` is the TOTAL number of spanning-tree attempts per
    move (1 = single draw, no re-draws) when no balanced edge exists —
    the batched analogue of gerrychain's ``node_repeats``/retry loop (the
    reference passes node_repeats=1, grid_chain_sec11.py:334; the host
    oracle retries unboundedly inside ``bipartition_tree``). Bounded so
    one unlucky chain cannot straggle the whole vmapped batch; a chain
    that exhausts its attempts keeps its partition for the round.

    ``label_values`` (i32[K] district -> +1/-1 label, as in StepParams) is
    required to keep the reference part_sum/num_flips parity metrics
    consistent when interleaving recom with flip chains; None skips the
    settlement (fine when parity metrics are unused)."""
    n = dg.n_nodes
    key, k_edge, k_draw, k_wait = jax.random.split(state.key, 4)
    a = state.assignment.astype(jnp.int32)

    # 1. random cut edge -> merged district pair
    cut_mask = state.cut > 0
    u = jax.random.uniform(k_edge, (dg.n_edges,))
    e_star = jnp.argmax(jnp.where(cut_mask, u, -1.0))
    any_cut = cut_mask.any()
    d1 = a[dg.edges[e_star, 0]]
    d2 = a[dg.edges[e_star, 1]]
    member = (a == d1) | (a == d2)
    root = dg.edges[e_star, 0]

    # 2+3. spanning tree -> balanced tree edge (masked Gumbel-max), with
    # bounded tree re-draws when no tree edge balances
    if pop_target is not None:
        target_s = jnp.float32(pop_target)

    def attempt(k):
        k_tree, k_cut = jax.random.split(k)
        in_tree = spanning_forest(dg, member, k_tree)
        parent, depth = tree_structure(dg, in_tree, member, root)
        sub = subtree_populations(dg, parent, depth)
        total = sub[root]
        target = total / 2.0 if pop_target is None else target_s
        lo, hi = target * (1 - epsilon), target * (1 + epsilon)
        is_tree_child = (depth > 0)  # every non-root member cuts its
        ok = is_tree_child & (sub >= lo) & (sub <= hi) \
            & (total - sub >= lo) & (total - sub <= hi)
        g = jax.random.gumbel(k_cut, (n,))
        cut_child = jnp.argmax(jnp.where(ok, g, -jnp.inf))
        return parent, depth, cut_child, ok.any()

    def retry_cond(carry):
        k, _, _, _, ok, tries = carry
        return (~ok) & (tries < tree_retries)

    def retry_body(carry):
        k, *_ , tries = carry
        k, ka = jax.random.split(k)
        parent, depth, cut_child, ok = attempt(ka)
        return (k, parent, depth, cut_child, ok, tries + 1)

    k0, ka = jax.random.split(k_draw)
    parent, depth, cut_child, ok0 = attempt(ka)
    _, parent, depth, cut_child, found_tree, _ = jax.lax.while_loop(
        retry_cond, retry_body,
        (k0, parent, depth, cut_child, ok0, jnp.int32(1)))
    found = found_tree & any_cut

    # 4. commit: subtree -> d1, rest of merged region -> d2
    side = mark_subtree(dg, parent, depth, cut_child)
    a_new = jnp.where(member, jnp.where(side, d1, d2), a)
    a_new = jnp.where(found, a_new, a).astype(state.assignment.dtype)

    cut, cut_deg, dist_pop, cut_count, b_count = derive(
        dg, a_new, spec.n_districts, spec.proposal)

    # settle per-node parity clocks for relabeled nodes: credit the OLD
    # sign over (last_flipped, now], stamp the relabel time, and count the
    # relabel as a flip — otherwise the next flip-kernel record()
    # attributes the pre-recom interval to the post-recom sign
    # (kernel/step.py record; reference part_sum semantics,
    # grid_chain_sec11.py:396-400).
    part_sum = state.part_sum
    last_flipped = state.last_flipped
    num_flips = state.num_flips
    if spec.parity_metrics and label_values is not None:
        lv = jnp.asarray(label_values, jnp.int32)
        changed = a_new.astype(jnp.int32) != a
        t_now = state.t_yield
        part_sum = part_sum + jnp.where(
            changed, lv[a] * (t_now - last_flipped), 0)
        last_flipped = jnp.where(changed, t_now, last_flipped)
        num_flips = num_flips + changed.astype(jnp.int32)

    # a committed recom changes the boundary wholesale: the memoized
    # geometric wait must be resampled from the NEW |b_nodes|, and the
    # flip-bookkeeping cursor cleared (recom is not a single-node flip, so
    # the reference's per-node flip metrics don't apply to this move)
    if spec.geom_waits:
        wait_new = kstep.sample_geom_minus1(
            k_wait, b_count, dg.n_nodes, spec.n_districts)
        cur_wait = jnp.where(found, wait_new, state.cur_wait)
    else:
        cur_wait = state.cur_wait
    cur_flip_node = jnp.where(found, jnp.int32(-1), state.cur_flip_node)
    return state.replace(
        key=key, assignment=a_new, cut=cut.astype(state.cut.dtype),
        cut_deg=cut_deg.astype(state.cut_deg.dtype), dist_pop=dist_pop,
        cut_count=cut_count, b_count=b_count,
        cur_wait=cur_wait, cur_flip_node=cur_flip_node,
        part_sum=part_sum, last_flipped=last_flipped, num_flips=num_flips,
        move_clock=state.move_clock + found.astype(jnp.int32),
        accept_count=state.accept_count + found.astype(jnp.int32))
