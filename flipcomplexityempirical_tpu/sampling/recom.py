"""Batched TPU ReCom: the spanning-tree recombination move as a jit+vmap
kernel over the (n_chains, n_nodes) assignment tensor.

The host oracle (semantics source) is compat/recom.py; the reference
constructs exactly this proposal at grid_chain_sec11.py:328-335. The
vectorized redesign replaces every data-dependent structure with
fixed-shape array passes:

- random spanning tree: iid uniform edge weights -> minimum spanning forest
  via Boruvka rounds (scatter-min per component + pointer-jumping union),
  the parallel-friendly MST that matches gerrychain's random-weight-MST
  tree distribution;
- rooting + subtree populations: parent pointers by masked BFS
  (lax.while_loop frontier expansion), then leaf-to-root accumulation by
  scatter-adding each BFS level from deepest to shallowest;
- balanced-cut choice: masked Gumbel-max over tree edges whose subtree
  population lands both sides within epsilon of target;
- the move commits by relabeling one subtree and re-deriving the chain
  state's incremental fields (a recom move touches O(N) nodes, so a full
  O(E) re-derive is the right cost model, unlike the O(deg) flip commit).

A chain whose bipartition finds no balanced tree edge draws fresh trees
up to a total of ``tree_retries`` attempts inside the move (the bounded
analogue of the host path's unbounded ``bipartition_tree`` retry), then
keeps its current partition for the round — the bound keeps one unlucky
chain from straggling the whole vmapped batch. ``tests/test_recom.py``
compares the batched and host-oracle chains' stationary statistics.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..graphs.lattice import DeviceGraph
from ..kernel import step as kstep
from ..kernel.step import Spec, StepParams
from ..state.chain_state import ChainState, derive
from .runner import (RunResult, _record_initial, assemble_history,
                     maybe_host, pick_chunk, snap_chunk_to, thin_outs)


def _ceil_log2(n: int) -> int:
    b = 1
    while (1 << b) < n:
        b += 1
    return b


def spanning_forest(dg: DeviceGraph, member, key):
    """Random minimum-spanning-forest edge mask of the subgraph induced by
    ``member`` (bool[N]): Boruvka with iid uniform weights. Non-member
    nodes stay singleton components. Returns bool[E]."""
    n, e = dg.n_nodes, dg.n_edges
    eu, ev = dg.edges[:, 0], dg.edges[:, 1]
    internal = member[eu] & member[ev]
    # Random-MST depends only on the weight ORDER, so draw a uniform random
    # permutation as integer ranks: ties are impossible by construction
    # (float iid uniforms collide, and Boruvka with ties can cycle). Kept
    # as int32 — a float32 cast would re-introduce ties above 2^24 edges.
    w = jax.random.permutation(key, e).astype(jnp.int32)
    big = jnp.int32(e)  # ranks are 0..e-1, so e acts as +inf

    def round_body(carry):
        comp, in_tree, _ = carry
        cu, cv = comp[eu], comp[ev]
        alive = internal & (cu != cv)
        we = jnp.where(alive, w, big)
        # per-component minimum outgoing edge (scatter-min both endpoints)
        best = jnp.full(n, big, jnp.int32).at[cu].min(we).at[cv].min(we)
        # an edge is selected if it is the minimum for either component
        sel = alive & ((we <= best[cu]) | (we <= best[cv]))
        # union: point the larger component id at the smaller (deterministic)
        lo = jnp.minimum(cu, cv)
        hi = jnp.maximum(cu, cv)
        parent = jnp.arange(n).at[jnp.where(sel, hi, 0)].min(
            jnp.where(sel, lo, n))
        parent = jnp.minimum(parent, jnp.arange(n))
        # pointer jumping to canonical roots
        for _ in range(_ceil_log2(max(n, 2))):
            parent = parent[parent]
        comp = parent[comp]
        return comp, in_tree | sel, alive.any()

    def cond(carry):
        return carry[2]

    comp0 = jnp.arange(n)
    in_tree0 = jnp.zeros(e, dtype=bool)
    comp, in_tree, _ = jax.lax.while_loop(
        cond, round_body, (comp0, in_tree0, jnp.bool_(True)))
    return in_tree


def tree_structure(dg: DeviceGraph, in_tree, member, root):
    """Parent pointers and BFS depth for the spanning tree restricted to
    ``member``, rooted at ``root``. parent[root] = root; non-members keep
    parent = self, depth = -1. Returns (parent i32[N], depth i32[N])."""
    n = dg.n_nodes
    tree_nbr = in_tree[dg.nbr_edge] & dg.nbr_mask          # (N, D)
    parent0 = jnp.arange(n).at[root].set(root)
    depth0 = jnp.full(n, -1, jnp.int32).at[root].set(0)

    def cond(carry):
        _, _, frontier, lvl = carry
        return frontier.any()

    def body(carry):
        parent, depth, frontier, lvl = carry
        # nodes adjacent (in-tree) to the frontier and not yet visited
        hit = (frontier[dg.nbr] & tree_nbr).any(axis=1)
        new = hit & (depth < 0) & member
        # choose the parent = any frontier tree-neighbor (first slot wins)
        nbr_is_par = frontier[dg.nbr] & tree_nbr
        first = jnp.argmax(nbr_is_par, axis=1)
        cand = dg.nbr[jnp.arange(n), first]
        parent = jnp.where(new, cand, parent)
        depth = jnp.where(new, lvl + 1, depth)
        return parent, depth, new, lvl + 1

    parent, depth, _, _ = jax.lax.while_loop(
        cond, body, (parent0, depth0,
                     jnp.zeros(n, bool).at[root].set(True), jnp.int32(0)))
    return parent, depth


def subtree_populations(dg: DeviceGraph, parent, depth):
    """f32[N] subtree population sums via level-by-level rollup from the
    deepest BFS level to the root."""
    n = dg.n_nodes
    pop = jnp.where(depth >= 0, dg.pop.astype(jnp.float32), 0.0)
    maxd = depth.max()

    def cond(carry):
        _, lvl = carry
        return lvl > 0

    def body(carry):
        acc, lvl = carry
        at_lvl = depth == lvl
        acc = acc.at[jnp.where(at_lvl, parent, n)].add(
            jnp.where(at_lvl, acc, 0.0), mode="drop")
        return acc, lvl - 1

    acc, _ = jax.lax.while_loop(cond, body, (pop, maxd))
    return acc


def mark_subtree(dg: DeviceGraph, parent, depth, cut_child):
    """bool[N]: nodes whose root-path passes through ``cut_child``, by
    top-down level sweep (a node is in the subtree iff its parent is,
    seeded at cut_child)."""
    n = dg.n_nodes
    mark0 = jnp.zeros(n, bool).at[cut_child].set(True)
    maxd = depth.max()

    def cond(carry):
        _, lvl = carry
        return lvl <= maxd

    def body(carry):
        mark, lvl = carry
        at_lvl = (depth == lvl) & (jnp.arange(n) != cut_child)
        mark = mark | (at_lvl & mark[parent])
        return mark, lvl + 1

    mark, _ = jax.lax.while_loop(
        cond, body, (mark0, depth[cut_child] + 1))
    return mark


def recom_move(dg: DeviceGraph, spec: Spec, state: ChainState,
               epsilon: float = 0.05, pop_target=None, label_values=None,
               tree_retries: int = 4):
    """One ReCom move for one chain (vmap over chains): merge the two
    districts straddling a random cut edge, tree-bipartition, commit if a
    balanced cut exists. Returns the new ChainState (unchanged assignment
    when no balanced edge was found).

    ``pop_target`` is the ideal per-district population the split sides
    must land within epsilon of (the reference's pop_target,
    grid_chain_sec11.py:330-335); default = half the merged pair's total
    (exact only while district populations haven't drifted).

    ``tree_retries`` is the TOTAL number of spanning-tree attempts per
    move (1 = single draw, no re-draws) when no balanced edge exists —
    the batched analogue of gerrychain's ``node_repeats``/retry loop (the
    reference passes node_repeats=1, grid_chain_sec11.py:334; the host
    oracle retries unboundedly inside ``bipartition_tree``). Bounded so
    one unlucky chain cannot straggle the whole vmapped batch; a chain
    that exhausts its attempts keeps its partition for the round.

    ``label_values`` (i32[K] district -> +1/-1 label, as in StepParams) is
    required to keep the reference part_sum/num_flips parity metrics
    consistent when interleaving recom with flip chains; None skips the
    settlement (fine when parity metrics are unused)."""
    n = dg.n_nodes
    key, k_edge, k_draw, k_wait = jax.random.split(state.key, 4)
    a = state.assignment.astype(jnp.int32)

    # 1. random cut edge -> merged district pair
    cut_mask = state.cut > 0
    u = jax.random.uniform(k_edge, (dg.n_edges,))
    e_star = jnp.argmax(jnp.where(cut_mask, u, -1.0))
    any_cut = cut_mask.any()
    d1 = a[dg.edges[e_star, 0]]
    d2 = a[dg.edges[e_star, 1]]
    member = (a == d1) | (a == d2)
    root = dg.edges[e_star, 0]

    # 2+3. spanning tree -> balanced tree edge (masked Gumbel-max), with
    # bounded tree re-draws when no tree edge balances
    if pop_target is not None:
        target_s = jnp.float32(pop_target)

    def attempt(k):
        k_tree, k_cut = jax.random.split(k)
        in_tree = spanning_forest(dg, member, k_tree)
        parent, depth = tree_structure(dg, in_tree, member, root)
        sub = subtree_populations(dg, parent, depth)
        total = sub[root]
        target = total / 2.0 if pop_target is None else target_s
        lo, hi = target * (1 - epsilon), target * (1 + epsilon)
        is_tree_child = (depth > 0)  # every non-root member cuts its
        ok = is_tree_child & (sub >= lo) & (sub <= hi) \
            & (total - sub >= lo) & (total - sub <= hi)
        g = jax.random.gumbel(k_cut, (n,))
        cut_child = jnp.argmax(jnp.where(ok, g, -jnp.inf))
        return parent, depth, cut_child, ok.any()

    def retry_cond(carry):
        k, _, _, _, ok, tries = carry
        return (~ok) & (tries < tree_retries)

    def retry_body(carry):
        k, *_ , tries = carry
        k, ka = jax.random.split(k)
        parent, depth, cut_child, ok = attempt(ka)
        return (k, parent, depth, cut_child, ok, tries + 1)

    k0, ka = jax.random.split(k_draw)
    parent, depth, cut_child, ok0 = attempt(ka)
    _, parent, depth, cut_child, found_tree, _ = jax.lax.while_loop(
        retry_cond, retry_body,
        (k0, parent, depth, cut_child, ok0, jnp.int32(1)))
    found = found_tree & any_cut

    # 4. commit: subtree -> d1, rest of merged region -> d2
    side = mark_subtree(dg, parent, depth, cut_child)
    a_new = jnp.where(member, jnp.where(side, d1, d2), a)
    a_new = jnp.where(found, a_new, a).astype(state.assignment.dtype)

    cut, cut_deg, dist_pop, cut_count, b_count = derive(
        dg, a_new, spec.n_districts, spec.proposal)

    # settle per-node parity clocks for relabeled nodes: credit the OLD
    # sign over (last_flipped, now], stamp the relabel time, and count the
    # relabel as a flip — otherwise the next flip-kernel record()
    # attributes the pre-recom interval to the post-recom sign
    # (kernel/step.py record; reference part_sum semantics,
    # grid_chain_sec11.py:396-400).
    part_sum = state.part_sum
    last_flipped = state.last_flipped
    num_flips = state.num_flips
    if spec.parity_metrics and label_values is not None:
        lv = jnp.asarray(label_values, jnp.int32)
        changed = a_new.astype(jnp.int32) != a
        t_now = state.t_yield
        part_sum = part_sum + jnp.where(
            changed, lv[a] * (t_now - last_flipped), 0)
        last_flipped = jnp.where(changed, t_now, last_flipped)
        num_flips = num_flips + changed.astype(jnp.int32)

    # a committed recom changes the boundary wholesale: the memoized
    # geometric wait must be resampled from the NEW |b_nodes|, and the
    # flip-bookkeeping cursor cleared (recom is not a single-node flip, so
    # the reference's per-node flip metrics don't apply to this move)
    if spec.geom_waits:
        wait_new = kstep.sample_geom_minus1(
            k_wait, b_count, dg.n_nodes, spec.n_districts)
        cur_wait = jnp.where(found, wait_new, state.cur_wait)
    else:
        cur_wait = state.cur_wait
    cur_flip_node = jnp.where(found, jnp.int32(-1), state.cur_flip_node)
    extra = {}
    if state.reject_count is not None:
        # recom reject taxonomy, preserving the tested invariant
        # reject_count.sum() + accept_count == tries_sum: slot 0
        # (nonboundary) — no cut edge to merge across; slot 1 (pop) —
        # trees drawn but no population-balanced cut edge survived the
        # retries. Slots 2/3 (disconnect/metropolis) cannot occur: the
        # tree split is connected by construction and recom has no
        # Metropolis coin. Exactly one slot fires per unfound move.
        zero = jnp.int32(0)
        extra["reject_count"] = state.reject_count + jnp.stack(
            [(~any_cut).astype(jnp.int32),
             (any_cut & ~found_tree).astype(jnp.int32), zero, zero])
    return state.replace(
        key=key, assignment=a_new, cut=cut.astype(state.cut.dtype),
        cut_deg=cut_deg.astype(state.cut_deg.dtype), dist_pop=dist_pop,
        cut_count=cut_count, b_count=b_count,
        cur_wait=cur_wait, cur_flip_node=cur_flip_node,
        part_sum=part_sum, last_flipped=last_flipped, num_flips=num_flips,
        move_clock=state.move_clock + found.astype(jnp.int32),
        accept_count=state.accept_count + found.astype(jnp.int32),
        tries_sum=state.tries_sum + 1,
        exhausted_count=state.exhausted_count
        + (~found).astype(jnp.int32),
        **extra)


@functools.partial(jax.jit, static_argnames=(
    "spec", "chunk", "collect", "epsilon", "pop_target", "tree_retries"))
def _run_recom_chunk(dg: DeviceGraph, spec: Spec, params: StepParams,
                     states: ChainState, chunk: int, collect: bool = True,
                     epsilon: float = 0.05, pop_target=None,
                     tree_retries: int = 4):
    paxes = StepParams.vmap_axes()

    def body(states, _):
        states = jax.vmap(
            lambda p, s: recom_move(dg, spec, s, epsilon=epsilon,
                                    pop_target=pop_target,
                                    label_values=p.label_values,
                                    tree_retries=tree_retries),
            in_axes=(paxes, 0))(params, states)
        states, out = jax.vmap(
            lambda p, s: kstep.record(dg, spec, p, s),
            in_axes=(paxes, 0))(params, states)
        return states, out if collect else {}

    states, outs = jax.lax.scan(body, states, None, length=chunk)
    return states, outs


def run_recom(dg: DeviceGraph, spec: Spec, params: StepParams,
              states: ChainState, n_steps: int,
              epsilon: float = 0.05, pop_target=None,
              tree_retries: int = 4,
              record_history: bool = True,
              chunk=None,
              record_initial: bool = True,
              record_every: int = 1,
              history_device: bool = False,
              recorder=None) -> RunResult:
    """The ReCom chain family's chunked runner: ``run_chains`` semantics
    (yield counting, checkpoint-segment continuation via
    ``record_initial=False``, thinning, waits drained per chunk) with
    ``recom_move`` as the transition. Obs events mirror the general
    runner's contract — one ``run_start``/``run_end``, a ``chunk`` event
    per executed chunk with the reject-reason breakdown — but tagged
    ``runner='recom'`` / ``kernel_path='recom'``: recom is a second
    CHAIN FAMILY, not a dispatch-ladder rung, so its records and bench
    metrics must never cross-gate against flip-walk paths.

    ``epsilon``/``pop_target``/``tree_retries`` are recom_move's knobs,
    static per compile (part of the jit cache key). The reject-counter
    enable/restore follows the runner's trailing-Optional contract:
    attaching a recorder turns ``states.reject_count`` on for the run
    and hands back the caller's treedef unchanged."""
    rec = obs.resolve_recorder(recorder)
    n_chains = states.assignment.shape[0]
    had_rej = states.reject_count is not None
    if rec and not had_rej:
        states = states.replace(
            reject_count=jnp.zeros((n_chains, 4), jnp.int32))
    if record_every < 1:
        raise ValueError(f"record_every must be >= 1, got {record_every}")
    if chunk is None:
        # recom moves are O(N log N) tree passes, ~100x a flip step:
        # smaller default chunk keeps per-chunk wall time comparable
        chunk = pick_chunk(n_steps + (0 if record_initial else 1), 256)
    if record_every > 1:
        chunk = snap_chunk_to(chunk, record_every)
    if pop_target is not None:
        pop_target = float(pop_target)

    def step_chunk(states, this):
        return _run_recom_chunk(dg, spec, params, states, this,
                                collect=record_history, epsilon=epsilon,
                                pop_target=pop_target,
                                tree_retries=tree_retries)

    if rec:
        rec.emit("run_start", runner="recom", path="recom",
                 chains=n_chains,
                 n_steps=n_steps, chunk=chunk,
                 record_history=record_history, record_every=record_every,
                 record_initial=record_initial,
                 history_device=history_device)
        watch = obs.JitWatch(_run_recom_chunk, "recom._run_recom_chunk")
        t_run0 = time.perf_counter()
        last_acc = int(np.asarray(states.accept_count, np.int64).sum())
        acc_start, hbm_bytes, transfer_total = last_acc, 0, 0
        last_tries = int(np.asarray(states.tries_sum, np.int64).sum())
        last_rej = (np.asarray(states.reject_count, np.int64).sum(axis=0)
                    if states.reject_count is not None else None)
        mon = obs.ChainMonitor(rec, total=n_steps, path="recom",
                               runner="recom")
        met = obs.MetricsRegistry()
        run_span = obs.span(rec, "run:recom", annotate=True,
                            kernel_path="recom", chains=n_chains,
                            n_steps=n_steps).begin()

    if record_initial:
        states, out0 = _record_initial(dg, spec, params, states)
        if record_history:
            out0 = maybe_host(out0, history_device)
            hist_parts = {k: [v[:, None]] for k, v in out0.items()}
            if rec:
                nb = obs.dict_nbytes(out0)
                if history_device:
                    hbm_bytes += nb
                else:
                    transfer_total += nb
                    rec.emit("transfer", what="initial_record", bytes=nb)
        else:
            hist_parts = None
        done = 1
    else:
        hist_parts = {} if record_history else None
        done = 0
    done0 = done
    waits_total = np.asarray(states.waits_sum, np.float64).copy()
    states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))

    t_prev = time.perf_counter() if rec else None
    while done < n_steps:
        this = min(chunk, n_steps - done)
        if rec:
            csp = obs.span(rec, "chunk", annotate=True,
                           kernel_path="recom", steps=this,
                           done=done).begin()
        states, outs = step_chunk(states, this)
        if rec:
            watch.poll(rec, chunk=this,
                       cost=lambda: obs.aot_cost(
                           _run_recom_chunk, dg, spec, params, states,
                           this, collect=record_history, epsilon=epsilon,
                           pop_target=pop_target,
                           tree_retries=tree_retries))
        transfer_bytes = 0
        host_outs = None
        if record_history:
            outs = maybe_host(thin_outs(outs, record_every), history_device)
            if not history_device:
                host_outs = outs
            if rec:
                nb = obs.dict_nbytes(outs)
                if history_device:
                    hbm_bytes += nb
                else:
                    transfer_bytes = nb
                    transfer_total += nb
            for k, v in outs.items():
                hist_parts.setdefault(k, []).append(v.T)
        waits_total += np.asarray(states.waits_sum, np.float64)
        states = states.replace(waits_sum=jnp.zeros_like(states.waits_sum))
        done += this
        if rec:
            acc = int(np.asarray(states.accept_count, np.int64).sum())
            now = time.perf_counter()
            wall = now - t_prev
            t_prev = now
            reject = None
            if last_rej is not None:
                rej = np.asarray(states.reject_count, np.int64).sum(axis=0)
                tries = int(np.asarray(states.tries_sum, np.int64).sum())
                d = rej - last_rej
                reject = {"nonboundary": int(d[0]), "pop": int(d[1]),
                          "disconnect": int(d[2]), "metropolis": int(d[3]),
                          "accepted": acc - last_acc,
                          "proposals": tries - last_tries}
                last_rej, last_tries = rej, tries
            accept_rate = (acc - last_acc) / (n_chains * this)
            flips_per_s = n_chains * this / max(wall, 1e-12)
            rec.emit("chunk", runner="recom", path="recom",
                     steps=this,
                     chains=n_chains, flips=n_chains * this,
                     wall_s=wall,
                     flips_per_s=flips_per_s,
                     accept_rate=accept_rate,
                     transfer_bytes=transfer_bytes,
                     hbm_history_bytes=hbm_bytes,
                     done=done, total=n_steps, reject=reject)
            last_acc = acc
            mon.observe_chunk(outs=host_outs, wall_s=wall,
                              flips_per_s=flips_per_s,
                              accept_rate=accept_rate, reject=reject,
                              done=done)
            csp.end(wall_s=wall, reject=reject)
            met.observe("chunk_wall_s", wall)
            met.observe("flips_per_s", flips_per_s)
            met.inc("chunks")
            met.inc("flips", n_chains * this)
            met.inc("transfer_bytes", transfer_bytes)
            met.set("done", done)
            met.notify(rec)

    history = assemble_history(hist_parts, record_history, history_device)
    if rec:
        wall = time.perf_counter() - t_run0
        flips = n_chains * (n_steps - done0)
        met.set("hbm_history_bytes", hbm_bytes)
        snap = met.snapshot()
        rec.emit("metrics_snapshot", counters=snap["counters"],
                 gauges=snap["gauges"], histograms=snap["histograms"],
                 runner="recom", path="recom")
        rec.emit("run_end", runner="recom", path="recom",
                 n_yields=n_steps,
                 chains=n_chains, flips=flips, wall_s=wall,
                 flips_per_s=flips / max(wall, 1e-12),
                 accept_rate=(last_acc - acc_start) / max(flips, 1),
                 transfer_bytes=transfer_total,
                 hbm_history_bytes=hbm_bytes, metrics=snap)
        run_span.end(flips=flips, wall_s=wall)
    if rec and not had_rej:
        states = states.replace(reject_count=None)
    return RunResult(state=states, history=history,
                     waits_total=waits_total, n_yields=n_steps)
