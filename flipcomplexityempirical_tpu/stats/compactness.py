"""District compactness scores (BASELINE config 5: "k districts with
compactness score").

Discrete scores work on any graph via edge counts; geometric scores
(Polsby-Popper) need per-node areas and per-edge shared-boundary lengths,
which the dual-graph importer (graphs/dualgraph.py) attaches from real
precinct geometry.
"""

from __future__ import annotations

import numpy as np


def cut_edge_count(assignment, edges) -> np.ndarray:
    """Cut edges per chain: (C,) from assignment (C, N) | (N,) and edge list
    (E, 2) — the discrete compactness score the reference's target
    pi ∝ base^(-|cut|) penalizes."""
    a = np.asarray(assignment)
    if a.ndim == 1:
        a = a[None, :]
    e = np.asarray(edges)
    return (a[:, e[:, 0]] != a[:, e[:, 1]]).sum(axis=1)


def perimeter_area(assignment, k: int, *, edges, shared_perim, node_area,
                   node_exterior_perim=None):
    """Per-district perimeter and area: two (C, K) arrays.

    District perimeter = sum of shared-boundary lengths of cut edges
    incident to the district + its nodes' exterior (map-edge) perimeter;
    area = sum of member node areas.
    """
    a = np.asarray(assignment)
    if a.ndim == 1:
        a = a[None, :]
    c, n = a.shape
    e = np.asarray(edges)
    sp = np.asarray(shared_perim, dtype=np.float64)
    area = np.asarray(node_area, dtype=np.float64)
    ext = (np.zeros(n) if node_exterior_perim is None
           else np.asarray(node_exterior_perim, dtype=np.float64))

    au, av = a[:, e[:, 0]], a[:, e[:, 1]]
    cut = au != av
    perim = np.zeros((c, k))
    areas = np.zeros((c, k))
    for d in range(k):
        member = a == d
        perim[:, d] = ((cut & (au == d)) * sp).sum(axis=1) \
            + ((cut & (av == d)) * sp).sum(axis=1) \
            + member @ ext
        areas[:, d] = member @ area
    return perim, areas


def polsby_popper(assignment, k: int, *, edges, shared_perim, node_area,
                  node_exterior_perim=None) -> np.ndarray:
    """Polsby-Popper score 4*pi*A / P^2 per district: (C, K) in (0, 1],
    1 = disc. NaN for empty districts."""
    perim, area = perimeter_area(
        assignment, k, edges=edges, shared_perim=shared_perim,
        node_area=node_area, node_exterior_perim=node_exterior_perim)
    out = np.full(perim.shape, np.nan)
    ok = perim > 0
    out[ok] = 4.0 * np.pi * area[ok] / perim[ok] ** 2
    return out
