"""Partisan metrics: the gerrychain surface the reference imports but never
calls (``Election``, ``mean_median``, ``efficiency_gap`` at
grid_chain_sec11.py:20-30 — dead capability breadcrumbs, SURVEY.md section
2.2) — implemented batched over the (C, N) assignment tensor so a whole
chain ensemble is scored in one XLA call.

Vote columns correspond to the reference's random ``pink``/``purple`` node
attributes (grid_chain_sec11.py:223-228).
"""

from __future__ import annotations

import numpy as np


def district_vote_tallies(assignment, votes, k: int) -> np.ndarray:
    """Sum per-node ``votes`` (N, P) into districts: returns (C, K, P).
    ``assignment`` is (C, N) or (N,) of district indices (the ``Election``
    updater's tally, vectorized over the chain batch)."""
    a = np.asarray(assignment)
    if a.ndim == 1:
        a = a[None, :]
    votes = np.asarray(votes, dtype=np.float64)
    c, n = a.shape
    p = votes.shape[1]
    out = np.zeros((c, k, p))
    for d in range(k):  # K is small; one masked matmul per district
        out[:, d, :] = (a == d) @ votes
    return out


def _shares(tallies) -> np.ndarray:
    """Party-0 vote share per district: (C, K) from (C, K, 2)."""
    tallies = np.asarray(tallies, dtype=np.float64)
    tot = tallies.sum(axis=-1)
    return np.divide(tallies[..., 0], tot, out=np.full(tot.shape, 0.5),
                     where=tot > 0)


def mean_median(tallies) -> np.ndarray:
    """median - mean of party-0 district vote shares, per chain: positive
    means party 0's median district exceeds its mean — an advantage for
    party 0 (gerrychain sign convention). (C,) from (C, K, 2)."""
    s = _shares(tallies)
    return np.median(s, axis=-1) - s.mean(axis=-1)


def efficiency_gap(tallies) -> np.ndarray:
    """(wasted_1 - wasted_0) / total votes, per chain. Wasted = losing
    party's full count + winner's surplus over 50%."""
    tallies = np.asarray(tallies, dtype=np.float64)
    v0, v1 = tallies[..., 0], tallies[..., 1]
    tot = v0 + v1
    need = tot / 2.0
    w0 = np.where(v0 > v1, v0 - need, v0)
    w1 = np.where(v1 >= v0, v1 - need, v1)
    total = tot.sum(axis=-1)
    return np.divide((w1 - w0).sum(axis=-1), total,
                     out=np.zeros(total.shape), where=total > 0)


def seats_won(tallies) -> np.ndarray:
    """Districts carried by party 0, per chain: (C,) int."""
    tallies = np.asarray(tallies)
    return (tallies[..., 0] > tallies[..., 1]).sum(axis=-1)
