"""Autocorrelation / ESS / R-hat / mixing-time estimators over (C, T)
batched histories.

All functions take ``x`` shaped ``(n_chains, T)`` (a single chain may pass
``(T,)``; it is promoted) as numpy or JAX arrays and compute with float64
numpy on host — these are O(C T log T) post-processing steps, far off the
device hot path, and float32 autocorrelations of 1e5-step trajectories lose
meaningful precision.
"""

from __future__ import annotations

import numpy as np


def _chains(x) -> np.ndarray:
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim != 2:
        raise ValueError(f"expected (C, T) or (T,), got shape {x.shape}")
    return x


def autocorrelation(x, max_lag: int | None = None) -> np.ndarray:
    """Per-chain normalized autocorrelation function via FFT.

    Returns ``rho`` shaped (C, max_lag + 1), ``rho[:, 0] == 1``. Chains with
    zero variance (a frozen observable) return rho = [1, 0, 0, ...].
    """
    x = _chains(x)
    c, t = x.shape
    if max_lag is None:
        max_lag = t - 1
    max_lag = min(max_lag, t - 1)
    xc = x - x.mean(axis=1, keepdims=True)
    n_fft = 1
    while n_fft < 2 * t:
        n_fft *= 2
    f = np.fft.rfft(xc, n=n_fft, axis=1)
    acov = np.fft.irfft(f * np.conj(f), n=n_fft, axis=1)[:, :max_lag + 1]
    acov /= t  # biased estimator (stable tails)
    var = acov[:, :1]
    rho = np.divide(acov, var, out=np.zeros_like(acov), where=var > 0)
    rho[:, 0] = 1.0
    return rho


def integrated_autocorr_time(x, c: float = 5.0) -> np.ndarray:
    """Per-chain integrated autocorrelation time tau via Sokal's adaptive
    windowing on the chain-averaged ACF: the window M is the smallest lag
    with M >= c * tau(M). Returns tau shaped (C,); tau >= 1; for iid data
    tau ~= 1.
    """
    x = _chains(x)
    if x.shape[1] < 4:
        # shorter than any meaningful autocorrelation window (a short
        # first segment under a small checkpoint_every): tau = 1, i.e.
        # every sample counts — ess degrades gracefully to T per chain
        # instead of dividing by a window the data cannot support
        # (device twin: stats/device.ess_device, parity-tested at tiny T)
        return np.ones(x.shape[0])
    rho = autocorrelation(x)
    # chain-averaged ACF gives a lower-variance window choice, but tau is
    # reported per chain from its own ACF with the shared window
    rho_mean = rho.mean(axis=0)
    taus_run = 2.0 * np.cumsum(rho_mean) - 1.0
    lags = np.arange(len(rho_mean))
    ok = lags >= c * taus_run
    m = int(np.argmax(ok)) if ok.any() else len(rho_mean) - 1
    m = max(m, 1)
    tau = 2.0 * rho[:, :m + 1].sum(axis=1) - 1.0
    return np.maximum(tau, 1.0)


def ess(x, c: float = 5.0):
    """Effective sample size. Returns ``(ess_per_chain, ess_total)`` where
    ``ess_total = sum_i T / tau_i`` — independent chains' effective samples
    add, each discounted by its own autocorrelation time."""
    x = _chains(x)
    tau = integrated_autocorr_time(x, c=c)
    per = x.shape[1] / tau
    return per, float(per.sum())


def gelman_rubin(x) -> float:
    """Split-R-hat across chains (each chain halved, so a single chain still
    yields a diagnostic). ~1.0 at convergence; > 1.1 signals poor mixing —
    on flip walks with small ``base`` this flags exactly the bottleneck
    phases the paper studies."""
    x = _chains(x)
    c, t = x.shape
    half = t // 2
    if half < 2:
        raise ValueError("need T >= 4 for split R-hat")
    halves = np.concatenate([x[:, :half], x[:, t - half:]], axis=0)
    m, n = halves.shape
    means = halves.mean(axis=1)
    variances = halves.var(axis=1, ddof=1)
    w = variances.mean()
    b = n * means.var(ddof=1)
    if w == 0:
        # zero within-chain variance: converged only if the chains also
        # agree; chains frozen at DIFFERENT values are maximally diverged
        # (the metastable regime this diagnostic exists to flag)
        return 1.0 if b == 0 else float("inf")
    var_plus = (n - 1) / n * w + b / n
    return float(np.sqrt(var_plus / w))


def autocorr_mixing_time(x, threshold: float = np.exp(-1.0)) -> float:
    """Exponential-autocorrelation-time estimate of mixing: the first lag at
    which the chain-averaged ACF of the observable drops below ``threshold``
    (default 1/e). This is the observable-relaxation proxy for the mixing
    time the paper bounds via bottleneck ratios; ``np.inf`` when the ACF
    never crosses within the recorded horizon.
    """
    rho = autocorrelation(_chains(x)).mean(axis=0)
    below = rho < threshold
    if not below.any():
        return float("inf")
    return float(np.argmax(below))


def well_crossings(x, lo: float, hi: float) -> np.ndarray:
    """Per-chain count of well-to-well transitions of a (C, T) trajectory
    between the metastable wells ``x < lo`` and ``x > hi``.

    Samples are classified low (-1) / high (+1) / transit (0); transit
    samples are dropped; each alternation of the remaining sign sequence
    is one crossing. This is the mode-mixing observable behind
    REPLICATION.md's plain-vs-tempered comparison on the bimodal FRANK
    B333 cell (wells |cut| < 40 and |cut| > 60, where that section's
    "round trips per chain" counted exactly these crossings — a chain
    whose only crossing is the one-way initial relaxation scores 1).
    """
    x = _chains(x)
    out = np.zeros(x.shape[0], dtype=np.int64)
    for c, row in enumerate(x):
        sign = np.where(row < lo, -1, np.where(row > hi, 1, 0))
        sign = sign[sign != 0]
        if sign.size < 2:
            continue
        out[c] = int((np.diff(sign) != 0).sum())
    return out


def round_trips(x, lo: float, hi: float) -> np.ndarray:
    """Per-chain COMPLETED round trips between the wells ``x < lo`` and
    ``x > hi``: two consecutive crossings (low->high->low or
    high->low->high) make one trip, so this is ``well_crossings // 2``
    and the one-way initial relaxation scores 0 — the stricter of the
    two mode-mixing counts (see ``well_crossings``)."""
    return well_crossings(x, lo, hi) // 2
