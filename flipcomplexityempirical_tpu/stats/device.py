"""Device-side ESS: the host estimators (diagnostics.py) need the full
(C, T) history on the host, which at bench scale is a ~200 MB readback —
on a tunneled TPU that readback dominates the whole "wall-clock to
target ESS" measurement (round 5: 18.8 s readback vs 0.7 s of chain).
This module computes the same Sokal-windowed integrated-autocorrelation
ESS as ``diagnostics.ess`` ON the device in f32, so the only readback is
one (C,) vector.

Algorithm parity: identical to ``diagnostics.integrated_autocorr_time``
(FFT autocovariance, biased normalization, chain-averaged ACF choosing
the adaptive window M = min{m : m >= c * tau(m)}, per-chain tau over the
shared window, tau >= 1) with two representational differences: f32
instead of f64 (tests pin agreement to ~0.1% on bench-scale
trajectories; f64 is not a TPU-native dtype) and a masked sum instead of
a dynamic slice for the windowed tau (the window M is data-dependent,
which XLA cannot shape a slice by).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("c",))
def ess_device(x, c: float = 5.0):
    """Effective sample size of a (C, T) device history, on device.

    Returns ``(ess_per_chain (C,), ess_total scalar)`` matching
    ``diagnostics.ess`` (independent chains add, each discounted by its
    own integrated autocorrelation time).
    """
    x = jnp.asarray(x, jnp.float32)
    if x.ndim == 1:
        x = x[None, :]
    ch, t = x.shape
    xc = x - x.mean(axis=1, keepdims=True)
    n_fft = 1
    while n_fft < 2 * t:
        n_fft *= 2
    f = jnp.fft.rfft(xc, n=n_fft, axis=1)
    acov = jnp.fft.irfft(f * jnp.conj(f), n=n_fft, axis=1)[:, :t] / t
    var = acov[:, :1]
    rho = jnp.where(var > 0, acov / jnp.where(var > 0, var, 1.0), 0.0)
    rho = rho.at[:, 0].set(1.0)

    rho_mean = rho.mean(axis=0)
    taus_run = 2.0 * jnp.cumsum(rho_mean) - 1.0
    lags = jnp.arange(t, dtype=jnp.float32)
    ok = lags >= c * taus_run
    m = jnp.where(ok.any(), jnp.argmax(ok), t - 1)
    m = jnp.maximum(m, 1)
    window = (jnp.arange(t) <= m).astype(jnp.float32)
    tau = jnp.maximum(2.0 * (rho * window[None, :]).sum(axis=1) - 1.0, 1.0)
    per = t / tau
    return per, per.sum()
