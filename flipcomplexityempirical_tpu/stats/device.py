"""Device-side ESS: the host estimators (diagnostics.py) need the full
(C, T) history on the host, which at bench scale is a ~200 MB readback —
on a tunneled TPU that readback dominates the whole "wall-clock to
target ESS" measurement (round 5: 18.8 s readback vs 0.7 s of chain).
This module computes the same Sokal-windowed integrated-autocorrelation
ESS as ``diagnostics.ess`` ON the device in f32, so the only readback is
one (C,) vector.

Algorithm parity: identical to ``diagnostics.integrated_autocorr_time``
(FFT autocovariance, biased normalization, chain-averaged ACF choosing
the adaptive window M = min{m : m >= c * tau(m)}, per-chain tau over the
shared window, tau >= 1) with two representational differences: f32
instead of f64 (tests pin agreement to ~0.1% on bench-scale
trajectories; f64 is not a TPU-native dtype) and a masked sum instead of
a dynamic slice for the windowed tau (the window M is data-dependent,
which XLA cannot shape a slice by).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("c",))
def ess_device(x, c: float = 5.0):
    """Effective sample size of a (C, T) device history, on device.

    Returns ``(ess_per_chain (C,), ess_total scalar)`` matching
    ``diagnostics.ess`` (independent chains add, each discounted by its
    own integrated autocorrelation time).
    """
    x = jnp.asarray(x, jnp.float32)
    if x.ndim == 1:
        x = x[None, :]
    ch, t = x.shape
    if t < 4:
        # host-parity tiny-T guard (diagnostics.integrated_autocorr_time
        # returns tau = 1 below any meaningful window): ess = T per
        # chain. t is a static shape, so the Python branch is trace-safe
        # — and it sidesteps the t=0/1 FFT division-by-zero entirely.
        per = jnp.full((ch,), float(t), jnp.float32)
        return per, per.sum()
    xc = x - x.mean(axis=1, keepdims=True)
    n_fft = 1
    while n_fft < 2 * t:
        n_fft *= 2
    f = jnp.fft.rfft(xc, n=n_fft, axis=1)
    acov = jnp.fft.irfft(f * jnp.conj(f), n=n_fft, axis=1)[:, :t] / t
    var = acov[:, :1]
    rho = jnp.where(var > 0, acov / jnp.where(var > 0, var, 1.0), 0.0)
    rho = rho.at[:, 0].set(1.0)

    rho_mean = rho.mean(axis=0)
    taus_run = 2.0 * jnp.cumsum(rho_mean) - 1.0
    lags = jnp.arange(t, dtype=jnp.float32)
    ok = lags >= c * taus_run
    m = jnp.where(ok.any(), jnp.argmax(ok), t - 1)
    m = jnp.maximum(m, 1)
    window = (jnp.arange(t) <= m).astype(jnp.float32)
    tau = jnp.maximum(2.0 * (rho * window[None, :]).sum(axis=1) - 1.0, 1.0)
    per = t / tau
    return per, per.sum()


@jax.jit
def conductance_profile_device(x, thresholds):
    """Device twin of ``bottleneck.conductance_profile`` for a (C, T)
    device history: Phi(S_r) over level sets S_r = {f <= r}, the paper's
    bottleneck-ratio estimator, without the history readback.

    ``thresholds`` is a concrete array (jit shapes the bincounts by its
    static length; the host default of "unique observed values" is
    data-dependent and cannot be shaped — pass e.g.
    ``jnp.arange(lo, hi + 1)`` for integer observables like cut counts,
    or a linspace), sorted HERE at trace time to match the host twin's
    unconditional sort (ADVICE r5: an unsorted grid previously produced
    silently wrong searchsorted bins). For f32-representable observables
    (every integer
    trajectory this framework records) the occupancy/crossing counts and
    the two-sided mask are exact int32 arithmetic (valid up to 2^31
    transitions = C*(T-1)) and only ONE final division is f32 vs the
    host's f64 (tests pin parity). A continuous observable is BINNED in
    f32 here vs f64 on host, so samples within f32 epsilon of a
    threshold may land on the other side of it — prefer thresholds away
    from data values in that regime.
    """
    x = jnp.asarray(x, jnp.float32)
    if x.ndim == 1:
        x = x[None, :]
    if x.shape[1] < 2:
        # static shape: raise at trace time like the host path, instead
        # of 0/0 -> all-NaN masquerading as the frozen-observable verdict
        raise ValueError("need T >= 2 transitions")
    thresholds = jnp.sort(jnp.asarray(thresholds, jnp.float32))
    nb = thresholds.shape[0]
    cur = x[:, :-1].ravel()
    nxt = x[:, 1:].ravel()
    n_trans = cur.shape[0]
    # bin once: b(v) = first threshold index >= v, so v <= thresholds[i]
    # iff b(v) <= i (same trick as the host path)
    bc = jnp.searchsorted(thresholds, cur, side="left")
    bn = jnp.searchsorted(thresholds, nxt, side="left")
    counts = jnp.cumsum(jnp.bincount(bc, length=nb + 1)[:nb])
    # transitions crossing out of S_i (b(cur) <= i < b(nxt)) accumulate
    # via a difference array; non-crossing rows park in the dropped slot
    out = bc < bn
    diff = (jnp.bincount(jnp.where(out, bc, nb), length=nb + 1)
            - jnp.bincount(jnp.where(out, bn, nb), length=nb + 1))
    crossings = jnp.cumsum(diff[:nb])
    # the two-sided mask and the denominator stay EXACT integers — an
    # occupancy division in f32 would round a level set missing only a
    # few of >2^24 transitions to exactly 1.0 and mask a finite phi the
    # host estimator reports (the headline config is 24.6M transitions).
    # Host algebra (c/n)/min(m/n, (n-m)/n) == c/min(m, n-m): one final
    # f32 divide carries the only rounding
    two_sided = (counts > 0) & (counts < n_trans)
    min_count = jnp.minimum(counts, n_trans - counts)
    phi = jnp.where(
        two_sided,
        crossings.astype(jnp.float32)
        / jnp.where(two_sided, min_count, 1).astype(jnp.float32),
        jnp.nan)
    return thresholds, phi


@jax.jit
def bottleneck_ratio_device(x, thresholds):
    """Device twin of ``bottleneck.bottleneck_ratio``: ``(phi_star,
    r_star)`` = the minimum Phi(S_r) and its threshold, ``(nan, nan)``
    when no level set is two-sided (frozen observable)."""
    thresholds, phi = conductance_profile_device(x, thresholds)
    filled = jnp.where(jnp.isnan(phi), jnp.inf, phi)
    i = jnp.argmin(filled)
    bad = jnp.isinf(filled[i])
    return (jnp.where(bad, jnp.nan, phi[i]),
            jnp.where(bad, jnp.nan, thresholds[i]))


@jax.jit
def gelman_rubin_device(x):
    """Device twin of ``diagnostics.gelman_rubin`` (split R-hat): chains
    halved, within/between variances, sqrt(var_plus / W) — the
    convergence reading of a device-resident history without readback.
    f32 vs the host's f64; the frozen contracts match (1.0 when every
    half-chain is constant AND they agree, inf when constant halves
    disagree)."""
    x = jnp.asarray(x, jnp.float32)
    if x.ndim == 1:
        x = x[None, :]
    t = x.shape[1]
    half = t // 2
    if half < 2:
        raise ValueError("need T >= 4 for split R-hat")
    # R-hat is shift-invariant, so center on the grand mean BEFORE
    # halving (ADVICE r5): the f32 cancellation residue of the variance
    # then scales with the CENTERED magnitude, not the raw offset — an
    # observable sitting at a large offset with genuinely small variance
    # (std ~0.1% of its magnitude) no longer trips the frozen floor.
    scale = jnp.abs(x).max()
    x = x - x.mean()
    halves = jnp.concatenate([x[:, :half], x[:, t - half:]], axis=0)
    n = halves.shape[1]
    means = halves.mean(axis=1)
    variances = halves.var(axis=1, ddof=1)
    w = variances.mean()
    b = n * means.var(ddof=1)
    var_plus = (n - 1) / n * w + b / n
    # frozen contract under f32+jit: XLA's fused mean/variance leaves
    # eps-scale residue on constant inputs, so both zero tests carry a
    # scale-relative tolerance against the RAW scale (centering itself
    # rounds at ~eps * offset, i.e. w-residue ~(eps*scale)^2 ~ 1.4e-14 *
    # scale^2 — the 1e-10 floor keeps ~100x margin over it instead of
    # the old 1e-6 floor's ~1e8x, ADVICE r5), and agreement is judged on
    # the SPREAD of the half-chain means rather than on b's residue. A
    # genuinely mixing observable has w and spread orders of magnitude
    # above these floors.
    frozen = w <= 1e-10 * scale * scale + 1e-30
    spread = means.max() - means.min()
    return jnp.where(
        ~frozen, jnp.sqrt(var_plus / jnp.where(frozen, 1.0, w)),
        jnp.where(spread > 1e-6 * scale, jnp.inf, 1.0))


def integer_thresholds(x):
    """Concrete integer level-set grid spanning a device history's range
    — the required ``thresholds`` boilerplate for integer observables
    (cut counts), shared by bench.py and the examples. One fused min/max
    readback: jit shapes the profile's bincounts by the grid's STATIC
    length, so the bounds must be concrete Python numbers."""
    import math

    lo, hi = (float(v) for v in
              jax.device_get(jnp.stack([jnp.min(x), jnp.max(x)])))
    return jnp.arange(math.floor(lo), math.ceil(hi) + 1.0,
                      dtype=jnp.float32)
