"""Device-resident analytics: in-scan summary accumulators (ISSUE 20).

The runners historically exfiltrated a full ``(C, T_chunk)`` history
block to the host every chunk to feed ChainMonitor, the control loop,
and the paper's artifact stats. Every one of those consumers only needs
summary statistics — moments, a bounded thinning buffer for split
R-hat/ESS, cumulative accept/wait counters, and (for the artifact
renderers) the chain-0 interface series. This module folds all of them
into the scan itself: a :class:`SummaryAcc` pytree rides the scan carry
of every kernel body, and per-chunk readback becomes one small summary
dict (a few hundred bytes) instead of the history block.

Contracts, all parity-tested against the post-hoc oracles:

- **Welford moments** (``n``/``mean``/``m2``): single-pass per-chain
  updates. ChainMonitor's host fold is f64 block-merge; this fold is f32
  per-step (f64 is not an accelerator-native dtype) — agreement is
  pinned to fp tolerance, exact in the integer-valued regimes the paper
  runs (cut counts well under 2^24).
- **Lazy-uniform weighted moments** (``wsum``/``wmean``/``wm2``): the
  lazy-chain reweighting (weight ``1 + wait``) computed where the
  geometric draws already live, so lazy-uniform expectations never need
  the trajectory.
- **Thinning buffer** (``buf``/``kept``/``stride``): byte-for-byte the
  stride-doubling thinning of ``ChainMonitor._fold_buffer`` fed one
  sample at a time — keep when ``n % stride == 0``, decimate ``[::2]``
  and double the stride at ``cap``. ``BufferMirror`` replays the same
  recurrence on the host from step counts alone, so the runner always
  knows ``kept``/``stride``/``filled`` without reading anything back.
- **Diagnostics** (:func:`summary_diagnostics`): split R-hat and Sokal
  ESS over ``buf[:, :filled]`` via the existing ``stats.device``
  oracles — when the buffer is unthinned these are exactly the post-hoc
  numbers; with stride ``s`` the ESS is scaled back up by ``s`` exactly
  as ChainMonitor does.
- **Heatmap tensors**: the per-edge cut-frequency and per-node
  flip-count tensors already live in the chain state (the board path's
  ``cut_times_*``/``num_flips`` bookkeeping, the general path's
  ``cut_times``). They are device-resident by construction and read
  back once at run end — the accumulator deliberately does not duplicate
  them; parity is pinned by the summary-vs-history state bit-match
  tests.
- **Artifact series** (``series``): optional full-length chain-0 series
  (interface ``slope``/``angle``) written by global step index, read
  back once at run end so the artifact renderers bit-match the
  history-mode PNGs. This is the only O(T) tensor in the pytree and it
  never moves during the run.

``fold_out(acc, out)`` is the single hook every scan body calls on the
per-yield ``out`` dict it already computes; a body whose carry holds
``acc=None`` traces to exactly the graph it traced before this module
existed (None is an empty pytree — the hot path is untouched).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from .device import ess_device, gelman_rubin_device

# host mirror of the summary() leaf set, in emission order
SUMMARY_FIELDS = ("n", "mean", "m2", "wsum", "wmean", "wm2", "accepts",
                  "waits", "kept", "stride")


@struct.dataclass
class SummaryAcc:
    """In-scan summary accumulator (one per run, carried chunk to chunk).

    All leaves are device arrays; ``observable`` (the ``out`` key folded
    into the moments/buffer) is a static aux field so two accs over
    different observables are distinct treedefs.
    """
    n: jnp.ndarray        # () int32   samples folded
    mean: jnp.ndarray     # (C,) f32   per-chain running mean
    m2: jnp.ndarray       # (C,) f32   per-chain sum of squared deviations
    wsum: jnp.ndarray     # (C,) f32   lazy-uniform total weight (n + waits)
    wmean: jnp.ndarray    # (C,) f32   lazy-uniform weighted mean
    wm2: jnp.ndarray      # (C,) f32   lazy-uniform weighted M2
    accepts: jnp.ndarray  # (C,) i32   cumulative accepts at last fold
    waits: jnp.ndarray    # (C,) f32   cumulative completed waits folded
    buf: jnp.ndarray      # (C, L) f32 stride-doubling thinning buffer
    kept: jnp.ndarray     # () int32   live columns of ``buf``
    stride: jnp.ndarray   # () int32   current keep-stride
    series: dict          # name -> (T_cap,) f32 chain-0 series (may be {})
    observable: str = struct.field(pytree_node=False, default="cut_count")


def init_summary(n_chains: int, *, cap: int = 4096,
                 observable: str = "cut_count",
                 series_keys=(), series_cap: int = 0) -> SummaryAcc:
    """Fresh accumulator for ``n_chains`` chains.

    ``cap`` (even, >= 8) bounds the thinning buffer; ``series_keys``
    requests full-length chain-0 series (each ``(series_cap,)``) for the
    artifact renderers — pass the run's total recorded steps.
    """
    cap = int(cap)
    if cap < 8 or cap % 2:
        raise ValueError("summary buffer cap must be even and >= 8")
    if series_keys and series_cap <= 0:
        raise ValueError("series_keys needs a positive series_cap")
    zc = jnp.zeros((n_chains,), jnp.float32)
    return SummaryAcc(
        n=jnp.zeros((), jnp.int32), mean=zc, m2=zc, wsum=zc, wmean=zc,
        wm2=zc, accepts=jnp.zeros((n_chains,), jnp.int32), waits=zc,
        buf=jnp.zeros((n_chains, cap), jnp.float32),
        kept=jnp.zeros((), jnp.int32), stride=jnp.ones((), jnp.int32),
        series={k: jnp.zeros((int(series_cap),), jnp.float32)
                for k in series_keys},
        observable=observable)


def fold_out(acc: SummaryAcc, out: dict) -> SummaryAcc:
    """Fold one yield's ``out`` dict (the per-step record every kernel
    body already computes) into the accumulator. Trace-safe inside
    ``lax.scan`` bodies; O(C + cap) per step."""
    x = out[acc.observable].astype(jnp.float32)           # (C,)
    n1 = (acc.n + 1).astype(jnp.float32)
    delta = x - acc.mean
    mean = acc.mean + delta / n1
    m2 = acc.m2 + delta * (x - mean)

    wait = out.get("wait")
    w = (jnp.ones_like(x) if wait is None
         else 1.0 + wait.astype(jnp.float32))
    wsum = acc.wsum + w
    wd = x - acc.wmean
    wmean = acc.wmean + wd * (w / wsum)
    wm2 = acc.wm2 + w * wd * (x - wmean)
    waits = acc.waits + (0.0 if wait is None
                         else wait.astype(jnp.float32))

    accepts = acc.accepts
    if "accepts" in out:
        accepts = out["accepts"].astype(jnp.int32)

    # --- thinning buffer: ChainMonitor._fold_buffer fed (C, 1) blocks.
    # Decimate-then-append is identical to the host's append-then-
    # decimate because cap is even: [0..L][::2] keeps the appended
    # column at position L and the even old columns, exactly the
    # decimated-prefix + append below.
    cap = acc.buf.shape[1]
    keep = (acc.n % acc.stride) == 0
    full = keep & (acc.kept >= cap)
    dec = jnp.concatenate(
        [acc.buf[:, ::2], jnp.zeros_like(acc.buf[:, : cap - cap // 2])],
        axis=1)
    buf0 = jnp.where(full, dec, acc.buf)
    kept0 = jnp.where(full, cap // 2, acc.kept)
    stride = jnp.where(full, acc.stride * 2, acc.stride)
    appended = lax.dynamic_update_slice(buf0, x[:, None], (0, kept0))
    buf = jnp.where(keep, appended, buf0)
    kept = jnp.where(keep, kept0 + 1, kept0)

    series = {k: lax.dynamic_update_slice(
        buf_k, out[k][0].astype(jnp.float32)[None], (acc.n,))
        for k, buf_k in acc.series.items()}

    return acc.replace(n=acc.n + 1, mean=mean, m2=m2, wsum=wsum,
                       wmean=wmean, wm2=wm2, accepts=accepts, waits=waits,
                       buf=buf, kept=kept, stride=stride, series=series)


def fold_block(acc: SummaryAcc, block: dict) -> SummaryAcc:
    """Fold a stacked ``(T, C)`` history block one step at a time —
    the promotion of the post-hoc oracles to a streaming fold. Used by
    the parity tests and by consumers holding a device history."""
    def body(a, row):
        return fold_out(a, row), None
    acc, _ = lax.scan(body, acc, block)
    return acc


def summary(acc: SummaryAcc) -> dict:
    """The per-chunk readback pytree: every leaf O(C) or scalar — the
    buffer and series stay on device. Order matches SUMMARY_FIELDS."""
    return {"n": acc.n, "mean": acc.mean, "m2": acc.m2, "wsum": acc.wsum,
            "wmean": acc.wmean, "wm2": acc.wm2, "accepts": acc.accepts,
            "waits": acc.waits, "kept": acc.kept, "stride": acc.stride}


def summary_nbytes(acc_or_summary) -> int:
    """Honest readback accounting for one summary pytree."""
    s = (summary(acc_or_summary) if isinstance(acc_or_summary, SummaryAcc)
         else acc_or_summary)
    return int(sum(int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
                   for v in s.values()))


def summary_host(acc_or_summary) -> dict:
    """Host copy of a summary dict (numpy leaves)."""
    s = (summary(acc_or_summary) if isinstance(acc_or_summary, SummaryAcc)
         else acc_or_summary)
    return {k: np.asarray(v) for k, v in s.items()}


def summary_diagnostics(acc: SummaryAcc, filled: int):
    """Split R-hat + Sokal ESS over the live buffer prefix, on device.

    ``filled`` is static (the host BufferMirror knows it without a
    readback); needs ``filled >= 4`` (gelman_rubin splits chains in
    half). Returns ``(rhat (), ess_total ())`` device scalars — the
    caller scales ESS by the mirrored stride (each kept sample stands
    for ``stride`` raw samples), matching ChainMonitor._diagnostics.
    """
    if filled < 4:
        raise ValueError("summary_diagnostics needs >= 4 kept samples")
    window = lax.slice_in_dim(acc.buf, 0, int(filled), axis=1)
    rhat = gelman_rubin_device(window)
    _, ess_total = ess_device(window)
    return rhat, ess_total


def summary_allreduce(s: dict, axis_name: str) -> dict:
    """Mesh form of a summary dict, for use inside pmap/shard_map with
    chains sharded over ``axis_name``: per-chain leaves are gathered to
    the global chain axis (R-hat needs every chain's moments — they are
    per-chain independent, so a gather IS the merge), pooled counters
    are ``psum``'d. Histories are not psum-able; summaries are."""
    out = {}
    for k, v in s.items():
        if v.ndim == 1:                       # per-chain: (C_local,)
            g = lax.all_gather(v, axis_name)  # (shards, C_local)
            out[k] = g.reshape((-1,))
        else:
            out[k] = v
    out["pooled_accepts"] = lax.psum(s["accepts"].sum(), axis_name)
    out["pooled_wsum"] = lax.psum(s["wsum"].sum(), axis_name)
    return out


class BufferMirror:
    """Host replay of the buffer recurrence: ``kept``/``stride``/``n``
    are deterministic functions of samples-seen and cap, so the host
    never reads the counters back. Parity with the device fold is
    pinned by tests."""

    def __init__(self, cap: int = 4096):
        self.cap = int(cap)
        self.n = 0
        self.kept = 0
        self.stride = 1

    def advance(self, steps: int) -> None:
        for _ in range(int(steps)):
            if self.n % self.stride == 0:
                if self.kept >= self.cap:
                    self.kept = self.cap // 2
                    self.stride *= 2
                self.kept += 1
            self.n += 1


class DeviceAnalytics:
    """Host coordinator for one run's device-resident analytics.

    Owns the :class:`SummaryAcc` (handed to the kernel each chunk and
    replaced with the fold result), the host :class:`BufferMirror`, and
    the diagnostics refresh policy: R-hat/ESS recompile per distinct
    buffer length, so they refresh when the kept count doubles (<=
    log2(cap) specializations) and once at run end, not every chunk.

    Nothing here syncs implicitly: ``summary_refs`` returns device
    refs (stash-safe on the board path's no-mid-run-sync contract);
    ``summary_host``/``maybe_diagnostics``/``series_host`` are the
    explicit, byte-accounted readbacks.
    """

    def __init__(self, n_chains: int, *, cap: int = 4096,
                 observable: str = "cut_count", series_keys=(),
                 series_cap: int = 0):
        self.acc = init_summary(n_chains, cap=cap, observable=observable,
                                series_keys=series_keys,
                                series_cap=series_cap)
        self.mirror = BufferMirror(cap)
        self.rhat = None          # latest device-diag values (host floats)
        self.ess = None
        self._diag_at = 0         # kept count at last refresh
        self.readback_bytes = 0   # cumulative explicit readback

    def update(self, acc: SummaryAcc, steps: int) -> None:
        """Adopt the post-chunk accumulator; advance the host mirror."""
        self.acc = acc
        self.mirror.advance(steps)

    def summary_refs(self) -> dict:
        """Device refs of the small summary pytree — no sync."""
        return summary(self.acc)

    def chunk_readback_bytes(self) -> int:
        return summary_nbytes(self.acc)

    def summary_to_host(self) -> dict:
        s = summary_host(self.acc)
        self.readback_bytes += summary_nbytes(self.acc)
        return s

    def maybe_diagnostics(self, force: bool = False):
        """Refresh (rhat, ess) from the device buffer when the kept
        count has doubled since the last refresh (or ``force``, for run
        end). Returns the current (possibly stale) values."""
        filled = self.mirror.kept
        if filled >= 4 and (force or filled >= 2 * max(self._diag_at, 2)):
            rhat_d, ess_d = summary_diagnostics(self.acc, filled)
            rhat = float(np.asarray(rhat_d))
            ess = float(np.asarray(ess_d)) * self.mirror.stride
            self.rhat = rhat if np.isfinite(rhat) else None
            self.ess = ess if np.isfinite(ess) else None
            self._diag_at = filled
            self.readback_bytes += 8
        return self.rhat, self.ess

    def series_host(self) -> dict:
        """Run-end readback of the chain-0 artifact series, trimmed to
        the folded length."""
        t = self.mirror.n
        out = {}
        for k, v in self.acc.series.items():
            out[k] = np.asarray(v)[:t]
            self.readback_bytes += out[k].nbytes
        return out
