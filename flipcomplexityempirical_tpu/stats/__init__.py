"""Batched chain diagnostics: the "same stats interface" of the north star.

The reference records trajectories (cut counts, boundary sizes, waits) but
ships no analysis code — its diagnostics were visual (SURVEY.md section 4).
This package supplies the quantitative layer the BASELINE.json north star
names: mixing-time / autocorrelation / ESS and bottleneck-ratio estimators
that consume batched ``(n_chains, T)`` histories exactly as ``run_chains``
returns them, plus the partisan metrics the reference imports but never
calls (mean_median / efficiency_gap, grid_chain_sec11.py:20-30) and
district compactness scores for real-geometry dual graphs.

``accumulators`` promotes the device oracles to *in-scan folds*: a
``SummaryAcc`` pytree carried through the chunk scans streams Welford
moments, lazy-uniform weighted moments, and a stride-doubling thinning
buffer entirely on device, so a run's telemetry readback shrinks to one
small summary pytree per chunk (``DeviceAnalytics`` is the host-side
wrapper the runners take via ``analytics=``).
"""

from .diagnostics import (
    autocorrelation, integrated_autocorr_time, ess, gelman_rubin,
    autocorr_mixing_time, round_trips, well_crossings,
)
from .bottleneck import conductance_profile, bottleneck_ratio
from .partisan import (
    district_vote_tallies, mean_median, efficiency_gap, seats_won,
)
from .compactness import polsby_popper, cut_edge_count, perimeter_area
from .device import (bottleneck_ratio_device,
                     conductance_profile_device, ess_device,
                     gelman_rubin_device, integer_thresholds)
from .accumulators import (
    SummaryAcc, init_summary, fold_out, fold_block, summary,
    summary_nbytes, summary_host, summary_diagnostics, summary_allreduce,
    BufferMirror, DeviceAnalytics,
)

__all__ = [
    "SummaryAcc", "init_summary", "fold_out", "fold_block", "summary",
    "summary_nbytes", "summary_host", "summary_diagnostics",
    "summary_allreduce", "BufferMirror", "DeviceAnalytics",
    "autocorrelation", "integrated_autocorr_time", "ess", "ess_device", "bottleneck_ratio_device",
    "conductance_profile_device", "gelman_rubin_device",
    "integer_thresholds", "gelman_rubin",
    "autocorr_mixing_time", "round_trips", "well_crossings",
    "conductance_profile", "bottleneck_ratio",
    "district_vote_tallies", "mean_median", "efficiency_gap", "seats_won",
    "polsby_popper", "cut_edge_count", "perimeter_area",
]
