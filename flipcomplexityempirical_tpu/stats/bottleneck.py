"""Bottleneck-ratio (conductance) estimation from chain trajectories.

The paper's central objects are bottleneck ratios: for a set S of states,
Phi(S) = Q(S, S^c) / pi(S) where Q is the edge measure of the chain. A small
Phi(S) certifies slow mixing (Cheeger: t_mix >= 1/(4 Phi)). Exact state-space
enumeration is exponential, but along a scalar observable f (cut count,
signed imbalance, ...) the level sets S_r = {x : f(x) <= r} have empirically
estimable conductance: pi(S_r) from occupation frequencies and Q(S_r, S_r^c)
from observed boundary crossings. The minimum over r is the trajectory
bottleneck ratio — the "CPU bottleneck-ratio estimates" the BASELINE.json
north star says must be reproduced, now fed by (C, T) batched histories.
"""

from __future__ import annotations

import numpy as np


def conductance_profile(x, thresholds=None):
    """Estimate Phi(S_r) for level sets S_r = {f <= r} of observable ``x``
    shaped (C, T) (or (T,)).

    Pools transitions across chains (each chain contributes T-1 transitions).
    Returns ``(thresholds, phi)`` with ``phi[i] = (crossings out of S_r /
    n_transitions) / min(occupancy, 1 - occupancy)`` — the symmetric form
    Phi(S) = Q(S, S^c) / min(pi(S), pi(S^c)), NaN where the level set (or
    its complement) is never visited.
    """
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1:
        x = x[None, :]
    c, t = x.shape
    if t < 2:
        raise ValueError("need T >= 2 transitions")
    if thresholds is None:
        uniq = np.unique(x)
        thresholds = uniq if len(uniq) <= 256 else \
            np.linspace(uniq[0], uniq[-1], 257)
    thresholds = np.sort(np.asarray(thresholds, dtype=np.float64))

    cur, nxt = x[:, :-1].ravel(), x[:, 1:].ravel()
    n_trans = cur.size
    nb = len(thresholds)
    # Bin once instead of scanning per threshold (O(C*T + B)):
    # b(v) = index of the first threshold >= v, so v <= thresholds[i]
    # iff b(v) <= i.
    bc = np.searchsorted(thresholds, cur, side="left")
    bn = np.searchsorted(thresholds, nxt, side="left")
    # occupancy of S_i = fraction with b(cur) <= i
    occ = np.cumsum(np.bincount(bc, minlength=nb + 1)[:nb]) / n_trans
    # a transition crosses out of S_i iff b(cur) <= i < b(nxt): contributes
    # to i in [b(cur), b(nxt)); accumulate via a difference array
    out = bc < bn
    diff = (np.bincount(bc[out], minlength=nb + 1)
            - np.bincount(bn[out], minlength=nb + 1))
    crossings = np.cumsum(diff[:nb])
    two_sided = (occ > 0.0) & (occ < 1.0)
    phi = np.full(nb, np.nan)
    denom = np.minimum(occ, 1.0 - occ)
    phi[two_sided] = (crossings[two_sided] / n_trans) / denom[two_sided]
    return thresholds, phi


def bottleneck_ratio(x, thresholds=None) -> tuple[float, float]:
    """The trajectory bottleneck ratio: ``min_r Phi(S_r)`` over the observed
    level sets, with the minimizing threshold. Returns ``(phi_star, r_star)``;
    ``(nan, nan)`` when no level set is two-sided (frozen observable)."""
    thresholds, phi = conductance_profile(x, thresholds)
    if np.all(np.isnan(phi)):
        return float("nan"), float("nan")
    i = int(np.nanargmin(phi))
    return float(phi[i]), float(thresholds[i])
