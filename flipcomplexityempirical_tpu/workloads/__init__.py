from .registry import (
    ResolvedWorkload, WorkloadSpec, get, names, register, resolve, specs,
)

__all__ = ["WorkloadSpec", "ResolvedWorkload", "register", "get",
           "names", "specs", "resolve"]
