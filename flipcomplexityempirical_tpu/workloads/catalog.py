"""The named workload catalog.

Every entry is a complete, tier-1-runnable scenario: the surgical
reference grids (sec11, Frankengraph), k∈{2,4,8}-district seeded
partitions on synthetic lattices, the committed precinct-style
dual-graph fixture (workloads/data/, ingested through the production
``from_geojson`` path), the ReCom chain family on both, and the
proposal variants (non-backtracking flip per arxiv 1204.4140,
lazy-uniform reweighting riding the geometric waiting-time machinery).

Run shapes are tuned small enough for a CPU smoke run inside the tier-1
budget; the CLI's ``--steps``/``--chains`` and bench's flags override
them without re-registering. ``kernel_path`` values are the DECLARED
dispatch expectations — tests/test_workloads.py asserts they match what
``lower.dispatch.kernel_path_for`` actually resolves, so a workload
silently falling off its fast path fails the suite.
"""

from __future__ import annotations

from ..experiments.config import MU
from .registry import WorkloadSpec, register

_W = register


# --- surgical reference grids -------------------------------------------
_W(WorkloadSpec(
    name="sec11",
    family="sec11",
    description="40x40 surgical sec11 grid, reference B263P10 cell, "
                "bit-packed lowered stencil body",
    overrides=(("alignment", 2), ("base", MU), ("pop_tol", 0.1),
               ("total_steps", 5000), ("n_chains", 8)),
    kernel_path="lowered_bits",
))
_W(WorkloadSpec(
    name="frank",
    family="frank",
    description="Frankengraph B333P10 cell (slow-mixing bimodal regime)",
    overrides=(("alignment", 2), ("base", 1 / .3), ("pop_tol", 0.1),
               ("total_steps", 5000), ("n_chains", 8)),
    kernel_path="lowered_bits",
))

# --- k-district seeded partitions on synthetic lattices -----------------
for _k in (2, 4, 8):
    _W(WorkloadSpec(
        name=f"grid-k{_k}",
        family="kpair",
        description=f"k={_k} pair walk on a 32x32 rook grid (width a "
                    f"multiple of 32, so the packed bit body applies), "
                    f"stripes seed plan",
        overrides=(("alignment", 0), ("base", 0.8), ("pop_tol", 0.5),
                   ("n_districts", _k), ("grid", 32),
                   ("total_steps", 4000), ("n_chains", 8)),
        kernel_path="bitboard",
    ))

# --- precinct-style dual-graph fixture (real ingestion path) ------------
for _k in (2, 4, 8):
    _W(WorkloadSpec(
        name="dual-fixture" if _k == 2 else f"dual-fixture-k{_k}",
        family="dual",
        description=f"k={_k} on the committed 80-precinct GeoJSON "
                    f"fixture via from_geojson (weighted-cut walk, "
                    f"compactness + partisan artifacts)",
        overrides=(("alignment", 0), ("base", MU), ("pop_tol", 0.25),
                   ("n_districts", _k), ("dual_source", "fixture"),
                   ("total_steps", 1500), ("n_chains", 4)),
        kernel_path="general_dense",
        stats=("compactness", "partisan"),
    ))

# --- ReCom chain family (sampling/recom.py) -----------------------------
_W(WorkloadSpec(
    name="recom-grid",
    family="kpair",
    description="spanning-tree ReCom, k=4 on an 8x8 grid — the second "
                "chain family; ~100x flip per-step cost, so few steps",
    overrides=(("alignment", 0), ("base", 1.0), ("pop_tol", 0.25),
               ("n_districts", 4), ("grid", 8),
               ("total_steps", 40), ("n_chains", 4)),
    chain="recom",
    kernel_path="recom",
))
_W(WorkloadSpec(
    name="recom-dual",
    family="dual",
    description="ReCom k=4 on the committed precinct fixture",
    overrides=(("alignment", 0), ("base", 1.0), ("pop_tol", 0.4),
               ("n_districts", 4), ("dual_source", "fixture"),
               ("total_steps", 30), ("n_chains", 2)),
    chain="recom",
    kernel_path="recom",
    stats=("compactness", "partisan"),
))

# --- proposal variants --------------------------------------------------
_W(WorkloadSpec(
    name="sec11-nobacktrack",
    family="sec11",
    description="non-backtracking flip proposal (arxiv 1204.4140) on "
                "the sec11 grid — excludes the last-flipped node from "
                "the boundary draw; runs the rejection-free dense "
                "general kernel",
    overrides=(("alignment", 2), ("base", MU), ("pop_tol", 0.1),
               ("total_steps", 3000), ("n_chains", 8)),
    variant="nobacktrack",
    kernel_path="general_dense",
))
_W(WorkloadSpec(
    name="frank-lazy",
    family="frank",
    description="lazy-uniform reweighting on the Frankengraph — "
                "per-sample weight 1 + geometric wait, riding the "
                "existing waiting-time machinery",
    overrides=(("alignment", 2), ("base", 1 / .3), ("pop_tol", 0.1),
               ("total_steps", 3000), ("n_chains", 8)),
    variant="lazy",
    kernel_path="general_dense",
))
