"""Committed workload fixtures.

``precincts_10x8.geojson`` is a deterministic precinct-style
FeatureCollection (generated once by ``graphs.dualgraph
.synthetic_precincts(10, 8, seed=20260806)`` and committed) so
dual-graph workloads exercise the REAL ingestion path —
``from_geojson`` polygon->rook-adjacency extraction, the same code
``graphs/shapefile.py``-loaded shapefiles take — without a network
fetch or an optional GIS dependency. 80 jittered quads, POP/NAME
properties, ~heterogeneous populations in [80, 120].
"""

from __future__ import annotations

import json
import os

_FIXTURE = "precincts_10x8.geojson"


def fixture_path() -> str:
    return os.path.join(os.path.dirname(__file__), _FIXTURE)


def load_fixture() -> dict:
    """The parsed FeatureCollection, ready for ``from_geojson``."""
    with open(fixture_path()) as f:
        return json.load(f)
