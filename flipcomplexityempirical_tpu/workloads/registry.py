"""Declarative workload registry: named, fingerprintable scenarios.

A *workload* is a runnable scenario with a stable name — a graph family,
a seed plan, a chain family (flip or ReCom), proposal-variant flags, and
the tuned run shape that makes it complete inside the tier-1 budget.
The registry turns "which experiment is this?" from a bag of CLI flags
into one token that every layer can key on: the CLI (`--workload NAME`),
the bench matrix (`--workload-matrix`), the service (jobs built from a
workload coalesce/journal under the underlying config fingerprint), and
bench_compare (`[workload=…]`-qualified metrics, so families never
cross-gate).

``WorkloadSpec`` is declarative — a frozen record of config overrides —
and ``resolve`` is the single materialisation path: it builds the
``ExperimentConfig``, runs the SAME ``build_graph_and_plan``/``spec_for``
the driver runs, and reports the dispatch-ladder rung
(``lower.dispatch.kernel_path_for``) the runners will actually select.
The ``kernel_path`` field on the spec is the *declared expectation*;
tests assert declared == resolved so a dispatch regression (a workload
silently falling off its fast path) fails loudly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Iterable, Tuple


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """One catalog entry. ``overrides`` is a sorted tuple of
    (field, value) pairs applied to ``ExperimentConfig`` — a tuple, not
    a dict, so the spec is hashable and its fingerprint is canonical."""
    name: str
    family: str               # ExperimentConfig.family
    description: str
    overrides: Tuple[Tuple[str, Any], ...] = ()
    chain: str = "flip"       # 'flip' | 'recom' (second chain family)
    variant: str = "none"     # 'none' | 'nobacktrack' | 'lazy'
    kernel_path: str = "general"  # expected dispatch rung ('recom' for
                                  # the ReCom chain family)
    stats: Tuple[str, ...] = ()   # artifact stat bundles the driver
                                  # attaches ('compactness', 'partisan')

    def to_config(self, **extra):
        """Materialise the ExperimentConfig. ``extra`` wins over the
        spec's overrides (CLI --steps/--chains tweak a workload without
        re-registering it) but never over family/chain/variant — those
        ARE the workload's identity."""
        from ..experiments.config import ExperimentConfig
        kw = dict(self.overrides)
        kw.update(extra)
        return ExperimentConfig(family=self.family, chain=self.chain,
                                variant=self.variant, **kw)

    def fingerprint(self) -> str:
        """Content hash of the full declaration (sorted canonical JSON).
        Distinct from ``ExperimentConfig.fingerprint()`` — that one keys
        kernel coalescing; this one names the catalog entry's contents,
        so a tuned override change moves the workload fingerprint even
        when the compiled kernel is unchanged."""
        payload = {
            "name": self.name,
            "family": self.family,
            "overrides": sorted([k, _jsonable(v)]
                                for k, v in self.overrides),
            "chain": self.chain,
            "variant": self.variant,
            "kernel_path": self.kernel_path,
            "stats": sorted(self.stats),
        }
        blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _jsonable(v):
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return v


@dataclasses.dataclass
class ResolvedWorkload:
    """What a name buys you: the graph, the seed plan, the kernel Spec,
    and the dispatch rung the runners will take — everything the driver,
    bench, and service need to run the scenario."""
    workload: WorkloadSpec
    config: Any               # ExperimentConfig
    graph: Any                # LatticeGraph
    plan: Any                 # (n_nodes,) seed assignment
    geo: Any                  # GeoAttributes or None (dual graphs only)
    spec: Any                 # kernel Spec
    kernel_path: str          # resolved rung (may differ from declared
                              # on dispatch regressions — tests compare)


_REGISTRY: Dict[str, WorkloadSpec] = {}
_CATALOG_LOADED = False


def _ensure_catalog() -> None:
    """Lazy-import the catalog so `import workloads` stays cheap and the
    registry module has no import cycle with catalog.py."""
    global _CATALOG_LOADED
    if not _CATALOG_LOADED:
        _CATALOG_LOADED = True
        from . import catalog  # noqa: F401  (registers on import)


def register(spec: WorkloadSpec, *, replace: bool = False) -> WorkloadSpec:
    if not replace and spec.name in _REGISTRY:
        raise ValueError(f"workload {spec.name!r} already registered")
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> WorkloadSpec:
    _ensure_catalog()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown workload {name!r}; known: {', '.join(names())}"
        ) from None


def names() -> Tuple[str, ...]:
    _ensure_catalog()
    return tuple(sorted(_REGISTRY))


def specs() -> Iterable[WorkloadSpec]:
    _ensure_catalog()
    return [_REGISTRY[n] for n in sorted(_REGISTRY)]


def resolve(name: str, **extra) -> ResolvedWorkload:
    """Name -> (config, graph, plan, geo, spec, kernel_path), through the
    driver's own builders so there is exactly one materialisation path."""
    wl = get(name) if isinstance(name, str) else name
    cfg = wl.to_config(**extra)
    from ..experiments.driver import build_graph_and_plan, spec_for
    g, plan, geo = build_graph_and_plan(cfg)
    spec = spec_for(cfg)
    if cfg.chain == "recom":
        path = "recom"            # ReCom is a chain family, not a rung
    else:
        from ..lower.dispatch import kernel_path_for
        path = kernel_path_for(g, spec)
    return ResolvedWorkload(workload=wl, config=cfg, graph=g, plan=plan,
                            geo=geo, spec=spec, kernel_path=path)
