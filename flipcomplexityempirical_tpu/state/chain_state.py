"""ChainState: the complete per-chain state of a flip walk as a JAX pytree.

Replaces gerrychain's object graph (Partition + lazy updater dicts,
SURVEY.md section 3.3) with dense arrays whose derived fields (cut mask,
per-node incident-cut counts, district tallies) are maintained incrementally
by the kernel and are, invariantly, pure functions of ``assignment`` —
``derive()`` recomputes them from scratch and tests assert the kernel never
lets them drift.

All fields are single-chain; the runner vmaps over a leading chains axis.
Accumulator fields mirror the reference's graph-attribute metric store
(grid_chain_sec11.py:383-400: cut_times per edge, num_flips/last_flipped/
part_sum per node) and its in-memory lists (waits).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..graphs.lattice import DeviceGraph


@struct.dataclass
class ChainState:
    key: jnp.ndarray           # PRNG key data, uint32[2]
    assignment: jnp.ndarray    # int8[N] district index 0..K-1
    cut: jnp.ndarray           # int8[E] 0/1 cut-edge indicator
    cut_deg: jnp.ndarray       # int8[N] number of incident cut edges
    dist_pop: jnp.ndarray      # int32[K]
    cut_count: jnp.ndarray     # int32 scalar
    b_count: jnp.ndarray       # int32 scalar |b_nodes|
    cur_wait: jnp.ndarray      # float32 scalar, memoized geometric wait
    cur_flip_node: jnp.ndarray  # int32 scalar, -1 until first acceptance
    t_yield: jnp.ndarray       # int32 scalar, number of yields recorded
    move_clock: jnp.ndarray    # int32 scalar: accepted moves since init —
                               # the reference's step_num; load-bearing for
                               # Spec.anneal schedules, NEVER reset mid-run
                               # (unlike the accept_count telemetry below)
    # accumulators (reference metric store)
    part_sum: jnp.ndarray      # int32[N] time-integral of signed membership
    last_flipped: jnp.ndarray  # int32[N]
    num_flips: jnp.ndarray     # int32[N]
    cut_times: jnp.ndarray     # int32[E]
    waits_sum: jnp.ndarray     # float32 scalar (chunk-local; host sums f64)
    # telemetry
    accept_count: jnp.ndarray  # int32
    tries_sum: jnp.ndarray     # int32 proposals drawn (incl. invalid retries)
    exhausted_count: jnp.ndarray  # int32 re-propose loops that hit the cap
    # reject-reason taxonomy (ISSUE 3): int32[4] counts of proposals lost
    # to [non-boundary, pop-bound, disconnect, Metropolis]. None (the
    # default everywhere) keeps the pytree treedef — and thus every
    # compiled graph and checkpoint — identical to before; runners
    # enable it with .replace(reject_count=zeros) when a recorder is
    # attached, which respecializes the jit via the treedef change.
    reject_count: Optional[jnp.ndarray] = None
    # packed per-node contiguity plane (ISSUE 15): uint32[ceil(N/32)],
    # bit i == "flipping node i keeps its origin district connected" for
    # the CURRENT assignment. Same trailing-Optional contract as
    # reject_count: None keeps the treedef (and every compiled graph and
    # checkpoint) identical; only the general_dense kernel enables it
    # (kernel/dense.py maintains it incrementally), and runners strip it
    # again before states escape.
    conn_bits: Optional[jnp.ndarray] = None

    @property
    def n_districts(self) -> int:
        return self.dist_pop.shape[-1]


def pair_move_mask(dg: DeviceGraph, a_i: jnp.ndarray, k: int, nodes=None):
    """(N, K) bool: the k-district pair move set — district d is present
    among node v's neighbors and differs from v's own (the reference's
    b_nodes pair updater, grid_chain_sec11.py:151-153, a SET of distinct
    (node, district) pairs). ``nodes`` restricts to a row subset (the
    incremental updater's affected rows), returning (len(nodes), K)."""
    nbr = dg.nbr if nodes is None else dg.nbr[nodes]
    nbm = dg.nbr_mask if nodes is None else dg.nbr_mask[nodes]
    own = a_i if nodes is None else a_i[nodes]
    nbr_a = a_i[nbr]                                         # (R, D)
    onehot = jax.nn.one_hot(nbr_a, k, dtype=jnp.bool_)       # (R, D, K)
    onehot = onehot & nbm[:, :, None]
    has_part = onehot.any(axis=1)                            # (R, K)
    return has_part & (jnp.arange(k)[None, :] != own[:, None])


def b_nodes_count(dg: DeviceGraph, assignment, cut_deg, k: int,
                  proposal: str):
    """|b_nodes| as the reference wires it per chain flavor: boundary
    NODES for the 2-district 'bi' walk (b_nodes_bi), distinct (node,
    district) PAIRS for the k-district pair walk (b_nodes pairs) — the
    value geom_wait's p = |b_nodes| / (n**k - 1) consumes."""
    if proposal == "pair":
        a_i = assignment.astype(jnp.int32)
        return pair_move_mask(dg, a_i, k).astype(jnp.int32).sum()
    return (cut_deg > 0).astype(jnp.int32).sum()


def derive(dg: DeviceGraph, assignment: jnp.ndarray, k: int,
           proposal: str = "bi"):
    """Recompute all derived fields from the assignment (the invariant
    checker, and the initializer)."""
    a = assignment.astype(jnp.int32)
    cut = (a[dg.edges[:, 0]] != a[dg.edges[:, 1]]).astype(jnp.int8)
    # incident-cut counts: each edge contributes to both endpoints
    cut_deg = jnp.zeros(dg.n_nodes, jnp.int32)
    cut_deg = cut_deg.at[dg.edges[:, 0]].add(cut.astype(jnp.int32))
    cut_deg = cut_deg.at[dg.edges[:, 1]].add(cut.astype(jnp.int32))
    dist_pop = jnp.zeros(k, jnp.int32).at[a].add(dg.pop)
    cut_count = cut.astype(jnp.int32).sum()
    b_count = b_nodes_count(dg, assignment, cut_deg, k, proposal)
    return cut, cut_deg.astype(jnp.int8), dist_pop, cut_count, b_count


def init_state(dg: DeviceGraph, assignment: jnp.ndarray, k: int,
               key: jnp.ndarray, label_values: jnp.ndarray,
               sample_initial_wait=None, proposal: str = "bi") -> ChainState:
    """Build the initial ChainState. ``label_values[district]`` is the
    reference's +1/-1 labeling used to seed part_sum
    (grid_chain_sec11.py:219: part_sum starts at the signed label).
    ``sample_initial_wait(key, b_count) -> float32`` seeds the memoized
    geometric wait of the initial state; None leaves it 0 (metrics off)."""
    assignment = assignment.astype(jnp.int8)
    cut, cut_deg, dist_pop, cut_count, b_count = derive(dg, assignment, k,
                                                       proposal)
    key, kw = jax.random.split(key)
    if sample_initial_wait is not None:
        wait = sample_initial_wait(kw, b_count)
    else:
        wait = jnp.float32(0.0)
    return ChainState(
        key=key,
        assignment=assignment,
        cut=cut,
        cut_deg=cut_deg,
        dist_pop=dist_pop,
        cut_count=cut_count,
        b_count=b_count,
        cur_wait=wait,
        cur_flip_node=jnp.int32(-1),
        t_yield=jnp.int32(0),
        part_sum=label_values[assignment.astype(jnp.int32)].astype(jnp.int32),
        last_flipped=jnp.zeros(dg.n_nodes, jnp.int32),
        num_flips=jnp.zeros(dg.n_nodes, jnp.int32),
        cut_times=jnp.zeros(dg.n_edges, jnp.int32),
        waits_sum=jnp.float32(0.0),
        move_clock=jnp.int32(0),
        accept_count=jnp.int32(0),
        tries_sum=jnp.int32(0),
        exhausted_count=jnp.int32(0),
    )
