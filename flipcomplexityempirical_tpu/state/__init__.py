from .chain_state import ChainState, derive, init_state

__all__ = ["ChainState", "derive", "init_state"]
