"""Stencil IR + lowering pass: compile near-grid graphs onto the board path.

The board kernel (kernel/board.py) executes a *stencil program*: every
per-step quantity is an elementwise combination of shifted copies of one
flat (C, N) plane. Historically its compile target was hardcoded — a full
HxW rook grid — which excluded the two graphs the source paper actually
studies: the sec11 corner-surgery grid (4 corner nodes removed, 4 diagonal
bypass edges) and the Frankengraph square+triangular composite (a seam of
diagonal edges). Both are *near-grid*: integer 2-tuple labels whose every
edge is a king move on the label lattice.

``lower_to_stencil`` embeds any such graph into an HxW canvas and emits a
``StencilSpec`` — the static plane set the generalized kernel bodies
consume:

- ``node_mask`` / ``cell_of_node``: the canvas embedding (holes = removed
  nodes and padding cells; hole cells carry district -1, population 0,
  degree 0, and are excluded from every count and from selection);
- ``adj``: 8 per-direction neighbor-existence planes in the kernel's ring
  order E, SE, S, SW, W, NW, N, NE — masked stencil reads replace the
  rook row-wrap masks, and diagonal edges are just two more planes;
- B2-window contiguity tables (``b2_offsets`` / ``b2_in`` / ``b2_adj`` /
  ``nbr_bits``): the general path's radius-2 ``patch_connected`` check
  re-expressed over *static flat canvas offsets* with per-cell membership
  masks, so the kernel can run the exact bitset label propagation with no
  gathers (see kernel/board.py::_stencil_patch_ok). Keying the tables by
  flat offset (not (dr, dc)) makes small-width aliasing impossible by
  construction: the offset IS the target cell. The ring's 8 direction
  planes do need distinct flat offsets, hence the h, w >= 3 requirement.
  On plain rook grids the kernel keeps its cheaper ring criterion (proven
  equivalent there); with diagonal edges the ring shortcut is *wrong*
  (a diagonal can bridge ring-nonadjacent neighbors), so the lowered body
  always uses the B2 propagation.
- wall/interface planes (``iface_key``): for ``record_interface`` specs,
  each wall edge's canonical index and doubled midpoint coordinates are
  packed into one int32 key per (forward direction, cell); the kernel
  min-reduces keys over the cut planes and decodes the two lowest —
  reproducing kernel/step.py::interface_metrics' deterministic
  "two smallest-index wall-cut edges" selection with no per-step gather.

``lower_to_stencil`` returns None for anything it cannot embed exactly
(non-integer labels, non-king edges, tiny or wasteful canvases, oversized
B2 windows); callers fall back to the general kernel. ``stencil_for``
caches per graph identity. This module is pure numpy — it imports no
kernel code, so the kernel layer can import it without cycles.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

from ..graphs.lattice import LatticeGraph

# ring order shared with kernel/board.py::same_planes: (dx, dy) label
# deltas; canvas row = x - xmin, col = y - ymin, flat = row * W + col
RING_DELTAS = ((0, 1), (1, 1), (1, 0), (1, -1),
               (0, -1), (-1, -1), (-1, 0), (-1, 1))
# forward (canonical, smaller-endpoint-first) directions: E, SE, S, SW
N_FWD = 4
# composite interface keys must stay positive int32 below the sentinel
IFACE_BIG = np.int32(2 ** 30)
_MAX_B2_OFFSETS = 30       # bitset lives in a signed int32 plane
_MAX_CANVAS_WASTE = 4.0    # reject canvases > 4x the node count


@dataclasses.dataclass(frozen=True, eq=False)
class StencilSpec:
    """Static lowering artifact: everything the board kernel needs to run
    a near-grid graph, as numpy planes over the HxW canvas (N = H*W).
    K = number of distinct B2-window flat offsets; E = graph edge count."""

    name: str
    h: int
    w: int
    origin: tuple                 # (xmin, ymin) label of canvas cell 0
    n_real: int                   # real node count (== graph.n_nodes)
    plain: bool                   # full rook grid (no surgery)
    uniform_pop: bool
    node_mask: np.ndarray         # bool[N] cell holds a real node
    cell_of_node: np.ndarray      # int32[n_real] canvas cell of node i
    pop: np.ndarray               # int32[N] node population (0 at holes)
    deg: np.ndarray               # int32[N] graph degree (0 at holes)
    adj: np.ndarray               # bool[8, N] ring-order edge existence
    # --- B2-window contiguity tables (patch_connected, offset-keyed) ---
    b2_offsets: tuple             # K static flat canvas offsets
    b2_in: np.ndarray             # bool[K, N] offset k in patch(cell)
    b2_adj: np.ndarray            # int32[K, N] bitset: offsets adjacent
                                  #   to cell+offset_k within patch(cell)
    nbr_bits: np.ndarray          # int32[N] bitset of direct-nbr offsets
    b2_disp: Optional[tuple]      # K (dr, dc) label displacements, one per
                                  #   b2_offset; None when a flat offset is
                                  #   realized by two distinct (dr, dc)
                                  #   pairs (only possible at w <= 4) —
                                  #   packed bodies need the 2-D form
    b2_iters: int                 # propagation rounds (max patch size - 1)
    patch_exact: bool             # B2 tables == graph patch tables
    # --- canonical edge mapping (cut_times in LatticeGraph edge order) ---
    edge_plane: np.ndarray        # int8[E] forward ring dir (0..3)
    edge_cell: np.ndarray         # int32[E] cell of the smaller endpoint
    # --- interface (wall) planes for record_interface ---
    iface_ok: bool
    iface_key: Optional[np.ndarray]   # int32[4, N], IFACE_BIG = no wall
    iface_decode: tuple               # (qx_off, qy_off, bx, by)
    center: tuple                 # (cx, cy) float

    @property
    def surgical(self) -> bool:
        """Anything beyond a plain full rook grid (holes, diagonals)."""
        return not self.plain

    @property
    def n(self) -> int:
        return self.h * self.w


def _int_label(lab) -> bool:
    return (isinstance(lab, tuple) and len(lab) == 2
            and all(isinstance(v, (int, np.integer)) for v in lab))


def _radius2_patches(n: int, nbr_lists) -> list[list[int]]:
    """Radius-2 BFS balls excluding the center, neighbors first — the
    same construction (and member order) as graphs/lattice.py's patch
    tables at patch_radius=2."""
    patches = []
    for v in range(n):
        first = list(nbr_lists[v])
        seen = {v, *first}
        ordered = list(first)
        for j in first:
            for k2 in nbr_lists[j]:
                if k2 not in seen:
                    seen.add(k2)
                    ordered.append(k2)
        patches.append(ordered)
    return patches


def lower_to_stencil(graph: LatticeGraph) -> Optional[StencilSpec]:
    """Embed ``graph`` into the board kernel's stencil representation, or
    return None when no exact embedding exists (caller falls back to the
    general kernel). Accepts any graph whose labels are integer 2-tuples
    and whose every edge is a king move on the label lattice: full rook
    grids, grids with removed nodes, extra diagonal/queen edges, and
    seamed composites like the Frankengraph."""
    labs = list(graph.labels)
    n_real = graph.n_nodes
    if n_real == 0 or not all(_int_label(l) for l in labs):
        return None
    xs = np.array([l[0] for l in labs], np.int64)
    ys = np.array([l[1] for l in labs], np.int64)
    xmin, ymin = int(xs.min()), int(ys.min())
    h = int(xs.max()) - xmin + 1
    w = int(ys.max()) - ymin + 1
    # the 8 ring directions must map to 8 DISTINCT flat offsets
    if h < 3 or w < 3:
        return None
    n = h * w
    if n > max(64, _MAX_CANVAS_WASTE * n_real):
        return None
    cell_of_node = ((xs - xmin) * w + (ys - ymin)).astype(np.int32)
    # canonical node order must be canvas row-major order (sorted lex
    # labels guarantee it; a custom node_order may not)
    if not bool(np.all(np.diff(cell_of_node) > 0)):
        return None

    fwd_of_delta = {d: i for i, d in enumerate(RING_DELTAS[:N_FWD])}
    edges = np.asarray(graph.edges, np.int64)
    e = edges.shape[0]
    edge_plane = np.empty(e, np.int8)
    edge_cell = np.empty(e, np.int32)
    for ei in range(e):
        a, b = int(edges[ei, 0]), int(edges[ei, 1])
        delta = (int(xs[b] - xs[a]), int(ys[b] - ys[a]))
        d = fwd_of_delta.get(delta)
        if d is None:         # not a king move (a < b => forward delta)
            return None
        edge_plane[ei] = d
        edge_cell[ei] = cell_of_node[a]

    node_mask = np.zeros(n, bool)
    node_mask[cell_of_node] = True
    pop = np.zeros(n, np.int32)
    pop[cell_of_node] = np.asarray(graph.pop, np.int32)
    adj = np.zeros((8, n), bool)
    for ei in range(e):
        d = int(edge_plane[ei])
        ca = int(edge_cell[ei])
        dx, dy = RING_DELTAS[d]
        cb = ca + dx * w + dy
        adj[d, ca] = True
        adj[(d + 4) % 8, cb] = True
    deg = adj.sum(axis=0).astype(np.int32)

    rook = h * (w - 1) + (h - 1) * w
    plain = (n == n_real and e == rook
             and bool(np.all(edge_plane % 2 == 0)))

    # --- B2 contiguity tables: radius-2 patches keyed by flat offset ---
    nbr_lists: list[list[int]] = [[] for _ in range(n_real)]
    for a, b in edges:
        nbr_lists[a].append(int(b))
        nbr_lists[b].append(int(a))
    patches = _radius2_patches(n_real, nbr_lists)
    max_patch = max((len(p) for p in patches), default=0)
    offset_set: set[int] = set()
    for v, pl in enumerate(patches):
        cv = int(cell_of_node[v])
        offset_set.update(int(cell_of_node[u]) - cv for u in pl)
    b2_offsets = tuple(sorted(offset_set))
    k = len(b2_offsets)
    if k > _MAX_B2_OFFSETS:
        return None
    off_idx = {o: i for i, o in enumerate(b2_offsets)}
    b2_in = np.zeros((k, n), bool)
    b2_adj = np.zeros((k, n), np.int32)
    nbr_bits = np.zeros(n, np.int32)
    nbrsets = [set(nl) for nl in nbr_lists]
    # 2-D displacement behind each flat offset: packed (bit-board) bodies
    # shift rows and columns separately, so they need (dr, dc), not dr*w+dc.
    # A flat offset realized by two distinct (dr, dc) pairs (needs
    # |dc|, |dc'| <= 2 with (dr - dr') * w == dc' - dc, i.e. w <= 4) makes
    # the 2-D form ill-defined — record None and let dispatch skip packing.
    disp_of_off: dict[int, tuple] = {}
    disp_ambiguous = False
    for v, pl in enumerate(patches):
        cv = int(cell_of_node[v])
        slot = {u: off_idx[int(cell_of_node[u]) - cv] for u in pl}
        for u in pl:
            o = int(cell_of_node[u]) - cv
            d2 = (int(xs[u] - xs[v]), int(ys[u] - ys[v]))
            if disp_of_off.setdefault(o, d2) != d2:
                disp_ambiguous = True
        for u, ku in slot.items():
            b2_in[ku, cv] = True
            word = 0
            for u2 in nbrsets[u]:
                k2 = slot.get(u2)
                if k2 is not None:
                    word |= 1 << k2
            b2_adj[ku, cv] = word
        for u in nbr_lists[v]:
            nbr_bits[cv] |= 1 << slot[u]
    b2_disp = (None if disp_ambiguous
               else tuple(disp_of_off[o] for o in b2_offsets))
    b2_iters = max(max_patch - 1, 0)
    patch_exact = bool(graph.patch_ok) and all(
        set(np.asarray(graph.patch_nodes[v, :graph.patch_size[v]]).tolist())
        == set(patches[v]) for v in range(n_real))

    # --- interface planes (two smallest-index wall-cut edges) ----------
    wall_id = np.asarray(graph.wall_id, np.int64)
    wall = wall_id >= 0
    coords = np.asarray(graph.coords, np.float64)
    iface_ok = False
    iface_key = None
    iface_decode = (0, 0, 0, 0)
    if bool(wall.any()):
        we = np.nonzero(wall)[0]
        q = coords[edges[we, 0]] + coords[edges[we, 1]]   # 2 * midpoint
        if bool(np.all(q == np.round(q))):
            qi = q.astype(np.int64)
            qx_off, qy_off = int(qi[:, 0].min()), int(qi[:, 1].min())
            bx = max(int(qi[:, 0].max()) - qx_off, 1).bit_length()
            by = max(int(qi[:, 1].max()) - qy_off, 1).bit_length()
            ebits = max(int(we.max()), 1).bit_length()
            if ebits + bx + by <= 30:
                iface_key = np.full((N_FWD, n), IFACE_BIG, np.int32)
                for j, ei in enumerate(we):
                    key = ((int(ei) << (bx + by))
                           | ((int(qi[j, 0]) - qx_off) << by)
                           | (int(qi[j, 1]) - qy_off))
                    iface_key[edge_plane[ei], edge_cell[ei]] = key
                iface_decode = (qx_off, qy_off, bx, by)
                iface_ok = True

    pops = np.asarray(graph.pop)
    return StencilSpec(
        name=graph.name, h=h, w=w, origin=(xmin, ymin), n_real=n_real,
        plain=plain,
        uniform_pop=bool(pops.size) and bool((pops == pops[0]).all()),
        node_mask=node_mask, cell_of_node=cell_of_node, pop=pop, deg=deg,
        adj=adj, b2_offsets=b2_offsets, b2_in=b2_in, b2_adj=b2_adj,
        nbr_bits=nbr_bits, b2_disp=b2_disp, b2_iters=b2_iters,
        patch_exact=patch_exact,
        edge_plane=edge_plane, edge_cell=edge_cell,
        iface_ok=iface_ok, iface_key=iface_key, iface_decode=iface_decode,
        center=(float(graph.center[0]), float(graph.center[1])))


@functools.lru_cache(maxsize=16)
def stencil_for(graph: LatticeGraph) -> Optional[StencilSpec]:
    """Cached ``lower_to_stencil`` (LatticeGraph is frozen with eq=False,
    so the cache keys on object identity — builders return fresh objects,
    but every layer of one run shares the same graph instance)."""
    return lower_to_stencil(graph)
