"""Stencil lowering subsystem: compile near-grid graphs onto the board
kernel's masked-plane representation (see lower/stencil.py docstring)."""

from .dispatch import kernel_path_for
from .stencil import IFACE_BIG, StencilSpec, lower_to_stencil, stencil_for

__all__ = ["IFACE_BIG", "StencilSpec", "kernel_path_for",
           "lower_to_stencil", "stencil_for"]
