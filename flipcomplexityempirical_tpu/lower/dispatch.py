"""Kernel-path resolution: which body will run a (graph, spec) workload.

The dispatch order is packed lowered stencil -> int8 lowered stencil ->
bitboard -> int8 board -> general: ``kernel/board.py::supports`` decides
whether the board family applies at all (via the lowering pass), and
``body_for`` picks the body within it. This module exposes that decision as a cheap, import-light
query for tagging — bench records, obs events, reports — so fallback
regressions show up in scoreboards instead of silently running 50x
slower. Kernel imports happen lazily inside the functions to keep
``lower`` importable from the kernel layer without cycles.
"""

from __future__ import annotations

import hashlib

from ..graphs.lattice import LatticeGraph
from .stencil import stencil_for

# The dispatch order, fastest body first. Degradation (resilience.degrade)
# walks this ladder downward when a body fails to compile or run — but
# only between bodies that share a state layout: lowered_bits -> lowered
# and bitboard -> board are in-segment retries (each pair carries the
# same BoardState), and general_dense -> general is in-segment on the
# general runner (both carry ChainState; the dense rung's extra
# conn_bits plane is stripped on the way down). Board-family ->
# general_dense/general means a config-level restart on the general
# runner.
DISPATCH_LADDER = ("lowered_bits", "lowered", "bitboard", "board",
                   "general_dense", "general")


def next_path(path: str) -> str | None:
    """The next-slower rung of the dispatch ladder, or None at the
    bottom (and for unknown paths)."""
    try:
        i = DISPATCH_LADDER.index(path)
    except ValueError:
        return None
    return DISPATCH_LADDER[i + 1] if i + 1 < len(DISPATCH_LADDER) else None


def kernel_path_for(graph: LatticeGraph, spec) -> str:
    """'lowered_bits' | 'lowered' | 'bitboard' | 'board' |
    'general_dense' | 'general' — the body the runners will select for
    this workload (sampling/board_runner.py + kernel/board.py::
    run_board_chunk dispatch, bits=None auto; sampling/runner.py
    general-family dispatch, kernel_path=None auto)."""
    from ..kernel import bitboard, board, dense

    if not board.supports(graph, spec):
        return "general_dense" if dense.supported(graph, spec) \
            else "general"
    st = stencil_for(graph)
    if st.surgical or spec.record_interface:
        # the packed-body gate duck-types on StencilSpec (uniform_pop,
        # b2_disp) just like the rook gates below
        return ("lowered_bits" if bitboard.supported_lowered(st, spec)
                else "lowered")
    # bitboard gates duck-type on (uniform_pop, w, n, surgical), which
    # StencilSpec provides — no BoardGraph construction needed here
    bits_ok = (bitboard.supported_pair(st, spec)
               if spec.proposal == "pair" else bitboard.supported(st, spec))
    return "bitboard" if bits_ok else "board"


def lowering_signature(graph: LatticeGraph, spec) -> str:
    """Stable content key for 'these workloads compile to the same
    kernel': the resolved dispatch-ladder path, the graph's topology
    (node/edge counts plus a hash of the edge list — graph NAMES are
    labels, not identity), and the full Spec statics (its frozen
    dataclass repr lists every field deterministically). Two (graph,
    spec) pairs with equal signatures trace to the same jaxpr modulo
    batch shape, so the service's compile cache keys on
    ``(lowering_signature, chain count, chunking)``. Returned as a
    short hex digest — a filename- and JSON-safe opaque token."""
    import numpy as np

    edges = np.ascontiguousarray(np.asarray(graph.edges, dtype=np.int64))
    h = hashlib.sha256()
    h.update(edges.tobytes())
    h.update(repr(edges.shape).encode())
    blob = (f"{kernel_path_for(graph, spec)}|n{graph.n_nodes}"
            f"|e{graph.n_edges}|{h.hexdigest()[:16]}|{spec!r}")
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]
