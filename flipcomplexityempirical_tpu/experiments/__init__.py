from .config import (
    ExperimentConfig, sec11_sweep, frank_sweep, MU,
    SEC11_BASES, SEC11_POPS, FRANK_BASES, FRANK_POPS,
)
from .driver import (
    run_config, run_sweep, is_done, build_graph_and_plan,
    save_checkpoint, load_checkpoint,
)
from .artifacts import ARTIFACT_KINDS

__all__ = [
    "ExperimentConfig", "sec11_sweep", "frank_sweep", "MU",
    "SEC11_BASES", "SEC11_POPS", "FRANK_BASES", "FRANK_POPS",
    "run_config", "run_sweep", "is_done", "build_graph_and_plan",
    "save_checkpoint", "load_checkpoint", "ARTIFACT_KINDS",
]
