from .config import (
    ExperimentConfig, sec11_sweep, frank_sweep, MU,
    SEC11_BASES, SEC11_POPS, FRANK_BASES, FRANK_POPS,
)
from .driver import (
    run_config, run_sweep, is_done, build_graph_and_plan,
    save_checkpoint, load_checkpoint, install_live_hooks,
)
from .artifacts import ARTIFACT_KINDS
from ..resilience.supervisor import (RetryPolicy, SweepReport,
                                     run_supervised_sweep)

__all__ = [
    "ExperimentConfig", "sec11_sweep", "frank_sweep", "MU",
    "SEC11_BASES", "SEC11_POPS", "FRANK_BASES", "FRANK_POPS",
    "run_config", "run_sweep", "is_done", "build_graph_and_plan",
    "save_checkpoint", "load_checkpoint", "install_live_hooks",
    "ARTIFACT_KINDS", "RetryPolicy", "SweepReport",
    "run_supervised_sweep",
]
