"""The 13-artifact-per-config pipeline, byte-compatible filenames.

Reproduces grid_chain_sec11.py:321-324,410-411,427-528 /
Frankenstein_chain.py:349-352,438-439,455-556: per config
{tag}start/edges/end/end2/wca/wca2/slope/angle/flip/flip2/logflip/logflip2
.png + {tag}wait.txt, with the reference's exact visual conventions
(node shapes, cmaps, node sizes, ylim, imshow index layout).
"""

from __future__ import annotations

import math
import os

import numpy as np

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from ..graphs.lattice import LatticeGraph


def _nx_graph(graph: LatticeGraph):
    import networkx as nx
    g = nx.Graph()
    g.add_nodes_from(graph.labels)
    for (a, b) in graph.edges:
        g.add_edge(graph.labels[a], graph.labels[b])
    return g


def _draw_nodes(graph, values, path, node_size, cmap="tab20"):
    import networkx as nx
    g = _nx_graph(graph)
    plt.figure()
    nx.draw(g, pos={x: x for x in graph.labels},
            node_color=[values[graph.index[x]] for x in graph.labels],
            node_size=node_size, node_shape="s", cmap=cmap)
    plt.savefig(path)
    plt.close()


def _draw_edges(graph, edge_values, path):
    import networkx as nx
    g = _nx_graph(graph)
    colors = {}
    for e in range(graph.n_edges):
        u = graph.labels[graph.edges[e, 0]]
        v = graph.labels[graph.edges[e, 1]]
        colors[frozenset((u, v))] = edge_values[e]
    plt.figure()
    nx.draw(g, pos={x: x for x in graph.labels},
            node_color=[0 for _ in graph.labels], node_size=10,
            edge_color=[colors[frozenset(e)] for e in g.edges()],
            node_shape="s", cmap="jet", width=5)
    plt.savefig(path)
    plt.close()


def _imshow(graph, family, values, path):
    # sec11: A2[40,40], A2[x,y] (grid_chain_sec11.py:440-443)
    # frank: A2[20,40], A2[x,y+19] (Frankenstein_chain.py:468-471)
    if family == "frank":
        a2 = np.zeros([20, 40])
        off = 19
    else:
        a2 = np.zeros([40, 40])
        off = 0
    for i, (x, y) in enumerate(graph.labels):
        a2[x, y + off] = values[i]
    plt.figure()
    plt.imshow(a2, cmap="jet")
    plt.colorbar()
    plt.savefig(path)
    plt.close()


def _lineplot(series, path, title, ylim=None):
    plt.figure()
    plt.title(title)
    plt.plot(series)
    if ylim is not None:
        plt.ylim(ylim)
    plt.savefig(path)
    plt.close()


def render_start(graph, family, outdir, tag, start_signed, node_size):
    _draw_nodes(graph, start_signed,
                os.path.join(outdir, tag + "start.png"), node_size)


def render_all(graph: LatticeGraph, family: str, outdir: str, tag: str, *,
               end_signed, cut_times, part_sum, num_flips, slopes, angles,
               waits_sum, node_size):
    """Render the 12 post-run artifacts + wait.txt (start.png is rendered
    before the run, as the reference does at grid_chain_sec11.py:321-324)."""
    os.makedirs(outdir, exist_ok=True)
    j = lambda kind: os.path.join(outdir, tag + kind)

    with open(j("wait.txt"), "w") as f:
        f.write(str(int(round(waits_sum))))

    lognum = np.array([math.log(n + 1) for n in num_flips])

    _draw_edges(graph, cut_times, j("edges.png"))
    _draw_nodes(graph, end_signed, j("end.png"), node_size)
    _imshow(graph, family, end_signed, j("end2.png"))
    _draw_nodes(graph, part_sum, j("wca.png"), node_size, cmap="jet")
    _imshow(graph, family, part_sum, j("wca2.png"))
    _lineplot(slopes, j("slope.png"), "Slopes")
    _lineplot(angles, j("angle.png"), "Angle", ylim=[0, 6.3])
    _draw_nodes(graph, num_flips, j("flip.png"), node_size, cmap="jet")
    _imshow(graph, family, num_flips, j("flip2.png"))
    _draw_nodes(graph, lognum, j("logflip.png"), node_size, cmap="jet")
    _imshow(graph, family, lognum, j("logflip2.png"))


ARTIFACT_KINDS = ["start.png", "edges.png", "end.png", "end2.png",
                  "wca.png", "wca2.png", "slope.png", "angle.png",
                  "flip.png", "flip2.png", "logflip.png", "logflip2.png",
                  "wait.txt"]
