"""The 13-artifact-per-config pipeline, byte-compatible filenames.

Reproduces grid_chain_sec11.py:321-324,410-411,427-528 /
Frankenstein_chain.py:349-352,438-439,455-556: per config
{tag}start/edges/end/end2/wca/wca2/slope/angle/flip/flip2/logflip/logflip2
.png + {tag}wait.txt, with the reference's exact visual conventions
(node shapes, cmaps, node sizes, ylim, imshow index layout).
"""

from __future__ import annotations

import math
import os

import numpy as np

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from ..graphs.lattice import LatticeGraph


def _nx_graph(graph: LatticeGraph):
    import networkx as nx
    g = nx.Graph()
    g.add_nodes_from(graph.labels)
    for (a, b) in graph.edges:
        g.add_edge(graph.labels[a], graph.labels[b])
    return g


def _positions(graph, pos=None):
    """Node positions for drawing: the labels themselves when they are
    coordinate tuples (the reference's pos={x: x}), else caller-provided
    (dual graphs pass precinct centroids)."""
    if pos is not None:
        return {lab: tuple(pos[graph.index[lab]]) for lab in graph.labels}
    return {x: x for x in graph.labels}


def _draw_nodes(graph, values, path, node_size, cmap="tab20", pos=None):
    import networkx as nx
    g = _nx_graph(graph)
    plt.figure()
    nx.draw(g, pos=_positions(graph, pos),
            node_color=[values[graph.index[x]] for x in graph.labels],
            node_size=node_size, node_shape="s", cmap=cmap)
    plt.savefig(path)
    plt.close()


def _draw_edges(graph, edge_values, path, pos=None):
    import networkx as nx
    g = _nx_graph(graph)
    colors = {}
    for e in range(graph.n_edges):
        u = graph.labels[graph.edges[e, 0]]
        v = graph.labels[graph.edges[e, 1]]
        colors[frozenset((u, v))] = edge_values[e]
    plt.figure()
    nx.draw(g, pos=_positions(graph, pos),
            node_color=[0 for _ in graph.labels], node_size=10,
            edge_color=[colors[frozenset(e)] for e in g.edges()],
            node_shape="s", cmap="jet", width=5)
    plt.savefig(path)
    plt.close()


def _imshow(graph, family, values, path):
    # sec11: A2[40,40], A2[x,y] (grid_chain_sec11.py:440-443)
    # frank: A2[20,40], A2[x,y+19] (Frankenstein_chain.py:468-471)
    # other families with integer-pair labels (e.g. kpair's plain rook
    # grid): the label bounding box
    if family == "frank":
        a2 = np.zeros([20, 40])
        xoff, yoff = 0, 19
    elif family == "sec11":
        a2 = np.zeros([40, 40])
        xoff, yoff = 0, 0
    else:
        xs = [l[0] for l in graph.labels]
        ys = [l[1] for l in graph.labels]
        a2 = np.zeros([max(xs) - min(xs) + 1, max(ys) - min(ys) + 1])
        xoff, yoff = -min(xs), -min(ys)
    for i, (x, y) in enumerate(graph.labels):
        a2[x + xoff, y + yoff] = values[i]
    plt.figure()
    plt.imshow(a2, cmap="jet")
    plt.colorbar()
    plt.savefig(path)
    plt.close()


def _lineplot(series, path, title, ylim=None):
    plt.figure()
    plt.title(title)
    plt.plot(series)
    if ylim is not None:
        plt.ylim(ylim)
    plt.savefig(path)
    plt.close()


def render_start(graph, family, outdir, tag, start_signed, node_size,
                 pos=None):
    os.makedirs(outdir, exist_ok=True)
    _draw_nodes(graph, start_signed,
                os.path.join(outdir, tag + "start.png"), node_size,
                pos=pos)


def render_all(graph: LatticeGraph, family: str, outdir: str, tag: str, *,
               end_signed, cut_times, part_sum, num_flips, slopes, angles,
               waits_sum, node_size):
    """Render the 12 post-run artifacts + wait.txt (start.png is rendered
    before the run, as the reference does at grid_chain_sec11.py:321-324)."""
    os.makedirs(outdir, exist_ok=True)
    j = lambda kind: os.path.join(outdir, tag + kind)

    with open(j("wait.txt"), "w") as f:
        f.write(str(int(round(waits_sum))))

    lognum = np.array([math.log(n + 1) for n in num_flips])

    _draw_edges(graph, cut_times, j("edges.png"))
    _draw_nodes(graph, end_signed, j("end.png"), node_size)
    _imshow(graph, family, end_signed, j("end2.png"))
    _draw_nodes(graph, part_sum, j("wca.png"), node_size, cmap="jet")
    _imshow(graph, family, part_sum, j("wca2.png"))
    _lineplot(slopes, j("slope.png"), "Slopes")
    _lineplot(angles, j("angle.png"), "Angle", ylim=[0, 6.3])
    _draw_nodes(graph, num_flips, j("flip.png"), node_size, cmap="jet")
    _imshow(graph, family, num_flips, j("flip2.png"))
    _draw_nodes(graph, lognum, j("logflip.png"), node_size, cmap="jet")
    _imshow(graph, family, lognum, j("logflip2.png"))


ARTIFACT_KINDS = ["start.png", "edges.png", "end.png", "end2.png",
                  "wca.png", "wca2.png", "slope.png", "angle.png",
                  "flip.png", "flip2.png", "logflip.png", "logflip2.png",
                  "wait.txt"]

# Per-family artifact manifests. sec11/frank keep the reference's full
# 13-artifact set byte-compatibly; the widened families emit the subset
# their walk defines (no slope/angle without wall-interface recording, no
# wca parity integral for k > 2 districts, no imshow off integer-pair
# labels) plus family-specific diagnostics.
FAMILY_ARTIFACTS = {
    "sec11": ARTIFACT_KINDS,
    "frank": ARTIFACT_KINDS,
    "kpair": ["start.png", "edges.png", "end.png", "end2.png",
              "flip.png", "flip2.png", "logflip.png", "logflip2.png",
              "wait.txt"],
    "tri": ["start.png", "edges.png", "end.png", "wca.png", "flip.png",
            "logflip.png", "wait.txt"],
    "hex": ["start.png", "edges.png", "end.png", "wca.png", "flip.png",
            "logflip.png", "wait.txt"],
    "temper": ["start.png", "edges.png", "end.png", "rungs.png",
               "swapstats.json", "wait.txt"],
    "dual": ["start.png", "edges.png", "end.png", "flip.png",
             "logflip.png", "compactness.json", "partisan.json",
             "wait.txt"],
}


def artifact_kinds(family: str):
    return FAMILY_ARTIFACTS[family]


def render_generic(graph, family: str, outdir: str, tag: str, *,
                   kinds, node_size, end_signed, cut_times, num_flips,
                   waits_sum, part_sum=None, pos=None):
    """The widened families' post-run artifacts: any subset of the
    reference kinds (start.png is rendered pre-run by render_start;
    family-specific diagnostics — rungs.png, swapstats.json,
    compactness.json — are written by the driver)."""
    os.makedirs(outdir, exist_ok=True)
    j = lambda kind: os.path.join(outdir, tag + kind)
    lognum = np.log(np.asarray(num_flips, np.float64) + 1.0)
    if "wait.txt" in kinds:
        with open(j("wait.txt"), "w") as f:
            f.write(str(int(round(waits_sum))))
    if "edges.png" in kinds:
        _draw_edges(graph, cut_times, j("edges.png"), pos=pos)
    if "end.png" in kinds:
        _draw_nodes(graph, end_signed, j("end.png"), node_size, pos=pos)
    if "end2.png" in kinds:
        _imshow(graph, family, end_signed, j("end2.png"))
    if "wca.png" in kinds:
        _draw_nodes(graph, part_sum, j("wca.png"), node_size, cmap="jet",
                    pos=pos)
    if "flip.png" in kinds:
        _draw_nodes(graph, num_flips, j("flip.png"), node_size,
                    cmap="jet", pos=pos)
    if "flip2.png" in kinds:
        _imshow(graph, family, num_flips, j("flip2.png"))
    if "logflip.png" in kinds:
        _draw_nodes(graph, lognum, j("logflip.png"), node_size,
                    cmap="jet", pos=pos)
    if "logflip2.png" in kinds:
        _imshow(graph, family, lognum, j("logflip2.png"))


def render_rungs(path, rung_cut, betas):
    """temper: per-rung reconstructed cut-count trajectories of ladder 0
    (the diagnostic the per-chain plots cannot show: after a swap the
    physical rung hops between chains)."""
    plt.figure()
    for r, beta in enumerate(betas):
        plt.plot(rung_cut[r], label=f"beta={beta:g}", lw=0.8)
    plt.legend(fontsize=7)
    plt.title("per-rung |cut| (ladder 0)")
    plt.savefig(path)
    plt.close()
