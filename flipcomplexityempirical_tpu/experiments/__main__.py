"""CLI: python -m flipcomplexityempirical_tpu.experiments
         --family sec11 --out plots/sec11 [--steps N] [--backend jax]
         [--only 2B30P10 ...]
     or: ... --workload dual-fixture --out plots/wl
         (one named catalog scenario; --list-workloads enumerates)

Runs the reference sweep grids — or a single named workload from the
catalog (workloads/catalog.py) — with skip-if-done resume, emitting the
13-artifact set per config with reference-compatible filenames.

Sweeps run SUPERVISED by default (resilience.supervisor): each config is
isolated, transient failures retry with seeded exponential backoff and
resume from the last checkpoint, deterministic failures quarantine the
config after a repeat, and the process exits nonzero when anything was
quarantined or exhausted its retries. ``--no-supervise`` restores the
bare fail-fast loop. ``--faults`` (or the GRAFT_FAULTS env var) installs
a deterministic fault-injection plan for chaos testing — see
resilience/faults.py for the grammar.
"""

import argparse
import os
import sys

from ..obs import from_spec
from ..resilience import faults as rfaults
from ..resilience.supervisor import RetryPolicy, run_supervised_sweep
from .config import SWEEPS
from .driver import run_sweep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=sorted(SWEEPS), default=None,
                    help="run a full sweep grid (exactly one of --family "
                         "/ --workload)")
    ap.add_argument("--workload", metavar="NAME", default=None,
                    help="run one named workload from the catalog "
                         "(workloads/catalog.py): a fingerprintable "
                         "scenario — graph, seed plan, chain family, "
                         "proposal variant, tuned run shape; --steps/"
                         "--chains override the tuned shape; "
                         "--list-workloads enumerates")
    ap.add_argument("--list-workloads", action="store_true",
                    help="print the workload catalog and exit")
    ap.add_argument("--out", required=False, default=None)
    ap.add_argument("--steps", type=int, default=None,
                    help="total transitions per config (default 100000, "
                         "or the workload's tuned value)")
    ap.add_argument("--chains", type=int, default=None,
                    help="batched chains per config (default 8, or the "
                         "workload's tuned value)")
    ap.add_argument("--record-every", type=int, default=1,
                    help="history thinning through the runners (yields "
                         "0, k, 2k, ... recorded; accumulators exact)")
    ap.add_argument("--analytics", choices=["history", "summary"],
                    default="history",
                    help="telemetry plane: 'history' exfiltrates the "
                         "per-chunk history block to the host (the "
                         "flagged oracle path), 'summary' folds moments/"
                         "R-hat/ESS into the scan and reads back one "
                         "small summary pytree per chunk (device-"
                         "resident analytics; incompatible with "
                         "--checkpoint-dir and --record-every > 1)")
    ap.add_argument("--backend", choices=["jax", "python"], default="jax")
    ap.add_argument("--contiguity", choices=["patch", "exact"],
                    default="patch")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="steps between mid-config checkpoints (0: only at "
                         "config completion); an interrupted config resumes "
                         "from the last saved segment")
    ap.add_argument("--only", nargs="*", default=None,
                    help="config tags to run, e.g. 2B30P10")
    ap.add_argument("--events", metavar="PATH", default=None,
                    help="append structured telemetry (obs JSONL: sweep "
                         "progress, per-chunk runner metrics, compile "
                         "events) to PATH; '-' streams to stderr; fold "
                         "with tools/obs_report.py")
    ap.add_argument("--heartbeat", metavar="PATH", default=None,
                    help="sweep progress heartbeat JSON (atomically "
                         "refreshed around every config); defaults to "
                         "OUT/heartbeat.json")
    ap.add_argument("--dual-source",
                    choices=["quads", "voronoi", "fixture"],
                    default="quads",
                    help="dual family geometry: jittered-quad lattice, "
                         "irregular Voronoi cells (realistic topology), "
                         "or the committed precinct-style GeoJSON "
                         "fixture (workloads/data/); ignored by other "
                         "families")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (jax.config, which works "
                         "even where JAX_PLATFORMS env is pre-pinned)")
    ap.add_argument("--jax-cache", metavar="DIR", default=None,
                    help="persistent XLA compilation cache directory: "
                         "sweep re-runs and resumed runs skip the "
                         "~30-60s/config compile (cache keys cover "
                         "graph shape, spec, and chain count)")
    ap.add_argument("--faults", metavar="SPEC", default=None,
                    help="fault-injection plan, e.g. "
                         "'checkpoint.write:once,segment.step:p=0.1,"
                         "seed=7' (overrides the GRAFT_FAULTS env var); "
                         "see resilience/faults.py for the grammar")
    ap.add_argument("--retries", type=int, default=3,
                    help="max retries per config before it is marked "
                         "failed (supervised sweeps)")
    ap.add_argument("--quarantine-after", type=int, default=2,
                    help="deterministic failures of one config before it "
                         "is quarantined instead of retried")
    ap.add_argument("--deadline", type=float, default=None, metavar="S",
                    help="cooperative per-config wall budget in seconds; "
                         "checked between segments, classified as a "
                         "resource failure")
    ap.add_argument("--no-supervise", action="store_true",
                    help="bare fail-fast sweep loop (no retries, no "
                         "quarantine, first error aborts the process)")
    ap.add_argument("--adaptive", action="store_true",
                    help="close the observe->act loop: consult the "
                         "control/ policies (early stop on split "
                         "R-hat + ESS targets, swap-rate ladder "
                         "reshaping) at segment boundaries; decisions "
                         "are emitted as control_action events "
                         "(requires --checkpoint-every > 0 to create "
                         "boundaries before config completion)")
    ap.add_argument("--target-rhat", type=float, default=1.05,
                    help="--adaptive: split R-hat early-stop target")
    ap.add_argument("--target-ess", type=float, default=200.0,
                    help="--adaptive: total-ESS early-stop target")
    args = ap.parse_args()
    if args.list_workloads:
        from .. import workloads
        for n in workloads.names():
            w = workloads.get(n)
            print(f"{n:22s} {w.fingerprint()}  "
                  f"[{w.chain}/{w.variant}/{w.kernel_path}] "
                  f"{w.description}")
        return
    if (args.family is None) == (args.workload is None):
        ap.error("exactly one of --family / --workload is required")
    if args.out is None:
        ap.error("--out is required")
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.jax_cache:
        import jax
        jax.config.update("jax_compilation_cache_dir", args.jax_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    overrides = dict(backend=args.backend, contiguity=args.contiguity,
                     seed=args.seed, record_every=args.record_every,
                     checkpoint_every=args.checkpoint_every)
    if args.analytics != "history":
        overrides["analytics"] = args.analytics
    if args.steps is not None:
        overrides["total_steps"] = args.steps
    if args.chains is not None:
        overrides["n_chains"] = args.chains
    if args.workload:
        # a workload is a single named config; explicit CLI flags win
        # over the catalog's tuned shape, catalog defaults otherwise
        from .. import workloads
        configs = [workloads.get(args.workload).to_config(**overrides)]
    else:
        sweep = SWEEPS[args.family]
        overrides.setdefault("total_steps", 100_000)
        overrides.setdefault("n_chains", 8)
        if args.family == "dual":
            overrides["dual_source"] = args.dual_source
        configs = list(sweep(**overrides))
    if args.only:
        configs = [c for c in configs if c.tag in set(args.only)]
    heartbeat = args.heartbeat or os.path.join(args.out, "heartbeat.json")
    if args.faults is not None:
        rfaults.install_from_spec(args.faults)
    else:
        rfaults.install_from_env()
    control = None
    if args.adaptive:
        from ..control import ControlLoop, default_policies
        control = ControlLoop(policies=default_policies(
            rhat_target=args.target_rhat, ess_target=args.target_ess))
    with from_spec(args.events) as rec:
        if args.no_supervise:
            run_sweep(configs, args.out,
                      checkpoint_dir=args.checkpoint_dir,
                      recorder=rec, heartbeat=heartbeat,
                      control=control)
            return
        policy = RetryPolicy(max_retries=args.retries,
                             quarantine_after=args.quarantine_after,
                             deadline_s=args.deadline, seed=args.seed)
        report = run_supervised_sweep(
            configs, args.out, checkpoint_dir=args.checkpoint_dir,
            recorder=rec, heartbeat=heartbeat, policy=policy,
            control=control)
    sys.exit(report.exit_code)


if __name__ == "__main__":
    main()
