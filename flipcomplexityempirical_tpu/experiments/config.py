"""Experiment configuration: the reference's sweep grids as data.

The reference encodes configuration in module constants + nested loops +
output filenames (SURVEY.md section 5 'Config / flag system';
grid_chain_sec11.py:33-36,182-184). Here a config is a dataclass; the
filename tag is byte-compatible: ``{alignment}B{int(100*base)}P{int(100*pop)}``
(grid_chain_sec11.py:323) — note int() truncation, e.g. 1/0.3 -> "333",
mu -> "263".
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Iterator

MU = 2.63815853  # Z^2 SAW connective constant (grid_chain_sec11.py:33)

SEC11_BASES = [.1, 1 / MU ** 2, .2, 1 / MU, .8, 1, MU, 4, MU ** 2, 10]
SEC11_POPS = [.01, .05, .1, .5, .9]
FRANK_BASES = [.3, 1 / .3]
FRANK_POPS = [.05, .1, .5, .9]


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    family: str               # 'sec11' | 'frank'
    alignment: int            # 0 | 1 | 2
    base: float
    pop_tol: float
    total_steps: int = 100_000
    n_chains: int = 8         # reference runs 1; chain 0 renders artifacts
    seed: int = 0
    backend: str = "jax"      # 'jax' | 'python'
    contiguity: str = "patch"  # 'patch' | 'exact'
    accept: str = "cut"       # 'cut' | 'corrected'
    checkpoint_every: int = 0  # steps between mid-config checkpoints
                               # (0 = only at completion); resume picks up
                               # from the last saved segment
    propose_parallel: int = 1  # kernel/step.py Spec.propose_parallel:
                               # candidates per re-propose round (batch
                               # accelerators benefit from >1)

    @property
    def tag(self) -> str:
        return (f"{self.alignment}B{int(100 * self.base)}"
                f"P{int(100 * self.pop_tol)}")

    @property
    def plot_node_size(self) -> int:
        # grid_chain_sec11.py:188 ns=120; Frankenstein_chain.py:37 ns=500
        return 120 if self.family == "sec11" else 500


def sec11_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """The 150-config sec11 grid (grid_chain_sec11.py:182-184; alignment
    iterates [2,1,0])."""
    for pop, base, al in itertools.product(SEC11_POPS, SEC11_BASES,
                                           [2, 1, 0]):
        yield ExperimentConfig(family="sec11", alignment=al, base=base,
                               pop_tol=pop, **overrides)


def frank_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """The 24-config Frankengraph grid (Frankenstein_chain.py:182-184)."""
    for pop, base, al in itertools.product(FRANK_POPS, FRANK_BASES,
                                           [2, 1, 0]):
        yield ExperimentConfig(family="frank", alignment=al, base=base,
                               pop_tol=pop, **overrides)
