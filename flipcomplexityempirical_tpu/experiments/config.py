"""Experiment configuration: the reference's sweep grids as data.

The reference encodes configuration in module constants + nested loops +
output filenames (SURVEY.md section 5 'Config / flag system';
grid_chain_sec11.py:33-36,182-184). Here a config is a dataclass; the
filename tag is byte-compatible: ``{alignment}B{int(100*base)}P{int(100*pop)}``
(grid_chain_sec11.py:323) — note int() truncation, e.g. 1/0.3 -> "333",
mu -> "263".
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
from typing import Iterator

MU = 2.63815853  # Z^2 SAW connective constant (grid_chain_sec11.py:33)

SEC11_BASES = [.1, 1 / MU ** 2, .2, 1 / MU, .8, 1, MU, 4, MU ** 2, 10]
SEC11_POPS = [.01, .05, .1, .5, .9]
FRANK_BASES = [.3, 1 / .3]
FRANK_POPS = [.05, .1, .5, .9]


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    family: str               # 'sec11' | 'frank' | 'kpair' | 'tri' |
                              # 'hex' | 'temper' | 'dual'
    alignment: int            # 0 | 1 | 2 (sec11/frank/temper); stripe
                              # axis 0 | 1 (kpair/tri/hex/dual)
    base: float
    pop_tol: float
    total_steps: int = 100_000
    n_chains: int = 8         # reference runs 1; chain 0 renders artifacts
    seed: int = 0
    backend: str = "jax"      # 'jax' | 'python'
    contiguity: str = "patch"  # 'patch' | 'exact'
    accept: str = "cut"       # 'cut' | 'corrected'
    checkpoint_every: int = 0  # steps between mid-config checkpoints
                               # (0 = only at completion); resume picks up
                               # from the last saved segment
    propose_parallel: int = 1  # kernel/step.py Spec.propose_parallel:
                               # candidates per re-propose round (batch
                               # accelerators benefit from >1)
    # --- widened families (BASELINE.json configs 2-5) ---
    n_districts: int = 2      # kpair/dual: k districts via the pair walk
    grid: int = 64            # kpair: board side (n x n rook grid)
    lattice_m: int = 14       # tri/hex: generator rows
    lattice_n: int = 28       # tri/hex: generator cols
    betas: tuple = ()         # temper: the beta ladder, rung 0 first
    swap_every: int = 0       # temper: transitions between swap rounds
    dual_nx: int = 12         # dual: synthetic-precinct state is nx x ny
    dual_ny: int = 12
    dual_source: str = "quads"  # dual: 'quads' (jittered lattice) |
                                # 'voronoi' (irregular-degree cells)
    record_every: int = 1     # history thinning through the runners
    chain: str = "flip"       # 'flip' (single-node flip walk) | 'recom'
                              # (spanning-tree ReCom, sampling/recom.py)
    variant: str = "none"     # proposal variant: 'none' | 'nobacktrack'
                              # (arxiv 1204.4140) | 'lazy' (lazy-uniform
                              # reweighting riding the geometric waits)
    analytics: str = "history"  # telemetry plane: 'history' (oracle
                                # path; full per-step histories read
                                # back per chunk) | 'summary'
                                # (device-resident accumulators; one
                                # small summary pytree per chunk)

    @property
    def tag(self) -> str:
        core = (f"{self.alignment}B{int(100 * self.base)}"
                f"P{int(100 * self.pop_tol)}")
        if self.family in ("sec11", "frank"):
            # reference families keep the reference's exact filename tag
            # (grid_chain_sec11.py:323)
            t = core
        # widened families prefix the family (artifact filenames and
        # checkpoint keys must not collide when sweeps share an output
        # or checkpoint directory) and their sweep-varying parameters
        elif self.family == "dual" and self.dual_source != "quads":
            t = (f"{self.family}-{self.dual_source[:3].upper()}-"
                 f"K{self.n_districts}-{core}")
        elif self.family in ("kpair", "dual"):
            t = f"{self.family}-K{self.n_districts}-{core}"
        elif self.family == "temper":
            t = (f"{self.family}-{core}"
                 f"R{len(self.betas)}S{self.swap_every}")
        else:
            t = f"{self.family}-{core}"
        # non-default chain/variant wrap the tag so artifacts and
        # checkpoint keys never collide with the flip walk's
        if self.chain != "flip":
            t = f"{self.chain}-{t}"
        if self.variant != "none":
            t = f"{t}-{self.variant[:4].upper()}"
        return t

    def fingerprint(self) -> str:
        """Content hash over the KERNEL-RELEVANT statics: two configs
        with equal fingerprints build the same graph, the same Spec, and
        the same run shape (steps, thinning), so the service scheduler
        may coalesce them into one device batch and the compile cache
        may key on it (service/cache.py).

        Deliberately EXCLUDED — everything that varies per tenant
        without changing the compiled kernel: ``alignment`` (initial
        plan only), ``base``/``pop_tol`` (per-chain StepParams leaves),
        ``seed`` (per-chain PRNG state; except the dual family, whose
        geometry generation consumes it), ``n_chains`` (the batch axis
        being coalesced), ``checkpoint_every`` (host-side segmenting).
        The tag encodes exactly alignment/base/pop_tol, so tag changes
        never move the fingerprint. Hashed as sorted canonical JSON —
        independent of field ordering."""
        payload = {
            "family": self.family,
            "backend": self.backend,
            "contiguity": self.contiguity,
            "accept": self.accept,
            "propose_parallel": self.propose_parallel,
            "n_districts": self.n_districts,
            "grid": self.grid,
            "lattice": [self.lattice_m, self.lattice_n],
            "betas": [float(b) for b in self.betas],
            "swap_every": self.swap_every,
            "dual": [self.dual_nx, self.dual_ny, self.dual_source],
            "total_steps": self.total_steps,
            "record_every": self.record_every,
        }
        if self.family == "dual":
            payload["seed"] = self.seed
        # appended conditionally so every pre-existing config keeps its
        # exact fingerprint (journal/cache compatibility)
        if self.chain != "flip":
            payload["chain"] = self.chain
        if self.variant != "none":
            payload["variant"] = self.variant
        if self.analytics != "history":
            # summary mode threads a SummaryAcc through the scan carry,
            # so the compiled kernel differs — coalescing across modes
            # would recompile per batch
            payload["analytics"] = self.analytics
        blob = json.dumps(payload, sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]

    @property
    def plot_node_size(self) -> int:
        # grid_chain_sec11.py:188 ns=120; Frankenstein_chain.py:37 ns=500
        if self.family in ("frank", "temper"):
            return 500
        if self.family in ("tri", "hex", "dual"):
            return 60
        return 120 if self.family == "sec11" else 10


def sec11_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """The 150-config sec11 grid (grid_chain_sec11.py:182-184; alignment
    iterates [2,1,0])."""
    for pop, base, al in itertools.product(SEC11_POPS, SEC11_BASES,
                                           [2, 1, 0]):
        yield ExperimentConfig(family="sec11", alignment=al, base=base,
                               pop_tol=pop, **overrides)


def frank_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """The 24-config Frankengraph grid (Frankenstein_chain.py:182-184)."""
    for pop, base, al in itertools.product(FRANK_POPS, FRANK_BASES,
                                           [2, 1, 0]):
        yield ExperimentConfig(family="frank", alignment=al, base=base,
                               pop_tol=pop, **overrides)


def kpair_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """BASELINE config 2: k-district (k=4, 8) pair walks on the 64x64
    grid (slow_reversible_propose semantics, grid_chain_sec11.py:117-130),
    routed through the board pair fast path."""
    for k, base, al in itertools.product([4, 8], [0.8, MU], [0, 1]):
        yield ExperimentConfig(family="kpair", alignment=al, base=base,
                               pop_tol=0.5, n_districts=k, **overrides)


def tri_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """BASELINE config 3a: 2-district flip walk on a triangular lattice
    (non-grid planar adjacency)."""
    for base, al in itertools.product(FRANK_BASES, [0, 1]):
        yield ExperimentConfig(family="tri", alignment=al, base=base,
                               pop_tol=0.1, **overrides)


def hex_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """BASELINE config 3b: 2-district flip walk on a hexagonal lattice."""
    for base, al in itertools.product(FRANK_BASES, [0, 1]):
        yield ExperimentConfig(family="hex", alignment=al, base=base,
                               pop_tol=0.1, **overrides)


# The default FRANK B333 ladder spans [1.0, 0.63]: the order-disorder
# transition sits near beta ~ 0.65 (REPLICATION.md "Tempering the B333
# bimodal regime"), so the hottest rungs melt the interface and refreeze
# it into a fresh mode, while 0.03-0.05 spacing keeps every adjacent
# swap rate above ~0.4. A naive wide ladder (1.0 .. 0.25, spacing 0.15)
# measured swap rates ~0.005 past the transition — betas beyond the melt
# point buy nothing and starve the ladder.
TEMPER_BETAS = (1.0, .95, .9, .85, .8, .76, .72, .69, .66, .63)


def temper_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """BASELINE config 4: beta-tempered Frankengraph chains with replica
    exchange, centred on the slow-mixing bimodal B333 regime
    (REPLICATION.md). The cold rung (beta=1) is the physical chain."""
    overrides.setdefault("betas", TEMPER_BETAS)
    overrides.setdefault("swap_every", 50)
    for al in [0, 1, 2]:
        yield ExperimentConfig(family="temper", alignment=al,
                               base=1 / .3, pop_tol=0.1, **overrides)


def dual_sweep(**overrides) -> Iterator[ExperimentConfig]:
    """BASELINE config 5: k districts on a precinct dual graph (synthetic
    jittered-quad state; from_geojson also ingests real shapefiles), with
    boundary-length Metropolis and Polsby-Popper compactness scores."""
    for k, al in itertools.product([4, 8], [0, 1]):
        yield ExperimentConfig(family="dual", alignment=al, base=MU,
                               pop_tol=0.25, n_districts=k, **overrides)


SWEEPS = {
    "sec11": sec11_sweep,
    "frank": frank_sweep,
    "kpair": kpair_sweep,
    "tri": tri_sweep,
    "hex": hex_sweep,
    "temper": temper_sweep,
    "dual": dual_sweep,
}
