"""Per-config experiment driver: the reference's measurement loop + artifact
emission on either backend.

- backend='jax': batched kernel chains (chain 0 renders the reference
  artifact set; the full batch feeds stats/ diagnostics).
- backend='python': the compat oracle running the literal reference loop
  (grid_chain_sec11.py:360-411) — the 'existing pure-Python runner' of the
  BASELINE.json north star.

Completion manifest: a config is done when all 13 artifacts exist
(ARTIFACT_KINDS); ``run_sweep`` skips completed configs, which upgrades the
reference's crash story (SURVEY.md section 5 'Failure detection': artifacts
on disk were the de-facto resume state, but the scripts always redid
everything).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import numpy as np

from .. import compat
from ..graphs import (grid_sec11, frankengraph, sec11_plan, frank_plan,
                      seed_votes, PARITY_LABELS)
from ..stats import partisan
from ..kernel.step import Spec, finalize_host
from ..sampling import init_batch, run_chains
from .artifacts import ARTIFACT_KINDS, render_all, render_start
from .config import ExperimentConfig


def build_graph_and_plan(cfg: ExperimentConfig):
    if cfg.family == "sec11":
        g = grid_sec11()
        plan = sec11_plan(g, cfg.alignment)
    elif cfg.family == "frank":
        g = frankengraph()
        plan = frank_plan(g, cfg.alignment)
    else:
        raise ValueError(f"family {cfg.family!r}")
    return g, plan


def is_done(cfg: ExperimentConfig, outdir: str) -> bool:
    return all(os.path.exists(os.path.join(outdir, cfg.tag + k))
               for k in ARTIFACT_KINDS)


def run_config(cfg: ExperimentConfig, outdir: str,
               checkpoint_dir: Optional[str] = None) -> dict:
    os.makedirs(outdir, exist_ok=True)
    g, plan = build_graph_and_plan(cfg)
    signed = PARITY_LABELS[plan]
    render_start(g, cfg.family, outdir, cfg.tag, signed, cfg.plot_node_size)
    t0 = time.time()
    if cfg.backend == "jax":
        data = _run_jax(cfg, g, plan, checkpoint_dir)
    elif cfg.backend == "python":
        data = _run_python(cfg, g, plan)
    else:
        raise ValueError(f"backend {cfg.backend!r}")
    data["seconds"] = time.time() - t0
    data["partisan"] = _partisan_summary(cfg, g, data)
    render_all(g, cfg.family, outdir, cfg.tag,
               end_signed=data["end_signed"], cut_times=data["cut_times"],
               part_sum=data["part_sum"], num_flips=data["num_flips"],
               slopes=data["slopes"], angles=data["angles"],
               waits_sum=data["waits_sum"], node_size=cfg.plot_node_size)
    return data


def _run_jax(cfg: ExperimentConfig, g, plan, checkpoint_dir=None,
             _stop_after_segments: Optional[int] = None) -> dict:
    """Batched run, in checkpoint segments when cfg.checkpoint_every > 0.

    A crash between segments loses at most ``checkpoint_every`` steps: the
    next run_config resumes chain state, histories, and wait totals from
    the per-config npz (upgrading the reference's redo-everything crash
    story, SURVEY.md section 5 'Checkpoint / resume'). The segmented run
    is bit-identical to an uninterrupted one because PRNG keys live in the
    chain state and segment boundaries reuse the chunked runner.
    ``_stop_after_segments`` simulates an interruption for tests."""
    spec = Spec(n_districts=2, proposal="bi", contiguity=cfg.contiguity,
                invalid="repropose", accept=cfg.accept,
                record_interface=True, parity_metrics=True, geom_waits=True,
                propose_parallel=cfg.propose_parallel)
    dg, states, params = init_batch(
        g, plan, n_chains=cfg.n_chains, seed=cfg.seed, spec=spec,
        base=cfg.base, pop_tol=cfg.pop_tol)

    done = 0
    n_parts = 0
    hist_parts: dict = {}
    waits_total = np.zeros(cfg.n_chains, np.float64)
    if checkpoint_dir:
        loaded = load_checkpoint(checkpoint_dir, cfg)
        if loaded is not None:
            done = int(loaded["meta_done"])
            n_parts = int(loaded["meta_n_parts"])
            states = _state_from_arrays(states, loaded)
            hist_parts = {k[len("hist_"):]: [v] for k, v in loaded.items()
                          if k.startswith("hist_")}
            waits_total = loaded["meta_waits_total"].copy()

    every = cfg.checkpoint_every or cfg.total_steps
    segments = 0
    while done < cfg.total_steps:
        n = min(every, cfg.total_steps - done)
        res = run_chains(dg, spec, params, states, n_steps=n,
                         record_initial=(done == 0))
        states = res.state
        for k, v in res.history.items():
            hist_parts.setdefault(k, []).append(v)
        waits_total += res.waits_total
        done += n
        segments += 1
        if checkpoint_dir:
            n_parts = save_checkpoint(
                checkpoint_dir, cfg, res.host_state(), done=done,
                waits_total=waits_total, new_hist=res.history,
                part_idx=n_parts)
        if _stop_after_segments and segments >= _stop_after_segments:
            raise _SegmentStop(done)

    history = {k: np.concatenate(v, axis=1) for k, v in hist_parts.items()}
    s = jax.tree.map(np.asarray, states)
    t_final = cfg.total_steps  # reference t after the loop (line 402)
    c0 = type(s)(**{f: np.asarray(getattr(s, f))[0]
                    for f in s.__dataclass_fields__})
    part_sum, _ = finalize_host(c0, np.asarray(PARITY_LABELS), t_final)
    return {
        "end_signed": np.asarray(PARITY_LABELS)[
            np.asarray(c0.assignment, dtype=np.int64)],
        "cut_times": np.asarray(c0.cut_times),
        "part_sum": part_sum,
        "num_flips": np.asarray(c0.num_flips),
        "slopes": history["slope"][0],
        "angles": history["angle"][0],
        "waits_sum": float(waits_total[0]),
        "history": history,
        "waits_all": waits_total,
        "state": s,
    }


def _partisan_summary(cfg: ExperimentConfig, g, data) -> dict:
    """Election scores over the run's final plans, from the reference's
    Bernoulli(1/2) pink/purple vote attributes (grid_chain_sec11.py:
    223-228; Election wiring of line 307). Batched: every chain's final
    plan is scored in one pass; the reference's single chain is row 0."""
    votes = seed_votes(g, cfg.seed)
    if data["state"] is not None:               # jax backend: (C, N) batch
        assign = np.asarray(data["state"].assignment)
    else:                                       # python backend: final plan
        assign = (np.asarray(data["end_signed"]) < 0).astype(np.int64)[None]
    tallies = partisan.district_vote_tallies(assign, votes, k=2)
    return {
        "mean_median": partisan.mean_median(tallies),
        "efficiency_gap": partisan.efficiency_gap(tallies),
        "seats_pink": partisan.seats_won(tallies),
    }


class _SegmentStop(RuntimeError):
    """Raised by _run_jax when _stop_after_segments simulates a crash."""

    def __init__(self, done):
        super().__init__(f"stopped after {done} steps")
        self.done = done


def _state_from_arrays(template, loaded: dict):
    """Rebuild a device ChainState from checkpoint arrays, using the
    freshly-initialized state as the shape/dtype template."""
    import jax.numpy as jnp

    fields = {}
    for f in template.__dataclass_fields__:
        arr = loaded[f"state_{f}"]
        fields[f] = jnp.asarray(arr)
    return type(template)(**fields)


def make_wall_lookup(g):
    table = {}
    for e in range(g.n_edges):
        u = g.labels[g.edges[e, 0]]
        v = g.labels[g.edges[e, 1]]
        table[frozenset((u, v))] = int(g.wall_id[e])
    return lambda u, v: table.get(frozenset((u, v)), -1)


def _run_python(cfg: ExperimentConfig, g, plan) -> dict:
    """The literal reference loop on the compat oracle."""
    rng = np.random.default_rng(cfg.seed)
    signed = {lab: int(PARITY_LABELS[plan[i]])
              for i, lab in enumerate(g.labels)}
    wall = make_wall_lookup(g)
    updaters = {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_bi,
        "base": lambda p: cfg.base,
        "geom": compat.make_geom_wait(rng),
        "slope": compat.make_boundary_slope(wall),
        "step_num": compat.step_num,
    }
    part = compat.Partition(g, signed, updaters)
    popbound = compat.within_percent_of_ideal_population(part, cfg.pop_tol)
    accept = (compat.make_cut_accept(rng) if cfg.accept == "cut"
              else compat.make_corrected_cut_accept(rng))
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        accept, part, cfg.total_steps)

    n = g.n_nodes
    cut_times = np.zeros(g.n_edges, np.int64)
    part_sum = np.array([signed[lab] for lab in g.labels], np.int64)
    last_flipped = np.zeros(n, np.int64)
    num_flips = np.zeros(n, np.int64)
    waits = []
    slopes, angles = [], []
    cut_hist, b_hist = [], []
    center = np.asarray(g.center)

    t = 0
    for p in chain:
        cut_hist.append(len(p["cut_edges"]))
        waits.append(p["geom"])
        b_hist.append(len(p["b_nodes"]))
        temp = p["slope"]
        if len(temp) >= 2:
            enda = ((temp[0][0][0] + temp[0][1][0]) / 2,
                    (temp[0][0][1] + temp[0][1][1]) / 2)
            endb = ((temp[1][0][0] + temp[1][1][0]) / 2,
                    (temp[1][0][1] + temp[1][1][1]) / 2)
            slopes.append((endb[1] - enda[1]) / (endb[0] - enda[0])
                          if endb[0] != enda[0] else np.inf)
            va = np.asarray(enda) - center
            vb = np.asarray(endb) - center
            angles.append(float(np.arccos(np.clip(
                np.dot(va / np.linalg.norm(va), vb / np.linalg.norm(vb)),
                -1, 1))))
        else:  # reference would IndexError here; we record NaN and survive
            slopes.append(np.nan)
            angles.append(np.nan)
        mask = p.cut_edge_mask()
        cut_times += mask
        if p.flips is not None:
            lab = next(iter(p.flips))
            f = g.index[lab]
            part_sum[f] -= p.assignment[lab] * (t - last_flipped[f])
            last_flipped[f] = t
            num_flips[f] += 1
        t += 1

    a = p.assignment_array
    never = last_flipped == 0
    part_sum[never] = t * a[never]
    return {
        "end_signed": a.copy(),
        "cut_times": cut_times,
        "part_sum": part_sum,
        "num_flips": num_flips,
        "slopes": np.asarray(slopes),
        "angles": np.asarray(angles),
        "waits_sum": float(sum(waits)),
        "history": {"cut_count": np.asarray(cut_hist)[None, :],
                    "b_count": np.asarray(b_hist)[None, :],
                    "wait": np.asarray(waits, dtype=float)[None, :]},
        "waits_all": np.asarray([float(sum(waits))]),
        "state": None,
    }


def _ckpt_identity(cfg: ExperimentConfig) -> str:
    """Everything the tag does NOT encode (or encodes lossily — the tag
    truncates base/pop_tol to int(100*x)) but resume correctness needs."""
    return (f"{cfg.family}|steps={cfg.total_steps}|chains={cfg.n_chains}|"
            f"seed={cfg.seed}|contiguity={cfg.contiguity}|"
            f"accept={cfg.accept}|base={cfg.base!r}|pop={cfg.pop_tol!r}|"
            f"kp={cfg.propose_parallel}")


def save_checkpoint(ckpt_dir: str, cfg: ExperimentConfig, host_state,
                    done: int = 0, waits_total=None, new_hist=None,
                    part_idx: int = 0) -> int:
    """Per-config checkpoint: ``<tag>.npz`` holds the chain state
    (state_*), progress + config identity (meta_*); each segment's history
    goes to its own ``<tag>.h<k>.npz`` part file so a save costs
    O(segment), not O(run-so-far). The main file is written atomically
    AFTER its part, so meta_n_parts never points at a missing file.
    Returns the next part index."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if new_hist:
        ppath = os.path.join(ckpt_dir, f"{cfg.tag}.h{part_idx:04d}.npz")
        np.savez_compressed(ppath + ".tmp.npz",
                            **{k: np.asarray(v)
                               for k, v in new_hist.items()})
        os.replace(ppath + ".tmp.npz", ppath)
        part_idx += 1
    arrays = {f"state_{f}": np.asarray(getattr(host_state, f))
              for f in host_state.__dataclass_fields__}
    arrays["meta_done"] = np.int64(done)
    arrays["meta_n_parts"] = np.int64(part_idx)
    arrays["meta_identity"] = np.array(_ckpt_identity(cfg))
    if waits_total is not None:
        arrays["meta_waits_total"] = np.asarray(waits_total, np.float64)
    path = os.path.join(ckpt_dir, cfg.tag + ".npz")
    np.savez_compressed(path + ".tmp.npz", **arrays)
    os.replace(path + ".tmp.npz", path)
    return part_idx


def load_checkpoint(ckpt_dir: str, cfg: ExperimentConfig):
    """Load and validate a checkpoint; None (fresh start) when absent,
    written by an incompatible config, or in an unrecognized format —
    the recovery path must never crash on stale files."""
    path = os.path.join(ckpt_dir, cfg.tag + ".npz")
    if not os.path.exists(path):
        return None
    d = dict(np.load(path))
    if "meta_done" not in d or "meta_identity" not in d:
        print(f"[ckpt] ignoring {path}: unrecognized format")
        return None
    if str(d["meta_identity"]) != _ckpt_identity(cfg):
        print(f"[ckpt] ignoring {path}: config mismatch "
              f"({d['meta_identity']} != {_ckpt_identity(cfg)})")
        return None
    if int(d["meta_done"]) > cfg.total_steps:
        print(f"[ckpt] ignoring {path}: more steps than requested")
        return None
    hist: dict = {}
    for k in range(int(d["meta_n_parts"])):
        ppath = os.path.join(ckpt_dir, f"{cfg.tag}.h{k:04d}.npz")
        if not os.path.exists(ppath):
            print(f"[ckpt] ignoring {path}: missing part {ppath}")
            return None
        for name, arr in np.load(ppath).items():
            hist.setdefault(name, []).append(arr)
    for name, parts in hist.items():
        d[f"hist_{name}"] = np.concatenate(parts, axis=1)
    return d


def run_sweep(configs, outdir: str, checkpoint_dir: Optional[str] = None,
              verbose: bool = True) -> list:
    """Sweep with skip-if-done resume (per-config completion manifest)."""
    results = []
    for cfg in configs:
        if is_done(cfg, outdir):
            if verbose:
                print(f"[skip] {cfg.family} {cfg.tag} (artifacts complete)")
            continue
        t0 = time.time()
        data = run_config(cfg, outdir, checkpoint_dir)
        if verbose:
            print(f"[done] {cfg.family} {cfg.tag} "
                  f"waits={data['waits_sum']:.4g} "
                  f"({time.time() - t0:.1f}s)")
        results.append((cfg, data))
    return results
