"""Per-config experiment driver: the reference's measurement loop + artifact
emission on either backend.

- backend='jax': batched kernel chains (chain 0 renders the reference
  artifact set; the full batch feeds stats/ diagnostics).
- backend='python': the compat oracle running the literal reference loop
  (grid_chain_sec11.py:360-411) — the 'existing pure-Python runner' of the
  BASELINE.json north star.

Completion manifest: a config is done when all 13 artifacts exist
(ARTIFACT_KINDS); ``run_sweep`` skips completed configs, which upgrades the
reference's crash story (SURVEY.md section 5 'Failure detection': artifacts
on disk were the de-facto resume state, but the scripts always redid
everything).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import time
from types import SimpleNamespace
from typing import Optional

import jax
import numpy as np

from .. import compat, obs
from ..resilience import degrade as rdegrade
from ..resilience import faults as rfaults
from ..resilience.errors import CheckpointIdentityError, KernelPathError
from ..resilience.supervisor import check_deadline


def _check_drain(tag: str) -> None:
    """Cooperative drain point at the segment boundary, next to
    check_deadline. Late import: service -> scheduler -> driver is the
    existing import chain, so driver cannot import the service package
    at module level (lifecycle itself only touches resilience/obs)."""
    from ..service.lifecycle import check_drain

    check_drain(tag)
from ..graphs import (grid_sec11, frankengraph, sec11_plan, frank_plan,
                      square_grid, triangular_lattice, hex_lattice,
                      stripes_plan, from_geojson, synthetic_precincts,
                      voronoi_precincts, seed_votes, validate_votes,
                      PARITY_LABELS)
from .. import stats
from ..stats import partisan, polsby_popper
from ..kernel import board as kboard
from ..kernel.step import Spec, finalize_host
from ..sampling import (init_batch, run_chains, run_recom, init_board,
                        init_tempered, run_tempered, per_rung_history)
from .artifacts import (artifact_kinds, render_all, render_generic,
                        render_rungs, render_start)
from .config import ExperimentConfig


def build_graph_and_plan(cfg: ExperimentConfig):
    """(graph, initial plan, GeoAttributes-or-None) for a config. The
    'temper' family runs the Frankengraph (its B333 cell is the
    slow-mixing regime the ladder exists for, REPLICATION.md)."""
    geo = None
    if cfg.family == "sec11":
        g = grid_sec11()
        plan = sec11_plan(g, cfg.alignment)
    elif cfg.family in ("frank", "temper"):
        g = frankengraph()
        plan = frank_plan(g, cfg.alignment)
    elif cfg.family == "kpair":
        g = square_grid(cfg.grid, cfg.grid)
        plan = stripes_plan(g, cfg.n_districts, axis=cfg.alignment)
    elif cfg.family == "tri":
        g = triangular_lattice(cfg.lattice_m, cfg.lattice_n)
        plan = stripes_plan(g, 2, axis=cfg.alignment)
    elif cfg.family == "hex":
        g = hex_lattice(cfg.lattice_m, cfg.lattice_n)
        plan = stripes_plan(g, 2, axis=cfg.alignment)
    elif cfg.family == "dual":
        if cfg.dual_source == "voronoi":
            fc = voronoi_precincts(cfg.dual_nx * cfg.dual_ny,
                                   seed=cfg.seed)
        elif cfg.dual_source == "quads":
            fc = synthetic_precincts(cfg.dual_nx, cfg.dual_ny,
                                     seed=cfg.seed)
        elif cfg.dual_source == "fixture":
            # the committed precinct-style fixture (workloads/data/):
            # a deterministic GeoJSON FeatureCollection ingested through
            # the SAME from_geojson path real shapefiles take
            # (graphs/shapefile.py being the on-disk loader), so fixture
            # runs exercise the production ingestion code end to end
            from ..workloads.data import load_fixture
            fc = load_fixture()
        else:
            raise ValueError(f"dual_source {cfg.dual_source!r}")
        g, geo = from_geojson(fc, pop_property="POP")
        plan = stripes_plan(g, cfg.n_districts, axis=cfg.alignment)
    else:
        raise ValueError(f"family {cfg.family!r}")
    return g, plan, geo


def spec_for(cfg: ExperimentConfig) -> Spec:
    """The kernel Spec a family's walk runs under. sec11/frank keep the
    reference's full metric set (wall-interface slopes need wall ids, so
    record_interface only exists there); kpair/dual route the k-district
    pair walk (slow_reversible_propose, grid_chain_sec11.py:117-130);
    dual scores boundary LENGTH (weighted_cut) for compactness.
    ``cfg.variant`` maps onto the Spec's proposal-variant flags last, so
    a variant config differs from its base by exactly that flag."""
    common = dict(contiguity=cfg.contiguity, invalid="repropose",
                  parity_metrics=True, geom_waits=True,
                  propose_parallel=cfg.propose_parallel)
    fam = cfg.family
    if fam in ("sec11", "frank"):
        spec = Spec(n_districts=2, proposal="bi", accept=cfg.accept,
                    record_interface=True, **common)
    elif fam in ("temper", "tri", "hex"):
        spec = Spec(n_districts=2, proposal="bi", accept=cfg.accept,
                    record_interface=False, **common)
    elif fam == "kpair":
        spec = Spec(n_districts=cfg.n_districts, proposal="pair",
                    accept="cut", record_interface=False, **common)
    elif fam == "dual":
        spec = Spec(n_districts=cfg.n_districts,
                    proposal="pair" if cfg.n_districts > 2 else "bi",
                    accept="cut", weighted_cut=True,
                    record_interface=False, **common)
    else:
        raise ValueError(f"family {fam!r}")
    if cfg.variant == "none":
        return spec
    if cfg.variant == "nobacktrack":
        if spec.proposal != "bi":
            raise ValueError(
                f"variant 'nobacktrack' needs the 2-district 'bi' walk; "
                f"family {fam!r} with k={cfg.n_districts} runs "
                f"{spec.proposal!r}")
        return dataclasses.replace(spec, nobacktrack=True)
    if cfg.variant == "lazy":
        # lazy-uniform reweighting rides the geometric waiting-time
        # machinery — every family spec above has geom_waits on
        return dataclasses.replace(spec, lazy_uniform=True)
    raise ValueError(f"variant {cfg.variant!r}")


def _labels_for(cfg: ExperimentConfig) -> np.ndarray:
    """District -> rendered value: the reference's +1/-1 for 2 districts,
    district ids for k > 2."""
    if cfg.n_districts == 2:
        return np.asarray(PARITY_LABELS)
    return np.arange(cfg.n_districts, dtype=np.int32)


def is_done(cfg: ExperimentConfig, outdir: str) -> bool:
    return all(os.path.exists(os.path.join(outdir, cfg.tag + k))
               for k in artifact_kinds(cfg.family))


def count_artifacts(cfg: ExperimentConfig, outdir: str) -> int:
    """How many of a config's manifest artifacts exist on disk (the
    sweep telemetry's per-config completion reading)."""
    return sum(os.path.exists(os.path.join(outdir, cfg.tag + k))
               for k in artifact_kinds(cfg.family))


def _control_history(hist_parts: dict, key: str = "cut_count"):
    """The accumulated (C, T) observable the control loop judges from:
    concatenated history parts, which resume restores in full — so a
    recovered run sees the bit-identical history a continuous run saw
    at the same boundary (the replay contract of control/policy.py)."""
    parts = hist_parts.get(key)
    if not parts:
        return None
    return np.concatenate([np.asarray(p) for p in parts], axis=1)


def run_config(cfg: ExperimentConfig, outdir: str,
               checkpoint_dir: Optional[str] = None,
               recorder=None, control=None) -> dict:
    os.makedirs(outdir, exist_ok=True)
    rec = obs.resolve_recorder(recorder)
    with obs.span(rec, "build_graph", tag=cfg.tag, family=cfg.family):
        g, plan, geo = build_graph_and_plan(cfg)
    labels = _labels_for(cfg)
    signed = labels[plan]
    pos = geo.centroid if geo is not None else None
    with obs.span(rec, "render", tag=cfg.tag, phase="start"):
        render_start(g, cfg.family, outdir, cfg.tag, signed,
                     cfg.plot_node_size, pos=pos)
    t0 = time.monotonic()
    if cfg.backend == "python":
        if cfg.family not in ("sec11", "frank"):
            raise ValueError("backend='python' (the compat oracle) only "
                             "covers the reference families sec11/frank")
        data = _run_python(cfg, g, plan)
    elif cfg.backend != "jax":
        raise ValueError(f"backend {cfg.backend!r}")
    elif cfg.family == "temper":
        data = _run_temper(cfg, g, plan, checkpoint_dir,
                           recorder=recorder, control=control)
    else:
        data = _run_jax(cfg, g, plan, checkpoint_dir, recorder=recorder,
                        control=control)
    data["seconds"] = time.monotonic() - t0
    if cfg.n_districts == 2 or cfg.family == "dual":
        with obs.span(rec, "partisan", tag=cfg.tag):
            data["partisan"] = _partisan_summary(cfg, g, data)

    if cfg.family in ("sec11", "frank"):
        with obs.span(rec, "render", tag=cfg.tag, phase="all"):
            render_all(g, cfg.family, outdir, cfg.tag,
                       end_signed=data["end_signed"],
                       cut_times=data["cut_times"],
                       part_sum=data["part_sum"],
                       num_flips=data["num_flips"],
                       slopes=data["slopes"], angles=data["angles"],
                       waits_sum=data["waits_sum"],
                       node_size=cfg.plot_node_size)
        return data

    with obs.span(rec, "render", tag=cfg.tag, phase="generic"):
        render_generic(g, cfg.family, outdir, cfg.tag,
                       kinds=artifact_kinds(cfg.family),
                       node_size=cfg.plot_node_size,
                       end_signed=data["end_signed"],
                       cut_times=data["cut_times"],
                       num_flips=data["num_flips"],
                       part_sum=data.get("part_sum"),
                       waits_sum=data["waits_sum"], pos=pos)
    j = lambda kind: os.path.join(outdir, cfg.tag + kind)
    if cfg.family == "temper":
        render_rungs(j("rungs.png"), data["rung_cut"], cfg.betas)
        with open(j("swapstats.json"), "w") as f:
            json.dump(data["swapstats"], f, indent=1)
    if cfg.family == "dual":
        pp = polsby_popper(
            np.asarray(data["assignments"]), cfg.n_districts,
            edges=g.edges, shared_perim=geo.shared_perim,
            node_area=geo.area, node_exterior_perim=geo.exterior_perim)
        data["polsby_popper"] = pp
        with open(j("compactness.json"), "w") as f:
            json.dump({
                "polsby_popper_per_chain_mean": pp.mean(axis=1).tolist(),
                "polsby_popper_batch_mean": float(pp.mean()),
                "initial": polsby_popper(
                    np.asarray(plan)[None], cfg.n_districts,
                    edges=g.edges, shared_perim=geo.shared_perim,
                    node_area=geo.area,
                    node_exterior_perim=geo.exterior_perim
                ).mean(axis=1).tolist(),
            }, f, indent=1)
        # partisan scores are a dual-family artifact (school-boundary
        # style analyses on real dual graphs, arxiv 2206.03703): the
        # summary computed above lands on disk next to compactness
        with open(j("partisan.json"), "w") as f:
            json.dump({k: (v.tolist() if hasattr(v, "tolist") else v)
                       for k, v in data["partisan"].items()}, f, indent=1)
    return data


def _run_jax(cfg: ExperimentConfig, g, plan, checkpoint_dir=None,
             _stop_after_segments: Optional[int] = None,
             recorder=None, _force_general: bool = False,
             control=None) -> dict:
    """Batched run, in checkpoint segments when cfg.checkpoint_every > 0.

    A crash between segments loses at most ``checkpoint_every`` steps: the
    next run_config resumes chain state, histories, and wait totals from
    the per-config npz (upgrading the reference's redo-everything crash
    story, SURVEY.md section 5 'Checkpoint / resume'). The segmented run
    is bit-identical to an uninterrupted one because PRNG keys live in the
    chain state and segment boundaries reuse the chunked runner.
    ``_stop_after_segments`` simulates an interruption for tests.

    Routes through the board (stencil) fast path whenever
    ``kernel.board.supports(graph, spec)`` holds — plain rook grids (the
    kpair family) AND near-grid graphs the lowering pass embeds onto the
    masked-plane stencil body: sec11's corner surgery, the Frankengraph
    seam, queen grids, triangular lattices (a grid plus one diagonal
    plane). Truly irregular graphs (hex — radius-3 patches — and dual
    graphs) fall back to the general gather kernel.

    ``_force_general`` is the kernel-degradation rerun (resilience
    ladder): when every board-family body has failed, the config reruns
    here on the general gather kernel; a board-path checkpoint is then
    incompatible (different state pytree) and is deliberately ignored —
    an honest fresh start beats resuming corrupt state.

    ``control`` (a control.ControlLoop) is consulted at every segment
    boundary with the accumulated observable history; a ``stop`` action
    closes the run there — the board epilogue finalizes at the boundary
    yield, the general path truncates t_final — and the returned data
    carries ``early_stopped`` with the boundary step. The consult sits
    NEXT TO ``_check_drain`` by design: a drain and a stop observe the
    same boundaries, so a drained/recovered run re-derives the identical
    decision from the checkpoint-restored history."""
    from ..sampling.board_runner import run_board_segment

    rec = obs.resolve_recorder(recorder)
    spec = spec_for(cfg)
    use_board = (kboard.supports(g, spec) and not _force_general
                 and cfg.chain == "flip")
    summary_mode = cfg.analytics == "summary"
    analytics = None
    if summary_mode:
        if checkpoint_dir:
            raise ValueError(
                "analytics='summary' keeps histories on device, so "
                "there is no host history to checkpoint; resumable runs "
                "need analytics='history' (checkpoint_every may still "
                "segment a summary run — it then only sets the control "
                "consult grid)")
        if cfg.record_every != 1:
            raise ValueError(
                "analytics='summary' folds every yield on device; "
                "record_every > 1 only thins a host history that "
                "summary mode never materializes")
        if cfg.chain == "recom":
            raise ValueError(
                "analytics='summary' covers the flip-walk runners "
                "(board/general); the recom chain stays on the "
                "history oracle path")
        series_keys = (("slope", "angle") if spec.record_interface
                       else ())
        analytics = stats.DeviceAnalytics(
            cfg.n_chains, observable="cut_count",
            series_keys=series_keys,
            series_cap=(cfg.total_steps if series_keys else 0))
    if use_board:
        handle, states, params = init_board(
            g, plan, n_chains=cfg.n_chains, seed=cfg.seed, spec=spec,
            base=cfg.base, pop_tol=cfg.pop_tol)
    else:
        handle, states, params = init_batch(
            g, plan, n_chains=cfg.n_chains, seed=cfg.seed, spec=spec,
            base=cfg.base, pop_tol=cfg.pop_tol)

    done = 0   # yields recorded (general) / transitions advanced (board)
    n_parts = 0
    hist_parts: dict = {}
    diag_points: list = []   # summary-mode consult points (step, rhat, ess)
    waits_total = np.zeros(cfg.n_chains, np.float64)
    resumed = _load_resume(checkpoint_dir, cfg, states, recorder=recorder,
                           ignore_mismatch=_force_general)
    if resumed is not None:
        done, n_parts, states, hist_parts, waits_total, _ = resumed

    every = cfg.checkpoint_every or cfg.total_steps
    if (cfg.checkpoint_every and cfg.record_every > 1
            and cfg.checkpoint_every % cfg.record_every):
        raise ValueError(
            f"checkpoint_every ({cfg.checkpoint_every}) must be a "
            f"multiple of record_every ({cfg.record_every}): each segment "
            f"thins relative to its own start, so off-grid segment "
            f"boundaries would silently skew the recorded time grid")
    total = cfg.total_steps - (1 if use_board else 0)
    segments = 0
    stopped_at: Optional[int] = None
    if control is not None and done > 0:
        # recovered run that already reached a journaled (adopted) stop
        # boundary: close immediately instead of running an extra
        # segment the reference run never ran
        _ss = control.stop_step(cfg.tag)
        if _ss is not None and done >= _ss:
            stopped_at = done
    while stopped_at is None and done < total:
        check_deadline()
        _check_drain(cfg.tag)
        rfaults.fault_point("segment.step", tag=cfg.tag, done=done)
        n = min(every, total - done)
        if use_board:
            try:
                res = run_board_segment(handle, spec, params, states, n,
                                        record_history=not summary_mode,
                                        record_every=cfg.record_every,
                                        recorder=recorder,
                                        analytics=analytics)
            except KernelPathError as e:
                # the board family is out of bodies for this workload:
                # rerun the whole config on the general gather kernel.
                # Board and general states are different pytrees, so any
                # board checkpoint is ignored (fresh general start).
                rdegrade.record_degradation(rec, e.path, "general",
                                            reason=str(e.cause),
                                            tag=cfg.tag)
                return _run_jax(cfg, g, plan, checkpoint_dir,
                                _stop_after_segments, recorder=recorder,
                                _force_general=True, control=control)
        elif cfg.chain == "recom":
            # second chain family: same segment/checkpoint/drain/control
            # machinery, recom_move as the transition. epsilon reuses the
            # config's population tolerance; the target is the ideal
            # per-district population (the reference's pop_target,
            # grid_chain_sec11.py:330-335).
            res = run_recom(handle, spec, params, states,
                            n_steps=n, record_initial=(done == 0),
                            record_every=cfg.record_every,
                            epsilon=cfg.pop_tol,
                            pop_target=float(np.asarray(g.pop).sum())
                            / cfg.n_districts,
                            recorder=recorder)
        else:
            res = run_chains(handle, spec, params, states,
                             n_steps=n, record_initial=(done == 0),
                             record_history=not summary_mode,
                             record_every=cfg.record_every,
                             recorder=recorder, analytics=analytics)
        states = res.state
        for k, v in res.history.items():
            hist_parts.setdefault(k, []).append(v)
        waits_total += res.waits_total
        done += n
        segments += 1
        if control is not None and done < total and summary_mode:
            # summary mode: the (C, T) history never reached the host —
            # hand the policy the device accumulator's boundary
            # diagnostics instead (one (step, rhat, ess) point per
            # boundary; +8 bytes readback each, honestly accounted)
            analytics.maybe_diagnostics(force=True)
            diag_points.append((done, analytics.rhat, analytics.ess))
        if (control is not None and done < total
                and control.consult_stop(
                    cfg.tag, family=cfg.family, done=done, total=total,
                    every=every,
                    history=_control_history(hist_parts),
                    diag=tuple(diag_points))):
            # the targets held: close the run at this boundary (the
            # checkpoint write is skipped — the job completes here)
            stopped_at = done
            break
        if checkpoint_dir:
            with obs.span(rec, "checkpoint", tag=cfg.tag, done=done):
                n_parts = save_checkpoint(
                    checkpoint_dir, cfg, res.host_state(), done=done,
                    waits_total=waits_total, new_hist=res.history,
                    part_idx=n_parts)
        if _stop_after_segments and segments >= _stop_after_segments:
            raise _SegmentStop(done)

    if use_board:
        # the final yield (no trailing transition) + its wait bookkeeping
        from ..sampling.board_runner import finalize_board_run
        t_close = (cfg.total_steps if stopped_at is None
                   else stopped_at + 1)
        res = finalize_board_run(handle, spec, params, states, hist_parts,
                                 waits_total, [], not summary_mode,
                                 t_close, cfg.record_every,
                                 recorder=recorder, analytics=analytics)
        states, history, waits_total = (res.state, res.history,
                                        res.waits_total)
    else:
        history = {k: np.concatenate(v, axis=1)
                   for k, v in hist_parts.items()}
    if analytics is not None:
        # the chain-0 interface series the sec11/frank artifacts render
        # accumulated full-length on device; one readback here stands in
        # for the per-chunk history stream (assemble_run_data sees the
        # identical (1, T) arrays the oracle path would hand it)
        history = dict(history)
        for k, v in analytics.series_host().items():
            history[k] = v[None, :]
    data = assemble_run_data(
        cfg, g, handle, use_board, states, history, waits_total,
        t_final=(None if stopped_at is None
                 else stopped_at + (1 if use_board else 0)))
    if stopped_at is not None:
        data["early_stopped"] = stopped_at
    if analytics is not None:
        data["summary"] = stats.summary_host(analytics.summary_refs())
        data["readback_bytes"] = analytics.readback_bytes
    return data


def assemble_run_data(cfg: ExperimentConfig, g, handle, use_board: bool,
                      states, history: dict, waits_total,
                      t_final: Optional[int] = None) -> dict:
    """The run epilogue shared by ``_run_jax`` and the sweep service's
    batched executor (service.scheduler slices one tenant's chain rows
    out of a coalesced batch and assembles them here): host readback,
    canvas -> node conversion on the board path, and the reference's
    final-accumulator bookkeeping (finalize_host). ``t_final`` defaults
    to the full schedule; an early-stopped run (control loop) passes
    the boundary it actually closed at."""
    labels = _labels_for(cfg)
    s = jax.tree.map(np.asarray, states)
    if t_final is None:
        t_final = cfg.total_steps  # reference t after the loop (line 402)
    c0 = type(s)(**{f: (np.asarray(v)[0] if (v := getattr(s, f))
                        is not None else None)
                    for f in s.__dataclass_fields__})
    if use_board:
        # canvas -> node order: on lowered (surgical) stencils the board
        # carries hole cells (district -1, untouched bookkeeping) that
        # must not reach the artifacts; node_view is the identity on
        # plain full grids
        assign0 = kboard.node_view(handle, c0.board).astype(np.int64)
        cut_times = kboard.edge_cut_times(g, s)[0]
        assignments = kboard.node_view(handle, s.board)
        c0 = SimpleNamespace(
            part_sum=kboard.node_view(handle, c0.part_sum),
            last_flipped=kboard.node_view(handle, c0.last_flipped),
            num_flips=kboard.node_view(handle, c0.num_flips))
    else:
        assign0 = np.asarray(c0.assignment, dtype=np.int64)
        cut_times = np.asarray(c0.cut_times)
        assignments = np.asarray(s.assignment)
    part_sum, _ = finalize_host(c0, labels, t_final, assignment=assign0)
    return {
        "end_signed": labels[assign0],
        "cut_times": cut_times,
        "part_sum": part_sum,
        "num_flips": np.asarray(c0.num_flips),
        "slopes": history["slope"][0] if "slope" in history else None,
        "angles": history["angle"][0] if "angle" in history else None,
        "waits_sum": float(waits_total[0]),
        "history": history,
        "waits_all": waits_total,
        "state": s,
        "assignments": assignments,
    }


def _run_temper(cfg: ExperimentConfig, g, plan,
                checkpoint_dir: Optional[str] = None,
                _stop_after_segments: Optional[int] = None,
                recorder=None, control=None) -> dict:
    """The temper family: n_chains LADDERS of len(betas) rungs each (so
    the batch is n_chains * n_rungs chains), swap rounds every
    ``swap_every`` transitions. Artifacts follow the chain that ENDS
    holding beta = betas[0] in ladder 0; the per-rung trajectory plot and
    swap-rate stats come from the reconstructed rung histories (a chain's
    own accumulators mix temperatures by design).

    Checkpointing mirrors _run_jax, with the ladder's continuation state
    (exchanged betas, swap key/parity, pair statistics, per-round beta
    assignment) carried in the checkpoint's extra_* arrays; segments are
    whole numbers of swap rounds."""
    if not cfg.betas:
        raise ValueError("temper family needs cfg.betas")
    if cfg.checkpoint_every and cfg.checkpoint_every % cfg.swap_every:
        raise ValueError(
            f"checkpoint_every ({cfg.checkpoint_every}) must be a "
            f"multiple of swap_every ({cfg.swap_every}): segments are "
            f"whole swap rounds")
    spec = spec_for(cfg)
    labels = _labels_for(cfg)
    handle, states, params = init_tempered(
        g, plan, betas=cfg.betas, n_ladders=cfg.n_chains, seed=cfg.seed,
        spec=spec, base=cfg.base, pop_tol=cfg.pop_tol)
    n_rungs = len(cfg.betas)

    if not checkpoint_dir and not cfg.checkpoint_every:
        res = run_tempered(handle, spec, params, states,
                           n_steps=cfg.total_steps, betas=cfg.betas,
                           n_ladders=cfg.n_chains,
                           swap_every=cfg.swap_every, swap_seed=cfg.seed,
                           record_every=cfg.record_every,
                           recorder=recorder)
    else:
        res = _run_temper_segmented(cfg, handle, spec, params, states,
                                    checkpoint_dir, _stop_after_segments,
                                    recorder=recorder, control=control)
    s = res.host_state()
    # the PHYSICAL (beta = betas[0]) chain of each ladder: swaps permute
    # betas, so the cold chain's batch row differs per ladder at run end
    beta_lr = np.asarray(res.params.beta).reshape(cfg.n_chains, n_rungs)
    cold_rows = (np.arange(cfg.n_chains) * n_rungs
                 + np.argmax(beta_lr == np.float32(cfg.betas[0]), axis=1))
    cold = int(cold_rows[0])
    cc = type(s)(**{f: (np.asarray(v)[cold] if (v := getattr(s, f))
                        is not None else None)
                    for f in s.__dataclass_fields__})
    if isinstance(s, kboard.BoardState):
        # board fast path (the Frankengraph lowers onto the stencil
        # body): canvas -> node order, holes dropped (see _run_jax)
        assign_c = kboard.node_view(handle, cc.board).astype(np.int64)
        cut_times_c = kboard.edge_cut_times(g, s)[cold]
        assignments = kboard.node_view(handle, s.board)[cold_rows]
        cc = SimpleNamespace(
            part_sum=kboard.node_view(handle, cc.part_sum),
            last_flipped=kboard.node_view(handle, cc.last_flipped),
            num_flips=kboard.node_view(handle, cc.num_flips))
    else:
        assign_c = np.asarray(cc.assignment, dtype=np.int64)
        cut_times_c = np.asarray(cc.cut_times)
        assignments = np.asarray(s.assignment)[cold_rows]
    part_sum, _ = finalize_host(cc, labels, cfg.total_steps,
                                assignment=assign_c)
    rung_cut = per_rung_history(res, "cut_count")[:, 0, :]  # ladder 0
    return {
        "end_signed": labels[assign_c],
        "cut_times": cut_times_c,
        "part_sum": part_sum,
        "num_flips": np.asarray(cc.num_flips),
        "slopes": None,
        "angles": None,
        "waits_sum": float(res.waits_total[cold]),
        "history": res.history,
        "waits_all": res.waits_total,
        "state": s,
        # one physical plan per ladder (partisan summaries must not mix
        # in molten hot-rung plans)
        "assignments": assignments,
        "rung_cut": rung_cut,
        "swapstats": {
            # pair r is the exchange between the chains holding the
            # (r+1)-th and (r+2)-th LARGEST betas (rank follows the
            # temperature as swaps permute it, tempering.chain_rungs)
            "betas": list(map(float, cfg.betas)),
            "betas_by_rank": sorted(map(float, cfg.betas), reverse=True),
            "swap_every": cfg.swap_every,
            "attempts": res.swap_attempts.tolist(),
            "accepts": res.swap_accepts.tolist(),
            "rates": res.swap_rates().tolist(),
        },
    }


def _run_temper_segmented(cfg: ExperimentConfig, handle, spec, params,
                          states, checkpoint_dir,
                          _stop_after_segments=None, recorder=None,
                          control=None):
    """Checkpointed temper run: whole-swap-round segments through
    run_tempered(segment=True), the between-segment ladder state in the
    checkpoint's extra_* arrays, the per-round beta assignment saved as a
    history part (transposed to the (C, T) part layout). Resumes
    bit-identically: chain PRNG keys live in the state, the swap key and
    parity in the extras.

    ``control`` is consulted between segments with the accumulated swap
    statistics and the current ladder (by rank); a ``reshape_ladder``
    action rewrites the per-chain betas rank-for-rank BEFORE the
    checkpoint is saved, so a resumed run continues with the reshaped
    ladder and the journal-adopted loop never re-derives the action.
    The cold rung (beta max) is exactly preserved by LadderPolicy, so
    _run_temper's cold-row bookkeeping and per_rung_history's
    rank-matching both survive the reshape. Early STOP is deliberately
    not offered to tempered runs (closing the run needs the mid-schedule
    final-yield epilogue; EarlyStopPolicy skips family='temper')."""
    from ..sampling.tempered import TemperResult, _host_rungs

    n_rungs = len(cfg.betas)
    c = cfg.n_chains * n_rungs
    done = 0                     # transitions advanced
    n_parts = 0
    hist_parts: dict = {}
    waits_total = np.zeros(c, np.float64)
    attempts = np.zeros(n_rungs - 1, np.int64)
    accepts = np.zeros(n_rungs - 1, np.int64)
    parity = 0
    swap_key = jax.random.PRNGKey(cfg.seed)
    resumed = _load_resume(checkpoint_dir, cfg, states, recorder=recorder)
    if resumed is not None:
        done, n_parts, states, hist_parts, waits_total, ex = resumed
        params = params.replace(beta=jax.numpy.asarray(ex["beta"]))
        attempts = ex["swap_attempts"].copy()
        accepts = ex["swap_accepts"].copy()
        parity = int(ex["parity"])
        swap_key = jax.numpy.asarray(ex["swap_key"])

    every = cfg.checkpoint_every or (cfg.total_steps - 1)
    total = cfg.total_steps - 1
    segments = 0
    res = None
    while done < total:
        check_deadline()
        _check_drain(cfg.tag)
        rfaults.fault_point("segment.step", tag=cfg.tag, done=done)
        n = min(every, total - done)
        last = done + n >= total
        res = run_tempered(
            handle, spec, params, states,
            n_steps=(n + 1 if last else n), betas=cfg.betas,
            n_ladders=cfg.n_chains, swap_every=cfg.swap_every,
            record_every=cfg.record_every, segment=not last,
            record_initial=(done == 0), start_parity=parity,
            swap_key=swap_key, recorder=recorder)
        states, params = res.state, res.params
        parity, swap_key = res.end_parity, res.end_swap_key
        seg_hist = dict(res.history)
        seg_hist["beta_hist"] = res.beta_hist.T       # (C, rounds) part
        for k, v in seg_hist.items():
            hist_parts.setdefault(k, []).append(v)
        waits_total += res.waits_total
        attempts += res.swap_attempts
        accepts += res.swap_accepts
        done += n
        segments += 1
        if control is not None and done < total:
            beta_now = np.asarray(params.beta)
            ladder = np.sort(beta_now.reshape(-1, n_rungs)[0])[::-1]
            for act in control.consult(
                    cfg.tag, family=cfg.family, done=done, total=total,
                    every=every, swap_attempts=attempts.copy(),
                    swap_accepts=accepts.copy(), betas=ladder):
                if act.kind != "reshape_ladder":
                    continue
                # rank-preserving rewrite: each chain keeps its rung
                # (rank) and receives that rank's new beta
                new_by_rank = np.asarray(act.detail["betas"],
                                         np.float32)
                rungs = _host_rungs(beta_now, n_rungs)
                params = params.replace(
                    beta=jax.numpy.asarray(new_by_rank[rungs]))
        if checkpoint_dir:
            with obs.span(obs.resolve_recorder(recorder), "checkpoint",
                          tag=cfg.tag, done=done):
                n_parts = save_checkpoint(
                    checkpoint_dir, cfg, res.host_state(), done=done,
                    waits_total=waits_total, new_hist=seg_hist,
                    part_idx=n_parts,
                    extra={"beta": np.asarray(params.beta),
                           "swap_attempts": attempts,
                           "swap_accepts": accepts,
                           "parity": np.int64(parity),
                           "swap_key": np.asarray(swap_key)})
        if _stop_after_segments and segments >= _stop_after_segments:
            raise _SegmentStop(done)

    history = {k: np.concatenate(v, axis=1) for k, v in hist_parts.items()}
    beta_hist = history.pop("beta_hist").T            # (rounds, C)
    return TemperResult(
        state=states, history=history, waits_total=waits_total,
        n_yields=cfg.total_steps, params=params,
        betas=np.asarray(cfg.betas, np.float64), n_rungs=n_rungs,
        swap_every=cfg.swap_every, record_every=cfg.record_every,
        general_initial=not isinstance(states, kboard.BoardState),
        beta_hist=beta_hist,
        swap_attempts=attempts, swap_accepts=accepts,
        end_parity=parity, end_swap_key=swap_key)


def _partisan_summary(cfg: ExperimentConfig, g, data) -> dict:
    """Election scores over the run's final plans, from the reference's
    Bernoulli(1/2) pink/purple vote attributes (grid_chain_sec11.py:
    223-228; Election wiring of line 307). Batched: every chain's final
    plan is scored in one pass; the reference's single chain is row 0.
    Works for any k (dual-graph workloads score k=4/8 plans); votes are
    alignment-validated against the graph before tallying."""
    votes = validate_votes(g, seed_votes(g, cfg.seed))
    if data.get("assignments") is not None:     # jax backend: (C, N) batch
        assign = np.asarray(data["assignments"])
    else:                                       # python backend: final plan
        assign = (np.asarray(data["end_signed"]) < 0).astype(np.int64)[None]
    tallies = partisan.district_vote_tallies(assign, votes,
                                             k=cfg.n_districts)
    return {
        "mean_median": partisan.mean_median(tallies),
        "efficiency_gap": partisan.efficiency_gap(tallies),
        "seats_pink": partisan.seats_won(tallies),
    }


class _SegmentStop(RuntimeError):
    """Raised by _run_jax when _stop_after_segments simulates a crash."""

    def __init__(self, done):
        super().__init__(f"stopped after {done} steps")
        self.done = done


def _state_from_arrays(template, loaded: dict, tag: str = "",
                       identity: str = ""):
    """Rebuild a device chain state from checkpoint arrays, using the
    freshly-initialized state as the shape/dtype template. Fields that
    are None on the template (absent from the checkpoint) stay None;
    a template field MISSING from the checkpoint means the checkpoint
    was written by a different kernel path (e.g. a pre-lowering general
    run of a now-lowered graph) — raise CheckpointIdentityError naming
    both field sets and the remedy instead of resuming corrupt state."""
    import jax.numpy as jnp

    found = [k[len("state_"):] for k in loaded
             if k.startswith("state_")]
    expected = [f for f in template.__dataclass_fields__
                if getattr(template, f) is not None]
    if set(expected) - set(found):
        raise CheckpointIdentityError(tag, expected, found,
                                      identity=identity)
    fields = {}
    for f in template.__dataclass_fields__:
        if getattr(template, f) is None and f"state_{f}" not in loaded:
            fields[f] = None
            continue
        arr = loaded[f"state_{f}"]
        fields[f] = jnp.asarray(arr)
    return type(template)(**fields)


def _load_resume(checkpoint_dir, cfg: ExperimentConfig, states_template,
                 recorder=None, ignore_mismatch: bool = False):
    """The shared resume unpack for every segmented runner: None for a
    fresh start, else (done, n_parts, states, hist_parts, waits_total,
    extras) — ``extras`` being the runner-specific extra_* continuation
    arrays (the temper family's ladder state).

    A state-field mismatch (checkpoint written under a different kernel
    path/Spec) raises ``CheckpointIdentityError`` — the supervisor
    classifies it deterministic, so it surfaces instead of being
    silently retried. ``ignore_mismatch=True`` (the kernel-degradation
    rerun) downgrades it to a loud fresh start."""
    if not checkpoint_dir:
        return None
    loaded = load_checkpoint(checkpoint_dir, cfg, recorder=recorder)
    if loaded is None:
        return None
    try:
        states = _state_from_arrays(states_template, loaded, tag=cfg.tag,
                                    identity=_ckpt_identity(cfg))
    except CheckpointIdentityError as e:
        if ignore_mismatch:
            # the checkpoint belongs to the kernel path we just
            # abandoned (degradation rerun): restart fresh, loudly
            print(f"[ckpt] {e}; restarting fresh on the degraded path")
            return None
        raise
    return (int(loaded["meta_done"]),
            int(loaded["meta_n_parts"]),
            states,
            {k[len("hist_"):]: [v] for k, v in loaded.items()
             if k.startswith("hist_")},
            loaded["meta_waits_total"].copy(),
            {k[len("extra_"):]: v for k, v in loaded.items()
             if k.startswith("extra_")})


def make_wall_lookup(g):
    table = {}
    for e in range(g.n_edges):
        u = g.labels[g.edges[e, 0]]
        v = g.labels[g.edges[e, 1]]
        table[frozenset((u, v))] = int(g.wall_id[e])
    return lambda u, v: table.get(frozenset((u, v)), -1)


def _run_python(cfg: ExperimentConfig, g, plan) -> dict:
    """The literal reference loop on the compat oracle."""
    rng = np.random.default_rng(cfg.seed)
    signed = {lab: int(PARITY_LABELS[plan[i]])
              for i, lab in enumerate(g.labels)}
    wall = make_wall_lookup(g)
    updaters = {
        "population": compat.Tally("population"),
        "cut_edges": compat.cut_edges,
        "b_nodes": compat.b_nodes_bi,
        "base": lambda p: cfg.base,
        "geom": compat.make_geom_wait(rng),
        "slope": compat.make_boundary_slope(wall),
        "step_num": compat.step_num,
    }
    part = compat.Partition(g, signed, updaters)
    popbound = compat.within_percent_of_ideal_population(part, cfg.pop_tol)
    accept = (compat.make_cut_accept(rng) if cfg.accept == "cut"
              else compat.make_corrected_cut_accept(rng))
    chain = compat.MarkovChain(
        compat.make_reversible_propose_bi(rng),
        compat.Validator([compat.single_flip_contiguous, popbound]),
        accept, part, cfg.total_steps)

    n = g.n_nodes
    cut_times = np.zeros(g.n_edges, np.int64)
    part_sum = np.array([signed[lab] for lab in g.labels], np.int64)
    last_flipped = np.zeros(n, np.int64)
    num_flips = np.zeros(n, np.int64)
    waits = []
    slopes, angles = [], []
    cut_hist, b_hist = [], []
    center = np.asarray(g.center)

    t = 0
    for p in chain:
        cut_hist.append(len(p["cut_edges"]))
        waits.append(p["geom"])
        b_hist.append(len(p["b_nodes"]))
        temp = p["slope"]
        if len(temp) >= 2:
            enda = ((temp[0][0][0] + temp[0][1][0]) / 2,
                    (temp[0][0][1] + temp[0][1][1]) / 2)
            endb = ((temp[1][0][0] + temp[1][1][0]) / 2,
                    (temp[1][0][1] + temp[1][1][1]) / 2)
            slopes.append((endb[1] - enda[1]) / (endb[0] - enda[0])
                          if endb[0] != enda[0] else np.inf)
            va = np.asarray(enda) - center
            vb = np.asarray(endb) - center
            angles.append(float(np.arccos(np.clip(
                np.dot(va / np.linalg.norm(va), vb / np.linalg.norm(vb)),
                -1, 1))))
        else:  # reference would IndexError here; we record NaN and survive
            slopes.append(np.nan)
            angles.append(np.nan)
        mask = p.cut_edge_mask()
        cut_times += mask
        if p.flips is not None:
            lab = next(iter(p.flips))
            f = g.index[lab]
            part_sum[f] -= p.assignment[lab] * (t - last_flipped[f])
            last_flipped[f] = t
            num_flips[f] += 1
        t += 1

    a = p.assignment_array
    never = last_flipped == 0
    part_sum[never] = t * a[never]
    return {
        "end_signed": a.copy(),
        "cut_times": cut_times,
        "part_sum": part_sum,
        "num_flips": num_flips,
        "slopes": np.asarray(slopes),
        "angles": np.asarray(angles),
        "waits_sum": float(sum(waits)),
        "history": {"cut_count": np.asarray(cut_hist)[None, :],
                    "b_count": np.asarray(b_hist)[None, :],
                    "wait": np.asarray(waits, dtype=float)[None, :]},
        "waits_all": np.asarray([float(sum(waits))]),
        "state": None,
    }


def _ckpt_identity(cfg: ExperimentConfig) -> str:
    """Everything the tag does NOT encode (or encodes lossily — the tag
    truncates base/pop_tol to int(100*x)) but resume correctness needs.

    Compatibility note (ADVICE r4): adding a field here invalidates every
    checkpoint written before the addition — identity mismatch makes
    resume restart the config from scratch (by design: a stale checkpoint
    must never be silently continued under new semantics). The round-4
    additions (k, grid, lattice/dual dims, record_every, betas,
    swap_every) did exactly that to round-3 checkpoints. Discarding is
    loud: the driver logs the mismatch before restarting."""
    return (f"{cfg.family}|steps={cfg.total_steps}|chains={cfg.n_chains}|"
            f"seed={cfg.seed}|contiguity={cfg.contiguity}|"
            f"accept={cfg.accept}|base={cfg.base!r}|pop={cfg.pop_tol!r}|"
            f"kp={cfg.propose_parallel}|k={cfg.n_districts}|"
            f"grid={cfg.grid}|lat={cfg.lattice_m}x{cfg.lattice_n}|"
            # '@source' only for non-default geometry: keeps every
            # checkpoint written before dual_source existed valid
            f"dual={cfg.dual_nx}x{cfg.dual_ny}"
            f"{'' if cfg.dual_source == 'quads' else '@' + cfg.dual_source}|"
            f"re={cfg.record_every}|"
            f"betas={tuple(map(float, cfg.betas))!r}|"
            f"se={cfg.swap_every}"
            # conditional suffixes: checkpoints written before
            # chain/variant existed stay valid for default configs
            + ("" if cfg.chain == "flip" else f"|chain={cfg.chain}")
            + ("" if cfg.variant == "none" else f"|var={cfg.variant}"))


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for blk in iter(lambda: f.read(1 << 20), b""):
            h.update(blk)
    return h.hexdigest()


def _fsync_dir(d: str):
    """Durably commit a rename: fsync the containing directory (a no-op
    where the platform/filesystem refuses directory fds)."""
    try:
        fd = os.open(d or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        return
    finally:
        os.close(fd)


def _write_npz(path: str, arrays: dict) -> str:
    """write-to-temp + fsync + atomic rename; returns the SHA-256 of
    the bytes written (hashed on the temp file, so any later divergence
    of the renamed file — a torn write, bit rot — is detectable)."""
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    digest = _sha256_file(tmp)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    return digest


def _read_npz(path: str) -> Optional[dict]:
    """dict of arrays, or None when the file is unreadable (truncated,
    bit-rotted, not an npz) — integrity handling must never crash."""
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except Exception:
        return None


def _manifest_path(ckpt_dir: str, cfg: ExperimentConfig) -> str:
    return os.path.join(ckpt_dir, cfg.tag + ".manifest.json")


def _load_manifest(ckpt_dir: str, cfg: ExperimentConfig):
    mpath = _manifest_path(ckpt_dir, cfg)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath, encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(man, dict) or man.get("version") != 1:
        return None
    man.setdefault("gen", -1)
    man.setdefault("current", None)
    man.setdefault("previous", None)
    man.setdefault("parts", {})
    return man


def _write_manifest(ckpt_dir: str, cfg: ExperimentConfig, man: dict):
    mpath = _manifest_path(ckpt_dir, cfg)
    tmp = mpath + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(man, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, mpath)
    _fsync_dir(ckpt_dir)


def save_checkpoint(ckpt_dir: str, cfg: ExperimentConfig, host_state,
                    done: int = 0, waits_total=None, new_hist=None,
                    part_idx: int = 0, extra: Optional[dict] = None) -> int:
    """Per-config checkpoint: ``<tag>.npz`` holds the chain state
    (state_*), progress + config identity (meta_*), and any
    runner-specific continuation arrays (extra_* — the temper family's
    ladder betas, swap key/parity, pair statistics); each segment's
    history goes to its own ``<tag>.h<k>.npz`` part file so a save costs
    O(segment), not O(run-so-far). The main file is written atomically
    AFTER its part, so meta_n_parts never points at a missing file.
    Returns the next part index.

    Integrity (ISSUE 7): every file goes through write-to-temp + fsync
    + atomic rename and its SHA-256 lands in ``<tag>.manifest.json``;
    the manifest keeps the last TWO generations — before each save the
    old main rotates to ``<tag>.prev.npz`` — so ``load_checkpoint`` can
    fall back one generation when the newest fails verification. The
    current generation stays at exactly ``<tag>.npz`` (pre-manifest
    readers and tooling keep working)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    rfaults.fault_point("checkpoint.write", tag=cfg.tag, done=done)
    man = _load_manifest(ckpt_dir, cfg)
    if man is None:
        man = {"version": 1, "tag": cfg.tag, "gen": -1,
               "current": None, "previous": None, "parts": {}}
    if new_hist:
        pname = f"{cfg.tag}.h{part_idx:04d}.npz"
        ppath = os.path.join(ckpt_dir, pname)
        man["parts"][pname] = _write_npz(
            ppath, {k: np.asarray(v) for k, v in new_hist.items()})
        rfaults.corrupt_file("checkpoint.write", ppath)
        part_idx += 1
    # None fields (e.g. the diagonal cut_times planes on non-lowered
    # board states) are omitted; _state_from_arrays restores them as None
    arrays = {f"state_{f}": np.asarray(v)
              for f in host_state.__dataclass_fields__
              if (v := getattr(host_state, f)) is not None}
    arrays["meta_done"] = np.int64(done)
    arrays["meta_n_parts"] = np.int64(part_idx)
    arrays["meta_identity"] = np.array(_ckpt_identity(cfg))
    if waits_total is not None:
        arrays["meta_waits_total"] = np.asarray(waits_total, np.float64)
    for k, v in (extra or {}).items():
        arrays[f"extra_{k}"] = np.asarray(v)
    path = os.path.join(ckpt_dir, cfg.tag + ".npz")
    prev_path = os.path.join(ckpt_dir, cfg.tag + ".prev.npz")
    cur = man["current"]
    if cur is not None:
        cur_path = os.path.join(ckpt_dir, cur["file"])
        if os.path.exists(cur_path):
            if os.path.abspath(cur_path) == os.path.abspath(path):
                os.replace(cur_path, prev_path)
                cur = dict(cur, file=os.path.basename(prev_path))
            # else: current already sits at the .prev slot (a
            # post-fallback resume) and simply stays the fallback
            man["previous"] = cur
    digest = _write_npz(path, arrays)
    rfaults.corrupt_file("checkpoint.write", path)
    man["gen"] += 1
    man["current"] = {"gen": man["gen"], "file": cfg.tag + ".npz",
                      "sha256": digest, "done": int(done),
                      "n_parts": int(part_idx)}
    _write_manifest(ckpt_dir, cfg, man)
    return part_idx


def _meta_checks(cfg: ExperimentConfig, d: dict, path: str) -> bool:
    """Format/identity/progress validation of a loaded main file — the
    'is this checkpoint for THIS run' gate (distinct from integrity:
    a mismatch means fresh start, never generation fallback)."""
    if "meta_done" not in d or "meta_identity" not in d:
        print(f"[ckpt] ignoring {path}: unrecognized format")
        return False
    if str(d["meta_identity"]) != _ckpt_identity(cfg):
        print(f"[ckpt] ignoring {path}: config mismatch "
              f"({d['meta_identity']} != {_ckpt_identity(cfg)})")
        return False
    if int(d["meta_done"]) > cfg.total_steps:
        print(f"[ckpt] ignoring {path}: more steps than requested")
        return False
    return True


def _generation_payload(ckpt_dir, cfg, man, entry):
    """Verify + load one manifest generation. Returns
    ``(d, None, None)`` on success (parts concatenated into hist_*),
    else ``(None, reason, bad_path)`` naming the file that failed."""
    epath = os.path.join(ckpt_dir, entry["file"])
    if not os.path.exists(epath):
        return None, "missing main file", epath
    if _sha256_file(epath) != entry["sha256"]:
        return None, "main file checksum mismatch", epath
    d = _read_npz(epath)
    if d is None:
        return None, "unreadable main file", epath
    hist: dict = {}
    for k in range(int(entry["n_parts"])):
        pname = f"{cfg.tag}.h{k:04d}.npz"
        ppath = os.path.join(ckpt_dir, pname)
        if not os.path.exists(ppath):
            return None, f"missing part {pname}", ppath
        want = man["parts"].get(pname)
        if want is not None and _sha256_file(ppath) != want:
            return None, f"part {pname} checksum mismatch", ppath
        pd = _read_npz(ppath)
        if pd is None:
            return None, f"unreadable part {pname}", ppath
        for name, arr in pd.items():
            hist.setdefault(name, []).append(arr)
    for name, parts in hist.items():
        d[f"hist_{name}"] = np.concatenate(parts, axis=1)
    return d, None, None


def _quarantine_generation(ckpt_dir, cfg, man, entry, reason, bad_path,
                           rec):
    """A generation failed verification: move its main file (and the
    specific bad file) into ``.corrupt/``, emit ``checkpoint_corrupt``,
    and promote the previous generation to current. Shared history
    parts the fallback generation still references are left in place
    (if the bad file IS shared, the fallback fails its own check next
    and resume degrades to a fresh start — never a crash)."""
    cdir = os.path.join(ckpt_dir, ".corrupt")
    os.makedirs(cdir, exist_ok=True)
    prev = man.get("previous")
    prev_parts = int(prev["n_parts"]) if prev else 0
    moved = []
    epath = os.path.join(ckpt_dir, entry["file"])
    for k in range(prev_parts, int(entry["n_parts"])):
        pname = f"{cfg.tag}.h{k:04d}.npz"
        ppath = os.path.join(ckpt_dir, pname)
        if os.path.exists(ppath):
            moved.append(ppath)
        man["parts"].pop(pname, None)
    if bad_path and bad_path not in moved and os.path.exists(bad_path) \
            and bad_path != epath:
        moved.append(bad_path)
    if os.path.exists(epath):
        moved.append(epath)
    for src in moved:
        dst = os.path.join(
            cdir, f"g{int(entry['gen']):04d}.{os.path.basename(src)}")
        os.replace(src, dst)
    print(f"[ckpt] {cfg.tag}: generation {entry['gen']} corrupt "
          f"({reason}); quarantined {len(moved)} file(s) to {cdir}, "
          f"falling back to generation "
          f"{prev['gen'] if prev else 'none (fresh start)'}")
    if rec:
        rec.emit("checkpoint_corrupt", tag=cfg.tag, path=epath,
                 reason=reason, generation=int(entry["gen"]),
                 quarantined=[os.path.basename(p) for p in moved])
    man["current"] = prev
    man["previous"] = None
    _write_manifest(ckpt_dir, cfg, man)


def load_checkpoint(ckpt_dir: str, cfg: ExperimentConfig, recorder=None):
    """Load and validate a checkpoint; None (fresh start) when absent,
    written by an incompatible config, or in an unrecognized format —
    the recovery path must never crash on stale files.

    With a manifest present every generation is SHA-256-verified before
    use; a corrupt/truncated generation is quarantined to ``.corrupt/``
    (``checkpoint_corrupt`` event) and the previous generation is tried
    instead — a torn checkpoint write now costs one generation of
    progress, not the whole run. Pre-manifest checkpoints load through
    the legacy unverified path."""
    rec = obs.resolve_recorder(recorder)
    path = os.path.join(ckpt_dir, cfg.tag + ".npz")
    rfaults.fault_point("checkpoint.load", tag=cfg.tag)
    rfaults.corrupt_file("checkpoint.load", path)
    man = _load_manifest(ckpt_dir, cfg)
    if man is None:
        # legacy (pre-manifest / hand-dropped) checkpoint: single
        # generation, no integrity data
        if not os.path.exists(path):
            return None
        d = _read_npz(path)
        if d is None:
            print(f"[ckpt] ignoring {path}: unreadable "
                  "(no manifest, no fallback generation)")
            return None
        if not _meta_checks(cfg, d, path):
            return None
        hist: dict = {}
        for k in range(int(d["meta_n_parts"])):
            ppath = os.path.join(ckpt_dir, f"{cfg.tag}.h{k:04d}.npz")
            if not os.path.exists(ppath):
                print(f"[ckpt] ignoring {path}: missing part {ppath}")
                return None
            pd = _read_npz(ppath)
            if pd is None:
                print(f"[ckpt] ignoring {path}: unreadable part {ppath}")
                return None
            for name, arr in pd.items():
                hist.setdefault(name, []).append(arr)
        for name, parts in hist.items():
            d[f"hist_{name}"] = np.concatenate(parts, axis=1)
        return d
    while man["current"] is not None:
        entry = man["current"]
        d, reason, bad_path = _generation_payload(ckpt_dir, cfg, man,
                                                  entry)
        if d is None:
            _quarantine_generation(ckpt_dir, cfg, man, entry, reason,
                                   bad_path, rec)
            continue
        epath = os.path.join(ckpt_dir, entry["file"])
        if not _meta_checks(cfg, d, epath):
            return None
        return d
    return None


def write_heartbeat(path: Optional[str], recorder=None, **payload):
    """Atomically (tmp+rename) refresh the sweep's heartbeat file: one
    small JSON object a watcher (or a resuming operator) can poll to see
    where a multi-hour sweep is WITHOUT parsing the event stream — the
    reference's only liveness signal was artifacts appearing on disk
    (SURVEY.md §5). Always carries ``ts``; a stale ts is the hang
    detector (obs_report --strict --heartbeat flags mtimes older than
    2x the expected interval).

    Failures are NON-fatal (ISSUE 7 satellite): a full disk or missing
    dir logs a ``heartbeat_error`` event (when a recorder is live) and
    the run continues — liveness telemetry must never abort a segment."""
    if not path:
        return
    try:
        rfaults.fault_point("heartbeat.write", path=path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        payload["ts"] = time.time()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    except (OSError, rfaults.InjectedFault) as e:
        msg = f"{type(e).__name__}: {e}"
        print(f"[heartbeat] write failed ({msg}); continuing",
              file=sys.stderr)
        rec = obs.resolve_recorder(recorder)
        if rec:
            rec.emit("heartbeat_error", message=msg, path=path)


def heartbeat_path_for(path: Optional[str], tag: str):
    """Per-job heartbeat file for one config under a shared base path:
    ``heartbeat.json`` + tag ``2B30P10`` -> ``heartbeat.2B30P10.json``.
    One-shot sweeps run configs strictly in sequence, so a single file
    is unambiguous there; the sweep SERVICE runs jobs interleaved
    (coalesced batches, retries) and concurrent refreshes of one file
    would clobber each other's ``current``/``diag`` payloads — each job
    gets its own file and the service maintains a merged summary at the
    base path (see service.scheduler; obs_report --heartbeat probes
    both shapes)."""
    if not path:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.{tag}{ext or '.json'}"


def install_live_hooks(rec, heartbeat, cfg, progress: dict,
                       namespace: bool = False, control=None):
    """Wire the recorder's live-observer hooks for one in-flight config:
    ChainMonitor calls ``rec.diag_hook`` / ``rec.anomaly_hook``, the
    runners' MetricsRegistry.notify calls ``rec.metrics_hook``; each
    refresh re-writes the heartbeat with whatever has been seen so far
    (keys ``diag`` / ``anomalies`` — a per-kind episode tally — /
    ``metrics``). Returns ``(hb_state, uninstall)``. ``hb_state`` is
    kept live even without a heartbeat path: the supervisor's error
    classifier reads ``hb_state["anomalies"]`` to tell a config that
    failed while frozen/collapsed (deterministic) from a machinery
    hiccup (transient). Shared by run_sweep and
    resilience.supervisor.run_supervised_sweep.

    ``namespace=True`` (the sweep service) redirects the refreshes to
    the config's own ``heartbeat_path_for(heartbeat, cfg.tag)`` file so
    concurrent in-flight jobs never clobber one shared file; the
    one-shot sweeps keep the single-file behavior unchanged."""
    hb_state = {"diag": None, "metrics": None, "anomalies": {}}
    if namespace:
        heartbeat = heartbeat_path_for(heartbeat, cfg.tag)

    def _uninstall():
        if rec:
            rec.diag_hook = None
            rec.anomaly_hook = None
            rec.metrics_hook = None

    if not rec:
        return hb_state, _uninstall

    def _hb_refresh(_tag=cfg.tag, _state=hb_state):
        if not heartbeat:
            return
        extra = {}
        if _state["diag"] is not None:
            extra["diag"] = {_tag: _state["diag"]}
        if _state["metrics"] is not None:
            extra["metrics"] = {_tag: _state["metrics"]}
        if _state["anomalies"]:
            extra["anomalies"] = {_tag: dict(_state["anomalies"])}
        write_heartbeat(heartbeat, recorder=rec, status="running",
                        current=_tag, last=None, **progress, **extra)

    def _on_diag(diag, _state=hb_state, _hb=_hb_refresh):
        _state["diag"] = diag
        _hb()

    def _on_anomaly(anom, _state=hb_state, _hb=_hb_refresh,
                    _ctl=control, _tag=cfg.tag):
        kind = anom.get("kind", "unknown")
        _state["anomalies"][kind] = _state["anomalies"].get(kind, 0) + 1
        if _ctl is not None:
            # forward to the control loop (LadderPolicy widens its
            # swap-rate band on acceptance_collapse / frozen_chain)
            _ctl.observe_anomaly(anom.get("tag", _tag) or _tag, kind)
        _hb()

    def _on_metrics(snap, _state=hb_state, _hb=_hb_refresh):
        _state["metrics"] = snap
        _hb()

    rec.diag_hook = _on_diag
    rec.anomaly_hook = _on_anomaly
    rec.metrics_hook = _on_metrics
    return hb_state, _uninstall


def run_sweep(configs, outdir: str, checkpoint_dir: Optional[str] = None,
              verbose: bool = True, recorder=None,
              heartbeat: Optional[str] = None, control=None) -> list:
    """Sweep with skip-if-done resume (per-config completion manifest).

    ``recorder``: an obs.Recorder receives one ``sweep_config`` event per
    config (status start/done/skip, artifact counts, seconds) and is
    threaded into every runner underneath for per-chunk telemetry; an
    uncaught per-config failure emits an ``error`` event before
    re-raising. The sweep and each attempted config are wrapped in
    ``sweep`` / ``config`` spans (obs.trace) — closed on the error path
    too, so the span stream of a failed sweep still validates.
    ``heartbeat``: path of a JSON progress file refreshed before and
    after each config (write_heartbeat) — while a config is running,
    each runner ``diag`` snapshot, each monitor ``anomaly``, and each
    per-chunk metrics snapshot also refresh it (keys ``diag`` /
    ``anomalies`` — a per-kind episode tally — / ``metrics`` — latest
    p50/p95/p99 chunk latency and flips/s), so the hang detector doubles
    as an in-flight health readout.
    ``control``: a control.ControlLoop consulted at segment boundaries
    (adaptive sweeps: early stop, ladder reshapes, advisory retunes);
    it adopts the sweep's recorder so its ``control_action`` events
    land in the same stream.
    """
    rec = obs.resolve_recorder(recorder)
    if control is not None:
        control.attach(recorder=rec)
    configs = list(configs)
    results = []
    n_done = n_skipped = 0
    sweep_span = obs.span(rec, "sweep", n_configs=len(configs))
    sweep_span.begin()
    try:
        for i, cfg in enumerate(configs):
            if is_done(cfg, outdir):
                n_skipped += 1
                if verbose:
                    print(f"[skip] {cfg.family} {cfg.tag} "
                          f"(artifacts complete)")
                rec.emit("sweep_config", tag=cfg.tag, family=cfg.family,
                         status="skip",
                         artifacts=len(artifact_kinds(cfg.family)),
                         index=i, n_configs=len(configs))
                write_heartbeat(heartbeat, recorder=rec,
                                status="running", current=None,
                                last=cfg.tag, n_done=n_done,
                                n_skipped=n_skipped,
                                n_configs=len(configs))
                continue
            t0 = time.monotonic()
            rec.emit("sweep_config", tag=cfg.tag, family=cfg.family,
                     status="start",
                     artifacts=count_artifacts(cfg, outdir),
                     index=i, n_configs=len(configs))
            write_heartbeat(heartbeat, recorder=rec, status="running",
                            current=cfg.tag,
                            last=None, n_done=n_done, n_skipped=n_skipped,
                            n_configs=len(configs))
            cfg_span = obs.span(rec, "config", tag=cfg.tag,
                                family=cfg.family).begin()
            _, uninstall = install_live_hooks(
                rec, heartbeat, cfg,
                dict(n_done=n_done, n_skipped=n_skipped,
                     n_configs=len(configs)), control=control)
            try:
                data = run_config(cfg, outdir, checkpoint_dir,
                                  recorder=rec, control=control)
            except Exception as e:
                rec.emit("error", message=f"{type(e).__name__}: {e}",
                         tag=cfg.tag, family=cfg.family)
                cfg_span.end(error=type(e).__name__)
                write_heartbeat(heartbeat, recorder=rec, status="error",
                                current=cfg.tag, last=None, n_done=n_done,
                                n_skipped=n_skipped,
                                n_configs=len(configs),
                                error=f"{type(e).__name__}: {e}")
                raise
            finally:
                uninstall()
            n_done += 1
            cfg_span.end(seconds=time.monotonic() - t0)
            rec.emit("sweep_config", tag=cfg.tag, family=cfg.family,
                     status="done",
                     artifacts=count_artifacts(cfg, outdir),
                     seconds=time.monotonic() - t0, index=i,
                     n_configs=len(configs))
            write_heartbeat(heartbeat, recorder=rec, status="running",
                            current=None, last=cfg.tag, n_done=n_done,
                            n_skipped=n_skipped, n_configs=len(configs))
            if verbose:
                print(f"[done] {cfg.family} {cfg.tag} "
                      f"waits={data['waits_sum']:.4g} "
                      f"({time.monotonic() - t0:.1f}s)")
            results.append((cfg, data))
    finally:
        sweep_span.end(n_done=n_done, n_skipped=n_skipped)
    write_heartbeat(heartbeat, recorder=rec, status="complete",
                    current=None, last=None, n_done=n_done,
                    n_skipped=n_skipped, n_configs=len(configs))
    return results
