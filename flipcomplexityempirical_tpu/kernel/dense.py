"""General kernel v2: rejection-free dense proposal over bit-packed
node sets (ISSUE 15).

The legacy general kernel (kernel/step.py:propose) re-proposes invalid
moves in a ``lax.while_loop``; under vmap that loop runs at the batch-MAX
trip count over all C chains, re-executing boundary prefix-sum selection
and ``patch_connected`` each pass (PROFILE.md round-2 diagnosis). This
module applies the board-kernel playbook to arbitrary sparse graphs:

1. **rejection-free proposal** — per step, build the full length-N (or
   N*K for the 'pair' walk) validity plane once and select the m-th
   valid move directly. Conditioned on the state, "uniform over the
   move set, re-propose until valid" IS "uniform over the valid subset"
   (rejection-sampling equivalence), so the step distribution matches
   the legacy kernel exactly whenever a valid move exists; a step with
   zero valid moves self-loops (the legacy kernel's max_tries
   exhaustion, reached deterministically instead of after 256 draws).
2. **bit-packed node sets** — the validity plane lives in
   ``ceil(N/32)`` uint32 words; selection is a two-stage
   ``lax.population_count`` reduction (word cumsum -> in-word prefix
   popcount), the bitboard-v3 selection generalized off the lattice.
3. **incremental contiguity plane** — ``ChainState.conn_bits`` carries
   "flipping node i keeps its origin district connected" as one bit per
   node. A committed flip at v only changes the plane inside
   {v} | patch(v) (radius-r patch balls are symmetric: u in patch(v)
   iff v in patch(u), asserted by tests/test_dense.py), so the refresh
   is O(P) ``patch_connected`` calls per step, not N.

Not bit-identical to the legacy kernel (different PRNG consumption),
so it ships as its own visibly tagged dispatch rung ``general_dense``
with the legacy kernel as correctness oracle and degradation target —
never a silent swap. Acceptance and all bookkeeping funnel through
``kernel/step.py:commit``, shared with the legacy kernel, which pins
the Metropolis/waits/counter semantics equal by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graphs.lattice import DeviceGraph
from ..state import chain_state
from ..state.chain_state import ChainState
from . import contiguity
from . import step as kstep
from .step import Spec, StepParams


def supported(graph, spec: Spec) -> bool:
    """True iff ``general_dense`` can run this (graph, spec). Gated OUT:
    'selfloop' invalid policy (one draw per step is a different walk than
    uniform-over-valid), frame_interface (a global plane, not per-node),
    'exact' contiguity (a whole-graph BFS per node would cost O(N^2));
    everything else the legacy general kernel accepts is in."""
    if spec.proposal not in ("bi", "pair"):
        return False
    if spec.proposal == "bi" and spec.n_districts != 2:
        return False
    if spec.nobacktrack and spec.proposal != "bi":
        return False
    if spec.invalid != "repropose":
        return False
    if spec.frame_interface:
        return False
    if spec.contiguity not in ("patch", "none"):
        return False
    if spec.contiguity == "patch" and not getattr(graph, "patch_ok", True):
        return False
    return True


def n_words(n: int) -> int:
    """uint32 words needed for an n-bit node set."""
    return (n + 31) // 32


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def pack_mask(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[M] -> uint32[ceil(M/32)], bit j of word w = mask[32*w + j].
    Pad bits (past M) are zero, so packed planes can be AND-ed freely
    without ever selecting a pad index."""
    m = mask.shape[0]
    w = n_words(m)
    padded = jnp.zeros(w * 32, bool).at[: m].set(mask)
    lanes = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(
        jnp.where(padded.reshape(w, 32),
                  jnp.uint32(1) << lanes[None, :], jnp.uint32(0)),
        axis=1, dtype=jnp.uint32)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def unpack_mask(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """uint32[W] -> bool[n] (inverse of pack_mask, pad bits dropped)."""
    lanes = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[:, None] >> lanes[None, :]) & jnp.uint32(1)
    return bits.reshape(-1)[:n].astype(bool)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def select_nth_set(words: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Index of the (m+1)-th set bit of a packed uint32[W] set — the
    two-stage popcount selection: word-level popcount cumsum finds the
    containing word, a 32-lane in-word prefix popcount finds the bit.
    Returns 0 when the set is empty (callers check total > 0)."""
    pc = jax.lax.population_count(words).astype(jnp.int32)
    c = jnp.cumsum(pc)
    wi = jnp.argmax(c > m).astype(jnp.int32)
    r = m - (c[wi] - pc[wi])                 # rank within the word
    lanes = jnp.arange(32, dtype=jnp.uint32)
    # (2 << lane) - 1 keeps bits 0..lane; at lane 31 the uint32 shift
    # wraps to 0 and the -1 yields the full mask — exactly right.
    prefix = jax.lax.population_count(
        words[wi] & ((jnp.uint32(2) << lanes) - jnp.uint32(1))
    ).astype(jnp.int32)
    bit = jnp.argmax(prefix > r).astype(jnp.int32)
    return wi * 32 + bit


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def conn_plane(dg: DeviceGraph, spec: Spec, assignment: jnp.ndarray):
    """bool[N]: "flipping node i out of its current district keeps that
    district connected" — the full recompute (init and oracle; the
    in-loop path maintains it incrementally via refresh_conn_bits)."""
    n = dg.n_nodes
    if spec.contiguity == "none":
        return jnp.ones(n, bool)
    a = assignment.astype(jnp.int32)
    return jax.vmap(
        lambda u: contiguity.patch_connected(dg, assignment, u, a[u])
    )(jnp.arange(n, dtype=jnp.int32))


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def init_conn_bits(dg: DeviceGraph, spec: Spec, assignment: jnp.ndarray):
    """uint32[ceil(N/32)] packed conn plane for one chain's assignment."""
    return pack_mask(conn_plane(dg, spec, assignment))


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def _joint_patch_connected(dg: DeviceGraph, assignment: jnp.ndarray,
                           nodes: jnp.ndarray) -> jnp.ndarray:
    """``contiguity.patch_connected`` for a whole (R,) index vector at
    once, with ONE fixpoint loop shared across the rows. Label
    propagation is a monotone map, so running all rows to the joint
    fixpoint computes exactly the per-node fixpoints — bit-identical to
    R independent patch_connected calls — while the while_loop stops at
    the deepest row's convergence (~member-subgraph diameter) instead
    of the static P-iteration worst case, the refresh-path win that
    pays for maintaining the conn plane every step."""
    p = dg.max_patch
    a = assignment.astype(jnp.int32)
    pn = dg.patch_nodes[nodes]                        # (R, P), pad = self
    padj = dg.patch_adj[nodes]                        # (R, P)
    slots = jnp.arange(p, dtype=jnp.int32)
    member = (a[pn] == a[nodes][:, None]) & (pn != nodes[:, None])
    lane = jnp.uint32(1) << slots.astype(jnp.uint32)
    member_word = jnp.sum(jnp.where(member, lane[None, :], 0),
                          axis=1, dtype=jnp.uint32)   # (R,)
    seed_mask = member & (slots[None, :] < dg.deg[nodes][:, None])
    seed_word = jnp.sum(jnp.where(seed_mask, lane[None, :], 0),
                        axis=1, dtype=jnp.uint32)
    n_seeds = seed_mask.sum(axis=1)
    start = seed_word & (~seed_word + jnp.uint32(1))  # lowest set bit

    def cond(carry):
        return carry[1]

    def body(carry):
        reach, _ = carry
        sel = ((reach[:, None] >> slots.astype(jnp.uint32))
               & jnp.uint32(1)).astype(bool)
        contrib = jnp.where(sel, padj, jnp.uint32(0))
        new = reach | (jax.lax.reduce(
            contrib, jnp.uint32(0), jax.lax.bitwise_or, (1,))
            & member_word)
        return new, (new != reach).any()

    reach, _ = jax.lax.while_loop(cond, body, (start, jnp.bool_(True)))
    all_reached = (seed_word & ~reach) == 0
    return jnp.where(n_seeds <= 1, True, all_reached)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def refresh_conn_bits(dg: DeviceGraph, spec: Spec, assignment: jnp.ndarray,
                      conn_bits: jnp.ndarray, v: jnp.ndarray):
    """Post-commit incremental refresh: recompute the conn bit of
    {v} | patch(v) against the committed assignment and splice the bits
    in place. Idempotent on a rejected step (the recomputed bits equal
    the carried ones), so no accept gating is needed. Patch pad slots
    (pn == v) are deduped via ``live`` so every touched (word, bit) pair
    is distinct and the scatter-adds cannot carry."""
    if spec.contiguity == "none":
        return conn_bits
    pn = dg.patch_nodes[v]                            # i32[P], pad = v
    aff = jnp.concatenate([v[None].astype(jnp.int32), pn])
    live = jnp.concatenate([jnp.ones((1,), bool), pn != v])
    new_bits = _joint_patch_connected(dg, assignment, aff)
    w = conn_bits.shape[0]
    wi = aff // 32
    bit = (aff % 32).astype(jnp.uint32)
    clear = jnp.zeros(w, jnp.uint32).at[wi].add(
        jnp.where(live, jnp.uint32(1) << bit, jnp.uint32(0)))
    sets = jnp.zeros(w, jnp.uint32).at[wi].add(
        jnp.where(live & new_bits, jnp.uint32(1) << bit, jnp.uint32(0)))
    return (conn_bits & ~clear) | sets


def _pop_plane_bi(dg: DeviceGraph, params, a, dist_pop):
    """bool[N] population feasibility for the 2-district sign flip
    (d_to = 1 - a): both bounds evaluated at the single target —
    two N-planes, not an (N, K) table."""
    popv = dg.pop.astype(jnp.float32)
    return (((dist_pop[a] - popv) >= params.pop_lo)
            & ((dist_pop[1 - a] + popv) <= params.pop_hi))


def _pop_planes(dg: DeviceGraph, params, a, dist_pop):
    """Population-bound planes: ``from_ok`` bool[N] (donor district stays
    >= pop_lo after losing node i) and ``to_ok`` bool[N, K] (district d
    stays <= pop_hi after gaining node i) — the vectorized form of the
    legacy _validate_parts pop predicate (pair walk)."""
    popv = dg.pop.astype(jnp.float32)
    from_ok = (dist_pop[a] - popv) >= params.pop_lo
    to_ok = (dist_pop[None, :] + popv[:, None]) <= params.pop_hi
    return from_ok, to_ok


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def propose_dense(dg: DeviceGraph, spec: Spec, params: StepParams,
                  state: ChainState, key,
                  count: bool = False):
    """Rejection-free proposal: one uniform, one packed-popcount
    selection over the exact valid-move set. Returns
    ``(v, d_to, valid, tries)`` (+ the int32[3] reject-reason vector
    when ``count``), the same contract as kernel/step.py:propose —
    ``tries`` is always 1 (each step consumes exactly one draw), and a
    zero-valid step returns valid=False (commit self-loops it and
    exhausted_count advances, the legacy exhaustion outcome)."""
    k = spec.n_districts
    n = dg.n_nodes
    a = state.assignment.astype(jnp.int32)
    dist_pop = state.dist_pop.astype(jnp.float32)

    if spec.proposal == "bi":
        if k != 2:
            raise ValueError("proposal 'bi' requires n_districts == 2")
        cand = state.cut_deg > 0
        if spec.nobacktrack:
            f = state.cur_flip_node
            fi = jnp.maximum(f, 0)
            excl = (f >= 0) & cand[fi] & (state.b_count > 1)
            cand = cand & ~((jnp.arange(n) == fi) & excl)
        pop_ok = _pop_plane_bi(dg, params, a, dist_pop)
        words = pack_mask(cand & pop_ok) & state.conn_bits
    elif spec.proposal == "pair":
        if spec.nobacktrack:
            raise ValueError("nobacktrack requires proposal 'bi' "
                             "(the pair walk has no single excluded "
                             "reverse move)")
        from_ok, to_ok = _pop_planes(dg, params, a, dist_pop)
        pm = chain_state.pair_move_mask(dg, a, k)         # (N, K)
        conn = unpack_mask(state.conn_bits, n)
        cand = pm.any(axis=1)
        pop_ok2 = from_ok[:, None] & to_ok
        pop_ok = (pm & pop_ok2).any(axis=1)
        words = pack_mask((pm & pop_ok2 & conn[:, None]).reshape(-1))
    else:
        raise ValueError(f"proposal {spec.proposal!r}")

    total = jnp.sum(jax.lax.population_count(words).astype(jnp.int32))
    u = jax.random.uniform(key)
    m = jnp.minimum((u * total.astype(jnp.float32)).astype(jnp.int32),
                    jnp.maximum(total - 1, 0))
    idx = select_nth_set(words, m)
    if spec.proposal == "bi":
        v = jnp.minimum(idx, n - 1)
        d_to = 1 - a[v]
    else:
        v = jnp.minimum(idx // k, n - 1)
        d_to = idx % k
    valid = total > 0
    tries = jnp.int32(1)
    if not count:
        return v, d_to, valid, tries
    # zero-valid attribution, priority-ordered like the legacy taxonomy
    # ([non-boundary, pop-bound, disconnect]): no boundary move at all ->
    # non-boundary; boundary moves but none pop-feasible -> pop; else the
    # contiguity plane killed the survivors -> disconnect.
    if spec.proposal == "bi":
        any_cand = cand.any()
        any_pop = (cand & pop_ok).any()
    else:
        any_cand = cand.any()
        any_pop = pop_ok.any()
    reason = jnp.where(~any_cand, 0, jnp.where(~any_pop, 1, 2))
    rej3 = ((jnp.arange(3) == reason) & ~valid).astype(jnp.int32)
    return v, d_to, valid, tries, rej3


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def transition(dg: DeviceGraph, spec: Spec, params: StepParams,
               state: ChainState) -> ChainState:
    """One general_dense chain step: rejection-free propose, then the
    SHARED accept/commit tail (kernel/step.py:commit), then the O(P)
    incremental conn-plane refresh. Requires ``state.conn_bits`` (the
    runner enables it at entry, exactly the reject_count pattern)."""
    if state.conn_bits is None:
        raise ValueError("general_dense transition needs state.conn_bits; "
                         "enable it with kernel.dense.ensure_conn_bits "
                         "(runners do this on entry)")
    key, kprop, kacc, kwait = jax.random.split(state.key, 4)
    count = state.reject_count is not None
    if count:
        v, d_to, valid, tries, rej3 = propose_dense(
            dg, spec, params, state, kprop, count=True)
    else:
        v, d_to, valid, tries = propose_dense(dg, spec, params, state, kprop)
        rej3 = None
    new = kstep.commit(dg, spec, params, state, key, kacc, kwait,
                       v, d_to, valid, tries, rej3)
    return new.replace(conn_bits=refresh_conn_bits(
        dg, spec, new.assignment, state.conn_bits, v))


def ensure_conn_bits(dg: DeviceGraph, spec: Spec, states: ChainState
                     ) -> ChainState:
    """Batch entry hook: attach the packed conn plane to a batch of
    chain states (leading chains axis) if absent. Treedef changes from
    None -> array, so callers jit AFTER this, never across it."""
    if states.conn_bits is not None:
        return states
    init = jax.jit(jax.vmap(lambda a: init_conn_bits(dg, spec, a)))
    return states.replace(conn_bits=init(states.assignment))


def strip_conn_bits(states: ChainState) -> ChainState:
    """Exit hook / degradation edge: drop the carried conn plane so the
    escaping treedef matches the legacy contract."""
    if states.conn_bits is None:
        return states
    return states.replace(conn_bits=None)
