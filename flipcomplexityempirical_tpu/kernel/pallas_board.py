"""Pallas TPU kernel for the board flip chain: chain-blocked, VMEM-resident.

The XLA board kernel (kernel/board.py) streams every (C, N) plane through
HBM once per step — ~0.6 ms/step at C=4096, bandwidth/ALU bound. This
kernel removes the HBM round-trips: a block of ``block_chains`` chains
stays resident in VMEM for a whole ``t_inner``-step chunk, so per-chunk
HBM traffic is one board read + accumulator/log writes instead of
per-step plane materialization.

Design (per grid step = one chain block):

- the board lives in the output ref (VMEM) and is updated in place across
  ``t_inner`` sequential steps of a ``fori_loop``;
- neighbor planes are ``pltpu.roll`` lane rotations of the flat (BC, N)
  board with static existence masks (roll wrap-arounds land exactly on
  masked-off positions — asserted against the XLA planes in tests);
- proposal selection is "argmax of random bits masked to the valid set":
  iid uint32 draws make the argmax uniform over valid nodes, which equals
  re-propose-until-valid exactly (kernel/board.py module docstring); one
  random plane + one row argmax replaces the two-level prefix selection;
- per-chain gathers (district / degree / diff-degree at the selected
  node) become ONE masked reduction of a packed code plane
  (board*64 + deg*8 + diff_deg);
- cut_times accumulates into int32 output refs (the runner folds them
  into the int32 state, as the XLA chunk runner does); every VMEM plane
  is int32 — this toolchain's Mosaic rejects sub-32-bit rotates and
  truncating vector stores, so i8/i32 conversion happens at the
  pallas_call boundary;
- the flip-bookkeeping log (pointer, sign) writes one (BC,) row per step;
  ``kernel.board.apply_flip_log`` replays it outside, unchanged.

RNG: ``pltpu.prng_random_bits`` seeded per (block, chunk). The interpret
path (CPU tests) has no TPU PRNG, so ``host_rng=True`` reads the same
bits from input refs instead — which also makes the whole chunk a
deterministic function of known bits, letting tests assert BIT-EXACT
equality against a pure-numpy simulator (test_pallas_board.py).

Semantics are the board kernel's (record yield t, then transition), same
quirk set as kernel/step.py; geometric waits use the literal ``n**k - 1``
denominator (grid_chain_sec11.py:147-148). Districts are 2 with the
reference's +1/-1 labels (sign = 1 - 2*district).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..graphs.lattice import LatticeGraph
from .board import (BoardGraph, BoardState, board_shape, recount_cuts,
                    supports as _board_supports)
from .step import Spec, StepParams


def supports(graph: LatticeGraph, spec: Spec, params: StepParams,
             n_chains: int, block_chains: int = 128) -> bool:
    """The pallas path serves the benchmark family: plain full rook
    grids (the hand-written kernel hardcodes the 4-neighbor stencil —
    lowered surgical graphs run the masked-plane body in board.py),
    reference +1/-1 labels, and a block-divisible batch."""
    lv = np.asarray(params.label_values)
    return (board_shape(graph) is not None
            and not spec.record_interface
            and _board_supports(graph, spec)
            and spec.n_districts == 2
            and spec.proposal == "bi"
            and spec.accept == "cut"
            and spec.anneal == "none"
            and lv.shape == (2,) and lv[0] == 1 and lv[1] == -1
            and n_chains % block_chains == 0)


def _masks(h: int, w: int):
    """Existence masks per ring direction, flat (N,). Roll wrap-arounds
    land only on masked positions (see module docstring)."""
    i = np.arange(h * w)
    x, y = i // w, i % w
    e = y < w - 1
    wk = y > 0
    s = x < h - 1
    n = x > 0
    return {
        "e": e, "w": wk, "s": s, "n": n,
        "se": s & e, "sw": s & wk, "ne": n & e, "nw": n & wk,
    }


def _u01(bits):
    """uint32 -> f32 uniform in (0, 1): 24-bit mantissa, never 0.

    The top 24 bits fit int32 exactly (sign bit clear), and Mosaic has
    no u32->f32 cast or u32->i32 convert, so the float conversion
    bitcasts to int32 first.
    """
    shifted = jnp.right_shift(bits, jnp.uint32(8))
    return (pltpu.bitcast(shifted, jnp.int32).astype(jnp.float32)
            + 1.0) * jnp.float32(1.0 / 16777218.0)


def _kernel(spec: Spec, h: int, w: int, t_inner: int, host_rng: bool,
            # refs (order mirrors pallas_call wiring below)
            seed_ref,
            board_in, pop_ref, deg_ref, mask_refs,
            dist_pop_in, scal_in, ints_in,
            bits_plane_ref, bits_scal_ref,
            # outputs
            board_out, dist_pop_out, scal_out, ints_out,
            log_f_ref, log_s_ref,
            hist_cut_ref, hist_b_ref, hist_wait_ref, hist_acc_ref,
            cut_e_acc_ref, cut_s_acc_ref):
    n = h * w
    bc = board_in.shape[0]
    f32 = jnp.float32

    if not host_rng:
        pltpu.prng_seed(seed_ref[pl.program_id(0)])

    # every plane is int32 in VMEM: Mosaic (this toolchain) rejects
    # sub-32-bit rotates, truncating stores, and u32 argmax/casts; the
    # runner-side i8/i32 conversions happen outside the kernel.
    # Per-chain quantities are explicit (BC, 1) COLUMNS, never 1-D
    # vectors: Mosaic's layout pass crashed (layout.h:320, implicit-dim
    # rank check) when the PRNG-score-derived accept mask flowed through
    # 1-D loop carries, and 2-D columns leave no implicit-dim layouts
    # anywhere in the carry chain (PROFILE.md round-5 bisection).
    board_out[:] = board_in[:]
    cut_e_acc_ref[:] = jnp.zeros_like(cut_e_acc_ref)
    cut_s_acc_ref[:] = jnp.zeros_like(cut_s_acc_ref)

    m_e = mask_refs[0][:]      # (1, N) int32 each
    m_w = mask_refs[1][:]
    m_s = mask_refs[2][:]
    m_n = mask_refs[3][:]
    m_se = mask_refs[4][:]
    m_sw = mask_refs[5][:]
    m_ne = mask_refs[6][:]
    m_nw = mask_refs[7][:]
    pop = pop_ref[:]           # (1, N) int32
    deg = deg_ref[:]           # (1, N) int32
    code_plane = deg * 8       # + board*64 + diff_deg, built per step

    # per-chain scalar params, (BC, 1) f32 columns (chains-major input)
    log_base = scal_in[:, 0:1]
    beta = scal_in[:, 1:2]
    pop_lo = scal_in[:, 2:3]
    pop_hi = scal_in[:, 3:4]
    denom = f32(float(n) ** 2 - 1.0)

    iota_n = jax.lax.broadcasted_iota(jnp.int32, (bc, n), 1)

    def step(t, carry):
        (dp0, dp1, cur_wait, pending, cur_flip, cur_sign, tyield,
         move_clock, acc_cnt, exh_cnt, waits_sum) = carry
        board = board_out[:]                    # (BC, N) int32
        b32 = board

        def rolled_same(shift, mask):
            # value[i] = board[i + shift]  (pltpu.roll needs shift >= 0);
            # rolls run on the i32 copy (no sub-32-bit rotate in Mosaic)
            # and the mask applies as boolean AND (a where() with a bool
            # scalar branch lowers to an unsupported i8->i1 truncation)
            return (mask != 0) & (pltpu.roll(b32, (-shift) % n, 1) == b32)

        s_e = rolled_same(1, m_e)
        s_w = rolled_same(-1, m_w)
        s_s = rolled_same(w, m_s)
        s_n = rolled_same(-w, m_n)
        s_se = rolled_same(w + 1, m_se)
        s_sw = rolled_same(w - 1, m_sw)
        s_ne = rolled_same(-w + 1, m_ne)
        s_nw = rolled_same(-w - 1, m_nw)

        same_deg = (s_e.astype(jnp.int32) + s_w + s_s + s_n)
        diff_deg = deg - same_deg
        b_mask = diff_deg > 0
        cut_e = (m_e != 0) & ~s_e
        cut_s = (m_s != 0) & ~s_s

        if spec.contiguity == "patch":
            # ring criterion: rook runs not linked through their diagonal
            runs = ((s_e & ~(s_ne & s_n)).astype(jnp.int32)
                    + (s_s & ~(s_se & s_e))
                    + (s_w & ~(s_sw & s_s))
                    + (s_n & ~(s_nw & s_w)))
            contig = (same_deg <= 1) | (runs <= 1)
        else:
            contig = jnp.ones_like(b_mask)

        popn = pop.astype(f32)
        pop_of = jnp.where(board == 1, dp1, dp0)
        pop_to = jnp.where(board == 1, dp0, dp1)
        pop_ok = ((pop_of.astype(f32) - popn >= pop_lo)
                  & (pop_to.astype(f32) + popn <= pop_hi))
        valid = b_mask & contig & pop_ok

        b_count = b_mask.astype(jnp.int32).sum(axis=1, keepdims=True)
        cut_count = (cut_e.astype(jnp.int32).sum(axis=1, keepdims=True)
                     + cut_s.astype(jnp.int32).sum(axis=1, keepdims=True))

        # ---- complete the pending wait from this state's boundary count
        if host_rng:
            u_wait = _u01(bits_scal_ref[t][:, 0:1])
        else:
            u_wait = _u01(pltpu.bitcast(
                pltpu.prng_random_bits((bc, 1)), jnp.uint32))
        if spec.geom_waits:
            p = b_count.astype(f32) / denom
            wnew = jnp.maximum(
                jnp.floor(jnp.log(jnp.maximum(u_wait, f32(1e-12)))
                          / jnp.log1p(-p)), 0.0)
            cur_wait = jnp.where(pending != 0, wnew, cur_wait)

        # ---- record yield t ((BC, 1) columns -> (BC,) row stores on the
        # proven dynamic-sublane path)
        hist_cut_ref[t, :] = cut_count[:, 0]
        hist_b_ref[t, :] = b_count[:, 0]
        hist_wait_ref[t, :] = cur_wait[:, 0]
        hist_acc_ref[t, :] = acc_cnt[:, 0]
        log_f_ref[t, :] = cur_flip[:, 0]
        log_s_ref[t, :] = cur_sign[:, 0]
        cut_e_acc_ref[:] = cut_e_acc_ref[:] + cut_e.astype(jnp.int32)
        cut_s_acc_ref[:] = cut_s_acc_ref[:] + cut_s.astype(jnp.int32)
        waits_sum = waits_sum + cur_wait
        tyield = tyield + 1

        # ---- propose: argmax of random bits over the valid set
        if host_rng:
            sel_bits = bits_plane_ref[t]
        else:
            sel_bits = pltpu.bitcast(
                pltpu.prng_random_bits((bc, n)), jnp.uint32)
        score = jnp.where(valid, jnp.bitwise_or(sel_bits, jnp.uint32(1)),
                          jnp.uint32(0))
        # Mosaic has no uint32 argmax/max: XOR the sign bit to map uint32
        # order onto int32 order, then argmax = max + first-index-of-max
        # as two int32 reductions (same first-occurrence index).
        s32 = pltpu.bitcast(score ^ jnp.uint32(0x80000000), jnp.int32)
        smax = jnp.max(s32, axis=1, keepdims=True)
        idx = jnp.min(jnp.where(s32 == smax, iota_n, n),
                      axis=1, keepdims=True).astype(jnp.int32)
        any_valid = smax > jnp.int32(-(2 ** 31))

        sel = iota_n == idx
        codes = code_plane + b32 * 64 + diff_deg
        code_at = jnp.where(sel, codes, 0).sum(axis=1, keepdims=True)
        pop_at = jnp.where(sel, pop, 0).sum(axis=1, keepdims=True)
        d_from = code_at // 64
        deg_at = (code_at // 8) % 8
        dd_at = code_at % 8
        dcut = deg_at - 2 * dd_at

        if host_rng:
            u_acc = _u01(bits_scal_ref[t][:, 1:2])
        else:
            u_acc = _u01(pltpu.bitcast(
                pltpu.prng_random_bits((bc, 1)), jnp.uint32))
        log_bound = (-beta * dcut.astype(f32) * log_base)
        logu = jnp.log(jnp.maximum(u_acc, f32(1e-12)))
        accept = any_valid & (logu < log_bound)

        # ---- commit
        d_to = 1 - d_from
        board_out[:] = jnp.where(
            sel & accept, d_to.astype(board.dtype), board)
        popv = jnp.where(accept, pop_at, 0)
        dp0 = dp0 + jnp.where(d_from == 0, -popv, popv)
        dp1 = dp1 + jnp.where(d_from == 0, popv, -popv)
        cur_flip = jnp.where(accept, idx, cur_flip)
        cur_sign = jnp.where(accept, 1 - 2 * d_to, cur_sign)
        pending = accept.astype(jnp.int32)
        move_clock = move_clock + accept.astype(jnp.int32)
        acc_cnt = acc_cnt + accept.astype(jnp.int32)
        exh_cnt = exh_cnt + (~any_valid).astype(jnp.int32)
        return (dp0, dp1, cur_wait, pending, cur_flip, cur_sign, tyield,
                move_clock, acc_cnt, exh_cnt, waits_sum)

    init = (dist_pop_in[:, 0:1], dist_pop_in[:, 1:2], scal_in[:, 4:5],
            ints_in[:, 0:1], ints_in[:, 1:2], ints_in[:, 2:3],
            ints_in[:, 3:4], ints_in[:, 4:5], ints_in[:, 5:6],
            ints_in[:, 6:7],
            jnp.zeros_like(scal_in[:, 4:5]))
    out = jax.lax.fori_loop(0, t_inner, step, init)
    (dp0, dp1, cur_wait, pending, cur_flip, cur_sign, tyield,
     move_clock, acc_cnt, exh_cnt, waits_sum) = out
    dist_pop_out[:, 0:1] = dp0
    dist_pop_out[:, 1:2] = dp1
    scal_out[:, 0:1] = cur_wait
    scal_out[:, 1:2] = waits_sum
    ints_out[:, 0:1] = pending
    ints_out[:, 1:2] = cur_flip
    ints_out[:, 2:3] = cur_sign
    ints_out[:, 3:4] = tyield
    ints_out[:, 4:5] = move_clock
    ints_out[:, 5:6] = acc_cnt
    ints_out[:, 6:7] = exh_cnt


@functools.partial(
    jax.jit,
    static_argnames=("spec", "h", "w", "t_inner", "block_chains",
                     "host_rng", "interpret"))
def run_pallas_chunk(spec: Spec, h: int, w: int, t_inner: int,
                     block_chains: int,
                     seeds, board, pop_plane, deg_plane, masks8,
                     dist_pop, scal_in, ints_in, bits_plane, bits_scal,
                     host_rng: bool = False, interpret: bool = False):
    """One chunk: t_inner yields + transitions for all chains, blocked
    over ``block_chains``-sized groups. Returns the kernel outputs; the
    runner stitches them back into a BoardState."""
    c, n = board.shape
    bc = block_chains
    nb = c // bc
    grid = (nb,)

    def cdim(shape):  # block over the chains axis (axis 0)
        return pl.BlockSpec((bc, *shape[1:]),
                            lambda b: (b, *([0] * (len(shape) - 1))))

    def rep(shape):   # replicated across blocks
        return pl.BlockSpec(shape, lambda b: tuple([0] * len(shape)))

    def tdim(shape):  # (T, ...) outputs, chains as the minor axis
        return pl.BlockSpec(shape[:1] + (bc, *shape[2:]),
                            lambda b: (0, b, *([0] * (len(shape) - 2))))

    in_specs = [
        # whole seeds vector in SMEM for every block (TPU rank-1 blocks
        # must cover the array); the kernel indexes it by program_id
        pl.BlockSpec((nb,), lambda b: (0,), memory_space=pltpu.SMEM),
        cdim(board.shape),                       # board
        rep(pop_plane.shape),                    # pop (1, N)
        rep(deg_plane.shape),                    # deg (1, N)
        *[rep(m.shape) for m in masks8],         # 8 masks (1, N)
        # per-chain packed state is chains-major (C, k): the kernel reads
        # (BC, 1) columns with no relayout (2-D-columns rule, see _kernel)
        pl.BlockSpec((bc, 2), lambda b: (b, 0)),  # dist_pop (C, 2)
        pl.BlockSpec((bc, 5), lambda b: (b, 0)),  # f32 scalars (C, 5)
        pl.BlockSpec((bc, 7), lambda b: (b, 0)),  # i32 counters (C, 7)
        (tdim(bits_plane.shape) if host_rng
         else rep((1, 1))),                      # bits plane (T, C, N)
        (pl.BlockSpec((t_inner, bc, 2), lambda b: (0, b, 0)) if host_rng
         else rep((1, 1))),                      # bits scal (T, C, 2)
    ]
    out_shape = (
        jax.ShapeDtypeStruct((c, n), jnp.int32),         # board
        jax.ShapeDtypeStruct((c, 2), jnp.int32),         # dist_pop
        jax.ShapeDtypeStruct((c, 2), jnp.float32),       # scalars out
        jax.ShapeDtypeStruct((c, 7), jnp.int32),         # counters out
        jax.ShapeDtypeStruct((t_inner, c), jnp.int32),   # log_f
        jax.ShapeDtypeStruct((t_inner, c), jnp.int32),   # log_s
        jax.ShapeDtypeStruct((t_inner, c), jnp.int32),   # hist cut
        jax.ShapeDtypeStruct((t_inner, c), jnp.int32),   # hist b
        jax.ShapeDtypeStruct((t_inner, c), jnp.float32),  # hist wait
        jax.ShapeDtypeStruct((t_inner, c), jnp.int32),   # hist accepts
        jax.ShapeDtypeStruct((c, n), jnp.int32),         # cut_e acc
        jax.ShapeDtypeStruct((c, n), jnp.int32),         # cut_s acc
    )
    out_specs = (
        cdim((c, n)),
        pl.BlockSpec((bc, 2), lambda b: (b, 0)),
        pl.BlockSpec((bc, 2), lambda b: (b, 0)),
        pl.BlockSpec((bc, 7), lambda b: (b, 0)),
        tdim((t_inner, c)),
        tdim((t_inner, c)),
        tdim((t_inner, c)),
        tdim((t_inner, c)),
        tdim((t_inner, c)),
        tdim((t_inner, c)),
        cdim((c, n)),
        cdim((c, n)),
    )

    # external contract stays (k, C) / (T, 2, C); chains-major is an
    # XLA-level transpose on the way in and out of the kernel
    dist_pop = dist_pop.T
    scal_in = scal_in.T
    ints_in = ints_in.T
    if host_rng:
        bits_scal = bits_scal.transpose(0, 2, 1)
    else:
        bits_plane = jnp.zeros((1, 1), jnp.uint32)
        bits_scal = jnp.zeros((1, 1), jnp.uint32)

    def kern(seed_ref, board_in, pop_ref, deg_ref,
             m0, m1, m2, m3, m4, m5, m6, m7,
             dist_pop_in, scal_in_ref, ints_in_ref, bp_ref, bs_ref, *outs):
        _kernel(spec, h, w, t_inner, host_rng,
                seed_ref, board_in, pop_ref, deg_ref,
                (m0, m1, m2, m3, m4, m5, m6, m7),
                dist_pop_in, scal_in_ref, ints_in_ref, bp_ref, bs_ref,
                *outs)

    # the benchmark shape's scoped stack peaks at 16.47M (compiler error
    # table, PROFILE.md), just over Mosaic's 16M default budget — and
    # shrinking the chunk pipelines WORSE (25.45M at chunk=250), so the
    # fix is an explicit budget: 2x the measured peak as headroom for
    # chunk/shape tuning, still a quarter of the chip's 128M VMEM.
    # Timed on-chip at this value (bench_runs/tpu_pallas_timing.json).
    kwargs = {}
    if not interpret:
        params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams")
        kwargs["compiler_params"] = params_cls(
            vmem_limit_bytes=32 * 1024 * 1024)
    outs = pl.pallas_call(
        kern, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=interpret, **kwargs,
    )(seeds, board.astype(jnp.int32), pop_plane, deg_plane, *masks8,
      dist_pop, scal_in, ints_in, bits_plane, bits_scal)
    # back to the BoardState dtype and the (k, C) packing outside the kernel
    return ((outs[0].astype(jnp.int8), outs[1].T, outs[2].T, outs[3].T)
            + tuple(outs[4:]))


def make_static_inputs(bg: BoardGraph):
    h, w = bg.h, bg.w
    masks = _masks(h, w)
    order = ("e", "w", "s", "n", "se", "sw", "ne", "nw")
    masks8 = tuple(jnp.asarray(masks[k][None, :], jnp.int32) for k in order)
    pop_plane = jnp.asarray(np.asarray(bg.pop)[None, :], jnp.int32)
    deg_plane = jnp.asarray(np.asarray(bg.deg)[None, :], jnp.int32)
    return pop_plane, deg_plane, masks8


def pack_state(state: BoardState, params: StepParams):
    """BoardState + params -> (dist_pop (2,C) i32, scalars (5,C) f32,
    counters (7,C) i32)."""
    dist_pop = jnp.stack([state.dist_pop[:, 0], state.dist_pop[:, 1]])
    f32 = jnp.float32
    scal = jnp.stack([
        params.log_base.astype(f32), params.beta.astype(f32),
        params.pop_lo.astype(f32), params.pop_hi.astype(f32),
        state.cur_wait.astype(f32),
    ])
    i32 = jnp.int32
    ints = jnp.stack([
        state.wait_pending.astype(i32),
        state.cur_flip.astype(i32),
        state.cur_sign.astype(i32),
        state.t_yield.astype(i32),
        state.move_clock.astype(i32),
        state.accept_count.astype(i32),
        state.exhausted_count.astype(i32),
    ])
    return dist_pop, scal, ints


def unpack_state(state: BoardState, bg, outs, t_inner: int) -> BoardState:
    """Merge kernel outputs back into a BoardState (tries_sum counts one
    draw per yield, as the board path does)."""
    (board, dist_pop, scal, ints, log_f, log_s, h_cut, h_b, h_wait, h_acc,
     cut_e_acc, cut_s_acc) = outs
    return state.replace(
        board=board,
        dist_pop=jnp.stack([dist_pop[0], dist_pop[1]], axis=1),
        # the board loop CARRIES cut_count (current board's count), while
        # h_cut[-1] is the last record's pre-transition value — recount
        cut_count=recount_cuts(bg, board),
        cur_wait=scal[0],
        wait_pending=ints[0] > 0,
        cur_flip=ints[1],
        cur_sign=ints[2],
        t_yield=ints[3],
        move_clock=ints[4],
        accept_count=ints[5],
        exhausted_count=ints[6],
        waits_sum=state.waits_sum + scal[1],
        tries_sum=state.tries_sum + t_inner,
        cut_times_e=state.cut_times_e + cut_e_acc,
        cut_times_s=state.cut_times_s + cut_s_acc,
    )


def check(spec: Spec, params: StepParams, n_chains: int,
          block_chains: int) -> None:
    """Raise unless this kernel reproduces the requested semantics —
    the Pallas path hardcodes the cut-Metropolis acceptance and the
    reference +1/-1 labels, a strict subset of board.supports()."""
    if spec.n_districts != 2 or spec.proposal != "bi":
        raise ValueError("pallas path requires the 2-district 'bi' "
                         f"proposal, got k={spec.n_districts} "
                         f"proposal={spec.proposal!r}")
    if spec.accept != "cut":
        raise ValueError(f"pallas path requires accept='cut', "
                         f"got {spec.accept!r}")
    if spec.anneal != "none":
        raise ValueError(f"pallas path requires anneal='none', "
                         f"got {spec.anneal!r}")
    lv = np.asarray(params.label_values)
    if lv.shape != (2,) or lv[0] != 1 or lv[1] != -1:
        raise ValueError(f"pallas path requires label_values [1, -1], "
                         f"got {lv.tolist()}")
    if n_chains % block_chains:
        raise ValueError(f"n_chains {n_chains} must be a multiple of "
                         f"block_chains {block_chains}")
