"""Fused Pallas TPU chain kernel: whole step-loop on-chip, state in VMEM.

Why this exists: under plain XLA, every per-chain dynamic index (scatter,
gather, one-hot select) on the (C, N) state lowers to a full HBM pass, and
the scan carry is re-materialized in HBM every step — measured ~8 ms per
step at C=4096, N=4096 regardless of arithmetic. The flip chain is
latency/bandwidth-bound, not FLOP-bound, so the fix is architectural: move
the entire T-step loop into one Pallas kernel whose grid blocks keep their
chains' state resident in VMEM, cutting HBM traffic from O(state x T) to
O(state + logs) per chunk.

TPU-native redesign of the step itself (square-grid, 2-district — the
BASELINE.json north-star workload):

- neighbors via static lane shifts of the flattened (chains, nx*ny) board
  (no gathers): cut masks, incident-cut counts, and flip deltas are dense
  elementwise stencils (VPU-cheap);
- single-flip contiguity is the Moore-ring arc criterion evaluated DENSELY
  for every node at once: the <=4 edge-neighbors of v form the nodes of a
  4-cycle whose links are the diagonal cells; the flip keeps the origin
  district connected iff (#present-neighbors - #active-links) <= 1. On a
  plain square lattice this equals the radius-2 patch criterion of
  kernel/contiguity.py (tests assert equivalence against the exact BFS);
- the re-propose-until-valid semantics of the reference chain collapses to
  ONE draw: uniform over boundary nodes retried until valid == uniform
  over the VALID boundary set, which the dense validity mask materializes
  directly — masked argmax over per-node random uniforms samples it in a
  single reduction, no while_loop;
- cross-lane REDUCTIONS are the on-chip cost unit (~20-40 us each vs ~ns
  elementwise), so the step uses exactly three: (1) argmax of the masked
  random scores -> v; (2) a packed-payload max that reads validity and
  dcut at v without a gather; (3) the new |b_nodes| for the geometric-wait
  sample. Everything else is elementwise or per-chain scalar rows.

Reference bookkeeping strategy: cut_times accumulates in VMEM as two
(C, N) edge panels (elementwise adds); the per-node parity metrics
(part_sum / last_flipped / num_flips, whose reference semantics re-apply
the LAST flip on every self-loop yield, grid_chain_sec11.py:396-400) are
NOT touched per step — the kernel emits a signed flip log
(+-(v+1) on accept, 0 on reject) and sampling/fused_runner.py replays the
log into the accumulators once per chunk, exactly.

Edge panels: a plain nx x ny grid's edges split into the 'vert' family
((x,y)-(x,y+1), slot u = x*ny+y, y < ny-1) and the 'horiz' family
((x,y)-(x+1,y), slot u, x < nx-1); fold_cut_panels maps them back to the
canonical LatticeGraph edge order.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rand_bits_i32(shape):
    """Random bits as int32 (Mosaic has no uint32->f32 cast path)."""
    return pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.int32)


def _u01(bits_i32):
    """int32 random bits -> float32 uniform in [0, 1) (23 mantissa bits)."""
    m = jax.lax.shift_right_logical(bits_i32, 9)
    return m.astype(jnp.float32) * jnp.float32(2 ** -23)


def _shift(x, s: int):
    """Shift lanes left by s (element u reads u+s), zero fill."""
    if s == 0:
        return x
    z = jnp.zeros_like(x)
    if s > 0:
        return jnp.concatenate([x[:, s:], z[:, :s]], axis=1)
    return jnp.concatenate([z[:, s:], x[:, :s]], axis=1)


def _grid_kernel(nx: int, ny: int, n_steps: int, log_base: float,
                 pop_lo: float, pop_hi: float, record: bool,
                 seed_ref, a_ref, ctv_ref, cth_ref, sc_i_ref, sc_f_ref,
                 flip_ref, *hist_refs):
    n = nx * ny
    bc = a_ref.shape[0]
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))

    idx = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    y = idx % ny
    x = idx // ny
    has_n = y < ny - 1
    has_s = y > 0
    has_e = x < nx - 1
    has_w = x > 0
    deg = (has_n.astype(jnp.int32) + has_s.astype(jnp.int32)
           + has_e.astype(jnp.int32) + has_w.astype(jnp.int32))
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (1, n_steps), 1)

    a0 = a_ref[:].astype(jnp.int32)
    # per-chain scalar rows (BC, 1)
    cut_count = sc_i_ref[:, 0:1]
    accept_count = sc_i_ref[:, 1:2]
    move_clock = sc_i_ref[:, 2:3]
    t_yield = sc_i_ref[:, 3:4]
    pop0_init = jnp.sum((a0 == 0).astype(jnp.int32), axis=1, keepdims=True)

    ctv_acc = ctv_ref[:]
    cth_acc = cth_ref[:]
    flip_log0 = jnp.zeros((bc, n_steps), jnp.int32)
    if record:
        cc_h0 = jnp.zeros((bc, n_steps), jnp.int32)
        bc_h0 = jnp.zeros((bc, n_steps), jnp.int32)
        w_h0 = jnp.zeros((bc, n_steps), jnp.float32)
    else:
        cc_h0 = bc_h0 = jnp.zeros((1, 1), jnp.int32)
        w_h0 = jnp.zeros((1, 1), jnp.float32)

    def body(t, carry):
        (a, pop0, cut_count, accept_count, move_clock, t_yield,
         cur_wait, waits_sum, ctv_acc, cth_acc, flip_log,
         cc_h, bc_h, w_h) = carry

        # --- dense stencils (elementwise; VPU-cheap) -------------------
        cut_v = (a != _shift(a, 1)) & has_n
        cut_h = (a != _shift(a, ny)) & has_e
        cut_deg = (cut_v.astype(jnp.int32) + cut_h.astype(jnp.int32)
                   + _shift(cut_v.astype(jnp.int32), -1)
                   + _shift(cut_h.astype(jnp.int32), -ny))
        b_mask = cut_deg > 0

        s_n = (~cut_v) & has_n
        s_e = (~cut_h) & has_e
        s_s = (_shift(s_n.astype(jnp.int32), -1) > 0) & has_s
        s_w = (_shift(s_e.astype(jnp.int32), -ny) > 0) & has_w
        l_ne = (a == _shift(a, ny + 1))
        l_se = (a == _shift(a, ny - 1))
        l_nw = (a == _shift(a, -ny + 1))
        l_sw = (a == _shift(a, -ny - 1))
        present = (s_n.astype(jnp.int32) + s_e.astype(jnp.int32)
                   + s_s.astype(jnp.int32) + s_w.astype(jnp.int32))
        links = ((s_n & s_e & l_ne).astype(jnp.int32)
                 + (s_e & s_s & l_se).astype(jnp.int32)
                 + (s_s & s_w & l_sw).astype(jnp.int32)
                 + (s_w & s_n & l_nw).astype(jnp.int32))
        contig_ok = (present - links) <= 1

        pop1 = n - pop0
        ok_from0 = ((pop0 - 1).astype(jnp.float32) >= pop_lo) \
            & ((pop1 + 1).astype(jnp.float32) <= pop_hi)
        ok_from1 = ((pop1 - 1).astype(jnp.float32) >= pop_lo) \
            & ((pop0 + 1).astype(jnp.float32) <= pop_hi)
        is0 = a == 0
        pop_ok = (is0 & ok_from0) | (~is0 & ok_from1)

        valid = b_mask & contig_ok & pop_ok

        # --- reduction 1: sample v uniform over the valid set ----------
        bits = _rand_bits_i32((bc, n))
        score = jnp.where(valid, _u01(bits), jnp.float32(-1.0))
        v = jnp.argmax(score, axis=1).astype(jnp.int32)[:, None]
        onehot = idx == v

        # --- reduction 2: packed payload at v ((dcut+8)*2 + is0, so one
        # max also yields validity, the flip delta, and the origin side
        # without any gather) ------------------------------------------
        dcut_map = deg - 2 * cut_deg                 # in [-4, 4]
        payload = jnp.where(
            valid,
            ((dcut_map + 8) * 2 + is0.astype(jnp.int32)).astype(
                jnp.float32),
            jnp.float32(0.0))
        pv = jnp.max(jnp.where(onehot, payload, jnp.float32(-1.0)),
                     axis=1, keepdims=True)
        chose_valid = pv > 0.5                       # S nonempty
        ipv = pv.astype(jnp.int32)
        from0 = ipv % 2                              # v was district 0
        dcut_v = ipv // 2 - 8

        u2 = _rand_bits_i32((bc, 2))
        logu = jnp.log(jnp.maximum(_u01(u2[:, 0:1]), jnp.float32(1e-12)))
        accept = chose_valid & (logu < -dcut_v.astype(jnp.float32)
                                * jnp.float32(log_base))

        # --- commit (elementwise) --------------------------------------
        a = jnp.where(onehot & accept, 1 - a, a)
        acc_i = accept.astype(jnp.int32)
        pop0 = pop0 + acc_i * (1 - 2 * from0)
        cut_count = cut_count + jnp.where(accept, dcut_v, 0)
        accept_count = accept_count + acc_i
        move_clock = move_clock + acc_i

        # --- reduction 3: new |b_nodes| for the wait sample ------------
        cut_v2 = (a != _shift(a, 1)) & has_n
        cut_h2 = (a != _shift(a, ny)) & has_e
        cut_deg2 = (cut_v2.astype(jnp.int32) + cut_h2.astype(jnp.int32)
                    + _shift(cut_v2.astype(jnp.int32), -1)
                    + _shift(cut_h2.astype(jnp.int32), -ny))
        b_new = jnp.sum((cut_deg2 > 0).astype(jnp.int32), axis=1,
                        keepdims=True)

        p = b_new.astype(jnp.float32) / jnp.float32(float(n) ** 2 - 1.0)
        uw = jnp.maximum(_u01(u2[:, 1:2]), jnp.float32(1e-12))
        w_new = jnp.maximum(jnp.floor(jnp.log(uw) / jnp.log1p(-p)), 0.0)
        cur_wait = jnp.where(accept, w_new, cur_wait)

        # --- record one yield ------------------------------------------
        ctv_acc = ctv_acc + cut_v2.astype(jnp.int32)
        cth_acc = cth_acc + cut_h2.astype(jnp.int32)
        waits_sum = waits_sum + cur_wait

        col = iota_t == t
        # signed flip log: sign = post-flip label of v (district 0 -> +1,
        # district 1 -> -1); v flipped FROM 0 means it is now district 1
        sign_new = 1 - 2 * from0
        logval = jnp.where(accept, sign_new * (v + 1), 0)
        flip_log = flip_log + jnp.where(col, logval, 0)

        if record:
            cc_h = cc_h + jnp.where(col, cut_count, 0)
            bc_h = bc_h + jnp.where(col, b_new, 0)
            w_h = w_h + jnp.where(col, cur_wait, 0.0)

        t_yield = t_yield + 1
        return (a, pop0, cut_count, accept_count, move_clock, t_yield,
                cur_wait, waits_sum, ctv_acc, cth_acc, flip_log,
                cc_h, bc_h, w_h)

    carry = (a0, pop0_init, cut_count, accept_count, move_clock, t_yield,
             sc_f_ref[:, 0:1], sc_f_ref[:, 1:2], ctv_acc, cth_acc,
             flip_log0, cc_h0, bc_h0, w_h0)
    carry = jax.lax.fori_loop(0, n_steps, body, carry)
    (a, pop0, cut_count, accept_count, move_clock, t_yield, cur_wait,
     waits_sum, ctv_acc, cth_acc, flip_log, cc_h, bc_h, w_h) = carry

    a_ref[:] = a.astype(jnp.int8)
    ctv_ref[:] = ctv_acc
    cth_ref[:] = cth_acc
    sc_i_ref[:, 0:1] = cut_count
    sc_i_ref[:, 1:2] = accept_count
    sc_i_ref[:, 2:3] = move_clock
    sc_i_ref[:, 3:4] = t_yield
    sc_f_ref[:, 0:1] = cur_wait
    sc_f_ref[:, 1:2] = waits_sum
    flip_ref[:] = flip_log
    if record:
        cc_r, bc_r, w_r = hist_refs
        cc_r[:] = cc_h
        bc_r[:] = bc_h
        w_r[:] = w_h


@functools.partial(jax.jit, static_argnames=(
    "nx", "ny", "n_steps", "log_base", "pop_lo", "pop_hi", "record",
    "block_chains"))
def fused_grid_chunk(seed, assignment, ct_v, ct_h, scal_i, scal_f, *, nx,
                     ny, n_steps, log_base, pop_lo, pop_hi, record,
                     block_chains=256):
    """Run n_steps yields for all chains, fully fused on-chip.

    State (chains-major): assignment i8 (C, N); ct_v/ct_h i32 (C, N)
    cut_times panels; scal_i i32 (C, 8) = [cut_count, accept_count,
    move_clock, t_yield, pad...]; scal_f f32 (C, 8) = [cur_wait,
    waits_sum, pad...]. Returns updated state + flip log (C, n_steps)
    (+histories of cut_count / b_count / wait when record=True)."""
    c, n = assignment.shape
    assert n == nx * ny
    # lane-dim alignment: blocks whose minor dim is not a multiple of
    # 128 force full-array VMEM materialization in Mosaic
    assert n_steps % 128 == 0, "chunk length must be a multiple of 128"
    assert scal_i.shape[1] == 128 and scal_f.shape[1] == 128
    bc = min(block_chains, c)
    assert c % bc == 0
    grid = (c // bc,)

    def row_block(cols):
        return pl.BlockSpec((bc, cols), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    kernel = functools.partial(_grid_kernel, nx, ny, n_steps,
                               float(log_base), float(pop_lo),
                               float(pop_hi), record)

    out_shape = [
        jax.ShapeDtypeStruct((c, n), jnp.int8),
        jax.ShapeDtypeStruct((c, n), jnp.int32),
        jax.ShapeDtypeStruct((c, n), jnp.int32),
        jax.ShapeDtypeStruct((c, 128), jnp.int32),
        jax.ShapeDtypeStruct((c, 128), jnp.float32),
        jax.ShapeDtypeStruct((c, n_steps), jnp.int32),   # flip log
    ]
    out_specs = [row_block(n), row_block(n), row_block(n),
                 row_block(128), row_block(128), row_block(n_steps)]
    if record:
        out_shape += [jax.ShapeDtypeStruct((c, n_steps), jnp.int32),
                      jax.ShapeDtypeStruct((c, n_steps), jnp.int32),
                      jax.ShapeDtypeStruct((c, n_steps), jnp.float32)]
        out_specs += [row_block(n_steps)] * 3

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        row_block(n), row_block(n), row_block(n), row_block(128),
        row_block(128),
    ]
    def wrapped(seed_ref, a_in, ctv_in, cth_in, si_in, sf_in,
                a_o, ctv_o, cth_o, si_o, sf_o, flip_o, *hist):
        # copy block in -> out, then run in-place on the output block
        # (no input_output_aliases: aliasing pins the whole result tuple
        # into VMEM in this Mosaic version, OOMing at C=4096)
        a_o[:] = a_in[:]
        ctv_o[:] = ctv_in[:]
        cth_o[:] = cth_in[:]
        si_o[:] = si_in[:]
        sf_o[:] = sf_in[:]
        kernel(seed_ref, a_o, ctv_o, cth_o, si_o, sf_o, flip_o, *hist)

    return pl.pallas_call(
        wrapped,
        grid=grid,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=out_specs,
    )(jnp.reshape(jnp.asarray(seed, jnp.int32), (1,)), assignment, ct_v,
      ct_h, scal_i, scal_f)


def fold_cut_panels(nx: int, ny: int, ct_v: np.ndarray, ct_h: np.ndarray,
                    graph) -> np.ndarray:
    """Fold the (C, N) vert/horiz cut_times panels into the canonical
    (C, E) edge order of ``graph`` (a plain square_grid LatticeGraph)."""
    c = ct_v.shape[0]
    out = np.zeros((c, graph.n_edges), dtype=np.int64)
    for ei in range(graph.n_edges):
        ia, ib = int(graph.edges[ei, 0]), int(graph.edges[ei, 1])
        (xa, ya), (xb, yb) = graph.labels[ia], graph.labels[ib]
        if xa == xb:
            out[:, ei] = ct_v[:, xa * ny + min(ya, yb)]
        else:
            out[:, ei] = ct_h[:, min(xa, xb) * ny + ya]
    return out


def replay_parity(flip_log: np.ndarray, t_start: np.ndarray,
                  part_sum: np.ndarray, last_flipped: np.ndarray,
                  num_flips: np.ndarray, cur_flip: np.ndarray,
                  cur_sign: np.ndarray):
    """Replay the signed flip log into the reference parity accumulators.

    Reference record semantics (grid_chain_sec11.py:396-400, re-applied on
    EVERY yield via the memoized part.flips): at yield t with flip cursor
    f and post-flip sign s: part_sum[f] -= s * (t - last_flipped[f]);
    last_flipped[f] = t; num_flips[f] += 1.

    Arguments are mutated in place. ``flip_log`` is (C, T) signed
    (+-(slot+1), 0 = rejected yield); ``cur_flip``/``cur_sign`` carry the
    cursor across chunks ((C,) arrays, slot index or -1). ``t_start`` (C,)
    is the absolute yield index of flip_log[:, 0].
    """
    c, t_len = flip_log.shape
    rows = np.arange(c)
    for t in range(t_len):
        ev = flip_log[:, t]
        newf = ev != 0
        cur_flip[newf] = np.abs(ev[newf]) - 1
        cur_sign[newf] = np.sign(ev[newf])
        has = cur_flip >= 0
        f = np.where(has, cur_flip, 0)
        t_abs = t_start + t
        dt = t_abs - last_flipped[rows, f]
        upd = np.where(has, -cur_sign * dt, 0)
        part_sum[rows, f] += upd
        last_flipped[rows, f] = np.where(has, t_abs,
                                         last_flipped[rows, f])
        num_flips[rows, f] += has.astype(np.int64)
