"""Vectorized single-flip contiguity checks.

The data-dependent graph search of gerrychain's ``single_flip_contiguous``
(the dominant per-step cost of the reference chain, SURVEY.md section 3.2)
becomes one of two TPU-friendly forms:

- ``patch_connected``: O(P) bitset label propagation inside the flipped
  node's precomputed radius-r ball (r=2, or 3 for hex faces; P <= 32,
  uint32 words; see
  graphs/lattice.py). Sufficient always; exact iff the origin district is
  simply connected — the common case on these lattices, validated
  empirically against the exact check in tests.
- ``exact_connected``: masked frontier expansion over the whole graph
  (lax.while_loop), gerrychain-equivalent on any graph, used as the oracle
  and for parity-grade runs.

Both return True when the flipped node has <= 1 same-district neighbor,
matching the oracle's vacuous-singleton semantics
(compat/chain.py::single_flip_contiguous).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graphs.lattice import DeviceGraph


def _or_reduce_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Bitwise-or reduction of a 1-D uint32 vector."""
    return jax.lax.reduce(x, jnp.uint32(0), jax.lax.bitwise_or, (0,))


def patch_connected(dg: DeviceGraph, assignment: jnp.ndarray,
                    v: jnp.ndarray, d_origin: jnp.ndarray) -> jnp.ndarray:
    """True iff v's d_origin neighbors stay mutually connected within the
    precomputed patch after removing v (=> the flip cannot disconnect the
    origin district)."""
    p = dg.max_patch
    pn = dg.patch_nodes[v]                      # i32[P], pad = v
    padj = dg.patch_adj[v]                      # u32[P]
    slots = jnp.arange(p, dtype=jnp.int32)
    member = (assignment[pn].astype(jnp.int32) == d_origin) & (pn != v)
    member_word = jnp.sum(
        jnp.where(member, jnp.uint32(1) << slots.astype(jnp.uint32), 0),
        dtype=jnp.uint32)
    # neighbors occupy the first deg slots of the patch (builder invariant)
    seed_mask = member & (slots < dg.deg[v])
    seed_word = jnp.sum(
        jnp.where(seed_mask, jnp.uint32(1) << slots.astype(jnp.uint32), 0),
        dtype=jnp.uint32)
    n_seeds = seed_mask.sum()

    start = seed_word & (~seed_word + jnp.uint32(1))  # lowest set bit

    def body(_, reach):
        sel = (reach >> slots.astype(jnp.uint32)) & jnp.uint32(1)
        contrib = jnp.where(sel.astype(bool), padj, jnp.uint32(0))
        return reach | (_or_reduce_u32(contrib) & member_word)

    reach = jax.lax.fori_loop(0, p, body, start)
    all_reached = (seed_word & ~reach) == 0
    return jnp.where(n_seeds <= 1, True, all_reached)


def exact_connected(dg: DeviceGraph, assignment: jnp.ndarray,
                    v: jnp.ndarray, d_origin: jnp.ndarray) -> jnp.ndarray:
    """gerrychain-exact check: BFS within the origin district minus v, from
    one of v's origin-district neighbors, until all of them are reached or
    the frontier dies."""
    n = dg.n_nodes
    a = assignment.astype(jnp.int32)
    nb = dg.nbr[v]                               # i32[D], pad = v
    seed_slots = (a[nb] == d_origin) & dg.nbr_mask[v]
    n_seeds = seed_slots.sum()

    targets = jnp.zeros(n, bool).at[nb].max(seed_slots)
    targets = targets.at[v].set(False)  # pad slots wrote to v
    district = (a == d_origin) & (jnp.arange(n) != v)

    start = nb[jnp.argmax(seed_slots)]
    visited0 = jnp.zeros(n, bool).at[start].set(True)

    def cond(carry):
        visited, changed = carry
        return changed & jnp.any(targets & ~visited)

    def body(carry):
        visited, _ = carry
        nbr_hit = (visited[dg.nbr] & dg.nbr_mask).any(axis=1)
        new = visited | (nbr_hit & district)
        return new, jnp.any(new != visited)

    visited, _ = jax.lax.while_loop(cond, body, (visited0, jnp.bool_(True)))
    all_reached = ~jnp.any(targets & ~visited)
    return jnp.where(n_seeds <= 1, True, all_reached)


def check(dg: DeviceGraph, assignment: jnp.ndarray, v: jnp.ndarray,
          d_origin: jnp.ndarray, mode: str) -> jnp.ndarray:
    if mode == "patch":
        return patch_connected(dg, assignment, v, d_origin)
    if mode == "exact":
        return exact_connected(dg, assignment, v, d_origin)
    if mode == "none":
        return jnp.bool_(True)
    raise ValueError(f"contiguity mode {mode!r}")
