"""Bit-board backend for the board kernel's hot loop.

The int8 board path (kernel/board.py) streams ~100 MB of (C, N) planes
per step: 8 stencil compares, ring criterion, validity, int16 cut_times
read-modify-write. At one byte per cell almost all of that traffic is
redundant — every plane is boolean. This backend packs the board and
every derived plane into uint32 words (32 cells per lane element), so
the same per-step dataflow touches ~1/8th the bytes:

- stencil neighbor reads are funnel shifts across the word array
  (``shift_down``/``shift_up``), with row-wrap and frame masks packed
  once per chunk (loop-invariant, hoisted by XLA);
- the ring contiguity criterion's two "count <= 1" tests become
  carry-save popcount logic (``_at_most_one``) — pure AND/OR/XOR;
- boundary and valid counts come from ``lax.population_count``;
- the two-level proposal selection reads per-row popcounts
  (words-per-row is static), and extracts the chosen row's cells by a
  one-hot masked sum — no dynamic gather anywhere;
- cut_times accumulates into ``ceil(log2(chunk+1))`` bit-sliced counter
  planes via ripple-carry adds (3 bitwise ops per slice on (C, NW)
  words), folded into the int32 totals once per chunk — replacing the
  ~100 MB/step int16 read-modify-write with ~1 MB/step of bitwise ops.

Semantics are IDENTICAL to the int8 path: the same PRNG stream drives
the same uniform draws, the selection picks the same m-th valid cell in
flat row-major order, and the acceptance formula is unchanged — so
trajectories are bit-identical (asserted by tests/test_bitboard.py).
``supported()`` gates the 2-district 'bi' body to the workloads where
the packing is clean and exact: uniform node population (the population
test collapses to one boolean per chain per side; true of every
reference config, grid_chain_sec11.py:221), W a multiple of 32 (rows
align to words), accept in ('cut', 'always') (the 'corrected'
boundary-ratio correction needs per-node degree counts the bit planes
don't keep), and no record_assignment_bits.

The k-district 'pair' walk (2 <= k <= 31) has its own bit body, gated
by ``supported_pair()`` under the same conditions: district ids live as
``ceil(log2(k))`` bit-sliced planes, neighbor equality is an OR of
per-plane XORs, the population gates become one per-cell plane per side
built in a single pass over the k districts, and selection runs over
the four per-direction pair planes in the int8 body's (node, direction)
order. Everything outside both gates silently uses the int8 bodies.

The LOWERED stencil family (surgical canvases — sec11, Frankengraph,
queen grids — and record_interface runs) has its own packed body at the
bottom of this module, gated by ``supported_lowered()``. It drops the
W % 32 requirement by packing ROW-ALIGNED: each canvas row is padded up
to a word boundary (``canvas_words`` words per row), so the (dr, dc)
stencil read of any direction — diagonals included — is one funnel
shift by ``dr * row_bits + dc`` (``shift_canvas``). Cross-row and
frame garbage from the shift is never masked arithmetically; every
consumer ANDs with an exact packed plane (``adj`` per direction,
``b2_in`` per window offset), which is also what makes holes exact:
hole cells pack as district-0 bits, but no adjacency plane ever has a
bit over a hole. The B2-window contiguity check
(board._stencil_patch_ok's bitset label propagation) vectorizes across
cells the other way around: one packed PLANE per window offset k
(member/seed/reach), with the static offset-pair adjacency
``b2_adj[k] bit j`` packed per (k, j) pair — the same Jacobi rounds in
the same order, so the result is bit-identical. cut_times keeps all
FOUR forward planes (E, SE, S, SW) in bit-sliced ripple-carry counters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .step import Spec, StepParams, geom_denom_finite

U32 = jnp.uint32


def _common_gates(bg, spec: Spec) -> bool:
    # surgical (holes / diagonal planes) graphs run the lowered stencil
    # body in kernel/board.py — the packed planes here are rook-only.
    # getattr: `bg` may be a BoardGraph or a lower.StencilSpec.
    return (
        bool(bg.uniform_pop)
        and not getattr(bg, "surgical", False)
        and not spec.record_interface
        and bg.w % 32 == 0
        and spec.accept in ("cut", "always")
        and spec.contiguity in ("patch", "none")
        and not spec.record_assignment_bits
    )


def supported(bg, spec: Spec) -> bool:
    """Static gate: may this chunk run on the 2-district bit body?"""
    return (_common_gates(bg, spec)
            and spec.n_districts == 2
            and spec.proposal == "bi")


def supported_pair(bg, spec: Spec) -> bool:
    """Static gate for the k-district pair bit body (district ids as
    ceil(log2(k)) bit-planes). Mirrors board.supports' geom-wait bound:
    the literal n**k - 1 wait denominator must stay finite in f32."""
    return (_common_gates(bg, spec)
            and spec.proposal == "pair"
            and 2 <= spec.n_districts <= 31
            and (not spec.geom_waits
                 or geom_denom_finite(bg.n, spec.n_districts)))


def n_words(n: int) -> int:
    return -(-n // 32)


def pack_bits(plane) -> jnp.ndarray:
    """(..., N) {0,1}/bool -> (..., NW) uint32, bit j of word k = cell
    k*32+j (LSB first). Pad cells are zero."""
    n = plane.shape[-1]
    nw = n_words(n)
    b = jnp.pad(plane.astype(U32), [(0, 0)] * (plane.ndim - 1)
                + [(0, nw * 32 - n)])
    b = b.reshape(*plane.shape[:-1], nw, 32)
    return jnp.sum(b << jnp.arange(32, dtype=U32), axis=-1, dtype=U32)


def unpack_bits(words, n: int) -> jnp.ndarray:
    """(..., NW) uint32 -> (..., N) int8."""
    nw = words.shape[-1]
    bits = ((jnp.repeat(words, 32, axis=-1)
             >> (jnp.arange(nw * 32, dtype=U32) % 32)) & U32(1))
    return bits[..., :n].astype(jnp.int8)


def shift_down(words, k: int):
    """Bit n+k moves to position n (read the +k neighbor). k static."""
    nw = words.shape[-1]
    wo, bo = divmod(k, 32)
    p = jnp.pad(words, [(0, 0)] * (words.ndim - 1) + [(0, wo + 1)])
    a = p[..., wo:wo + nw]
    if bo == 0:
        return a
    b = p[..., wo + 1:wo + 1 + nw]
    return (a >> U32(bo)) | (b << U32(32 - bo))


def shift_up(words, k: int):
    """Bit n-k moves to position n (read the -k neighbor). k static."""
    nw = words.shape[-1]
    wo, bo = divmod(k, 32)
    p = jnp.pad(words, [(0, 0)] * (words.ndim - 1) + [(wo + 1, 0)])
    a = p[..., 1:1 + nw]
    if bo == 0:
        return a
    b = p[..., 0:nw]
    return (a << U32(bo)) | (b >> U32(32 - bo))


def _at_most_one(a, b, c, d):
    """Bitwise per-cell: at most one of the four bit-planes is set."""
    return ~((a & b) | (c & d) | ((a | b) & (c | d)))


def static_masks(bg):
    """Existence masks per ring direction, packed. Loop-invariant —
    computed inside the jitted chunk and hoisted by XLA."""
    n, w, h = bg.n, bg.w, bg.h
    idx = jnp.arange(n)
    e = bg.east_ok
    wk = bg.west_ok
    s = idx < (h - 1) * w
    nn = idx >= w
    # ring order: E, SE, S, SW, W, NW, N, NE (board.same_planes)
    dirs = [e, s & e, s, s & wk, wk, nn & wk, nn, nn & e]
    return [pack_bits(m[None, :]) for m in dirs]


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def planes_bits(bg, spec: Spec, params: StepParams, board_w, dist_pop,
                count: bool = False):
    """Bit-plane analogue of board._planes: same[] ring planes, boundary
    mask/count, contiguity, population gate, validity. ``count`` adds
    ``has_pop`` (C,) — any boundary cell passing the population gate —
    for the reject-reason taxonomy."""
    masks = static_masks(bg)
    w = bg.w
    offs = [(shift_down, 1), (shift_down, w + 1), (shift_down, w),
            (shift_down, w - 1), (shift_up, 1), (shift_up, w + 1),
            (shift_up, w), (shift_up, w - 1)]
    same = []
    diff = []
    for (fn, k), m in zip(offs, masks):
        x = board_w ^ fn(board_w, k)
        same.append(~x & m)
        diff.append(x & m)

    b_mask = diff[0] | diff[2] | diff[4] | diff[6]
    b_count = jax.lax.population_count(b_mask).astype(jnp.int32).sum(1)

    if spec.contiguity == "patch":
        seeds_le1 = _at_most_one(same[0], same[2], same[4], same[6])
        runs = [same[i] & ~(same[i - 1] & same[i - 2]) for i in
                (0, 2, 4, 6)]
        contig = seeds_le1 | _at_most_one(*runs)
    else:
        contig = ~jnp.zeros_like(b_mask)

    # uniform population: the bound test collapses to one boolean per
    # chain per side (board.supports gates non-uniform pop off this body)
    # ceil/floor keep every operand an exact f32 integer so this matches
    # the general path's exact-difference bound test bit-for-bit (see
    # board._board_planes' population-gate comment)
    unit = bg.pop[0].astype(jnp.float32)
    p0 = dist_pop[:, 0].astype(jnp.float32)
    p1 = dist_pop[:, 1].astype(jnp.float32)
    lo = jnp.ceil(params.pop_lo)
    hi = jnp.floor(params.pop_hi)
    ok0 = unit <= jnp.minimum(p0 - lo, hi - p1)
    ok1 = unit <= jnp.minimum(p1 - lo, hi - p0)
    full = U32(0xFFFFFFFF)
    pop_ok = ((board_w & jnp.where(ok1, full, U32(0))[:, None])
              | (~board_w & jnp.where(ok0, full, U32(0))[:, None]))

    valid = b_mask & contig & pop_ok
    cut_e = diff[0]                       # edge (i, i+1), masked to E
    cut_s = diff[2]                       # edge (i, i+W), masked to S
    out = dict(valid=valid, b_count=b_count, diff=diff,
               cut_e=cut_e, cut_s=cut_s)
    if count:
        out["has_pop"] = (jax.lax.population_count(b_mask & pop_ok)
                          .astype(jnp.int32).sum(1) > 0)
    return out


def _word_at(words, wi):
    """words[c, wi[c]] without a dynamic gather: one-hot masked sum."""
    nw = words.shape[1]
    sel = jnp.arange(nw)[None, :] == wi[:, None]
    return jnp.sum(jnp.where(sel, words, U32(0)), axis=1, dtype=U32)


def bit_at(words, flat):
    """Bit ``flat[c]`` of each chain's plane, as int32 0/1."""
    wsel = _word_at(words, flat // 32)
    return ((wsel >> (flat % 32).astype(U32)) & U32(1)).astype(jnp.int32)


def _pick_row(rowcnt, u):
    """Shared first level of the two-level m-th-valid selection: draw m
    uniform on the total count, pick the row holding the m-th valid slot.
    Returns (row, m_in_row, any_valid, onehot-row (C, n_rows, 1))."""
    h = rowcnt.shape[1]
    rowcum = jnp.cumsum(rowcnt, axis=1)
    total = rowcum[:, -1]
    any_valid = total > 0
    m = jnp.minimum((u * total.astype(jnp.float32)).astype(jnp.int32),
                    jnp.maximum(total - 1, 0))
    row = jnp.argmax(rowcum > m[:, None], axis=1).astype(jnp.int32)
    oh_prev = jnp.arange(h)[None, :] == (row - 1)[:, None]
    before = jnp.sum(jnp.where(oh_prev, rowcum, 0), axis=1,
                     dtype=jnp.int32)
    oh_row = (jnp.arange(h)[None, :, None] == row[:, None, None])
    return row, m - before, any_valid, oh_row


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def select_flat(bg, valid, u):
    """The (m+1)-th valid cell in flat row-major order — identical choice
    to the int8 path's two-matmul selection, via popcounts.

    Returns (flat, any_valid)."""
    c = valid.shape[0]
    h, w = bg.h, bg.w
    wpr = w // 32                          # static; gated by supported()
    pc = jax.lax.population_count(valid).astype(jnp.int32)
    row, m_in_row, any_valid, oh_row = _pick_row(
        pc.reshape(c, h, wpr).sum(-1), u)

    rw = jnp.sum(jnp.where(oh_row, valid.reshape(c, h, wpr), U32(0)),
                 axis=1, dtype=U32)        # (C, wpr): the chosen row
    colcum = jnp.cumsum(unpack_bits(rw, w).astype(jnp.int32), axis=1)
    col = jnp.argmax(colcum > m_in_row[:, None], axis=1).astype(jnp.int32)
    return row * w + col, any_valid


def flip_bit(board_w, flat, accept):
    """XOR the chosen cell's bit where accepted (2 districts: flip)."""
    nw = board_w.shape[1]
    sel = ((jnp.arange(nw)[None, :] == (flat // 32)[:, None])
           & accept[:, None])
    val = (U32(1) << (flat % 32).astype(U32))[:, None]
    return board_w ^ jnp.where(sel, val, U32(0))


# ---------------------------------------------------------------------------
# k-district pair walk on bit-sliced district ids
# ---------------------------------------------------------------------------

def bits_per_district(k: int) -> int:
    return max(1, (k - 1).bit_length())


def pack_board_planes(board, k: int):
    """int8 (C, N) district ids -> list of bit-sliced (C, NW) planes,
    plane b holding bit b of every id."""
    return [pack_bits((board.astype(jnp.int32) >> b) & 1)
            for b in range(bits_per_district(k))]


def unpack_board_planes(planes, n: int):
    out = jnp.zeros(planes[0].shape[:-1] + (n,), jnp.int8)
    for b, p in enumerate(planes):
        out = out + (unpack_bits(p, n) << b)
    return out


def _full_if_bit(bits, d):
    """(C, 1) uint32: all-ones where bit ``d`` of per-chain mask is set."""
    on = ((bits >> d) & 1) == 1
    return jnp.where(on, U32(0xFFFFFFFF), U32(0))[:, None]


def _eq_const(planes, d: int):
    """Bit-plane mask of cells whose district id == d."""
    acc = planes[0] if (d >> 0) & 1 else ~planes[0]
    for b in range(1, len(planes)):
        acc = acc & (planes[b] if (d >> b) & 1 else ~planes[b])
    return acc


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def planes_bits_pair(bg, spec: Spec, params: StepParams, planes, dist_pop,
                     count: bool = False):
    """Bit-plane analogue of board._planes_pair: per-(node, rook
    direction) pair validity with district dedup, ring contiguity of the
    origin district, per-chain district-bitmask population gates.
    ``count`` adds ``has_pop`` (C,) — any deduped boundary pair passing
    both population gates — for the reject-reason taxonomy."""
    k = spec.n_districts
    masks = static_masks(bg)
    w = bg.w
    offs = [(shift_down, 1), (shift_down, w + 1), (shift_down, w),
            (shift_down, w - 1), (shift_up, 1), (shift_up, w + 1),
            (shift_up, w), (shift_up, w - 1)]
    sh = [[fn(p, kk) for p in planes] for (fn, kk) in offs]   # 8 x B
    same8, diff8 = [], []
    for i in range(8):
        x = planes[0] ^ sh[i][0]
        for b in range(1, len(planes)):
            x = x | (planes[b] ^ sh[i][b])
        same8.append(~x & masks[i])
        diff8.append(x & masks[i])

    if spec.contiguity == "patch":
        seeds_le1 = _at_most_one(same8[0], same8[2], same8[4], same8[6])
        runs = [same8[i] & ~(same8[i - 1] & same8[i - 2])
                for i in (0, 2, 4, 6)]
        contig = seeds_le1 | _at_most_one(*runs)
    else:
        contig = ~jnp.zeros_like(diff8[0])

    # population gates as per-chain district bitmasks (uniform pop)
    unit = bg.pop[0].astype(jnp.float32)
    dp = dist_pop.astype(jnp.float32)                        # (C, K)
    from_ok = dp - unit >= params.pop_lo[:, None]
    to_ok = dp + unit <= params.pop_hi[:, None]
    weights = (jnp.int32(1) << jnp.arange(k, dtype=jnp.int32))[None, :]
    from_bits = jnp.sum(jnp.where(from_ok, weights, 0), axis=1,
                        dtype=jnp.int32)
    to_bits = jnp.sum(jnp.where(to_ok, weights, 0), axis=1,
                      dtype=jnp.int32)
    # one pass over the k districts builds BOTH per-cell gate planes;
    # each direction's to-gate is then just the shifted to_plane (pad
    # garbage is masked by diff8)
    ok_from = jnp.zeros_like(planes[0])
    to_plane = jnp.zeros_like(planes[0])
    for d in range(k):
        eq = _eq_const(planes, d)
        ok_from = ok_from | (eq & _full_if_bit(from_bits, d))
        to_plane = to_plane | (eq & _full_if_bit(to_bits, d))

    rook = (0, 2, 4, 6)                      # E, S, W, N (ring indices)
    pair, b_count = [], jnp.zeros(planes[0].shape[0], jnp.int32)
    hp = None
    for jj, i in enumerate(rook):
        pj = diff8[i]
        for jp in rook[:jj]:                 # dedup repeated districts
            eq = sh[i][0] ^ sh[jp][0]
            for b in range(1, len(planes)):
                eq = eq | (sh[i][b] ^ sh[jp][b])
            pj = pj & ~(masks[jp] & ~eq)
        b_count = b_count + jax.lax.population_count(pj).astype(
            jnp.int32).sum(1)
        fn, kk = offs[i]
        gate = ok_from & fn(to_plane, kk)
        pair.append(pj & contig & gate)
        if count:
            pp = pj & gate
            hp = pp if hp is None else hp | pp

    out = dict(valid4=pair, b_count=b_count,
               cut_e=diff8[0], cut_s=diff8[2])
    if count:
        out["has_pop"] = (jax.lax.population_count(hp)
                          .astype(jnp.int32).sum(1) > 0)
    return out


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def select_flat_pair(bg, valid4, u):
    """The (m+1)-th valid (node, direction) slot in the int8 pair body's
    row-major order (flat' = v*4 + j). Returns (flat4, any_valid)."""
    c = valid4[0].shape[0]
    h, w = bg.h, bg.w
    wpr = w // 32
    pc = sum(jax.lax.population_count(vj).astype(jnp.int32)
             for vj in valid4)
    row, m_in_row, any_valid, oh_row = _pick_row(
        pc.reshape(c, h, wpr).sum(-1), u)

    rows = [jnp.sum(jnp.where(oh_row, vj.reshape(c, h, wpr), U32(0)),
                    axis=1, dtype=U32) for vj in valid4]     # 4 x (C, wpr)
    # interleave to the int8 body's (y, j) lexicographic order
    row_bits = jnp.stack([unpack_bits(r, w) for r in rows],
                         axis=2).reshape(c, 4 * w)
    colcum = jnp.cumsum(row_bits.astype(jnp.int32), axis=1)
    col4 = jnp.argmax(colcum > m_in_row[:, None],
                      axis=1).astype(jnp.int32)
    return row * (4 * w) + col4, any_valid


def value_at(planes, flat):
    """District id at ``flat[c]`` from the bit-sliced planes, int32."""
    out = jnp.zeros(flat.shape, jnp.int32)
    for b, p in enumerate(planes):
        out = out + (bit_at(p, flat) << b)
    return out


def counter_init(c: int, nw: int, slices: int):
    return [jnp.zeros((c, nw), U32) for _ in range(slices)]


def counter_add(slices, plane_w):
    """Ripple-carry add of a 1-bit plane into bit-sliced counters."""
    carry = plane_w
    out = []
    for s in slices:
        out.append(s ^ carry)
        carry = s & carry
    return out


def counter_fold(slices, n: int):
    """Bit-sliced counters -> (C, N) int32 totals (once per chunk)."""
    tot = 0
    for k, s in enumerate(slices):
        tot = tot + (unpack_bits(s, n).astype(jnp.int32) << k)
    return tot


# ---------------------------------------------------------------------------
# Lowered stencil family: row-aligned packing over the HxW canvas
# ---------------------------------------------------------------------------

# ring-order (dr, dc) canvas deltas, E SE S SW W NW N NE — the same
# order as lower.stencil.RING_DELTAS and board._ring_offsets (kept
# literal here so this module stays import-light, like _ring_offsets)
_RING_DELTAS = ((0, 1), (1, 1), (1, 0), (1, -1),
                (0, -1), (-1, -1), (-1, 0), (-1, 1))


def supported_lowered(bg, spec: Spec) -> bool:
    """Static gate: may a lowered-family chunk (surgical stencil and/or
    record_interface) run on the packed stencil body? Duck-types on
    BoardGraph / lower.StencilSpec like the rook gates. Requirements:
    uniform node population (one pop boolean per chain per side), the
    2-district 'bi' walk, accept in ('cut', 'always') (the 'corrected'
    reversibility term needs per-neighbor boundary counts the bit
    planes don't keep), and — under 'patch' contiguity — an unambiguous
    2-D displacement per B2-window offset (``b2_disp``; a flat offset
    realized by two (dr, dc) pairs only happens at canvas width <= 4).
    No width restriction: rows pack word-aligned."""
    return (
        bool(bg.uniform_pop)
        and spec.n_districts == 2
        and spec.proposal == "bi"
        and spec.accept in ("cut", "always")
        and spec.contiguity in ("patch", "none")
        and (spec.contiguity != "patch"
             or getattr(bg, "b2_disp", None) is not None)
    )


def canvas_words(w: int) -> int:
    """Words per canvas row (rows pad up to a word boundary so every
    row starts at bit 0 of a fresh word)."""
    return n_words(w)


def pack_canvas(plane, h: int, w: int) -> jnp.ndarray:
    """(..., N=h*w) {0,1}/bool -> (..., h*wpr) uint32, row-aligned: row
    r occupies words [r*wpr, (r+1)*wpr), bit j of word r*wpr+q = cell
    r*w + q*32 + j. Pad bits (columns >= w) are zero."""
    wpr = canvas_words(w)
    p = plane.reshape(*plane.shape[:-1], h, w)
    return pack_bits(p).reshape(*plane.shape[:-1], h * wpr)


def unpack_canvas(words, h: int, w: int) -> jnp.ndarray:
    """(..., h*wpr) uint32 -> (..., N) int8 (inverse of pack_canvas)."""
    wpr = words.shape[-1] // h
    u = unpack_bits(words.reshape(*words.shape[:-1], h, wpr), w)
    return u.reshape(*words.shape[:-1], h * w)


def canvas_bit_index(flat, w: int):
    """Canvas-flat cell index -> bit index in the row-aligned packing
    (identity when w % 32 == 0)."""
    r = flat // w
    return r * (canvas_words(w) * 32) + (flat - r * w)


def shift_canvas(words, dr: int, dc: int, w: int):
    """Packed read of the (dr, dc) canvas neighbor: cell (r+dr, c+dc)'s
    bit moves to cell (r, c)'s position. Cross-row and frame garbage
    survives in the shifted words — every caller masks with an exact
    packed plane (adj / b2_in), never arithmetically."""
    off = dr * canvas_words(w) * 32 + dc
    if off == 0:
        return words
    return shift_down(words, off) if off > 0 else shift_up(words, -off)


def _patch_ok_bits(bg, board_w):
    """EXACT board._stencil_patch_ok on packed planes: per-cell bitsets
    over the K B2-window offsets become K packed PLANES (member / seed /
    reach), and the per-cell offset-pair adjacency ``b2_adj[k] bit j``
    becomes one static packed plane per nonzero (k, j) pair
    (``bg.b2_pairs``, precomputed on the host). Same lowest-seed
    initialization and the same ``b2_iters`` Jacobi rounds in the same
    order, so the reachability fixpoint — and therefore the contiguity
    verdict — is bit-identical. Holes are exact for free: ``b2_in[k]``
    is only set where both the cell and its offset-k partner are real
    nodes, so the hole cells' district-0 packing never leaks in."""
    h, w = bg.h, bg.w
    kk = len(bg.b2_offsets)
    member = []
    for k in range(kk):
        dr, dc = bg.b2_disp[k]
        same_k = ~(board_w ^ shift_canvas(board_w, dr, dc, w))
        member.append(same_k & pack_canvas(bg.b2_in[k][None, :], h, w))
    seeds = [member[k] & pack_canvas(
        ((bg.nbr_bits >> k) & 1)[None, :], h, w) for k in range(kk)]

    # reach starts at the lowest-index seed (int32 body: seeds & -seeds)
    reach = []
    lower = None
    for k in range(kk):
        reach.append(seeds[k] if lower is None else seeds[k] & ~lower)
        lower = seeds[k] if lower is None else lower | seeds[k]

    adj_pair = {(k, j): pack_canvas(((bg.b2_adj[k] >> j) & 1)[None, :],
                                    h, w)
                for (k, j) in bg.b2_pairs}
    for _ in range(bg.b2_iters):
        contrib = [None] * kk
        for (k, j) in bg.b2_pairs:
            t = reach[k] & adj_pair[(k, j)]
            contrib[j] = t if contrib[j] is None else contrib[j] | t
        reach = [r if c is None else r | (c & m)
                 for r, c, m in zip(reach, contrib, member)]

    bad = None
    for k in range(kk):
        b = seeds[k] & ~reach[k]
        bad = b if bad is None else bad | b
    return ~bad


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def planes_bits_lowered(bg, spec: Spec, params: StepParams, board_w,
                        dist_pop, count: bool = False):
    """Bit-plane analogue of board._planes_stencil: 8 masked direction
    planes (diagonals are just two more shift offsets), boundary mask
    and count, exact B2 contiguity, population gate, validity, and all
    four forward cut planes. ``count`` adds ``has_pop`` (C,) for the
    reject-reason taxonomy."""
    h, w = bg.h, bg.w
    diff = []
    for d, (dr, dc) in enumerate(_RING_DELTAS):
        x = board_w ^ shift_canvas(board_w, dr, dc, w)
        diff.append(x & pack_canvas(bg.adj[d][None, :], h, w))

    # adj planes only exist over real cells, so the boundary mask needs
    # no separate node_mask AND (board._planes_stencil's b_mask)
    b_mask = diff[0]
    for p in diff[1:]:
        b_mask = b_mask | p
    b_count = jax.lax.population_count(b_mask).astype(jnp.int32).sum(1)

    if spec.contiguity == "patch":
        contig = _patch_ok_bits(bg, board_w)
    else:
        contig = ~jnp.zeros_like(b_mask)

    # uniform population (gated): same exact-f32 threshold trick as the
    # rook bit body; the unit comes from the first REAL cell (bg.pop[0]
    # may be a hole carrying population 0)
    unit = bg.pop[bg.cell_of_node[0]].astype(jnp.float32)
    p0 = dist_pop[:, 0].astype(jnp.float32)
    p1 = dist_pop[:, 1].astype(jnp.float32)
    lo = jnp.ceil(params.pop_lo)
    hi = jnp.floor(params.pop_hi)
    ok0 = unit <= jnp.minimum(p0 - lo, hi - p1)
    ok1 = unit <= jnp.minimum(p1 - lo, hi - p0)
    full = U32(0xFFFFFFFF)
    pop_ok = ((board_w & jnp.where(ok1, full, U32(0))[:, None])
              | (~board_w & jnp.where(ok0, full, U32(0))[:, None]))

    valid = b_mask & contig & pop_ok
    out = dict(valid=valid, b_count=b_count, diff=diff,
               cut_e=diff[0], cut_se=diff[1], cut_s=diff[2],
               cut_sw=diff[3])
    if count:
        out["has_pop"] = (jax.lax.population_count(b_mask & pop_ok)
                          .astype(jnp.int32).sum(1) > 0)
    return out


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def select_flat_lowered(bg, valid, u):
    """The (m+1)-th valid cell in CANVAS row-major order — identical
    choice to board._select_two_level on the unpacked plane, via per-row
    popcounts over the row-aligned words. Returns (flat, any_valid)
    with ``flat`` a canvas-flat index (callers convert to a packed bit
    index with ``canvas_bit_index``)."""
    c = valid.shape[0]
    h, w = bg.h, bg.w
    wpr = canvas_words(w)
    pc = jax.lax.population_count(valid).astype(jnp.int32)
    row, m_in_row, any_valid, oh_row = _pick_row(
        pc.reshape(c, h, wpr).sum(-1), u)

    rw = jnp.sum(jnp.where(oh_row, valid.reshape(c, h, wpr), U32(0)),
                 axis=1, dtype=U32)        # (C, wpr): the chosen row
    colcum = jnp.cumsum(unpack_bits(rw, w).astype(jnp.int32), axis=1)
    col = jnp.argmax(colcum > m_in_row[:, None], axis=1).astype(jnp.int32)
    return row * w + col, any_valid


def counter_fold_canvas(slices, h: int, w: int):
    """Bit-sliced canvas counters -> (C, N) int32 totals."""
    tot = 0
    for k, s in enumerate(slices):
        tot = tot + (unpack_canvas(s, h, w).astype(jnp.int32) << k)
    return tot
