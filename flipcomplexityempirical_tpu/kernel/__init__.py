from .step import (
    Spec, StepParams, make_params, transition, record, propose,
    sample_geom_minus1, interface_metrics, finalize_host,
)
from . import contiguity
from . import board

__all__ = [
    "Spec", "StepParams", "make_params", "transition", "record", "propose",
    "sample_geom_minus1", "interface_metrics", "finalize_host", "contiguity",
    "board",
]
