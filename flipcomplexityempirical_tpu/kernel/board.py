"""Board kernel: the dense stencil fast path for plain rook-grid lattices.

This is the TPU-first redesign of the hot flip chain for the benchmark
workload (2-district chains on an HxW rook grid, BASELINE.json north star).
Where ``kernel/step.py`` is general (any padded-neighbor graph, re-propose
``while_loop``, per-node gather contiguity), this kernel exploits the grid
and the memory system:

- State is a flat ``(C, N)`` int8 board (N = H*W, minor dim N so every
  plane tiles the full 128-lane vector width with no padding waste).
  Neighbor reads are *stencil slices* of one padded array at offsets
  {+-1, +-W, +-W+-1} with static row-wrap masks — no gathers in the hot
  loop, so XLA fuses the whole per-step dataflow into a few passes.
- The re-propose-until-valid loop of the reference chain (gerrychain
  MarkovChain semantics, SURVEY.md section 2.3) collapses into ONE masked
  draw: the proposal is uniform over boundary nodes and the state does not
  change between retries, so "redraw until valid" is exactly "uniform over
  the *valid* boundary nodes" (and an empty valid set is exactly the
  exhausted self-loop). This removes the batch-synchronized
  ``lax.while_loop`` whose iteration count is the max tries over all C
  chains (~3-4 full batch passes per step at C=4096).
- Contiguity is the ring criterion: flipping v keeps its origin district
  locally connected iff v's same-district rook neighbors form a single
  block in the cyclic 8-neighborhood ring, where two cyclically adjacent
  rook neighbors are linked iff the diagonal between them is also
  same-district. On a plain rook grid this is *equivalent* to the
  radius-2 patch check of ``kernel/contiguity.patch_connected`` (the
  distance-2 straight nodes of the patch are pendants attached to a
  single rook neighbor, so they never affect seed-to-seed connectivity);
  ``tests/test_board.py`` proves the equivalence exhaustively over all
  2^8 neighborhood patterns at interior, edge, and corner positions.
  Computed for ALL nodes at once as ~12 fused elementwise ops.
- The reference's per-yield flip bookkeeping (part_sum / last_flipped /
  num_flips, grid_chain_sec11.py:396-400) would cost three full (C, N)
  read-modify-write passes per step as in-loop accumulators — the
  dominant cost by far. Instead the scan emits a 2-word-per-chain log
  (flip pointer, sign) per yield, and ``apply_flip_log`` reconstructs all
  three arrays once per chunk: one composite-key sort groups each chain's
  log by pointer node, per-group telescoping turns the recurrence into
  per-entry weights, and a batched MATMUL histogram accumulates the
  weights into (C, N) planes — no dynamic gather or scatter anywhere
  (see its docstring). ``tests/test_board.py`` checks the reconstruction
  against a sequential replay, including mid-run chunk splits.
- cut_times accumulates in chunk-local int16 planes (chunk <= 32767
  asserted) folded into the int32 state once per chunk — half the HBM
  traffic of the per-step int32 read-modify-write.
- On uniform-population 2-district 'bi' workloads whose width is a
  multiple of 32, the whole scan body switches to the bit-board backend
  (``kernel/bitboard.py``): board and planes packed 32 cells per uint32
  lane, cut_times in bit-sliced ripple-carry counters — bit-identical
  trajectories at a fraction of the plane traffic
  (``tests/test_bitboard.py``). The lowered stencil family (surgical
  canvases, record_interface) has its own packed body with row-aligned
  words and all four forward cut counters bit-sliced
  (``bitboard.supported_lowered``; ``tests/test_bitboard_lowered.py``).
- The k-district 'pair' proposal (slow_reversible_propose semantics,
  grid_chain_sec11.py:117-130) has its own int8 body: per-(node,
  direction) pair validity planes with district dedup, selection over
  the (N*4)-slot row-major mask, population gates as per-chain district
  bitmasks (``tests/test_board_pair.py``).

Reference semantics preserved (same quirk set as kernel/step.py):
- uniform boundary-node proposal, flip to the other district
  (grid_chain_sec11.py:132-145);
- literal Metropolis ``base**(-dcut)`` without the reversibility
  correction (grid_chain_sec11.py:171-179);
- memoized geometric waits with the literal ``n**k - 1`` denominator
  (grid_chain_sec11.py:147-148) — sampled from the boundary count of the
  *post-move* state, re-recorded unchanged on self-loop yields;
- per-yield re-application of the last flip's part_sum / last_flipped /
  num_flips bookkeeping (grid_chain_sec11.py:396-400);
- per-yield cut_times accumulation over the current cut set
  (grid_chain_sec11.py:383-384).

The board loop records yield t *before* transition t+1 (the general path
records after), so the wait of a freshly accepted move is sampled from the
next iteration's boundary plane; the yielded sequence is identical:
R(S_0), [T, R] x (n-1)  ==  [R, T] x (n-1), R (epilogue).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..graphs.lattice import LatticeGraph
from ..lower.stencil import stencil_for
from ..stats import accumulators as _sacc
from . import bitboard
from .step import Spec, StepParams, sample_geom_minus1
from .step import geom_denom_finite as kstep_geom_ok

@struct.dataclass
class BoardGraph:
    """Static per-graph planes (a small pytree; loop-invariant).

    ``h``/``w`` ride the treedef (static), so jitted kernels specialize on
    the grid shape. Built from a ``lower.StencilSpec``: plain full rook
    grids keep the original rook bodies (bit-identical), while *surgical*
    stencils (holes, diagonal planes — sec11, Frankengraph, queen grids)
    and ``record_interface`` specs run the generalized lowered body
    (masked 8-direction planes, B2-window contiguity, wall-key interface
    reduction)."""

    pop: jnp.ndarray      # int32[N] node population weights (0 at holes)
    deg: jnp.ndarray      # int32[N] graph degree (<= 8)
    east_ok: jnp.ndarray  # bool[N] node has an east (+1 flat) neighbor
    west_ok: jnp.ndarray  # bool[N] node has a west (-1 flat) neighbor
    # --- lowered-stencil planes (see lower/stencil.py::StencilSpec) ---
    adj: Optional[jnp.ndarray] = None           # bool[8, N] ring order
    node_mask: Optional[jnp.ndarray] = None     # bool[N] real node cells
    cell_of_node: Optional[jnp.ndarray] = None  # int32[n_real]
    b2_in: Optional[jnp.ndarray] = None         # bool[K, N]
    b2_adj: Optional[jnp.ndarray] = None        # int32[K, N]
    nbr_bits: Optional[jnp.ndarray] = None      # int32[N]
    iface_key: Optional[jnp.ndarray] = None     # int32[4, N]
    h: int = struct.field(pytree_node=False, default=0)
    w: int = struct.field(pytree_node=False, default=0)
    # static because the bit-board body is chosen at trace time
    uniform_pop: bool = struct.field(pytree_node=False, default=False)
    # static: body selection and loop structure specialize on these
    surgical: bool = struct.field(pytree_node=False, default=False)
    real_nodes: int = struct.field(pytree_node=False, default=0)
    b2_offsets: tuple = struct.field(pytree_node=False, default=())
    # 2-D (dr, dc) displacement per B2 offset and the static nonzero
    # (k, j) pairs of b2_adj — consumed only by the packed lowered body
    # (bitboard.supported_lowered / _patch_ok_bits)
    b2_disp: Optional[tuple] = struct.field(pytree_node=False, default=None)
    b2_pairs: tuple = struct.field(pytree_node=False, default=())
    b2_iters: int = struct.field(pytree_node=False, default=0)
    patch_exact: bool = struct.field(pytree_node=False, default=False)
    iface_ok: bool = struct.field(pytree_node=False, default=False)
    iface_decode: tuple = struct.field(pytree_node=False,
                                       default=(0, 0, 0, 0))
    center: tuple = struct.field(pytree_node=False, default=(0.0, 0.0))

    @property
    def n(self) -> int:
        return self.h * self.w

    @property
    def n_real(self) -> int:
        """Real node count (canvas minus holes) — the geometric-wait
        denominator and abits width use THIS, never the canvas size."""
        return self.real_nodes or self.h * self.w


@struct.dataclass
class BoardState:
    """Batched chain state in board form. C chains over an HxW grid.

    Mirrors state.ChainState field-for-field where semantics overlap;
    node-indexed arrays are flat (C, N) with flat index = x*W + y
    (LatticeGraph's sorted (x, y) label order; on surgical stencils the
    canvas embedding, hole cells carrying district -1). ``cut_times_e[c,
    i]`` counts cut yields of edge (i, i+1) (zero where no east
    neighbor); ``cut_times_s[c, i]`` of edge (i, i+W); the lowered body
    adds the diagonal planes ``cut_times_se`` (i, i+W+1) and
    ``cut_times_sw`` (i, i+W-1), None on rook-body states."""

    key: jnp.ndarray           # uint32[C, 2] per-chain PRNG keys
    board: jnp.ndarray         # int8[C, N] district 0..K-1 (0/1 for 'bi')
    dist_pop: jnp.ndarray      # int32[C, K]
    cut_count: jnp.ndarray     # int32[C]
    cur_wait: jnp.ndarray      # f32[C] memoized geometric wait
    wait_pending: jnp.ndarray  # bool[C] accepted move awaits its wait sample
    cur_flip: jnp.ndarray      # int32[C] flat node of last accepted flip; -1
    cur_sign: jnp.ndarray      # int32[C] label of cur_flip's district (the
                               # board never changes under cur_flip between
                               # accepts, so carrying the label at accept
                               # time replaces a per-record board gather)
    t_yield: jnp.ndarray       # int32[C]
    move_clock: jnp.ndarray    # int32[C] accepted moves (reference step_num)
    part_sum: jnp.ndarray      # int32[C, N]
    last_flipped: jnp.ndarray  # int32[C, N]
    num_flips: jnp.ndarray     # int32[C, N]
    cut_times_e: jnp.ndarray   # int32[C, N]
    cut_times_s: jnp.ndarray   # int32[C, N]
    waits_sum: jnp.ndarray     # f32[C] chunk-local (host drains to f64)
    accept_count: jnp.ndarray  # int32[C]
    tries_sum: jnp.ndarray     # int32[C] == yields processed (one draw/step)
    exhausted_count: jnp.ndarray  # int32[C] steps with empty valid set
    cut_times_se: Optional[jnp.ndarray] = None  # int32[C, N] lowered body
    cut_times_sw: Optional[jnp.ndarray] = None  # int32[C, N] lowered body
    # reject-reason taxonomy (ISSUE 3): int32[C, 4] proposals lost to
    # [non-boundary, pop-bound, disconnect, Metropolis]. None by default
    # (treedef — and thus compiled graphs and checkpoints — unchanged);
    # runners enable with .replace(reject_count=zeros) when recording.
    # Small (C, 4), so it rides the scan carry, NOT _BOOKKEEPING.
    reject_count: Optional[jnp.ndarray] = None


# ---------------------------------------------------------------------------
# Grid-shape inference and support predicate
# ---------------------------------------------------------------------------

def board_shape(graph: LatticeGraph):
    """(H, W) if ``graph`` is a plain full rook grid in sorted (x, y) label
    order — the layout this kernel requires — else None."""
    labs = graph.labels
    n = graph.n_nodes
    if n == 0 or not all(isinstance(l, tuple) and len(l) == 2 for l in labs):
        return None
    xs = [l[0] for l in labs]
    ys = [l[1] for l in labs]
    if not all(isinstance(v, (int, np.integer)) for v in (*xs[:1], *ys[:1])):
        return None
    h, w = max(xs) + 1, max(ys) + 1
    if min(xs) != 0 or min(ys) != 0 or h * w != n:
        return None
    if list(labs) != [(x, y) for x in range(h) for y in range(w)]:
        return None
    if graph.n_edges != h * (w - 1) + (h - 1) * w:
        return None
    lab_arr = np.array(labs, dtype=np.int64)
    d = np.abs(lab_arr[graph.edges[:, 0]] - lab_arr[graph.edges[:, 1]])
    if not (d.sum(axis=1) == 1).all():
        return None
    return h, w


def supports(graph: LatticeGraph, spec: Spec) -> bool:
    """True iff the board kernel family reproduces run_chains semantics
    exactly for (graph, spec) — via the lowering pass
    (lower.lower_to_stencil), so near-grid graphs with holes and diagonal
    planes (sec11, Frankengraph, queen grids) qualify. Everything outside
    falls back to the general path. ``body_for`` picks the body within
    the family (lowered / bitboard / int8 board)."""
    st = stencil_for(graph)
    if st is None:
        return False
    if spec.n_districts == 2 and spec.proposal == "bi":
        prop_ok = spec.accept in ("cut", "corrected", "always")
    elif (spec.proposal == "pair" and 2 <= spec.n_districts <= 31
          and not st.surgical):
        # k-district pair walk (slow_reversible_propose): the pair body
        # needs uniform node population (its per-district bound test is a
        # per-chain bitmask) and has no reversibility-corrected accept;
        # geom waits need the literal n**k - 1 denominator to stay finite
        # in f32; gating here fails such configs at init (the general
        # fallback raises the explanatory error from sample_geom_minus1)
        # instead of mid-trace inside a board body. Rook stencils only.
        pop = np.asarray(graph.pop)
        prop_ok = (spec.accept in ("cut", "always")
                   and pop.size > 0 and bool((pop == pop[0]).all())
                   and (not spec.geom_waits or kstep_geom_ok(
                       graph.n_nodes, spec.n_districts)))
    else:
        return False
    # 'patch' contiguity: plain rook grids use the ring criterion (proven
    # equivalent); surgical stencils run the B2 propagation, which must
    # match the graph's own patch tables exactly (radius-2 lattices only —
    # a radius-3 patch graph like hex falls back to the general kernel)
    contig_ok = (spec.contiguity == "none"
                 or (spec.contiguity == "patch"
                     and (not st.surgical or st.patch_exact)))
    iface_ok = (not spec.record_interface
                or (st.iface_ok and spec.proposal == "bi"))
    return (
        prop_ok
        and contig_ok
        and iface_ok
        and spec.invalid == "repropose"
        and spec.anneal in ("none", "linear")
        and not spec.frame_interface
        and not spec.weighted_cut
        # proposal variants: the stencil bodies draw from the packed
        # boundary planes and record no importance weights — both
        # variants run the general kernel
        and not spec.nobacktrack
        and not spec.lazy_uniform
        and (not spec.record_assignment_bits
             or st.n_real * max(
                 1, (spec.n_districts - 1).bit_length()) <= 32)
    )


def body_for(bg: BoardGraph, spec: Spec, bits: Optional[bool] = None) -> str:
    """The body ``run_board_chunk`` will execute: 'lowered_bits' |
    'lowered' | 'bitboard' | 'board'. Surgical stencils and interface
    recording run the lowered family — packed (lowered_bits) where
    ``bitboard.supported_lowered`` holds, the int8 stencil body
    otherwise; plain rook grids keep the bit-identical rook bodies."""
    if bg.surgical or spec.record_interface:
        lbits_ok = bitboard.supported_lowered(bg, spec)
        use_bits = lbits_ok if bits is None else bool(bits)
        return "lowered_bits" if use_bits else "lowered"
    bits_ok = (bitboard.supported_pair(bg, spec)
               if spec.proposal == "pair" else bitboard.supported(bg, spec))
    use_bits = bits_ok if bits is None else bool(bits)
    return "bitboard" if use_bits else "board"


def make_board_graph(graph: LatticeGraph) -> BoardGraph:
    st = stencil_for(graph)
    if st is None:
        raise ValueError(f"graph {graph.name!r} does not lower to a board "
                         "stencil (see lower.lower_to_stencil)")
    b2_adj_np = np.asarray(st.b2_adj)
    kk = len(st.b2_offsets)
    b2_pairs = tuple((k, j) for k in range(kk) for j in range(kk)
                     if bool(np.any(b2_adj_np[k] & (1 << j))))
    return BoardGraph(
        pop=jnp.asarray(st.pop),
        deg=jnp.asarray(st.deg),
        east_ok=jnp.asarray(st.adj[0]),
        west_ok=jnp.asarray(st.adj[4]),
        adj=jnp.asarray(st.adj),
        node_mask=jnp.asarray(st.node_mask),
        cell_of_node=jnp.asarray(st.cell_of_node),
        b2_in=jnp.asarray(st.b2_in),
        b2_adj=jnp.asarray(st.b2_adj),
        nbr_bits=jnp.asarray(st.nbr_bits),
        iface_key=(jnp.asarray(st.iface_key)
                   if st.iface_key is not None else None),
        h=st.h, w=st.w,
        uniform_pop=st.uniform_pop,
        surgical=st.surgical,
        real_nodes=st.n_real,
        b2_offsets=st.b2_offsets,
        b2_disp=st.b2_disp,
        b2_pairs=b2_pairs,
        b2_iters=st.b2_iters,
        patch_exact=st.patch_exact,
        iface_ok=st.iface_ok,
        iface_decode=st.iface_decode,
        center=st.center)


def node_view(bg: BoardGraph, arr):
    """Restrict a canvas-indexed (..., N) array to real nodes in node
    order (..., n_real) — identity on plain full grids. Host-side."""
    return np.asarray(arr)[..., np.asarray(bg.cell_of_node)]


# ---------------------------------------------------------------------------
# Stencil planes
# ---------------------------------------------------------------------------

def same_planes(bg: BoardGraph, board):
    """same[i][c, n] = ring-offset-i neighbor of n exists and shares n's
    district. Ring order (cyclic, rook at even indices): E(+1), SE(+1+W),
    S(+W), SW(+W-1), W(-1), NW(-1-W), N(-W), NE(-W+1) in flat offsets.
    Out-of-grid pads compare against -1 => False; row wraps are masked."""
    w, n = bg.w, bg.n
    p = jnp.pad(board, ((0, 0), (w + 1, w + 1)), constant_values=-1)

    def sh(o):
        return p[:, w + 1 + o: w + 1 + o + n] == board

    e, wk = bg.east_ok, bg.west_ok
    return [sh(1) & e, sh(w + 1) & e, sh(w), sh(w - 1) & wk,
            sh(-1) & wk, sh(-w - 1) & wk, sh(-w), sh(-w + 1) & e]


def cut_planes(bg: BoardGraph, board):
    """(cut_e, cut_s) bool[C, N]: cut indicators for the east (i, i+1)
    and south (i, i+W) edges of each node."""
    w, n = bg.w, bg.n
    south_ok = jnp.arange(n) < (bg.h - 1) * w
    p = jnp.pad(board, ((0, 0), (0, w)), constant_values=-1)
    cut_e = bg.east_ok[None] & (p[:, 1:1 + n] != board)
    cut_s = south_ok[None] & (p[:, w:w + n] != board)
    return cut_e, cut_s


def recount_cuts(bg: BoardGraph, board) -> jnp.ndarray:
    """i32[C] cut-edge count recomputed from the board. The chunk loop
    carries BoardState.cut_count incrementally (+dcut on accept); this
    from-scratch recount serves out-of-loop callers (replica-exchange
    acceptance over a freshly permuted board) and drift tests."""
    if bg.surgical:
        same = _same_planes_stencil(bg, board)
        total = jnp.zeros(board.shape[0], jnp.int32)
        for d in range(4):  # forward planes only: each edge counted once
            total = total + (bg.adj[d][None] & ~same[d]).sum(
                axis=1, dtype=jnp.int32)
        return total
    cut_e, cut_s = cut_planes(bg, board)
    return (cut_e.sum(axis=1, dtype=jnp.int32)
            + cut_s.sum(axis=1, dtype=jnp.int32))


def ring_contig_ok(same):
    """The ring criterion (== patch_connected on plain rook grids; see
    module docstring). ok iff <=1 same-district rook neighbor, or all
    same-district rook neighbors lie in one cyclic-adjacent block."""
    seeds = (same[0].astype(jnp.int8) + same[2] + same[4] + same[6])
    runs = jnp.zeros_like(seeds)
    for i in (0, 2, 4, 6):
        linked = same[(i - 1) % 8] & same[(i - 2) % 8]
        runs = runs + (same[i] & ~linked)
    return (seeds <= 1) | (runs <= 1)


def _planes(bg: BoardGraph, spec: Spec, params: StepParams,
            state: BoardState, count: bool = False):
    """One fused pass over the board: cut planes, boundary mask, per-node
    validity, boundary count. ``count`` (a trace-time flag) additionally
    reduces ``has_pop`` — "some boundary cell survives the population
    gate" — for the reject-reason taxonomy; off, the traced graph is
    exactly the historical one."""
    board = state.board
    same = same_planes(bg, board)
    # small-range planes stay int8: half/quarter the HBM traffic of the
    # default int32 promotion, and values are <= 4 by construction
    same_deg = (same[0].astype(jnp.int8) + same[2] + same[4] + same[6])
    diff_deg = bg.deg[None].astype(jnp.int8) - same_deg
    b_mask = diff_deg > 0
    b_count = b_mask.sum(axis=1, dtype=jnp.int32)
    south_ok = jnp.arange(bg.n) < (bg.h - 1) * bg.w
    cut_e = bg.east_ok[None] & ~same[0]      # edge (i, i+1)
    cut_s = south_ok[None] & ~same[2]        # edge (i, i+W)
    # cut_count is NOT reduced here: the loop carries it incrementally
    # (+dcut on accept) — one fewer (C, E)-scale reduction per step.
    # recount_cuts() recomputes from scratch for out-of-loop callers.

    if spec.contiguity == "patch":
        contig = ring_contig_ok(same)
    else:  # 'none'
        contig = jnp.ones_like(b_mask)

    # population bounds for flipping each node OUT of its current district
    # collapse to one per-chain threshold per side (flipping out of d must
    # keep d >= pop_lo and the other side <= pop_hi), so the plane test is
    # a single broadcast compare instead of two (C, N) f32 constructions.
    # ceil/floor of the f32 bounds keep every operand an exact f32 integer
    # (populations < 2^24), so the compare reproduces the general path's
    # exact-difference test (p0 - popn >= pop_lo) bit-for-bit: an integer
    # m >= real lo iff m >= ceil(lo), and fl(p0 - ceil(lo)) is exact where
    # fl(p0 - pop_lo) could round across an integer.
    p0 = state.dist_pop[:, 0].astype(jnp.float32)
    p1 = state.dist_pop[:, 1].astype(jnp.float32)
    lo = jnp.ceil(params.pop_lo)
    hi = jnp.floor(params.pop_hi)
    thr0 = jnp.minimum(p0 - lo, hi - p1)  # leaving 0
    thr1 = jnp.minimum(p1 - lo, hi - p0)  # leaving 1
    is1 = board == 1
    popn = bg.pop[None].astype(jnp.float32)
    pop_ok = popn <= jnp.where(is1, thr1[:, None], thr0[:, None])

    valid = b_mask & contig & pop_ok
    planes = dict(valid=valid, b_count=b_count, diff_deg=diff_deg,
                  cut_e=cut_e, cut_s=cut_s)
    if count:
        planes["has_pop"] = (b_mask & pop_ok).any(axis=1)
    return planes


# ---------------------------------------------------------------------------
# Lowered stencil body: masked 8-direction planes (holes + diagonals)
# ---------------------------------------------------------------------------

_RING_FLAT = ("+1", "+w+1", "+w", "+w-1", "-1", "-w-1", "-w", "-w+1")


def _ring_offsets(w: int) -> tuple:
    return (1, w + 1, w, w - 1, -1, -w - 1, -w, -w + 1)


def _same_planes_stencil(bg: BoardGraph, board):
    """same[d][c, i] = the ring-d neighbor EDGE exists in the lowered
    graph and its cell shares i's district. Unlike ``same_planes``, every
    direction (diagonals included) is masked by its static adjacency
    plane, so removed nodes and seam edges are exact."""
    w, n = bg.w, bg.n
    p = jnp.pad(board, ((0, 0), (w + 1, w + 1)), constant_values=-1)

    def sh(o):
        return p[:, w + 1 + o: w + 1 + o + n] == board

    return [sh(o) & bg.adj[d][None]
            for d, o in enumerate(_ring_offsets(w))]


def _stencil_patch_ok(bg: BoardGraph, board):
    """EXACT ``contiguity.patch_connected`` for every cell at once, as a
    gather-free bitset propagation over static flat offsets.

    The ring-criterion shortcut of the rook body is WRONG once diagonal
    edges exist (a diagonal can bridge two ring-nonadjacent neighbors),
    so the lowered body runs the real check: member bitset over the K
    B2-window offsets (same district as the center, in the center's
    radius-2 patch), seeds = direct neighbors, propagate reachability
    from the lowest seed through ``b2_adj`` for ``b2_iters`` rounds (max
    patch size - 1 bounds any simple path), ok iff every seed is reached
    (<= 1 seed is vacuously ok: seeds & ~reach == 0). Bit k of every
    word refers to offset ``b2_offsets[k]`` — per-cell masks ``b2_in`` /
    ``b2_adj`` make the same bit mean a different *node* at each cell,
    which is what lets one static program serve an irregular graph."""
    n = bg.n
    pad = 2 * bg.w + 2
    p = jnp.pad(board, ((0, 0), (pad, pad)), constant_values=-1)
    member = jnp.zeros(board.shape, jnp.int32)
    for k, o in enumerate(bg.b2_offsets):
        same_k = (p[:, pad + o: pad + o + n] == board) & bg.b2_in[k][None]
        member = member | jnp.where(same_k, jnp.int32(1 << k), 0)
    seeds = member & bg.nbr_bits[None]
    reach = seeds & -seeds                     # lowest set bit (0 if none)
    for _ in range(bg.b2_iters):
        contrib = jnp.zeros_like(reach)
        for k in range(len(bg.b2_offsets)):
            hit = ((reach >> k) & 1) == 1
            contrib = contrib | jnp.where(hit, bg.b2_adj[k][None], 0)
        reach = reach | (contrib & member)
    return (seeds & ~reach) == 0


def _planes_stencil(bg: BoardGraph, spec: Spec, params: StepParams,
                    state: BoardState, count: bool = False):
    """The lowered body's fused plane pass: 8 masked same-planes, full
    graph degree, 4 forward cut planes (E, SE, S, SW), B2 contiguity.
    ``count`` adds the reject-taxonomy ``has_pop`` reduce (see
    ``_planes``)."""
    board = state.board
    same = _same_planes_stencil(bg, board)
    same_deg = same[0].astype(jnp.int8)
    for s in same[1:]:
        same_deg = same_deg + s
    diff_deg = bg.deg[None].astype(jnp.int8) - same_deg
    b_mask = (diff_deg > 0) & bg.node_mask[None]
    b_count = b_mask.sum(axis=1, dtype=jnp.int32)
    cut_e = bg.adj[0][None] & ~same[0]
    cut_se = bg.adj[1][None] & ~same[1]
    cut_s = bg.adj[2][None] & ~same[2]
    cut_sw = bg.adj[3][None] & ~same[3]

    if spec.contiguity == "patch":
        contig = _stencil_patch_ok(bg, board)
    else:  # 'none'
        contig = jnp.ones_like(b_mask)

    # same exact-f32 threshold trick as _planes; hole cells hold board
    # -1 => is1 False, pop 0, and are excluded by b_mask regardless
    p0 = state.dist_pop[:, 0].astype(jnp.float32)
    p1 = state.dist_pop[:, 1].astype(jnp.float32)
    lo = jnp.ceil(params.pop_lo)
    hi = jnp.floor(params.pop_hi)
    thr0 = jnp.minimum(p0 - lo, hi - p1)
    thr1 = jnp.minimum(p1 - lo, hi - p0)
    is1 = board == 1
    popn = bg.pop[None].astype(jnp.float32)
    pop_ok = popn <= jnp.where(is1, thr1[:, None], thr0[:, None])

    valid = b_mask & contig & pop_ok
    planes = dict(valid=valid, b_count=b_count, diff_deg=diff_deg,
                  cut_e=cut_e, cut_se=cut_se, cut_s=cut_s, cut_sw=cut_sw)
    if count:
        planes["has_pop"] = (b_mask & pop_ok).any(axis=1)
    return planes


def _interface_stencil(bg: BoardGraph, cuts):
    """step.interface_metrics on the lowered planes, gather-free: each
    wall edge's static int32 key packs (canonical edge index << coord
    bits | doubled midpoint coords), so min-reducing keys over the cut
    planes selects the two smallest-INDEX wall-cut edges (the general
    path's deterministic choice) and the midpoints decode arithmetically
    from the winning keys. Exact in f32: integer coords, *0.5 decode."""
    qx_off, qy_off, bx, by = bg.iface_decode
    big = jnp.int32(2 ** 30)
    keyed = [jnp.where(cuts[d], bg.iface_key[d][None], big)
             for d in range(4)]
    first = keyed[0].min(axis=1)
    for kd in keyed[1:]:
        first = jnp.minimum(first, kd.min(axis=1))
    second = None
    for kd in keyed:
        s = jnp.where(kd > first[:, None], kd, big).min(axis=1)
        second = s if second is None else jnp.minimum(second, s)
    ok = second < big

    def decode(key):
        qy = (key & ((1 << by) - 1)) + qy_off
        qx = ((key >> by) & ((1 << bx) - 1)) + qx_off
        return qx.astype(jnp.float32) * 0.5, qy.astype(jnp.float32) * 0.5

    ax, ay = decode(first)
    ex, ey = decode(second)
    dx, dy = ex - ax, ey - ay
    slope = jnp.where(dx != 0, dy / jnp.where(dx != 0, dx, 1.0), jnp.inf)
    cx = jnp.float32(bg.center[0])
    cy = jnp.float32(bg.center[1])
    vax, vay = ax - cx, ay - cy
    vbx, vby = ex - cx, ey - cy
    norm = (jnp.sqrt(vax * vax + vay * vay)
            * jnp.sqrt(vbx * vbx + vby * vby))
    cosang = jnp.clip((vax * vbx + vay * vby) / jnp.maximum(norm, 1e-12),
                      -1.0, 1.0)
    angle = jnp.arccos(cosang)
    nan = jnp.float32(jnp.nan)
    return (jnp.where(ok, slope, nan).astype(jnp.float32),
            jnp.where(ok, angle, nan).astype(jnp.float32))


_CUT_KEYS = ("cut_e", "cut_se", "cut_s", "cut_sw")


def _record_stencil(bg: BoardGraph, spec: Spec, params: StepParams,
                    state: BoardState, cts16, planes, cur_wait):
    """The lowered body's measurement yield: 4 cut-plane accumulators,
    node-rank abits packing (holes excluded), interface slope/angle."""
    state, out, log = _record_common(state, planes["b_count"], cur_wait)
    if spec.record_interface:
        if not bg.iface_ok:
            raise ValueError("record_interface needs wall planes the "
                             "lowering could not encode (lower.stencil)")
        out["slope"], out["angle"] = _interface_stencil(
            bg, [planes[k] for k in _CUT_KEYS])
    if spec.record_assignment_bits:
        bits_per = max(1, (spec.n_districts - 1).bit_length())
        if bg.n_real * bits_per > 32:
            raise ValueError("record_assignment_bits needs n_nodes * "
                             "ceil(log2(k)) <= 32")
        rank = jnp.cumsum(bg.node_mask.astype(jnp.uint32)) - 1
        shifts = (rank * bits_per)[None, :]
        out["abits"] = jnp.sum(
            jnp.where(bg.node_mask[None],
                      state.board.astype(jnp.uint32) << shifts, 0),
            axis=1, dtype=jnp.uint32)
    cts16 = tuple(a + planes[k].astype(jnp.int16)
                  for a, k in zip(cts16, _CUT_KEYS))
    return state, cts16, out, log


def _transition_stencil(bg: BoardGraph, spec: Spec, params: StepParams,
                        state: BoardState, planes, kprop, kacc):
    """The lowered transition: identical structure to ``_transition``,
    with degree/boundary arithmetic over all 8 masked directions."""
    c, n = state.board.shape
    h, w = bg.h, bg.w
    cidx = jnp.arange(c)

    flat, any_valid = _select_two_level(planes["valid"], _uniform(kprop),
                                        h, w)

    d_from = state.board[cidx, flat].astype(jnp.int32)
    d_to = 1 - d_from
    dd = planes["diff_deg"][cidx, flat].astype(jnp.int32)
    dcut = bg.deg[flat] - 2 * dd

    if spec.accept == "corrected":
        # 8-direction generalization of the rook nbr_delta (see
        # _transition): a neighbor u enters the boundary iff its only
        # relation changed (same -> cut with diff_deg 0), leaves iff its
        # only cut edge was to v; v leaves iff all neighbors differed
        diff_deg_p = planes["diff_deg"].astype(jnp.int32)
        board_i = state.board.astype(jnp.int32)
        delta = jnp.zeros(c, jnp.int32)
        for d, off in enumerate(_ring_offsets(w)):
            exists = bg.adj[d][flat]
            uc = jnp.clip(flat + off, 0, n - 1)
            same_u = board_i[cidx, uc] == d_from
            dd_u = diff_deg_p[cidx, uc]
            delta = delta + jnp.where(
                exists,
                jnp.where(same_u & (dd_u == 0), 1,
                          jnp.where(~same_u & (dd_u == 1), -1, 0)),
                0)
        b_new = (planes["b_count"] + delta
                 - (dd == bg.deg[flat]).astype(jnp.int32))
        corr_log = (jnp.log(planes["b_count"].astype(jnp.float32))
                    - jnp.log(jnp.maximum(b_new, 1).astype(jnp.float32)))
    else:
        corr_log = None
    accept = _accept_decision(spec, params, state.move_clock, dcut,
                              any_valid, kacc, corr_log)

    sel = (jnp.arange(n)[None, :] == flat[:, None]) & accept[:, None]
    board = jnp.where(sel, d_to[:, None].astype(state.board.dtype),
                      state.board)
    popv = bg.pop[flat] * accept.astype(jnp.int32)
    sgn = jnp.where(d_from == 0, 1, -1)
    dist_pop = state.dist_pop.at[:, 0].add(-popv * sgn)
    dist_pop = dist_pop.at[:, 1].add(popv * sgn)

    rej = (_reject_increment(planes["b_count"], planes["has_pop"], accept,
                             any_valid)
           if state.reject_count is not None else None)
    return _commit_transition(state, params, board, dist_pop, flat, d_to,
                              dcut, accept, any_valid, rej=rej)


# ---------------------------------------------------------------------------
# One scan iteration: [complete pending wait, record yield, transition]
# ---------------------------------------------------------------------------

def _split4(keys):
    ks = jax.vmap(lambda k: jax.random.split(k, 4))(keys)
    return ks[:, 0], ks[:, 1], ks[:, 2], ks[:, 3]


def _uniform(keys):
    return jax.vmap(jax.random.uniform)(keys)


def _complete_wait(spec: Spec, state: BoardState, b_count, kwait,
                   n_nodes: int):
    if not spec.geom_waits:
        return state.cur_wait
    nd = spec.n_districts
    w = jax.vmap(lambda k, b: sample_geom_minus1(k, b, n_nodes, nd))(
        kwait, b_count)
    return jnp.where(state.wait_pending, w, state.cur_wait)


def _accept_decision(spec: Spec, params: StepParams, move_clock, dcut,
                     any_valid, kacc, corr_log=None):
    """The Metropolis decision shared by the int8 and bit-board bodies:
    literal ``base**(-dcut)`` bound (grid_chain_sec11.py:171-179), with
    the optional linear annealing schedule on the accepted-move clock and
    an optional reversibility-correction log term."""
    if spec.accept == "always":
        return any_valid
    if spec.anneal == "linear":
        t = (move_clock + 1).astype(jnp.float32)
        beta = jnp.clip((t - params.anneal_t0) / params.anneal_ramp,
                        0.0, params.anneal_beta_max)
    else:
        beta = params.beta
    log_bound = -beta * dcut.astype(jnp.float32) * params.log_base
    if corr_log is not None:
        log_bound = log_bound + corr_log
    logu = jnp.log(jnp.maximum(_uniform(kacc), jnp.float32(1e-12)))
    return any_valid & (logu < log_bound)


def _record_common(state: BoardState, b_count, cur_wait):
    """The per-yield record shared by both bodies: history row, flip-log
    row, wait bookkeeping, yield clock."""
    out = {
        "cut_count": state.cut_count,
        "b_count": b_count,
        "wait": cur_wait,
        "accepts": state.accept_count,
    }
    log = {"f": state.cur_flip, "s": state.cur_sign}
    state = state.replace(
        cur_wait=cur_wait, wait_pending=jnp.zeros_like(state.wait_pending),
        waits_sum=state.waits_sum + cur_wait, t_yield=state.t_yield + 1)
    return state, out, log


def _reject_increment(b_count, has_pop, accept, any_valid):
    """(C, 4) int32 one-hot per step: why this step's single masked draw
    produced no accepted move — [non-boundary (no boundary cell at all),
    pop-bound (boundary exists, none passes the population gate),
    disconnect (a cell passes pop but contiguity/validity kills them
    all), Metropolis (a valid cell was drawn, the coin said no)]. The
    board kernel makes one draw per step, so an exhausted step is one
    attributed rejection and reject_count.sum() + accept_count ==
    tries_sum exactly (tested)."""
    ex = ~any_valid
    has_bnd = b_count > 0
    nonbnd = ex & ~has_bnd
    pop = ex & has_bnd & ~has_pop
    disc = ex & has_bnd & has_pop
    met = any_valid & ~accept
    return jnp.stack([nonbnd, pop, disc, met], axis=1).astype(jnp.int32)


def _commit_transition(state: BoardState, params: StepParams, board,
                       dist_pop, flat, d_to, dcut, accept, any_valid,
                       rej=None):
    """The accept-commit shared by both bodies (board/dist_pop given in
    the body's own representation). ``rej`` is the optional (C, 4)
    reject-reason increment from ``_reject_increment`` — present exactly
    when ``state.reject_count`` is enabled."""
    acc_i = accept.astype(jnp.int32)
    extra = {}
    if rej is not None:
        extra["reject_count"] = state.reject_count + rej
    return state.replace(
        board=board,
        dist_pop=dist_pop,
        cut_count=state.cut_count + dcut * acc_i,
        cur_flip=jnp.where(accept, flat, state.cur_flip),
        cur_sign=jnp.where(accept, params.label_values[d_to],
                           state.cur_sign),
        wait_pending=accept,
        move_clock=state.move_clock + acc_i,
        accept_count=state.accept_count + acc_i,
        tries_sum=state.tries_sum + 1,
        exhausted_count=state.exhausted_count
        + (~any_valid).astype(jnp.int32),
        **extra,
    )


def _record(bg: BoardGraph, spec: Spec, params: StepParams,
            state: BoardState, ct_e16, ct_s16, planes, cur_wait):
    """The measurement yield (grid_chain_sec11.py:366-402), batched.
    Bookkeeping for part_sum/last_flipped/num_flips is deferred: this
    emits the (flip pointer, sign) log row instead."""
    state, out, log = _record_common(state, planes["b_count"], cur_wait)
    if spec.record_assignment_bits:
        bits_per = max(1, (spec.n_districts - 1).bit_length())
        if bg.n * bits_per > 32:
            raise ValueError("record_assignment_bits needs n_nodes * "
                             "ceil(log2(k)) <= 32")
        shifts = (jnp.arange(bg.n, dtype=jnp.uint32) * bits_per)[None, :]
        out["abits"] = jnp.sum(
            state.board.astype(jnp.uint32) << shifts, axis=1,
            dtype=jnp.uint32)
    ct_e16 = ct_e16 + planes["cut_e"].astype(jnp.int16)
    ct_s16 = ct_s16 + planes["cut_s"].astype(jnp.int16)
    return state, ct_e16, ct_s16, out, log


def _select_two_level(valid, u, n_rows: int, row_w: int):
    """Index of the (m+1)-th True cell of a row-major (C, n_rows*row_w)
    boolean mask, for m uniform on the True count — with BOTH selection
    levels on the MXU so the hot loop has no big gather and no big cumsum:

    1. rowcnt[c, x] = valid @ block-indicator (bf16 products, exact f32
       accumulation), tiny (C, n_rows) cumsum picks the row;
    2. vrow[c, y] = (valid & onehot-row) @ column-indicator — with
       exactly one row unmasked the column sums ARE that row's cells,
       so this doubles as the row extraction. (jnp.take_along_axis
       here lowered to a kCustom gather that ran ~3 ms/step; a flat
       (C, N) cumsum lowered to ~0.9 ms of reduce-window passes.)

    Returns (flat, any_valid)."""
    c, n = valid.shape
    cidx = jnp.arange(c)
    block = (jnp.arange(n)[:, None] // row_w
             == jnp.arange(n_rows)[None, :]).astype(jnp.bfloat16)
    colsel = (jnp.arange(n)[:, None] % row_w
              == jnp.arange(row_w)[None, :]).astype(jnp.bfloat16)
    valid_bf = valid.astype(jnp.bfloat16)
    rowcnt = jnp.dot(valid_bf, block,
                     preferred_element_type=jnp.float32).astype(jnp.int32)
    rowcum = jnp.cumsum(rowcnt, axis=1)                    # (C, n_rows)
    total = rowcum[:, -1]                                  # (C,)
    any_valid = total > 0
    m = jnp.minimum((u * total.astype(jnp.float32)).astype(jnp.int32),
                    jnp.maximum(total - 1, 0))
    row = jnp.argmax(rowcum > m[:, None], axis=1).astype(jnp.int32)
    before = jnp.where(row > 0,
                       rowcum[cidx, jnp.maximum(row - 1, 0)], 0)
    m_in_row = m - before
    rowmask = ((jnp.arange(n) // row_w)[None, :] == row[:, None])
    vrow = jnp.dot(jnp.where(rowmask, valid_bf, jnp.bfloat16(0)), colsel,
                   preferred_element_type=jnp.float32) > 0.5
    colcum = jnp.cumsum(vrow.astype(jnp.int32), axis=1)
    col = jnp.argmax(colcum > m_in_row[:, None], axis=1).astype(jnp.int32)
    return row * row_w + col, any_valid


def _transition(bg: BoardGraph, spec: Spec, params: StepParams,
                state: BoardState, planes, kprop, kacc):
    """Propose (single masked draw == re-propose-until-valid), accept,
    commit."""
    c, n = state.board.shape
    h, w = bg.h, bg.w
    cidx = jnp.arange(c)

    flat, any_valid = _select_two_level(planes["valid"], _uniform(kprop),
                                        h, w)

    d_from = state.board[cidx, flat].astype(jnp.int32)
    d_to = 1 - d_from
    # 2 districts: post-flip differing neighbors = pre-flip same neighbors
    dd = planes["diff_deg"][cidx, flat].astype(jnp.int32)
    dcut = bg.deg[flat] - 2 * dd

    if spec.accept == "corrected":
        # reversibility correction log(|b|/|b'|): the post-flip
        # boundary count follows from v's local neighborhood —
        # a neighbor u enters the boundary iff its only relation
        # changed (same -> cut with diff_deg 0), leaves iff its only
        # cut edge was to v; v itself leaves iff all neighbors
        # differed (annealing_cut_accept_backwards's ratio,
        # grid_chain_sec11.py:99; kernel/step.py accept='corrected')
        diff_deg_p = planes["diff_deg"].astype(jnp.int32)
        board_i = state.board.astype(jnp.int32)

        def nbr_delta(off, ok_mask):
            u = flat + off
            exists = ok_mask[flat]
            uc = jnp.clip(u, 0, n - 1)
            same_u = board_i[cidx, uc] == d_from
            dd_u = diff_deg_p[cidx, uc]
            return jnp.where(
                exists,
                jnp.where(same_u & (dd_u == 0), 1,
                          jnp.where(~same_u & (dd_u == 1), -1, 0)),
                0)

        south_ok = jnp.arange(n) < (bg.h - 1) * bg.w
        north_ok = jnp.arange(n) >= bg.w
        delta = (nbr_delta(1, bg.east_ok)
                 + nbr_delta(-1, bg.west_ok)
                 + nbr_delta(w, south_ok)
                 + nbr_delta(-w, north_ok))
        b_new = (planes["b_count"] + delta
                 - (dd == bg.deg[flat]).astype(jnp.int32))
        corr_log = (jnp.log(planes["b_count"].astype(jnp.float32))
                    - jnp.log(jnp.maximum(b_new, 1).astype(jnp.float32)))
    else:
        corr_log = None
    accept = _accept_decision(spec, params, state.move_clock, dcut,
                              any_valid, kacc, corr_log)

    # one-hot masked write: cheaper than a batched scatter on TPU (no
    # layout round-trip; fuses with the surrounding elementwise pass)
    sel = (jnp.arange(n)[None, :] == flat[:, None]) & accept[:, None]
    board = jnp.where(sel, d_to[:, None].astype(state.board.dtype),
                      state.board)
    popv = bg.pop[flat] * accept.astype(jnp.int32)
    sgn = jnp.where(d_from == 0, 1, -1)       # moving out of 0 => 0 loses
    dist_pop = state.dist_pop.at[:, 0].add(-popv * sgn)
    dist_pop = dist_pop.at[:, 1].add(popv * sgn)

    rej = (_reject_increment(planes["b_count"], planes["has_pop"], accept,
                             any_valid)
           if state.reject_count is not None else None)
    return _commit_transition(state, params, board, dist_pop, flat, d_to,
                              dcut, accept, any_valid, rej=rej)


# ---------------------------------------------------------------------------
# k-district pair proposal (slow_reversible_propose semantics)
# ---------------------------------------------------------------------------

_PAIR_DIRS = 4          # rook directions, fixed order E, S, W, N


def _nbr_value_planes(bg: BoardGraph, board):
    """Rook-neighbor district-id planes (pad/absent = -1), with their
    existence masks, in E, S, W, N order."""
    w, n = bg.w, bg.n
    p = jnp.pad(board, ((0, 0), (w, w)), constant_values=-1)

    def nv(o):
        return p[:, w + o: w + o + n]

    south_ok = (jnp.arange(n) < (bg.h - 1) * bg.w)[None]
    north_ok = (jnp.arange(n) >= bg.w)[None]
    return [(nv(1), bg.east_ok[None]), (nv(w), south_ok),
            (nv(-1), bg.west_ok[None]), (nv(-w), north_ok)]


def _planes_pair(bg: BoardGraph, spec: Spec, params: StepParams,
                 state: BoardState, count: bool = False):
    """Per-(node, direction) pair validity for the k-district proposal
    (slow_reversible_propose, grid_chain_sec11.py:117-130): uniform over
    DISTINCT (boundary node, adjacent district != own) pairs. A direction
    carries a pair iff its neighbor exists, differs from the node's
    district, and no earlier direction saw the same district (dedup —
    the reference's b_nodes pair updater is a SET)."""
    board = state.board
    nbrs = _nbr_value_planes(bg, board)
    same = same_planes(bg, board)

    diff = []
    for (v, ex), s in zip(nbrs, (same[0], same[2], same[4], same[6])):
        diff.append(ex & ~s)
    south_ok = jnp.arange(bg.n) < (bg.h - 1) * bg.w
    cut_e = bg.east_ok[None] & ~same[0]
    cut_s = south_ok[None] & ~same[2]

    if spec.contiguity == "patch":
        contig = ring_contig_ok(same)
    else:
        contig = jnp.ones_like(diff[0])

    # population gate per district as one bitmask per chain (uniform node
    # population — supports() gates non-uniform pop off this path): bit d
    # of from_bits[c] = "district d may lose one unit", of to_bits[c] =
    # "may gain one unit"; plane tests are variable-shift extracts.
    k = spec.n_districts
    unit = bg.pop[0].astype(jnp.float32)
    dp = state.dist_pop.astype(jnp.float32)             # (C, K)
    from_ok = dp - unit >= params.pop_lo[:, None]       # (C, K) bool
    to_ok = dp + unit <= params.pop_hi[:, None]
    weights = (jnp.int32(1) << jnp.arange(k, dtype=jnp.int32))[None, :]
    from_bits = jnp.sum(jnp.where(from_ok, weights, 0), axis=1,
                        dtype=jnp.int32)                # (C,)
    to_bits = jnp.sum(jnp.where(to_ok, weights, 0), axis=1,
                      dtype=jnp.int32)
    ok_from = ((from_bits[:, None] >> board.astype(jnp.int32)) & 1) == 1

    pairs = []
    b_count = jnp.zeros(board.shape[0], jnp.int32)
    hp = None
    for j, (v, ex) in enumerate(nbrs):
        pj = diff[j]
        for jp in range(j):
            vp, exp = nbrs[jp]
            pj &= ~(exp & (vp == v))                    # dedup districts
        # |b_nodes| for the pair walk is the DISTINCT-PAIR count (the
        # reference's pair updater feeding geom_wait), before the
        # validity gates — one count per deduped slot
        b_count = b_count + pj.sum(axis=1, dtype=jnp.int32)
        vi = jnp.maximum(v.astype(jnp.int32), 0)
        ok_to = ((to_bits[:, None] >> vi) & 1) == 1
        pairs.append(pj & contig & ok_from & ok_to)
        if count:
            # "some pair survives the population gates" (pre-contiguity)
            pop_pass = pj & ok_from & ok_to
            hp = pop_pass if hp is None else hp | pop_pass

    # row-major (node, direction) interleave: flat' = v*4 + j
    valid = jnp.stack(pairs, axis=2).reshape(board.shape[0], -1)
    planes = dict(valid=valid, b_count=b_count, cut_e=cut_e, cut_s=cut_s)
    if count:
        planes["has_pop"] = hp.any(axis=1)
    return planes


def _transition_pair(bg: BoardGraph, spec: Spec, params: StepParams,
                     state: BoardState, planes, kprop, kacc):
    """Pair-proposal transition: select the m-th valid (node, direction)
    slot, flip the node to that direction's neighbor district."""
    c, n = state.board.shape
    h, w = bg.h, bg.w
    cidx = jnp.arange(c)

    flat4, any_valid = _select_two_level(
        planes["valid"], _uniform(kprop), h, w * _PAIR_DIRS)
    flat = flat4 // _PAIR_DIRS
    j = flat4 % _PAIR_DIRS

    offs = jnp.asarray([1, w, -1, -w], jnp.int32)
    u_idx = jnp.clip(flat + offs[j], 0, n - 1)
    board_i = state.board.astype(jnp.int32)
    d_from = board_i[cidx, flat]
    d_to = board_i[cidx, u_idx]          # the chosen direction's district

    # dcut from v's rook neighborhood: each existing neighbor u changes
    # the edge (v,u) cut state per (a(u) != d_to) - (a(u) != d_from)
    south_ok = jnp.arange(n) < (bg.h - 1) * bg.w
    north_ok = jnp.arange(n) >= bg.w
    masks = (bg.east_ok, south_ok, bg.west_ok, north_ok)
    dcut = jnp.zeros(c, jnp.int32)
    for off, ok in zip((1, w, -1, -w), masks):
        ui = jnp.clip(flat + off, 0, n - 1)
        au = board_i[cidx, ui]
        ex = ok[flat]
        dcut += jnp.where(ex, (au != d_to).astype(jnp.int32)
                          - (au != d_from).astype(jnp.int32), 0)

    accept = _accept_decision(spec, params, state.move_clock, dcut,
                              any_valid, kacc)
    sel = (jnp.arange(n)[None, :] == flat[:, None]) & accept[:, None]
    board = jnp.where(sel, d_to[:, None].astype(state.board.dtype),
                      state.board)
    popv = bg.pop[flat] * accept.astype(jnp.int32)
    k = spec.n_districts
    oh_to = jnp.arange(k)[None, :] == d_to[:, None]
    oh_from = jnp.arange(k)[None, :] == d_from[:, None]
    dist_pop = state.dist_pop + popv[:, None] * (
        oh_to.astype(jnp.int32) - oh_from.astype(jnp.int32))

    rej = (_reject_increment(planes["b_count"], planes["has_pop"], accept,
                             any_valid)
           if state.reject_count is not None else None)
    return _commit_transition(state, params, board, dist_pop, flat, d_to,
                              dcut, accept, any_valid, rej=rej)


# ---------------------------------------------------------------------------
# Deferred flip bookkeeping: log -> (part_sum, last_flipped, num_flips)
# ---------------------------------------------------------------------------

def apply_flip_log(part_sum, last_flipped, num_flips, log_f, log_s, t0,
                   slice_bytes=4 << 30):
    """Replay the reference's per-yield flip bookkeeping
    (grid_chain_sec11.py:396-400) from a chunk's (T, C) log with
    order-independent dense algebra. ``t0[c]`` is the absolute yield index
    of log row 0.

    Sequential semantics reproduced exactly, per yield t with pointer f
    (f >= 0) and sign s = label of f's current district:
        part_sum[f]     += -s * (t - last_flipped[f])
        last_flipped[f]  = t
        num_flips[f]    += 1

    Implementation, built for a TPU whose dynamic gather/scatter emitter
    runs ~10 ns per element (a 2M-element scatter = ~20 ms):

    1. ONE sort of the composite key ``f*T + t_rel`` (sign as the only
       payload) groups each chain's entries by pointer node with yield
       order preserved inside groups; f and t_rel are recovered
       arithmetically. Per-group telescoping turns the part_sum
       recurrence into per-entry weights: interior entries contribute
       ``-s*(t - prev_t)``, each group's first entry ``-s*t_rel`` plus a
       carry term ``s*(last_flipped[f] - t0)`` resolved densely in step 3.
    2. The per-entry weights are accumulated into (C, N) planes by a
       batched MATMUL histogram instead of scatters: factor
       ``n = x*WF + y``, build one-hot row/column indicator operands, and
       contract ``einsum('ctx,cty->cxy')`` with the four weight streams
       (part_sum delta, first-entry sign, flip count, last yield+1)
       stacked along the column operand. All weights are chunk-relative
       (<= 2*T), so f32 accumulation with Precision.HIGHEST is
       integer-exact.
    3. Dense elementwise combine: the first-entry sign plane multiplies
       the CARRIED last_flipped plane (resolving step 1's carry term with
       no gather), and the last-yield plane overwrites last_flipped where
       the chunk touched the node.

    Chunk boundaries compose exactly through the carried last_flipped
    (asserted by tests/test_board.py::test_apply_flip_log_chunked_composition).

    The one-hot einsum operands scale as (C, T, 4*wf) f32 — 16.8 GB at
    C=16384, T=500 — which OOMed 16G HBM in the round-5 chain sweep. The
    replay is therefore applied over T-sub-slices (the exact chunk
    composition above) sized to bound the stacked column operand near
    ``slice_bytes`` (default 4 GB); at the benchmark shape (C=4096,
    T=500) the bound is not hit and the replay stays a single einsum.
    """
    tlen, c = log_f.shape
    n = part_sum.shape[1]
    wf = n if n < 128 else 128                           # full lane width
    hf = -(-n // wf)
    # bytes per log row across BOTH one-hot operands: a_ind (C, T, hf)
    # and the 4-stream b_all (C, T, 4*wf), f32 each
    row_bytes = c * (hf + 4 * wf) * 4
    slice_t = max(16, min(tlen, slice_bytes // row_bytes))
    if slice_t < tlen:
        for a in range(0, tlen, slice_t):
            part_sum, last_flipped, num_flips = apply_flip_log(
                part_sum, last_flipped, num_flips,
                log_f[a:a + slice_t], log_s[a:a + slice_t], t0 + a,
                slice_bytes=slice_bytes)
        return part_sum, last_flipped, num_flips
    if n * tlen >= 2 ** 31:
        raise ValueError(
            f"composite sort key n*chunk = {n}*{tlen} overflows int32; "
            "use a smaller chunk for this graph")
    f32 = jnp.float32
    f_cm = log_f.T                                       # (C, T)
    s_cm = log_s.T

    key = f_cm * tlen + jnp.arange(tlen, dtype=jnp.int32)[None, :]
    key_s, s_s = jax.lax.sort((key, s_cm), dimension=1, num_keys=1)
    f_s = jnp.floor_divide(key_s, tlen)                  # -1 preserved
    t_rel = jnp.remainder(key_s, tlen)                   # chunk-relative
    act = f_s >= 0

    prev_same = jnp.concatenate(
        [jnp.zeros((c, 1), bool), f_s[:, 1:] == f_s[:, :-1]], axis=1)
    prev_t = jnp.concatenate(
        [jnp.zeros((c, 1), t_rel.dtype), t_rel[:, :-1]], axis=1)
    is_last = jnp.concatenate(
        [f_s[:, :-1] != f_s[:, 1:], jnp.ones((c, 1), bool)], axis=1)

    s_f = s_s.astype(f32)
    w_ps = jnp.where(
        act, -s_f * (t_rel - jnp.where(prev_same, prev_t, 0)).astype(f32),
        0.0)
    w_s1 = jnp.where(act & ~prev_same, s_f, 0.0)
    w_nf = act.astype(f32)
    w_lf = jnp.where(act & is_last, (t_rel + 1).astype(f32), 0.0)

    fr = jnp.floor_divide(f_s, wf)                       # -1 matches no x
    fc = jnp.remainder(f_s, wf)
    a_ind = (fr[:, :, None] == jnp.arange(hf)[None, None, :]).astype(f32)
    c_ind = (fc[:, :, None] == jnp.arange(wf)[None, None, :]).astype(f32)
    b_all = jnp.concatenate(
        [c_ind * w[:, :, None] for w in (w_ps, w_s1, w_nf, w_lf)], axis=2)
    out = jnp.einsum('ctx,cty->cxy', a_ind, b_all,
                     precision=jax.lax.Precision.HIGHEST)
    out = out.reshape(c, hf, 4, wf).astype(jnp.int32)

    def plane(k):
        return out[:, :, k, :].reshape(c, hf * wf)[:, :n]

    t0c = t0[:, None]
    ps_new = (part_sum + plane(0)
              + plane(1) * (last_flipped - t0c))
    nf_new = num_flips + plane(2)
    lf_d = plane(3)
    lf_new = jnp.where(lf_d > 0, t0c + lf_d - 1, last_flipped)
    return ps_new, lf_new, nf_new


# ---------------------------------------------------------------------------
# Chunk runners
# ---------------------------------------------------------------------------

_BOOKKEEPING = ("part_sum", "last_flipped", "num_flips",
                "cut_times_e", "cut_times_s")
_BOOKKEEPING_DIAG = ("cut_times_se", "cut_times_sw")


def _bookkeeping_names(state: BoardState) -> tuple:
    """The heavy per-node accumulators kept OUT of the scan carry; the
    diagonal cut_times planes exist only on the lowered body."""
    extra = tuple(k for k in _BOOKKEEPING_DIAG
                  if getattr(state, k) is not None)
    return _BOOKKEEPING + extra


def _scan_stencil(bg: BoardGraph, spec: Spec, params: StepParams,
                  loop_state: BoardState, chunk: int, collect: bool,
                  acc=None):
    """The chunk scan on the lowered stencil body: masked 8-direction
    planes (holes, diagonal/seam edges), exact B2-window contiguity,
    keyed-plane interface metrics. Same scan shape as the int8 rook body
    — heavy accumulators (4 cut_times planes) ride int16 beside the
    carry and fold afterwards. ``acc`` (an optional
    stats.accumulators.SummaryAcc) rides the carry and folds every
    yield's ``out``; None traces to the pre-analytics graph (an empty
    pytree node costs nothing)."""
    c, n = loop_state.board.shape
    count = loop_state.reject_count is not None

    def body(carry, _):
        state, cts16, acc = carry
        key, kprop, kacc, kwait = _split4(state.key)
        state = state.replace(key=key)
        planes = _planes_stencil(bg, spec, params, state, count=count)
        cur_wait = _complete_wait(spec, state, planes["b_count"], kwait,
                                  bg.n_real)
        state, cts16, out, log = _record_stencil(
            bg, spec, params, state, cts16, planes, cur_wait)
        if acc is not None:
            acc = _sacc.fold_out(acc, out)
        state = _transition_stencil(bg, spec, params, state, planes,
                                    kprop, kacc)
        return (state, cts16, acc), (out if collect else {}, log)

    ct0 = tuple(jnp.zeros((c, n), jnp.int16) for _ in _CUT_KEYS)
    (loop_state, cts16, acc), (outs, logs) = jax.lax.scan(
        body, (loop_state, ct0, acc), None, length=chunk)
    return loop_state, outs, logs, cts16, acc


def _record_stencil_bits(bg: BoardGraph, spec: Spec, state: BoardState,
                         planes, cur_wait):
    """``_record_stencil`` on packed planes: the cut-plane accumulation
    moves to the caller's bit-sliced counters; the measurement-only
    interface/abits outputs unpack the packed planes per RECORDED step
    (exactly the int8 formulas, so bit-identical — and dead-code-
    eliminated entirely when the chunk does not collect)."""
    h, w = bg.h, bg.w
    state, out, log = _record_common(state, planes["b_count"], cur_wait)
    if spec.record_interface:
        if not bg.iface_ok:
            raise ValueError("record_interface needs wall planes the "
                             "lowering could not encode (lower.stencil)")
        cuts = [bitboard.unpack_canvas(planes[k], h, w).astype(bool)
                for k in _CUT_KEYS]
        out["slope"], out["angle"] = _interface_stencil(bg, cuts)
    if spec.record_assignment_bits:
        bits_per = max(1, (spec.n_districts - 1).bit_length())
        if bg.n_real * bits_per > 32:
            raise ValueError("record_assignment_bits needs n_nodes * "
                             "ceil(log2(k)) <= 32")
        ub = bitboard.unpack_canvas(state.board, h, w)
        rank = jnp.cumsum(bg.node_mask.astype(jnp.uint32)) - 1
        shifts = (rank * bits_per)[None, :]
        out["abits"] = jnp.sum(
            jnp.where(bg.node_mask[None],
                      ub.astype(jnp.uint32) << shifts, 0),
            axis=1, dtype=jnp.uint32)
    return state, out, log


def _scan_bits_lowered(bg: BoardGraph, spec: Spec, params: StepParams,
                       loop_state: BoardState, chunk: int, collect: bool,
                       acc=None):
    """The lowered-family chunk scan on the packed stencil backend
    (kernel/bitboard.py's row-aligned canvas packing): the board rides
    as one bit per cell (holes pack as 0 — every packed plane that
    could read them is masked by exact adjacency/window planes), all
    four forward cut planes accumulate in bit-sliced ripple-carry
    counters, and the trajectory is bit-identical to ``_scan_stencil``
    (same PRNG stream, same m-th-valid selection, same acceptance and
    B2-contiguity arithmetic — tests/test_bitboard_lowered.py asserts
    equality field-for-field)."""
    c, n = loop_state.board.shape
    h, w = bg.h, bg.w
    count = loop_state.reject_count is not None

    def body(carry, _):
        state, ct_sl, acc = carry
        key, kprop, kacc, kwait = _split4(state.key)
        state = state.replace(key=key)
        planes = bitboard.planes_bits_lowered(
            bg, spec, params, state.board, state.dist_pop, count=count)
        cur_wait = _complete_wait(spec, state, planes["b_count"], kwait,
                                  bg.n_real)
        state, out, log = _record_stencil_bits(bg, spec, state, planes,
                                               cur_wait)
        if acc is not None:
            acc = _sacc.fold_out(acc, out)
        ct_sl = tuple(bitboard.counter_add(sl, planes[k])
                      for sl, k in zip(ct_sl, _CUT_KEYS))

        # transition: single masked draw, flip the chosen cell's bit
        u = _uniform(kprop)
        flat, any_valid = bitboard.select_flat_lowered(
            bg, planes["valid"], u)
        pflat = bitboard.canvas_bit_index(flat, w)
        d_from = bitboard.bit_at(state.board, pflat)
        d_to = 1 - d_from
        dd = bitboard.bit_at(planes["diff"][0], pflat)
        for p in planes["diff"][1:]:
            dd = dd + bitboard.bit_at(p, pflat)
        dcut = bg.deg[flat] - 2 * dd
        accept = _accept_decision(spec, params, state.move_clock, dcut,
                                  any_valid, kacc)
        # uniform pop (gated); bg.pop[0] may be a hole carrying pop 0
        unit = bg.pop[bg.cell_of_node[0]]
        popv = unit * accept.astype(jnp.int32)
        sgn = jnp.where(d_from == 0, 1, -1)
        dist_pop = state.dist_pop.at[:, 0].add(-popv * sgn)
        dist_pop = dist_pop.at[:, 1].add(popv * sgn)
        rej = (_reject_increment(planes["b_count"], planes["has_pop"],
                                 accept, any_valid) if count else None)
        state = _commit_transition(
            state, params, bitboard.flip_bit(state.board, pflat, accept),
            dist_pop, flat, d_to, dcut, accept, any_valid, rej=rej)
        return (state, ct_sl, acc), (out if collect else {}, log)

    npw = h * bitboard.canvas_words(w)
    slices = max(chunk.bit_length(), 1)
    loop_state = loop_state.replace(
        board=bitboard.pack_canvas(loop_state.board == 1, h, w))
    ct0 = tuple(bitboard.counter_init(c, npw, slices) for _ in _CUT_KEYS)
    (loop_state, ct_sl, acc), (outs, logs) = jax.lax.scan(
        body, (loop_state, ct0, acc), None, length=chunk)
    board = bitboard.unpack_canvas(loop_state.board, h, w)
    loop_state = loop_state.replace(
        board=jnp.where(bg.node_mask[None], board, jnp.int8(-1)))
    cts = tuple(bitboard.counter_fold_canvas(sl, h, w) for sl in ct_sl)
    return loop_state, outs, logs, cts, acc


def _scan_bits(bg: BoardGraph, spec: Spec, params: StepParams,
               loop_state: BoardState, chunk: int, collect: bool,
               acc=None):
    """The chunk scan on the bit-board backend (kernel/bitboard.py): the
    board and every derived plane live as packed uint32 words inside the
    loop, cut_times accumulates in bit-sliced ripple-carry counters, and
    the trajectory is bit-identical to the int8 body (same PRNG stream,
    same m-th-valid selection, same acceptance arithmetic —
    tests/test_bitboard.py asserts equality field-for-field)."""
    n = bg.n
    c = loop_state.board.shape[0]
    count = loop_state.reject_count is not None

    def body(carry, _):
        state, ct_e_sl, ct_s_sl, acc = carry
        key, kprop, kacc, kwait = _split4(state.key)
        state = state.replace(key=key)
        planes = bitboard.planes_bits(bg, spec, params, state.board,
                                      state.dist_pop, count=count)
        cur_wait = _complete_wait(spec, state, planes["b_count"], kwait, n)

        # record (grid_chain_sec11.py:366-402)
        state, out, log = _record_common(state, planes["b_count"],
                                         cur_wait)
        if acc is not None:
            acc = _sacc.fold_out(acc, out)
        ct_e_sl = bitboard.counter_add(ct_e_sl, planes["cut_e"])
        ct_s_sl = bitboard.counter_add(ct_s_sl, planes["cut_s"])

        # transition: single masked draw, flip the chosen bit
        u = _uniform(kprop)
        flat, any_valid = bitboard.select_flat(bg, planes["valid"], u)
        d_from = bitboard.bit_at(state.board, flat)
        d_to = 1 - d_from
        dd = (bitboard.bit_at(planes["diff"][0], flat)
              + bitboard.bit_at(planes["diff"][2], flat)
              + bitboard.bit_at(planes["diff"][4], flat)
              + bitboard.bit_at(planes["diff"][6], flat))
        dcut = bg.deg[flat] - 2 * dd
        accept = _accept_decision(spec, params, state.move_clock, dcut,
                                  any_valid, kacc)
        popv = bg.pop[0] * accept.astype(jnp.int32)  # uniform pop (gated)
        sgn = jnp.where(d_from == 0, 1, -1)
        dist_pop = state.dist_pop.at[:, 0].add(-popv * sgn)
        dist_pop = dist_pop.at[:, 1].add(popv * sgn)
        rej = (_reject_increment(planes["b_count"], planes["has_pop"],
                                 accept, any_valid) if count else None)
        state = _commit_transition(
            state, params, bitboard.flip_bit(state.board, flat, accept),
            dist_pop, flat, d_to, dcut, accept, any_valid, rej=rej)
        return (state, ct_e_sl, ct_s_sl, acc), (out if collect else {},
                                                log)

    nw = bitboard.n_words(n)
    slices = max(chunk.bit_length(), 1)
    loop_state = loop_state.replace(
        board=bitboard.pack_bits(loop_state.board))
    ct0 = (bitboard.counter_init(c, nw, slices),
           bitboard.counter_init(c, nw, slices))
    (loop_state, ct_e_sl, ct_s_sl, acc), (outs, logs) = jax.lax.scan(
        body, (loop_state, *ct0, acc), None, length=chunk)
    loop_state = loop_state.replace(
        board=bitboard.unpack_bits(loop_state.board, n))
    return (loop_state, outs, logs,
            bitboard.counter_fold(ct_e_sl, n),
            bitboard.counter_fold(ct_s_sl, n), acc)


def _scan_bits_pair(bg: BoardGraph, spec: Spec, params: StepParams,
                    loop_state: BoardState, chunk: int, collect: bool,
                    acc=None):
    """The k-district pair chunk scan on bit-sliced district planes
    (kernel/bitboard.py): same trajectory as the int8 pair body,
    bit-for-bit (tests/test_bitboard.py)."""
    n = bg.n
    c = loop_state.board.shape[0]
    k = spec.n_districts
    w = bg.w
    count = loop_state.reject_count is not None

    def body(carry, _):
        state, ct_e_sl, ct_s_sl, acc = carry
        key, kprop, kacc, kwait = _split4(state.key)
        state = state.replace(key=key)
        planes = bitboard.planes_bits_pair(bg, spec, params, state.board,
                                           state.dist_pop, count=count)
        cur_wait = _complete_wait(spec, state, planes["b_count"], kwait, n)
        state, out, log = _record_common(state, planes["b_count"],
                                         cur_wait)
        if acc is not None:
            acc = _sacc.fold_out(acc, out)
        ct_e_sl = bitboard.counter_add(ct_e_sl, planes["cut_e"])
        ct_s_sl = bitboard.counter_add(ct_s_sl, planes["cut_s"])

        u = _uniform(kprop)
        flat4, any_valid = bitboard.select_flat_pair(
            bg, planes["valid4"], u)
        flat = flat4 // _PAIR_DIRS
        j = flat4 % _PAIR_DIRS
        offs = jnp.asarray([1, w, -1, -w], jnp.int32)
        u_idx = jnp.clip(flat + offs[j], 0, n - 1)
        d_from = bitboard.value_at(state.board, flat)
        d_to = bitboard.value_at(state.board, u_idx)

        south_ok = jnp.arange(n) < (bg.h - 1) * bg.w
        north_ok = jnp.arange(n) >= bg.w
        dcut = jnp.zeros(c, jnp.int32)
        for off, ok in zip((1, w, -1, -w),
                           (bg.east_ok, south_ok, bg.west_ok, north_ok)):
            ui = jnp.clip(flat + off, 0, n - 1)
            au = bitboard.value_at(state.board, ui)
            ex = ok[flat]
            dcut += jnp.where(ex, (au != d_to).astype(jnp.int32)
                              - (au != d_from).astype(jnp.int32), 0)

        accept = _accept_decision(spec, params, state.move_clock, dcut,
                                  any_valid, kacc)
        xor = d_from ^ d_to
        new_planes = [
            bitboard.flip_bit(p, flat, accept & (((xor >> b) & 1) == 1))
            for b, p in enumerate(state.board)]
        popv = bg.pop[0] * accept.astype(jnp.int32)
        oh_to = jnp.arange(k)[None, :] == d_to[:, None]
        oh_from = jnp.arange(k)[None, :] == d_from[:, None]
        dist_pop = state.dist_pop + popv[:, None] * (
            oh_to.astype(jnp.int32) - oh_from.astype(jnp.int32))
        rej = (_reject_increment(planes["b_count"], planes["has_pop"],
                                 accept, any_valid) if count else None)
        state = _commit_transition(state, params, new_planes, dist_pop,
                                   flat, d_to, dcut, accept, any_valid,
                                   rej=rej)
        return (state, ct_e_sl, ct_s_sl, acc), (out if collect else {},
                                                log)

    nw = bitboard.n_words(n)
    slices = max(chunk.bit_length(), 1)
    loop_state = loop_state.replace(
        board=bitboard.pack_board_planes(loop_state.board, k))
    ct0 = (bitboard.counter_init(c, nw, slices),
           bitboard.counter_init(c, nw, slices))
    (loop_state, ct_e_sl, ct_s_sl, acc), (outs, logs) = jax.lax.scan(
        body, (loop_state, *ct0, acc), None, length=chunk)
    loop_state = loop_state.replace(
        board=bitboard.unpack_board_planes(loop_state.board, n))
    return (loop_state, outs, logs,
            bitboard.counter_fold(ct_e_sl, n),
            bitboard.counter_fold(ct_s_sl, n), acc)


@functools.partial(jax.jit,
                   static_argnames=("spec", "chunk", "collect", "bits"))
def run_board_chunk(bg: BoardGraph, spec: Spec, params: StepParams,
                    state: BoardState, chunk: int, collect: bool = True,
                    bits: bool = None, acc=None):
    """``chunk`` iterations of [complete-wait, record, transition]; records
    yields t .. t+chunk-1 and advances ``chunk`` transitions. The heavy
    accumulators stay OUT of the scan carry: cut_times in int16 planes
    folded afterwards, flip bookkeeping replayed from the emitted log.
    ``bits`` overrides the bit-board dispatch (None = auto via
    ``bitboard.supported`` / ``supported_pair`` /
    ``supported_lowered``; False forces the int8 body of the active
    family — packed and int8 bodies are bit-identical, so the choice is
    purely a performance matter).

    ``acc`` (optional ``stats.accumulators.SummaryAcc``): the
    device-resident analytics accumulator — it rides the scan carry,
    folding every yield's ``out`` on-chip, and comes back as a third
    return value: ``(state, outs, acc)``. With ``acc=None`` (the
    default, a distinct jit specialization) the return stays
    ``(state, outs)`` and the traced graph is the pre-analytics one —
    the hot path is untouched. ``collect=False, acc=...`` is the
    summary-readback mode: no history block materializes at all."""
    if chunk > 32767:
        raise ValueError("chunk must be <= 32767 (int16 cut_times planes)")
    n = bg.n
    c = state.board.shape[0]
    t0 = state.t_yield
    names = _bookkeeping_names(state)
    big = {k: getattr(state, k) for k in names}
    loop_state = state.replace(
        **{k: None for k in names})

    lowered = bg.surgical or spec.record_interface
    if lowered:
        lbits_ok = bitboard.supported_lowered(bg, spec)
        use_lbits = lbits_ok if bits is None else bool(bits)
        if use_lbits and not lbits_ok:
            raise ValueError("bits=True: workload not supported by the "
                             "packed lowered body (see "
                             "bitboard.supported_lowered); bits=False "
                             "selects the int8 'lowered' body")
        scan = _scan_bits_lowered if use_lbits else _scan_stencil
        loop_state, outs, logs, cts16, acc = scan(
            bg, spec, params, loop_state, chunk, collect, acc)
        for k, ct in zip(("cut_times_e", "cut_times_se", "cut_times_s",
                          "cut_times_sw"), cts16):
            big[k] = big[k] + ct
    elif (bits if bits is not None else
          (bitboard.supported_pair(bg, spec)
           if spec.proposal == "pair" else bitboard.supported(bg, spec))):
        bits_ok = (bitboard.supported_pair(bg, spec)
                   if spec.proposal == "pair"
                   else bitboard.supported(bg, spec))
        if not bits_ok:
            raise ValueError("bits=True: workload not supported by the "
                             "bit-board body (see bitboard.supported / "
                             "supported_pair)")
        scan_bits = (_scan_bits_pair if spec.proposal == "pair"
                     else _scan_bits)
        (loop_state, outs, logs, cte, cts, acc) = scan_bits(
            bg, spec, params, loop_state, chunk, collect, acc)
        big["cut_times_e"] = big["cut_times_e"] + cte
        big["cut_times_s"] = big["cut_times_s"] + cts
    else:
        make_planes = (_planes_pair if spec.proposal == "pair"
                       else _planes)
        make_transition = (_transition_pair if spec.proposal == "pair"
                           else _transition)

        count = state.reject_count is not None

        def body(carry, _):
            state, ct_e16, ct_s16, acc = carry
            key, kprop, kacc, kwait = _split4(state.key)
            state = state.replace(key=key)
            planes = make_planes(bg, spec, params, state, count=count)
            cur_wait = _complete_wait(spec, state, planes["b_count"],
                                      kwait, n)
            state, ct_e16, ct_s16, out, log = _record(
                bg, spec, params, state, ct_e16, ct_s16, planes, cur_wait)
            if acc is not None:
                acc = _sacc.fold_out(acc, out)
            state = make_transition(bg, spec, params, state, planes, kprop,
                                    kacc)
            return (state, ct_e16, ct_s16, acc), (out if collect else {},
                                                  log)

        ct16 = (jnp.zeros((c, n), jnp.int16), jnp.zeros((c, n), jnp.int16))
        (loop_state, ct_e16, ct_s16, acc), (outs, logs) = jax.lax.scan(
            body, (loop_state, *ct16, acc), None, length=chunk)
        big["cut_times_e"] = big["cut_times_e"] + ct_e16
        big["cut_times_s"] = big["cut_times_s"] + ct_s16

    if spec.parity_metrics:
        big["part_sum"], big["last_flipped"], big["num_flips"] = \
            apply_flip_log(big["part_sum"], big["last_flipped"],
                           big["num_flips"], logs["f"], logs["s"], t0)
    state = loop_state.replace(**big)
    if acc is not None:
        return state, outs, acc
    return state, outs


@functools.partial(jax.jit, static_argnames=("spec",))
def record_final(bg: BoardGraph, spec: Spec, params: StepParams,
                 state: BoardState):
    """Epilogue: complete any pending wait and record the last yield,
    without a trailing transition."""
    t0 = state.t_yield
    names = _bookkeeping_names(state)
    big = {k: getattr(state, k) for k in names}
    loop_state = state.replace(**{k: None for k in names})
    key, _, _, kwait = _split4(loop_state.key)
    loop_state = loop_state.replace(key=key)
    if bg.surgical or spec.record_interface:
        planes = _planes_stencil(bg, spec, params, loop_state)
        cur_wait = _complete_wait(spec, loop_state, planes["b_count"],
                                  kwait, bg.n_real)
        ct16 = tuple(jnp.zeros_like(big["cut_times_e"], jnp.int16)
                     for _ in _CUT_KEYS)
        loop_state, cts16, out, log = _record_stencil(
            bg, spec, params, loop_state, ct16, planes, cur_wait)
        for k, ct in zip(("cut_times_e", "cut_times_se", "cut_times_s",
                          "cut_times_sw"), cts16):
            big[k] = big[k] + ct
        if spec.parity_metrics:
            big["part_sum"], big["last_flipped"], big["num_flips"] = \
                apply_flip_log(big["part_sum"], big["last_flipped"],
                               big["num_flips"], log["f"][None],
                               log["s"][None], t0)
        return loop_state.replace(**big), out
    planes = (_planes_pair if spec.proposal == "pair" else _planes)(
        bg, spec, params, loop_state)
    cur_wait = _complete_wait(spec, loop_state, planes["b_count"], kwait,
                              bg.n)
    ct16 = (jnp.zeros_like(big["cut_times_e"], jnp.int16),
            jnp.zeros_like(big["cut_times_s"], jnp.int16))
    loop_state, ct_e16, ct_s16, out, log = _record(
        bg, spec, params, loop_state, *ct16, planes, cur_wait)
    big["cut_times_e"] = big["cut_times_e"] + ct_e16
    big["cut_times_s"] = big["cut_times_s"] + ct_s16
    if spec.parity_metrics:
        big["part_sum"], big["last_flipped"], big["num_flips"] = \
            apply_flip_log(big["part_sum"], big["last_flipped"],
                           big["num_flips"], log["f"][None], log["s"][None],
                           t0)
    return loop_state.replace(**big), out


# ---------------------------------------------------------------------------
# Init and host-side conversions
# ---------------------------------------------------------------------------

def init_board_state(graph: LatticeGraph, bg: BoardGraph,
                     assignment: np.ndarray, n_chains: int, seed: int,
                     spec: Spec, params: StepParams) -> BoardState:
    """Broadcast a node-order assignment (length n_real) onto the canvas
    (holes carry district -1, pop 0) and seed the per-chain state."""
    n = bg.n
    lowered = bg.surgical or spec.record_interface
    a_nodes = np.asarray(assignment, np.int8)
    cell_of_node = np.asarray(bg.cell_of_node)
    a0 = np.full(n, -1, np.int8)
    a0[cell_of_node] = a_nodes
    board = jnp.broadcast_to(jnp.asarray(a0), (n_chains, n))
    pops = np.bincount(a_nodes.astype(np.int64), weights=graph.pop,
                       minlength=spec.n_districts).astype(np.int32)
    dist_pop = jnp.broadcast_to(jnp.asarray(pops),
                                (n_chains, spec.n_districts))
    keys = jax.random.key_data(
        jax.random.split(jax.random.PRNGKey(seed), n_chains))
    label_values = np.asarray(params.label_values)
    part0 = np.zeros(n, np.int32)
    part0[cell_of_node] = label_values[a_nodes.astype(np.int64)]
    cut0 = int((a_nodes[graph.edges[:, 0]]
                != a_nodes[graph.edges[:, 1]]).sum())
    zplane = jnp.zeros((n_chains, n), jnp.int32)
    return BoardState(
        key=keys,
        board=board,
        dist_pop=dist_pop,
        cut_count=jnp.full(n_chains, cut0, jnp.int32),
        cur_wait=jnp.zeros(n_chains, jnp.float32),
        # the initial state's wait is sampled at the first yield via the
        # pending mechanism, matching init_state's sample_initial_wait
        wait_pending=jnp.full(n_chains, bool(spec.geom_waits)),
        cur_flip=jnp.full(n_chains, -1, jnp.int32),
        cur_sign=jnp.zeros(n_chains, jnp.int32),
        t_yield=jnp.zeros(n_chains, jnp.int32),
        move_clock=jnp.zeros(n_chains, jnp.int32),
        part_sum=jnp.broadcast_to(jnp.asarray(part0), (n_chains, n)),
        last_flipped=zplane,
        num_flips=zplane,
        cut_times_e=zplane,
        cut_times_s=zplane,
        waits_sum=jnp.zeros(n_chains, jnp.float32),
        accept_count=jnp.zeros(n_chains, jnp.int32),
        tries_sum=jnp.zeros(n_chains, jnp.int32),
        exhausted_count=jnp.zeros(n_chains, jnp.int32),
        cut_times_se=zplane if lowered else None,
        cut_times_sw=zplane if lowered else None,
    )


def edge_cut_times(graph: LatticeGraph, state: BoardState) -> np.ndarray:
    """cut_times as an (C, E) array in LatticeGraph edge order (for the
    artifact pipeline and general-path parity tests). Each edge's plane
    and cell come from the lowering's per-edge map, so holes, diagonal
    and seam edges land in the right accumulator."""
    st = stencil_for(graph)
    planes = {0: np.asarray(state.cut_times_e),
              2: np.asarray(state.cut_times_s)}
    if state.cut_times_se is not None:
        planes[1] = np.asarray(state.cut_times_se)
        planes[3] = np.asarray(state.cut_times_sw)
    c = planes[0].shape[0]
    out = np.empty((c, graph.n_edges), planes[0].dtype)
    for d in (0, 1, 2, 3):
        sel = np.asarray(st.edge_plane) == d
        if not sel.any():
            continue
        if d not in planes:
            raise ValueError("graph has diagonal edges but state has no "
                             "diagonal cut_times planes (the chunk was "
                             "not run on the lowered body)")
        out[:, sel] = planes[d][:, np.asarray(st.edge_cell)[sel]]
    return out
