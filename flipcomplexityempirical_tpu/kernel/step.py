"""The jit-compiled flip-chain transition: one yield of the reference chain.

This is the TPU replacement for the whole gerrychain hot loop (SURVEY.md
section 3.2: propose uniform boundary flip -> validate contiguity+population
-> Metropolis accept -> incremental updater refresh). Everything is O(N) or
O(max_deg) per chain with no host interaction; the runner vmaps it over a
chains axis and scans it over steps.

Semantics parity notes (each is load-bearing for replication targets):
- invalid proposals re-propose WITHOUT consuming a step (gerrychain
  MarkovChain semantics; bounded here by ``max_tries`` with telemetry).
- the literal acceptance ``base**(-dcut)`` omits the |b_nodes| reversibility
  correction exactly as grid_chain_sec11.py:171-179 does; spec.accept =
  'corrected' enables the dead-code correction of line 99.
- the geometric wait is memoized per state: rejected steps re-record the
  same sample (gerrychain updater memoization, grid_chain_sec11.py:147-148).
- on every yield the last-accepted flip node's bookkeeping is re-applied
  (num_flips/part_sum/last_flipped, grid_chain_sec11.py:396-400 — the
  reference re-increments on self-loop yields because part.flips points at
  the move that created the current state).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..graphs.lattice import DeviceGraph
from ..state import chain_state
from ..state.chain_state import ChainState
from . import contiguity


@dataclasses.dataclass(frozen=True)
class Spec:
    """Static kernel configuration (hashable; part of the jit cache key)."""

    n_districts: int = 2
    proposal: str = "bi"          # 'bi' (2-district sign flip) | 'pair'
    contiguity: str = "patch"     # 'patch' | 'exact' | 'none'
    invalid: str = "repropose"    # 'repropose' | 'selfloop'
    accept: str = "cut"           # 'cut' | 'corrected' | 'always'
    anneal: str = "none"          # 'none' | 'linear': beta follows the
                                  # reference's piecewise schedule (the
                                  # commented-out code of
                                  # grid_chain_sec11.py:88-95) instead of
                                  # the constant StepParams.beta
    frame_interface: bool = False  # boundary_condition constraint
                                   # (grid_chain_sec11.py:43-52): the outer
                                   # frame must touch >= 2 districts
    weighted_cut: bool = False    # Metropolis on boundary LENGTH
                                  # (sum of DeviceGraph.edge_len over cut
                                  # edges) instead of cut-edge count — the
                                  # geometric compactness target for real
                                  # precinct dual graphs (BASELINE config 5)
    max_tries: int = 256          # re-propose cap per step
    propose_parallel: int = 1     # candidates drawn per re-propose round:
                                  # the state is fixed across retries, so
                                  # "first valid of K iid boundary draws"
                                  # IS re-propose semantics, and K > 1
                                  # makes the (batch-synchronized)
                                  # while_loop fire only when all K miss
                                  # (~p_invalid^K per chain-step). K=1 is
                                  # best on CPU (throughput-bound); larger
                                  # K trades duplicate draw work for fewer
                                  # whole-batch loop iterations on TPU
    record_interface: bool = False  # slope/angle wall metrics
    parity_metrics: bool = True   # reference-exact accumulator quirks
    geom_waits: bool = True       # sample geometric waiting times
    record_assignment_bits: bool = False  # pack the assignment to uint32
                                          # per yield at ceil(log2(k))
                                          # bits/node (graphs with
                                          # N*bits <= 32; exact-
                                          # distribution tests)
    nobacktrack: bool = False     # exclude the last-flipped node from the
                                  # 'bi' boundary draw (the non-backtracking
                                  # proposal of arxiv 1204.4140) unless it
                                  # is the sole boundary node; general
                                  # kernel only (board.supports gates it)
    lazy_uniform: bool = False    # emit a per-yield importance weight
                                  # 1 + cur_wait (the lazy chain's holding
                                  # time, riding the geometric waiting-time
                                  # machinery) under history key 'weight'


@struct.dataclass
class StepParams:
    """Per-chain runtime parameters (a pytree; leading chains axis under
    vmap for everything except label_values)."""

    log_base: jnp.ndarray   # f32 scalar: log of the Metropolis base
    beta: jnp.ndarray       # f32 scalar: inverse-temperature multiplier
    pop_lo: jnp.ndarray     # f32 scalar: district population lower bound
    pop_hi: jnp.ndarray     # f32 scalar: upper bound
    label_values: jnp.ndarray  # i32[K]: district -> reference +1/-1 label
    # Spec.anneal == 'linear' schedule constants (grid_chain_sec11.py:88-95:
    # beta = 0 until t0, then (t - t0)/ramp, capped at beta_max). Replicated
    # across chains; ignored unless annealing is on.
    anneal_t0: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.float32(100000.0))
    anneal_ramp: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.float32(100000.0))
    anneal_beta_max: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.float32(3.0))

    @classmethod
    def vmap_axes(cls):
        return cls(log_base=0, beta=0, pop_lo=0, pop_hi=0, label_values=None,
                   anneal_t0=None, anneal_ramp=None, anneal_beta_max=None)


def make_params(base, pop_lo, pop_hi, label_values, beta=1.0, n_chains=None,
                anneal_t0=100000.0, anneal_ramp=100000.0,
                anneal_beta_max=3.0):
    """Broadcast scalars to per-chain arrays when n_chains is given."""
    def rep(x):
        x = jnp.asarray(x, jnp.float32)
        if n_chains is not None and x.ndim == 0:
            x = jnp.broadcast_to(x, (n_chains,))
        return x
    return StepParams(
        log_base=rep(jnp.log(jnp.asarray(base, jnp.float32))),
        beta=rep(beta), pop_lo=rep(pop_lo), pop_hi=rep(pop_hi),
        label_values=jnp.asarray(label_values, jnp.int32),
        anneal_t0=jnp.float32(anneal_t0),
        anneal_ramp=jnp.float32(anneal_ramp),
        anneal_beta_max=jnp.float32(anneal_beta_max))


def effective_beta(spec: Spec, params: StepParams, state: ChainState):
    """Inverse temperature for the current proposal: constant, or the
    reference's piecewise-linear annealing schedule
    (grid_chain_sec11.py:88-95: 0 until t0, (t-t0)/ramp, capped).

    The schedule clock is the reference's ``step_num`` updater, which
    advances only on ACCEPTED moves (a rejected step re-yields the parent,
    grid_chain_sec11.py:282-289); the proposed child's step_num is one past
    the accepts so far — NOT the yield counter, which also counts
    rejections."""
    if spec.anneal == "none":
        return params.beta
    if spec.anneal == "linear":
        t = (state.move_clock + 1).astype(jnp.float32)
        return jnp.clip((t - params.anneal_t0) / params.anneal_ramp,
                        0.0, params.anneal_beta_max)
    raise ValueError(f"anneal mode {spec.anneal!r}")


def geom_denom_finite(n_nodes: int, k: int) -> bool:
    """True iff the literal wait denominator n**k - 1 survives the f32
    cast. Past that point p underflows to 0 and every wait silently
    becomes infinite, diverging from the reference's float64 geom_wait —
    the single guard shared by sample_geom_minus1 and the fast-path gates
    (board.supports, bitboard.supported_pair). Compared in log space:
    float(n)**k itself would raise OverflowError past 1e308."""
    if n_nodes <= 1:
        return True
    return k * math.log(float(n_nodes)) < math.log(3.4028235e38)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def sample_geom_minus1(key, b_count, n_nodes: int, k: int):
    """The reference waiting-time sample (grid_chain_sec11.py:147-148):
    Geometric(p) - 1 with p = |b_nodes| / (n_nodes**k - 1), via inverse CDF.

    Large-k configs whose denominator fails ``geom_denom_finite`` must
    disable ``Spec.geom_waits`` (their waits exceed f32/int64 range, so
    no backend could represent them anyway).
    """
    if not geom_denom_finite(n_nodes, k):
        raise ValueError(
            f"geom_waits: denominator n**k - 1 = {n_nodes}**{k} - 1 "
            f"overflows float32; disable Spec.geom_waits for this config "
            f"(its waits are not representable)")
    denom = jnp.float32(float(n_nodes) ** k - 1.0)
    p = b_count.astype(jnp.float32) / denom
    u = jnp.maximum(jax.random.uniform(key), jnp.float32(1e-12))
    w = jnp.floor(jnp.log(u) / jnp.log1p(-p))
    return jnp.maximum(w, 0.0).astype(jnp.float32)


def _select_nth_true(mask, m):
    """Index of the (m+1)-th True element of a boolean vector (prefix-sum
    selection). Returns 0 when mask is empty — callers must check
    mask[idx]."""
    c = jnp.cumsum(mask.astype(jnp.int32))
    return jnp.argmax(c > m).astype(jnp.int32)


def _sample_bi(key, state: ChainState, nobacktrack: bool = False):
    """Uniform over boundary nodes, flip to the other district
    (grid_chain_sec11.py:132-145). One uniform + prefix-sum selection —
    NOT a per-node Gumbel/uniform draw, which would cost N PRNG evaluations
    per proposal (the dominant kernel cost at N=4096).

    ``nobacktrack`` removes the last-flipped node from the draw (the
    non-backtracking proposal of arxiv 1204.4140) unless it is the SOLE
    boundary node — the walk must always have a move."""
    b_mask = state.cut_deg > 0
    bc = state.b_count
    if nobacktrack:
        f = state.cur_flip_node
        fi = jnp.maximum(f, 0)
        excl = (f >= 0) & b_mask[fi] & (bc > 1)
        b_mask = b_mask & ~((jnp.arange(b_mask.shape[0]) == fi) & excl)
        bc = bc - excl.astype(bc.dtype)
    u = jax.random.uniform(key)
    m = jnp.minimum((u * bc.astype(jnp.float32)).astype(jnp.int32),
                    jnp.maximum(bc - 1, 0))
    v = _select_nth_true(b_mask, m)
    d_from = state.assignment[v].astype(jnp.int32)
    return v, 1 - d_from, b_mask[v]


def _sample_pair(key, dg: DeviceGraph, state: ChainState, k: int):
    """Uniform over distinct (boundary node, neighboring district) pairs
    (grid_chain_sec11.py:117-130, the k-district move set). One uniform +
    prefix-sum selection over the flattened (N, K) pair mask."""
    a = state.assignment.astype(jnp.int32)
    pair_mask = chain_state.pair_move_mask(dg, a, k).reshape(-1)
    c = jnp.cumsum(pair_mask.astype(jnp.int32))
    total = c[-1]
    u = jax.random.uniform(key)
    m = jnp.minimum((u * total.astype(jnp.float32)).astype(jnp.int32),
                    jnp.maximum(total - 1, 0))
    idx = jnp.argmax(c > m)
    v = (idx // k).astype(jnp.int32)
    d_to = (idx % k).astype(jnp.int32)
    return v, d_to, pair_mask[idx]


def _frame_counts(dg: DeviceGraph, spec: Spec, state: ChainState):
    """Per-district counts of outer-frame nodes for the current assignment
    (loop-invariant across re-propose tries; computed once per step, over
    the O(sqrt N) static frame index set only)."""
    a_f = state.assignment[dg.frame_idx].astype(jnp.int32)
    return jnp.zeros(spec.n_districts, jnp.int32).at[a_f].add(1)


def _validate_parts(dg: DeviceGraph, spec: Spec, params: StepParams,
                    state: ChainState, v, d_to, sampled_ok,
                    frame_counts=None):
    """Component predicates of proposal validation for a tentative flip
    of v to d_to: ``(sampled_eff, pop_ok, conn_ok)``. ``sampled_eff`` is
    "the draw hit a real boundary move"; ``conn_ok`` folds in the frame-
    interface constraint (its failures count as disconnects in the
    reject taxonomy: both are connectivity-shape vetoes). The proposal
    is valid iff all three hold — exposed separately so the reject-
    reason counters can attribute each invalid draw."""
    d_from = state.assignment[v].astype(jnp.int32)
    popv = dg.pop[v]
    pop_from_new = (state.dist_pop[d_from] - popv).astype(jnp.float32)
    pop_to_new = (state.dist_pop[d_to] + popv).astype(jnp.float32)
    sampled_eff = sampled_ok & (d_to != d_from)
    pop_ok = (pop_from_new >= params.pop_lo) & (pop_to_new <= params.pop_hi)
    conn = contiguity.check(dg, state.assignment, v, d_from, spec.contiguity)
    if spec.frame_interface:
        # boundary_condition (grid_chain_sec11.py:43-52): after the flip,
        # the outer-frame nodes must not all lie in one district. Post-flip
        # per-district frame counts = current counts adjusted for v.
        vf = dg.frame_mask[v].astype(jnp.int32)
        counts = frame_counts.at[d_from].add(-vf).at[d_to].add(vf)
        conn &= counts.max() < dg.frame_idx.shape[0]
    return sampled_eff, pop_ok, conn


def _validate(dg: DeviceGraph, spec: Spec, params: StepParams,
              state: ChainState, v, d_to, sampled_ok, frame_counts=None):
    """Population bounds + contiguity for a tentative flip of v to d_to."""
    sampled_eff, pop_ok, conn = _validate_parts(
        dg, spec, params, state, v, d_to, sampled_ok, frame_counts)
    return sampled_eff & pop_ok & conn


def _reject_reason(sampled_eff, pop_ok, valid):
    """int32[3] one-hot of why an invalid draw died, priority-ordered to
    match the validation short-circuit: [non-boundary, pop-bound,
    disconnect]. All-zero when the draw is valid."""
    reason = jnp.where(~sampled_eff, 0, jnp.where(~pop_ok, 1, 2))
    return ((jnp.arange(3) == reason) & ~valid).astype(jnp.int32)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def propose(dg: DeviceGraph, spec: Spec, params: StepParams,
            state: ChainState, key, count: bool = False):
    """Draw a proposal per the invalid-move policy. Returns
    (v, d_to, valid, tries), plus a trailing int32[3] reject-reason
    vector ([non-boundary, pop, disconnect] over this step's invalid
    draws) when ``count`` — the trace-time flag the runners derive from
    ``state.reject_count is not None``. With ``count=False`` the traced
    graph (and the PRNG stream either way: counting draws nothing) is
    exactly the historical one."""
    k = spec.n_districts
    frame_counts = _frame_counts(dg, spec, state) if spec.frame_interface \
        else None

    def draw(key):
        if spec.proposal == "bi":
            if k != 2:
                raise ValueError("proposal 'bi' requires n_districts == 2")
            v, d_to, ok = _sample_bi(key, state,
                                     nobacktrack=spec.nobacktrack)
        elif spec.proposal == "pair":
            if spec.nobacktrack:
                raise ValueError("nobacktrack requires proposal 'bi' "
                                 "(the pair walk has no single excluded "
                                 "reverse move)")
            v, d_to, ok = _sample_pair(key, dg, state, k)
        else:
            raise ValueError(f"proposal {spec.proposal!r}")
        if not count:
            return v, d_to, _validate(dg, spec, params, state, v, d_to, ok,
                                      frame_counts)
        sampled_eff, pop_ok, conn = _validate_parts(
            dg, spec, params, state, v, d_to, ok, frame_counts)
        valid = sampled_eff & pop_ok & conn
        return v, d_to, valid, _reject_reason(sampled_eff, pop_ok, valid)

    zero3 = jnp.zeros(3, jnp.int32)

    if spec.invalid == "selfloop":
        if count:
            v, d_to, valid, rej = draw(key)
            return v, d_to, valid, jnp.int32(1), rej
        v, d_to, valid = draw(key)
        return v, d_to, valid, jnp.int32(1)

    # round 1 (propose_parallel > 1): K iid candidates validated in
    # parallel, first valid wins. Correctness: the state is constant
    # across re-proposals, so this is exactly "re-propose until valid"
    # with the loop unrolled K-wide; the while_loop below only fires when
    # all K candidates are invalid. propose_parallel == 1 keeps the
    # plain loop (single draw() instantiation, unchanged PRNG stream).
    kp = spec.propose_parallel
    if not 1 <= kp <= spec.max_tries:
        raise ValueError(f"propose_parallel {kp} must be in "
                         f"[1, max_tries={spec.max_tries}]")
    if kp > 1:
        key, kdraw = jax.random.split(key)
        if count:
            vs, d_tos, valids, rejs = jax.vmap(draw)(
                jax.random.split(kdraw, kp))
        else:
            vs, d_tos, valids = jax.vmap(draw)(jax.random.split(kdraw, kp))
        first = jnp.argmax(valids).astype(jnp.int32)
        any_valid = valids.any()
        tries0 = jnp.where(any_valid, first + 1, kp)
        init = (key, vs[first], d_tos[first], any_valid, tries0)
        if count:
            # the consumed draws are 0..tries0-1; all but a winning last
            # one are invalid, and each rejs row is already zero when
            # its draw was valid
            consumed = (jnp.arange(kp) < tries0)[:, None]
            init += (jnp.sum(rejs * consumed, axis=0, dtype=jnp.int32),)
    else:
        init = (key, jnp.int32(0), jnp.int32(0), jnp.bool_(False),
                jnp.int32(0))
        if count:
            init += (zero3,)

    def cond(carry):
        valid, tries = carry[3], carry[4]
        return (~valid) & (tries < spec.max_tries)

    if count:
        def body(carry):
            key, _, _, _, tries, rej = carry
            key, kd = jax.random.split(key)
            v, d_to, valid, r = draw(kd)
            return key, v, d_to, valid, tries + 1, rej + r

        _, v, d_to, valid, tries, rej = jax.lax.while_loop(cond, body, init)
        return v, d_to, valid, tries, rej

    def body(carry):
        key, _, _, _, tries = carry
        key, kd = jax.random.split(key)
        v, d_to, valid = draw(kd)
        return key, v, d_to, valid, tries + 1

    _, v, d_to, valid, tries = jax.lax.while_loop(cond, body, init)
    return v, d_to, valid, tries


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def transition(dg: DeviceGraph, spec: Spec, params: StepParams,
               state: ChainState) -> ChainState:
    """One chain step: propose(+retries), Metropolis-accept, commit."""
    key, kprop, kacc, kwait = jax.random.split(state.key, 4)
    count = state.reject_count is not None
    if count:
        v, d_to, valid, tries, rej3 = propose(dg, spec, params, state,
                                              kprop, count=True)
    else:
        v, d_to, valid, tries = propose(dg, spec, params, state, kprop)
        rej3 = None
    return commit(dg, spec, params, state, key, kacc, kwait,
                  v, d_to, valid, tries, rej3)


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def commit(dg: DeviceGraph, spec: Spec, params: StepParams,
           state: ChainState, key, kacc, kwait, v, d_to, valid, tries,
           rej3=None) -> ChainState:
    """Metropolis-accept + masked state commit for a drawn proposal
    (v, d_to, valid). Shared tail of every general-path transition —
    the legacy re-propose kernel above and the rejection-free dense
    kernel (kernel/dense.py) both funnel through it, which is what makes
    their acceptance/bookkeeping semantics identical by construction.
    ``rej3`` is the int32[3] pre-accept reject taxonomy (None when the
    state carries no reject_count); the Metropolis taxon is added here."""
    k = spec.n_districts
    count = state.reject_count is not None

    d_from = state.assignment[v].astype(jnp.int32)
    nb = dg.nbr[v]                       # (D,), pad = v
    nbm = dg.nbr_mask[v]
    eids = dg.nbr_edge[v]
    na = state.assignment[nb].astype(jnp.int32)
    old_cut = (na != d_from) & nbm
    new_cut = (na != d_to) & nbm
    delta = new_cut.astype(jnp.int32) - old_cut.astype(jnp.int32)
    dcut = delta.sum()

    if spec.proposal == "pair":
        # incremental distinct-pair |b_nodes| (the pair walk's geom_wait
        # input): only v's row and its true neighbors' rows of the
        # (N, K) pair mask can change when v flips — O(D^2 K), not a
        # full recount
        aff = jnp.concatenate([v[None], nb])
        wrow = jnp.concatenate([jnp.ones((1,), bool), nbm])
        a_tent = state.assignment.at[v].set(
            d_to.astype(state.assignment.dtype))

        def pair_rows(a_arr):
            rows = chain_state.pair_move_mask(
                dg, a_arr.astype(jnp.int32), k, nodes=aff)
            return jnp.sum(rows & wrow[:, None], dtype=jnp.int32)

        d_pairs = pair_rows(a_tent) - pair_rows(state.assignment)

    # Metropolis in log space: u < base**(beta * -dcut) [* b ratio]
    beta = effective_beta(spec, params, state)
    if spec.weighted_cut:
        dscore = jnp.sum(jnp.where(
            nbm, delta.astype(jnp.float32) * dg.edge_len[eids], 0.0))
    else:
        dscore = dcut.astype(jnp.float32)
    log_bound = -beta * dscore * params.log_base
    if spec.accept == "corrected":
        # reversibility ratio |b(parent)|/|b(child)| in the move set's
        # own units: boundary nodes for 'bi', distinct pairs for 'pair'
        if spec.proposal == "pair":
            b_new = state.b_count + d_pairs
        else:
            cut_deg_new = state.cut_deg.astype(jnp.int32)
            cut_deg_new = cut_deg_new.at[nb].add(jnp.where(nbm, delta, 0))
            cut_deg_new = cut_deg_new.at[v].set(new_cut.sum())
            b_new = (cut_deg_new > 0).sum()
        log_bound += (jnp.log(state.b_count.astype(jnp.float32))
                      - jnp.log(jnp.maximum(b_new, 1).astype(jnp.float32)))
    if spec.accept == "always":
        accept = valid
    else:
        logu = jnp.log(jnp.maximum(jax.random.uniform(kacc),
                                   jnp.float32(1e-12)))
        accept = valid & (logu < log_bound)

    # commit (masked): assignment, cut mask, incident counts, tallies
    a_new = state.assignment.at[v].set(
        jnp.where(accept, d_to, d_from).astype(state.assignment.dtype))
    upd = jnp.where(accept & nbm, delta, 0)
    cut = state.cut.at[eids].add(upd.astype(state.cut.dtype))
    cut_deg = state.cut_deg.at[nb].add(upd.astype(state.cut_deg.dtype))
    cut_deg = cut_deg.at[v].set(
        jnp.where(accept, new_cut.sum(), state.cut_deg[v].astype(jnp.int32))
        .astype(state.cut_deg.dtype))
    popv = dg.pop[v] * accept.astype(jnp.int32)
    dist_pop = state.dist_pop.at[d_from].add(-popv).at[d_to].add(popv)
    cut_count = state.cut_count + jnp.where(accept, dcut, 0)
    if spec.proposal == "pair":
        b_count = state.b_count + jnp.where(accept, d_pairs, 0)
    else:
        b_count = (cut_deg > 0).sum().astype(jnp.int32)

    if spec.geom_waits:
        wait_new = sample_geom_minus1(kwait, b_count, dg.n_nodes, k)
        cur_wait = jnp.where(accept, wait_new, state.cur_wait)
    else:
        cur_wait = state.cur_wait
    cur_flip_node = jnp.where(accept, v, state.cur_flip_node)

    extra = {}
    if count:
        # fourth taxon: a valid proposal the Metropolis coin rejected.
        # Invariant (tested): reject_count.sum() + accept_count ==
        # tries_sum — every draw is accepted or attributed a reason.
        met = (valid & ~accept).astype(jnp.int32)
        extra["reject_count"] = state.reject_count + jnp.concatenate(
            [rej3, met[None]])
    return state.replace(
        key=key, assignment=a_new, cut=cut, cut_deg=cut_deg,
        dist_pop=dist_pop, cut_count=cut_count, b_count=b_count,
        cur_wait=cur_wait, cur_flip_node=cur_flip_node,
        move_clock=state.move_clock + accept.astype(jnp.int32),
        accept_count=state.accept_count + accept.astype(jnp.int32),
        tries_sum=state.tries_sum + tries,
        exhausted_count=state.exhausted_count + (~valid).astype(jnp.int32),
        **extra,
    )


# graftlint: traced  (entered via cross-module jit/vmap/scan)
def record(dg: DeviceGraph, spec: Spec, params: StepParams,
           state: ChainState):
    """One yield of the measurement loop (grid_chain_sec11.py:366-402):
    returns (state-with-updated-accumulators, per-step outputs dict)."""
    t = state.t_yield
    out = {
        "cut_count": state.cut_count,
        "b_count": state.b_count,
        "wait": state.cur_wait,
        "accepts": state.accept_count,
    }
    if spec.lazy_uniform:
        # lazy-uniform reweighting: this yield stands for 1 + wait
        # consecutive visits of the lazy chain, so downstream estimators
        # weight it by the holding time
        out["weight"] = 1.0 + state.cur_wait

    cut_times = state.cut_times + state.cut.astype(jnp.int32)
    waits_sum = state.waits_sum + state.cur_wait

    f = state.cur_flip_node
    has_flip = f >= 0
    fi = jnp.maximum(f, 0)
    if spec.parity_metrics:
        sign = params.label_values[state.assignment[fi].astype(jnp.int32)]
        dt = t - state.last_flipped[fi]
        part_sum = state.part_sum.at[fi].add(
            jnp.where(has_flip, -sign * dt, 0))
        last_flipped = state.last_flipped.at[fi].set(
            jnp.where(has_flip, t, state.last_flipped[fi]))
        num_flips = state.num_flips.at[fi].add(
            jnp.where(has_flip, 1, 0))
    else:
        part_sum, last_flipped, num_flips = (
            state.part_sum, state.last_flipped, state.num_flips)

    if spec.record_interface:
        slope, angle = interface_metrics(dg, state.cut)
        out["slope"] = slope
        out["angle"] = angle

    if spec.record_assignment_bits:
        bits_per = max(1, (spec.n_districts - 1).bit_length())
        if dg.n_nodes * bits_per > 32:
            raise ValueError("record_assignment_bits needs n_nodes * "
                             "ceil(log2(k)) <= 32")
        shifts = jnp.arange(dg.n_nodes, dtype=jnp.uint32) * bits_per
        out["abits"] = jnp.sum(
            state.assignment.astype(jnp.uint32) << shifts, dtype=jnp.uint32)

    state = state.replace(
        cut_times=cut_times, waits_sum=waits_sum, part_sum=part_sum,
        last_flipped=last_flipped, num_flips=num_flips,
        t_yield=t + 1)
    return state, out


def interface_metrics(dg: DeviceGraph, cut):
    """Slope and angle of the interface endpoints, from the two wall-cut
    edges of smallest canonical index (the reference takes elements [0] and
    [1] of an arbitrarily-ordered set, grid_chain_sec11.py:371-394; the
    deterministic choice here is documented implementation-defined
    behavior). NaN when fewer than two wall-cut edges exist (the reference
    raises IndexError and dies — we keep the chain alive)."""
    e_ids = jnp.arange(dg.n_edges)
    wc = (cut > 0) & (dg.wall_id >= 0)
    first = jnp.argmax(wc)
    wc2 = wc & (e_ids != first)
    second = jnp.argmax(wc2)
    ok = wc.any() & wc2.any()

    def midpoint(e):
        pts = dg.coords[dg.edges[e]]
        return (pts[0] + pts[1]) / 2.0

    enda, endb = midpoint(first), midpoint(second)
    dxy = endb - enda
    slope = jnp.where(dxy[0] != 0, dxy[1] / jnp.where(dxy[0] != 0, dxy[0], 1.0),
                      jnp.inf)
    anga = enda - dg.center
    angb = endb - dg.center
    norm = (jnp.linalg.norm(anga) * jnp.linalg.norm(angb))
    cosang = jnp.clip(jnp.dot(anga, angb) / jnp.maximum(norm, 1e-12),
                      -1.0, 1.0)
    angle = jnp.arccos(cosang)
    nan = jnp.float32(jnp.nan)
    return (jnp.where(ok, slope, nan).astype(jnp.float32),
            jnp.where(ok, angle, nan).astype(jnp.float32))


def finalize_host(state_np, label_values, t_final, assignment=None):
    """Reference post-run finalization (grid_chain_sec11.py:416-419),
    host-side numpy: never-flipped nodes get part_sum = t * final_sign;
    lognum_flips = log(num_flips + 1). Note the reference does NOT add the
    tail segment for flipped nodes — preserved verbatim.

    ``assignment`` overrides ``state_np.assignment`` for state flavors
    that carry it under another name (the board path's ``.board``)."""
    import numpy as np

    if assignment is None:
        assignment = state_np.assignment
    sign = np.asarray(label_values)[np.asarray(assignment,
                                               dtype=np.int64)]
    part_sum = np.array(state_np.part_sum)
    never = np.array(state_np.last_flipped) == 0
    part_sum[never] = t_final * sign[never]
    lognum = np.log(np.array(state_np.num_flips) + 1.0)
    return part_sum, lognum
