"""Native ESRI shapefile I/O: no geopandas/fiona/shapely dependency.

BASELINE config 5 names "real precinct dual graph (small-state
shapefile)" as a capability; the reference's geopandas import is a dead
breadcrumb (grid_chain_sec11.py:4). This environment has no geo stack
and no network, so the capability is supplied natively: a pure
numpy/struct reader for the two files a precinct map needs — the ``.shp``
geometry file (Polygon/PolygonZ/PolygonM records) and its ``.dbf``
dBase-III attribute table — returning a GeoJSON-shaped FeatureCollection
dict that ``dualgraph.from_geojson`` ingests unchanged. A matching
writer exists so the round trip (write -> read -> dual graph) is testable
hermetically, and so synthetic states can be exported for external GIS
tools.

Format notes (ESRI Shapefile Technical Description, July 1998):
- .shp = 100-byte header (big-endian file code 9994 + length, little-
  endian version 1000 + shape type + 8-double bbox), then records of
  [BE record number, BE content length (16-bit words)] + [LE shape type,
  bbox, numParts, numPoints, part offsets, xy doubles].
- .dbf = dBase III: 32-byte header (0x03, date, LE record count, header
  size, record size), 32-byte field descriptors (11-byte name, type C/N/F,
  length, decimal count), 0x0D terminator; records are fixed-width ASCII
  prefixed by a deletion flag; 0x1A terminates the file.
- Ring orientation: .shp outer rings are clockwise, holes counter-
  clockwise — the signed-shoelace convention ``from_geojson`` already
  uses to subtract hole areas, so rings pass through untouched.
"""

from __future__ import annotations

import os
import struct

import numpy as np

SHAPE_NULL = 0
SHAPE_POLYGON = 5
SHAPE_POLYGONZ = 15
SHAPE_POLYGONM = 25
_POLYGON_TYPES = (SHAPE_POLYGON, SHAPE_POLYGONZ, SHAPE_POLYGONM)


def _read_dbf(path: str) -> list:
    """Parse a dBase III table into a list of property dicts. Character
    fields come back str, numeric fields int/float, blanks None."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < 33:
        raise ValueError(f"{path}: truncated dBase file ({len(buf)} bytes)")
    n_rec = struct.unpack_from("<I", buf, 4)[0]
    hdr_size, rec_size = struct.unpack_from("<HH", buf, 8)
    if hdr_size > len(buf) or hdr_size < 33:
        raise ValueError(f"{path}: dBase header size {hdr_size} "
                         f"inconsistent with file length {len(buf)}")
    if rec_size < 1:  # spec minimum: the deletion flag byte
        raise ValueError(f"{path}: dBase record size {rec_size} corrupt")
    if hdr_size + n_rec * rec_size > len(buf) + 1:  # +1: some writers
        raise ValueError(                           # omit the 0x1A EOF
            f"{path}: dBase table truncated ({n_rec} records of "
            f"{rec_size} bytes declared, {len(buf) - hdr_size} present)")
    fields = []
    off = 32
    while off < hdr_size - 1 and buf[off] != 0x0D:
        raw_name = buf[off:off + 11].split(b"\x00", 1)[0]
        ftype = chr(buf[off + 11])
        flen = buf[off + 16]
        fdec = buf[off + 17]
        fields.append((raw_name.decode("ascii", "replace"), ftype,
                       flen, fdec))
        off += 32
    recs = []
    pos = hdr_size
    for _ in range(n_rec):
        if pos + rec_size > len(buf):
            break
        rec = buf[pos:pos + rec_size]
        pos += rec_size
        # NOTE: rows soft-deleted by dBase tools (flag '*') are parsed
        # like live rows — .shp geometry has no deletion concept, so
        # dropping them here would break the mandatory 1:1 shp/dbf row
        # alignment (the convention shapefile readers follow)
        props = {}
        p = 1
        for fname, ftype, flen, fdec in fields:
            cell = rec[p:p + flen]
            p += flen
            text = cell.decode("ascii", "replace").strip()
            if ftype in ("N", "F"):
                if not text:
                    props[fname] = None
                elif ftype == "N" and fdec == 0 and "." not in text:
                    props[fname] = int(text)
                else:
                    props[fname] = float(text)
            elif ftype == "L":
                props[fname] = (True if text in ("T", "t", "Y", "y")
                                else False if text in ("F", "f", "N", "n")
                                else None)
            else:                   # C, D, ... -> raw text
                props[fname] = text
        recs.append(props)
    return recs


def _read_shp(path: str) -> list:
    """Parse polygon records of a .shp into GeoJSON-style geometry dicts
    (one "Polygon" whose coordinate list holds ALL parts/rings — exactly
    what from_geojson._rings iterates). Null shapes come back None."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < 100:
        raise ValueError(f"{path}: truncated shapefile "
                         f"({len(buf)} bytes < 100-byte header)")
    file_code, = struct.unpack_from(">i", buf, 0)
    if file_code != 9994:
        raise ValueError(f"{path}: not a shapefile (file code {file_code})")
    file_len_words, = struct.unpack_from(">i", buf, 24)
    if 2 * file_len_words > len(buf):
        raise ValueError(
            f"{path}: truncated shapefile (header declares "
            f"{2 * file_len_words} bytes, {len(buf)} present)")
    version, global_type = struct.unpack_from("<ii", buf, 28)
    if version != 1000:
        raise ValueError(f"{path}: unsupported shapefile version {version}")
    if global_type not in _POLYGON_TYPES and global_type != SHAPE_NULL:
        raise ValueError(
            f"{path}: shape type {global_type} is not a polygon type; "
            "precinct dual graphs need Polygon (5/15/25) shapefiles")
    end = min(len(buf), 2 * file_len_words)
    geoms = []
    pos = 100
    while pos + 8 <= end:
        _rec_no, content_words = struct.unpack_from(">ii", buf, pos)
        pos += 8
        rec_end = pos + 2 * content_words
        if rec_end > len(buf) or content_words < 2:
            raise ValueError(
                f"{path}: truncated or corrupt record at byte {pos - 8} "
                f"(content length {content_words} words, file "
                f"{len(buf)} bytes)")
        stype, = struct.unpack_from("<i", buf, pos)
        if stype == SHAPE_NULL:
            geoms.append(None)
        elif stype in _POLYGON_TYPES:
            n_parts, n_points = struct.unpack_from("<ii", buf, pos + 36)
            parts = np.frombuffer(buf, "<i4", n_parts, pos + 44)
            pts = np.frombuffer(buf, "<f8", 2 * n_points,
                                pos + 44 + 4 * n_parts)
            pts = pts.reshape(n_points, 2)
            bounds = np.append(parts, n_points)
            rings = [pts[bounds[i]:bounds[i + 1]].tolist()
                     for i in range(n_parts)]
            geoms.append({"type": "Polygon", "coordinates": rings})
        else:
            raise ValueError(f"{path}: record shape type {stype} "
                             "unsupported (polygon types only)")
        pos = rec_end
    return geoms


def read_shapefile(path: str) -> dict:
    """Read ``<path>.shp`` (+ sibling ``.dbf`` when present) into a
    GeoJSON FeatureCollection dict. ``path`` may include or omit the
    .shp suffix. Null-shape records are dropped (with their attribute
    rows kept aligned)."""
    base, ext = os.path.splitext(path)
    shp = path if ext.lower() == ".shp" else path + ".shp"
    base = base if ext.lower() == ".shp" else path
    geoms = _read_shp(shp)
    dbf = base + ".dbf"
    props = _read_dbf(dbf) if os.path.exists(dbf) else [{} for _ in geoms]
    if len(props) != len(geoms):
        raise ValueError(
            f"{shp}: {len(geoms)} shapes but {len(props)} attribute rows "
            f"in {dbf} — the sidecar does not belong to this .shp")
    feats = [{"type": "Feature", "properties": p, "geometry": g}
             for g, p in zip(geoms, props) if g is not None]
    return {"type": "FeatureCollection", "features": feats}


def _ring_signed_area(ring: np.ndarray) -> float:
    x, y = ring[:, 0], ring[:, 1]
    return float((x * np.roll(y, -1) - np.roll(x, -1) * y).sum() / 2.0)


def write_shapefile(path: str, feature_collection: dict) -> None:
    """Write a GeoJSON FeatureCollection of Polygon/MultiPolygon features
    as ``<path>.shp`` + ``.shx`` + ``.dbf``. First rings are emitted
    clockwise and subsequent (hole) rings counter-clockwise per the spec.
    Attribute columns are the union of feature property keys: bool -> L
    (logical), int -> N, float -> N with 6 decimals, everything else
    -> C."""
    base = os.path.splitext(path)[0]
    feats = feature_collection["features"]

    shp_records = []
    for feat in feats:
        geom = feat["geometry"]
        if geom["type"] == "Polygon":
            parts_nested = [geom["coordinates"]]
        elif geom["type"] == "MultiPolygon":
            parts_nested = geom["coordinates"]
        else:
            raise ValueError(f"unsupported geometry {geom['type']!r}")
        rings = []
        for poly in parts_nested:
            for k, ring in enumerate(poly):
                r = np.asarray(ring, np.float64)
                if not np.allclose(r[0], r[-1]):
                    r = np.vstack([r, r[:1]])
                want_cw = (k == 0)
                if (_ring_signed_area(r) > 0) == want_cw:
                    r = r[::-1]   # shoelace>0 is CCW; outer must be CW
                rings.append(r)
        shp_records.append(rings)

    # --- .shp + .shx ---
    rec_payloads = []
    for rings in shp_records:
        n_points = sum(len(r) for r in rings)
        all_pts = np.vstack(rings)
        bbox = (all_pts[:, 0].min(), all_pts[:, 1].min(),
                all_pts[:, 0].max(), all_pts[:, 1].max())
        parts = np.cumsum([0] + [len(r) for r in rings[:-1]]).astype("<i4")
        payload = struct.pack("<i4d2i", SHAPE_POLYGON, *bbox,
                              len(rings), n_points)
        payload += parts.tobytes() + all_pts.astype("<f8").tobytes()
        rec_payloads.append(payload)

    gx = np.vstack([np.vstack(r) for r in shp_records])
    gbox = (gx[:, 0].min(), gx[:, 1].min(), gx[:, 0].max(), gx[:, 1].max())
    shp_len = 100 + sum(8 + len(p) for p in rec_payloads)
    header = struct.pack(">i5ii", 9994, 0, 0, 0, 0, 0, shp_len // 2)
    header += struct.pack("<ii", 1000, SHAPE_POLYGON)
    header += struct.pack("<8d", *gbox, 0, 0, 0, 0)
    with open(base + ".shp", "wb") as f:
        f.write(header)
        for i, payload in enumerate(rec_payloads):
            f.write(struct.pack(">ii", i + 1, len(payload) // 2))
            f.write(payload)
    shx_len = 100 + 8 * len(rec_payloads)
    with open(base + ".shx", "wb") as f:
        f.write(header[:24] + struct.pack(">i", shx_len // 2) + header[28:])
        off = 100
        for payload in rec_payloads:
            f.write(struct.pack(">ii", off // 2, len(payload) // 2))
            off += 8 + len(payload)

    # --- .dbf ---
    keys = []
    for feat in feats:
        for k in (feat.get("properties") or {}):
            if k not in keys:
                keys.append(k)
    cols = []
    for k in keys:
        vals = [(feat.get("properties") or {}).get(k) for feat in feats]
        # bool is an int subclass: test it FIRST or True lands in an
        # N column as the unparseable text 'True'
        if all(isinstance(v, (bool, np.bool_)) or v is None for v in vals):
            cols.append((k, "L", 1, 0))
        elif all(not isinstance(v, (bool, np.bool_))
                 and (isinstance(v, (int, np.integer)) or v is None)
                 for v in vals):
            width = max([len(str(v)) for v in vals if v is not None] + [1])
            cols.append((k, "N", min(max(width, 4), 18), 0))
        elif all(not isinstance(v, (bool, np.bool_))
                 and (isinstance(v, (int, float, np.number)) or v is None)
                 for v in vals):
            cols.append((k, "N", 18, 6))
        else:
            width = max([len(str(v)) for v in vals if v is not None] + [1])
            cols.append((k, "C", min(max(width, 1), 254), 0))
    rec_size = 1 + sum(c[2] for c in cols)
    hdr_size = 32 + 32 * len(cols) + 1
    with open(base + ".dbf", "wb") as f:
        f.write(struct.pack("<B3BIHH20x", 0x03, 26, 7, 30, len(feats),
                            hdr_size, rec_size))
        for name, ftype, flen, fdec in cols:
            f.write(struct.pack("<11sc4xBB14x",
                                name.encode("ascii")[:10],
                                ftype.encode("ascii"), flen, fdec))
        f.write(b"\x0d")
        for feat in feats:
            props = feat.get("properties") or {}
            f.write(b" ")
            for name, ftype, flen, fdec in cols:
                v = props.get(name)
                if v is None:
                    cell = "?" if ftype == "L" else ""
                elif ftype == "L":
                    cell = "T" if v else "F"
                elif ftype == "N" and fdec:
                    cell = f"{float(v):.{fdec}f}"
                else:
                    cell = str(v)
                cell = cell[:flen]
                pad = (cell.rjust(flen) if ftype == "N"
                       else cell.ljust(flen))
                f.write(pad.encode("ascii", "replace"))
        f.write(b"\x1a")
