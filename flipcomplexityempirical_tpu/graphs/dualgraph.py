"""Real-geometry dual graphs: precinct polygons -> LatticeGraph.

BASELINE config 5 ("real precinct dual graph (small-state shapefile), k
districts with compactness score"). The reference imports geopandas but
never uses it (grid_chain_sec11.py:4, a dead capability breadcrumb); this
module supplies the live capability without depending on it:

- ``from_geojson``: pure-Python importer for a GeoJSON FeatureCollection of
  Polygon/MultiPolygon precincts. Adjacency is computed from shared
  geometry: rook = the polygons share a full boundary segment, queen = they
  share at least a vertex. Per-node area (shoelace), perimeter, centroid
  and per-adjacent-pair shared-boundary length are attached so the
  compactness scores (stats/compactness.py) and boundary-length-weighted
  chain targets work on top.
- ``from_shapefile``: thin gated wrapper that uses geopandas when it is
  installed to convert a .shp to the same feature-dict form.
- ``synthetic_precincts``: a jittered-quadrilateral "state" generator used
  by tests and demos, so the geometry path is exercised without shipping
  shapefile fixtures.

Coordinates are rounded to ``snap`` decimals when keying shared geometry —
the standard tolerance trick for topologically clean precinct files.
"""

from __future__ import annotations

import dataclasses
import json
from collections import defaultdict
from typing import Optional

import numpy as np

from .lattice import LatticeGraph, build_lattice


@dataclasses.dataclass(frozen=True)
class GeoAttributes:
    """Per-node / per-edge geometry riding along with a dual LatticeGraph.

    ``shared_perim[e]`` is the total boundary length shared by the two
    endpoint precincts of graph edge e (edge order matches graph.edges);
    ``exterior_perim[v]`` is the part of v's perimeter shared with no other
    precinct (the map's outer boundary or holes)."""

    area: np.ndarray            # f64[N]
    perimeter: np.ndarray       # f64[N]
    centroid: np.ndarray        # f64[N, 2]
    shared_perim: np.ndarray    # f64[E]
    exterior_perim: np.ndarray  # f64[N]


def _rings(geometry: dict):
    """Yield the exterior + hole rings of a Polygon/MultiPolygon as
    (closed) coordinate lists."""
    t = geometry["type"]
    if t == "Polygon":
        for ring in geometry["coordinates"]:
            yield ring
    elif t == "MultiPolygon":
        for poly in geometry["coordinates"]:
            for ring in poly:
                yield ring
    else:
        raise ValueError(f"unsupported geometry type {t!r}")


def _ring_area_centroid(ring: np.ndarray):
    """Signed shoelace area and area-weighted centroid of one ring."""
    x, y = ring[:, 0], ring[:, 1]
    x1, y1 = np.roll(x, -1), np.roll(y, -1)
    cross = x * y1 - x1 * y
    a = cross.sum() / 2.0
    if a == 0:
        return 0.0, ring[:-1].mean(axis=0)
    cx = ((x + x1) * cross).sum() / (6.0 * a)
    cy = ((y + y1) * cross).sum() / (6.0 * a)
    return a, np.array([cx, cy])


def from_geojson(src, *, pop_property: Optional[str] = None,
                 name_property: Optional[str] = None,
                 adjacency: str = "rook", snap: int = 9,
                 pop_scale: float = 1.0, name: str = "dualgraph"):
    """Build (LatticeGraph, GeoAttributes) from a GeoJSON FeatureCollection.

    ``src`` is a path, a JSON string, or an already-parsed dict.
    ``pop_property`` names the feature property holding population
    (default: population 1 per precinct, like the reference's unit weights,
    grid_chain_sec11.py:218); ``pop_scale`` divides it (graph populations
    are integers). ``adjacency`` is 'rook' (shared boundary segment) or
    'queen' (shared vertex).
    """
    if isinstance(src, dict):
        gj = src
    elif isinstance(src, str) and src.lstrip().startswith("{"):
        gj = json.loads(src)
    else:
        with open(src) as f:
            gj = json.load(f)
    feats = gj["features"]
    n = len(feats)

    areas = np.zeros(n)
    perims = np.zeros(n)
    cents = np.zeros((n, 2))
    pops = np.ones(n, dtype=np.int64)
    labels = []

    # segment/vertex keys -> owning precincts, with lengths for segments
    seg_owner: dict = defaultdict(list)   # seg key -> [(node, length)]
    vert_owner: dict = defaultdict(set)   # vertex key -> {nodes}

    for i, feat in enumerate(feats):
        props = feat.get("properties") or {}
        if name_property and name_property in props:
            labels.append(props[name_property])
        else:
            labels.append(i)
        if pop_property:
            pops[i] = max(0, round(float(props[pop_property]) / pop_scale))
        area_i = 0.0
        cent_i = np.zeros(2)
        for ring in _rings(feat["geometry"]):
            r = np.asarray(ring, dtype=np.float64)
            if np.allclose(r[0], r[-1]):
                r_closed = r
            else:
                r_closed = np.vstack([r, r[:1]])
            a, c = _ring_area_centroid(r_closed)
            area_i += a
            cent_i += c * a
            pts = np.round(r_closed, snap)
            seglen = np.linalg.norm(np.diff(r_closed, axis=0), axis=1)
            perims[i] += seglen.sum()
            for s in range(len(pts) - 1):
                pa, pb = tuple(pts[s]), tuple(pts[s + 1])
                if pa == pb:
                    continue
                key = (pa, pb) if pa <= pb else (pb, pa)
                seg_owner[key].append((i, seglen[s]))
                vert_owner[pa].add(i)
            vert_owner[tuple(pts[-1])].add(i)
        if area_i == 0:
            raise ValueError(f"feature {labels[-1]!r} has zero area")
        areas[i] = abs(area_i)
        cents[i] = cent_i / area_i

    # rook adjacency + shared lengths from co-owned segments
    pair_len: dict = defaultdict(float)
    for key, owners in seg_owner.items():
        if len(owners) < 2:
            continue
        nodes = sorted({o for o, _ in owners})
        length = owners[0][1]
        for ai in range(len(nodes)):
            for bi in range(ai + 1, len(nodes)):
                pair_len[(nodes[ai], nodes[bi])] += length

    adj: dict = {i: set() for i in range(n)}
    if adjacency == "rook":
        for (u, v) in pair_len:
            adj[u].add(v)
            adj[v].add(u)
    elif adjacency == "queen":
        for owners in vert_owner.values():
            owners = sorted(owners)
            for ai in range(len(owners)):
                for bi in range(ai + 1, len(owners)):
                    adj[owners[ai]].add(owners[bi])
                    adj[owners[bi]].add(owners[ai])
    else:
        raise ValueError(f"adjacency {adjacency!r}")

    if len(set(labels)) != n:
        # label-keyed maps would silently collapse duplicates into one node
        from collections import Counter
        dupes = [lab for lab, c in Counter(labels).items() if c > 1][:5]
        raise ValueError(
            f"{name_property!r} values are not unique across features "
            f"(e.g. {dupes}); pass a unique name_property or None to key "
            "precincts by feature index")

    label_adj = {labels[i]: [labels[j] for j in sorted(adj[i])]
                 for i in range(n)}
    coords = {labels[i]: tuple(cents[i]) for i in range(n)}
    popd = {labels[i]: int(pops[i]) for i in range(n)}

    graph = build_lattice(
        label_adj, name=name, coords=coords, pop=popd,
        center=tuple(cents.mean(axis=0)), node_order=labels)

    # per-graph-edge shared perimeter, exterior perimeter per node
    shared = np.zeros(graph.n_edges)
    for ei in range(graph.n_edges):
        u, v = int(graph.edges[ei, 0]), int(graph.edges[ei, 1])
        shared[ei] = pair_len.get((min(u, v), max(u, v)), 0.0)
    shared_per_node = np.zeros(n)
    for (u, v), length in pair_len.items():
        shared_per_node[u] += length
        shared_per_node[v] += length
    exterior = np.maximum(perims - shared_per_node, 0.0)

    geo = GeoAttributes(area=areas, perimeter=perims, centroid=cents,
                        shared_perim=shared, exterior_perim=exterior)
    graph = dataclasses.replace(graph, edge_len=shared.astype(np.float32))
    return graph, geo


def from_shapefile(path, **kwargs):
    """Read a polygon shapefile (.shp + sidecar .dbf attribute table)
    with the NATIVE reader (graphs/shapefile.py — no geopandas/fiona
    dependency; pure numpy/struct parsing of the ESRI format) and
    delegate to from_geojson. tests/test_dualgraph.py proves the round
    trip write_shapefile -> from_shapefile == from_geojson on the same
    features, dual graph and geometry attributes identical."""
    from .shapefile import read_shapefile
    return from_geojson(read_shapefile(path), **kwargs)


def voronoi_precincts(n: int, *, seed: int = 0, width: float = None,
                      height: float = None,
                      pop_range: tuple = (50, 200)) -> dict:
    """An irregular Voronoi-tessellated 'state' as a GeoJSON dict — the
    realistic-topology counterpart to ``synthetic_precincts``: precinct
    degrees vary (real precinct dual graphs are not 4-regular), cells are
    convex irregular polygons, and shared boundaries have genuine varied
    lengths for the boundary-length-weighted chain target.

    Seeds are a jittered sqrt(n)-ish grid (no near-coincident generators);
    the diagram is clipped EXACTLY to the bounding box by the standard
    mirror trick (reflect the generators across all four box edges and
    tessellate the 5n points — each interior cell's clipped boundary then
    falls out of the tessellation itself, so neighboring cells share
    bit-identical vertex coordinates and from_geojson's snap-keyed rook
    adjacency is watertight). No real shapefile ships in this offline
    environment (README documents the limitation); this generator is the
    honest stand-in exercising the same code path real files take.
    """
    from scipy.spatial import Voronoi

    rng = np.random.default_rng(seed)
    nx_ = int(np.ceil(np.sqrt(n)))
    ny_ = int(np.ceil(n / nx_))
    w = float(width if width is not None else nx_)
    h = float(height if height is not None else ny_)
    gx = (np.arange(nx_) + 0.5) * (w / nx_)
    gy = (np.arange(ny_) + 0.5) * (h / ny_)
    pts = np.stack(np.meshgrid(gx, gy, indexing="ij"),
                   axis=-1).reshape(-1, 2)[:n]
    pts = pts + rng.uniform(-0.35, 0.35, pts.shape) * [w / nx_, h / ny_]

    mirrored = [pts]
    for axis, bound in ((0, 0.0), (0, w), (1, 0.0), (1, h)):
        m = pts.copy()
        m[:, axis] = 2 * bound - m[:, axis]
        mirrored.append(m)
    vor = Voronoi(np.vstack(mirrored))

    feats = []
    for i in range(n):
        region = vor.regions[vor.point_region[i]]
        if -1 in region or not region:       # cannot happen post-mirror
            raise RuntimeError(f"unbounded Voronoi cell {i}")
        verts = vor.vertices[region]
        # convex cell: exact CCW order = angular order about the mean
        ang = np.arctan2(verts[:, 1] - verts[:, 1].mean(),
                         verts[:, 0] - verts[:, 0].mean())
        verts = verts[np.argsort(ang)]
        ring = np.vstack([verts, verts[:1]]).tolist()
        feats.append({
            "type": "Feature",
            "properties": {"NAME": f"v{i}",
                           "POP": int(rng.integers(*pop_range))},
            "geometry": {"type": "Polygon", "coordinates": [ring]},
        })
    return {"type": "FeatureCollection", "features": feats}


def synthetic_precincts(nx_: int, ny_: int, *, seed: int = 0,
                        jitter: float = 0.25,
                        pop_range: tuple = (80, 120)) -> dict:
    """A jittered nx x ny quadrilateral 'state' as a GeoJSON dict: interior
    lattice vertices are perturbed (consistently for all four incident
    quads, keeping the planar subdivision topologically clean), and each
    precinct gets a POP property. Dual graph = rook grid."""
    rng = np.random.default_rng(seed)
    vx = np.tile(np.arange(nx_ + 1, dtype=np.float64)[:, None], (1, ny_ + 1))
    vy = np.tile(np.arange(ny_ + 1, dtype=np.float64)[None, :], (nx_ + 1, 1))
    interior = np.zeros((nx_ + 1, ny_ + 1), dtype=bool)
    interior[1:-1, 1:-1] = True
    vx = vx + np.where(interior, rng.uniform(-jitter, jitter,
                                             vx.shape), 0.0)
    vy = vy + np.where(interior, rng.uniform(-jitter, jitter,
                                             vy.shape), 0.0)
    feats = []
    for i in range(nx_):
        for j in range(ny_):
            ring = [
                [vx[i, j], vy[i, j]],
                [vx[i + 1, j], vy[i + 1, j]],
                [vx[i + 1, j + 1], vy[i + 1, j + 1]],
                [vx[i, j + 1], vy[i, j + 1]],
                [vx[i, j], vy[i, j]],
            ]
            feats.append({
                "type": "Feature",
                "properties": {
                    "NAME": f"p{i}_{j}",
                    "POP": int(rng.integers(*pop_range)),
                },
                "geometry": {"type": "Polygon", "coordinates": [ring]},
            })
    return {"type": "FeatureCollection", "features": feats}
