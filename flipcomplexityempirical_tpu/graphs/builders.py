"""Lattice builders: the reference's graph zoo plus generalizations.

Reproduces, against the array substrate of ``lattice.py``:

- ``grid_sec11``: the sec11 40x40 grid with 4 corner-diagonal bypass edges
  and the 4 corners removed — 1596 nodes / 3116 edges
  (reference grid_chain_sec11.py:191,236,252).
- ``frankengraph``: 20x20 square grid (relabeled to y in [-19, 0]) composed
  with a triangular lattice (y in [0, 20]) sharing the y==0 row — 800 nodes /
  1920 edges (reference Frankenstein_chain.py:186-195).
- plain ``square_grid`` (any size, the 64x64 benchmark workload), and
  ``triangular_lattice`` / ``hex_lattice`` for the non-grid planar adjacency
  configs of BASELINE.json.

networkx is used as a host-side generator for the triangular/hex node sets so
label conventions match the reference exactly; everything it produces is
converted immediately into frozen arrays.
"""

from __future__ import annotations

import numpy as np

from .lattice import LatticeGraph, build_lattice, from_networkx

# The four corner-bypass diagonal edges the sec11 script adds
# (grid_chain_sec11.py:236) and the corner nodes it removes (line 252).
_SEC11_DIAGONALS = [((0, 1), (1, 0)), ((0, 38), (1, 39)),
                    ((38, 0), (39, 1)), ((38, 39), (39, 38))]
_SEC11_CORNERS = [(0, 0), (0, 39), (39, 0), (39, 39)]


def square_grid(nx_: int, ny_: int | None = None, *, name: str | None = None,
                extra_edges=(), remove_nodes=(), wall=None, frame=None,
                center=None, queen: bool = False) -> LatticeGraph:
    """Rook-adjacency nx_ x ny_ grid with optional edge/node surgery.

    ``queen=True`` adds both diagonals of every unit cell (the
    reference's commented-out queen block, grid_chain_sec11.py:241-249):
    an n x n queen grid has 2n(n-1) rook + 2(n-1)^2 diagonal edges.
    Queen grids lower onto the board kernel's stencil fast path as two
    extra diagonal planes (flipcomplexityempirical_tpu/lower)."""
    ny_ = nx_ if ny_ is None else ny_
    removed = set(remove_nodes)
    nodes = [(x, y) for x in range(nx_) for y in range(ny_)
             if (x, y) not in removed]
    nodeset = set(nodes)
    adjacency = {n: [] for n in nodes}
    offsets = (((1, 0), (0, 1), (1, 1), (1, -1)) if queen
               else ((1, 0), (0, 1)))
    for (x, y) in nodes:
        for (dx, dy) in offsets:
            m = (x + dx, y + dy)
            if m in nodeset:
                adjacency[(x, y)].append(m)
                adjacency[m].append((x, y))
    for (u, v) in extra_edges:
        if u in nodeset and v in nodeset:
            adjacency[u].append(v)
            adjacency[v].append(u)
    if frame is None:
        frame = lambda n: n[0] in (0, nx_ - 1) or n[1] in (0, ny_ - 1)
    if center is None:
        center = (nx_ / 2.0, ny_ / 2.0)
    return build_lattice(
        adjacency,
        name=name or f"{'queen' if queen else 'grid'}{nx_}x{ny_}",
        frame=frame, wall=wall, center=center)


def grid_sec11() -> LatticeGraph:
    """The sec11 experiment graph: 1596 nodes, 3116 edges.

    Wall ids implement the reference ``boundary_slope`` classification
    (grid_chain_sec11.py:63-75): 0: both x==0; 1: both y==0; 2: both x==39;
    3: both y==39; 4: the four corner diagonal edges.
    """
    diag = {frozenset(e) for e in _SEC11_DIAGONALS}

    def wall(u, v):
        if u[0] == 0 and v[0] == 0:
            return 0
        if u[1] == 0 and v[1] == 0:
            return 1
        if u[0] == 39 and v[0] == 39:
            return 2
        if u[1] == 39 and v[1] == 39:
            return 3
        if frozenset((u, v)) in diag:
            return 4
        return -1

    return square_grid(
        40, 40, name="grid_sec11",
        extra_edges=_SEC11_DIAGONALS, remove_nodes=_SEC11_CORNERS,
        wall=wall, frame=lambda n: 0 in n or 39 in n, center=(20.0, 20.0))


def _label_center(labels) -> tuple:
    xs = [x for (x, _) in labels]
    ys = [y for (_, y) in labels]
    return ((min(xs) + max(xs)) / 2.0, (min(ys) + max(ys)) / 2.0)


def triangular_lattice(m: int, n: int, *, name: str | None = None,
                       frame=None, wall=None, center=None) -> LatticeGraph:
    """Triangular lattice via the networkx generator (label parity with the
    reference's ``nx.triangular_lattice_graph``, Frankenstein_chain.py:188)."""
    import networkx as nx

    g = nx.triangular_lattice_graph(m, n)
    return from_networkx(g, name=name or f"tri{m}x{n}", frame=frame,
                         wall=wall, center=center or _label_center(g.nodes()))


def hex_lattice(m: int, n: int, *, name: str | None = None,
                frame=None, wall=None, center=None) -> LatticeGraph:
    """Hexagonal lattice (degree <= 3 planar adjacency). Patch radius 3:
    neighbors of a flipped node reconnect around a hexagonal face through
    distance-3 nodes, so the radius-2 default would falsely reject valid
    flips."""
    import networkx as nx

    g = nx.hexagonal_lattice_graph(m, n)
    return from_networkx(g, name=name or f"hex{m}x{n}", frame=frame,
                         wall=wall, center=center or _label_center(g.nodes()),
                         patch_radius=3)


def frankengraph(m: int = 20) -> LatticeGraph:
    """Square-grid + triangular-lattice hybrid ("Frankengraph").

    Matches Frankenstein_chain.py:186-195: an m x m grid relabeled so its
    rows span y in [-(m-1), 0], composed with ``triangular_lattice_graph(m,
    2m-2)`` spanning y in [0, m]; the m nodes of the y==0 row are shared.
    For m=20: 800 nodes, 1920 edges. Wall ids per
    Frankenstein_chain.py:64-71: 0: both x==0; 1: both y==-19; 2: both
    x==19; 3: both y==20.
    """
    import networkx as nx

    g = nx.grid_graph([m, m])
    h = nx.triangular_lattice_graph(m, 2 * m - 2)
    adjacency: dict = {}
    for node in g.nodes():
        lab = (node[0], node[1] - m + 1)
        adjacency.setdefault(lab, set()).update(
            (v[0], v[1] - m + 1) for v in g[node])
    for node in h.nodes():
        adjacency.setdefault(node, set()).update(h[node])
    adjacency = {k: sorted(v) for k, v in adjacency.items()}

    y_lo, y_hi = -(m - 1), m

    def wall(u, v):
        if u[0] == 0 and v[0] == 0:
            return 0
        if u[1] == y_lo and v[1] == y_lo:
            return 1
        if u[0] == m - 1 and v[0] == m - 1:
            return 2
        if u[1] == y_hi and v[1] == y_hi:
            return 3
        return -1

    return build_lattice(
        adjacency, name=f"frankengraph{m}",
        frame=lambda nd: nd[0] in (0, m - 1) or nd[1] in (y_hi, y_lo),
        wall=wall, center=(float(m), float(m)))


# ---------------------------------------------------------------------------
# Initial plans (the reference's alignment-indexed starting assignments).
# Internally districts are 0..K-1; ``PARITY_LABELS`` maps district index to
# the reference's +1/-1 labels (district 0 <-> +1).
# ---------------------------------------------------------------------------

PARITY_LABELS = np.array([1, -1], dtype=np.int32)


def sec11_plan(graph: LatticeGraph, alignment: int) -> np.ndarray:
    """grid_chain_sec11.py:197-214 — 0: vertical split at x>19; 1: horizontal
    at y>19; 2: diagonal x>y with x==y tie broken at x>19. District 0 is the
    reference's +1 side."""
    out = np.empty(graph.n_nodes, dtype=np.int8)
    for i, (x, y) in enumerate(graph.labels):
        if alignment == 0:
            plus = x > 19
        elif alignment == 1:
            plus = y > 19
        elif alignment == 2:
            plus = (x > y) or (x == y and x > 19)
        else:
            raise ValueError(f"alignment {alignment}")
        out[i] = 0 if plus else 1
    return out


def frank_plan(graph: LatticeGraph, alignment: int, m: int = 20) -> np.ndarray:
    """Frankenstein_chain.py:207-246 — start_plans = [diagonal, vertical,
    horizontal][alignment]; membership gets the reference's +1 (district 0)."""
    out = np.empty(graph.n_nodes, dtype=np.int8)
    for i, (x, y) in enumerate(graph.labels):
        if alignment == 0:
            plus = 2 * x - y <= m - 3
        elif alignment == 1:
            plus = x < m / 2
        elif alignment == 2:
            plus = y < 0
        else:
            raise ValueError(f"alignment {alignment}")
        out[i] = 0 if plus else 1
    return out


def stripes_plan(graph: LatticeGraph, k: int, axis: int = 0) -> np.ndarray:
    """k vertical (axis=0) or horizontal (axis=1) bands of near-equal
    population — the generic k-district starting plan for BASELINE config 2."""
    coords = graph.coords[:, axis]
    order = np.argsort(coords, kind="stable")
    csum = np.cumsum(graph.pop[order])
    total = csum[-1]
    out = np.empty(graph.n_nodes, dtype=np.int8)
    bounds = total * (np.arange(1, k + 1) / k)
    dist = np.searchsorted(bounds, csum, side="left").clip(0, k - 1)
    out[order] = dist.astype(np.int8)
    return out
